# Task runner (parity with the reference's invoke tasks, reference tasks.py:1-101).
PY ?= python

.PHONY: test test-fast chaos fleet-chaos elasticity elasticity-bench obs obs-report incident timeline slo slo-bench gateway stream-bench decode-strategy decode-tune cov bench serve-bench paged-bench quant-kv quant-bench prefix-cache prefix-bench preemption preempt-bench swap swap-bench speculative spec-bench dryrun lint

test:
	$(PY) -m pytest tests/ -q

test-fast:
	$(PY) -m pytest tests/ -q -m "not slow"

# deterministic fault-injection suite (docs/reliability.md) — CPU-fast,
# also included in the tier-1 "not slow" run
chaos:
	$(PY) -m pytest tests/ -q -m chaos --continue-on-collection-errors

# supervised serving-fleet suite (docs/serving.md): replica failover,
# circuit breakers, exactly-once recovery drills — CPU-fast, also tier-1
fleet-chaos:
	$(PY) -m pytest tests/ -q -m fleet --continue-on-collection-errors

# fleet-elasticity suite (docs/serving.md "Elasticity"): burn-rate
# autoscaler ladder drills, zero-downtime scale-down with exactly-once
# replay, spike-arrival loadgen, healthz-stays-ready pins — CPU-fast,
# also tier-1, per-test timeout budget via the conftest SIGALRM guard
elasticity:
	$(PY) -m pytest tests/ -q -m elasticity --continue-on-collection-errors

# flash-crowd elasticity A/B at the CPU-fallback shape (docs/serving.md
# "Elasticity"): the same deterministic FakeClock spike offered to a
# static fleet and an autoscaled one — goodput-under-SLO both ways, the
# scale-event timeline, zero-drop / token-identity / pool zero-leak pins
elasticity-bench:
	$(PY) -c "import json, jax, jax.numpy as jnp; \
	jax.config.update('jax_platforms', 'cpu'); \
	import importlib.util; \
	spec = importlib.util.spec_from_file_location('bench', 'bench.py'); \
	bench = importlib.util.module_from_spec(spec); spec.loader.exec_module(bench); \
	from perceiver_io_tpu.models.text.clm import CausalLanguageModel; \
	cfg = bench._mk_config(bench.CPU_SHAPE); \
	model = CausalLanguageModel(cfg); \
	params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, cfg.max_seq_len), jnp.int32), cfg.max_seq_len - cfg.max_latents)['params']; \
	print(json.dumps({'elasticity': bench._bench_elasticity(model, params, cfg)}, indent=2))"

# unified telemetry layer suite (docs/observability.md) — CPU-fast,
# also included in the tier-1 "not slow" run
obs:
	$(PY) -m pytest tests/ -q -m observability --continue-on-collection-errors

# offline `obs report` analyzer over the checked-in fixture artifacts
# (docs/observability.md): per-phase latency, worst-request waterfall,
# compile/memory ledger table, padding waste — no dashboard, no live run
obs-report:
	$(PY) -m perceiver_io_tpu.observability.report tests/fixtures/events.jsonl \
		--snapshot tests/fixtures/metrics_snapshot.json

# incident flight-recorder suite (docs/observability.md "Flight recorder
# & incident bundles"): trace-sampling determinism + tail-keep, triggered
# bundle drills (cooldown/budget), the FakeClock chaos acceptance drill,
# and the `obs incident` analyzer — then the analyzer over the checked-in
# fixture bundle. CPU-fast, also tier-1.
incident:
	$(PY) -m pytest tests/test_flight_recorder.py -q -m flight_recorder
	$(PY) -m perceiver_io_tpu.observability.report --incident tests/fixtures/incident

# scheduler flight-deck suite (docs/observability.md "Scheduler timeline &
# post-mortems"): timeline ring + JSONL export, timeline<->span join, the
# exact TTFT/ITL telescoping bar, Chrome-trace schema, preemption
# post-mortems, per-tenant/per-tier attribution — then the `obs timeline`
# analyzer over the checked-in fixture (regenerate it with
# tests/fixtures/timeline/generate.py). CPU-fast, also tier-1.
timeline:
	$(PY) -m pytest tests/test_timeline.py -q -m timeline
	$(PY) -m perceiver_io_tpu.observability.report \
		--timeline tests/fixtures/timeline/timeline.jsonl \
		tests/fixtures/timeline/events.jsonl

# SLO telemetry suite (docs/observability.md): burn-rate monitor drills,
# load-generator determinism, TTFT/ITL accounting, fleet admission
# tightening — CPU-fast, also tier-1
slo:
	$(PY) -m pytest tests/ -q -m slo --continue-on-collection-errors

# goodput-under-SLO sweep at the CPU-fallback shape (docs/observability.md):
# offered-load sweep through the slot engine via the Poisson load generator,
# printing p95 TTFT / p95 inter-token latency per point and the knee
slo-bench:
	$(PY) -c "import json, jax, jax.numpy as jnp; \
	jax.config.update('jax_platforms', 'cpu'); \
	import importlib.util; \
	spec = importlib.util.spec_from_file_location('bench', 'bench.py'); \
	bench = importlib.util.module_from_spec(spec); spec.loader.exec_module(bench); \
	from perceiver_io_tpu.models.text.clm import CausalLanguageModel; \
	cfg = bench._mk_config(bench.CPU_SHAPE); \
	model = CausalLanguageModel(cfg); \
	params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, cfg.max_seq_len), jnp.int32), cfg.max_seq_len - cfg.max_latents)['params']; \
	print(json.dumps({'slo_goodput': bench._bench_slo_goodput(model, params, cfg)}, indent=2))"

# HTTP/SSE streaming-gateway suite (docs/serving.md "Streaming"): token
# streaming over real sockets, client-disconnect cancellation, zero
# slot/page leak, socket-anchored TTFT — CPU-fast, also tier-1
gateway:
	$(PY) -m pytest tests/ -q -m gateway --continue-on-collection-errors

# mid-stream mass-abandonment drill at the CPU-fallback shape
# (docs/serving.md "Streaming"): scripted client abandonment against the
# paged slot engine under FakeClock — cancelled-slot reclaim latency,
# pool-page zero-leak, survivor token-identity
stream-bench:
	$(PY) -c "import json, jax, jax.numpy as jnp; \
	jax.config.update('jax_platforms', 'cpu'); \
	import importlib.util; \
	spec = importlib.util.spec_from_file_location('bench', 'bench.py'); \
	bench = importlib.util.module_from_spec(spec); spec.loader.exec_module(bench); \
	from perceiver_io_tpu.models.text.clm import CausalLanguageModel; \
	cfg = bench._mk_config(bench.CPU_SHAPE); \
	model = CausalLanguageModel(cfg); \
	params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, cfg.max_seq_len), jnp.int32), cfg.max_seq_len - cfg.max_latents)['params']; \
	print(json.dumps({'streaming': bench._bench_streaming(model, params, cfg)}, indent=2))"

# decode-strategy suite (per-phase cached-vs-recompute + chunked prefill;
# docs/serving.md, docs/benchmarks.md) — CPU-fast, also tier-1
decode-strategy:
	$(PY) -m pytest tests/ -q -m decode_strategy --continue-on-collection-errors

# boundary-phase autotune probe on CPU: measures cached vs recompute at a
# small shape and prints the chosen strategy (persist with --out; the serve
# CLI's --serve.decode_strategy=auto warmup runs the same probe at the
# deployed shape)
decode-tune:
	$(PY) -m perceiver_io_tpu.inference.decode_strategy --ctx 512 --num-latents 64 --num-channels 64 --num-layers 2

cov:
	$(PY) -m pytest tests/ -q --cov=perceiver_io_tpu --cov-report=term-missing

bench:
	$(PY) bench.py

# slots-vs-bucket serving A/B at the CPU-fallback shape (docs/serving.md):
# mixed prompt lengths + heterogeneous max_new_tokens through both engines,
# printing the tokens/s ratio, slot occupancy, and padding-waste split
serve-bench:
	$(PY) -c "import json, jax, jax.numpy as jnp; \
	jax.config.update('jax_platforms', 'cpu'); \
	import importlib.util; \
	spec = importlib.util.spec_from_file_location('bench', 'bench.py'); \
	bench = importlib.util.module_from_spec(spec); spec.loader.exec_module(bench); \
	from perceiver_io_tpu.models.text.clm import CausalLanguageModel; \
	from perceiver_io_tpu.inference import cast_float_params; \
	cfg = bench._mk_config(bench.CPU_SHAPE); \
	model = CausalLanguageModel(cfg); \
	params = cast_float_params(model.init(jax.random.PRNGKey(0), jnp.zeros((1, cfg.max_seq_len), jnp.int32), cfg.max_seq_len - cfg.max_latents)['params'], jnp.bfloat16); \
	print(json.dumps({'serve_ab': bench._bench_serve_ab(model, params, cfg)}, indent=2))"

# dense-vs-paged KV layout A/B at the CPU-fallback shape (docs/serving.md
# "Block-paged KV"): a long-tail mixed-context workload through both slot
# layouts at ONE simulated HBM budget, printing max concurrent residents,
# the ratio, tokens/s, and the pool's page-utilization stats
paged-bench:
	$(PY) -c "import json, jax, jax.numpy as jnp; \
	jax.config.update('jax_platforms', 'cpu'); \
	import importlib.util; \
	spec = importlib.util.spec_from_file_location('bench', 'bench.py'); \
	bench = importlib.util.module_from_spec(spec); spec.loader.exec_module(bench); \
	from perceiver_io_tpu.models.text.clm import CausalLanguageModel; \
	cfg = bench._mk_config(bench.CPU_SHAPE); \
	model = CausalLanguageModel(cfg); \
	params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, cfg.max_seq_len), jnp.int32), cfg.max_seq_len - cfg.max_latents)['params']; \
	print(json.dumps({'paged_kv': bench._bench_paged_kv(model, params, cfg)}, indent=2))"

# quantized-KV suite (docs/serving.md "Quantized KV"): int8 pool + scale
# scatter/gather units, greedy parity vs the exact paged layout, quality-
# gated autotune/persistence, ragged-kernel interpreter parity — CPU-fast,
# also tier-1, per-test timeout budget via the conftest SIGALRM guard
quant-kv:
	$(PY) -m pytest tests/ -q -m quant_kv --continue-on-collection-errors

# exact-vs-int8 paged-KV A/B at the CPU-fallback shape (docs/serving.md
# "Quantized KV"): ONE simulated HBM budget, residents-per-HBM-byte
# ratio, tokens/s, greedy token-match rate, quality-gate verdict
quant-bench:
	$(PY) -c "import json, jax, jax.numpy as jnp; \
	jax.config.update('jax_platforms', 'cpu'); \
	import importlib.util; \
	spec = importlib.util.spec_from_file_location('bench', 'bench.py'); \
	bench = importlib.util.module_from_spec(spec); spec.loader.exec_module(bench); \
	from perceiver_io_tpu.models.text.clm import CausalLanguageModel; \
	cfg = bench._mk_config(bench.CPU_SHAPE); \
	model = CausalLanguageModel(cfg); \
	params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, cfg.max_seq_len), jnp.int32), cfg.max_seq_len - cfg.max_latents)['params']; \
	print(json.dumps({'quant_kv': bench._bench_quant_kv(model, params, cfg)}, indent=2))"

# cross-request prefix-sharing suite (docs/serving.md "Prefix sharing"):
# COW/refcount allocator drills, radix-index units, greedy token-identity
# across hot/partial/divergent/chunked/cancel/failover geometries, LRU
# eviction under pool pressure — CPU-fast, also tier-1, per-test timeout
# budget via the conftest SIGALRM guard
prefix-cache:
	$(PY) -m pytest tests/ -q -m prefix_cache --continue-on-collection-errors

# prefix-sharing A/B at the CPU-fallback shape (docs/serving.md "Prefix
# sharing"): Zipf-distributed shared prefixes through the paged slot
# engine, unshared vs COW-shared at ONE simulated HBM budget — TTFT
# p50/p95 ratio, residents-per-HBM-byte, hit ratio, token identity
prefix-bench:
	$(PY) -c "import json, jax, jax.numpy as jnp; \
	jax.config.update('jax_platforms', 'cpu'); \
	import importlib.util; \
	spec = importlib.util.spec_from_file_location('bench', 'bench.py'); \
	bench = importlib.util.module_from_spec(spec); spec.loader.exec_module(bench); \
	from perceiver_io_tpu.models.text.clm import CausalLanguageModel; \
	cfg = bench._mk_config(bench.CPU_SHAPE); \
	model = CausalLanguageModel(cfg); \
	params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, cfg.max_seq_len), jnp.int32), cfg.max_seq_len - cfg.max_latents)['params']; \
	print(json.dumps({'prefix_cache': bench._bench_prefix_cache(model, params, cfg)}, indent=2))"

# preemption suite (docs/serving.md "Preemption & priorities"): lazy-
# admission allocator units, token-identity through preempt/requeue/
# readmit cycles across dense/paged/int8/prefix-shared/chunked
# geometries, priority-tier + tenant victim selection, kv.exhaust chaos
# zero-leak storm, frees_by_cause completeness — CPU-fast, also tier-1,
# per-test timeout budget via the conftest SIGALRM guard
preemption:
	$(PY) -m pytest tests/ -q -m preemption --continue-on-collection-errors

# strict-vs-optimistic admission A/B at the CPU-fallback shape
# (docs/serving.md "Preemption & priorities"): long-tail declared-max_new
# workload at ONE simulated HBM budget — max-resident ratio, residents
# per HBM byte, goodput-under-SLO both ways, preemption/readmission
# counts, greedy token-identity pin
preempt-bench:
	$(PY) -c "import json, jax, jax.numpy as jnp; \
	jax.config.update('jax_platforms', 'cpu'); \
	import importlib.util; \
	spec = importlib.util.spec_from_file_location('bench', 'bench.py'); \
	bench = importlib.util.module_from_spec(spec); spec.loader.exec_module(bench); \
	from perceiver_io_tpu.models.text.clm import CausalLanguageModel; \
	cfg = bench._mk_config(bench.CPU_SHAPE); \
	model = CausalLanguageModel(cfg); \
	params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, cfg.max_seq_len), jnp.int32), cfg.max_seq_len - cfg.max_latents)['params']; \
	print(json.dumps({'preemption': bench._bench_preemption(model, params, cfg)}, indent=2))"

# host-swap suite (docs/serving.md "Host-swap preemption"): extract/
# restore primitive units, token-identity through swap-out/restore across
# paged/int8/prefix-shared/chunked geometries, kv.exhaust zero-leak storm
# under preemption=swap, auto per-victim arbitration honesty, swap_gbps
# calibration + registry persistence — CPU-fast, also tier-1
swap:
	$(PY) -m pytest tests/ -q -m swap --continue-on-collection-errors

# recompute-vs-swap-vs-auto preemption A/B over a generated-length sweep
# at ONE fixed pool budget (docs/serving.md "Host-swap preemption"):
# wall-to-drain + goodput-under-SLO per arm per length, the measured
# crossover length where paying transfer beats paying recompute, greedy
# token-identity vs an unpressured baseline, and the model honesty bars
# (predicted vs realized advantage sign, auto never picks the worse arm).
# The CPU lane runs a REDUCED shape (512 ctx), not CPU_SHAPE: the pool
# budget is denominated in full-context slots, so at 2048 ctx a sweep
# with genuine exhaustion pressure needs 200+-token decodes per request
# and the recompute arm's replay churn makes the lane hours-scale on
# CPU. At 512 ctx the 1-slot budget is 32 x 16-token blocks, 8
# residents cross it from the FIRST sweep point, and victim replays
# stay cheap — every point preempts for real instead of measuring
# compile noise. On real TPU run _bench_swap at the full shape with
# default kwargs to measure the uncapped crossover (ROADMAP item 2)
swap-bench:
	$(PY) -c "import json, jax, jax.numpy as jnp; \
	jax.config.update('jax_platforms', 'cpu'); \
	import importlib.util; \
	spec = importlib.util.spec_from_file_location('bench', 'bench.py'); \
	bench = importlib.util.module_from_spec(spec); spec.loader.exec_module(bench); \
	from perceiver_io_tpu.models.text.clm import CausalLanguageModel; \
	cfg = bench._mk_config((1, 512, 64, 128, 4, 2)); \
	model = CausalLanguageModel(cfg); \
	params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, cfg.max_seq_len), jnp.int32), cfg.max_seq_len - cfg.max_latents)['params']; \
	print(json.dumps({'swap': bench._bench_swap(model, params, cfg, budget_slots=1, n_requests=12, lengths=(24, 64, 128))}, indent=2))"

# speculative-decoding suite (docs/serving.md "Speculative decoding"):
# truncated-stack self-draft + single batched verify — greedy token-
# identity across dense/paged/int8/prefix-shared/chunked/mesh geometries,
# compile-bound +2, burst TTFT/ITL telescoping, ensure_many atomicity,
# kv.exhaust zero-leak, autotune pays/declines pins — CPU-fast, also tier-1
speculative:
	$(PY) -m pytest tests/ -q -m speculative --continue-on-collection-errors

# speculative A/B at the dispatch-bound probe shape (docs/serving.md
# "Speculative decoding"): the same greedy workload with speculation off
# vs a self-draft geometry — tokens/s both ways, acceptance rate, tokens
# per round, token-identity pin, plus the autotune pays/declines verdicts
spec-bench:
	$(PY) -c "import json, jax; \
	jax.config.update('jax_platforms', 'cpu'); \
	import importlib.util; \
	spec = importlib.util.spec_from_file_location('bench', 'bench.py'); \
	bench = importlib.util.module_from_spec(spec); spec.loader.exec_module(bench); \
	cfg = bench._mk_config(bench.CPU_SHAPE); \
	print(json.dumps({'speculative': bench._bench_speculative(None, None, cfg)}, indent=2))"

# sharded serving-runtime suite (docs/serving.md "Sharded serving"):
# 1-device byte parity, 8-virtual-device token parity across dense/paged/
# chunked/prefix-shared geometries, mesh-keyed executor identity + ledger
# attribution, zero-leak cancel/evacuate drills — CPU-fast, also tier-1
sharded:
	$(PY) -m pytest tests/ -q -m sharded --continue-on-collection-errors

# sharded serving A/B: the self-contained probe subprocessed at 1 device
# vs a 2x4 mesh over 8 virtual CPU devices (XLA_FLAGS-injected) — tokens/s,
# compile counts, per-model-shard resident KV bytes, token-identity pin
shard-bench:
	$(PY) -c "import json; \
	import importlib.util; \
	spec = importlib.util.spec_from_file_location('bench', 'bench.py'); \
	bench = importlib.util.module_from_spec(spec); spec.loader.exec_module(bench); \
	print(json.dumps({'sharded_serving': bench._bench_sharded_serving()}, indent=2))"

dryrun:
	$(PY) -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

lint:
	$(PY) -m compileall -q perceiver_io_tpu tests examples
