# Task runner (parity with the reference's invoke tasks, reference tasks.py:1-101).
PY ?= python

.PHONY: test test-fast chaos obs cov bench dryrun lint

test:
	$(PY) -m pytest tests/ -q

test-fast:
	$(PY) -m pytest tests/ -q -m "not slow"

# deterministic fault-injection suite (docs/reliability.md) — CPU-fast,
# also included in the tier-1 "not slow" run
chaos:
	$(PY) -m pytest tests/ -q -m chaos --continue-on-collection-errors

# unified telemetry layer suite (docs/observability.md) — CPU-fast,
# also included in the tier-1 "not slow" run
obs:
	$(PY) -m pytest tests/ -q -m observability --continue-on-collection-errors

cov:
	$(PY) -m pytest tests/ -q --cov=perceiver_io_tpu --cov-report=term-missing

bench:
	$(PY) bench.py

dryrun:
	$(PY) -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

lint:
	$(PY) -m compileall -q perceiver_io_tpu tests examples
