"""Perceiver AR CLM scaling-study runner.

Sweeps (num_channels, num_layers) configurations at a fixed token budget,
trains each with the step-based Trainer, and exports per-run validation-loss
trajectories as CSVs in the reference's format
(``Wall time,Step,Value`` — reference
``examples/scaling/clm/data/validation/*.csv``) plus a ``summary.csv`` with
the (params, FLOPs, tokens, final val_loss) columns the compute-optimal
analysis (``analyze.py``) consumes. Mirrors the reference experiment driver
``examples/scaling/clm/train.py:26-101`` with the dataset swapped for a
deterministic synthetic byte corpus (this environment is zero-egress; pass
``--dataset wikitext`` etc. on a connected machine to use the real data
modules).

Example (tiny CPU smoke sweep)::

    python examples/scaling/run.py --channels 32 64 --layers 2 \
        --steps 60 --val-interval 30 --max-seq-len 128 --latents 32 --out data/
"""
from __future__ import annotations

import os
import sys

# runnable without `pip install -e .`
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

import argparse
import csv
import time

import numpy as np


def synthetic_byte_corpus(vocab_size: int = 64, order: int = 2, size: int = 1 << 16, seed: int = 0):
    """Deterministic order-``order`` Markov byte stream — learnable structure
    with a nontrivial entropy floor, so val-loss curves separate by model
    capacity the way real text does."""
    rng = np.random.default_rng(seed)
    # Sparse transition table: each context prefers a few successors.
    table = rng.dirichlet(np.full(vocab_size, 0.05), size=vocab_size**order)
    out = np.empty(size, np.int32)
    ctx = 0
    for i in range(size):
        out[i] = rng.choice(vocab_size, p=table[ctx])
        ctx = (ctx * vocab_size + int(out[i])) % (vocab_size**order)
    return out


def batches(corpus: np.ndarray, batch_size: int, seq_len: int, seed: int):
    """Infinite iterator of CLM batches (shift-by-one labels)."""
    rng = np.random.default_rng(seed)
    while True:
        starts = rng.integers(0, len(corpus) - seq_len - 1, batch_size)
        windows = np.stack([corpus[s : s + seq_len + 1] for s in starts])
        yield {"input_ids": windows[:, :-1], "labels": windows[:, 1:]}


def run_one(args, num_channels: int, num_layers: int, corpus, val_corpus):
    import jax
    import optax

    from perceiver_io_tpu.models.text.clm import (
        CausalLanguageModel,
        CausalLanguageModelConfig,
    )
    from perceiver_io_tpu.parallel import make_mesh
    from perceiver_io_tpu.training.lrs import cosine_with_warmup
    from perceiver_io_tpu.training.tasks import clm_loss_fn
    from perceiver_io_tpu.training.trainer import Trainer, TrainerConfig
    from perceiver_io_tpu.utils import flops as F

    cfg = CausalLanguageModelConfig(
        vocab_size=args.vocab_size,
        max_seq_len=args.max_seq_len,
        max_latents=args.latents,
        num_channels=num_channels,
        num_heads=max(1, num_channels // 32),
        # reference counts the cross-attention layer in --num_layers
        num_self_attention_layers=num_layers - 1,
        cross_attention_dropout=0.5,
    )
    model = CausalLanguageModel(cfg)
    name = f"{args.experiment}_c{num_channels}_l{num_layers}"
    csv_path = os.path.join(args.out, "validation", f"{name}-tag-val_loss.csv")
    os.makedirs(os.path.dirname(csv_path), exist_ok=True)
    rows = []

    def log_val(trainer, state, step, metrics):
        rows.append((time.time(), step, float(metrics["loss"])))

    schedule = cosine_with_warmup(
        args.lr, warmup_steps=min(200, args.steps // 5), training_steps=args.steps
    )
    trainer = Trainer(
        TrainerConfig(
            max_steps=args.steps,
            val_check_interval=args.val_interval,
            log_every_n_steps=args.val_interval,
            default_root_dir=os.path.join(args.out, "logs", name),
            enable_checkpointing=False,
            enable_tensorboard=False,
        ),
        make_mesh(),
        clm_loss_fn(model, cfg.max_latents),
        optax.chain(optax.adam(schedule)),
        model_config=cfg,
        callbacks=[log_val],
    )

    def init_params():
        return model.init(
            jax.random.PRNGKey(0),
            np.zeros((1, cfg.max_seq_len), np.int32),
            cfg.max_seq_len - cfg.max_latents,
        )["params"]

    train_iter = batches(corpus, args.batch_size, cfg.max_seq_len, seed=1)
    train_data = (next(train_iter) for _ in iter(int, 1))

    def val_data():
        it = batches(val_corpus, args.batch_size, cfg.max_seq_len, seed=2)
        return [next(it) for _ in range(args.val_batches)]

    trainer.fit(init_params, train_data, val_data=val_data)
    final = trainer.validate(val_data())
    trainer.close()

    with open(csv_path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["Wall time", "Step", "Value"])
        w.writerows(rows)

    est = F.ComputeEstimator(cfg.vocab_size, cfg.max_seq_len, cfg.max_latents)
    total_flops, tokens = F.training_flops(
        est, num_channels, num_layers, args.steps, args.batch_size
    )
    params = F.count_params(
        model, np.zeros((1, cfg.max_seq_len), np.int32), cfg.max_seq_len - cfg.max_latents
    )
    return {
        "experiment": name,
        "num_channels": num_channels,
        "num_layers": num_layers,
        "params": params,
        "flops": total_flops,
        "tokens": tokens,
        "val_loss": float(final["loss"]),
    }


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--channels", type=int, nargs="+", default=[128, 256, 384])
    p.add_argument("--layers", type=int, nargs="+", default=[3, 6, 9])
    p.add_argument("--steps", type=int, default=2000)
    p.add_argument("--val-interval", type=int, default=250)
    p.add_argument("--val-batches", type=int, default=8)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--max-seq-len", type=int, default=1024)
    p.add_argument("--latents", type=int, default=256)
    p.add_argument("--vocab-size", type=int, default=64)
    p.add_argument("--lr", type=float, default=2e-4)
    p.add_argument("--corpus-size", type=int, default=1 << 16)
    p.add_argument("--experiment", default="scaling")
    p.add_argument("--out", default=os.path.join(os.path.dirname(__file__), "data"))
    args = p.parse_args()

    corpus = synthetic_byte_corpus(args.vocab_size, size=args.corpus_size, seed=0)
    val_corpus = synthetic_byte_corpus(args.vocab_size, size=args.corpus_size // 4, seed=7)

    results = []
    for c in args.channels:
        for l in args.layers:
            print(f"[scaling] run c={c} l={l}", flush=True)
            results.append(run_one(args, c, l, corpus, val_corpus))
            print(f"[scaling] {results[-1]}", flush=True)

    os.makedirs(args.out, exist_ok=True)
    summary = os.path.join(args.out, "summary.csv")
    with open(summary, "w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=list(results[0]))
        w.writeheader()
        w.writerows(results)
    print(f"[scaling] wrote {summary}")


if __name__ == "__main__":
    main()
