"""Compute-optimal analysis over a scaling-study ``summary.csv``.

Fits the reference's power-law allocation (reference
``examples/scaling/clm/scaling/laws.py``, Chinchilla-style exponents) over
the runs on the loss-vs-compute frontier, prints the fitted law and the
optimal (N, D) for a list of target budgets, and optionally renders the
loss-vs-compute plot (``--plot out.png``; matplotlib required only then).

Usage::

    python examples/scaling/analyze.py data/summary.csv --budgets 1e15 1e16
"""
from __future__ import annotations

import os
import sys

# runnable without `pip install -e .`
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

import argparse
import csv

from perceiver_io_tpu.utils.flops import fit_scaling_law


def load_summary(path: str):
    with open(path, newline="") as f:
        return [
            {
                **row,
                "params": float(row["params"]),
                "flops": float(row["flops"]),
                "tokens": float(row["tokens"]),
                "val_loss": float(row["val_loss"]),
            }
            for row in csv.DictReader(f)
        ]


def frontier(rows):
    """Runs not dominated by a cheaper-and-better run (loss-vs-compute)."""
    rows = sorted(rows, key=lambda r: r["flops"])
    best, out = float("inf"), []
    for r in rows:
        if r["val_loss"] < best:
            best = r["val_loss"]
            out.append(r)
    return out


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("summary")
    p.add_argument("--a", type=float, default=0.5, help="N_opt exponent")
    p.add_argument("--b", type=float, default=0.5, help="D_opt exponent")
    p.add_argument("--budgets", type=float, nargs="*", default=[])
    p.add_argument("--plot", default=None, help="write loss-vs-compute PNG here")
    args = p.parse_args()

    rows = load_summary(args.summary)
    front = frontier(rows)
    law = fit_scaling_law(
        [r["flops"] for r in front],
        [r["params"] for r in front],
        [r["tokens"] for r in front],
        a=args.a,
        b=args.b,
    )
    print(law)
    for c in args.budgets:
        print(f"C = {c:.3e}:  N_opt = {law.n_opt(c):.3e}  D_opt = {law.d_opt(c):.3e}")

    if args.plot:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        fig, ax = plt.subplots(figsize=(6, 4))
        for r in rows:
            ax.scatter(r["flops"], r["val_loss"], color="tab:blue")
            ax.annotate(
                f"c{int(r['num_channels'])}/l{int(r['num_layers'])}",
                (r["flops"], r["val_loss"]),
                fontsize=7,
            )
        ax.plot(
            [r["flops"] for r in front],
            [r["val_loss"] for r in front],
            color="tab:orange",
            label="frontier",
        )
        ax.set_xscale("log")
        ax.set_xlabel("training FLOPs")
        ax.set_ylabel("val loss")
        ax.legend()
        fig.tight_layout()
        fig.savefig(args.plot, dpi=150)
        print(f"wrote {args.plot}")


if __name__ == "__main__":
    main()
