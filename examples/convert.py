"""Checkpoint conversion CLI — parity with the reference's
``examples/convert.py`` (which drives 3 official HF models + 5 hosted
training checkpoints through one entrypoint). This environment is
zero-egress, so sources are local files instead of hub downloads:

Official DeepMind HF models (``pytorch_model.bin`` + ``config.json`` from
the hub):

    python examples/convert.py mlm pytorch_model.bin out_dir --hf-config config.json
    python examples/convert.py img-clf pytorch_model.bin out_dir --hf-config config.json
    python examples/convert.py flow pytorch_model.bin out_dir --hf-config config.json

Reference training checkpoints (Lightning ``.ckpt`` or bare state dicts,
reference-backend layout):

    python examples/convert.py clm epoch=000-val_loss=2.820.ckpt out_dir \
        --vocab-size 32000 --max-seq-len 1024 --max-latents 512 --num-channels 896
    python examples/convert.py sam epoch=027-val_loss=1.944.ckpt out_dir \
        --max-seq-len 6144 --max-latents 2048 --num-channels 768
    python examples/convert.py mlm mlm.ckpt out_dir            # 201M default shape
    python examples/convert.py txt-clf txt_clf.ckpt out_dir --num-classes 2

Export (the reverse direction — reference ``examples/convert.py:14-89``
produces the same artifact from Lightning checkpoints): a model trained in
this framework (``save_pretrained`` dir or trainer checkpoint dir) → a
reference-format ``save_pretrained`` directory (``config.json`` +
``backend_model.``-prefixed ``pytorch_model.bin``) the reference library
loads with ``Perceiver<Task>.from_pretrained``:

    python examples/convert.py export clm trained_model_dir out_dir
    python examples/convert.py export mlm trained_model_dir out_dir
    python examples/convert.py export clm trained_model_dir out_dir \
        --push_to_hub --repo-id user/model   # needs network + HF token

Key mappings live in ``perceiver_io_tpu/convert/`` (``torch_import`` for the
reference layout, ``hf_import`` for transformers state dicts, ``export`` for
the reverse direction), each parity-tested in ``tests/test_torch_parity.py``
/ ``tests/test_hf_convert.py`` / ``tests/test_export.py``.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

# runnable without `pip install -e .`: python examples/convert.py ...
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _force_cpu() -> None:
    """Conversion is a host-side param transform — never claim an
    accelerator for it (and never hang if one is configured but
    unreachable). Must run after importing jax, before its first use;
    the JAX_PLATFORMS env var alone is not enough on hosts whose
    sitecustomize force-registers an accelerator plugin."""
    import jax

    jax.config.update("jax_platforms", "cpu")


def _load_state_dict(path: str):
    import torch

    if path.endswith(".safetensors"):
        from safetensors.torch import load_file

        return load_file(path)
    sd = torch.load(path, map_location="cpu", weights_only=True)
    if "state_dict" in sd:  # Lightning checkpoint wrapper
        sd = sd["state_dict"]
    # Reference Lit* wrappers hold the backend as ``self.model`` (reference
    # ``clm/lightning.py:41``), so real .ckpt keys carry a uniform "model."
    # prefix the backend importers don't expect — strip it.
    if sd and all(k.startswith("model.") for k in sd):
        sd = {k[len("model."):]: v for k, v in sd.items()}
    return sd


def _d(value, fallback):
    return fallback if value is None else value


def _mlm_config(args):
    """Reference-layout MLM config; unset flags fall back to the 201M model
    the reference trains/fine-tunes (docs/training-examples.md:90-118):
    d_model 768, 26 layers, ctx 2048, 256x1280 latents."""
    from perceiver_io_tpu.models.core.config import PerceiverIOConfig
    from perceiver_io_tpu.models.text.common import TextEncoderConfig
    from perceiver_io_tpu.models.text.mlm import TextDecoderConfig

    vocab = _d(args.vocab_size, 262)
    seq = _d(args.max_seq_len, 2048)
    encoder = TextEncoderConfig(
        vocab_size=vocab,
        max_seq_len=seq,
        num_input_channels=_d(args.num_channels, 768),
        num_cross_attention_heads=8,
        num_self_attention_heads=8,
        num_self_attention_layers_per_block=_d(args.num_layers, 26),
        num_self_attention_blocks=1,
    )
    decoder = TextDecoderConfig(vocab_size=vocab, max_seq_len=seq)
    return PerceiverIOConfig(
        encoder, decoder, num_latents=_d(args.num_latents, 256),
        num_latent_channels=_d(args.num_latent_channels, 1280),
    )


def export_main(argv) -> None:
    parser = argparse.ArgumentParser(
        prog="convert.py export",
        description="Export a trained model to the reference (torch) "
        "save_pretrained format.",
    )
    parser.add_argument("task", choices=["clm", "sam", "mlm", "img-clf", "flow", "txt-clf"])
    parser.add_argument("model_dir", help="save_pretrained dir or trainer checkpoint dir")
    parser.add_argument("out_dir")
    # hub-publication surface, parity with the reference converter's
    # ``--push_to_hub``/``--commit_message`` (reference examples/convert.py:70-89,
    # which pushes each save_dir as a hub repo named after its basename)
    parser.add_argument(
        "--push_to_hub", "--push-to-hub", action="store_true",
        help="after writing out_dir, upload it to the HF hub",
    )
    parser.add_argument(
        "--repo-id", "--repo_id", default=None,
        help="hub repo id for --push_to_hub (default: basename of out_dir, "
        "matching the reference's save_dir-as-repo-name convention)",
    )
    parser.add_argument("--commit_message", "--commit-message", default=None)
    args = parser.parse_args(argv)

    import perceiver_io_tpu.convert as convert
    from perceiver_io_tpu.training.checkpoint import load_pretrained

    params, cfg = load_pretrained(args.model_dir)
    if cfg is None:
        raise SystemExit(f"{args.model_dir} carries no model config; cannot export")
    convert.save_reference_checkpoint(params, cfg, args.out_dir, args.task)
    print(f"exported {args.task} model to reference format at {args.out_dir}")
    if args.push_to_hub:
        _push_to_hub(args.out_dir, args.repo_id, args.commit_message)


def _push_to_hub(out_dir: str, repo_id, commit_message) -> None:
    """Upload an exported artifact dir to the HF hub. Fails with a clear
    message when huggingface_hub is unavailable, no token is configured, or
    the network is unreachable (e.g. a zero-egress sandbox)."""
    if repo_id is None:
        repo_id = os.path.basename(os.path.normpath(out_dir))
    try:
        from huggingface_hub import HfApi
    except ImportError:
        raise SystemExit(
            "--push_to_hub requires the huggingface_hub package "
            "(pip install huggingface_hub)"
        )
    api = HfApi()
    try:
        api.create_repo(repo_id, exist_ok=True)
        api.upload_folder(
            repo_id=repo_id,
            folder_path=out_dir,
            commit_message=commit_message or f"Upload {repo_id}",
        )
    except Exception as e:  # hub/network/auth errors all surface identically
        raise SystemExit(
            f"--push_to_hub failed for repo '{repo_id}': {e}\n"
            f"The exported artifact is intact at {out_dir}; push it later with "
            "huggingface-cli upload, or re-run with network + HF_TOKEN available."
        )
    print(f"pushed {out_dir} to hub repo {repo_id}")


def main() -> None:
    _force_cpu()
    if len(sys.argv) > 1 and sys.argv[1] == "export":
        export_main(sys.argv[2:])
        return
    parser = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter
    )
    parser.add_argument("task", choices=["clm", "sam", "mlm", "img-clf", "flow", "txt-clf"])
    parser.add_argument("state_dict", help="torch .pt/.ckpt/.bin/.safetensors file")
    parser.add_argument("out_dir")
    parser.add_argument(
        "--hf-config",
        help="transformers config.json — switches mlm/img-clf/flow to the "
        "official-HF-model key layout (deepmind/* checkpoints)",
    )
    # shape flags default per task: clm/sam fall back to the reference AR
    # shape (4096 ctx, 512 latents/channels, 8 layers); mlm/txt-clf to the
    # 201M language-perceiver shape (2048 ctx, 768 ch, 26 layers, 256x1280)
    parser.add_argument("--vocab-size", type=int, default=None)
    parser.add_argument("--max-seq-len", type=int, default=None)
    parser.add_argument("--max-latents", type=int, default=None)
    parser.add_argument("--num-channels", type=int, default=None)
    parser.add_argument("--num-layers", type=int, default=None)
    parser.add_argument("--num-latents", type=int, default=None)
    parser.add_argument("--num-latent-channels", type=int, default=None)
    parser.add_argument("--num-classes", type=int, default=2)
    args = parser.parse_args()

    import perceiver_io_tpu.convert as convert
    from perceiver_io_tpu.training.checkpoint import save_pretrained

    sd = _load_state_dict(args.state_dict)

    if args.hf_config:
        import transformers

        with open(args.hf_config) as f:
            hf_cfg = transformers.PerceiverConfig(**json.load(f))
        from perceiver_io_tpu.convert import hf_import

        if args.task == "mlm":
            cfg = hf_import.mlm_config_from_hf(hf_cfg)
            params = hf_import.import_hf_masked_language_model(sd, cfg)
        elif args.task == "img-clf":
            cfg = hf_import.image_classifier_config_from_hf(hf_cfg)
            params = hf_import.import_hf_image_classifier(sd, cfg)
        elif args.task == "flow":
            cfg = hf_import.optical_flow_config_from_hf(hf_cfg)
            params = hf_import.import_hf_optical_flow(sd, cfg)
        else:
            raise SystemExit(f"--hf-config applies to mlm/img-clf/flow, not {args.task}")
    elif args.task in ("clm", "sam"):
        if args.task == "clm":
            from perceiver_io_tpu.models.text.clm import CausalLanguageModelConfig as Cfg

            importer = convert.import_causal_language_model
        else:
            from perceiver_io_tpu.models.audio.symbolic import SymbolicAudioModelConfig as Cfg

            importer = convert.import_symbolic_audio_model
        cfg = Cfg(
            vocab_size=_d(args.vocab_size, 262),
            max_seq_len=_d(args.max_seq_len, 4096),
            max_latents=_d(args.max_latents, 512),
            num_channels=_d(args.num_channels, 512),
            num_self_attention_layers=_d(args.num_layers, 8),
        )
        params = importer(sd, cfg)
    elif args.task == "mlm":
        cfg = _mlm_config(args)
        params = convert.import_masked_language_model(sd, cfg)
    elif args.task == "txt-clf":
        from perceiver_io_tpu.models.core.config import (
            ClassificationDecoderConfig,
            PerceiverIOConfig,
        )

        mlm_cfg = _mlm_config(args)
        cfg = PerceiverIOConfig(
            mlm_cfg.encoder,
            ClassificationDecoderConfig(num_classes=args.num_classes),
            num_latents=mlm_cfg.num_latents,
            num_latent_channels=mlm_cfg.num_latent_channels,
        )
        params = convert.import_text_classifier(sd, cfg)
    else:
        raise SystemExit(f"{args.task} requires --hf-config (official HF layout)")

    save_pretrained(args.out_dir, params, cfg)
    print(f"saved {args.task} model to {args.out_dir}")


if __name__ == "__main__":
    main()
