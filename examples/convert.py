"""Checkpoint conversion CLI — parity with the reference's ``examples/convert.py``:
import torch checkpoints (reference Lightning ``.ckpt`` state dicts or HF
``pytorch_model.bin``/safetensors state dicts) into a TPU-native
``save_pretrained`` dir.

    python examples/convert.py clm path/to/state_dict.pt out_dir \
        --vocab-size 262 --max-seq-len 4096 --max-latents 512

The state-dict key mapping lives in ``perceiver_io_tpu/convert/torch_import.py``
(one import_* function per task family, each parity-tested against the
reference models in ``tests/test_torch_parity.py``).
"""
from __future__ import annotations

import argparse


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("task", choices=["clm", "mlm", "sam"])
    parser.add_argument("state_dict", help="torch .pt/.ckpt file")
    parser.add_argument("out_dir")
    parser.add_argument("--vocab-size", type=int, default=262)
    parser.add_argument("--max-seq-len", type=int, default=4096)
    parser.add_argument("--max-latents", type=int, default=512)
    parser.add_argument("--num-channels", type=int, default=512)
    parser.add_argument("--num-layers", type=int, default=8)
    args = parser.parse_args()

    import torch

    import perceiver_io_tpu.convert as convert
    from perceiver_io_tpu.training.checkpoint import save_pretrained

    sd = torch.load(args.state_dict, map_location="cpu", weights_only=True)
    if "state_dict" in sd:  # Lightning checkpoint wrapper
        sd = sd["state_dict"]

    if args.task in ("clm", "sam"):
        if args.task == "clm":
            from perceiver_io_tpu.models.text.clm import CausalLanguageModelConfig as Cfg

            importer = convert.import_causal_language_model
        else:
            from perceiver_io_tpu.models.audio.symbolic import SymbolicAudioModelConfig as Cfg

            importer = convert.import_symbolic_audio_model
        cfg = Cfg(
            vocab_size=args.vocab_size,
            max_seq_len=args.max_seq_len,
            max_latents=args.max_latents,
            num_channels=args.num_channels,
            num_self_attention_layers=args.num_layers,
        )
        params = importer(sd, cfg)
    else:
        raise SystemExit("mlm conversion needs encoder/decoder configs; use the API directly")

    save_pretrained(args.out_dir, params, cfg)
    print(f"saved {args.task} model to {args.out_dir}")


if __name__ == "__main__":
    main()
