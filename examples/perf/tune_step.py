"""On-hardware tuning sweep for the AR train step.

Runs one subprocess per configuration (fresh jit cache, fresh env knobs, hard
timeout so a hung backend cannot take the sweep down) and records chained
step times with the value-fetch fencing from ``bench.py`` — the only timing
this backend cannot fake (see ``docs/benchmarks.md``).

Swept knobs:
- ``attention_impl``: flash vs xla end-to-end
- ``PERCEIVER_FLASH_MIN_KV``: auto-dispatch floor — xla for the short
  (1024×1024) self-attention, flash for the long-kv cross-attention
- ``PERCEIVER_FLASH_BLOCKS``: Pallas block-size schedule

Usage::

    python examples/perf/tune_step.py            # bench shape, full sweep
    python examples/perf/tune_step.py --quick    # small shape smoke
    python examples/perf/tune_step.py --out results.json

Exit is always 0 with a JSON summary on stdout; individual config failures
and timeouts are recorded, not fatal.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)

import bench  # noqa: E402  (heavy imports inside bench are function-local)

FULL_SHAPE = bench.FULL_SHAPE
QUICK_SHAPE = (2, 2048, 256, 256, 8, 2)

SWEEP = [
    {"name": "flash-default", "impl": "auto", "env": {}},
    {"name": "flash-minkv2048", "impl": "auto", "env": {"PERCEIVER_FLASH_MIN_KV": "2048"}},
    {"name": "flash-minkv1536", "impl": "auto", "env": {"PERCEIVER_FLASH_MIN_KV": "1536"}},
    {"name": "flash-blocks1024", "impl": "auto", "env": {"PERCEIVER_FLASH_BLOCKS": "1024,512,256,128"}},
    {"name": "flash-blocks256", "impl": "auto", "env": {"PERCEIVER_FLASH_BLOCKS": "256,128"}},
    {
        "name": "flash-blocks1024-minkv2048",
        "impl": "auto",
        "env": {"PERCEIVER_FLASH_BLOCKS": "1024,512,256,128", "PERCEIVER_FLASH_MIN_KV": "2048"},
    },
    {"name": "xla", "impl": "xla", "env": {}},
    # Fused same-input projections (modules.py:_fused_dense): one wider
    # matmul for self-attn q/k/v and cross-attn k/v. Exactness-tested on CPU
    # (tests/test_fused_qkv.py); throughput effect is measured here.
    {"name": "flash-fusedqkv", "impl": "auto", "env": {"PERCEIVER_FUSED_QKV": "1"}},
    {
        "name": "flash-fusedqkv-minkv2048",
        "impl": "auto",
        "env": {"PERCEIVER_FUSED_QKV": "1", "PERCEIVER_FLASH_MIN_KV": "2048"},
    },
    # Latency-hiding scheduler: overlaps collective/memory traffic with
    # compute at the XLA schedule level — a pure-flags candidate for the
    # ~20%-MFU dense blocks (appended to ambient XLA_FLAGS by run_one).
    {
        "name": "flash-lhs",
        "impl": "auto",
        "env": {"XLA_FLAGS": "--xla_tpu_enable_latency_hiding_scheduler=true"},
        "tpu_only": True,  # the flag is rejected by the CPU backend
    },
    {
        "name": "flash-fusedqkv-lhs",
        "impl": "auto",
        "env": {
            "PERCEIVER_FUSED_QKV": "1",
            "XLA_FLAGS": "--xla_tpu_enable_latency_hiding_scheduler=true",
        },
        "tpu_only": True,
    },
]


def child(shape, impl: str, trace_dir: str | None = None) -> None:
    import contextlib

    import jax
    import numpy as np

    from perceiver_io_tpu.parallel import shard_batch, single_device_mesh

    cfg = bench._mk_config(shape)
    batch_size = shape[0]
    mesh = single_device_mesh(jax.devices()[0])
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, size=(batch_size, cfg.max_seq_len + 1), dtype=np.int32)
    with mesh:
        sharded = shard_batch({"input_ids": ids[:, :-1], "labels": ids[:, 1:]}, mesh)
        _, state, step, _ = bench._build_ar(cfg, mesh, impl)
        # When tracing, capture the already-warm chained window only: the
        # xplane then contains just N identical steady-state steps — the
        # per-kernel decomposition the MFU analysis needs.
        ctx = (
            jax.profiler.trace(trace_dir)
            if trace_dir is not None
            else contextlib.nullcontext()
        )
        chained_ms, synced_ms, state, loss = bench._time_train(
            step, state, sharded, jax.random.PRNGKey(1), n_chain=20, n_sync=2
        )
        if trace_dir is not None:
            with ctx:
                for i in range(3):
                    state, metrics = step(state, sharded, jax.random.fold_in(jax.random.PRNGKey(3), i))
                bench._fetch(metrics["loss"])
    out = {
        "chained_ms": round(chained_ms, 2),
        "synced_ms": round(synced_ms, 2),
        "loss": round(loss, 4),
        "tokens_per_sec": round(batch_size * cfg.max_seq_len / (chained_ms / 1e3), 1),
    }
    if trace_dir is not None:
        out["trace_dir"] = trace_dir
    print(json.dumps(out), flush=True)


def ceiling_child() -> None:
    print(json.dumps({"matmul_tflops": round(bench._matmul_ceiling_tflops(), 1)}), flush=True)


def run_one(args_list, env_extra, timeout_s):
    # Start from an env with every perf knob stripped: configs must see
    # exactly the knobs they declare, not leftovers from the shell.
    env = {
        k: v for k, v in os.environ.items()
        if not k.startswith("PERCEIVER_FLASH_") and k != "PERCEIVER_FUSED_QKV"
    }
    # XLA_FLAGS entries append to (not replace) the ambient flags — the host
    # may carry required platform flags.
    if "XLA_FLAGS" in env_extra:
        env_extra = dict(env_extra)
        env_extra["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "") + " " + env_extra["XLA_FLAGS"]
        ).strip()
    # shared XLA disk cache: identical programs across sweep configs (e.g.
    # the xla attention path under different env knobs) compile once
    env.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(tempfile.gettempdir(), f"perceiver_xla_cache_{os.getuid()}"),
    )
    env.update(env_extra)
    t0 = time.monotonic()
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), *args_list],
            env=env, stdout=subprocess.PIPE, stderr=sys.stderr,
            text=True, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return {"error": "timeout", "wall_s": round(time.monotonic() - t0, 1)}
    if proc.returncode != 0:
        return {"error": f"rc={proc.returncode}", "wall_s": round(time.monotonic() - t0, 1)}
    for line in (proc.stdout or "").splitlines()[::-1]:
        try:
            out = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(out, dict):
            out["wall_s"] = round(time.monotonic() - t0, 1)
            return out
    return {"error": "no JSON result on stdout", "wall_s": round(time.monotonic() - t0, 1)}


_BACKEND_PROBE: dict = {}  # memoized {"is_tpu": bool} from the subprocess probe


def _probed_backend_is_tpu(timeout_s: float = 120.0) -> bool:
    """Probe the backend children will actually get: a tiny subprocess that
    imports jax and prints ``jax.default_backend()`` (the parent never
    imports jax by design). Memoized; a probe that fails, hangs, or prints
    anything but ``tpu`` counts as non-TPU — the conservative answer, since
    its only consumer skips configs whose XLA flags a CPU backend rejects."""
    if "is_tpu" not in _BACKEND_PROBE:
        is_tpu = False
        try:
            proc = subprocess.run(
                [sys.executable, "-c",
                 "import jax; print(jax.default_backend())"],
                stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                text=True, timeout=timeout_s,
            )
            lines = [l.strip() for l in (proc.stdout or "").splitlines() if l.strip()]
            is_tpu = proc.returncode == 0 and bool(lines) and lines[-1] == "tpu"
        except (subprocess.TimeoutExpired, OSError):
            pass
        _BACKEND_PROBE["is_tpu"] = is_tpu
    return _BACKEND_PROBE["is_tpu"]


def _on_cpu() -> bool:
    """True when child subprocesses will NOT land on a TPU backend, so
    ``tpu_only`` sweep configs (TPU-specific XLA flags) must skip. When
    ``JAX_PLATFORMS`` is set it is the cheap authoritative signal — the
    driver's TPU session sets ``axon``, CPU validation runs set ``cpu``
    (membership check, not equality: 'cpu,tpu' etc.). When it is UNSET the
    actual backend is probed once in a subprocess (ADVICE r5: an unset env
    used to read as "not cpu", so tpu_only configs ran on CPU hosts and
    died on the rejected XLA flag instead of skipping cleanly)."""
    platforms = os.environ.get("JAX_PLATFORMS")
    if platforms is not None and platforms.strip():
        return "cpu" in [p.strip() for p in platforms.split(",") if p.strip()]
    return not _probed_backend_is_tpu()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--out", default=None)
    ap.add_argument("--timeout", type=float, default=600.0)
    ap.add_argument(
        "--trace", default=None, metavar="NAME",
        help="run only the named sweep config with a jax.profiler device "
        "trace of 3 steady-state steps (xplane written under "
        "<out dir>/trace-NAME) — the per-kernel decomposition for MFU "
        "analysis",
    )
    args = ap.parse_args()
    shape = QUICK_SHAPE if args.quick else FULL_SHAPE
    shape_arg = ",".join(map(str, shape))

    if args.trace is not None:
        cfg = next((c for c in SWEEP if c["name"] == args.trace), None)
        if cfg is None:
            raise SystemExit(
                f"unknown config {args.trace!r}; choose from "
                f"{[c['name'] for c in SWEEP]}"
            )
        if cfg.get("tpu_only") and _on_cpu():
            raise SystemExit(f"{cfg['name']} is a tpu-only config; needs hardware")
        trace_dir = os.path.abspath(
            os.path.join(os.path.dirname(args.out or "."), f"trace-{cfg['name']}")
        )
        r = run_one(
            ["--child", shape_arg, cfg["impl"], trace_dir], cfg["env"], args.timeout
        )
        print(json.dumps({"shape": list(shape), "trace": r}))
        return

    results = {"shape": list(shape), "configs": {}}
    print(f"[tune] matmul ceiling...", file=sys.stderr, flush=True)
    results["ceiling"] = run_one(["--ceiling"], {}, min(args.timeout, 300.0))
    print(f"[tune] ceiling: {results['ceiling']}", file=sys.stderr, flush=True)

    for cfg in SWEEP:
        if cfg.get("tpu_only") and _on_cpu():
            results["configs"][cfg["name"]] = {"skipped": "tpu-only config"}
            print(f"[tune] {cfg['name']}: skipped (tpu-only)", file=sys.stderr, flush=True)
            continue
        print(f"[tune] {cfg['name']}...", file=sys.stderr, flush=True)
        r = run_one(["--child", shape_arg, cfg["impl"]], cfg["env"], args.timeout)
        results["configs"][cfg["name"]] = r
        print(f"[tune] {cfg['name']}: {r}", file=sys.stderr, flush=True)

    print(json.dumps(results))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(results, f, indent=2)


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--child":
        child(
            tuple(int(x) for x in sys.argv[2].split(",")),
            sys.argv[3],
            trace_dir=sys.argv[4] if len(sys.argv) > 4 else None,
        )
    elif len(sys.argv) > 1 and sys.argv[1] == "--ceiling":
        ceiling_child()
    else:
        main()
