"""Cached vs recompute decode throughput as context length grows, with every
generated token pinned into ONE cache phase (``--phase``):

- ``latent`` — latent-growth: the cached step runs O(1) tokens of compute
  per token vs the recompute path's full window (measured ~6× on CPU,
  ``docs/benchmarks.md`` round-5 curves).
- ``boundary`` — prefix-growth: the cache elides the full-window embedding +
  cross-k/v projections (the ``2·n·c²`` matmuls) but recomputes the latent
  stack like the recompute path does (measured sub-1× on CPU at 256 ch).

Under the static right-aligned window formulation both paths' per-token cost
is a function of the *window* size ``n = max_seq_len`` (left pads are
computed and masked), so the scaling axis is context length, not prompt
length. Prints one JSON line per point and a markdown table suitable for
``docs/benchmarks.md``.

Boundary-phase points also feed the decode-strategy registry
(``inference/decode_strategy.py``): each point records the autotuner's
chosen strategy for its shape, the summary reports the cached/recompute
crossing point across context lengths, and ``--emit-strategy PATH`` writes
the same JSON artifact the strategy persistence layer consumes — so a
scaling study doubles as a deployment's warmup measurement.

With ``--speculation`` each boundary point additionally runs the
speculative-decoding autotune probe (docs/serving.md "Speculative
decoding") at its shape: the per-ctx verdict (``off`` or the winning
``k<K>d<D>`` draft geometry), acceptance rate, and per-token timings land
in the point and the registry, and the summary reports the speculation
crossover — the first context length at which drafting stops paying
(verify-lane FLOPs grow with the window; the fixed per-step cost they
amortize does not).

Usage::

    python examples/perf/decode_scaling.py                  # boundary, 1k->8k
    python examples/perf/decode_scaling.py --phase latent   # the cache's win
    python examples/perf/decode_scaling.py --ctxs 1024 2048 # subset
    python examples/perf/decode_scaling.py --tpu            # real chip
    python examples/perf/decode_scaling.py --emit-strategy strategy.json
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--ctxs", type=int, nargs="+", default=[1024, 2048, 4096, 8192])
    p.add_argument("--num-latents", type=int, default=512)
    p.add_argument("--num-channels", type=int, default=256)
    p.add_argument("--num-layers", type=int, default=4)
    p.add_argument("--num-heads", type=int, default=8)
    p.add_argument("--batch", type=int, default=1)
    p.add_argument("--new-tokens", type=int, default=8)
    p.add_argument("--tpu", action="store_true",
                   help="run on the default accelerator backend (else force CPU)")
    p.add_argument(
        "--phase", choices=["boundary", "latent"], default="boundary",
        help="which cache phase every generated token lands in: 'boundary' "
        "(prefix-growth — latents already maxed; the cache elides only the "
        "full-window embedding + cross-k/v projections) or 'latent' "
        "(latent-growth — the cache runs O(1) tokens of compute per step "
        "vs the recompute path's full window)",
    )
    p.add_argument("--out", default=None, help="also append JSON lines here")
    p.add_argument(
        "--speculation", action="store_true",
        help="also run the speculative-decoding autotune probe per context "
        "length (boundary phase only): records the per-ctx verdict + "
        "acceptance and reports the ctx at which drafting stops paying",
    )
    p.add_argument(
        "--spec-candidates", nargs="+", default=["k4d1", "k8d1"],
        help="draft geometries the per-ctx speculation probe measures",
    )
    p.add_argument(
        "--emit-strategy", default=None,
        help="write the decode-strategy registry JSON artifact here (the "
        "file inference/decode_strategy.py persistence consumes; boundary "
        "phase only)",
    )
    args = p.parse_args()
    if args.phase == "latent" and args.new_tokens >= args.num_latents:
        p.error(
            f"--phase latent pins every generated token into latent growth, "
            f"which requires --new-tokens ({args.new_tokens}) < "
            f"--num-latents ({args.num_latents})"
        )

    import jax

    if not args.tpu:
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    import numpy as np

    from perceiver_io_tpu.inference import cast_float_params
    from perceiver_io_tpu.inference import decode_strategy as strategy_mod
    from perceiver_io_tpu.inference.generate import GenerationConfig, generate
    from perceiver_io_tpu.models.text.clm import (
        CausalLanguageModel,
        CausalLanguageModelConfig,
    )

    platform = jax.default_backend()
    rows = []
    for ctx in args.ctxs:
        cfg = CausalLanguageModelConfig(
            vocab_size=262,
            max_seq_len=ctx,
            max_latents=args.num_latents,
            num_channels=args.num_channels,
            num_heads=args.num_heads,
            num_self_attention_layers=args.num_layers,
        )
        model = CausalLanguageModel(cfg, dtype=jnp.bfloat16 if args.tpu else None)
        rng = np.random.default_rng(0)
        prefix_len = ctx - args.num_latents
        params = model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, ctx), jnp.int32), prefix_len
        )["params"]
        if args.tpu:
            params = cast_float_params(params, jnp.bfloat16)

        # Both phases keep the prompt near the window so the recompute path
        # always pays the full (b, ctx) forward. 'boundary': latents start
        # at max, every token migrates the prefix boundary. 'latent':
        # latents start low enough that all new tokens grow the latent tail
        # — the cached step then runs O(1) tokens of compute vs the
        # recompute path's full window.
        prompt_len = ctx - args.new_tokens
        if args.phase == "boundary":
            start_latents = args.num_latents  # already maxed
        else:
            start_latents = args.num_latents - args.new_tokens
        prompt = jnp.asarray(
            rng.integers(1, cfg.vocab_size, size=(args.batch, prompt_len), dtype=np.int32)
        )
        gcfg = GenerationConfig(
            max_new_tokens=args.new_tokens, num_latents=start_latents
        )

        point = {"ctx": ctx, "phase": args.phase, "platform": platform, "batch": args.batch,
                 "new_tokens": args.new_tokens, "channels": args.num_channels,
                 "layers": args.num_layers, "num_latents": args.num_latents}
        for label, use_cache in (("cached", True), ("recompute", False)):
            ids = generate(model, params, prompt, gcfg, use_cache=use_cache)
            _ = int(np.asarray(jax.device_get(ids))[0, -1])  # warm + fence
            t0 = time.perf_counter()
            ids = generate(model, params, prompt, gcfg, use_cache=use_cache)
            _ = int(np.asarray(jax.device_get(ids))[0, -1])
            dt = time.perf_counter() - t0
            point[f"{label}_tokens_per_sec"] = round(
                args.batch * args.new_tokens / dt, 2)
            point[f"{label}_ms_per_token"] = round(dt / args.new_tokens * 1e3, 2)
        point["speedup"] = round(
            point["cached_tokens_per_sec"] / point["recompute_tokens_per_sec"], 2
        )
        if args.phase == "boundary":
            # record this shape's verdict in the decode-strategy registry —
            # the measurement the warmup autotuner would repeat, reusing the
            # timings just taken instead of re-running the probe
            chosen = (
                "cached"
                if point["cached_ms_per_token"] <= point["recompute_ms_per_token"]
                else "recompute"
            )
            strategy_mod.record(
                model, chosen,
                cached_ms_per_token=point["cached_ms_per_token"],
                recompute_ms_per_token=point["recompute_ms_per_token"],
                batch=args.batch, new_tokens=args.new_tokens,
                source="decode_scaling",
            )
            point["chosen_strategy"] = chosen
            point["cached_over_recompute"] = point["speedup"]
            if args.speculation:
                # the same measure-once discipline for the speculation
                # knob: the probe A/Bs each draft geometry against the
                # plain one-token step at THIS shape and memoizes the
                # verdict (off = drafting doesn't pay here)
                verdict = strategy_mod.autotune_speculation(
                    model, params,
                    candidates=tuple(args.spec_candidates), force=True,
                )
                entry = strategy_mod.spec_entry(model) or {}
                point["speculation"] = verdict
                point["speculation_acceptance"] = entry.get(
                    "acceptance", {}).get(verdict)
                point["speculation_ms_per_token"] = entry.get(
                    "timings_ms_per_token", {})
        rows.append(point)
        print(json.dumps(point), flush=True)
        if args.out:
            with open(args.out, "a") as f:
                f.write(json.dumps(point) + "\n")
    if args.emit_strategy and args.phase == "boundary":
        strategy_mod.save_registry(args.emit_strategy)
        print(f"wrote decode-strategy artifact: {args.emit_strategy}",
              file=sys.stderr)

    if args.phase == "boundary":
        spec_col = " speculation |" if args.speculation else ""
        print("\n| ctx | cached tok/s | recompute tok/s | cached ms/tok | recompute ms/tok | speedup | chosen |" + spec_col)
        print("|---|---|---|---|---|---|---|" + ("---|" if args.speculation else ""))
        for r in rows:
            extra = f" {r['speculation']} |" if args.speculation else ""
            print(f"| {r['ctx']} | {r['cached_tokens_per_sec']} | "
                  f"{r['recompute_tokens_per_sec']} | {r['cached_ms_per_token']} | "
                  f"{r['recompute_ms_per_token']} | {r['speedup']}x | "
                  f"{r['chosen_strategy']} |" + extra)
        # the cached/recompute crossing point: the first context length at
        # which the cached boundary step wins (None = recompute everywhere)
        crossover = next(
            (r["ctx"] for r in rows if r["chosen_strategy"] == "cached"), None
        )
        summary = {
            "crossover_ctx": crossover,
            "chosen_by_ctx": {str(r["ctx"]): r["chosen_strategy"] for r in rows},
        }
        if args.speculation:
            # the speculation crossover runs the OTHER way: drafting pays
            # at small windows (per-step cost amortized over the burst)
            # and stops once verify-lane FLOPs dominate
            summary["speculation_by_ctx"] = {
                str(r["ctx"]): r["speculation"] for r in rows
            }
            summary["speculation_stops_paying_ctx"] = next(
                (r["ctx"] for r in rows if r["speculation"] == "off"), None
            )
        print(json.dumps(summary))
    else:
        print("\n| ctx | cached tok/s | recompute tok/s | cached ms/tok | recompute ms/tok | speedup |")
        print("|---|---|---|---|---|---|")
        for r in rows:
            print(f"| {r['ctx']} | {r['cached_tokens_per_sec']} | "
                  f"{r['recompute_tokens_per_sec']} | {r['cached_ms_per_token']} | "
                  f"{r['recompute_ms_per_token']} | {r['speedup']}x |")


if __name__ == "__main__":
    main()
