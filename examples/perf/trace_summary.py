"""Summarize a jax.profiler xplane trace: per-kernel time decomposition.

Reads the ``*.xplane.pb`` a ``jax.profiler.trace`` capture writes (e.g. from
``tune_step.py --trace flash-default`` or the trainer's ``profile_start``
window) and prints, per device plane, the top ops by accumulated duration
with their share of total device-busy time — the decomposition needed to
attribute the gap between achieved and peak MFU to specific kernels
(docs/benchmarks.md "vs the north star").

No tensorboard involved: the XSpace protobuf is parsed directly via the
``xplane_pb2`` module bundled with the baked-in tensorflow wheel.

Usage::

    python examples/perf/trace_summary.py <trace_dir_or_xplane.pb> [--top 25]
"""
from __future__ import annotations

import argparse
import glob
import os
import sys
from collections import defaultdict


def find_xplane(path: str) -> str:
    if os.path.isfile(path):
        return path
    hits = sorted(glob.glob(os.path.join(path, "**", "*.xplane.pb"), recursive=True))
    if not hits:
        raise SystemExit(f"no *.xplane.pb under {path}")
    return hits[-1]  # latest capture


def load_xspace(pb_path: str):
    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2
    except ImportError as e:
        raise SystemExit(
            f"xplane_pb2 unavailable ({e}); install tensorflow or inspect the "
            "trace with tensorboard's profile plugin instead"
        )
    space = xplane_pb2.XSpace()
    with open(pb_path, "rb") as f:
        space.ParseFromString(f.read())
    return space


def summarize_plane(plane, top: int) -> None:
    """Aggregate per LINE, not per plane: a plane's lines overlap in time
    (e.g. an 'XLA Modules' line whose one envelope event spans every kernel
    on the 'XLA Ops' line), so mixing lines would double-count and distort
    the per-op percentages. Within a line events are siblings on one
    timeline and their shares are meaningful."""
    meta = {m_id: m.name for m_id, m in plane.event_metadata.items()}
    for line in plane.lines:
        totals = defaultdict(int)  # name -> ps
        counts = defaultdict(int)
        span_lo, span_hi = None, 0
        for ev in line.events:
            name = meta.get(ev.metadata_id, f"#{ev.metadata_id}")
            totals[name] += ev.duration_ps
            counts[name] += 1
            lo = line.timestamp_ns * 1000 + ev.offset_ps
            span_lo = lo if span_lo is None else min(span_lo, lo)
            span_hi = max(span_hi, lo + ev.duration_ps)
        busy_ps = sum(totals.values())
        if not totals or busy_ps == 0:  # e.g. instant-marker-only lines
            continue
        span_ms = (span_hi - (span_lo or 0)) / 1e9
        print(f"\n== plane: {plane.name} | line: {line.name or line.id}  "
              f"(span={span_ms:.2f} ms, busy={busy_ps / 1e9:.2f} ms) ==")
        print(f"{'ms':>10} {'%busy':>6} {'calls':>6}  op")
        for name, ps in sorted(totals.items(), key=lambda kv: -kv[1])[:top]:
            print(f"{ps / 1e9:10.3f} {100 * ps / busy_ps:6.1f} {counts[name]:6d}  "
                  f"{name[:110]}")


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("path", help="trace dir or .xplane.pb file")
    ap.add_argument("--top", type=int, default=25)
    ap.add_argument("--all-planes", action="store_true",
                    help="include host/python planes (default: device planes "
                    "only, falling back to all when none found)")
    args = ap.parse_args()

    pb = find_xplane(args.path)
    print(f"trace: {pb}", file=sys.stderr)
    space = load_xspace(pb)

    device_planes = [
        p for p in space.planes
        if "TPU" in p.name or "GPU" in p.name or p.name.startswith("/device")
    ]
    planes = list(space.planes) if args.all_planes or not device_planes else device_planes
    for plane in planes:
        summarize_plane(plane, args.top)


if __name__ == "__main__":
    main()
