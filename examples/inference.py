"""Inference pipeline demos — parity with the reference's
``examples/inference.ipynb``: one snippet per pipeline surface, each loading
a ``save_pretrained`` dir produced by training or ``examples/convert.py``.

Run individual demos:  python examples/inference.py text-generation logs/clm/export
"""
from __future__ import annotations

import os
import sys

# runnable without `pip install -e .`
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def text_generation(model_dir: str) -> None:
    from perceiver_io_tpu.data.text.tokenizers import ByteTokenizer
    from perceiver_io_tpu.inference import pipeline_from_pretrained

    pipe = pipeline_from_pretrained(
        "text-generation", model_dir, ByteTokenizer(padding_side="left")
    )
    print(pipe("A man walked into", max_new_tokens=64, num_latents=64, top_k=40)[0])
    # deterministic beam decode (HF generate(num_beams=k) semantics)
    print(pipe("A man walked into", max_new_tokens=64, num_latents=64, num_beams=4)[0])


def fill_mask(model_dir: str) -> None:
    from perceiver_io_tpu.data.text.preprocessor import TextPreprocessor
    from perceiver_io_tpu.data.text.tokenizers import ByteTokenizer
    from perceiver_io_tpu.inference import pipeline_from_pretrained

    prep = TextPreprocessor(ByteTokenizer(), max_seq_len=2048)
    pipe = pipeline_from_pretrained("fill-mask", model_dir, prep)
    print(pipe("I watched this <mask> and it was awesome", top_k=5))


def sentiment(model_dir: str) -> None:
    from perceiver_io_tpu.data.text.preprocessor import TextPreprocessor
    from perceiver_io_tpu.data.text.tokenizers import ByteTokenizer
    from perceiver_io_tpu.inference import pipeline_from_pretrained

    prep = TextPreprocessor(ByteTokenizer(), max_seq_len=2048)
    pipe = pipeline_from_pretrained("sentiment-analysis", model_dir, prep)
    print(pipe(["I admire this movie", "terrible, save your money"]))


def image_classification(model_dir: str) -> None:
    from perceiver_io_tpu.inference import pipeline_from_pretrained

    pipe = pipeline_from_pretrained("image-classification", model_dir)
    images = np.random.default_rng(0).integers(0, 256, (2, 28, 28), dtype=np.uint8)
    print(pipe(images, top_k=3))


def optical_flow(model_dir: str) -> None:
    from perceiver_io_tpu.inference import pipeline_from_pretrained

    pipe = pipeline_from_pretrained("optical-flow", model_dir, render=True)
    rng = np.random.default_rng(0)
    frame1 = rng.integers(0, 256, (368, 496, 3), dtype=np.uint8)
    frame2 = np.roll(frame1, 4, axis=1)
    print(pipe((frame1, frame2)).shape)  # (368, 496, 3) rendered RGB


def serving(model_dir: str) -> None:
    """Shape-bucketed serving over mixed-length traffic (docs/serving.md):
    warmup compiles every bucket ahead of time, ragged prompts are
    micro-batched onto the static executor grid, and the stats show the
    retracing that did NOT happen (compiles bounded by the grid)."""
    from perceiver_io_tpu.data.text.tokenizers import ByteTokenizer
    from perceiver_io_tpu.inference import pipeline_from_pretrained
    from perceiver_io_tpu.serving import BucketTable

    pipe = pipeline_from_pretrained(
        "text-generation", model_dir, ByteTokenizer(padding_side="left"),
        bucketing=True,
        bucket_table=BucketTable(prompt_lens=(64, 128, 256), batch_sizes=(1, 2, 4, 8)),
    )
    pipe.warmup(max_new_tokens=32, num_latents=64)
    prompts = [
        "A man walked into",
        "Once",
        "The history of the region begins with",
        "It was a dark and stormy night, and the",
    ]
    for text in pipe(prompts, max_new_tokens=32, num_latents=64, temperature=0.0):
        print(repr(text))
    print(pipe.serving_stats())


def symbolic_audio(model_dir: str) -> None:
    from perceiver_io_tpu.inference import pipeline_from_pretrained

    pipe = pipeline_from_pretrained("symbolic-audio-generation", model_dir)
    prompt = np.asarray([60, 256 + 49, 128 + 60], np.int32)  # C4 quarter note
    events = pipe(prompt, max_new_tokens=512, num_latents=1, top_p=0.95)[0]
    print(f"generated {len(events)} events")
    # pipe.generate_midi(prompt, path="out.mid")  # requires pretty_midi


DEMOS = {
    "text-generation": text_generation,
    "fill-mask": fill_mask,
    "sentiment-analysis": sentiment,
    "image-classification": image_classification,
    "optical-flow": optical_flow,
    "symbolic-audio-generation": symbolic_audio,
    "serving": serving,
}

if __name__ == "__main__":
    if len(sys.argv) != 3 or sys.argv[1] not in DEMOS:
        raise SystemExit(f"usage: python examples/inference.py {{{'|'.join(DEMOS)}}} <model_dir>")
    DEMOS[sys.argv[1]](sys.argv[2])
