#!/usr/bin/env bash
# Perceiver IO MNIST classifier — reference examples/training/img_clf.
python -m perceiver_io_tpu.scripts.vision.image_classifier fit \
  --data=mnist \
  --data.batch_size=128 \
  --model.num_latents=32 \
  --model.num_latent_channels=128 \
  --optimizer.lr=1e-3 \
  --trainer.max_steps=5000 \
  --trainer.default_root_dir=logs/img_clf
