#!/usr/bin/env bash
# Perceiver AR symbolic audio on GiantMIDI — reference examples/training/sam.
# Effective batch 32 = the reference's 8/device x 2 devices x
# accumulate_grad_batches=2; 8-row microbatches via grad_accum_steps=4.
python -m perceiver_io_tpu.scripts.audio.symbolic fit \
  --data=giantmidi \
  --data.dataset_dir=.cache/giantmidi \
  --data.max_seq_len=6144 \
  --data.min_seq_len=4096 \
  --data.batch_size=32 \
  --trainer.grad_accum_steps=4 \
  --model.max_latents=2048 \
  --model.num_channels=768 \
  --optimizer.lr=2e-4 \
  --trainer.max_steps=50000 \
  --trainer.default_root_dir=logs/sam
