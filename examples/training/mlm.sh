#!/usr/bin/env bash
# Perceiver IO masked LM (UTF-8 bytes) — reference examples/training/mlm/train.sh.
python -m perceiver_io_tpu.scripts.text.mlm fit \
  --data=wikitext \
  --data.dataset_dir=.cache/wikitext \
  --data.task=mlm \
  --data.max_seq_len=2048 \
  --data.batch_size=32 \
  --model.num_latents=256 \
  --model.num_latent_channels=1280 \
  --optimizer.lr=1e-4 \
  --lr_scheduler.warmup_steps=1000 \
  --trainer.max_steps=50000 \
  --trainer.default_root_dir=logs/mlm
