#!/usr/bin/env bash
# Perceiver AR causal LM on WikiText-103 raw (UTF-8 bytes) — the reference's
# examples/training/clm/train.sh configuration on a TPU mesh. Effective batch
# 80 = the reference's 20/device x 2 devices x accumulate_grad_batches=2;
# grad_accum_steps=4 bounds activation memory to 20-row microbatches.
python -m perceiver_io_tpu.scripts.text.clm fit \
  --data=wikitext \
  --data.dataset_dir=.cache/wikitext \
  --data.max_seq_len=4096 \
  --data.batch_size=80 \
  --model.max_latents=512 \
  --model.num_channels=512 \
  --model.num_self_attention_layers=8 \
  --model.cross_attention_dropout=0.5 \
  --trainer.grad_accum_steps=4 \
  --trainer.steps_per_execution=8 \
  --optimizer.lr=2e-4 \
  --lr_scheduler.warmup_steps=200 \
  --trainer.max_steps=25000 \
  --trainer.val_check_interval=1000 \
  --trainer.save_state_every_n_steps=1000 \
  --trainer.default_root_dir=logs/clm
# Preempted? Re-run with --trainer.resume=logs/clm to continue exactly
# where the snapshot left off (same loss trajectory).
