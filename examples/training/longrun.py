"""Sustained-training evidence run (VERDICT r4 ask #2).

Drives the real family CLI (``perceiver_io_tpu.scripts.text.clm``) through a
thousands-of-steps training job on the deterministic synthetic Markov corpus,
deliberately interrupting it twice:

- **SIGTERM** mid-run — the preemption path: the trainer snapshots the full
  TrainState on the way out (``training/trainer.py``), as on a TPU-pod
  eviction notice.
- **SIGKILL** mid-run — the crash path: no goodbye snapshot; resume falls
  back to the latest periodic ``save_state_every_n_steps`` snapshot and the
  loss trajectory must continue as if uninterrupted (per-step rng is
  fold_in-derived and the data stream is fast-forwarded).

After the final phase completes, the analyzer:

1. checks ``metrics.jsonl`` step continuity across both resume seams,
2. compares the final train/val loss against the corpus's *computable*
   conditional-entropy floor — the synthetic corpus is an order-1 Markov
   chain over a seeded transition matrix (``data/text/sources.py``), so a
   correctly-learning model's CE must approach
   ``H = -sum_s pi_s sum_t P[s,t] ln P[s,t]`` and cannot go below it,
3. writes a downsampled loss curve (``curve.csv``) + ``summary.json`` for
   ``docs/training-examples.md``.

Usage::

    python examples/training/longrun.py --root runs/longrun          # full
    python examples/training/longrun.py --root /tmp/lr --max-steps 60 \
        --kill1 20 --kill2 40 --channels 64 --layers 2 \
        --seq 128 --latents 64 --train-docs 16 --val-every 20 \
        --log-every 5 --snap-every 10                                # smoke
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)


def cli_cmd(args, resume: bool) -> list:
    cmd = [
        sys.executable, "-m", "perceiver_io_tpu.scripts.text.clm", "fit",
        "--data=synthetic",
        f"--data.dataset_dir={args.root}/data",
        f"--data.num_train_docs={args.train_docs}",
        "--data.num_valid_docs=32",
        f"--data.doc_chars={args.doc_chars}",
        f"--data.max_seq_len={args.seq}",
        f"--data.batch_size={args.batch}",
        f"--model.max_latents={args.latents}",
        f"--model.num_channels={args.channels}",
        f"--model.num_self_attention_layers={args.layers}",
        "--optimizer.lr=1e-3",
        f"--trainer.max_steps={args.max_steps}",
        f"--trainer.val_check_interval={args.val_every}",
        f"--trainer.log_every_n_steps={args.log_every}",
        f"--trainer.save_state_every_n_steps={args.snap_every}",
        "--trainer.steps_per_execution=2",
        "--trainer.grad_clip_norm=1.0",
        f"--trainer.default_root_dir={args.root}/run",
    ]
    if resume:
        cmd.append(f"--trainer.resume={args.root}/run")
    return cmd


def child_env(args) -> dict:
    """CPU children must not claim the accelerator: on hosts whose
    sitecustomize force-registers a TPU plugin when ``PALLAS_AXON_POOL_IPS``
    is set, a dead relay makes the PJRT claim hang rather than error — so
    the axon trigger vars are stripped and CPU is forced. ``--tpu`` keeps
    the inherited environment for a real on-chip run."""
    env = dict(os.environ)
    if not args.tpu:
        for var in ("PALLAS_AXON_POOL_IPS", "PALLAS_AXON_REMOTE_COMPILE"):
            env.pop(var, None)
        env["JAX_PLATFORMS"] = "cpu"
    # A leaked virtual-device-count flag (e.g. from the test suite's
    # conftest) would give the CLI an N-device mesh the tiny batch cannot
    # shard over — this run is a single-device evidence run either way.
    flags = [
        f for f in env.get("XLA_FLAGS", "").split()
        if not f.startswith("--xla_force_host_platform_device_count")
    ]
    if flags:
        env["XLA_FLAGS"] = " ".join(flags)
    else:
        env.pop("XLA_FLAGS", None)
    return env


def run_phase(args, name: str, resume: bool, kill_at: int | None,
              kill_sig: int | None, events: list) -> int:
    """Run one CLI invocation; optionally kill it once metrics.jsonl passes
    ``kill_at`` steps. Returns the subprocess return code.

    A per-phase wall-clock watchdog (``--phase-timeout``) bounds every
    phase: a child that hangs (dead data source, wedged backend claim) is
    SIGKILLed with the tail of its log as diagnostic instead of blocking
    the orchestrator forever (ADVICE r5)."""
    log_path = os.path.join(args.root, f"{name}.log")
    log = open(log_path, "w")
    t0 = time.time()
    proc = subprocess.Popen(
        cli_cmd(args, resume), cwd=REPO, stdout=log, stderr=subprocess.STDOUT,
        env=child_env(args),
    )
    metrics = os.path.join(args.root, "run", "metrics.jsonl")
    sent = None
    watchdog_fired = False
    while proc.poll() is None:
        time.sleep(2.0)
        if args.phase_timeout and time.time() - t0 > args.phase_timeout:
            watchdog_fired = True
            proc.kill()
            proc.wait()
            break
        if kill_at is not None and sent is None and os.path.exists(metrics):
            last = latest_step(metrics)
            if last >= kill_at:
                sent = kill_sig
                proc.send_signal(kill_sig)
                events.append({"event": f"sent signal {kill_sig} ({name})",
                               "at_step": last, "t": round(time.time() - t0, 1)})
    log.close()
    if watchdog_fired:
        events.append({"event": f"watchdog killed {name}",
                       "timeout_s": args.phase_timeout,
                       "wall_s": round(time.time() - t0, 1)})
        with open(log_path) as fh:
            tail = "".join(fh.readlines()[-20:])
        raise SystemExit(
            f"[longrun] watchdog: {name} exceeded --phase-timeout="
            f"{args.phase_timeout:.0f}s and was SIGKILLed; last step seen: "
            f"{latest_step(metrics) if os.path.exists(metrics) else 'none'}. "
            f"Tail of {log_path}:\n{tail}"
        )
    events.append({"event": f"{name} exited", "rc": proc.returncode,
                   "wall_s": round(time.time() - t0, 1)})
    print(f"[longrun] {name}: rc={proc.returncode} "
          f"wall={time.time() - t0:.0f}s", flush=True)
    return proc.returncode


def latest_step(metrics_path: str) -> int:
    last = 0
    with open(metrics_path) as f:
        for line in f:
            try:
                last = max(last, json.loads(line).get("step", 0))
            except json.JSONDecodeError:
                pass  # partial trailing line mid-write
    return last


def markov_entropy_floor(corpus_seed: int = 0) -> float:
    """Conditional entropy (nats/char) of the synthetic corpus's Markov
    source. The transition matrix comes from the SAME function the
    datamodule draws it from (``sources.markov_transition``, first draw of
    ``default_rng(corpus_seed)``), so this floor cannot silently diverge
    from the corpus construction."""
    import numpy as np

    from perceiver_io_tpu.data.text.sources import markov_transition

    trans = markov_transition(np.random.default_rng(corpus_seed))
    # stationary distribution: left eigenvector of the transition matrix
    evals, evecs = np.linalg.eig(trans.T)
    pi = np.real(evecs[:, np.argmax(np.real(evals))])
    pi = np.abs(pi) / np.abs(pi).sum()
    h_rows = -(trans * np.log(np.clip(trans, 1e-30, None))).sum(axis=1)
    return float((pi * h_rows).sum())


def analyze(args, events: list) -> dict:
    metrics = os.path.join(args.root, "run", "metrics.jsonl")
    rows = []
    with open(metrics) as f:
        for line in f:
            try:
                rows.append(json.loads(line))
            except json.JSONDecodeError:
                pass  # torn line from the SIGKILL phase, mid-write
    train = [(r["step"], r["train/loss"]) for r in rows if "train/loss" in r]
    val = [(r["step"], r["val/loss"]) for r in rows if "val/loss" in r]

    # 1. continuity + replay equality. metrics.jsonl is append-only across
    # resumes, so a SIGKILL that lost progress since the last periodic
    # snapshot produces overlapping step ranges at the seam. Those replayed
    # steps are the strongest evidence in the file: fold_in-derived rng plus
    # a fast-forwarded data stream mean the resumed process must reproduce
    # the killed process's losses at the same steps.
    seen: dict = {}
    seams = replayed = 0
    prev_step = 0
    for s, l in train:
        if s <= prev_step:
            seams += 1
        if s in seen:
            replayed += 1
            assert abs(seen[s] - l) <= 1e-5 * max(1.0, abs(l)), (
                f"resume replay diverged at step {s}: {seen[s]} vs {l}"
            )
        seen[s] = l
        prev_step = s
    train = sorted(seen.items())
    # final flush lands on the last log boundary at or before max_steps
    expected_last = args.max_steps - (args.max_steps % args.log_every)
    assert train[-1][0] >= expected_last, f"run incomplete: {train[-1][0]}"
    val = sorted(dict(val).items())

    floor = markov_entropy_floor()
    final_train = train[-1][1]
    final_val = val[-1][1] if val else None
    # 2. sanity: the CE floor is never crossed (which would mean leakage or a
    # loss bug, not learning); closeness to the floor is reported, not gated.
    # Slack 0.05 nats: each logged loss is a finite-batch mean (~10k tokens
    # per flush window → std ~0.015 nats), so a converged run's min-of-tail
    # can dip slightly below the asymptotic floor by sampling noise.
    tail = [l for _, l in train[-10:]]
    assert min(tail) >= floor - 0.05, f"loss {min(tail)} below entropy floor {floor}"

    with open(os.path.join(args.root, "curve.csv"), "w") as f:
        f.write("step,train_loss\n")
        stride = max(1, len(train) // 200)
        for s, l in train[::stride]:
            f.write(f"{s},{l:.4f}\n")
        if train[-1][0] % stride:
            f.write(f"{train[-1][0]},{train[-1][1]:.4f}\n")
    with open(os.path.join(args.root, "val_curve.csv"), "w") as f:
        f.write("step,val_loss\n")
        for s, l in val:
            f.write(f"{s},{l:.4f}\n")

    summary = {
        "config": {
            "model": f"Perceiver AR, {args.channels}ch x {args.layers} layers, "
                     f"ctx {args.seq} / {args.latents} latents, vocab 262",
            "data": f"synthetic order-1 Markov corpus, {args.train_docs} docs "
                    f"x {args.doc_chars} chars, batch {args.batch}",
            "steps_per_execution": 2,
        },
        "max_steps": args.max_steps,
        "final_train_loss": round(final_train, 4),
        "final_val_loss": round(final_val, 4) if final_val is not None else None,
        "entropy_floor_nats": round(floor, 4),
        "gap_to_floor": round(final_train - floor, 4),
        "resume_seams": seams,
        "replayed_steps_checked": replayed,
        "events": events,
    }
    with open(os.path.join(args.root, "summary.json"), "w") as f:
        json.dump(summary, f, indent=2)
    print(json.dumps(summary, indent=2), flush=True)
    return summary


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--root", required=True)
    p.add_argument("--max-steps", type=int, default=3000)
    p.add_argument("--kill1", type=int, default=1200, help="SIGTERM after this step")
    p.add_argument("--kill2", type=int, default=2100, help="SIGKILL after this step")
    p.add_argument("--batch", type=int, default=2)
    p.add_argument("--seq", type=int, default=1024)
    p.add_argument("--latents", type=int, default=512)
    # 256ch x 8 layers at ctx 1024/512 latents: ~1.5 s/step on the sandbox's
    # single CPU core (512ch measured 6.4 s/step — 3000 steps would be 5+ h)
    p.add_argument("--channels", type=int, default=256)
    p.add_argument("--layers", type=int, default=8)
    p.add_argument("--train-docs", type=int, default=512)
    p.add_argument("--doc-chars", type=int, default=8192)
    p.add_argument("--val-every", type=int, default=250)
    p.add_argument("--log-every", type=int, default=10)
    p.add_argument("--snap-every", type=int, default=200)
    p.add_argument("--tpu", action="store_true",
                   help="inherit the accelerator environment instead of "
                   "forcing CPU children")
    p.add_argument("--phase-timeout", type=float, default=7200.0,
                   help="per-phase wall-clock watchdog in seconds; a phase "
                   "that outlives it is SIGKILLed with a diagnostic "
                   "(0 disables)")
    args = p.parse_args()

    # Replay-equality at the SIGKILL seam compares window-averaged losses,
    # which only line up when resume points land on log boundaries.
    if args.snap_every % args.log_every:
        raise SystemExit(
            f"--snap-every ({args.snap_every}) must be a multiple of "
            f"--log-every ({args.log_every}) so resumed flush windows align "
            "with the killed run's for the replay-equality check"
        )
    os.makedirs(args.root, exist_ok=True)
    events: list = []

    rc = run_phase(args, "phase1", resume=False, kill_at=args.kill1,
                   kill_sig=signal.SIGTERM, events=events)
    events.append({"note": f"phase1 rc={rc} (SIGTERM preemption)"})
    rc = run_phase(args, "phase2", resume=True, kill_at=args.kill2,
                   kill_sig=signal.SIGKILL, events=events)
    events.append({"note": f"phase2 rc={rc} (SIGKILL crash)"})
    rc = run_phase(args, "phase3", resume=True, kill_at=None,
                   kill_sig=None, events=events)
    if rc != 0:
        raise SystemExit(f"final phase failed rc={rc}")
    analyze(args, events)


if __name__ == "__main__":
    main()
