#!/usr/bin/env bash
# Two-stage IMDb classifier — reference examples/training/txt_clf:
# stage 1: frozen pretrained MLM encoder, train decoder only.
python -m perceiver_io_tpu.scripts.text.classifier fit \
  --data=imdb \
  --data.dataset_dir=.cache/imdb \
  --data.task=clf \
  --model.encoder.params=logs/mlm/checkpoints/best \
  --model.encoder.freeze=true \
  --optimizer.lr=1e-3 \
  --trainer.max_steps=5000 \
  --trainer.default_root_dir=logs/txt_clf_stage1
# stage 2: unfreeze everything and fine-tune.
python -m perceiver_io_tpu.scripts.text.classifier fit \
  --data=imdb \
  --data.dataset_dir=.cache/imdb \
  --data.task=clf \
  --model.encoder.params=logs/mlm/checkpoints/best \
  --model.encoder.freeze=false \
  --optimizer.lr=5e-5 \
  --trainer.max_steps=5000 \
  --trainer.default_root_dir=logs/txt_clf_stage2
