"""Benchmark: Perceiver AR 8k-context training-step throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

The reference publishes no throughput numbers (BASELINE.md), so the baseline
is the north star from BASELINE.json: **0.8× an A100 on the same step**. The
A100 step time is estimated analytically: training FLOPs (fwd + 2× bwd) on
the same configuration at 312 bf16 TFLOP/s × 40% MFU — a generous MFU for
the reference's eager torch implementation (no flash attention, no fusion;
measured MFUs for it would be lower, making this baseline conservative).

``vs_baseline`` > 1.0 means this framework beats that target.

Config: the 8k-context north-star shape (BASELINE.json `configs`): Perceiver
AR, vocab 262 (UTF-8 bytes), 8192 ctx / 1024 latents, 512 channels, 8 layers
— the reference's WikiText-103 model (reference
``examples/training/clm/train.py``) widened to the 8k context it targets for
long-context work (``docs/training-examples.md:158-162`` scale).

Self-defence (the round-1 TPU backend hung on a bare matmul): the parent
process never touches jax. It runs (1) a backend probe, (2) the benchmark,
each in a subprocess with a hard timeout and retry-with-backoff on
flaky-backend failures; if the accelerator is unusable it falls back to a
reduced-shape CPU run so a real measured number is always emitted; and it
ALWAYS prints a parseable JSON line before exiting, even on total failure.
All stage progress goes to stderr so hangs are attributable.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time

GLOBAL_DEADLINE_S = 540.0  # parent always prints JSON before this
_T0 = time.monotonic()

METRIC = "perceiver_ar_8k_train_tokens_per_sec_per_chip"

A100_BF16_FLOPS = 312e12
A100_ASSUMED_MFU = 0.40
BASELINE_FACTOR = 0.8  # north star: >= 0.8x A100 step time

# (batch, seq, latents, channels, heads, layers)
FULL_SHAPE = (8, 8192, 1024, 512, 8, 8)
CPU_SHAPE = (1, 2048, 256, 256, 8, 4)  # reduced fallback, still the same model


def log(msg: str) -> None:
    print(f"[bench +{time.monotonic() - _T0:6.1f}s] {msg}", file=sys.stderr, flush=True)


def remaining() -> float:
    return GLOBAL_DEADLINE_S - (time.monotonic() - _T0)


# ---------------------------------------------------------------- child side


def _mk_config(shape):
    from perceiver_io_tpu.models.text.clm import CausalLanguageModelConfig

    batch, seq, latents, channels, heads, layers = shape
    return CausalLanguageModelConfig(
        vocab_size=262,
        max_seq_len=seq,
        max_latents=latents,
        num_channels=channels,
        num_heads=heads,
        num_self_attention_layers=layers,
        cross_attention_dropout=0.5,
    )


def training_flops(cfg, batch: int) -> float:
    """Analytic training FLOPs per step (fwd + 2x bwd = 3x fwd), mirroring the
    reference's scaling-study estimator (reference
    ``examples/scaling/clm/scaling/flops.py:7-190``): dense matmul FLOPs +
    attention score/value FLOPs."""
    n, m, c = cfg.max_seq_len, cfg.max_latents, cfg.num_channels
    v, L = cfg.vocab_size, cfg.num_self_attention_layers
    wf_cross, wf_self = (
        cfg.cross_attention_widening_factor,
        cfg.self_attention_widening_factor,
    )
    cross = 2 * (m * c * c + 2 * n * c * c + m * c * c) + 2 * (2 * m * c * wf_cross * c)
    cross_attn = 2 * 2 * m * n * c  # scores + weighted values
    self_ = 2 * (4 * m * c * c) + 2 * (2 * m * c * wf_self * c)
    self_attn = 2 * 2 * m * m * c
    head = 2 * m * c * v
    fwd = cross + cross_attn + L * (self_ + self_attn) + head
    return 3.0 * batch * fwd


def child_probe() -> None:
    """Initialize the backend and run one tiny matmul + model step."""
    log("probe: importing jax")
    import jax
    import jax.numpy as jnp

    log(f"probe: backend={jax.default_backend()} devices={jax.devices()}")
    x = jnp.ones((256, 256), jnp.bfloat16)
    jax.block_until_ready(x @ x)
    log("probe: matmul OK")
    print("PROBE_OK", flush=True)


def child_run(shape, out_path: str, force_cpu: bool = False) -> None:
    import jax

    if force_cpu:
        # The sitecustomize force-registers the axon plugin and overrides
        # JAX_PLATFORMS; CPU must be re-forced via jax.config after import.
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    import optax

    from perceiver_io_tpu.models.text.clm import CausalLanguageModel
    from perceiver_io_tpu.parallel import (
        create_train_state,
        make_train_step,
        shard_batch,
        single_device_mesh,
    )
    from perceiver_io_tpu.training.tasks import clm_loss_fn

    platform = jax.default_backend()
    log(f"run: backend={platform} shape={shape}")
    batch_size = shape[0]
    cfg = _mk_config(shape)
    mesh = single_device_mesh(jax.devices()[0])

    def build(attention_impl: str):
        model = CausalLanguageModel(cfg, dtype=jnp.bfloat16, attention_impl=attention_impl)
        prefix_len = cfg.max_seq_len - cfg.max_latents

        def init():
            return model.init(
                jax.random.PRNGKey(0),
                jnp.zeros((1, cfg.max_seq_len), jnp.int32),
                prefix_len,
            )["params"]

        tx = optax.adamw(3e-4)
        state, shardings = create_train_state(init, tx, mesh)
        step = make_train_step(clm_loss_fn(model, cfg.max_latents), mesh, shardings)
        return state, step

    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, size=(batch_size, cfg.max_seq_len + 1), dtype=np.int32)
    batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:]}

    with mesh:
        # Small-shape smoke step first so a hang here is attributable to the
        # backend, not to the big compile.
        log("run: smoke step (tiny shapes)")
        smoke_cfg_shape = (1, 64, 16, 32, 4, 1)
        smoke_cfg = _mk_config(smoke_cfg_shape)
        smoke_model = CausalLanguageModel(smoke_cfg, dtype=jnp.bfloat16)
        smoke_ids = jnp.zeros((1, smoke_cfg.max_seq_len), jnp.int32)
        smoke_params = smoke_model.init(
            jax.random.PRNGKey(0), smoke_ids, smoke_cfg.max_seq_len - smoke_cfg.max_latents
        )
        jax.block_until_ready(
            smoke_model.apply(
                smoke_params, smoke_ids, smoke_cfg.max_seq_len - smoke_cfg.max_latents
            )
        )
        log("run: smoke OK; compiling main step")

        sharded = shard_batch(batch, mesh)
        key = jax.random.PRNGKey(1)
        # 'auto' resolves to the Pallas flash kernel on TPU, XLA einsum elsewhere.
        impl_used = "flash" if platform == "tpu" else "xla"
        try:
            state, step = build("auto")
            state, metrics = step(state, sharded, key)
            jax.block_until_ready(metrics["loss"])
        except Exception as e:  # Pallas path failed on this backend
            log(f"run: flash path failed ({type(e).__name__}: {e}); retrying with xla")
            impl_used = "xla"
            state = step = metrics = None
            state, step = build("xla")
            state, metrics = step(state, sharded, key)
            jax.block_until_ready(metrics["loss"])
        log("run: compile+warmup done; timing")

        n_steps = 10 if platform != "cpu" else 3
        t0 = time.perf_counter()
        for i in range(n_steps):
            state, metrics = step(state, sharded, jax.random.fold_in(key, i))
        jax.block_until_ready(metrics["loss"])
        dt = (time.perf_counter() - t0) / n_steps
    log(f"run: {n_steps} steps, {dt * 1e3:.1f} ms/step")

    tokens_per_sec = batch_size * cfg.max_seq_len / dt
    flops = training_flops(cfg, batch_size)
    a100_step_time = flops / (A100_BF16_FLOPS * A100_ASSUMED_MFU)
    baseline_step_time = a100_step_time / BASELINE_FACTOR
    result = {
        "metric": METRIC,
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(baseline_step_time / dt, 3),
        "platform": platform,
        "attention_impl": impl_used,
        "step_time_ms": round(dt * 1e3, 2),
        "mfu": round(flops / dt / _peak_flops(platform), 4) if _peak_flops(platform) else None,
        "shape": list(shape),
    }
    with open(out_path, "w") as f:
        json.dump(result, f)
    log(f"run: wrote {out_path}")


def _peak_flops(platform: str) -> float:
    # v5p bf16 peak ~459 TFLOP/s; only meaningful on the TPU platform.
    return 459e12 if platform not in ("cpu",) else 0.0


# --------------------------------------------------------------- parent side


def _spawn(args, timeout, env_extra=None):
    env = dict(os.environ)
    if env_extra:
        env.update(env_extra)
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), *args],
            env=env,
            stdout=subprocess.PIPE,
            stderr=sys.stderr,
            text=True,
            timeout=timeout,
        )
        return proc.returncode, proc.stdout or ""
    except subprocess.TimeoutExpired:
        return -1, "TIMEOUT"


def main() -> None:
    result = None
    note = []

    # Stage 1: probe the default (accelerator) backend, with retry/backoff.
    accel_ok = False
    for attempt in range(2):
        budget = min(90.0, remaining() - 240.0)
        if budget < 20.0:
            note.append("probe skipped: out of time budget")
            break
        log(f"probe attempt {attempt + 1} (timeout {budget:.0f}s)")
        rc, out = _spawn(["--probe"], timeout=budget)
        if rc == 0 and "PROBE_OK" in out:
            accel_ok = True
            break
        log(f"probe attempt {attempt + 1} failed (rc={rc})")
        note.append(f"accelerator probe attempt {attempt + 1} failed rc={rc}")
        time.sleep(5 * (attempt + 1))

    # Stage 2: the real benchmark on the accelerator.
    if accel_ok:
        budget = max(60.0, remaining() - 170.0)
        with tempfile.NamedTemporaryFile(suffix=".json", delete=False) as f:
            out_path = f.name
        log(f"accelerator benchmark (timeout {budget:.0f}s)")
        rc, _ = _spawn(["--run", "full", out_path], timeout=budget)
        if rc == 0 and os.path.exists(out_path) and os.path.getsize(out_path) > 0:
            with open(out_path) as f:
                result = json.load(f)
        else:
            note.append(f"accelerator benchmark failed rc={rc}")
            log(f"accelerator benchmark failed (rc={rc})")

    # Stage 3: CPU fallback with reduced shapes so a measured number exists.
    if result is None:
        budget = max(60.0, remaining() - 20.0)
        with tempfile.NamedTemporaryFile(suffix=".json", delete=False) as f:
            out_path = f.name
        log(f"cpu fallback benchmark (timeout {budget:.0f}s)")
        rc, _ = _spawn(["--run", "cpu", out_path], timeout=budget)
        if rc == 0 and os.path.exists(out_path) and os.path.getsize(out_path) > 0:
            with open(out_path) as f:
                result = json.load(f)
            note.append("accelerator unavailable; value measured on CPU at reduced shape")
        else:
            note.append(f"cpu fallback failed rc={rc}")
            log(f"cpu fallback failed (rc={rc})")

    if result is None:
        result = {
            "metric": METRIC,
            "value": 0.0,
            "unit": "tokens/s",
            "vs_baseline": 0.0,
        }
    if note:
        result["note"] = "; ".join(note)
    print(json.dumps(result), flush=True)


if __name__ == "__main__":
    if len(sys.argv) >= 2 and sys.argv[1] == "--probe":
        child_probe()
    elif len(sys.argv) >= 4 and sys.argv[1] == "--run":
        if sys.argv[2] == "full":
            child_run(FULL_SHAPE, sys.argv[3])
        else:
            child_run(CPU_SHAPE, sys.argv[3], force_cpu=True)
    else:
        main()
