"""Benchmark: Perceiver AR 8k-context training-step throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no throughput numbers (BASELINE.md), so the baseline
is the north star from BASELINE.json: **0.8× an A100 on the same step**. The
A100 step time is estimated analytically: training FLOPs (fwd + 2× bwd) on
the same configuration at 312 bf16 TFLOP/s × 40% MFU — a generous MFU for
the reference's eager torch implementation (no flash attention, no fusion;
measured MFUs for it would be lower, making this baseline conservative).

``vs_baseline`` > 1.0 means this framework beats that target.

Config: the 8k-context north-star shape (BASELINE.json `configs`): Perceiver
AR, vocab 262 (UTF-8 bytes), 8192 ctx / 1024 latents, 512 channels, 8 layers
— the reference's WikiText-103 model (reference
``examples/training/clm/train.py``) widened to the 8k context it targets for
long-context work (``docs/training-examples.md:158-162`` scale).
"""
from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from perceiver_io_tpu.models.text.clm import CausalLanguageModel, CausalLanguageModelConfig
from perceiver_io_tpu.parallel import create_train_state, make_train_step, shard_batch, single_device_mesh
from perceiver_io_tpu.training.tasks import clm_loss_fn

BATCH = 8
CFG = CausalLanguageModelConfig(
    vocab_size=262,
    max_seq_len=8192,
    max_latents=1024,
    num_channels=512,
    num_heads=8,
    num_self_attention_layers=8,
    cross_attention_dropout=0.5,
)

A100_BF16_FLOPS = 312e12
A100_ASSUMED_MFU = 0.40
BASELINE_FACTOR = 0.8  # north star: >= 0.8x A100 step time


def training_flops(cfg: CausalLanguageModelConfig, batch: int) -> float:
    """Analytic training FLOPs per step (fwd + 2x bwd = 3x fwd), mirroring the
    reference's scaling-study estimator (reference
    ``examples/scaling/clm/scaling/flops.py:7-190``): dense matmul FLOPs +
    attention score/value FLOPs."""
    n, m, c = cfg.max_seq_len, cfg.max_latents, cfg.num_channels
    v, L = cfg.vocab_size, cfg.num_self_attention_layers
    wf_cross, wf_self = cfg.cross_attention_widening_factor, cfg.self_attention_widening_factor
    # Cross-attention block: q over m, k/v over n, out over m, MLP over m.
    cross = 2 * (m * c * c + 2 * n * c * c + m * c * c) + 2 * (2 * m * c * wf_cross * c)
    cross_attn = 2 * 2 * m * n * c  # scores + weighted values
    # Self-attention layer over m latents.
    self_ = 2 * (4 * m * c * c) + 2 * (2 * m * c * wf_self * c)
    self_attn = 2 * 2 * m * m * c
    # Embedding lookup is a gather; output head is a matmul over m.
    head = 2 * m * c * v
    fwd = cross + cross_attn + L * (self_ + self_attn) + head
    return 3.0 * batch * fwd


def _build(mesh, attention_impl: str):
    model = CausalLanguageModel(CFG, dtype=jnp.bfloat16, attention_impl=attention_impl)
    prefix_len = CFG.max_seq_len - CFG.max_latents

    def init():
        return model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, CFG.max_seq_len), jnp.int32), prefix_len
        )["params"]

    tx = optax.adamw(3e-4)
    state, shardings = create_train_state(init, tx, mesh)
    step = make_train_step(clm_loss_fn(model, CFG.max_latents), mesh, shardings)
    return state, step


def main() -> None:
    devices = jax.devices()
    mesh = single_device_mesh(devices[0])

    rng = np.random.default_rng(0)
    ids = rng.integers(0, CFG.vocab_size, size=(BATCH, CFG.max_seq_len + 1), dtype=np.int32)
    batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:]}

    with mesh:
        sharded = shard_batch(batch, mesh)
        key = jax.random.PRNGKey(1)
        # Warmup / compile; if the Pallas flash path fails to compile on this
        # backend, fall back to the XLA einsum attention rather than dying.
        try:
            state, step = _build(mesh, "auto")
            state, metrics = step(state, sharded, key)
            jax.block_until_ready(metrics["loss"])
        except Exception as e:
            print(
                f"flash-attention path failed ({type(e).__name__}: {e}); "
                "retrying with xla attention",
                file=sys.stderr,
                flush=True,
            )
            state = step = metrics = None  # release device buffers before rebuild
            state, step = _build(mesh, "xla")
            state, metrics = step(state, sharded, key)
            jax.block_until_ready(metrics["loss"])
        # Timed steps.
        n_steps = 10
        t0 = time.perf_counter()
        for i in range(n_steps):
            state, metrics = step(state, sharded, jax.random.fold_in(key, i))
        jax.block_until_ready(metrics["loss"])
        dt = (time.perf_counter() - t0) / n_steps

    tokens_per_sec = BATCH * CFG.max_seq_len / dt
    flops = training_flops(CFG, BATCH)
    a100_step_time = flops / (A100_BF16_FLOPS * A100_ASSUMED_MFU)
    baseline_step_time = a100_step_time / BASELINE_FACTOR  # 0.8x a100 time target
    vs_baseline = baseline_step_time / dt  # >1 == faster than target

    print(
        json.dumps(
            {
                "metric": "perceiver_ar_8k_train_tokens_per_sec_per_chip",
                "value": round(tokens_per_sec, 1),
                "unit": "tokens/s",
                "vs_baseline": round(vs_baseline, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
