"""Benchmark: Perceiver AR 8k-context training throughput on one chip, plus
the Perceiver IO MLM training config, cached-decode throughput, a
mixed-length bucketed-serving probe (``extras.serve``: tokens/s,
compile_count, p50/p95 queue wait — the serving-layer trajectory), and an
instrumented telemetry probe (``extras.observability``: per-phase latency
histograms, goodput, MFU gauges; docs/observability.md).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...} with
secondary metrics under "extras"; the record also carries the process-wide
registry snapshot (``metrics_snapshot``) and the device-cost ledger
(``compile_ledger``: per-executor compile time, XLA cost/memory analysis,
retrace attribution — docs/observability.md) so BENCH_* files ship
telemetry and are ``obs report``-able offline.

The reference publishes no throughput numbers (BASELINE.md), so the baseline
is the north star from BASELINE.json: **0.8× an A100 on the same step**. The
A100 step time is estimated analytically: training FLOPs (fwd + 2× bwd) on
the same configuration at 312 bf16 TFLOP/s × 40% MFU — a generous MFU for
the reference's eager torch implementation (no flash attention, no fusion;
measured MFUs for it would be lower, making this baseline conservative).
``vs_baseline`` > 1.0 means this framework beats that target.

Timing methodology (hard-won on this backend):

- ``block_until_ready`` is NOT a reliable fence here: on the tunneled axon
  TPU it returned 1.5 ms/"step" for a computation whose device trace shows
  ~45 ms — the round-2 record's 213× inflation. The only sync this backend
  cannot fake is a host value fetch (``float(loss)``), which must wait for
  the real result.
- The primary number is **chained** timing: N train steps whose TrainState
  is donated, so step k+1's inputs are step k's outputs and device execution
  serializes, with one value fetch at the end. This matches real training
  (loss is not fetched every step) and amortizes the host→tunnel dispatch
  latency (~70 ms/call here) that a per-step fetch would charge to every
  step. The per-step-fetch median is also recorded
  (``step_time_ms_synced``) as the conservative upper bound.
- MFU is validated: a record with mfu outside (0, 1) is refused, and peak
  FLOPs come from the detected device kind, not a hardcoded constant.
- The Pallas flash path is cross-checked against the XLA einsum path every
  run (same params, same batch, same dropout rng): the loss difference and
  both forward times land in the record (VERDICT r2 ask #1d/#7), and a
  mismatch beyond tolerance withdraws the primary metric from the record
  before the child aborts.

Config: the 8k-context north-star shape (BASELINE.json `configs`): Perceiver
AR, vocab 262 (UTF-8 bytes), 8192 ctx / 1024 latents, 512 channels, 8 layers
— the reference's WikiText-103 model (reference
``examples/training/clm/train.py``) widened to the 8k context it targets for
long-context work (``docs/training-examples.md:158-162`` scale). The MLM
extra uses the ``deepmind/language-perceiver`` shape (201M params: d_model
768, 256×1280 latents, 26 layers, ctx 2048) the reference fine-tunes in
``docs/training-examples.md:90-118``.

Self-defence (the round-1 TPU backend hung on a bare matmul): the parent
process never touches jax. It runs (1) a backend probe, (2) the benchmark,
each in a subprocess with a hard timeout and retry-with-backoff; the child
writes its result file incrementally after every completed stage, and the
parent accepts a partial file even if the child dies later. If the
accelerator is unusable it falls back to a reduced-shape CPU run so a real
measured number is always emitted; and it ALWAYS prints a parseable JSON
line before exiting. All stage progress goes to stderr.

Tunnel-outage resilience (round-3 postmortem: the axon relay died
mid-session, two probes timed out at rc=-1, and the round silently forfeited
to CPU): the accelerator here is reached through a loopback relay
(``PALLAS_AXON_POOL_IPS=127.0.0.1``, ports 8080-8089). When that relay is
down the PJRT claim HANGS rather than erroring, so a plain TCP connect to
the relay ports is the only cheap tell. The parent now (a) socket-checks the
relay before paying for a JAX-import probe, (b) retries the probe with
backoff over a multi-minute window instead of twice, (c) records WHY the
accelerator was unavailable (``tpu_status``: "relay_down" = nothing
listening, vs "probe_failed" = listener present but backend broken), and
(d) after the CPU fallback, re-probes once more so a mid-session outage that
heals does not forfeit the round. Budget knobs: ``BENCH_DEADLINE_S`` (global,
default 900), ``BENCH_PROBE_WINDOW_S`` (initial probe window, default 240).
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time

GLOBAL_DEADLINE_S = float(os.environ.get("BENCH_DEADLINE_S", "900"))
_T0 = time.monotonic()

# The axon relay's loopback ports (memory: healthy relay listens on 808x).
RELAY_HOST = "127.0.0.1"
RELAY_PORTS = tuple(range(8080, 8090))

METRIC = "perceiver_ar_8k_train_tokens_per_sec_per_chip"

A100_BF16_FLOPS = 312e12
A100_ASSUMED_MFU = 0.40
BASELINE_FACTOR = 0.8  # north star: >= 0.8x A100 step time

# (batch, seq, latents, channels, heads, layers)
FULL_SHAPE = (8, 8192, 1024, 512, 8, 8)
CPU_SHAPE = (1, 2048, 256, 256, 8, 4)  # reduced fallback, still the same model

# bf16 peak FLOP/s by device kind substring (lowercased match, first hit wins).
_PEAK_BY_KIND = (
    ("v5 lite", 197e12),   # v5e
    ("v5e", 197e12),
    ("v5p", 459e12),
    ("v6", 918e12),        # Trillium
    ("v4", 275e12),
    ("v3", 123e12),
)


def log(msg: str) -> None:
    print(f"[bench +{time.monotonic() - _T0:6.1f}s] {msg}", file=sys.stderr, flush=True)


def remaining() -> float:
    return GLOBAL_DEADLINE_S - (time.monotonic() - _T0)


# ---------------------------------------------------------------- child side


def _mk_config(shape):
    from perceiver_io_tpu.models.text.clm import CausalLanguageModelConfig

    batch, seq, latents, channels, heads, layers = shape
    return CausalLanguageModelConfig(
        vocab_size=262,
        max_seq_len=seq,
        max_latents=latents,
        num_channels=channels,
        num_heads=heads,
        num_self_attention_layers=layers,
        cross_attention_dropout=0.5,
    )


def ar_train_flops(cfg, batch: int) -> float:
    """fwd+bwd FLOPs of one AR train step via the shared scaling-study
    estimator (utils/flops.py; VERDICT r2 ask #1e — no duplicate math here).
    prefix_dropout=0 counts the full prefix: the upper bound, so MFU is not
    flattered by the dropped-prefix steps."""
    from perceiver_io_tpu.utils.flops import ComputeEstimator, training_flops_per_step

    est = ComputeEstimator(
        vocab_size=cfg.vocab_size,
        max_seq_len=cfg.max_seq_len,
        num_latents=cfg.max_latents,
    )
    return float(
        training_flops_per_step(
            est,
            num_channels=cfg.num_channels,
            num_layers=cfg.num_self_attention_layers + 1,  # + hybrid cross layer
            batch_size=batch,
            prefix_dropout=0.0,
        )
    )


def peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    for sub, peak in _PEAK_BY_KIND:
        if sub in kind:
            return peak
    return 0.0  # unknown device (CPU fallback): no MFU claim


def _fetch(x) -> float:
    """Host value fetch — the only execution fence this backend can't fake."""
    return float(x)


def _matmul_ceiling_tflops(dim: int = 4096) -> float:
    """Measured bf16 matmul throughput — the chip's *practical* ceiling,
    recorded so the MFU figure is interpretable against what this device
    actually delivers rather than only the nominal peak.

    Methodology: K matmuls chained inside ONE jitted ``fori_loop`` (one
    dispatch, one value-fetch fence), at two different K; the differenced
    time cancels both the dispatch and the fetch constants, which on this
    tunneled backend would otherwise dominate (~70 ms/fetch vs ~0.7 ms of
    device work per 4096^3 matmul)."""
    import functools

    import jax
    import jax.numpy as jnp

    w = jnp.ones((dim, dim), jnp.bfloat16)

    @functools.partial(jax.jit, static_argnums=1)
    def chain(x, k):
        return jax.lax.fori_loop(0, k, lambda _, y: jax.lax.dot(y, w), x)

    s = jax.jit(lambda t: jnp.sum(t.astype(jnp.float32)))

    def run(k):
        x = jnp.ones((dim, dim), jnp.bfloat16)
        _fetch(s(chain(x, k)))  # compile + warm
        t0 = time.perf_counter()
        _fetch(s(chain(x, k)))
        return time.perf_counter() - t0

    k1, k2 = 16, 144
    dt = run(k2) - run(k1)
    if dt <= 0:
        raise RuntimeError("ceiling measurement non-monotonic — backend timing broken")
    return 2 * dim**3 * (k2 - k1) / dt / 1e12


def child_probe() -> None:
    """Initialize the backend and run one tiny matmul + value fetch."""
    log("probe: importing jax")
    import jax
    import jax.numpy as jnp

    log(f"probe: backend={jax.default_backend()} devices={jax.devices()}")
    x = jnp.ones((256, 256), jnp.bfloat16)
    s = _fetch(jnp.sum(x @ x))
    log(f"probe: matmul OK (sum={s})")
    print("PROBE_OK", flush=True)


class MetricWithdrawn(RuntimeError):
    """Deliberate refusal to publish (kernel mismatch, impossible MFU)."""


class _Result:
    """Incrementally written result file: survives a mid-run child death."""

    def __init__(self, out_path: str):
        self.out_path = out_path
        self.data = {}

    def update(self, **kv):
        self.data.update(kv)
        tmp = self.out_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.data, f)
        os.replace(tmp, self.out_path)


def _build_ar(cfg, mesh, impl):
    import jax
    import jax.numpy as jnp
    import optax

    from perceiver_io_tpu.models.text.clm import CausalLanguageModel
    from perceiver_io_tpu.parallel import create_train_state, make_train_step
    from perceiver_io_tpu.training.tasks import clm_loss_fn

    model = CausalLanguageModel(cfg, dtype=jnp.bfloat16, attention_impl=impl)
    prefix_len = cfg.max_seq_len - cfg.max_latents

    def init():
        return model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, cfg.max_seq_len), jnp.int32), prefix_len
        )["params"]

    state, shardings = create_train_state(init, optax.adamw(3e-4), mesh)
    step = make_train_step(clm_loss_fn(model, cfg.max_latents), mesh, shardings)
    return model, state, step, shardings


def _time_train(step, state, sharded, key, *, n_chain: int, n_sync: int):
    """(chained ms/step, per-step-fetch median ms, final state, final loss)."""
    import jax
    import numpy as np

    for i in range(4):  # warm past the slow first post-compile steps
        state, metrics = step(state, sharded, jax.random.fold_in(key, i))
    _fetch(metrics["loss"])

    t0 = time.perf_counter()
    for i in range(n_chain):
        state, metrics = step(state, sharded, jax.random.fold_in(key, 100 + i))
    loss = _fetch(metrics["loss"])
    chained_ms = (time.perf_counter() - t0) / n_chain * 1e3

    ts = []
    for i in range(n_sync):
        t0 = time.perf_counter()
        state, metrics = step(state, sharded, jax.random.fold_in(key, 200 + i))
        _fetch(metrics["loss"])
        ts.append(time.perf_counter() - t0)
    synced_ms = float(np.median(ts)) * 1e3 if ts else None
    return chained_ms, synced_ms, state, loss


def child_run(shape, out_path: str, force_cpu: bool = False, deadline_s: float = 420.0) -> None:
    t_start = time.monotonic()

    def left() -> float:
        return deadline_s - (time.monotonic() - t_start)

    import jax

    if force_cpu:
        # The sitecustomize force-registers the axon plugin and overrides
        # JAX_PLATFORMS; CPU must be re-forced via jax.config after import.
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from perceiver_io_tpu.parallel import shard_batch, single_device_mesh

    platform = jax.default_backend()
    device = jax.devices()[0]
    log(f"run: backend={platform} kind={getattr(device, 'device_kind', '?')} shape={shape}")
    batch_size = shape[0]
    cfg = _mk_config(shape)
    mesh = single_device_mesh(device)
    res = _Result(out_path)

    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, size=(batch_size, cfg.max_seq_len + 1), dtype=np.int32)
    batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:]}

    flops = ar_train_flops(cfg, batch_size)
    peak = peak_flops(device)

    with mesh:
        sharded = shard_batch(batch, mesh)
        key = jax.random.PRNGKey(1)

        # ---- primary: AR train step, flash path (auto = flash on TPU) ----
        impl_used = "flash" if platform == "tpu" else "xla"
        n_chain = 20 if platform == "tpu" else 3
        log("run: building AR train step (flash/auto)")
        try:
            model, state, step, shardings = _build_ar(cfg, mesh, "auto")
            chained_ms, synced_ms, state, loss = _time_train(
                step, state, sharded, key, n_chain=n_chain, n_sync=4
            )
        except Exception as e:
            log(f"run: flash path failed ({type(e).__name__}: {e}); retrying with xla")
            impl_used = "xla"
            model = state = step = None  # free the failed build's device memory
            model, state, step, shardings = _build_ar(cfg, mesh, "xla")
            chained_ms, synced_ms, state, loss = _time_train(
                step, state, sharded, key, n_chain=n_chain, n_sync=4
            )
        dt = chained_ms / 1e3
        tokens_per_sec = batch_size * cfg.max_seq_len / dt
        a100_step_time = flops / (A100_BF16_FLOPS * A100_ASSUMED_MFU)
        baseline_step_time = a100_step_time / BASELINE_FACTOR
        mfu = flops / dt / peak if peak else None
        if mfu is not None and not 0.0 < mfu < 1.0:
            raise RuntimeError(
                f"refusing to emit physically impossible MFU {mfu:.4f} "
                f"(flops={flops:.3e}, step={dt * 1e3:.2f} ms, peak={peak:.3e}) — "
                "timing or accounting is broken"
            )
        log(
            f"run: AR train {chained_ms:.1f} ms/step chained, "
            f"{synced_ms:.1f} ms synced, loss {loss:.4f}, mfu {mfu if mfu is None else round(mfu, 4)}"
        )
        res.update(
            metric=METRIC,
            value=round(tokens_per_sec, 1),
            unit="tokens/s",
            vs_baseline=round(baseline_step_time / dt, 3),
            platform=platform,
            device_kind=getattr(device, "device_kind", "unknown"),
            attention_impl=impl_used,
            step_time_ms=round(chained_ms, 2),
            step_time_ms_synced=round(synced_ms, 2),
            train_loss=round(loss, 4),
            mfu=None if mfu is None else round(mfu, 4),
            peak_flops=peak or None,
            flops_per_step=flops,
            shape=list(shape),
            timing=f"chained-{n_chain}-donated-steps + host value fetch (see bench.py docstring)",
            extras={},
        )

        # ---- extra: fused multi-step block (zero host dispatch per step) ----
        # 10 optimizer steps in ONE device program (lax.scan; the trainer's
        # steps_per_execution path): per-step time with the host entirely out
        # of the loop — the deployment-mode number for long training runs.
        if platform == "tpu" and left() > 150.0:
            log("run: fused 10-step block")
            fstate = fused = stacked = None
            try:
                from perceiver_io_tpu.parallel import make_train_step
                from perceiver_io_tpu.training.tasks import clm_loss_fn

                K = 10
                # donate=False: reuses the live primary state without
                # consuming it (the cross-check/decode stages still need it)
                fused = make_train_step(
                    clm_loss_fn(model, cfg.max_latents), mesh, shardings,
                    multi_steps=K, donate=False,
                )
                stk = {
                    k2: np.broadcast_to(np.asarray(v)[None], (K, *np.shape(v))).copy()
                    for k2, v in batch.items()
                }
                stacked = shard_batch(stk, mesh, stacked_steps=True)
                keys = jax.random.split(jax.random.PRNGKey(3), K)
                fstate, fm = fused(state, stacked, keys)  # compile + warm
                _fetch(fm["loss"][-1])
                t0 = time.perf_counter()
                fstate, fm = fused(state, stacked, keys)
                _fetch(fm["loss"][-1])
                fused_ms = (time.perf_counter() - t0) / K * 1e3
                res.update(extras={**res.data["extras"], "fused_multi_step": {
                    "per_step_ms": round(fused_ms, 2),
                    "tokens_per_sec": round(
                        batch_size * cfg.max_seq_len / (fused_ms / 1e3), 1),
                    "block_steps": K,
                }})
                log(f"run: fused block {fused_ms:.1f} ms/step")
            except Exception as e:
                log(f"run: fused block failed ({type(e).__name__}: {e})")
                res.update(extras={**res.data["extras"], "fused_multi_step": {
                    "error": f"{type(e).__name__}: {e}"}})
            finally:
                fstate = fused = stacked = None  # release HBM for later stages

        # ---- extra: practical matmul ceiling (contextualizes MFU) ----
        if platform == "tpu" and left() > 150.0:
            log("run: matmul ceiling")
            try:
                ceiling = round(_matmul_ceiling_tflops(), 1)
                res.update(measured_matmul_tflops=ceiling)
                log(f"run: matmul ceiling {ceiling} TF/s")
            except Exception as e:
                log(f"run: ceiling measurement skipped ({type(e).__name__}: {e})")

        # ---- cross-check: flash vs xla loss on identical params/batch ----
        # Uses the live post-timing params (the timed state was donated away
        # step by step; state.params is the current generation).
        if impl_used == "flash" and left() > 120.0:
            log("run: flash-vs-xla cross-check")
            try:
                from perceiver_io_tpu.training.tasks import clm_loss_fn
                from perceiver_io_tpu.models.text.clm import CausalLanguageModel

                xmodel = CausalLanguageModel(cfg, dtype=jnp.bfloat16, attention_impl="xla")
                xloss_fn = jax.jit(clm_loss_fn(xmodel, cfg.max_latents))
                floss_fn = jax.jit(clm_loss_fn(model, cfg.max_latents))
                ckey = jax.random.PRNGKey(7)
                live = state.params

                def timed_loss(fn):
                    _fetch(fn(live, sharded, ckey)[0])  # compile + warm
                    t0 = time.perf_counter()
                    value = _fetch(fn(live, sharded, ckey)[0])
                    return value, (time.perf_counter() - t0) * 1e3

                lf, fwd_flash_ms = timed_loss(floss_fn)
                lx, fwd_xla_ms = timed_loss(xloss_fn)
                diff = abs(lf - lx)
                ok = diff <= 5e-3
                log(f"run: cross-check loss flash={lf:.6f} xla={lx:.6f} diff={diff:.2e} ok={ok}")
                res.update(extras={**res.data["extras"], "flash_vs_xla": {
                    "loss_flash": lf, "loss_xla": lx, "loss_diff": diff, "ok": ok,
                    "fwd_flash_ms": round(fwd_flash_ms, 2),
                    "fwd_xla_ms": round(fwd_xla_ms, 2),
                }})
                if not ok:
                    # withdraw the primary metric: a mismatched kernel must
                    # not publish a passing-looking record
                    res.data.pop("value", None)
                    res.update(
                        error=f"flash/xla loss mismatch {diff:.2e} — "
                        "kernel correctness regression; metric withdrawn"
                    )
                    raise MetricWithdrawn(res.data["error"])
            except MetricWithdrawn:
                raise
            except Exception as e:  # backend failure here is not a verdict
                log(f"run: cross-check skipped ({type(e).__name__}: {e})")
                res.update(extras={**res.data["extras"], "flash_vs_xla": {
                    "error": f"{type(e).__name__}: {e}"}})

        # ---- extra: MLM samples/sec (BASELINE.json metric, second half) ----
        if left() > 150.0:
            log("run: MLM samples/sec (language-perceiver 201M shape)")
            try:
                mlm_sps = _bench_mlm(mesh, platform)
                res.update(extras={**res.data["extras"], "mlm": mlm_sps})
                log(f"run: MLM {mlm_sps['samples_per_sec']} samples/s")
            except Exception as e:
                log(f"run: MLM bench failed ({type(e).__name__}: {e})")
                res.update(extras={**res.data["extras"], "mlm": {
                    "error": f"{type(e).__name__}: {e}"}})

        # ---- extra: cached vs recompute decode throughput ----
        if left() > 150.0:
            log("run: decode throughput (cached vs recompute)")
            try:
                dec = _bench_decode(model, state.params, cfg)
                res.update(extras={**res.data["extras"], "decode": dec})
                log(f"run: decode cached {dec['cached_tokens_per_sec']} tok/s, "
                    f"recompute {dec['recompute_tokens_per_sec']} tok/s "
                    f"(latent phase {dec['latent']['speedup']}x, boundary "
                    f"phase {dec['boundary']['speedup']}x cached-vs-recompute)")
            except Exception as e:
                log(f"run: decode bench failed ({type(e).__name__}: {e})")
                res.update(extras={**res.data["extras"], "decode": {
                    "error": f"{type(e).__name__}: {e}"}})

        # ---- extra: bucketed serving probe (mixed-length traffic) ----
        if left() > 120.0:
            log("run: serving probe (shape-bucketed micro-batching)")
            try:
                # the slots-vs-bucket A/B runs ~2 min at the CPU shape;
                # skip it when the remaining budget couldn't also fit the
                # chaos + observability probes
                srv = _bench_serve(model, state.params, cfg, with_ab=left() > 300.0)
                res.update(extras={**res.data["extras"], "serve": srv})
                log(f"run: serve {srv['tokens_per_sec']} tok/s, "
                    f"{srv['compile_count']} compiles for "
                    f"{srv['distinct_prompt_lens']} distinct prompt lengths")
                ab = srv.get("slots_vs_bucket", {})
                if ab:
                    log(f"run: serve A/B slots {ab['slots']['tokens_per_sec']} "
                        f"vs bucket {ab['bucket']['tokens_per_sec']} tok/s "
                        f"(speedup {ab['slots_vs_bucket_speedup']}x, slot "
                        f"occupancy {ab['slots']['slot_occupancy']})")
            except Exception as e:
                log(f"run: serving probe failed ({type(e).__name__}: {e})")
                res.update(extras={**res.data["extras"], "serve": {
                    "error": f"{type(e).__name__}: {e}"}})

        # ---- extra: chunked-prefill A/B (resident latency under long admit) ----
        if left() > 150.0:
            log("run: chunked-prefill A/B (p95 resident inter-token latency)")
            try:
                pc = _bench_prefill_chunk_ab(cfg)
                res.update(extras={**res.data["extras"], "prefill_chunk": pc})
                log(f"run: prefill-chunk A/B p95 without="
                    f"{pc['without_chunking']['p95_inter_token_ms']}ms "
                    f"with={pc['with_chunking']['p95_inter_token_ms']}ms "
                    f"(lower with chunking: {pc['chunking_lowers_p95']})")
            except Exception as e:
                log(f"run: chunked-prefill A/B failed ({type(e).__name__}: {e})")
                res.update(extras={**res.data["extras"], "prefill_chunk": {
                    "error": f"{type(e).__name__}: {e}"}})

        # ---- extra: paged-KV A/B (long-tail residents at a fixed HBM budget) ----
        if left() > 150.0:
            log("run: paged-KV A/B (dense vs block-paged residents at one budget)")
            try:
                pkv = _bench_paged_kv(model, state.params, cfg)
                res.update(extras={**res.data["extras"], "paged_kv": pkv})
                log(f"run: paged-KV residents {pkv['paged']['max_residents']} "
                    f"vs dense {pkv['dense']['max_residents']} at the same "
                    f"budget ({pkv['max_residents_ratio']}x, token_identical="
                    f"{pkv['token_identical']}, paged "
                    f"{pkv['paged']['tokens_per_sec']} tok/s)")
            except Exception as e:
                log(f"run: paged-KV A/B failed ({type(e).__name__}: {e})")
                res.update(extras={**res.data["extras"], "paged_kv": {
                    "error": f"{type(e).__name__}: {e}"}})

        # ---- extra: preemption A/B (strict vs optimistic admission) ----
        if left() > 150.0:
            log("run: preemption A/B (strict vs optimistic admission at "
                "one budget)")
            try:
                pmt = _bench_preemption(model, state.params, cfg)
                res.update(extras={**res.data["extras"], "preemption": pmt})
                log(f"run: preemption residents "
                    f"{pmt['optimistic']['max_residents']} vs strict "
                    f"{pmt['strict']['max_residents']} at the same budget "
                    f"({pmt['max_residents_ratio']}x, goodput_under_slo "
                    f"{pmt['optimistic']['goodput_under_slo']} vs "
                    f"{pmt['strict']['goodput_under_slo']}, "
                    f"{pmt['optimistic']['preemptions']} preemptions, "
                    f"token_identical={pmt['token_identical']})")
                pm = pmt["optimistic"]["postmortems"]
                if pm["count"]:
                    log(f"run: preemption post-mortems {pm['count']} victims, "
                        f"{pm['tokens_discarded']} tokens replayed, recompute "
                        f"{pm['recompute_est_ms']}ms vs swap "
                        f"{pm['swap_est_ms']}ms at {pm['swap_link_gbps']}GB/s "
                        f"(swap_advantage {pm['swap_advantage_ms']}ms)")
            except Exception as e:
                log(f"run: preemption A/B failed ({type(e).__name__}: {e})")
                res.update(extras={**res.data["extras"], "preemption": {
                    "error": f"{type(e).__name__}: {e}"}})

        # ---- extra: host-swap A/B (recompute vs swap vs auto over length) ----
        if left() > 150.0:
            log("run: host-swap A/B (recompute vs swap vs auto preemption "
                "over a generated-length sweep)")
            try:
                swp = _bench_swap(model, state.params, cfg)
                res.update(extras={**res.data["extras"], "swap": swp})
                last = swp["sweep"][-1] if swp["sweep"] else {}
                log(f"run: host-swap crossover_length="
                    f"{swp['crossover_length']} (longest point: recompute "
                    f"{last.get('recompute', {}).get('wall_s')}s vs swap "
                    f"{last.get('swap', {}).get('wall_s')}s, realized "
                    f"advantage {last.get('realized_advantage_ms')}ms, "
                    f"predicted {last.get('predicted_advantage_ms')}ms), "
                    f"token_identical={swp['token_identical']}, "
                    f"auto_agrees={swp['auto_agrees']}, sign_agrees="
                    f"{swp['advantage_sign_agrees']}")
            except Exception as e:
                log(f"run: host-swap A/B failed ({type(e).__name__}: {e})")
                res.update(extras={**res.data["extras"], "swap": {
                    "error": f"{type(e).__name__}: {e}"}})

        # ---- extra: quantized-KV A/B (exact vs int8 pool at one budget) ----
        if left() > 150.0:
            log("run: quant-KV A/B (exact vs int8 paged pool at one budget)")
            try:
                qkv = _bench_quant_kv(model, state.params, cfg)
                res.update(extras={**res.data["extras"], "quant_kv": qkv})
                log(f"run: quant-KV residents {qkv['int8']['max_residents']} "
                    f"vs exact {qkv['exact']['max_residents']} at the same "
                    f"budget ({qkv['residents_per_hbm_byte_ratio']}x, "
                    f"token_match={qkv['token_match_rate']}, quality gate "
                    f"passed={qkv['quality_gate']['passed']})")
            except Exception as e:
                log(f"run: quant-KV A/B failed ({type(e).__name__}: {e})")
                res.update(extras={**res.data["extras"], "quant_kv": {
                    "error": f"{type(e).__name__}: {e}"}})

        # ---- extra: prefix-cache A/B (Zipf shared prefixes, COW sharing) ----
        if left() > 150.0:
            log("run: prefix-cache A/B (Zipf shared prefixes, unshared vs COW-shared)")
            try:
                pfx = _bench_prefix_cache(model, state.params, cfg)
                res.update(extras={**res.data["extras"], "prefix_cache": pfx})
                log(f"run: prefix-cache TTFT p95 ratio {pfx['ttft_p95_ratio']}x, "
                    f"residents/byte ratio {pfx['residents_per_hbm_byte_ratio']}x, "
                    f"hit_ratio={pfx['hit_ratio']}, token_identical="
                    f"{pfx['token_identical']}")
            except Exception as e:
                log(f"run: prefix-cache A/B failed ({type(e).__name__}: {e})")
                res.update(extras={**res.data["extras"], "prefix_cache": {
                    "error": f"{type(e).__name__}: {e}"}})

        # ---- extra: speculative-decoding A/B (self-draft vs one-token steps) ----
        if left() > 120.0:
            log("run: speculative A/B (self-draft k+1-token rounds vs "
                "one-token steps, plus the autotune pays/declines pins)")
            try:
                spc = _bench_speculative(model, state.params, cfg)
                res.update(extras={**res.data["extras"], "speculative": spc})
                log(f"run: speculative {spc['spec']['tokens_per_sec']} tok/s vs "
                    f"off {spc['off']['tokens_per_sec']} tok/s (speedup "
                    f"{spc['speedup']}x, acceptance {spc['acceptance_rate']}, "
                    f"{spc['tokens_per_round']} tok/round, token_identical="
                    f"{spc['token_identical']}; autotune pays="
                    f"{spc['autotune']['pays']['speculation']}, declines="
                    f"{spc['autotune']['decline']['speculation']})")
            except Exception as e:
                log(f"run: speculative A/B failed ({type(e).__name__}: {e})")
                res.update(extras={**res.data["extras"], "speculative": {
                    "error": f"{type(e).__name__}: {e}"}})

        # ---- extra: chaos drill (fault-injected serving, deterministic) ----
        if left() > 60.0:
            log("run: chaos probe (backpressure / deadlines / fault isolation)")
            try:
                chs = _bench_chaos(model, state.params, cfg)
                res.update(extras={**res.data["extras"], "chaos": chs})
                log(f"run: chaos survived={chs['survived']} "
                    f"(shed {chs['shed']}, timed_out {chs['timed_out']}, "
                    f"failed {chs['failed']}, completed {chs['completed']})")
            except Exception as e:
                log(f"run: chaos probe failed ({type(e).__name__}: {e})")
                res.update(extras={**res.data["extras"], "chaos": {
                    "error": f"{type(e).__name__}: {e}"}})

        # ---- extra: fleet chaos drill (mid-decode replica kill) ----
        if left() > 90.0:
            log("run: fleet-chaos probe (replica kill / failover / exactly-once)")
            try:
                flc = _bench_fleet_chaos(model, state.params, cfg)
                res.update(extras={**res.data["extras"], "fleet_chaos": flc})
                log(f"run: fleet-chaos completion_ratio={flc['completion_ratio']} "
                    f"token_identical={flc['token_identical']} "
                    f"(failovers {flc['failovers']}, redispatches "
                    f"{flc['redispatches']}, goodput "
                    f"{flc['goodput_tokens_per_sec']} tok/s)")
            except Exception as e:
                log(f"run: fleet-chaos probe failed ({type(e).__name__}: {e})")
                res.update(extras={**res.data["extras"], "fleet_chaos": {
                    "error": f"{type(e).__name__}: {e}"}})

        # ---- extra: elasticity A/B (autoscaled vs static fleet flash crowd) ----
        if left() > 120.0:
            log("run: elasticity probe (flash crowd: breach -> scale-up -> "
                "recover -> scale-down, vs a static fleet)")
            try:
                ela = _bench_elasticity(model, state.params, cfg)
                res.update(extras={**res.data["extras"], "elasticity": ela})
                log(f"run: elasticity goodput-under-SLO "
                    f"{ela['autoscaled']['goodput_under_slo']} autoscaled vs "
                    f"{ela['static']['goodput_under_slo']} static "
                    f"(beats={ela['elastic_beats_static']}, scale_ups "
                    f"{ela['autoscaled']['scale_ups']}, scale_downs "
                    f"{ela['autoscaled']['scale_downs']}, zero_dropped="
                    f"{ela['zero_dropped']}, token_identical="
                    f"{ela['token_identical']})")
            except Exception as e:
                log(f"run: elasticity probe failed ({type(e).__name__}: {e})")
                res.update(extras={**res.data["extras"], "elasticity": {
                    "error": f"{type(e).__name__}: {e}"}})

        # ---- extra: observability probe (telemetry layer end to end) ----
        if left() > 60.0:
            log("run: observability probe (histograms / goodput / MFU gauges)")
            try:
                obs = _bench_observability(model, state.params, cfg)
                res.update(extras={**res.data["extras"], "observability": obs})
                log(f"run: observability goodput={obs['goodput']} "
                    f"mfu={obs['mfu']} span_accounting_closed="
                    f"{obs['span_accounting_closed']}")
            except Exception as e:
                log(f"run: observability probe failed ({type(e).__name__}: {e})")
                res.update(extras={**res.data["extras"], "observability": {
                    "error": f"{type(e).__name__}: {e}"}})

        # ---- extra: goodput-under-SLO sweep (offered load vs p95 TTFT/ITL) ----
        if left() > 120.0:
            log("run: slo-goodput sweep (offered load vs p95 TTFT / inter-token)")
            try:
                slo = _bench_slo_goodput(model, state.params, cfg)
                res.update(extras={**res.data["extras"], "slo_goodput": slo})
                log(f"run: slo-goodput knee at {slo['knee']['offered_rps']} rps "
                    f"offered ({slo['knee']['goodput_rps']} rps good, factor "
                    f"{slo['knee']['rate_factor']}x; report matches registry: "
                    f"{slo['report_percentiles_match_registry']})")
            except Exception as e:
                log(f"run: slo-goodput sweep failed ({type(e).__name__}: {e})")
                res.update(extras={**res.data["extras"], "slo_goodput": {
                    "error": f"{type(e).__name__}: {e}"}})

        # ---- extra: streaming abandonment drill (gateway cancellation path) ----
        if left() > 90.0:
            log("run: streaming probe (mid-stream mass abandonment, zero-leak)")
            try:
                stm = _bench_streaming(model, state.params, cfg)
                res.update(extras={**res.data["extras"], "streaming": stm})
                log(f"run: streaming abandoned {stm['abandoned']}/{stm['requests']} "
                    f"mid-stream — survivors token_identical="
                    f"{stm['token_identical']}, pool leak {stm['pool']['leaked']} "
                    f"blocks, reclaim p95 {stm['reclaim']['p95_ms']} ms "
                    f"(accounting_closed={stm['accounting_closed']})")
            except Exception as e:
                log(f"run: streaming probe failed ({type(e).__name__}: {e})")
                res.update(extras={**res.data["extras"], "streaming": {
                    "error": f"{type(e).__name__}: {e}"}})

        # ---- extra: incident flight-recorder chaos drill ----
        if left() > 60.0:
            log("run: incident probe (replica crash during SLO breach -> "
                "bundle -> analyzer joins)")
            try:
                inc = _bench_incident(model, state.params, cfg)
                res.update(extras={**res.data["extras"], "incident": inc})
                log(f"run: incident bundles={inc['bundles']} "
                    f"(kinds={inc['bundle_kinds']}, suppressed="
                    f"{inc['suppressed']}), trace_join={inc['trace_join']}, "
                    f"decomposition_exact={inc['decomposition_exact']}, "
                    f"nonok_traces_kept={inc['nonok_traces_kept']} at "
                    f"{inc['sample_rate']} sampling (span accounting closed="
                    f"{inc['span_accounting_closed']})")
            except Exception as e:
                log(f"run: incident probe failed ({type(e).__name__}: {e})")
                res.update(extras={**res.data["extras"], "incident": {
                    "error": f"{type(e).__name__}: {e}"}})

        # ---- extra: sharded serving A/B (1-device vs 8-virtual-device mesh) ----
        if left() > 150.0:
            log("run: sharded serving probe (1-device vs 2x4 CPU mesh A/B)")
            try:
                shd = _bench_sharded_serving(budget_s=min(240.0, left() - 30.0))
                res.update(extras={**res.data["extras"], "sharded_serving": shd})
                log(f"run: sharded serving {shd['sharded']['mesh']['data']}x"
                    f"{shd['sharded']['mesh']['model']} mesh "
                    f"{shd['sharded']['tokens_per_s']} tok/s vs single "
                    f"{shd['single']['tokens_per_s']} tok/s "
                    f"(speedup {shd['speedup']}, token_identical="
                    f"{shd['token_identical']}, per-shard resident "
                    f"{shd['sharded']['per_shard_resident_bytes']} B)")
            except Exception as e:
                log(f"run: sharded serving probe failed "
                    f"({type(e).__name__}: {e})")
                res.update(extras={**res.data["extras"], "sharded_serving": {
                    "error": f"{type(e).__name__}: {e}"}})

        # BENCH_* records carry the process-wide telemetry snapshot AND the
        # device-cost ledger (per-executor compile/memory/retrace table;
        # docs/observability.md) — every BENCH_* file is `obs report`-able.
        try:
            from perceiver_io_tpu.observability import default_ledger, default_registry

            default_ledger().update_device_gauges()  # hbm_bytes_in_use on TPU
            res.update(
                metrics_snapshot=default_registry().snapshot(),
                compile_ledger=default_ledger().snapshot(),
            )
        except Exception as e:
            log(f"run: metrics snapshot skipped ({type(e).__name__}: {e})")

    log(f"run: wrote {out_path}")


def _bench_mlm(mesh, platform: str):
    """Perceiver IO MLM train step, deepmind/language-perceiver shape
    (201M params; reference fine-tunes it in docs/training-examples.md:90-118)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from perceiver_io_tpu.models.text.common import TextEncoderConfig
    from perceiver_io_tpu.models.text.mlm import (
        MaskedLanguageModel,
        MaskedLanguageModelConfig,
        TextDecoderConfig,
    )
    from perceiver_io_tpu.parallel import create_train_state, make_train_step, shard_batch
    from perceiver_io_tpu.training.tasks import mlm_loss_fn

    if platform == "tpu":
        # deepmind/language-perceiver: qk 256 / v 1280, widening 1 (the HF
        # PerceiverConfig defaults) — 201M params exactly, not the reference
        # library's widening-4 defaults.
        seq, vocab, batch = 2048, 262, 8
        channels, latents, latent_channels, layers = 768, 256, 1280, 26
        qk, widen = 256, 1
        config_note = "deepmind/language-perceiver 201M (768ch, 256x1280 latents, 26 layers)"
    else:  # CPU fallback: same architecture, reduced shape
        seq, vocab, batch = 512, 262, 2
        channels, latents, latent_channels, layers = 256, 64, 512, 4
        qk, widen = 128, 1
        config_note = "reduced CPU shape (256ch, 64x512 latents, 4 layers)"
    cfg = MaskedLanguageModelConfig(
        encoder=TextEncoderConfig(
            vocab_size=vocab,
            max_seq_len=seq,
            num_input_channels=channels,
            num_cross_attention_qk_channels=qk,
            num_cross_attention_v_channels=latent_channels,
            num_cross_attention_heads=8,
            num_self_attention_qk_channels=qk,
            num_self_attention_v_channels=latent_channels,
            num_self_attention_heads=8,
            num_self_attention_layers_per_block=layers,
            num_self_attention_blocks=1,
            cross_attention_widening_factor=widen,
            self_attention_widening_factor=widen,
        ),
        decoder=TextDecoderConfig(
            vocab_size=vocab,
            max_seq_len=seq,
            num_cross_attention_qk_channels=qk,
            num_cross_attention_v_channels=channels,
            num_cross_attention_heads=8,
            cross_attention_widening_factor=widen,
            cross_attention_residual=False,
        ),
        num_latents=latents,
        num_latent_channels=latent_channels,
    )
    model = MaskedLanguageModel(cfg, dtype=jnp.bfloat16)

    def init():
        return model.init(jax.random.PRNGKey(0), jnp.zeros((1, seq), jnp.int32))["params"]

    state, shardings = create_train_state(init, optax.adamw(3e-4), mesh)
    step = make_train_step(mlm_loss_fn(model), mesh, shardings)

    rng = np.random.default_rng(0)
    ids = rng.integers(0, vocab, size=(batch, seq), dtype=np.int32)
    labels = np.where(rng.random((batch, seq)) < 0.15, ids, -100).astype(np.int32)
    batch_d = shard_batch({"input_ids": ids, "labels": labels}, mesh)

    key = jax.random.PRNGKey(1)
    n_chain = 10 if platform == "tpu" else 2
    chained_ms, synced_ms, _, loss = _time_train(
        step, state, batch_d, key, n_chain=n_chain, n_sync=2
    )
    return {
        "metric": "perceiver_io_mlm_train_samples_per_sec",
        "samples_per_sec": round(batch / (chained_ms / 1e3), 2),
        "step_time_ms": round(chained_ms, 2),
        "step_time_ms_synced": round(synced_ms, 2),
        "batch": batch,
        "seq": seq,
        "train_loss": round(loss, 4),
        "config": config_note,
    }


def _bench_decode(model, params, cfg):
    """Cached vs windowed-recompute decode tokens/s at the 8k-ctx shape —
    the KV cache's reason to exist (VERDICT r2 ask #4a). Weights are stored
    bf16 (cast_float_params): the deployment config — decode is HBM-bandwidth
    bound at small batch, and fp32 weight reads would double that traffic."""
    import jax.numpy as jnp
    import numpy as np

    from perceiver_io_tpu.inference import cast_float_params
    from perceiver_io_tpu.inference.generate import GenerationConfig, generate

    params = cast_float_params(params, jnp.bfloat16)

    b, new_tokens = 4, 32
    prompt_len = cfg.max_seq_len // 2  # latent-growth + prefix-growth phases
    num_latents = cfg.max_latents
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(
        rng.integers(1, cfg.vocab_size, size=(b, prompt_len), dtype=np.int32)
    )
    gcfg = GenerationConfig(max_new_tokens=new_tokens, num_latents=num_latents)

    out = {}
    for label, use_cache in (("cached", True), ("recompute", False)):
        ids = generate(model, params, prompt, gcfg, use_cache=use_cache)
        _fetch(ids[0, -1])  # warm (compile included above; fence here)
        t0 = time.perf_counter()
        ids = generate(model, params, prompt, gcfg, use_cache=use_cache)
        _fetch(ids[0, -1])
        dt = time.perf_counter() - t0
        out[f"{label}_tokens_per_sec"] = round(b * new_tokens / dt, 1)
        out[f"{label}_ms_per_token"] = round(dt / new_tokens * 1e3, 2)
    out["speedup"] = round(
        out["cached_tokens_per_sec"] / out["recompute_tokens_per_sec"], 2
    )
    out.update(batch=b, prompt_len=prompt_len, new_tokens=new_tokens)
    out["boundary_strategy"] = _bench_decode_boundary(model, params, cfg)
    # per-phase split (the decode_scaling.py pins): the blended probe above
    # mixes latent-growth and prefix-growth steps, which hides that the
    # cache's win is phase-dependent — report each phase's tok/s on its own
    # pin. Boundary numbers come free from the strategy probe (same pin).
    bs = out["boundary_strategy"]
    out["boundary"] = {
        "cached_tokens_per_sec": bs["cached_tokens_per_sec"],
        "recompute_tokens_per_sec": bs["recompute_tokens_per_sec"],
        "speedup": round(
            bs["cached_tokens_per_sec"] / bs["recompute_tokens_per_sec"], 2
        ),
        "prompt_len": bs["prompt_len"],
        "new_tokens": bs["new_tokens"],
        "start_latents": cfg.max_latents,
    }
    out["latent"] = _bench_decode_latent(model, params, cfg)
    return out


def _bench_decode_latent(model, params, cfg, *, new_tokens: int = 8):
    """Latent-growth phase pin (``examples/perf/decode_scaling.py --phase
    latent``): latents start ``new_tokens`` below max so every generated
    token lands in latent growth — the cached step runs O(1) tokens of
    compute per step while the recompute path pays the full window, the
    phase where the cache's advantage is largest. Requires ``new_tokens <
    max_latents`` (clamped). ``params`` arrive bf16-cast from the
    caller."""
    import jax.numpy as jnp
    import numpy as np

    from perceiver_io_tpu.inference.generate import GenerationConfig, generate

    b = 1
    new_tokens = max(1, min(
        new_tokens, cfg.max_latents - 1, cfg.max_seq_len - cfg.max_latents
    ))
    prompt_len = cfg.max_seq_len - new_tokens
    start_latents = cfg.max_latents - new_tokens
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(
        rng.integers(1, cfg.vocab_size, size=(b, prompt_len), dtype=np.int32)
    )
    gcfg = GenerationConfig(max_new_tokens=new_tokens, num_latents=start_latents)

    out = {}
    for label, use_cache in (("cached", True), ("recompute", False)):
        ids = generate(model, params, prompt, gcfg, use_cache=use_cache)
        _fetch(ids[0, -1])  # compile + fence
        t0 = time.perf_counter()
        ids = generate(model, params, prompt, gcfg, use_cache=use_cache)
        _fetch(ids[0, -1])
        dt = time.perf_counter() - t0
        out[f"{label}_tokens_per_sec"] = round(b * new_tokens / dt, 1)
    out["speedup"] = round(
        out["cached_tokens_per_sec"] / out["recompute_tokens_per_sec"], 2
    )
    out.update(
        prompt_len=prompt_len, new_tokens=new_tokens,
        start_latents=start_latents,
    )
    return out


def _bench_decode_boundary(model, params, cfg, *, new_tokens: int = 8):
    """Boundary-phase strategy probe (ISSUE 5 acceptance): pin every
    generated token into the prefix-growth phase (latents start maxed, the
    prompt fills the window minus ``new_tokens``), measure the cached and
    recompute implementations, record the winner in the strategy registry
    from those same timings, then measure ``decode_strategy="auto"`` —
    which resolves to the recorded winner and reuses its compiled executor, so the
    effective throughput must sit within noise of max(cached, recompute)
    (``auto_vs_best``; the acceptance bar is >= 0.98). ``params`` arrive
    bf16-cast from the caller."""
    import jax.numpy as jnp
    import numpy as np

    from perceiver_io_tpu.inference import decode_strategy as strategy_mod
    from perceiver_io_tpu.inference.generate import GenerationConfig, generate

    b = 1
    new_tokens = max(1, min(new_tokens, cfg.max_seq_len - cfg.max_latents))
    prompt_len = cfg.max_seq_len - new_tokens
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(
        rng.integers(1, cfg.vocab_size, size=(b, prompt_len), dtype=np.int32)
    )
    gcfg = GenerationConfig(max_new_tokens=new_tokens, num_latents=cfg.max_latents)

    def measure(mode):
        ids = generate(model, params, prompt, gcfg, decode_strategy=mode)
        _fetch(ids[0, -1])  # compile + fence
        t0 = time.perf_counter()
        ids = generate(model, params, prompt, gcfg, decode_strategy=mode)
        _fetch(ids[0, -1])
        return b * new_tokens / (time.perf_counter() - t0)

    out = {}
    for mode in ("cached", "recompute"):
        out[f"{mode}_tokens_per_sec"] = round(measure(mode), 1)
    # record the winner from the timings just taken (the decode_scaling.py
    # pattern) rather than re-running autotune's identical probe — the
    # deadline-budgeted child_run can't afford four redundant fenced passes
    # at the near-full-window shape (tie -> cached, matching the autotuner)
    winner = (
        "cached"
        if out["cached_tokens_per_sec"] >= out["recompute_tokens_per_sec"]
        else "recompute"
    )
    strategy_mod.record(
        model, winner,
        cached_ms_per_token=round(1e3 / out["cached_tokens_per_sec"], 4),
        recompute_ms_per_token=round(1e3 / out["recompute_tokens_per_sec"], 4),
        batch=b, new_tokens=new_tokens, source="bench",
    )
    out["auto_tokens_per_sec"] = round(measure("auto"), 1)
    best = max(out["cached_tokens_per_sec"], out["recompute_tokens_per_sec"])
    out.update(
        strategy=winner,
        auto_vs_best=round(out["auto_tokens_per_sec"] / best, 4),
        new_tokens=new_tokens,
        prompt_len=prompt_len,
    )
    return out


def _bench_serve(model, params, cfg, *, n_requests: int = 24, new_tokens: int = 8,
                 with_ab: bool = True):
    """Mixed-length serving probe: a ragged prompt distribution (>= 8
    distinct lengths when the context allows) through the shape-bucketed
    ``ServingEngine`` (docs/serving.md). Two passes over the same traffic:
    the first pays every bucket compile (``compile_count`` — bounded by the
    bucket grid, not by the number of distinct shapes), the second measures
    steady-state serving throughput plus queue-wait percentiles. Shapes are
    derived from ``cfg`` so the probe also runs at the reduced CPU-fallback
    shape — the serving trajectory gets a real number without hardware."""
    import jax.numpy as jnp
    import numpy as np

    from perceiver_io_tpu.inference import cast_float_params
    from perceiver_io_tpu.inference.generate import GenerationConfig
    from perceiver_io_tpu.serving import BucketTable, ServingEngine

    params = cast_float_params(params, jnp.bfloat16)
    num_latents = min(16, cfg.max_latents)
    max_prefix = cfg.max_seq_len - cfg.max_latents
    max_len = min(256, cfg.max_seq_len // 2, max_prefix + num_latents)
    lens_grid = sorted({max(num_latents, max_len // 4), max(num_latents, max_len // 2), max_len})
    table = BucketTable(prompt_lens=tuple(lens_grid), batch_sizes=(2, 4, 8))
    gcfg = GenerationConfig(max_new_tokens=new_tokens, num_latents=num_latents)

    rng = np.random.default_rng(0)
    lo = max(1, max_len // 8)
    prompt_lens = rng.integers(lo, max_len + 1, size=n_requests)
    prompts = [
        rng.integers(1, cfg.vocab_size, size=int(n), dtype=np.int32)
        for n in prompt_lens
    ]

    compile_engine = ServingEngine(model, params, gcfg, table)
    compile_engine.serve(prompts)  # pays every bucket compile
    compile_count = compile_engine.stats()["compiles"]

    engine = ServingEngine(model, params, gcfg, table)
    t0 = time.perf_counter()
    outs = engine.serve(prompts)
    _fetch(outs[-1][-1])
    dt = time.perf_counter() - t0
    stats = engine.stats()
    out = {
        "tokens_per_sec": round(n_requests * new_tokens / dt, 1),
        "compile_count": compile_count,
        "steady_state_compiles": stats["compiles"],
        "p50_queue_wait_ms": stats["queue_wait_ms"]["p50"],
        "p95_queue_wait_ms": stats["queue_wait_ms"]["p95"],
        "requests": n_requests,
        "new_tokens": new_tokens,
        "batches": stats["batches"],
        "distinct_prompt_lens": int(len(set(int(n) for n in prompt_lens))),
        "bucket_grid": stats["bucket_grid"],
        "prompt_padding_efficiency": stats["prompt_padding_efficiency"],
    }
    if with_ab:  # the tier-1 probe test skips this (suite-budget control)
        out["slots_vs_bucket"] = _bench_serve_ab(model, params, cfg)
    return out


def _bench_serve_ab(model, params, cfg, *, n_requests: int = 16, slots: int = 8):
    """Slots-vs-bucket A/B on the workload that exposes generation-granular
    batching's two wastes (ISSUE 4 / the ragged-batch TPU-serving papers):
    ragged prompt lengths AND heterogeneous ``max_new_tokens``. The bucket
    engine can only pack identical-config requests, so mixed decode lengths
    fragment into underfilled micro-batches padded to the batch bucket —
    filler rows burn real decode compute. The slot engine's persistent
    ``S``-slot decode state retires each row the token it finishes and
    refills the freed slot from the queue mid-generation, so its padded-row
    fraction is just the drain tail.

    The primary comparison fixes BOTH engines to one resident batch shape
    (``batch_sizes=(slots,)``) — the TPU-serving configuration the papers
    target, where the hardware runs one compiled decode shape and filler
    rows cost real compute (this CPU probe prices filler rows linearly,
    standing in for the TPU's fixed-shape executor). Because an operator
    COULD instead give the bucket engine a full batch grid and let small
    batches pack exactly, the record also carries a ``bucket_exact``
    variant (grid ``1,2,4,...,slots``, 4x the executor count) so the
    scheduling-granularity and table effects are separable. All engines
    run the identical request list after a compile pass; tokens/s counts
    USEFUL tokens (sum of each request's own ``max_new_tokens``).
    ``params`` arrive bf16-cast from :func:`_bench_serve`; shapes derive
    from ``cfg``, so the probe is CPU-runnable at the reduced fallback
    shape."""
    import dataclasses

    import numpy as np

    from perceiver_io_tpu.inference.generate import GenerationConfig
    from perceiver_io_tpu.serving import BucketTable, ServingEngine, SlotServingEngine

    n = cfg.max_seq_len
    num_latents = min(4, cfg.max_latents)
    max_len = min(64, n // 2, cfg.max_seq_len - cfg.max_latents + num_latents)
    # decode-length pool: ~8 distinct values (real traffic rarely shares a
    # max_new_tokens, and the bucket engine can only pack identical-config
    # requests), capped so the probe stays seconds-scale on CPU and every
    # request fits the slot window
    cap = min(n - max_len, 32)
    pool = tuple(sorted({max(1, cap * f // 32) for f in (2, 3, 4, 6, 8, 12, 16, 32)}))
    base = GenerationConfig(max_new_tokens=pool[-1], num_latents=num_latents)
    cfgs = [
        dataclasses.replace(base, max_new_tokens=pool[i % len(pool)])
        for i in range(n_requests)
    ]
    rng = np.random.default_rng(0)
    sizes = rng.integers(num_latents, max_len + 1, size=n_requests)
    prompts = [
        rng.integers(1, cfg.vocab_size, size=int(s), dtype=np.int32) for s in sizes
    ]
    useful_tokens = sum(c.max_new_tokens for c in cfgs)
    grid = tuple(sorted({max(num_latents, max_len // 2), max_len}))

    def run(make_engine):
        compile_engine = make_engine()
        for p, c in zip(prompts, cfgs):
            compile_engine.submit(p, config=c)
        compile_engine.run_until_idle()
        engine = make_engine()
        t0 = time.perf_counter()
        for p, c in zip(prompts, cfgs):
            engine.submit(p, config=c)
        engine.run_until_idle()
        dt = time.perf_counter() - t0
        return engine, dt

    def row_waste(engine):
        counts = engine.registry.counters()
        return round(
            counts.get("serving_decode_rows_padded_total", 0.0)
            / max(1.0, counts.get("serving_decode_rows_total", 0.0)), 4,
        )

    table = BucketTable(prompt_lens=grid, batch_sizes=(slots,))
    exact_sizes = tuple(sorted({2 ** i for i in range(slots.bit_length())} | {slots}))
    table_exact = BucketTable(prompt_lens=grid, batch_sizes=exact_sizes)
    bucket_engine, bucket_dt = run(
        lambda: ServingEngine(model, params, base, table)
    )
    bucket_exact_engine, bucket_exact_dt = run(
        lambda: ServingEngine(model, params, base, table_exact)
    )
    slot_engine, slot_dt = run(
        lambda: SlotServingEngine(model, params, base, table, slots=slots)
    )
    slot_stats = slot_engine.stats()
    bucket_tps = useful_tokens / bucket_dt
    bucket_exact_tps = useful_tokens / bucket_exact_dt
    slot_tps = useful_tokens / slot_dt
    return {
        "workload": {
            "requests": n_requests,
            "useful_tokens": useful_tokens,
            "max_new_pool": list(pool),
            "distinct_prompt_lens": int(len(set(int(s) for s in sizes))),
            "slots": slots,
        },
        "bucket": {
            "tokens_per_sec": round(bucket_tps, 1),
            "batches": bucket_engine.stats()["batches"],
            "decode_rows_padding_waste": row_waste(bucket_engine),
        },
        "bucket_exact": {
            "tokens_per_sec": round(bucket_exact_tps, 1),
            "batches": bucket_exact_engine.stats()["batches"],
            "decode_rows_padding_waste": row_waste(bucket_exact_engine),
            "batch_sizes": list(exact_sizes),
        },
        "slots": {
            "tokens_per_sec": round(slot_tps, 1),
            "decode_steps": slot_stats["decode_steps"],
            "prefills": slot_stats["prefills"],
            "slot_occupancy": slot_stats["slot_occupancy"],
            "decode_rows_padding_waste": slot_stats["decode_rows_padding_waste"],
            "p50_decode_step_ms": slot_stats["decode_step_ms"]["p50"],
        },
        "slots_vs_bucket_speedup": round(slot_tps / bucket_tps, 2),
        "slots_vs_bucket_exact_speedup": round(slot_tps / bucket_exact_tps, 2),
    }


def _bench_paged_kv(model, params, cfg, *, dense_slots: int = 4,
                    paged_slots: int = 12, n_requests: int = 24,
                    block_size: int = None):
    """Dense-vs-paged KV layout A/B on a long-tail mixed-context workload
    (ISSUE 9 acceptance; docs/serving.md "Block-paged KV"). The dense slot
    engine sizes every resident's cross-KV cache at the FULL context, so a
    simulated HBM budget of ``dense_slots`` context-lengths of KV caps it
    at ``dense_slots`` residents no matter how short the requests are. The
    paged engine gets the SAME budget as a block pool
    (``kv_blocks = dense_slots * pages_per_slot``) behind more slots: each
    resident consumes only its own ``ceil((prompt + max_new)/block)``
    blocks, so the mostly-short long-tail traffic packs strictly more
    concurrent residents into the same bytes — ``max_residents`` and the
    ratio are the recorded acceptance numbers, alongside tokens/s, the
    pool's page-utilization stats, and a token-identity check between the
    two layouts' outputs (the exactness invariant, also pinned by
    ``tests/test_paged_kv.py``).

    Shapes derive from ``cfg``, so the probe runs at the reduced
    CPU-fallback shape; prompt lengths are capped the way the other serve
    probes cap them (the dense layout's per-resident cost is
    context-sized regardless of prompt length, so the capacity comparison
    is unaffected)."""
    import dataclasses

    import jax.numpy as jnp
    import numpy as np

    from perceiver_io_tpu.inference import cast_float_params
    from perceiver_io_tpu.inference.generate import GenerationConfig
    from perceiver_io_tpu.serving import BucketTable, SlotServingEngine

    params = cast_float_params(params, jnp.bfloat16)
    n = cfg.max_seq_len
    num_latents = min(4, cfg.max_latents)
    if block_size is None:
        block_size = max(4, n // 32)
    pages_per_slot = -(-n // block_size)
    short_new = max(2, min(8, cfg.max_latents - num_latents))
    long_new = 2
    short_len = max(num_latents, min(64, n // 8))
    long_len = max(short_len, min(256, n // 2, model.max_prefix_len + num_latents,
                                  n - long_new))
    rng = np.random.default_rng(0)
    from perceiver_io_tpu.inference.samplers import SamplingConfig

    # greedy: the token-identity check must not depend on the two arms'
    # PRNG streams lining up
    base = GenerationConfig(
        max_new_tokens=short_new, num_latents=num_latents,
        sampling=SamplingConfig(temperature=0.0),
    )
    long_cfg = dataclasses.replace(base, max_new_tokens=long_new)
    reqs = []
    for i in range(n_requests):
        if i % 6 == 1:  # the long tail: ~1 in 6 requests near the cap
            reqs.append((
                rng.integers(1, cfg.vocab_size, size=long_len, dtype=np.int32),
                long_cfg,
            ))
        else:
            reqs.append((
                rng.integers(1, cfg.vocab_size, size=short_len, dtype=np.int32),
                base,
            ))
    useful_tokens = sum(c.max_new_tokens for _, c in reqs)
    table = BucketTable(
        prompt_lens=tuple(sorted({short_len, long_len})), batch_sizes=(1,)
    )
    budget_blocks = dense_slots * pages_per_slot  # the simulated HBM budget

    def run(make_engine):
        compile_engine = make_engine()
        for p, c in reqs:
            compile_engine.submit(p, config=c)
        compile_engine.run_until_idle()
        engine = make_engine()
        handles = []
        for p, c in reqs:
            handles.append(engine.submit(p, config=c))
        max_residents = 0
        t0 = time.perf_counter()
        while engine.pending():
            engine.step()
            active = sum(1 for s in engine._slots if s is not None)
            if engine._admitting is not None:
                active += 1
            max_residents = max(max_residents, active)
        dt = time.perf_counter() - t0
        return engine, dt, max_residents, [h.result for h in handles]

    dense_engine, dense_dt, dense_res, dense_outs = run(
        lambda: SlotServingEngine(
            model, params, base, table, slots=dense_slots, kv_layout="dense"
        )
    )
    paged_engine, paged_dt, paged_res, paged_outs = run(
        lambda: SlotServingEngine(
            model, params, base, table, slots=paged_slots, kv_layout="paged",
            kv_block_size=block_size, kv_blocks=budget_blocks,
        )
    )
    token_identical = all(
        a is not None and b is not None and bool(np.array_equal(a, b))
        for a, b in zip(dense_outs, paged_outs)
    )
    pool = paged_engine.stats()["kv_pool"]
    token_bytes = paged_engine._kv_token_bytes
    return {
        "workload": {
            "requests": n_requests,
            "useful_tokens": useful_tokens,
            "short_len": short_len,
            "long_len": long_len,
            "long_fraction": round(sum(1 for _, c in reqs if c is long_cfg)
                                   / n_requests, 3),
            "block_size": block_size,
            "hbm_budget_blocks": budget_blocks,
            "hbm_budget_bytes": budget_blocks * block_size * token_bytes,
        },
        "dense": {
            "slots": dense_slots,
            "max_residents": dense_res,
            "tokens_per_sec": round(useful_tokens / dense_dt, 1),
            "kv_resident_bytes": dense_slots * n * token_bytes,
        },
        "paged": {
            "slots": paged_slots,
            "max_residents": paged_res,
            "tokens_per_sec": round(useful_tokens / paged_dt, 1),
            "blocks_high_water": pool["high_water"],
            "page_utilization_high_water": round(
                pool["high_water"] / max(1, pool["blocks"]), 4
            ),
            "admit_waits": pool["admit_waits"],
            "block_allocs": pool["allocs_total"],
            "block_frees": pool["frees_total"],
        },
        "max_residents_ratio": round(paged_res / max(1, dense_res), 2),
        "paged_vs_dense_tokens_ratio": round(
            (useful_tokens / paged_dt) / (useful_tokens / dense_dt), 2
        ),
        "token_identical": token_identical,
    }


def _bench_preemption(model, params, cfg, *, budget_slots: int = 3,
                      engine_slots: int = 10, n_requests: int = 24,
                      block_size: int = None):
    """Strict-reservation vs optimistic-admission A/B at ONE simulated HBM
    budget (ISSUE 17 acceptance; docs/serving.md "Preemption &
    priorities") on a long-tail ``max_new`` workload: most requests decode
    a couple of tokens, ~1 in 6 declares a near-context ``max_new`` cap.
    The strict arm (``preemption=off``) reserves every resident's WORST
    CASE up front, so each long-tail request pins near a context-length of
    pool blocks it mostly never maps, and short requests queue behind that
    paper debt. The optimistic arm (``preemption="recompute"``) admits on
    prompt pages + headroom and reclaims real pages by preempting victims
    (recompute-from-prompt replay) only on genuine exhaustion — packing
    strictly more concurrent residents into the SAME bytes.

    Recorded acceptance numbers: ``max_residents_ratio`` and
    ``residents_per_hbm_byte`` per arm (the packing win),
    ``goodput_under_slo`` per arm — the fraction of requests completing
    within an SLO pinned at the STRICT arm's p50 completion latency, so
    the strict arm scores ~0.5 by construction and the optimistic arm
    beats it by finishing the short tail sooner — the preemption /
    readmission counts actually exercised, and the greedy token-identity
    check between the arms (preempt/replay must be invisible in the token
    stream, the bar pinned by ``tests/test_kv_preemption.py``)."""
    import dataclasses

    import jax.numpy as jnp
    import numpy as np

    from perceiver_io_tpu.inference import cast_float_params
    from perceiver_io_tpu.inference.generate import GenerationConfig
    from perceiver_io_tpu.inference.samplers import SamplingConfig
    from perceiver_io_tpu.serving import BucketTable, SlotServingEngine

    params = cast_float_params(params, jnp.bfloat16)
    n = cfg.max_seq_len
    num_latents = min(4, cfg.max_latents)
    if block_size is None:
        block_size = max(4, n // 32)
    pages_per_slot = -(-n // block_size)
    short_new = max(2, min(4, cfg.max_latents - num_latents))
    short_len = max(num_latents, min(64, n // 8))
    # the long tail declares a near-context max_new CAP — the strict arm
    # reserves it all up front; actual decode still stops at the cap
    long_len = short_len
    long_new = max(short_new + 1, min(n - long_len, model.max_prefix_len))
    rng = np.random.default_rng(0)
    base = GenerationConfig(
        max_new_tokens=short_new, num_latents=num_latents,
        sampling=SamplingConfig(temperature=0.0),  # greedy: identity check
    )
    long_cfg = dataclasses.replace(base, max_new_tokens=long_new)
    reqs = []
    for i in range(n_requests):
        cfg_i = long_cfg if i % 3 == 1 else base
        reqs.append((
            rng.integers(1, cfg.vocab_size, size=short_len, dtype=np.int32),
            cfg_i,
        ))
    useful_tokens = sum(c.max_new_tokens for _, c in reqs)
    table = BucketTable(prompt_lens=(short_len,), batch_sizes=(1,))
    budget_blocks = budget_slots * pages_per_slot  # the simulated budget

    def run(preemption):
        def make_engine():
            return SlotServingEngine(
                model, params, base, table, slots=engine_slots,
                kv_layout="paged", kv_block_size=block_size,
                kv_blocks=budget_blocks, preemption=preemption,
                admit_headroom_blocks=1 if preemption else 0,
            )
        compile_engine = make_engine()
        for p, c in reqs:
            compile_engine.submit(p, config=c)
        compile_engine.run_until_idle()
        engine = make_engine()
        handles = [engine.submit(p, config=c) for p, c in reqs]
        done_at = [None] * len(handles)
        max_residents = 0
        t0 = time.perf_counter()
        while engine.pending():
            engine.step()
            now = time.perf_counter() - t0
            active = sum(1 for s in engine._slots if s is not None)
            if engine._admitting is not None:
                active += 1
            max_residents = max(max_residents, active)
            for i, h in enumerate(handles):
                if done_at[i] is None and h.done:
                    done_at[i] = now
        dt = time.perf_counter() - t0
        outs = [h.result for h in handles]
        return engine, dt, max_residents, outs, done_at

    strict_engine, strict_dt, strict_res, strict_outs, strict_done = run(None)
    lazy_engine, lazy_dt, lazy_res, lazy_outs, lazy_done = run("recompute")
    token_identical = all(
        a is not None and b is not None and bool(np.array_equal(a, b))
        for a, b in zip(strict_outs, lazy_outs)
    )
    # SLO pinned at the strict arm's p50 completion latency: the strict
    # arm scores ~0.5 by construction, so goodput_under_slo is directly
    # comparable across arms without picking a magic number
    slo_s = float(np.median([t for t in strict_done if t is not None]))

    def arm(engine, dt, residents, done, preemption):
        pool = engine.stats()["kv_pool"]
        pre = engine.stats().get("preemption") or {}
        token_bytes = engine._kv_token_bytes
        budget_bytes = budget_blocks * block_size * token_bytes
        return {
            "preemption": preemption or "off",
            "max_residents": residents,
            "residents_per_hbm_byte": round(residents / budget_bytes, 12),
            "tokens_per_sec": round(useful_tokens / dt, 1),
            "goodput_under_slo": round(
                sum(1 for t in done if t is not None and t <= slo_s)
                / len(done), 4
            ),
            "preemptions": int(pre.get("preemptions", 0)),
            "readmissions": int(pre.get("readmissions", 0)),
            "blocks_high_water": pool["high_water"],
            "admit_waits": pool["admit_waits"],
            # recompute-vs-swap post-mortem model (ISSUE 18): what each
            # eviction cost in replayed decode steps vs what a host-swap of
            # the victim's pages would have cost at swap_link_gbps — the
            # number that decides whether a swap tier is worth building
            "postmortems": engine.postmortems(),
        }

    return {
        "workload": {
            "requests": n_requests,
            "useful_tokens": useful_tokens,
            "prompt_len": short_len,
            "short_max_new": short_new,
            "long_max_new": long_new,
            "long_fraction": round(sum(1 for _, c in reqs if c is long_cfg)
                                   / n_requests, 3),
            "block_size": block_size,
            "hbm_budget_blocks": budget_blocks,
            "slo_s": round(slo_s, 4),
        },
        "strict": arm(strict_engine, strict_dt, strict_res, strict_done,
                      None),
        "optimistic": arm(lazy_engine, lazy_dt, lazy_res, lazy_done,
                          "recompute"),
        "max_residents_ratio": round(lazy_res / max(1, strict_res), 2),
        "token_identical": token_identical,
    }


def _bench_swap(model, params, cfg, *, budget_slots: int = 3,
                engine_slots: int = 8, n_requests: int = 12,
                block_size: int = None, lengths=None):
    """Recompute vs host-swap vs auto preemption over a generated-length
    sweep at ONE fixed pool budget (ISSUE 20 acceptance; docs/serving.md
    "Host-swap preemption"). Every request declares the same ``max_new``
    per sweep point, so a victim's discarded work grows linearly with the
    sweep axis while its page footprint (the swap transfer) stays bounded
    by the pool — recompute cost scales with generated length, swap cost
    doesn't, and the measured wall-clock crossing is the
    ``crossover_length`` the post-mortem model predicts.

    Recorded acceptance numbers per arm and length: wall-to-drain,
    ``goodput_under_slo`` (SLO pinned at the recompute arm's p50
    completion per length), preemption/swap churn, and greedy
    ``token_identical`` vs an UNPRESSURED baseline. Plus the two model
    honesty bars: ``predicted_advantage_ms`` (recompute arm's post-mortem
    ``swap_advantage_ms``) must agree in sign with
    ``realized_advantage_ms`` (recompute wall - swap wall) at the longest
    length, and the ``auto`` arm's per-victim dispositions must never
    pick the arm its own post-mortem record scores worse
    (``auto_agrees``)."""
    import dataclasses

    import jax.numpy as jnp
    import numpy as np

    from perceiver_io_tpu.inference import cast_float_params
    from perceiver_io_tpu.inference.generate import GenerationConfig
    from perceiver_io_tpu.inference.samplers import SamplingConfig
    from perceiver_io_tpu.serving import BucketTable, SlotServingEngine

    params = cast_float_params(params, jnp.bfloat16)
    n = cfg.max_seq_len
    num_latents = min(4, cfg.max_latents)
    if block_size is None:
        block_size = max(4, n // 32)
    pages_per_slot = -(-n // block_size)
    prompt_len = max(num_latents, min(64, n // 8))
    max_len = min(n - prompt_len, model.max_prefix_len)
    if lengths is None:
        lengths = sorted({max(2, max_len // 8), max(3, max_len // 2),
                          max(4, max_len)})
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, cfg.vocab_size, size=prompt_len,
                            dtype=np.int32) for _ in range(n_requests)]
    table = BucketTable(prompt_lens=(prompt_len,), batch_sizes=(1,))
    budget_blocks = budget_slots * pages_per_slot
    base = GenerationConfig(
        max_new_tokens=2, num_latents=num_latents,
        sampling=SamplingConfig(temperature=0.0),  # greedy: identity check
    )

    def run(preemption, gen_cfg, kv_blocks, *, warm=True):
        def make_engine():
            return SlotServingEngine(
                model, params, gen_cfg, table, slots=engine_slots,
                kv_layout="paged", kv_block_size=block_size,
                kv_blocks=kv_blocks, preemption=preemption,
                admit_headroom_blocks=1 if preemption else 0,
            )
        if warm:
            compile_engine = make_engine()
            for p in prompts:
                compile_engine.submit(p, config=gen_cfg)
            compile_engine.run_until_idle()
        engine = make_engine()
        handles = [engine.submit(p, config=gen_cfg) for p in prompts]
        done_at = [None] * len(handles)
        t0 = time.perf_counter()
        while engine.pending():
            engine.step()
            now = time.perf_counter() - t0
            for i, h in enumerate(handles):
                if done_at[i] is None and h.done:
                    done_at[i] = now
        dt = time.perf_counter() - t0
        return engine, dt, [h.result for h in handles], done_at

    sweep = []
    crossover = None
    for length in lengths:
        gen_cfg = dataclasses.replace(base, max_new_tokens=int(length))
        # unpressured baseline: enough blocks that nothing preempts
        _, _, ref_outs, _ = run(
            None, gen_cfg, engine_slots * pages_per_slot, warm=True
        )
        arms = {}
        for mode in ("recompute", "swap", "auto"):
            # warmed per arm: the pool size is part of the executor shape,
            # so the baseline's compile pass doesn't cover the budget pool
            engine, dt, outs, done = run(
                mode, gen_cfg, budget_blocks, warm=True
            )
            arms[mode] = (engine, dt, outs, done)
        slo_s = float(np.median(
            [t for t in arms["recompute"][3] if t is not None]
        ))
        point = {"length": int(length), "slo_s": round(slo_s, 4)}
        for mode, (engine, dt, outs, done) in arms.items():
            pre = engine.stats().get("preemption") or {}
            pm = engine.postmortems()
            point[mode] = {
                "wall_s": round(dt, 4),
                "goodput_under_slo": round(
                    sum(1 for t in done if t is not None and t <= slo_s)
                    / len(done), 4
                ),
                "preemptions": int(pre.get("preemptions", 0)),
                "swaps": int(pre.get("swaps", 0)),
                "swap_restores": int(pre.get("swap_restores", 0)),
                "swap_bytes": int(pre.get("swap_bytes", 0)),
                "token_identical": all(
                    a is not None and b is not None
                    and bool(np.array_equal(a, b))
                    for a, b in zip(outs, ref_outs)
                ),
                "postmortems": {
                    k: pm[k] for k in (
                        "count", "swapped", "recompute_est_ms",
                        "swap_est_ms", "swap_advantage_ms",
                        "swap_measured_ms", "swap_link_gbps",
                    )
                },
            }
        point["realized_advantage_ms"] = round(
            (arms["recompute"][1] - arms["swap"][1]) * 1e3, 3
        )
        point["predicted_advantage_ms"] = \
            point["recompute"]["postmortems"]["swap_advantage_ms"]
        # the auto honesty bar: every per-victim disposition matches the
        # cheaper side of its own post-mortem record
        auto_recent = arms["auto"][0].postmortems()["recent"]
        point["auto_agrees"] = all(
            r["mode"] == ("swap" if r["swap_est_ms"] < r["recompute_est_ms"]
                          else "recompute")
            for r in auto_recent
        )
        if crossover is None and point["realized_advantage_ms"] > 0:
            crossover = int(length)
        sweep.append(point)

    last = sweep[-1] if sweep else {}
    return {
        "workload": {
            "requests": n_requests,
            "prompt_len": prompt_len,
            "lengths": [int(x) for x in lengths],
            "block_size": block_size,
            "hbm_budget_blocks": budget_blocks,
        },
        "sweep": sweep,
        "crossover_length": crossover,
        "token_identical": all(
            p[mode]["token_identical"]
            for p in sweep for mode in ("recompute", "swap", "auto")
        ),
        "auto_agrees": all(p["auto_agrees"] for p in sweep),
        "advantage_sign_agrees": (
            bool(last) and
            (last["predicted_advantage_ms"] > 0)
            == (last["realized_advantage_ms"] > 0)
        ),
    }


def _bench_quant_kv(model, params, cfg, *, exact_slots: int = 4,
                    n_requests: int = 32, block_size: int = None,
                    new_tokens: int = 4):
    """Exact-vs-int8 paged KV A/B at ONE simulated HBM budget (ISSUE 16
    acceptance; docs/serving.md "Quantized KV"). The exact arm sizes a
    block pool to ``exact_slots`` context-lengths of KV; the int8 arm gets
    the SAME byte budget, which buys ``~4d/(d+4)`` times the blocks (int8
    entries + f32 per-(position, head) scales vs exact entries) and
    therefore proportionally more concurrent residents on short-request
    traffic — ``residents_per_hbm_byte_ratio`` is the recorded acceptance
    number, alongside tokens/s, the greedy token-match rate between the
    arms, and the autotuner quality probe's logit-delta verdict (the gate
    that decides whether ``kv_layout="auto"`` may ever pick int8).

    Params stay f32 — the CPU probe's computation dtype — so the byte
    ratio is the honest f32-pool-vs-int8-pool one (recorded per arm as
    ``pos_bytes``/``dtype``), not an assumed-bf16 figure."""
    import numpy as np

    from perceiver_io_tpu.inference import decode_strategy as strategy_mod
    from perceiver_io_tpu.inference.generate import GenerationConfig
    from perceiver_io_tpu.inference.samplers import SamplingConfig
    from perceiver_io_tpu.serving import BucketTable, SlotServingEngine

    n = cfg.max_seq_len
    num_latents = min(4, cfg.max_latents)
    if block_size is None:
        block_size = max(4, n // 32)
    pages_per_slot = -(-n // block_size)
    prompt_len = max(num_latents, min(24, n // 4))
    rng = np.random.default_rng(0)
    gen = GenerationConfig(
        max_new_tokens=new_tokens, num_latents=num_latents,
        sampling=SamplingConfig(temperature=0.0),  # greedy: comparable arms
    )
    prompts = [
        rng.integers(1, cfg.vocab_size, size=prompt_len, dtype=np.int32)
        for _ in range(n_requests)
    ]
    useful_tokens = n_requests * new_tokens
    table = BucketTable(prompt_lens=(prompt_len,), batch_sizes=(1,))

    # per-position byte costs from the ENGINES' own accounting (satellite:
    # capacity math follows the resolved layout's dtype), read off two
    # 1-slot throwaway engines rather than re-derived here
    def pos_bytes(layout):
        e = SlotServingEngine(
            model, params, gen, table, slots=1, kv_layout=layout,
            kv_block_size=block_size,
        )
        return e._kv_token_bytes + e._kv_scale_token_bytes, str(
            e.stats()["kv_pool"]["dtype"]
        )

    exact_pos_bytes, exact_dtype = pos_bytes("paged")
    int8_pos_bytes, int8_dtype = pos_bytes("paged_int8")
    bpr = -(-(prompt_len + new_tokens) // block_size)  # blocks per request
    # the simulated HBM budget: exactly ``exact_slots`` concurrent
    # residents' worth of exact-pool blocks — scarce enough that BOTH arms
    # are block-bound (not request- or slot-capped), so the resident ratio
    # measures bytes and nothing else
    budget_blocks = exact_slots * bpr
    budget_bytes = budget_blocks * block_size * exact_pos_bytes
    int8_blocks = int(budget_bytes // (block_size * int8_pos_bytes))
    slots_e = max(1, min(n_requests, budget_blocks // bpr))
    slots_q = max(1, min(n_requests, int8_blocks // bpr))

    def run(layout, slots, kv_blocks):
        def make():
            return SlotServingEngine(
                model, params, gen, table, slots=slots, kv_layout=layout,
                kv_block_size=block_size, kv_blocks=kv_blocks,
            )
        compile_engine = make()
        for p in prompts:
            compile_engine.submit(p)
        compile_engine.run_until_idle()
        engine = make()
        handles = [engine.submit(p) for p in prompts]
        max_residents = 0
        t0 = time.perf_counter()
        while engine.pending():
            engine.step()
            active = sum(1 for s in engine._slots if s is not None)
            if engine._admitting is not None:
                active += 1
            max_residents = max(max_residents, active)
        dt = time.perf_counter() - t0
        return engine, dt, max_residents, [h.result for h in handles]

    _, exact_dt, exact_res, exact_outs = run("paged", slots_e, budget_blocks)
    int8_engine, int8_dt, int8_res, int8_outs = run(
        "paged_int8", slots_q, int8_blocks
    )
    ident = total = match = 0
    for a, b in zip(exact_outs, int8_outs):
        if a is None or b is None:
            continue
        a, b = np.asarray(a), np.asarray(b)
        ident += int(np.array_equal(a, b))
        L = min(a.size, b.size)
        total += max(a.size, b.size)
        match += int(np.sum(a[:L] == b[:L]))
    quality = strategy_mod.quant_quality_probe(
        model, params, block_size=min(block_size, 16)
    )
    pool = int8_engine.stats()["kv_pool"]
    return {
        "workload": {
            "requests": n_requests,
            "useful_tokens": useful_tokens,
            "prompt_len": prompt_len,
            "new_tokens": new_tokens,
            "block_size": block_size,
            "blocks_per_request": bpr,
            "hbm_budget_bytes": int(budget_bytes),
        },
        "exact": {
            "layout": "paged",
            "dtype": exact_dtype,
            "pos_bytes": int(exact_pos_bytes),
            "slots": slots_e,
            "kv_blocks": budget_blocks,
            "max_residents": exact_res,
            "tokens_per_sec": round(useful_tokens / exact_dt, 1),
        },
        "int8": {
            "layout": "paged_int8",
            "dtype": int8_dtype,
            "pos_bytes": int(int8_pos_bytes),
            "slots": slots_q,
            "kv_blocks": int8_blocks,
            "max_residents": int8_res,
            "tokens_per_sec": round(useful_tokens / int8_dt, 1),
            "block_scale_bytes": pool["block_scale_bytes"],
            "blocks_high_water": pool["high_water"],
        },
        "block_bytes_ratio": round(exact_pos_bytes / int8_pos_bytes, 2),
        "residents_per_hbm_byte_ratio": round(int8_res / max(1, exact_res), 2),
        "int8_vs_exact_tokens_ratio": round(
            (useful_tokens / int8_dt) / (useful_tokens / exact_dt), 2
        ),
        "requests_token_identical": ident,
        "token_match_rate": round(match / max(1, total), 4),
        "quality_gate": quality,
    }


def _bench_prefix_cache(model, params, cfg, *, slots: int = 8,
                        n_requests: int = 24, n_prefixes: int = 2,
                        block_size: int = None, prefix_tokens: int = None,
                        budget_blocks: int = None, new_tokens: int = 4,
                        zipf: float = 2.5):
    """Prefix-sharing A/B (ISSUE 12 acceptance; docs/serving.md "Prefix
    sharing"): a Zipf-distributed shared-prefix workload — the
    :class:`~perceiver_io_tpu.observability.WorkloadSpec` shared-prefix
    distribution, a pool of ``n_prefixes`` long "system prompts" sampled
    by Zipf popularity with short fresh tails — served through the paged
    slot engine twice at ONE simulated HBM budget: ``prefix_cache="off"``
    (every admit re-projects its full prompt and reserves private pages)
    vs ``"on"`` (hot prefixes map by reference, prefill projects only the
    suffix). Recorded acceptance numbers: the TTFT p50/p95 ratio (the
    unshared full-window projection + the deeper queue it causes, vs
    block-table writes + suffix projection), concurrent
    residents-per-HBM-byte (shared blocks are reserved once, not per
    resident), the hit ratio, and ``token_identical`` between the two
    arms' greedy outputs (the exactness bar, also pinned by
    ``tests/test_prefix_cache.py``).

    Like ``_bench_prefill_chunk_ab``, the probe builds its own model at
    ``cfg``'s context/width but with a TIGHT latent segment
    (``max_latents = 2 * num_latents``): admission cost then comes from
    the prefix positions themselves — the full-window embedding +
    cross-k/v projection sharing elides — rather than from the
    latent-segment stack, which every admission pays identically in both
    arms (at ``max_latents=256`` the stack is most of the prefill and
    buries the A/B in shared cost)."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np

    from perceiver_io_tpu.inference import cast_float_params
    from perceiver_io_tpu.inference.generate import GenerationConfig
    from perceiver_io_tpu.inference.samplers import SamplingConfig
    from perceiver_io_tpu.models.text.clm import (
        CausalLanguageModel,
        CausalLanguageModelConfig,
    )
    from perceiver_io_tpu.observability import MetricsRegistry, WorkloadSpec
    from perceiver_io_tpu.serving import BucketTable, SlotServingEngine

    n = cfg.max_seq_len
    num_latents = min(4, cfg.max_latents)
    if cfg.max_latents > 2 * num_latents:
        probe_cfg = CausalLanguageModelConfig(
            vocab_size=cfg.vocab_size,
            max_seq_len=n,
            max_latents=2 * num_latents,
            num_channels=cfg.num_channels,
            num_heads=cfg.num_heads,
            num_self_attention_layers=cfg.num_self_attention_layers,
            cross_attention_dropout=0.0,
        )
        model = CausalLanguageModel(probe_cfg)
        params = model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, n), jnp.int32),
            n - probe_cfg.max_latents,
        )["params"]
        cfg = probe_cfg
    params = cast_float_params(params, jnp.bfloat16)
    if block_size is None:
        block_size = max(4, min(16, n // 32))
    if prefix_tokens is None:
        # long hot prefix, well past the latent budget, bounded by the
        # prefix-capacity scope check
        prefix_tokens = max(
            block_size * 2,
            min(n // 4, model.max_prefix_len - 32, 384) // block_size * block_size,
        )
    tail_lo, tail_hi = 8, 16
    bucket = prefix_tokens + tail_hi
    if bucket + new_tokens > n:
        raise ValueError("prefix-cache probe shape exceeds the context")
    table = BucketTable(prompt_lens=(bucket,), batch_sizes=(1,))
    gcfg = GenerationConfig(
        max_new_tokens=new_tokens, num_latents=num_latents,
        sampling=SamplingConfig(temperature=0.0),  # greedy: cross-arm identity
    )
    workload = WorkloadSpec(
        prompt_len=(tail_lo, tail_hi),
        max_new_tokens=(new_tokens, new_tokens),
        vocab=(1, cfg.vocab_size),
        shared_prefix_pool=n_prefixes,
        shared_prefix_len=(prefix_tokens, prefix_tokens),
        shared_prefix_zipf=zipf,
    )
    rng = np.random.default_rng(0)
    prompts = [workload.sample_prompt(rng) for _ in range(n_requests)]
    per_req_blocks = -(-(prefix_tokens + tail_hi + new_tokens) // block_size)
    if budget_blocks is None:
        # fits ~3 unshared residents: the unshared arm serializes on the
        # pool while the shared arm — whose residents reserve only their
        # private suffix pages — packs the cached prefixes plus a full
        # house of slots into the same bytes
        budget_blocks = per_req_blocks * 7 // 2
    token_bytes = None

    def run(pc):
        nonlocal token_bytes
        registry = MetricsRegistry()
        engine = SlotServingEngine(
            model, params, gcfg, table, slots=slots, kv_layout="paged",
            kv_block_size=block_size, kv_blocks=budget_blocks,
            prefix_cache=pc, registry=registry,
        )
        engine.warmup()  # compiles are process-global: measured once
        token_bytes = engine._kv_token_bytes
        handles = [engine.submit(p, config=gcfg) for p in prompts]
        max_residents = 0
        t0 = time.perf_counter()
        while engine.pending():
            engine.step()
            active = sum(1 for s in engine._slots if s is not None)
            if engine._admitting is not None:
                active += 1
            max_residents = max(max_residents, active)
        dt = time.perf_counter() - t0
        stats = engine.stats()
        assert engine._pool.leaked() == 0
        return {
            "outs": [h.result for h in handles],
            "ttft_p50_ms": registry.percentile("serving_ttft_ms", 50.0),
            "ttft_p95_ms": registry.percentile("serving_ttft_ms", 95.0),
            "max_residents": max_residents,
            "tokens_per_sec": round(n_requests * new_tokens / dt, 1),
            "admit_waits": stats["kv_pool"]["admit_waits"],
            "prefix": stats["prefix_cache"],
        }

    off = run("off")
    on = run("on")
    token_identical = all(
        a is not None and b is not None and bool(np.array_equal(a, b))
        for a, b in zip(off["outs"], on["outs"])
    )
    budget_bytes = budget_blocks * block_size * token_bytes

    def arm(r):
        return {
            "ttft_p50_ms": None if r["ttft_p50_ms"] is None else round(r["ttft_p50_ms"], 3),
            "ttft_p95_ms": None if r["ttft_p95_ms"] is None else round(r["ttft_p95_ms"], 3),
            "max_residents": r["max_residents"],
            "residents_per_hbm_gb": round(r["max_residents"] / (budget_bytes / 2**30), 2),
            "tokens_per_sec": r["tokens_per_sec"],
            "admit_waits": r["admit_waits"],
        }

    return {
        "workload": {
            "requests": n_requests,
            "prefixes": n_prefixes,
            "zipf": zipf,
            "prefix_tokens": prefix_tokens,
            "tail_tokens": [tail_lo, tail_hi],
            "block_size": block_size,
            "hbm_budget_blocks": budget_blocks,
            "hbm_budget_bytes": budget_bytes,
        },
        "unshared": arm(off),
        "shared": {**arm(on), "prefix": on["prefix"]},
        "ttft_p50_ratio": round(
            (off["ttft_p50_ms"] or 0.0) / max(1e-9, on["ttft_p50_ms"] or 0.0), 2
        ),
        "ttft_p95_ratio": round(
            (off["ttft_p95_ms"] or 0.0) / max(1e-9, on["ttft_p95_ms"] or 0.0), 2
        ),
        "residents_per_hbm_byte_ratio": round(
            on["max_residents"] / max(1, off["max_residents"]), 2
        ),
        "hit_ratio": on["prefix"]["hit_ratio"],
        "token_identical": token_identical,
    }


def _bench_speculative(model, params, cfg, *, slots: int = 1,
                       n_requests: int = 6, new_tokens: int = 16,
                       speculation: str = "k8d1"):
    """Speculative-decoding A/B (ISSUE 19 acceptance; docs/serving.md
    "Speculative decoding"): the same greedy workload served through the
    slot engine twice — ``speculation="off"`` (one fixed-shape forward per
    token) vs a self-draft geometry (one truncated-stack draft + one
    batched verify per up-to-``k+1``-token round). Recorded acceptance
    numbers: tokens/s per arm and their ratio, the draft acceptance rate,
    accepted tokens per round, and ``token_identical`` between the arms'
    greedy outputs (the exactness bar, also pinned by
    ``tests/test_speculative.py``).

    Speculation pays where decode steps are dispatch-bound, not
    flop-bound — the verify forward batches ``k+1`` lanes, so its FLOPs
    grow with ``k`` while its fixed per-step cost does not. The probe
    therefore builds a deliberately SMALL model (per-step overhead
    dominates, the regime edge TPU serving lives in at batch 1) rather
    than reusing ``cfg``'s width, and serves a SINGLE slot — a lone
    resident pays the full per-pass cost for every one-token step, which
    is exactly what a multi-token round amortizes. The ``autotune`` block pins both
    verdict directions: ``pays`` measures draft geometries on the
    dispatch-bound probe and picks one; ``decline`` offers only a draft
    as deep as the model itself (``d == num_self_attention_layers``), so
    every candidate is skipped and the verdict stays ``"off"``."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from perceiver_io_tpu.inference import decode_strategy as strategy_mod
    from perceiver_io_tpu.inference.generate import GenerationConfig
    from perceiver_io_tpu.inference.samplers import SamplingConfig
    from perceiver_io_tpu.models.text.clm import (
        CausalLanguageModel,
        CausalLanguageModelConfig,
    )
    from perceiver_io_tpu.serving import BucketTable, SlotServingEngine

    probe_cfg = CausalLanguageModelConfig(
        vocab_size=cfg.vocab_size,
        max_seq_len=min(cfg.max_seq_len, 32),
        num_channels=min(cfg.num_channels, 16),
        max_latents=8,
        num_heads=2,
        num_self_attention_layers=2,
        cross_attention_dropout=0.0,
    )
    n = probe_cfg.max_seq_len
    model = CausalLanguageModel(probe_cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, n), jnp.int32),
        n - probe_cfg.max_latents,
    )["params"]
    gcfg = GenerationConfig(
        max_new_tokens=new_tokens, num_latents=2,
        sampling=SamplingConfig(temperature=0.0),  # greedy: cross-arm identity
    )
    table = BucketTable(prompt_lens=(16,), batch_sizes=(1,))
    rng = np.random.default_rng(0)
    prompts = [
        rng.integers(1, probe_cfg.vocab_size, size=int(m)).astype(np.int32)
        for m in rng.integers(6, 14, size=n_requests)
    ]

    def run(spec):
        engine = SlotServingEngine(
            model, params, gcfg, table, slots=slots, speculation=spec,
        )
        engine.warmup()  # compiles are process-global: measured once
        t0 = time.perf_counter()
        outs = engine.serve(prompts)
        dt = time.perf_counter() - t0
        emitted = sum(len(np.asarray(o)) for o in outs)
        stats = engine.stats()
        return {
            "outs": [np.asarray(o) for o in outs],
            "tokens_per_sec": round(emitted / dt, 1),
            "steps": stats["decode_steps"],
            "speculation": stats["speculation"],
        }

    off = run("off")
    spec = run(speculation)
    token_identical = all(
        bool(np.array_equal(a, b)) for a, b in zip(off["outs"], spec["outs"])
    )

    # the autotuner's two verdict directions, measured on the same probe
    # (force=True: the second run must re-measure, not return the first
    # verdict; entries key on the probe shape so neither pollutes cfg's)
    pays = strategy_mod.autotune_speculation(
        model, params, candidates=("k4d1", "k8d1"), force=True,
    )
    pays_entry = strategy_mod.spec_entry(model) or {"speculation": pays}
    decline = strategy_mod.autotune_speculation(
        model, params,
        candidates=(f"k4d{probe_cfg.num_self_attention_layers}",),
        force=True,
    )
    decline_entry = strategy_mod.spec_entry(model) or {"speculation": decline}

    return {
        "workload": {
            "requests": n_requests,
            "new_tokens": new_tokens,
            "speculation": speculation,
            "probe": {
                "channels": probe_cfg.num_channels,
                "layers": probe_cfg.num_self_attention_layers,
                "context": n,
            },
        },
        "off": {"tokens_per_sec": off["tokens_per_sec"],
                "decode_steps": off["steps"]},
        "spec": {"tokens_per_sec": spec["tokens_per_sec"],
                 "decode_steps": spec["steps"]},
        "speedup": round(
            spec["tokens_per_sec"] / max(1e-9, off["tokens_per_sec"]), 2
        ),
        "acceptance_rate": spec["speculation"]["acceptance_rate"],
        "tokens_per_round": spec["speculation"]["tokens_per_round"],
        "token_identical": token_identical,
        "autotune": {"pays": pays_entry, "decline": decline_entry},
    }


def _bench_prefill_chunk_ab(cfg, *, slots: int = 2,
                            resident_new: int = 48, n_long: int = 5,
                            chunk: int = None, episodes: int = 5):
    """Chunked-prefill A/B (ISSUE 5 acceptance): a resident slot decodes
    while a stream of near-window-length admissions flows through the other
    slot, with and without ``prefill_chunk``. Without chunking each
    admission's full-window prefill runs between two decode steps, so the
    resident request's inter-token latency spikes by the whole prefix's
    cost once per admission; with chunking the prefix cache is built one
    bounded chunk per ``step()``. The reported number is the resident
    request's p95 inter-token gap — lower with chunking is the acceptance
    bar at the CPU-fallback shape.

    Two deliberate probe choices. (1) A *stream* of admissions, not one: a
    single admission elevates one gap in ~30, which the 95th percentile
    never sees — the metric only speaks when admissions are a steady
    fraction of traffic, which is also the serving regime chunking is for.
    (2) The probe builds its own model at ``cfg``'s context/width but with
    a tight latent segment (``max_latents = 2 * num_latents``): admission
    cost then comes from the prefix positions themselves (embedding +
    cross-k/v over ~``n`` tokens — the part chunking amortizes) rather
    than from the latent-segment stack, which every admission pays
    identically in both arms (at ``max_latents=256`` it is ~85% of the
    prefill, drowning the A/B in shared cost). Both engines warm up first
    (compiles stay out of the gaps) and serve the identical submission
    schedule, repeated for ``episodes`` interleaved passes with the median
    per-episode p95 reported (this host's steal-time spikes are the same
    order as the signal; one spiked pass must not decide the verdict)."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np

    from perceiver_io_tpu.inference import cast_float_params
    from perceiver_io_tpu.inference.generate import GenerationConfig
    from perceiver_io_tpu.models.text.clm import (
        CausalLanguageModel,
        CausalLanguageModelConfig,
    )
    from perceiver_io_tpu.serving import BucketTable, SlotServingEngine

    n = cfg.max_seq_len
    num_latents = min(16, cfg.max_latents)
    # 4x headroom: the resident request must stay in the cheap latent-growth
    # phase for its whole lifetime (resident_new <= max_latents -
    # num_latents), or every post-crossing step pays the boundary variant's
    # full-window cost in BOTH arms and buries the admission signal
    probe_cfg = CausalLanguageModelConfig(
        vocab_size=cfg.vocab_size,
        max_seq_len=n,
        max_latents=min(cfg.max_latents, 4 * num_latents),
        num_channels=cfg.num_channels,
        num_heads=cfg.num_heads,
        num_self_attention_layers=cfg.num_self_attention_layers,
        cross_attention_dropout=0.0,
    )
    model = CausalLanguageModel(probe_cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, n), jnp.int32),
        n - probe_cfg.max_latents,
    )["params"]
    params = cast_float_params(params, jnp.bfloat16)

    if probe_cfg.max_latents > num_latents:
        # floor of 2: the resident must emit at least two tokens or it has
        # no inter-token gaps to measure (shapes whose latent headroom is 1
        # trade a little boundary-phase noise for a runnable probe)
        resident_new = max(2, min(resident_new, probe_cfg.max_latents - num_latents))
    long_new = 2
    # prefix ~ the whole window, within the bucket feasibility bound
    # (len - num_latents <= max_prefix_len) and the slot scope (len +
    # long_new <= n)
    long_len = min(n - long_new, model.max_prefix_len + num_latents)
    short_len = max(num_latents, min(64, n // 8))
    if chunk is None:
        # ~4 chunk calls per admission: enough to bound each per-step stall
        # well under the one-shot prefill, few enough that the per-call
        # dispatch overhead stays a minority of the chunked arm's gaps
        chunk = max(16, -(-(long_len - num_latents) // 4))
    table = BucketTable(
        prompt_lens=tuple(sorted({short_len, long_len})), batch_sizes=(1,)
    )
    base = GenerationConfig(max_new_tokens=resident_new, num_latents=num_latents)
    rng = np.random.default_rng(0)
    short = rng.integers(1, cfg.vocab_size, size=short_len, dtype=np.int32)
    longs = [
        rng.integers(1, cfg.vocab_size, size=long_len, dtype=np.int32)
        for _ in range(n_long)
    ]
    long_cfg = dataclasses.replace(base, max_new_tokens=long_new)

    def episode(engine) -> "np.ndarray":
        """One measured pass of the workload: a resident decode with a
        steady stream of long admissions; returns the resident's inter-token
        gaps in ms."""
        resident = engine.submit(short)
        gaps = []
        last = None
        emitted = 0
        submitted = 0
        while engine.pending():
            engine.step()
            now = time.perf_counter()
            entry = next(
                (s for s in engine._slots if s is not None and s.req is resident),
                None,
            )
            count = len(entry.emitted) if entry is not None else resident_new
            if count > emitted:
                if last is not None:
                    gaps.append(now - last)
                last = now
                emitted = count
            # steady admission pressure: one long request queued at a time,
            # the next submitted the moment the previous leaves the queue —
            # identical schedule in both arms
            if submitted < n_long and emitted >= 2 and not engine._queue:
                engine.submit(longs[submitted], config=long_cfg)
                submitted += 1
        return np.asarray(gaps) * 1e3

    engines = {
        arm: SlotServingEngine(
            model, params, base, table, slots=slots,
            prefill_chunk=chunk if arm else None,
        )
        for arm in (False, True)
    }
    for engine in engines.values():
        engine.warmup()
    # interleave the arms' episodes so background-noise drift (this host's
    # steal-time spikes) hits both arms equally, and take the median across
    # episodes so one spiked pass cannot decide the verdict
    runs = {False: [], True: []}
    for _ in range(max(1, episodes)):
        for arm in (False, True):
            runs[arm].append(episode(engines[arm]))

    def summarize(arm: bool) -> dict:
        per_ep = runs[arm]
        all_gaps = np.concatenate(per_ep)
        stats = engines[arm].stats()
        return {
            "p95_inter_token_ms": round(float(np.median(
                [np.percentile(g, 95) for g in per_ep])), 3),
            "max_inter_token_ms": round(float(all_gaps.max()), 3),
            "p50_inter_token_ms": round(float(np.percentile(all_gaps, 50)), 3),
            "gaps": int(all_gaps.size),
            "episodes": len(per_ep),
            "prefill_chunks": stats["prefill_chunks"],
            "completed": stats["completed"],
        }

    without = summarize(False)
    with_c = summarize(True)
    return {
        "workload": {
            "slots": slots, "chunk": chunk, "resident_prompt_len": short_len,
            "long_prompt_len": long_len, "long_admissions": n_long,
            "resident_new_tokens": resident_new, "long_new_tokens": long_new,
            "probe_max_latents": probe_cfg.max_latents,
            "probe_ctx": n,
        },
        "without_chunking": without,
        "with_chunking": with_c,
        "p95_ratio_without_over_with": round(
            without["p95_inter_token_ms"] / max(1e-9, with_c["p95_inter_token_ms"]), 2
        ),
        "chunking_lowers_p95": with_c["p95_inter_token_ms"]
        < without["p95_inter_token_ms"],
    }


def _bench_chaos(model, params, cfg, *, n_requests: int = 8, new_tokens: int = 4):
    """Deterministic chaos drill over the serving engine (docs/reliability.md):
    a bounded queue under overload (shed counter), one request hung past its
    deadline (``timed_out``), one request failed at pack time (``failed``) —
    while every other request completes. Faults come from the explicit-hook
    chaos registry on a fake clock, so the probe's outcome is bit-identical
    on every run and every backend; ``survived`` asserts the engine's
    accounting closed (submitted == completed + timed_out + failed + shed)."""
    import jax.numpy as jnp
    import numpy as np

    from perceiver_io_tpu.inference import cast_float_params
    from perceiver_io_tpu.inference.generate import GenerationConfig
    from perceiver_io_tpu.reliability import QueueFull
    from perceiver_io_tpu.reliability.chaos import ChaosRegistry, FakeClock
    from perceiver_io_tpu.serving import BucketTable, ServingEngine

    params = cast_float_params(params, jnp.bfloat16)
    num_latents = min(8, cfg.max_latents)
    max_len = min(32, cfg.max_seq_len // 2, cfg.max_seq_len - cfg.max_latents + num_latents)
    table = BucketTable(prompt_lens=(max_len,), batch_sizes=(2,))
    gcfg = GenerationConfig(max_new_tokens=new_tokens, num_latents=num_latents)

    chaos = ChaosRegistry()
    chaos.hang_request(1, delay_s=2.0)  # > its 1s deadline, < the others'
    chaos.fail_request(2)
    engine = ServingEngine(
        model, params, gcfg, table,
        max_queue=n_requests - 2, default_deadline_s=60.0,
        clock=FakeClock(), chaos=chaos,
    )

    rng = np.random.default_rng(0)
    prompts = [
        rng.integers(1, cfg.vocab_size, size=max_len, dtype=np.int32)
        for _ in range(n_requests)
    ]
    shed = 0
    t0 = time.perf_counter()
    for i, p in enumerate(prompts):
        try:
            engine.submit(p, deadline_s=1.0 if i == 1 else None)
        except QueueFull:
            shed += 1
    engine.drain()
    wall_s = time.perf_counter() - t0
    s = engine.stats()
    accounted = s["completed"] + s["timed_out"] + s["failed"] + shed
    return {
        "submitted": n_requests,
        "shed": shed,
        "timed_out": s["timed_out"],
        "failed": s["failed"],
        "completed": s["completed"],
        "batches": s["batches"],
        "survived": accounted == n_requests and s["queued"] == 0,
        "ready_after_drain": engine.health()["ready"],
        "wall_s": round(wall_s, 3),
    }


def _bench_fleet_chaos(model, params, cfg, *, n_requests: int = 8,
                       new_tokens: int = 6, replicas: int = 3):
    """Supervised-fleet chaos drill (docs/serving.md): a FleetRouter over
    ``replicas`` slot-engine replicas serves a mixed workload while a
    scripted fault kills one replica MID-DECODE (``fleet.replica_step.<r>``
    chaos site). The probe reports goodput and completion ratio under the
    kill, and pins the recovery guarantees: every accepted request
    completes exactly once and — greedy decode being deterministic — every
    recovered output is token-identical to a no-fault reference run.
    Scheduling runs on a FakeClock, so the fault script and outcome replay
    bit-identically; only the goodput wall time is real."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from perceiver_io_tpu.inference import cast_float_params
    from perceiver_io_tpu.inference.generate import GenerationConfig
    from perceiver_io_tpu.reliability.chaos import ChaosRegistry, FakeClock
    from perceiver_io_tpu.serving import BucketTable, FleetRouter, SlotServingEngine

    params = cast_float_params(params, jnp.bfloat16)
    num_latents = min(4, cfg.max_latents)
    max_len = min(
        16, cfg.max_seq_len - new_tokens,
        cfg.max_seq_len - cfg.max_latents + num_latents,
    )
    table = BucketTable(prompt_lens=(max_len,), batch_sizes=(1,))
    gcfg = GenerationConfig(max_new_tokens=new_tokens, num_latents=num_latents)
    rng = np.random.default_rng(0)
    prompts = [
        rng.integers(1, cfg.vocab_size, size=max_len, dtype=np.int32)
        for _ in range(n_requests)
    ]

    def run(chaos):
        clock = FakeClock()

        def factory():
            return SlotServingEngine(
                model, params, gcfg, table, slots=2, clock=clock,
                rng=jax.random.PRNGKey(1),
            )

        fleet = FleetRouter(
            [factory] * replicas, clock=clock, chaos=chaos,
        )
        reqs = [fleet.submit(p) for p in prompts]
        fleet.run_until_idle()
        return fleet, reqs

    _, ref_reqs = run(None)  # no-fault reference (also warms the executors)
    reference = [r.result for r in ref_reqs]

    chaos = ChaosRegistry()
    chaos.crash_replica(0, 3)  # replica 0's 3rd supervised step: mid-decode
    t0 = time.perf_counter()
    fleet, reqs = run(chaos)
    wall_s = time.perf_counter() - t0
    s = fleet.stats()
    completed = sum(1 for r in reqs if r.status == "ok")
    token_identical = all(
        r.status == "ok" and np.array_equal(r.result, want)
        for r, want in zip(reqs, reference)
    )
    from perceiver_io_tpu.observability import goodput_ratio, offered_load

    fleet_counts = fleet.registry.counters()
    return {
        "replicas": replicas,
        "submitted": n_requests,
        "completed": completed,
        "completion_ratio": round(completed / n_requests, 4),
        # the shared goodput definition (observability/slo.py): completed /
        # offered (accepted + shed + rejected) — same helper as the
        # observability and slo_goodput probes
        "offered": offered_load(fleet_counts, "fleet"),
        "goodput_ratio": round(goodput_ratio(fleet_counts, "fleet"), 4),
        "failovers": s["failovers"],
        "redispatches": s["redispatches"],
        "replica_restarts": s["replica_restarts"],
        "duplicate_results_ignored": s["duplicate_results_ignored"],
        "token_identical": token_identical,
        # exactly-once accounting closes: every submission one disposition
        "survived": (
            s["completed"] + s["timed_out"] + s["failed"] == n_requests
            and s["queued"] == 0 and s["dispatched"] == 0
        ),
        "goodput_tokens_per_sec": round(completed * new_tokens / wall_s, 2),
        "wall_s": round(wall_s, 3),
    }


def _bench_elasticity(model, params, cfg, *, n_requests: int = 24,
                      new_tokens: int = 8, slots: int = 1,
                      max_replicas: int = 3, spike_factor: float = 3.0):
    """Fleet-elasticity A/B (docs/serving.md "Elasticity"): the SAME
    deterministic FakeClock flash crowd — baseline Poisson with a
    ``spike_factor``x step (the loadgen ``spike`` arrival) at ~3x one
    replica's capacity — offered to (a) a STATIC single-replica fleet and
    (b) the same fleet behind a :class:`FleetAutoscaler` bounded at
    ``max_replicas``. Both runs share the SLO targets calibrated from a
    healthy closed-loop pass, and goodput-under-SLO is per-point: a
    request is GOOD when it completed AND its own first-token latency met
    the TTFT target (joined from its ``serving.first_token`` event).

    The probe reports both runs' SLO-goodput, the autoscaled run's
    breach -> scale-up -> recovery -> cooldown-gated scale-down timeline
    (``autoscaler.*`` events), and the acceptance pins: the autoscaled
    fleet's goodput-under-SLO beats the static baseline, NO accepted
    request is dropped across the scale transitions, completed outputs are
    token-identical between the two runs (greedy determinism — scale
    churn adds capacity, not entropy), and the scale-down victim's pool
    accounting is zero-leak with its frees tagged ``scale_down``.
    Everything but wall time replays bit-identically."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from perceiver_io_tpu.inference import cast_float_params
    from perceiver_io_tpu.inference.generate import GenerationConfig
    from perceiver_io_tpu.observability import (
        LoadGenerator,
        MetricsRegistry,
        Tracer,
        TTFTProbe,
        WorkloadSpec,
    )
    from perceiver_io_tpu.observability.slo import SLOMonitor, SLOPolicy
    from perceiver_io_tpu.reliability.chaos import FakeClock
    from perceiver_io_tpu.serving import (
        BucketTable,
        FleetAutoscaler,
        FleetRouter,
        SlotServingEngine,
    )

    params = cast_float_params(params, jnp.bfloat16)
    num_latents = min(4, cfg.max_latents)
    max_len = min(
        16, cfg.max_seq_len - new_tokens,
        cfg.max_seq_len - cfg.max_latents + num_latents,
    )
    table = BucketTable(prompt_lens=(max_len,), batch_sizes=(1,))
    gcfg = GenerationConfig(max_new_tokens=new_tokens, num_latents=num_latents)
    workload = WorkloadSpec(
        prompt_len=(max(2, max_len // 2), max_len),
        max_new_tokens=(max(2, 3 * new_tokens // 4), new_tokens),
        vocab=(1, cfg.vocab_size),
    )
    step_cost_s = 0.01

    def build(clock, *, autoscale: bool, registry, tracer, monitor):
        def factory():
            return SlotServingEngine(
                model, params, gcfg, table, slots=slots, clock=clock,
                kv_layout="paged", rng=jax.random.PRNGKey(3),
            )

        fleet = FleetRouter(
            [factory], clock=clock, registry=registry, tracer=tracer,
            slo_monitor=monitor,
        )
        scaler = None
        if autoscale:
            scaler = FleetAutoscaler(
                fleet, min_replicas=1, max_replicas=max_replicas,
                up_cooldown_s=0.3, down_cooldown_s=2.0,
                up_evidence=2, down_evidence=25,
                queue_high=1.0, queue_low=0.5,
            )
        return fleet, scaler

    # warm the executor grid once; every later replica (initial or
    # autoscaler-spawned) reuses the process-global caches
    SlotServingEngine(
        model, params, gcfg, table, slots=slots, kv_layout="paged",
    ).warmup()

    # calibration: a healthy closed-loop pass on one static replica sets
    # capacity (completed req/s on the fake clock) and the TTFT target
    cal_clock = FakeClock()
    cal_fleet, _ = build(
        cal_clock, autoscale=False, registry=MetricsRegistry(clock=cal_clock),
        tracer=None, monitor=None,
    )
    cal = LoadGenerator(
        cal_fleet, workload=workload, mode="closed", users=max(1, slots),
        max_requests=max(6, n_requests // 4), rng=0, clock=cal_clock,
        step_cost_s=step_cost_s,
    ).run()
    base_rps = max(cal["completed_rps"], 0.1)
    cal_reg = cal_fleet.registry
    # target floor = a few scheduler passes: an unqueued FakeClock request
    # can see TTFT 0 (tokens materialize before the pass's clock charge),
    # so the calibration p95 alone can undershoot the service floor
    slo_ttft_ms = round(
        3.0 * max(
            cal_reg.percentile("serving_ttft_ms", 95.0) or 0.0,
            step_cost_s * 1e3,
        ), 3,
    )
    spike_start_s = 1.0
    spike_duration_s = 4.0

    def run(autoscale: bool):
        clock = FakeClock()
        registry = MetricsRegistry(clock=clock)
        tracer = Tracer(clock=clock)
        monitor = SLOMonitor(
            SLOPolicy(ttft_p95_ms=slo_ttft_ms), clock=clock,
            registry=registry, tracer=tracer,
            fast_window_s=1.0, slow_window_s=4.0,
            breach_burn_rate=1.5, min_samples=4,
        )
        fleet, scaler = build(
            clock, autoscale=autoscale, registry=registry, tracer=tracer,
            monitor=monitor,
        )
        # client-side per-request TTFT through the on_token sink — the
        # fleet-drill goodput join (the engines' serving.first_token
        # events carry per-replica trace ids, not the fleet handle's)
        probe = TTFTProbe(fleet, clock)
        gen = LoadGenerator(
            probe, workload=workload, mode="open", arrival="spike",
            rate_rps=0.8 * base_rps, spike_factor=spike_factor,
            spike_start_s=spike_start_s, spike_duration_s=spike_duration_s,
            max_requests=n_requests, config=gcfg, rng=1, clock=clock,
            step_cost_s=step_cost_s,
        )
        report = gen.run()
        # settle: keep the control loop polling after the crowd passes so
        # recovery evidence accumulates and the cooldown-gated scale-down
        # fires (bounded — the drill must terminate even if it never does)
        for _ in range(600):
            if scaler is None or len(fleet.replicas) <= scaler.min_replicas:
                break
            fleet.step()
            clock.advance(step_cost_s)
        good = probe.good_under(slo_ttft_ms)
        return {
            "fleet": fleet, "scaler": scaler, "gen": gen, "probe": probe,
            "report": report, "registry": registry, "tracer": tracer,
            "good": good,
            "goodput_under_slo": round(good / max(1, report["offered"]), 4),
        }

    static = run(False)
    auto = run(True)

    # token identity: same rng -> same offered prompt sequence; every
    # request completed in BOTH runs must match bit-for-bit. Pair by the
    # probe's OFFERED index, not positionally — the runs shed differently
    # (that asymmetry is the whole point of the A/B), so the accepted
    # handle lists misalign as soon as one run drops an offer
    def _by_index(r):
        return {
            rec["index"]: rec["handle"] for rec in r["probe"].records
            if rec["handle"] is not None
        }

    auto_h, static_h = _by_index(auto), _by_index(static)
    pairs = [
        (auto_h[i], static_h[i]) for i in sorted(set(auto_h) & set(static_h))
        if auto_h[i].status == "ok" and static_h[i].status == "ok"
    ]
    token_identical = bool(pairs) and all(
        np.array_equal(a.result, s.result) for a, s in pairs
    )
    scaler = auto["scaler"]
    fleet = auto["fleet"]
    counts = auto["registry"].counters()
    live_pools = [
        r.engine._pool for r in fleet.replicas if r.engine._pool is not None
    ]
    retired_pools = [r["pool"] for r in scaler.retired if r["pool"]]
    timeline = [
        {"at_s": round(sp.start_s, 4), "event": sp.name,
         **{k: sp.attrs[k] for k in ("reason", "replica", "rung",
                                     "replicas_after") if k in sp.attrs}}
        for sp in auto["tracer"].spans()
        if sp.name.startswith(("autoscaler.", "slo."))
    ]
    s = fleet.stats()
    return {
        "requests": n_requests,
        "slots": slots,
        "max_replicas": max_replicas,
        "spike_factor": spike_factor,
        "slo_ttft_ms": slo_ttft_ms,
        "capacity_rps": round(base_rps, 4),
        "static": {
            "goodput_under_slo": static["goodput_under_slo"],
            "completed": static["report"]["completed"],
            "p95_ttft_ms": round(
                static["registry"].percentile("serving_ttft_ms", 95.0) or 0.0, 3
            ),
        },
        "autoscaled": {
            "goodput_under_slo": auto["goodput_under_slo"],
            "completed": auto["report"]["completed"],
            "p95_ttft_ms": round(
                auto["registry"].percentile("serving_ttft_ms", 95.0) or 0.0, 3
            ),
            "scale_ups": scaler.scale_ups,
            "scale_downs": scaler.scale_downs,
            "breaches": int(counts.get("slo_breach_total", 0)),
            "replicas_final": len(fleet.replicas),
            "rung_final": scaler.rung,
        },
        "goodput_ratio_vs_static": round(
            auto["goodput_under_slo"] / max(static["goodput_under_slo"], 1e-4), 4
        ),
        # acceptance pins (tests/test_elasticity.py asserts these)
        "elastic_beats_static": (
            auto["goodput_under_slo"] > static["goodput_under_slo"]
        ),
        "zero_dropped": (
            s["completed"] + s["timed_out"] + s["failed"]
            == s["submitted"] and s["queued"] == 0 and s["dispatched"] == 0
            and s["failed"] == 0
        ),
        "token_identical": token_identical,
        "pool_zero_leak": (
            all(p["leaked"] == 0 and p["in_use"] == 0 for p in retired_pools)
            and all(p.leaked() == 0 and p.in_use == 0 for p in live_pools)
        ),
        # a victim that still held in-flight work at removal tags its
        # frees "scale_down"; one already idle freed on ordinary retire —
        # either way every page was returned (tests/test_elasticity.py
        # pins the tag itself on a mid-flight remove_replica)
        "scale_down_clean": (
            None if not retired_pools else all(
                "scale_down" in p["frees_by_cause"]
                or (p["in_use"] == 0 and p["leaked"] == 0)
                for p in retired_pools
            )
        ),
        "retired": scaler.retired,
        "timeline": timeline,
    }


def _bench_observability(model, params, cfg, *, n_requests: int = 12,
                         new_tokens: int = 4):
    """Unified-telemetry probe (docs/observability.md): mixed-length traffic
    through a registry+tracer-instrumented ``ServingEngine``, with one
    deterministic pack-time fault so goodput < 1 is exercised, not assumed.
    Reports the three per-phase latency histograms (queue wait, batch
    assembly, device execute), serving throughput, goodput
    (completed / submitted), and an MFU gauge — decode FLOPs/token from
    ``utils/flops.flops_approx`` (fwd-only ≈ 2N) against the detected device
    peak (None on the CPU fallback, where no peak is claimable). Also
    asserts span accounting closes: every submission ends in exactly one
    terminal ``serving.request`` span."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from perceiver_io_tpu.inference import cast_float_params
    from perceiver_io_tpu.inference.generate import GenerationConfig
    from perceiver_io_tpu.observability import MetricsRegistry, Tracer
    from perceiver_io_tpu.reliability.chaos import ChaosRegistry
    from perceiver_io_tpu.serving import BucketTable, ServingEngine
    from perceiver_io_tpu.utils.flops import flops_approx

    params = cast_float_params(params, jnp.bfloat16)
    num_latents = min(16, cfg.max_latents)
    max_prefix = cfg.max_seq_len - cfg.max_latents
    max_len = min(128, cfg.max_seq_len // 2, max_prefix + num_latents)
    lens_grid = sorted({max(num_latents, max_len // 2), max_len})
    table = BucketTable(prompt_lens=tuple(lens_grid), batch_sizes=(2, 4))
    gcfg = GenerationConfig(max_new_tokens=new_tokens, num_latents=num_latents)

    chaos = ChaosRegistry()
    chaos.fail_request(2)  # deterministic non-ok terminal state
    registry = MetricsRegistry()
    tracer = Tracer()
    engine = ServingEngine(
        model, params, gcfg, table, chaos=chaos,
        registry=registry, tracer=tracer,
    )

    rng = np.random.default_rng(0)
    lo = max(1, max_len // 4)
    prompts = [
        rng.integers(1, cfg.vocab_size, size=int(n), dtype=np.int32)
        for n in rng.integers(lo, max_len + 1, size=n_requests)
    ]
    t0 = time.perf_counter()
    for p in prompts:
        engine.submit(p)
    engine.drain()
    wall = time.perf_counter() - t0

    s = engine.stats()
    terminal: dict = {}
    for sp in tracer.spans("serving.request"):
        terminal[sp.status] = terminal.get(sp.status, 0) + 1
    # goodput denominator is OFFERED load (accepted + shed + rejected) —
    # the ONE shared definition (observability/slo.py), also used by the
    # fleet-chaos and slo-goodput probes so the three cannot drift
    from perceiver_io_tpu.observability import goodput_ratio
    goodput = goodput_ratio(registry.counters())
    tokens_per_sec = s["tokens_generated"] / wall

    n_params = sum(
        int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(params)
    )
    decode_flops_per_token = flops_approx(n_params) // 3  # fwd-only ≈ 2N
    peak = peak_flops(jax.devices()[0])
    mfu = (
        round(tokens_per_sec * decode_flops_per_token / peak, 6) if peak else None
    )
    registry.set_gauge("serving_throughput_tokens_per_sec", tokens_per_sec)
    registry.set_gauge("serving_goodput_ratio", goodput)
    if mfu is not None:
        registry.set_gauge("serving_mfu", mfu)
    snap = registry.snapshot()
    return {
        "tokens_per_sec": round(tokens_per_sec, 1),
        "goodput": round(goodput, 4),
        "mfu": mfu,
        "queue_wait_ms": snap["histograms"].get("serving_queue_wait_ms"),
        "batch_assembly_ms": snap["histograms"].get("serving_batch_assembly_ms"),
        "device_execute_ms": snap["histograms"].get("serving_device_execute_ms"),
        "request_latency_ms": snap["histograms"].get("serving_request_latency_ms"),
        "terminal_spans": terminal,
        "span_accounting_closed": sum(terminal.values()) == n_requests,
        "requests": n_requests,
        "new_tokens": new_tokens,
        "snapshot": snap,
    }


def _bench_streaming(model, params, cfg, *, slots: int = 4, n_requests: int = 10,
                     abandon_every: int = 2, cancel_after_tokens: int = 2,
                     new_tokens: int = 6):
    """Mid-stream mass-abandonment drill (docs/serving.md "Streaming"):
    the gateway's cancellation-safe retirement path, driven deterministically
    under :class:`~perceiver_io_tpu.reliability.FakeClock` — no sockets, so
    the drill replays bit-identically and the numbers are scheduling, not
    network, latency.

    ``n_requests`` streamed requests run through a PAGED slot engine with
    per-request ``on_token`` sinks; every ``abandon_every``-th stream is
    abandoned the scheduler pass after its ``cancel_after_tokens``-th token
    materializes (how a gateway notices a disconnect: between steps). The
    record pins the three acceptance invariants:

    - **reclaim latency** — token-instant → pool-pages-freed, per victim
      (bounded by one scheduler pass; the "within one step()" bar);
    - **zero leak** — ``kv_pool`` blocks in use / reserved / leaked all 0
      at drain, with the cancelled frees separable in ``frees_by_cause``;
    - **survivor token-identity** — unaffected streams' outputs match a
      fault-free engine pass exactly, incrementally-streamed tokens
      included (``completed + cancelled == accepted`` closes accounting).
    """
    import jax
    import numpy as np

    from perceiver_io_tpu.inference.generate import GenerationConfig
    from perceiver_io_tpu.observability import MetricsRegistry, Tracer
    from perceiver_io_tpu.reliability import FakeClock
    from perceiver_io_tpu.serving import BucketTable, SlotServingEngine

    num_latents = min(4, cfg.max_latents)
    max_len = min(
        16, cfg.max_seq_len - new_tokens,
        cfg.max_seq_len - cfg.max_latents + num_latents,
    )
    table = BucketTable(prompt_lens=(max_len,), batch_sizes=(1,))
    gcfg = GenerationConfig(max_new_tokens=new_tokens, num_latents=num_latents)
    rng = np.random.default_rng(7)
    prompts = [
        rng.integers(1, cfg.vocab_size, size=int(n)).astype(np.int32)
        for n in rng.integers(max(num_latents, max_len // 2), max_len + 1,
                              size=n_requests)
    ]
    step_cost_s = 0.01

    def make_engine(clock, tracer, registry):
        return SlotServingEngine(
            model, params, gcfg, table, slots=slots, kv_layout="paged",
            clock=clock, tracer=tracer, registry=registry,
            rng=jax.random.PRNGKey(3),
        )

    # warm once; the reference pass and the drill reuse every executor
    make_engine(FakeClock(), None, MetricsRegistry()).warmup()

    # fault-free reference pass: the survivor-identity oracle
    ref_engine = make_engine(FakeClock(), None, MetricsRegistry())
    ref_out = ref_engine.serve(prompts)

    clock = FakeClock()
    tracer = Tracer(clock=clock)
    registry = MetricsRegistry(clock=clock)
    engine = make_engine(clock, tracer, registry)
    streams = {}
    for i, p in enumerate(prompts):
        toks: list = []
        req = engine.submit(
            p, on_token=lambda idx, t, _toks=toks: _toks.append((idx, t))
        )
        streams[req.request_id] = {
            "req": req, "tokens": toks, "victim": i % abandon_every == 0,
            "token_at": None, "reclaim_ms": None,
        }
    abandoned = 0
    reclaims = []
    while engine.pending():
        engine.step()
        clock.advance(step_cost_s)
        for s in streams.values():
            if (
                s["victim"] and not s["req"].done and s["reclaim_ms"] is None
                and len(s["tokens"]) >= cancel_after_tokens
            ):
                if s["token_at"] is None:
                    s["token_at"] = clock()  # noticed between steps
                    continue  # the gateway notices on the NEXT pass
                if engine.cancel(s["req"].request_id):
                    s["reclaim_ms"] = (clock() - s["token_at"]) * 1e3
                    reclaims.append(s["reclaim_ms"])
                    abandoned += 1
    engine.drain()
    pool = engine._pool
    survivors = [s for s in streams.values() if s["reclaim_ms"] is None]
    # request ids are assigned in submit order, so sorted(streams) aligns
    # 1:1 with the reference pass's output order
    identical = all(
        s["req"].status == "ok"
        and np.array_equal(s["req"].result, ref)
        and [t for _, t in s["tokens"]] == [
            int(t) for t in ref[: len(s["tokens"])]
        ]
        for s, ref in (
            (streams[rid], ref_out[j])
            for j, rid in enumerate(sorted(streams))
            if streams[rid]["reclaim_ms"] is None
        )
    )
    counts = registry.counters()
    completed = int(counts.get("serving_requests_completed_total", 0))
    cancelled = int(counts.get("serving_requests_cancelled_total", 0))
    reclaims_sorted = sorted(reclaims)
    return {
        "slots": slots,
        "requests": n_requests,
        "abandoned": abandoned,
        "survivors": len(survivors),
        "cancel_after_tokens": cancel_after_tokens,
        "token_identical": bool(identical),
        "accounting_closed": completed + cancelled == n_requests,
        "completed": completed,
        "cancelled": cancelled,
        "reclaim": {
            "p50_ms": round(
                reclaims_sorted[len(reclaims_sorted) // 2], 3
            ) if reclaims_sorted else None,
            "p95_ms": round(
                reclaims_sorted[
                    min(len(reclaims_sorted) - 1,
                        int(0.95 * len(reclaims_sorted)))
                ], 3
            ) if reclaims_sorted else None,
            "max_ms": round(max(reclaims_sorted), 3) if reclaims_sorted else None,
            "bound_ms": round(step_cost_s * 1e3, 3),  # one scheduler pass
        },
        "pool": {
            "leaked": pool.leaked(),
            "in_use_after_drain": pool.in_use,
            "reserved_after_drain": pool.reserved,
            "frees_by_cause": dict(sorted(pool.frees_by_cause.items())),
            "high_water": pool.high_water,
        },
    }


def _bench_incident(model, params, cfg, *, n_requests: int = 4,
                    new_tokens: int = 4, sample_rate: float = 0.1):
    """Incident flight-recorder chaos drill (docs/observability.md "Flight
    recorder & incident bundles"), deterministic under
    :class:`~perceiver_io_tpu.reliability.FakeClock`: a healthy warm-up
    cohort, then a latency fault (requests age past the TTFT target) with
    a scripted replica crash mid-decode — the SLO breach and the replica
    failure each dump exactly one bounded atomic bundle (per-kind
    cooldown), and the ``obs incident`` analyzer is run over the post-run
    capture to pin the joins:

    - **trace_join** — every trace id the crash bundle names appears in
      the (10%-sampled) events.jsonl, because non-ok terminals are always
      tail-kept;
    - **decomposition_exact** — the analyzer's per-request TTFT
      components telescope to the registry's recorded ``serving_ttft_ms``
      with zero unattributed residue, and the worst decomposed request
      matches the registry max exactly;
    - **nonok_traces_kept** — 100% of non-ok terminal traces reached disk
      despite head sampling, with kept + sampled_out == total closing the
      span accounting.
    """
    import json as _json
    import os
    import tempfile

    import jax
    import numpy as np

    from perceiver_io_tpu.inference.generate import GenerationConfig
    from perceiver_io_tpu.observability import (
        FlightRecorder,
        JsonlSpanSink,
        MetricsRegistry,
        SamplingSpanSink,
        SLOMonitor,
        SLOPolicy,
        Tracer,
        read_events_jsonl,
    )
    from perceiver_io_tpu.observability import report as report_mod
    from perceiver_io_tpu.observability.tracing import TAIL_KEEP_STATUSES
    from perceiver_io_tpu.reliability import ChaosRegistry, FakeClock, RetryPolicy
    from perceiver_io_tpu.serving import BucketTable, FleetRouter, SlotServingEngine

    num_latents = min(4, cfg.max_latents)
    max_len = min(
        8, cfg.max_seq_len - new_tokens,
        cfg.max_seq_len - cfg.max_latents + num_latents,
    )
    table = BucketTable(prompt_lens=(max_len,), batch_sizes=(1,))
    gcfg = GenerationConfig(max_new_tokens=new_tokens, num_latents=num_latents)
    root = tempfile.mkdtemp(prefix="bench-incident-")
    events_path = os.path.join(root, "events.jsonl")
    clock = FakeClock()
    reg = MetricsRegistry(clock=clock)
    sampler = SamplingSpanSink(
        JsonlSpanSink(events_path), rate=sample_rate, registry=reg
    )
    tracer = Tracer(clock=clock, sink=sampler)
    recorder = FlightRecorder(
        os.path.join(root, "incidents"), tracer=tracer, registry=reg,
        clock=clock, cooldown_s=3600.0, max_bundles=8, keep_spans=256,
        snapshot_every_s=0.5,
    )
    monitor = SLOMonitor(
        SLOPolicy(ttft_p95_ms=50.0), clock=clock, registry=reg,
        tracer=tracer, flight_recorder=recorder,
        fast_window_s=5.0, slow_window_s=20.0, min_samples=3,
    )
    chaos = ChaosRegistry()

    def factory():
        return SlotServingEngine(
            model, params, gcfg, table, slots=2, clock=clock, tracer=tracer,
            rng=jax.random.PRNGKey(3),
        )

    fleet = FleetRouter(
        [factory] * 2, clock=clock, registry=reg, tracer=tracer,
        chaos=chaos, slo_monitor=monitor, flight_recorder=recorder,
        # no redispatch budget: crash victims fail terminally, so their
        # non-ok traces are tail-kept on disk — the join evidence
        redispatch_policy=RetryPolicy(max_retries=0, backoff_base_s=0.0),
    )
    recorder.add_source("health", fleet.health)
    rng = np.random.default_rng(11)

    def prompt():
        return rng.integers(1, cfg.vocab_size, size=max_len).astype(np.int32)

    def drain():
        while fleet.pending():
            fleet.step()
            recorder.maybe_record()
            clock.advance(0.01)
        fleet.step()

    for _ in range(n_requests):  # healthy warm-up: the "before" evidence
        fleet.submit(prompt())
    drain()
    # the incident: the cohort ages past the TTFT target while replica 0's
    # 2nd upcoming supervised step carries a scripted crash (mid-decode)
    steps_so_far = chaos._counters.get("fleet.replica_step.0", 0)
    chaos.crash_replica(0, steps_so_far + 2)
    victims = [fleet.submit(prompt()) for _ in range(n_requests)]
    clock.advance(1.0)
    drain()
    sampler.flush()
    bundle_kinds = sorted(
        os.path.basename(b).split("-", 2)[2] for b in recorder.bundles
    )
    drill_bundles = len(recorder.bundles)
    rows = read_events_jsonl(events_path)
    disk_traces = {r["trace_id"] for r in rows if r.get("trace_id")}
    failed_tids = {r.trace_id for r in victims if r.status == "failed"}
    crash_tids = set()
    for b in recorder.bundles:
        if b.endswith("replica_failure"):
            with open(os.path.join(b, "manifest.json")) as fh:
                crash_tids = set(_json.load(fh)["trigger"]["trace_ids"])
    bad_traces = {
        s.trace_id for s in tracer.finished
        if s.status in TAIL_KEEP_STATUSES and s.trace_id
    }
    final = recorder.trigger("manual", "bench post-drill capture")
    analysis = _json.loads(report_mod.run_incident(final, as_json=True))
    decomp = analysis["decomposition"]
    ttft_max = reg.snapshot()["histograms"]["serving_ttft_ms"]["max"]
    counts = reg.counters()
    return {
        "requests": 2 * n_requests,
        "sample_rate": sample_rate,
        "triggers": int(counts.get("incident_triggers_total", 0)),
        "bundles": drill_bundles,
        "bundle_kinds": bundle_kinds,
        "suppressed": int(counts.get("incident_suppressed_total", 0)),
        "dump_errors": int(counts.get("incident_dump_errors_total", 0)),
        "failed_requests": len(failed_tids),
        "trace_join": bool(crash_tids) and crash_tids == failed_tids
        and crash_tids <= disk_traces,
        "nonok_traces_kept": bool(bad_traces) and bad_traces <= disk_traces,
        "span_accounting_closed": (
            counts.get("tracing_spans_kept_total", 0)
            + counts.get("tracing_spans_sampled_out_total", 0)
            == counts.get("tracing_spans_total", 0)
        ),
        "spans_sampled_out": int(
            counts.get("tracing_spans_sampled_out_total", 0)
        ),
        "decomposition_exact": bool(decomp) and all(
            r["unattributed_ms"] == 0.0
            and round(sum(r["components"].values()), 3) == r["ttft_ms"]
            for r in decomp
        ) and decomp[0]["ttft_ms"] == round(float(ttft_max), 3),
        "worst_request": decomp[0] if decomp else None,
        "timeline_events": len(analysis["timeline"]),
        "bundle_dir": recorder.dir,
    }


def _bench_sharded_serving(*, requests: int = 8, new_tokens: int = 8,
                           slots: int = 4, budget_s: float = 240.0):
    """Sharded-serving A/B (docs/serving.md "Sharded serving"): the
    self-contained probe (``python -m perceiver_io_tpu.serving.sharding``)
    runs twice in child processes — a 1-device single mesh and a
    2 data x 4 model mesh over 8 virtual CPU devices, the device count
    injected per child via ``XLA_FLAGS`` (the same simulation strategy the
    test suite uses) — on identical seeded paged workloads. The record
    A/Bs tokens/s, compile counts, and per-model-shard resident KV bytes,
    and pins ``token_identical``: greedy output must not move when GSPMD
    partitions the executors. ``make shard-bench`` is the one-command
    form; tier-1 pins the same parity in-process (tests/test_sharding.py).
    """
    import json as _json

    repo_root = os.path.dirname(os.path.abspath(__file__))
    base_args = [
        "--slots", str(slots), "--requests", str(requests),
        "--new-tokens", str(new_tokens), "--kv-layout", "paged",
    ]

    def probe(device_count: int, data: int, model_axis: int, timeout: float):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={device_count}"
        ).strip()
        proc = subprocess.run(
            [sys.executable, "-m", "perceiver_io_tpu.serving.sharding",
             "--data", str(data), "--model", str(model_axis), *base_args],
            env=env, cwd=repo_root, stdout=subprocess.PIPE,
            stderr=sys.stderr, text=True, timeout=timeout,
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"shard probe ({data}x{model_axis}@{device_count}dev) "
                f"exited rc={proc.returncode}"
            )
        return _json.loads(proc.stdout.strip().splitlines()[-1])

    keep = ("devices", "mesh", "kv_layout", "compile_count",
            "tokens_generated", "tokens_per_s", "wall_s", "resident_bytes",
            "per_shard_resident_bytes")
    single = probe(1, 1, 1, timeout=budget_s / 2)
    sharded = probe(8, 2, 4, timeout=budget_s / 2)
    return {
        "workload": {"requests": requests, "new_tokens": new_tokens,
                     "slots": slots},
        "single": {k: single[k] for k in keep},
        "sharded": {k: sharded[k] for k in keep},
        # tiny CPU shapes are dispatch/collective-bound, so no winner is
        # asserted — the ratio and the per-shard bytes are the record
        "speedup": round(
            sharded["tokens_per_s"] / max(single["tokens_per_s"], 1e-9), 3
        ),
        "token_identical": single["tokens"] == sharded["tokens"],
    }


def _bench_slo_goodput(model, params, cfg, *, requests_per_rate: int = 10,
                       new_tokens: int = 6, slots: int = 4,
                       rate_factors=(0.5, 1.0, 2.0),
                       transport: str = "inproc"):
    """Goodput-under-SLO sweep (docs/observability.md): offered load vs
    p95 TTFT / p95 inter-token latency through the slot engine, driven by
    the open-loop Poisson load generator — the serving-paper measurement
    surface (PAPERS.md [1]) as a bench probe.

    A closed-loop calibration run at full slot concurrency estimates the
    engine's capacity (completed req/s) and the healthy-load latency
    percentiles; the SLO targets are set at 3x those (generous headroom a
    saturated point still blows through). The sweep then offers Poisson
    load at ``rate_factors`` x capacity. Per point: the registry's p95
    TTFT/ITL, completed rate, and **goodput under SLO** — requests/s that
    completed AND met the TTFT target per-request (joined from their
    ``serving.first_token`` events) at a point whose aggregate p95 ITL
    also met target. The knee is the point of max goodput: past it,
    added offered load only grows latency. The probe also cross-checks
    that ``obs report``'s SLO section reproduces the registry's
    nearest-rank percentiles exactly (the acceptance pin).

    All accounting uses the shared offered-load goodput definition
    (``observability/slo.py``) — the same helper the fleet-chaos and
    observability probes use, so the denominators cannot drift.

    ``transport`` is the one-flag in-process/over-sockets switch
    (docs/serving.md "Streaming"): ``"inproc"`` drives the engine
    directly; ``"http"`` runs every point through a real
    :class:`~perceiver_io_tpu.serving.StreamingGateway` socket via
    :class:`~perceiver_io_tpu.observability.GatewayHttpClient`, so the
    sweep's TTFT is socket-anchored and the report gains bytes-on-wire."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from perceiver_io_tpu.inference import cast_float_params
    from perceiver_io_tpu.inference.generate import GenerationConfig
    from perceiver_io_tpu.observability import (
        GatewayHttpClient,
        LoadGenerator,
        MetricsRegistry,
        Tracer,
        WorkloadSpec,
        goodput_ratio,
    )
    from perceiver_io_tpu.observability import report as obs_report
    from perceiver_io_tpu.serving import BucketTable, SlotServingEngine, StreamingGateway

    if transport not in ("inproc", "http"):
        raise ValueError(f"transport must be 'inproc' or 'http', got {transport!r}")
    params = cast_float_params(params, jnp.bfloat16)
    num_latents = min(4, cfg.max_latents)
    max_len = min(
        16, cfg.max_seq_len - new_tokens,
        cfg.max_seq_len - cfg.max_latents + num_latents,
    )
    table = BucketTable(prompt_lens=(max_len,), batch_sizes=(1,))
    gcfg = GenerationConfig(max_new_tokens=new_tokens, num_latents=num_latents)
    # shared-prefix workload (docs/serving.md "Prefix sharing"): a small
    # pool of fixed system prompts + fresh tails, so the sweep exercises
    # the prefix cache end to end — in-process AND over the HTTP
    # transport. Block size divides the prefix so hot admissions share.
    prefix_tokens = max(num_latents, max_len // 2)
    kv_block = max(2, prefix_tokens // 2)
    workload = WorkloadSpec(
        prompt_len=(2, max_len - prefix_tokens),
        max_new_tokens=(max(2, new_tokens // 2), new_tokens),
        vocab=(1, cfg.vocab_size),
        shared_prefix_pool=3,
        shared_prefix_len=(prefix_tokens, prefix_tokens),
    )

    def run_point(rate_rps, mode, seed):
        registry = MetricsRegistry()
        tracer = Tracer()
        engine = SlotServingEngine(
            model, params, gcfg, table, slots=slots,
            kv_layout="paged", kv_block_size=kv_block, prefix_cache="on",
            registry=registry, tracer=tracer, rng=jax.random.PRNGKey(2),
        )
        gateway = None
        driver = engine
        if transport == "http":
            # the full network path: the gateway drives the engine from
            # its own loop, the load generator offers over real sockets,
            # and TTFT anchors at socket accept (same registry, so the
            # percentile reads below are transport-independent)
            gateway = StreamingGateway(engine, tracer=tracer).run_in_thread()
            driver = GatewayHttpClient(gateway.host, gateway.port)
        gen = LoadGenerator(
            driver, workload=workload, mode=mode, arrival="poisson",
            rate_rps=rate_rps, users=slots, max_requests=requests_per_rate,
            config=gcfg, rng=seed,
        )
        try:
            report = gen.run()
        finally:
            if gateway is not None:
                gateway.close()
        return registry, tracer, gen, report

    # warm every executor once up front — the sweep measures serving, not
    # compiles (caches are process-global, so later engines reuse them)
    SlotServingEngine(
        model, params, gcfg, table, slots=slots,
        kv_layout="paged", kv_block_size=kv_block, prefix_cache="on",
    ).warmup()

    # calibration: closed loop at full slot concurrency = capacity estimate
    reg_c, _, _, rep_c = run_point(1.0, "closed", seed=0)
    base_rps = max(rep_c["completed_rps"], 0.1)
    cal_ttft = reg_c.percentile("serving_ttft_ms", 95.0) or 1.0
    cal_itl = reg_c.percentile("serving_inter_token_ms", 95.0) or 1.0
    slo_ttft_ms = round(3.0 * cal_ttft, 3)
    slo_itl_ms = round(3.0 * cal_itl, 3)

    sweep = []
    report_matches = True
    for factor in rate_factors:
        rate = base_rps * factor
        registry, tracer, gen, rep = run_point(rate, "open", seed=1)
        p95_ttft = registry.percentile("serving_ttft_ms", 95.0)
        p95_itl = registry.percentile("serving_inter_token_ms", 95.0)
        itl_ok = p95_itl is not None and p95_itl <= slo_itl_ms
        ttft_by_trace = {
            sp.trace_id: sp.attrs.get("ttft_ms")
            for sp in tracer.spans("serving.first_token")
        }
        good = sum(
            1 for h in gen.handles
            if h.status == "ok"
            and (ttft_by_trace.get(h.trace_id) or float("inf")) <= slo_ttft_ms
        ) if itl_ok else 0
        # the acceptance pin: obs report's SLO section over this point's
        # own artifacts reproduces the registry's nearest-rank percentiles
        snap = registry.snapshot()
        slo_sec = obs_report.analyze(
            [sp.to_row() for sp in tracer.spans()],
            {"histograms": snap["histograms"], "counters": snap["counters"]},
        )["slo"]
        report_matches = report_matches and (
            slo_sec["ttft"]["p95_ms"] == (
                None if p95_ttft is None else round(p95_ttft, 6)
            )
            and slo_sec["inter_token"]["p95_ms"] == (
                None if p95_itl is None else round(p95_itl, 6)
            )
        )
        sweep.append({
            "rate_factor": factor,
            "offered_rps_target": round(rate, 3),
            "offered_rps": rep["offered_rps"],
            "offered": rep["offered"],
            "completed": rep["completed"],
            "shed": rep["shed"],
            "completed_rps": rep["completed_rps"],
            "p95_ttft_ms": None if p95_ttft is None else round(p95_ttft, 3),
            "p95_inter_token_ms": (
                None if p95_itl is None else round(p95_itl, 3)
            ),
            "slo_met_aggregate": bool(
                itl_ok and p95_ttft is not None and p95_ttft <= slo_ttft_ms
            ),
            "goodput_rps": round(good / rep["span_s"], 4),
            "goodput_ratio": round(goodput_ratio(registry.counters()), 4),
            "bytes_on_wire": rep.get("bytes_on_wire"),
            # shared-prefix workload: sharing is live through this point
            # (in-process or over the HTTP transport alike)
            "prefix_hit_ratio": round(
                registry.counter("kv_prefix_hits_total")
                / max(1, registry.counter("kv_prefix_hits_total")
                      + registry.counter("kv_prefix_misses_total")), 4
            ),
        })
    knee_idx = max(
        range(len(sweep)), key=lambda i: (sweep[i]["goodput_rps"], -i)
    )
    return {
        "slots": slots,
        "requests_per_rate": requests_per_rate,
        "transport": transport,
        "slo": {"ttft_p95_ms": slo_ttft_ms, "inter_token_p95_ms": slo_itl_ms},
        "calibration": {
            "base_rps": round(base_rps, 3),
            "p95_ttft_ms": round(cal_ttft, 3),
            "p95_inter_token_ms": round(cal_itl, 3),
        },
        "sweep": sweep,
        "knee": {
            "index": knee_idx,
            "rate_factor": sweep[knee_idx]["rate_factor"],
            "offered_rps": sweep[knee_idx]["offered_rps"],
            "goodput_rps": sweep[knee_idx]["goodput_rps"],
        },
        "report_percentiles_match_registry": report_matches,
    }


# --------------------------------------------------------------- parent side


def _spawn(args, timeout, env_extra=None):
    env = dict(os.environ)
    # Persistent XLA compilation cache: re-runs (and the retry/fallback
    # stages) skip the 20-40s first-compile of unchanged programs.
    env.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(tempfile.gettempdir(), f"perceiver_xla_cache_{os.getuid()}"),
    )
    if env_extra:
        env.update(env_extra)
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), *args],
            env=env,
            stdout=subprocess.PIPE,
            stderr=sys.stderr,
            text=True,
            timeout=timeout,
        )
        return proc.returncode, proc.stdout or ""
    except subprocess.TimeoutExpired:
        return -1, "TIMEOUT"


def _read_result(out_path):
    """Accept whatever stages the child completed (file is written
    incrementally). Returns (result_or_None, withdrawal_error_or_None):
    a file without the primary metric is no result, but a recorded
    "error" (deliberate metric withdrawal) must reach the final JSON."""
    if os.path.exists(out_path) and os.path.getsize(out_path) > 0:
        try:
            with open(out_path) as f:
                data = json.load(f)
        except (json.JSONDecodeError, OSError):
            return None, None
        if "value" in data:
            return data, None
        return None, data.get("error")
    return None, None


def relay_port():
    """First relay port accepting a TCP connect, else None. No jax involved —
    this is the cheap 'is the tunnel alive at all' check: a dead relay makes
    the PJRT claim hang (not fail), so only a socket probe can tell
    relay-down from backend-broken."""
    import socket

    for p in RELAY_PORTS:
        try:
            with socket.create_connection((RELAY_HOST, p), timeout=1.0):
                return p
        except OSError:
            continue
    return None


def patient_probe(window_s: float, note: list, *, spawn=None, sleep=time.sleep,
                  now=time.monotonic):
    """Probe the accelerator repeatedly for up to ``window_s`` seconds.

    Returns (ok, status): status is "ok" | "relay_down" | "probe_failed" |
    "unprobed". When the accelerator is tunneled (PALLAS_AXON_POOL_IPS set),
    each JAX probe is gated on a relay socket check — while nothing listens,
    we wait-and-recheck (cheap) instead of burning a 90 s PJRT-claim hang.
    ``spawn``/``sleep``/``now`` are injectable for tests.
    """
    spawn = spawn or _spawn
    t_end = now() + window_s
    tunneled = bool(os.environ.get("PALLAS_AXON_POOL_IPS"))
    status = "unprobed"
    attempt = 0
    while now() < t_end:
        if tunneled:
            port = relay_port()
            if port is None:
                if status != "relay_down":
                    log(f"probe: no relay listener on {RELAY_HOST}:"
                        f"{RELAY_PORTS[0]}-{RELAY_PORTS[-1]} — relay down, waiting")
                status = "relay_down"
                if now() + 15.0 >= t_end:
                    break
                sleep(15.0)
                continue
            log(f"probe: relay listener up on port {port}")
        attempt += 1
        budget = min(90.0, t_end - now(), remaining() - 120.0)
        if budget < 20.0:
            break
        log(f"probe attempt {attempt} (timeout {budget:.0f}s)")
        rc, out = spawn(["--probe"], timeout=budget)
        if rc == 0 and "PROBE_OK" in out:
            return True, "ok"
        status = "probe_failed"
        detail = " (relay listener present)" if tunneled else ""
        note.append(f"accelerator probe attempt {attempt} failed rc={rc}{detail}")
        log(f"probe attempt {attempt} failed (rc={rc}){detail}")
        if rc != -1 and attempt >= 2:
            # Fast deterministic failure (not a timeout): the backend is
            # reproducibly broken — more retries only burn the deadline.
            break
        backoff = min(10.0 * attempt, 30.0)
        if now() + backoff >= t_end:
            break  # window can't fit another attempt; don't sleep past it
        sleep(backoff)
    if status == "relay_down":
        note.append(
            f"tpu relay down: no listener on {RELAY_HOST} ports "
            f"{RELAY_PORTS[0]}-{RELAY_PORTS[-1]}"
        )
    return False, status


def _run_accel_bench(note):
    """Spawn the full-shape accelerator benchmark child. Returns
    (result_or_None, withdrawal_or_None)."""
    budget = max(60.0, remaining() - 110.0)
    with tempfile.NamedTemporaryFile(suffix=".json", delete=False) as f:
        out_path = f.name
    log(f"accelerator benchmark (timeout {budget:.0f}s)")
    rc, _ = _spawn(["--run", "full", out_path, f"{budget - 10:.0f}"], timeout=budget)
    result, withdrawal = _read_result(out_path)
    if withdrawal:
        note.append(f"metric withdrawn: {withdrawal}")
        log(f"accelerator metric withdrawn: {withdrawal}")
    elif result is None:
        note.append(f"accelerator benchmark failed rc={rc}")
        log(f"accelerator benchmark failed (rc={rc})")
    elif rc != 0:
        note.append(f"child exited rc={rc}; partial result accepted")
    return result, withdrawal


def main() -> None:
    result = None
    withdrawal = None
    note = []

    # Stage 1: patient probe — socket-gated, retry with backoff over a
    # multi-minute window (round-3 postmortem: two quick rc=-1 probes
    # forfeited the round to CPU when the relay flapped).
    probe_window = min(
        float(os.environ.get("BENCH_PROBE_WINDOW_S", "240")), remaining() - 300.0
    )
    accel_ok, tpu_status = False, "unprobed"
    if probe_window >= 20.0:
        accel_ok, tpu_status = patient_probe(probe_window, note)
    else:
        note.append("probe skipped: out of time budget")

    # Stage 2: the real benchmark on the accelerator.
    if accel_ok:
        result, withdrawal = _run_accel_bench(note)
        if not withdrawal and (result is None or result.get("platform") != "tpu"):
            tpu_status = "bench_failed"  # probe passed but no TPU record

    # Stage 3: CPU fallback with reduced shapes so a measured number exists.
    # A deliberate withdrawal (kernel mismatch) must NOT be papered over by
    # a passing-looking CPU record — the zero record carries the error.
    if result is None and not withdrawal:
        budget = min(300.0, max(60.0, remaining() - 120.0))
        with tempfile.NamedTemporaryFile(suffix=".json", delete=False) as f:
            out_path = f.name
        log(f"cpu fallback benchmark (timeout {budget:.0f}s)")
        rc, _ = _spawn(["--run", "cpu", out_path, f"{budget - 10:.0f}"], timeout=budget)
        result, _withdrawal = _read_result(out_path)
        if result is not None:
            note.append("value measured on CPU at reduced shape")
        else:
            note.append(f"cpu fallback failed rc={rc}")
            log(f"cpu fallback failed (rc={rc})")

    # Stage 4: late re-probe — a mid-session outage that heals before the
    # deadline must not forfeit the round to the CPU record.
    if (
        not withdrawal
        and (result is None or result.get("platform") != "tpu")
        and remaining() > 300.0
    ):
        log("late re-probe: checking whether the accelerator came back")
        ok2, status2 = patient_probe(min(90.0, remaining() - 240.0), note)
        if ok2:
            late, withdrawal = _run_accel_bench(note)
            if withdrawal:
                result = None
                tpu_status = "ok"
            elif late is not None and late.get("platform") == "tpu":
                result = late
                tpu_status = "ok"
                note.append("accelerator recovered on late re-probe")
            else:
                tpu_status = "bench_failed"
        elif status2 != "unprobed":
            tpu_status = status2  # report the freshest failure cause

    if result is None:
        result = {
            "metric": METRIC,
            "value": 0.0,
            "unit": "tokens/s",
            "vs_baseline": 0.0,
        }
        # A zeroed record must not carry a note claiming a measured value
        # (e.g. a CPU measurement discarded by a later metric withdrawal).
        note = [n for n in note if not n.startswith("value measured")]
    result["tpu_status"] = tpu_status
    if note:
        result["note"] = "; ".join(dict.fromkeys(note))  # dedupe, keep order
    print(json.dumps(result), flush=True)


if __name__ == "__main__":
    if len(sys.argv) >= 2 and sys.argv[1] == "--probe":
        child_probe()
    elif len(sys.argv) >= 4 and sys.argv[1] == "--run":
        deadline = float(sys.argv[4]) if len(sys.argv) > 4 else 420.0
        if sys.argv[2] == "full":
            child_run(FULL_SHAPE, sys.argv[3], deadline_s=deadline)
        else:
            child_run(CPU_SHAPE, sys.argv[3], force_cpu=True, deadline_s=deadline)
    else:
        main()
