"""Exponential-backoff retry for transient data faults.

Streaming corpora (HF hub streams, GCS reads) fail transiently all the
time; a multi-hour TPU run must not die because one HTTP read did. Two
shapes of retry live here:

- :func:`call_with_retry` — retry a single call (the map-style loader's
  per-example fetch).
- :func:`resilient_source` — retry a *stream*: on a mid-iteration
  exception, re-open the source and fast-forward past the records already
  emitted, so downstream consumers see one uninterrupted, duplicate-free
  stream. Assumes the source replays deterministically (true for file and
  hub streams); the fast-forward re-reads, so seek cost is O(position) per
  retry.

``sleep`` is injectable everywhere so chaos tests assert the exact backoff
schedule without waiting for it, and jitter (off by default) only ever
comes from an *injected* rng — the default schedule stays bit-identical.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Callable, Iterable, Iterator, Optional, Tuple, Type


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff: attempt ``k`` (0-based) sleeps
    ``min(backoff_base_s * backoff_factor**k, backoff_max_s)`` before
    retrying; after ``max_retries`` failed attempts the error propagates.

    ``jitter`` spreads retries so N clients backing off from one shared
    fault don't re-dispatch in lockstep (the serving fleet's re-dispatch
    storm after a replica failure, docs/serving.md): with ``jitter=j`` and
    an rng supplied to :meth:`delay_s`, the delay is scaled by a uniform
    factor in ``[1, 1 + j]``. It is OFF unless both are provided — the
    default schedule is a pure function of ``attempt``, so existing
    backoff-schedule chaos assertions stay bit-identical — and
    deterministic under a seeded ``random.Random``."""

    max_retries: int = 3
    backoff_base_s: float = 0.5
    backoff_factor: float = 2.0
    backoff_max_s: float = 30.0
    retry_on: Tuple[Type[BaseException], ...] = (Exception,)
    jitter: float = 0.0

    def __post_init__(self):
        if self.jitter < 0.0:
            raise ValueError(f"jitter must be >= 0, got {self.jitter}")

    def delay_s(self, attempt: int, *, rng=None) -> float:
        delay = min(
            self.backoff_base_s * self.backoff_factor ** attempt,
            self.backoff_max_s,
        )
        if self.jitter > 0.0 and rng is not None:
            delay *= 1.0 + self.jitter * rng.random()
        return delay


def call_with_retry(
    fn: Callable,
    policy: RetryPolicy,
    *,
    sleep: Callable[[float], None] = time.sleep,
    on_retry: Optional[Callable[[int, BaseException], None]] = None,
    rng=None,
):
    """Call ``fn()`` with up to ``policy.max_retries`` backed-off retries.
    ``rng`` (e.g. a seeded ``random.Random``) enables the policy's jitter;
    None keeps the deterministic un-jittered schedule."""
    attempt = 0
    while True:
        try:
            return fn()
        except policy.retry_on as e:
            if attempt >= policy.max_retries:
                raise
            if on_retry is not None:
                on_retry(attempt, e)
            sleep(policy.delay_s(attempt, rng=rng))
            attempt += 1


def resilient_source(
    source_fn: Callable[[], Iterable],
    policy: RetryPolicy,
    *,
    sleep: Callable[[float], None] = time.sleep,
    on_retry: Optional[Callable[[int, BaseException], None]] = None,
    rng=None,
) -> Iterator:
    """Iterate ``source_fn()``, surviving mid-stream exceptions.

    On a failure, back off per ``policy``, re-invoke ``source_fn`` and skip
    the records already emitted (deterministic replay assumed), then resume
    yielding. The retry budget resets whenever a record is successfully
    emitted, so ``max_retries`` bounds *consecutive* failures, not total
    failures over an arbitrarily long stream.
    """
    emitted = 0
    attempt = 0
    while True:
        try:
            it = iter(source_fn())
            skipped = 0
            while skipped < emitted:  # fast-forward past what we already yielded
                next(it)
                skipped += 1
            for item in it:
                yield item
                emitted += 1
                attempt = 0
            return
        except StopIteration:
            # source shrank below the fast-forward point — nothing to resume
            return
        except policy.retry_on as e:
            if attempt >= policy.max_retries:
                raise
            if on_retry is not None:
                on_retry(attempt, e)
            sleep(policy.delay_s(attempt, rng=rng))
            attempt += 1
