"""Deterministic chaos harness: fault injection at explicit hook sites.

Every fault is registered up front against a named **site** and a key, and
fires when the site's hook is consulted with a matching key — there is no
randomness, no wall clock, and no monkeypatching, so a chaos test replays
bit-identically on CPU. The hook sites the codebase exposes:

==========================  =============================================
site                        keying
==========================  =============================================
``trainer.step``            execution count (1-based): the Nth optimizer
                            step this trainer ran — NOT the step index, so
                            a fault does not re-fire when rollback replays
                            the same step numbers
``data.record``             execution count (1-based): the Nth record
                            pulled from a chaos-wrapped source
                            (:meth:`ChaosRegistry.wrap_source`)
``serving.request``         explicit key: the ``request_id`` the engine
                            assigned (0-based submission order)
``serving.batch``           execution count (1-based): the Nth micro-batch
                            the engine dispatched
``fleet.dispatch``          execution count (1-based): the Nth dispatch
                            attempt the :class:`FleetRouter` performed,
                            across all replicas — attempt-count keying is
                            retry-safe (a re-dispatch of the same request
                            is a NEW attempt, so an ``error`` fault fails
                            one attempt, not the request forever)
``fleet.replica_step.<r>``  per-replica execution count (1-based): the Nth
                            supervised step of replica ``r``. ``error``
                            models a scripted replica crash (the router
                            restarts it and re-dispatches its in-flight
                            work); ``hang`` advances the shared injectable
                            clock by ``delay_s``, tripping the router's
                            ``step_timeout_s`` wall-time deadline — the
                            hung-replica drill
``gateway.disconnect.<s>``  per-stream execution count (1-based): the Nth
                            token about to go onto stream ``s``'s socket
                            (accept order assigns stream ids). ``error``
                            models the client vanishing mid-generation —
                            the gateway aborts the connection and
                            propagates a :meth:`cancel` to the engine,
                            freeing the slot and its pool pages — the
                            mass-abandonment drill
                            (:meth:`ChaosRegistry.disconnect_stream`)
``fleet.scale_up``          execution count (1-based): the Nth replica
                            spawn attempt (``FleetRouter.add_replica`` —
                            autoscaler- or operator-driven alike).
                            ``error`` models a SPAWN FAILURE: the new
                            replica's process never comes up — counted
                            ``fleet_scale_up_failed_total``, and the
                            autoscaler holds its up-cooldown instead of
                            spinning (:meth:`ChaosRegistry.fail_scale_up`)
``fleet.scale_down``        execution count (1-based): the Nth replica
                            retirement (``FleetRouter.remove_replica``),
                            consulted AFTER the victim's in-flight work
                            failed over. ``error`` models the victim
                            CRASHING MID-DRAIN: the clean evacuation never
                            runs (a dead process frees its memory by
                            dying), the failure is charged, and the
                            removal still completes — the failed-over
                            work is already safe on survivors
                            (:meth:`ChaosRegistry.crash_scale_down`)
``kv.exhaust``              execution count (1-based): the Nth decode step
                            the slot engine ran with preemption enabled.
                            ``error`` forces the first resident's page
                            mapping down the ``PoolExhausted`` path that
                            step — scripted memory pressure driving the
                            boundary-crossing preemption machinery without
                            filling the pool
                            (:meth:`ChaosRegistry.exhaust_kv`)
==========================  =============================================

Fault kinds: ``"error"`` (the site raises — or records — an exception),
``"nan"`` (the trainer replaces the step loss with NaN), ``"hang"`` (the
serving engine advances its injectable clock by ``delay_s``, simulating a
request stalling its slot past deadlines). Time-dependent faults only make
sense with a :class:`FakeClock`; a real ``time.monotonic`` clock ignores the
advance, by design.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Iterable, Iterator, List, Optional, Tuple


class InjectedFault(RuntimeError):
    """The default exception a chaos ``error`` fault raises at its site."""


class FakeClock:
    """A monotonic clock the chaos harness (or a test) advances explicitly.

    Drop-in for the engine's ``clock=time.monotonic`` parameter: calling the
    instance returns the current time; ``advance`` moves it forward. Hang
    faults use ``advance`` when present, so deadline expiry is deterministic
    instead of sleep-based.
    """

    def __init__(self, start: float = 0.0):
        self._t = float(start)

    def __call__(self) -> float:
        return self._t

    def advance(self, seconds: float) -> None:
        if seconds < 0:
            raise ValueError("FakeClock only moves forward")
        self._t += float(seconds)


@dataclasses.dataclass
class Fault:
    """One registered fault: fires when ``site`` is hit with a key in
    ``[at, at + count)``. ``count > 1`` models K *consecutive* bad events
    (the trainer's rollback trigger)."""

    site: str
    kind: str  # "error" | "nan" | "hang"
    at: int
    count: int = 1
    delay_s: float = 0.0
    message: str = ""
    exc_factory: Optional[Callable[[], BaseException]] = None
    fired: int = 0

    def matches(self, key: int) -> bool:
        return self.at <= key < self.at + self.count

    def make_error(self) -> BaseException:
        if self.exc_factory is not None:
            return self.exc_factory()
        return InjectedFault(
            self.message
            or f"injected {self.kind} fault at {self.site}[{self.at}]"
        )


class ChaosRegistry:
    """Registry of pre-declared faults consulted at explicit hook sites.

    Hooks call :meth:`hit`; registered faults matching the site/key fire (and
    are recorded in :attr:`log`). Components take an optional ``chaos``
    parameter and skip the hook entirely when it is None, so production paths
    pay nothing.
    """

    def __init__(self):
        self._faults: List[Fault] = []
        self._counters: Dict[str, int] = {}
        #: every fired fault as ``(site, key, kind)``, in firing order
        self.log: List[Tuple[str, int, str]] = []

    # -- registration ------------------------------------------------------
    def add(self, site: str, kind: str, at: int, *, count: int = 1,
            delay_s: float = 0.0, message: str = "",
            exc_factory: Optional[Callable[[], BaseException]] = None) -> Fault:
        if kind not in ("error", "nan", "hang"):
            raise ValueError(f"unknown fault kind {kind!r}")
        if count < 1:
            raise ValueError("count must be >= 1")
        fault = Fault(site, kind, int(at), count=int(count), delay_s=delay_s,
                      message=message, exc_factory=exc_factory)
        self._faults.append(fault)
        return fault

    def nan_loss_at_step(self, step: int, *, count: int = 1) -> Fault:
        """NaN train loss on the trainer's ``step``-th executed step (and the
        ``count - 1`` following ones) — the divergence-policy drill."""
        return self.add("trainer.step", "nan", step, count=count)

    def loader_error_on_record(self, record: int, *, count: int = 1,
                               exc_factory=None) -> Fault:
        """Transient exception on the ``record``-th record pulled from a
        :meth:`wrap_source`-wrapped stream."""
        return self.add("data.record", "error", record, count=count,
                        exc_factory=exc_factory)

    def fail_request(self, request_id: int, *, message: str = "") -> Fault:
        """Fail one serving request at pack time (its micro-batch peers are
        unaffected — the error-isolation drill)."""
        return self.add("serving.request", "error", request_id, message=message)

    def hang_request(self, request_id: int, *, delay_s: float) -> Fault:
        """Stall one serving request's slot for ``delay_s`` engine-clock
        seconds (needs a :class:`FakeClock`); with a deadline shorter than
        the stall, the request surfaces as ``timed_out``."""
        return self.add("serving.request", "hang", request_id, delay_s=delay_s)

    def fail_batch(self, batch_index: int, *, exc_factory=None) -> Fault:
        """Fail the engine's ``batch_index``-th micro-batch dispatch (1-based)
        — the executor-failure drill; every packed request in it is marked
        ``failed`` and the rest of the queue still drains."""
        return self.add("serving.batch", "error", batch_index,
                        exc_factory=exc_factory)

    def crash_replica(self, replica_id: int, at_step: int, *, count: int = 1,
                      exc_factory=None) -> Fault:
        """Crash fleet replica ``replica_id`` on its ``at_step``-th supervised
        step (1-based, and the ``count - 1`` following ones) — the scripted
        mid-decode replica-kill drill (docs/serving.md): the router restarts
        the replica and fails over its in-flight requests."""
        return self.add(f"fleet.replica_step.{replica_id}", "error", at_step,
                        count=count, exc_factory=exc_factory)

    def hang_replica(self, replica_id: int, at_step: int, *,
                     delay_s: float) -> Fault:
        """Stall fleet replica ``replica_id``'s ``at_step``-th step for
        ``delay_s`` clock seconds (needs the shared :class:`FakeClock`); a
        stall past the router's ``step_timeout_s`` is detected as a hung
        replica — its slow copy may still finish later, which is exactly the
        duplicate-completion case the router's request-id dedupe absorbs."""
        return self.add(f"fleet.replica_step.{replica_id}", "hang", at_step,
                        delay_s=delay_s)

    def disconnect_stream(self, stream_id: int, *, after_tokens: int) -> Fault:
        """Abandon gateway stream ``stream_id`` mid-generation: the gateway
        consults ``gateway.disconnect.<stream_id>`` once per token about to
        go on the wire (1-based), so the fault fires just before the
        ``after_tokens``-th token is written — the client "vanishes", the
        connection is torn down, and the gateway cancels the engine request
        (slot retired + pool pages returned; docs/serving.md "Streaming")."""
        if after_tokens < 1:
            raise ValueError(f"after_tokens must be >= 1, got {after_tokens}")
        return self.add(f"gateway.disconnect.{stream_id}", "error", after_tokens)

    def fail_scale_up(self, attempt: int, *, count: int = 1,
                      exc_factory=None) -> Fault:
        """Fail the fleet's ``attempt``-th replica spawn (1-based) — the
        scale-up chaos drill (docs/serving.md "Elasticity"): the factory's
        process never comes up, ``fleet_scale_up_failed_total`` counts it,
        and the autoscaler holds its cooldown before retrying."""
        return self.add("fleet.scale_up", "error", attempt, count=count,
                        exc_factory=exc_factory)

    def crash_scale_down(self, attempt: int, *, count: int = 1,
                         exc_factory=None) -> Fault:
        """Crash the victim of the fleet's ``attempt``-th scale-down
        (1-based) MID-DRAIN — after its in-flight work failed over, before
        the clean evacuation: the removal completes anyway and no accepted
        request is lost (the drill's pin)."""
        return self.add("fleet.scale_down", "error", attempt, count=count,
                        exc_factory=exc_factory)

    def exhaust_kv(self, step: int, *, count: int = 1) -> Fault:
        """Script KV-pool pressure: the slot engine (with preemption
        enabled) consults ``kv.exhaust`` once per decode step (1-based)
        and an ``error`` fault forces the first resident's page mapping
        down the :class:`PoolExhausted` path that step — a deterministic
        preemption storm with no need to actually fill the pool
        (docs/serving.md "Preemption & priorities"; the zero-leak drill
        in ``tests/test_kv_preemption.py``)."""
        return self.add("kv.exhaust", "error", step, count=count)

    def fail_dispatch(self, attempt: int, *, count: int = 1) -> Fault:
        """Fail the router's ``attempt``-th dispatch attempt (1-based,
        fleet-wide) — the request is re-dispatched under the router's backoff
        policy and the fault charges the chosen replica's circuit breaker."""
        return self.add("fleet.dispatch", "error", attempt, count=count)

    # -- hook side ---------------------------------------------------------
    def hit(self, site: str, key: Optional[int] = None) -> Optional[Fault]:
        """Consult the registry at ``site``. With ``key=None`` the site's
        execution counter advances and serves as the key (1-based). Returns
        the firing fault, or None."""
        if key is None:
            key = self._counters.get(site, 0) + 1
            self._counters[site] = key
        for fault in self._faults:
            if fault.site == site and fault.matches(int(key)):
                fault.fired += 1
                self.log.append((site, int(key), fault.kind))
                return fault
        return None

    def fired_count(self, site: Optional[str] = None) -> int:
        """How many faults fired (optionally at one site) — test bookkeeping."""
        return sum(1 for s, _, _ in self.log if site is None or s == site)

    # -- source wrapper ----------------------------------------------------
    def wrap_source(self, source_fn: Callable[[], Iterable],
                    site: str = "data.record") -> Callable[[], Iterator]:
        """Wrap a zero-arg source factory so every pulled record consults
        ``site`` first; an ``error`` fault raises there. Because the site
        counter keeps advancing across re-invocations, a fault at record N
        fires exactly once even when a retry wrapper re-opens the source —
        the transient-fault model."""

        def wrapped() -> Iterator:
            for item in source_fn():
                fault = self.hit(site)
                if fault is not None and fault.kind == "error":
                    raise fault.make_error()
                yield item

        return wrapped
