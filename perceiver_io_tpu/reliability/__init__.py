"""Fault-tolerance layer (docs/reliability.md).

Production TPU stacks treat preemption, data faults, and loss spikes as
routine events, not crashes (PAPERS.md: the pjit/TPUv4 scalable-training
paper's divergence-recovery loop; the Gemma-on-TPU serving comparison's
backpressure/deadline practices). This package holds the pieces both load
paths share:

- :class:`QueueFull` — the serving engine's explicit backpressure signal
  (``ServingEngine.submit`` raises it past ``max_queue`` instead of letting
  the queue grow unboundedly).
- :mod:`~perceiver_io_tpu.reliability.retry` — exponential-backoff retry for
  transient data-source faults (``RetryPolicy``, ``call_with_retry``,
  ``resilient_source``), wired into ``data.loader.DataLoader`` and
  ``data.text.streaming.StreamingTextPipeline``.
- :mod:`~perceiver_io_tpu.reliability.chaos` — a deterministic, seed-free
  fault-injection registry (``ChaosRegistry``) plus a controllable
  ``FakeClock``. Faults fire at explicit hook sites in the trainer, loader,
  serving engines, and the fleet router (replica crash/hang + dispatch
  faults) — never via monkeypatched timing — so every chaos test
  reproduces bit-identically on CPU.

The trainer's divergence policies (``TrainerConfig.non_finite_policy`` =
``halt`` / ``skip`` / ``rollback``) build on these hooks; see
``training/trainer.py`` and docs/reliability.md.
"""
from __future__ import annotations


class QueueFull(RuntimeError):
    """Backpressure: the serving queue is at ``max_queue``; the request was
    shed, not enqueued. Callers either retry after draining (the CLI steps
    the engine and resubmits) or propagate load-shedding upstream."""


from perceiver_io_tpu.reliability.chaos import (  # noqa: E402
    ChaosRegistry,
    FakeClock,
    Fault,
    InjectedFault,
)
from perceiver_io_tpu.reliability.retry import (  # noqa: E402
    RetryPolicy,
    call_with_retry,
    resilient_source,
)

__all__ = [
    "QueueFull",
    "ChaosRegistry",
    "FakeClock",
    "Fault",
    "InjectedFault",
    "RetryPolicy",
    "call_with_retry",
    "resilient_source",
]
