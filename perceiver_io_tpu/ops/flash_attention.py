"""Pallas TPU flash attention kernels (filled in by the perf pass).

Until the kernels land, :func:`supported` returns False so
:func:`perceiver_io_tpu.ops.attention.dot_product_attention` always takes the
XLA einsum path.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp


def supported(q, k, v, *, causal: bool) -> bool:
    return False


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    pad_mask: Optional[jnp.ndarray] = None,
    causal: bool = False,
) -> jnp.ndarray:
    raise NotImplementedError("Pallas flash attention not yet implemented")
