"""Pallas TPU flash attention for the Perceiver attention patterns.

The reference bounds attention memory by serializing over head groups
(``max_heads_parallel``, reference ``perceiver/model/core/modules.py:129-151``)
and still materializes the full ``(b, h, i, j)`` attention matrix per group.
Here the matrix never leaves VMEM: queries/keys/values are streamed block by
block from HBM, softmax runs online (running max / running sum), and the
backward pass recomputes probabilities blockwise from the saved logsumexp —
the standard flash-attention schedule, laid out for the TPU MXU.

Perceiver specifics the stock kernels don't cover:

- **right-aligned causal masking of unequal q/kv** — Perceiver AR latents
  (length ``i``) attend causally over ``[prefix ‖ latents]`` (length ``j``),
  so position ``r`` of the query may see kv positions ``c ≤ r + (j - i)``
  (reference mask ``triu(j-i+1)``, ``modules.py:120-125``). The offset is
  baked into the block mask and into block-level skipping: kv blocks wholly
  above the shifted diagonal are never computed.
- **key padding masks** (``True`` = pad, reference ``modules.py:97``) for the
  left-padded batches the text models use. Kernels are statically
  specialized on pad presence, so the common unpadded call streams no mask.

Layout notes (mirroring what Mosaic compiles well): grid is
``(b, h, i_blocks, j_blocks)`` with the kv dimension innermost and
"arbitrary" semantics so the running-softmax scratch carries across kv
blocks; logsumexp residuals are kept lane-replicated ``(b, h, i, 128)`` in
float32 — cheap because every Perceiver query length is the latent count,
not the sequence length. Matmuls feed the MXU in the input dtype (bf16 in
training) with float32 accumulation; softmax math is float32 on the VPU.

Queries arrive pre-scaled and pre-rotated (see
:func:`perceiver_io_tpu.ops.attention.dot_product_attention`).
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANES = 128
_BLOCK_CANDIDATES = (512, 256, 128)
# Large-but-finite mask value (f32 min would overflow when subtracted).
_MASK = -0.7 * float(jnp.finfo(jnp.float32).max)


def _candidates() -> Tuple[int, ...]:
    """Block-size preference order, largest first. Overridable via
    ``PERCEIVER_FLASH_BLOCKS`` (comma-separated, e.g. ``1024,512,256``) so the
    schedule can be tuned on hardware without a code edit; invalid values are
    ignored in favor of the default."""
    import os

    raw = os.environ.get("PERCEIVER_FLASH_BLOCKS")
    if raw:
        try:
            blocks = tuple(int(x) for x in raw.split(","))
            if blocks and all(b > 0 and b % LANES == 0 for b in blocks):
                return blocks
        except ValueError:
            pass
    return _BLOCK_CANDIDATES


def _pick_block(n: int) -> Optional[int]:
    for b in _candidates():
        if n % b == 0:
            return b
    return None


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def supported(q, k, v, *, causal: bool) -> bool:
    if q.ndim != 4 or k.ndim != 4 or v.ndim != 4:
        return False
    if q.dtype not in (jnp.float32, jnp.bfloat16):
        return False
    if q.dtype != k.dtype or q.dtype != v.dtype:
        return False
    i, j = q.shape[2], k.shape[2]
    if causal and j < i:
        return False
    if _pick_block(i) is None or _pick_block(j) is None:
        return False
    # Head dims must be lane-tileable; Mosaic pads, but tiny dims would waste
    # most of the MXU — leave those to the XLA path.
    if q.shape[3] < 32 or v.shape[3] < 32:
        return False
    return True


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    pad_mask: Optional[jnp.ndarray] = None,
    causal: bool = False,
) -> jnp.ndarray:
    """Flash attention with Perceiver masking semantics.

    :param q: ``(b, h, i, d)`` pre-scaled queries.
    :param k: ``(b, h, j, d)`` keys.
    :param v: ``(b, h, j, dv)`` values.
    :param pad_mask: optional boolean ``(b, j)``, True marks padding.
    :param causal: right-aligned causal masking (offset ``j - i``).

    Dead-row semantics: a query row whose entire visible window is padded
    gets **zero output and zero gradients** here. The einsum path (like the
    torch reference) instead softmaxes a uniform distribution over the masked
    keys, leaking activations/gradients into padding. Such rows are
    themselves padding in every Perceiver model (their loss contribution is
    masked), so the results never differ for real positions — the flash
    behavior is the deliberate one.
    """
    pad = None if pad_mask is None else pad_mask.astype(jnp.float32)
    return _flash(q, k, v, pad, causal)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _flash(q, k, v, pad, causal):
    o, _ = _forward(q, k, v, pad, causal)
    return o


def _flash_fwd(q, k, v, pad, causal):
    o, lse = _forward(q, k, v, pad, causal)
    return o, (q, k, v, pad, o, lse)


def _flash_bwd(causal, res, do):
    q, k, v, pad, o, lse = res
    delta = jnp.sum(o.astype(jnp.float32) * do.astype(jnp.float32), axis=-1)
    delta = jnp.broadcast_to(delta[..., None], (*delta.shape, LANES))
    dq = _backward_dq(q, k, v, pad, lse, delta, do, causal)
    dk, dv = _backward_dkv(q, k, v, pad, lse, delta, do, causal)
    dpad = None if pad is None else jnp.zeros_like(pad)
    return dq, dk, dv, dpad


_flash.defvjp(_flash_fwd, _flash_bwd)


def _block_mask(i_idx, j_idx, bi: int, bj: int, offset: int, causal: bool, pad_blk):
    """Boolean (bi, bj) "allowed" mask for the current block pair, or None
    when the block is unconstrained."""
    allowed = None
    if pad_blk is not None:
        allowed = jnp.broadcast_to(pad_blk < 0.5, (bi, bj))  # (1, bj) over rows
    if causal:
        rows = jax.lax.broadcasted_iota(jnp.int32, (bi, bj), 0) + i_idx * bi
        cols = jax.lax.broadcasted_iota(jnp.int32, (bi, bj), 1) + j_idx * bj
        cm = cols <= rows + offset
        allowed = cm if allowed is None else jnp.logical_and(allowed, cm)
    return allowed


def _run_block(i_idx, j_idx, bi: int, bj: int, offset: int, causal: bool):
    """Whether this (i, j) block intersects the allowed region."""
    if not causal:
        return None  # statically always
    return j_idx * bj <= i_idx * bi + (bi - 1) + offset


def _maybe_when(run, body):
    if run is None:
        body()
    else:
        pl.when(run)(body)


def _qk_spec(bi, d, by_dim2=True):
    if by_dim2:
        return pl.BlockSpec((1, 1, bi, d), lambda b_, h_, x_, y_: (b_, h_, x_, 0))
    return pl.BlockSpec((1, 1, bi, d), lambda b_, h_, x_, y_: (b_, h_, y_, 0))


def _pad_spec(bj, by_dim2=False):
    if by_dim2:
        return pl.BlockSpec((1, bj), lambda b_, h_, x_, y_: (b_, x_))
    return pl.BlockSpec((1, bj), lambda b_, h_, x_, y_: (b_, y_))


# jax renamed TPUCompilerParams -> CompilerParams across releases; accept
# whichever the pinned version exposes.
_COMPILER_PARAMS = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
_DIM_SEMANTICS = _COMPILER_PARAMS(
    dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
)


def _forward(q, k, v, pad, causal) -> Tuple[jnp.ndarray, jnp.ndarray]:
    b, h, i, d = q.shape
    j, dv = k.shape[2], v.shape[3]
    bi, bj = _pick_block(i), _pick_block(j)
    offset = j - i
    nj = j // bj
    has_pad = pad is not None

    def kernel(q_ref, k_ref, v_ref, *rest):
        if has_pad:
            pad_ref, o_ref, lse_ref, m_sc, l_sc, acc_sc = rest
        else:
            o_ref, lse_ref, m_sc, l_sc, acc_sc = rest
            pad_ref = None
        i_idx, j_idx = pl.program_id(2), pl.program_id(3)

        @pl.when(j_idx == 0)
        def _():
            m_sc[:] = jnp.full_like(m_sc, -jnp.inf)
            l_sc[:] = jnp.zeros_like(l_sc)
            acc_sc[:] = jnp.zeros_like(acc_sc)

        def body():
            s = jax.lax.dot_general(
                q_ref[0, 0], k_ref[0, 0], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            allowed = _block_mask(
                i_idx, j_idx, bi, bj, offset, causal,
                pad_ref[:] if has_pad else None,
            )
            if allowed is not None:
                s = jnp.where(allowed, s, _MASK)

            m_prev = m_sc[:, :1]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
            p = jnp.exp(s - m_new)
            if allowed is not None:
                p = jnp.where(allowed, p, 0.0)
            alpha = jnp.exp(m_prev - m_new)
            l_new = alpha * l_sc[:, :1] + jnp.sum(p, axis=1, keepdims=True)
            acc_sc[:] = acc_sc[:] * alpha + jax.lax.dot_general(
                p.astype(v_ref.dtype), v_ref[0, 0], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            m_sc[:] = jnp.broadcast_to(m_new, m_sc.shape)
            l_sc[:] = jnp.broadcast_to(l_new, l_sc.shape)

        _maybe_when(_run_block(i_idx, j_idx, bi, bj, offset, causal), body)

        @pl.when(j_idx == nj - 1)
        def _():
            l = l_sc[:, :1]
            safe_l = jnp.where(l > 0.0, l, 1.0)
            o_ref[0, 0] = (acc_sc[:] / safe_l).astype(o_ref.dtype)
            lse_ref[0, 0] = jnp.broadcast_to(
                m_sc[:, :1] + jnp.log(safe_l), lse_ref.shape[2:]
            )

    in_specs = [
        _qk_spec(bi, d, by_dim2=True),
        _qk_spec(bj, d, by_dim2=False),
        _qk_spec(bj, dv, by_dim2=False),
    ]
    args = [q, k, v]
    if has_pad:
        in_specs.append(_pad_spec(bj))
        args.append(pad)

    out = pl.pallas_call(
        kernel,
        grid=(b, h, i // bi, nj),
        in_specs=in_specs,
        out_specs=[
            _qk_spec(bi, dv, by_dim2=True),
            _qk_spec(bi, LANES, by_dim2=True),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, i, dv), q.dtype),
            jax.ShapeDtypeStruct((b, h, i, LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bi, LANES), jnp.float32),
            pltpu.VMEM((bi, LANES), jnp.float32),
            pltpu.VMEM((bi, dv), jnp.float32),
        ],
        compiler_params=_DIM_SEMANTICS,
        interpret=_interpret(),
    )(*args)
    return out[0], out[1]


def _backward_dq(q, k, v, pad, lse, delta, do, causal):
    b, h, i, d = q.shape
    j, dv = k.shape[2], v.shape[3]
    bi, bj = _pick_block(i), _pick_block(j)
    offset = j - i
    nj = j // bj
    has_pad = pad is not None

    def kernel(q_ref, k_ref, v_ref, *rest):
        if has_pad:
            pad_ref, lse_ref, delta_ref, do_ref, dq_ref, dq_sc = rest
        else:
            lse_ref, delta_ref, do_ref, dq_ref, dq_sc = rest
            pad_ref = None
        i_idx, j_idx = pl.program_id(2), pl.program_id(3)

        @pl.when(j_idx == 0)
        def _():
            dq_sc[:] = jnp.zeros_like(dq_sc)

        def body():
            kb = k_ref[0, 0]
            s = jax.lax.dot_general(
                q_ref[0, 0], kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            allowed = _block_mask(
                i_idx, j_idx, bi, bj, offset, causal,
                pad_ref[:] if has_pad else None,
            )
            p = jnp.exp(s - lse_ref[0, 0][:, :1])
            if allowed is not None:
                p = jnp.where(allowed, p, 0.0)
            dp = jax.lax.dot_general(
                do_ref[0, 0], v_ref[0, 0], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            ds = p * (dp - delta_ref[0, 0][:, :1])
            dq_sc[:] = dq_sc[:] + jax.lax.dot_general(
                ds.astype(kb.dtype), kb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

        _maybe_when(_run_block(i_idx, j_idx, bi, bj, offset, causal), body)

        @pl.when(j_idx == nj - 1)
        def _():
            dq_ref[0, 0] = dq_sc[:].astype(dq_ref.dtype)

    in_specs = [
        _qk_spec(bi, d, by_dim2=True),
        _qk_spec(bj, d, by_dim2=False),
        _qk_spec(bj, dv, by_dim2=False),
    ]
    args = [q, k, v]
    if has_pad:
        in_specs.append(_pad_spec(bj))
        args.append(pad)
    in_specs += [
        _qk_spec(bi, LANES, by_dim2=True),
        _qk_spec(bi, LANES, by_dim2=True),
        _qk_spec(bi, dv, by_dim2=True),
    ]
    args += [lse, delta, do]

    return pl.pallas_call(
        kernel,
        grid=(b, h, i // bi, nj),
        in_specs=in_specs,
        out_specs=_qk_spec(bi, d, by_dim2=True),
        out_shape=jax.ShapeDtypeStruct((b, h, i, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bi, d), jnp.float32)],
        compiler_params=_DIM_SEMANTICS,
        interpret=_interpret(),
    )(*args)


def _backward_dkv(q, k, v, pad, lse, delta, do, causal):
    b, h, i, d = q.shape
    j, dv = k.shape[2], v.shape[3]
    bi, bj = _pick_block(i), _pick_block(j)
    offset = j - i
    ni = i // bi
    has_pad = pad is not None

    # Grid dim 2 walks kv blocks, dim 3 walks q blocks (innermost, so the
    # dk/dv accumulators carry across q blocks).
    def kernel(q_ref, k_ref, v_ref, *rest):
        if has_pad:
            pad_ref, lse_ref, delta_ref, do_ref, dk_ref, dv_ref, dk_sc, dv_sc = rest
        else:
            lse_ref, delta_ref, do_ref, dk_ref, dv_ref, dk_sc, dv_sc = rest
            pad_ref = None
        j_idx, i_idx = pl.program_id(2), pl.program_id(3)

        @pl.when(i_idx == 0)
        def _():
            dk_sc[:] = jnp.zeros_like(dk_sc)
            dv_sc[:] = jnp.zeros_like(dv_sc)

        def body():
            qb, dob = q_ref[0, 0], do_ref[0, 0]
            s = jax.lax.dot_general(
                qb, k_ref[0, 0], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            allowed = _block_mask(
                i_idx, j_idx, bi, bj, offset, causal,
                pad_ref[:] if has_pad else None,
            )
            p = jnp.exp(s - lse_ref[0, 0][:, :1])
            if allowed is not None:
                p = jnp.where(allowed, p, 0.0)
            dv_sc[:] = dv_sc[:] + jax.lax.dot_general(
                p.astype(qb.dtype), dob, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            dp = jax.lax.dot_general(
                dob, v_ref[0, 0], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            ds = (p * (dp - delta_ref[0, 0][:, :1])).astype(qb.dtype)
            dk_sc[:] = dk_sc[:] + jax.lax.dot_general(
                ds, qb, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

        _maybe_when(_run_block(i_idx, j_idx, bi, bj, offset, causal), body)

        @pl.when(i_idx == ni - 1)
        def _():
            dk_ref[0, 0] = dk_sc[:].astype(dk_ref.dtype)
            dv_ref[0, 0] = dv_sc[:].astype(dv_ref.dtype)

    in_specs = [
        _qk_spec(bi, d, by_dim2=False),   # q blocks walk grid dim 3
        _qk_spec(bj, d, by_dim2=True),    # k blocks walk grid dim 2
        _qk_spec(bj, dv, by_dim2=True),
    ]
    args = [q, k, v]
    if has_pad:
        in_specs.append(_pad_spec(bj, by_dim2=True))
        args.append(pad)
    in_specs += [
        _qk_spec(bi, LANES, by_dim2=False),
        _qk_spec(bi, LANES, by_dim2=False),
        _qk_spec(bi, dv, by_dim2=False),
    ]
    args += [lse, delta, do]

    return pl.pallas_call(
        kernel,
        grid=(b, h, j // bj, ni),
        in_specs=in_specs,
        out_specs=[
            _qk_spec(bj, d, by_dim2=True),
            _qk_spec(bj, dv, by_dim2=True),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, j, d), k.dtype),
            jax.ShapeDtypeStruct((b, h, j, dv), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bj, d), jnp.float32),
            pltpu.VMEM((bj, dv), jnp.float32),
        ],
        compiler_params=_DIM_SEMANTICS,
        interpret=_interpret(),
    )(*args)
