"""Paged KV gather/scatter helpers + the ragged paged decode attention op.

The slot engine's paged layout (``serving/kv_pool.py``, docs/serving.md)
keeps every resident's cross-attention k/v in ONE flat device pool of
shape ``(pool_tokens, heads, head_dim)``, addressed through per-slot
block tables. This module is the device-side address arithmetic plus the
decode-attention op over that layout, in two implementations:

- **Gather reference (every backend).** Flatten the block table into
  per-position pool indices, ``jnp.take`` the pages back into a dense
  ``(b, h, n, d)`` view, and run the caller's attend. Because masking in
  :func:`~perceiver_io_tpu.ops.attention.dot_product_attention` is a
  ``where`` select on the fp32 logits, positions whose pages are
  unmapped (they gather null-block trash) contribute exactly what the
  dense layout's masked garbage contributes — nothing — so greedy output
  is **bitwise identical** to the dense layout (pinned by
  ``tests/test_paged_kv.py``). The gathered view is a transient XLA
  temp, not resident HBM; the persistent footprint is the pool.
  Sharing-transparent by construction: the gather addresses pages purely
  through the table, so two slots whose tables alias the SAME physical
  blocks (cross-request prefix sharing, docs/serving.md "Prefix
  sharing") read bitwise-identical values — no read-path change was
  needed for copy-on-write sharing, and the aliased-table parity is
  pinned by ``tests/test_prefix_cache.py``.
- **Pallas TPU kernel (opt-in).** ``PERCEIVER_PAGED_KERNEL=1`` on a TPU
  backend dispatches ``jax.experimental.pallas.ops.tpu.paged_attention``
  (the SNIPPETS.md [1] usage), which reads only the live pages — the
  "Ragged Paged Attention" kernel design. The kernel's blockwise softmax
  is exact but not bit-identical to the XLA einsum, so it is opt-in and
  the parity tests pin the gather path; the flag is folded into
  ``modules.trace_env_fingerprint`` so a mid-process toggle rebuilds the
  decode executors instead of silently reusing the other trace.
"""
from __future__ import annotations

import contextlib
import contextvars
import os
from typing import Optional

import jax
import jax.numpy as jnp

#: trace-time sharding hint for the gather path's transient dense view
#: (docs/serving.md "Sharded serving"): the sharded slot engine sets it
#: around its decode executors' trace so the gathered (b, h, n, d) k/v
#: stay slot-sharded along ``data`` and head-sharded along ``model`` —
#: the attend computes shard-local and only the o-projection all-reduces
#: (the ``sharded_paged_attention`` shape, derived by GSPMD instead of a
#: hand-written shard_map). None (the default) changes nothing.
_GATHER_SHARDING: contextvars.ContextVar = contextvars.ContextVar(
    "paged_gather_sharding", default=None
)


@contextlib.contextmanager
def gather_constraint(sharding):
    """Install a ``NamedSharding`` constraint for the paged gather's dense
    view during an executor trace (no-op for ``None``). Trace-time only:
    the constraint is baked into the jitted program, so the context needs
    to be live when the executor's Python body runs, not per dispatch."""
    if sharding is None:
        yield
        return
    token = _GATHER_SHARDING.set(sharding)
    try:
        yield
    finally:
        _GATHER_SHARDING.reset(token)

#: trace-time env flag enabling the Pallas TPU kernel path (see module
#: docstring; folded into ``modules.trace_env_fingerprint``)
ENV_KERNEL = "PERCEIVER_PAGED_KERNEL"


def kernel_requested() -> bool:
    """Normalized read of :data:`ENV_KERNEL` (trace-time, like the flash
    knobs — ``attention._flash_eligible`` discipline)."""
    return os.environ.get(ENV_KERNEL, "0") == "1"


def kernel_enabled() -> bool:
    """True when the Pallas paged-attention kernel should be traced:
    requested via env AND running on a TPU backend (the kernel is
    Mosaic-only; every other backend uses the gather reference)."""
    return kernel_requested() and jax.default_backend() == "tpu"


def flat_position_indices(table: jnp.ndarray, block_size: int, n: int) -> jnp.ndarray:
    """Pool indices for token positions ``0..n-1`` through a block table.

    :param table: ``(..., pages)`` int32 block ids (0 = null block).
    :param block_size: token positions per block.
    :param n: positions to address (``<= pages * block_size``).
    :return: ``(..., n)`` int32 indices into the flat token-major pool.
    """
    pos = jnp.arange(n, dtype=jnp.int32)
    return table[..., pos // block_size] * block_size + pos % block_size


def flat_write_indices(table: jnp.ndarray, positions: jnp.ndarray,
                       block_size: int) -> jnp.ndarray:
    """Pool indices for per-row write ``positions``.

    :param table: ``(b, pages)`` int32 block table.
    :param positions: ``(b, ...)`` int32 token positions (each row indexes
        its own table row).
    :return: same shape as ``positions``, indices into the flat pool.
    """
    b = table.shape[0]
    rows = jnp.arange(b).reshape((b,) + (1,) * (positions.ndim - 1))
    return table[rows, positions // block_size] * block_size + positions % block_size


def _constrain_gather(x: jnp.ndarray) -> jnp.ndarray:
    """Apply the installed :func:`gather_constraint` to one gathered dense
    view, dropping any dim the constraint cannot shard (a batch-1 prefill
    gather keeps its heads sharded while its slot dim replicates)."""
    constraint = _GATHER_SHARDING.get()
    if constraint is None:
        return x
    from jax.sharding import NamedSharding, PartitionSpec

    mesh, spec = constraint.mesh, constraint.spec
    dims = []
    for i in range(x.ndim):
        axis = spec[i] if i < len(spec) else None
        size = int(mesh.shape.get(axis, 1)) if axis is not None else 1
        dims.append(axis if size > 1 and x.shape[i] % size == 0 else None)
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, PartitionSpec(*dims))
    )


def gather_kv(pool: jnp.ndarray, flat_idx: jnp.ndarray) -> jnp.ndarray:
    """Gather pool rows into a dense per-slot view.

    Every caller — the decode step below, the boundary-phase step and the
    prefill finalize in ``inference/generate.py`` — flows through here, so
    the :func:`gather_constraint` sharding hint covers ALL paged gathers:
    on a serving mesh the transient view stays slot/head-sharded instead
    of all-gathering the model-sharded pool.

    :param pool: ``(pool_tokens, h, d)`` flat token-major pool.
    :param flat_idx: ``(b, n)`` indices from :func:`flat_position_indices`.
    :return: ``(b, h, n, d)`` dense view (transient).
    """
    return _constrain_gather(
        jnp.take(pool, flat_idx, axis=0).transpose(0, 2, 1, 3)
    )


def paged_decode_attention(
    attend,
    q: jnp.ndarray,
    pool_k: jnp.ndarray,
    pool_v: jnp.ndarray,
    table: jnp.ndarray,
    *,
    block_size: int,
    n: int,
    pad_mask: jnp.ndarray,
    lengths: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """One decode step's cross attention over the paged pool.

    :param attend: the caller's attend (``mha.attend`` — the SAME callable
        the dense layout runs, for bitwise parity on the gather path).
    :param q: ``(b, h, 1, d)`` pre-scaled, pre-rotated query.
    :param pool_k/pool_v: ``(pool_tokens, h, d)`` flat pools.
    :param table: ``(b, pages)`` block table rows for these b slots.
    :param block_size: pool block size in token positions.
    :param n: dense context length being addressed.
    :param pad_mask: ``(b, n)`` True = masked (the future/pad mask the
        dense attend uses).
    :param lengths: ``(b,)`` valid-token counts INCLUDING the position
        written this step — only the kernel path consumes it (the gather
        path's masking comes entirely from ``pad_mask``).
    :return: ``(b, h, 1, d)`` attention output.
    """
    if kernel_enabled() and lengths is not None:
        out = _pallas_paged_attention(
            q, pool_k, pool_v, table, lengths, block_size=block_size
        )
        if out is not None:
            return out
    flat = flat_position_indices(table, block_size, n)
    k = gather_kv(pool_k, flat)  # gather_constraint applies inside
    v = gather_kv(pool_v, flat)
    return attend(q, k, v, pad_mask=pad_mask, deterministic=True)


def _pallas_paged_attention(q, pool_k, pool_v, table, lengths, *, block_size):
    """Dispatch the Pallas TPU paged-attention kernel; None on any
    unavailability (old jax, unsupported shape) so the caller degrades to
    the gather reference instead of failing the decode step."""
    try:
        from jax.experimental.pallas.ops.tpu.paged_attention import (
            paged_attention as _kernel,
        )
    except Exception:
        return None
    try:
        tokens, h, d = pool_k.shape
        pages = tokens // block_size
        # flat (tokens, h, d) -> kernel layout (kv_heads, pages, page, d)
        k_pages = pool_k.reshape(pages, block_size, h, d).transpose(2, 0, 1, 3)
        v_pages = pool_v.reshape(pages, block_size, h, d).transpose(2, 0, 1, 3)
        # q arrives pre-scaled by ck**-0.5 (the projection applies it), and
        # the kernel adds no scale of its own — consistent with the einsum
        # path. One query token per sequence: (b, h, 1, d) -> (b, h, d).
        out = _kernel(
            q[:, :, 0, :],
            k_pages,
            v_pages,
            lengths.astype(jnp.int32),
            table.astype(jnp.int32),
        )
        return out[:, :, None, :].astype(q.dtype)
    except Exception:
        return None
