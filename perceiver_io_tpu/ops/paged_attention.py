"""Paged KV gather/scatter helpers + the paged cross-attention dispatchers.

The slot engine's paged layout (``serving/kv_pool.py``, docs/serving.md)
keeps every resident's cross-attention k/v in ONE flat device pool of
shape ``(pool_tokens, heads, head_dim)``, addressed through per-slot
block tables. This module is the device-side address arithmetic, the
optional int8 quantization of pool rows, and the attention dispatchers
over that layout:

- **Gather reference (every backend).** Flatten the block table into
  per-position pool indices, ``jnp.take`` the pages back into a dense
  ``(b, h, n, d)`` view, and run the caller's attend. Because masking in
  :func:`~perceiver_io_tpu.ops.attention.dot_product_attention` is a
  ``where`` select on the fp32 logits, positions whose pages are
  unmapped (they gather null-block trash) contribute exactly what the
  dense layout's masked garbage contributes — nothing — so greedy output
  is **bitwise identical** to the dense layout (pinned by
  ``tests/test_paged_kv.py``). The gathered view is a transient XLA
  temp, not resident HBM; the persistent footprint is the pool.
  Sharing-transparent by construction: the gather addresses pages purely
  through the table, so two slots whose tables alias the SAME physical
  blocks (cross-request prefix sharing, docs/serving.md "Prefix
  sharing") read bitwise-identical values — no read-path change was
  needed for copy-on-write sharing, and the aliased-table parity is
  pinned by ``tests/test_prefix_cache.py``.
- **Ragged kernel (opt-in).** ``PERCEIVER_RAGGED_KERNEL=1`` dispatches
  :mod:`perceiver_io_tpu.ops.ragged_attention` — one Pallas kernel that
  consumes the block table and per-row lengths directly and reads only
  the live pages, for chunked-prefill rows (multi-query) and decode rows
  (single query) alike. Pallas-compiled on TPU, ``interpret=True``
  elsewhere so the tier-1 CPU suite exercises the same kernel body. The
  kernel's blockwise online softmax is exact but not bit-identical to
  the XLA einsum, so the gather path stays the bitwise oracle; the flag
  is folded into ``modules.trace_env_fingerprint`` so a mid-process
  toggle rebuilds the decode executors instead of silently reusing the
  other trace.

**Quantized pools** (``kv_layout="paged_int8"``, docs/serving.md
"Quantized KV"): pool rows are stored int8 with per-(position, head)
symmetric f32 scales carried in twin ``(pool_tokens, heads, 1)`` arrays
addressed by the SAME flat indices as the pool. :func:`scatter_kv`
quantizes at every append site (decode scatter, chunked-prefill stage,
prefix-share COW copy) and :func:`gather_kv` dequantizes into the
transient dense view, so the attend math itself stays full precision.
A never-written row has scale 0 and dequantizes to exactly 0.0 — never
NaN — which keeps null-block reads as harmless as the exact layout's
(pinned by ``tests/test_quant_kv.py``).
"""
from __future__ import annotations

import contextlib
import contextvars
from typing import Optional

import jax
import jax.numpy as jnp

#: trace-time sharding hint for the gather path's transient dense view
#: (docs/serving.md "Sharded serving"): the sharded slot engine sets it
#: around its decode executors' trace so the gathered (b, h, n, d) k/v
#: stay slot-sharded along ``data`` and head-sharded along ``model`` —
#: the attend computes shard-local and only the o-projection all-reduces
#: (the ``sharded_paged_attention`` shape, derived by GSPMD instead of a
#: hand-written shard_map). The ragged kernel reads the same hint to
#: derive its shard_map specs, so both read paths honor one constraint.
#: None (the default) changes nothing.
_GATHER_SHARDING: contextvars.ContextVar = contextvars.ContextVar(
    "paged_gather_sharding", default=None
)


@contextlib.contextmanager
def gather_constraint(sharding):
    """Install a ``NamedSharding`` constraint for the paged gather's dense
    view during an executor trace (no-op for ``None``). Trace-time only:
    the constraint is baked into the jitted program, so the context needs
    to be live when the executor's Python body runs, not per dispatch."""
    if sharding is None:
        yield
        return
    token = _GATHER_SHARDING.set(sharding)
    try:
        yield
    finally:
        _GATHER_SHARDING.reset(token)


def flat_position_indices(table: jnp.ndarray, block_size: int, n: int) -> jnp.ndarray:
    """Pool indices for token positions ``0..n-1`` through a block table.

    :param table: ``(..., pages)`` int32 block ids (0 = null block).
    :param block_size: token positions per block.
    :param n: positions to address (``<= pages * block_size``).
    :return: ``(..., n)`` int32 indices into the flat token-major pool.
    """
    pos = jnp.arange(n, dtype=jnp.int32)
    return table[..., pos // block_size] * block_size + pos % block_size


def flat_write_indices(table: jnp.ndarray, positions: jnp.ndarray,
                       block_size: int) -> jnp.ndarray:
    """Pool indices for per-row write ``positions``.

    :param table: ``(b, pages)`` int32 block table.
    :param positions: ``(b, ...)`` int32 token positions (each row indexes
        its own table row).
    :return: same shape as ``positions``, indices into the flat pool.
    """
    b = table.shape[0]
    rows = jnp.arange(b).reshape((b,) + (1,) * (positions.ndim - 1))
    return table[rows, positions // block_size] * block_size + positions % block_size


def quantize_kv(x: jnp.ndarray):
    """Per-(position, head) symmetric int8 quantization over head_dim.

    The scale is the row's absmax over the head_dim axis divided by 127,
    so dequantization is a single fused multiply and the worst-case
    relative error is bounded by the 8-bit grid. An all-zero row (a
    never-written pool position, or genuinely zero k/v) yields scale 0
    AND quantized 0 — the ``maximum(scale, eps)`` guard keeps the
    quantizing divide finite without shifting any nonzero row's grid.

    :param x: ``(..., d)`` values, any float dtype.
    :return: ``(q, scale)`` — int8 same shape as ``x``, f32 scale of
        shape ``x.shape[:-1] + (1,)``.
    """
    xf = x.astype(jnp.float32)
    scale = jnp.max(jnp.abs(xf), axis=-1, keepdims=True) / 127.0
    q = jnp.clip(jnp.round(xf / jnp.maximum(scale, 1e-30)), -127.0, 127.0)
    return q.astype(jnp.int8), scale


def scatter_kv(pool: jnp.ndarray, scale: Optional[jnp.ndarray],
               flat_idx: jnp.ndarray, values: jnp.ndarray):
    """Append ``values`` into the pool at ``flat_idx``, quantizing when the
    layout carries scales — the ONE write primitive every paged append
    site flows through (decode scatter, boundary migrate+append, prefill
    finalize latent scatter, chunked-prefill stage), so the int8 layout
    cannot drift between sites.

    :param pool: ``(pool_tokens, h, d)`` flat pool (int8 or float).
    :param scale: ``(pool_tokens, h, 1)`` f32 scales, or None for the
        exact layout (then values are cast to the pool dtype, the
        pre-quantization behavior, bitwise unchanged).
    :param flat_idx: ``(...,)`` int32 flat pool indices.
    :param values: ``flat_idx.shape + (h, d)`` new k or v rows.
    :return: ``(pool, scale)`` with the rows written (scale None in the
        exact layout).
    """
    if scale is None:
        return pool.at[flat_idx].set(values.astype(pool.dtype)), None
    q, s = quantize_kv(values)
    return pool.at[flat_idx].set(q), scale.at[flat_idx].set(s.astype(scale.dtype))


def _constrain_gather(x: jnp.ndarray) -> jnp.ndarray:
    """Apply the installed :func:`gather_constraint` to one gathered dense
    view, dropping any dim the constraint cannot shard (a batch-1 prefill
    gather keeps its heads sharded while its slot dim replicates)."""
    constraint = _GATHER_SHARDING.get()
    if constraint is None:
        return x
    from jax.sharding import NamedSharding, PartitionSpec

    mesh, spec = constraint.mesh, constraint.spec
    dims = []
    for i in range(x.ndim):
        axis = spec[i] if i < len(spec) else None
        size = int(mesh.shape.get(axis, 1)) if axis is not None else 1
        dims.append(axis if size > 1 and x.shape[i] % size == 0 else None)
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, PartitionSpec(*dims))
    )


def gather_kv(
    pool: jnp.ndarray,
    flat_idx: jnp.ndarray,
    scale: Optional[jnp.ndarray] = None,
    out_dtype=None,
) -> jnp.ndarray:
    """Gather pool rows into a dense per-slot view, dequantizing when the
    layout carries scales.

    Every gather-path caller — the decode step, the boundary-phase step
    and the prefill finalize in ``inference/generate.py`` — flows through
    here, so the :func:`gather_constraint` sharding hint covers ALL paged
    gathers: on a serving mesh the transient view stays slot/head-sharded
    instead of all-gathering the model-sharded pool.

    :param pool: ``(pool_tokens, h, d)`` flat token-major pool.
    :param flat_idx: ``(b, n)`` indices from :func:`flat_position_indices`.
    :param scale: ``(pool_tokens, h, 1)`` f32 scales for the int8 layout
        (gathered by the same indices; ``int8 * f32`` in f32 — a zero
        scale dequantizes to exactly 0.0, never a 0/0 NaN).
    :param out_dtype: cast the dequantized view to this dtype (the attend
        compute dtype); ignored for the exact layout.
    :return: ``(b, h, n, d)`` dense view (transient).
    """
    g = jnp.take(pool, flat_idx, axis=0)
    if scale is not None:
        s = jnp.take(scale, flat_idx, axis=0)
        g = g.astype(jnp.float32) * s.astype(jnp.float32)
        if out_dtype is not None:
            g = g.astype(out_dtype)
    return _constrain_gather(g.transpose(0, 2, 1, 3))


def _ragged_kernel_attention(
    q, pool_k, pool_v, table, lengths, *, block_size, scale_k, scale_v, project_out
):
    """Dispatch the ragged kernel + output projection, or None when the
    kernel is not enabled (caller degrades to the gather reference)."""
    from perceiver_io_tpu.ops import ragged_attention as ragged

    if not ragged.kernel_enabled():
        return None
    o = ragged.ragged_paged_attention(
        q, pool_k, pool_v, table, lengths,
        block_size=block_size, scale_k=scale_k, scale_v=scale_v,
    )
    return project_out(o.astype(q.dtype))


def paged_decode_attention(
    attend,
    q: jnp.ndarray,
    pool_k: jnp.ndarray,
    pool_v: jnp.ndarray,
    table: jnp.ndarray,
    *,
    block_size: int,
    n: int,
    pad_mask: jnp.ndarray,
    lengths: Optional[jnp.ndarray] = None,
    scale_k: Optional[jnp.ndarray] = None,
    scale_v: Optional[jnp.ndarray] = None,
    project_out=None,
) -> jnp.ndarray:
    """One decode step's cross attention over the paged pool.

    :param attend: the caller's attend (``mha.attend`` — the SAME callable
        the dense layout runs, for bitwise parity on the gather path; it
        includes the output projection).
    :param q: ``(b, h, 1, d)`` pre-scaled, pre-rotated query.
    :param pool_k/pool_v: ``(pool_tokens, h, d)`` flat pools.
    :param table: ``(b, pages)`` block table rows for these b slots.
    :param block_size: pool block size in token positions.
    :param n: dense context length being addressed.
    :param pad_mask: ``(b, n)`` True = masked (the future/pad mask the
        dense attend uses).
    :param lengths: ``(b,)`` valid-token counts INCLUDING the position
        written this step — only the kernel path consumes it (the gather
        path's masking comes entirely from ``pad_mask``).
    :param scale_k/scale_v: int8-layout dequant scales, or None.
    :param project_out: ``mha.project_out`` — applies the output
        projection to the kernel's raw ``(b, h, q, d)`` attention (the
        gather path's ``attend`` already includes it). Required for the
        kernel path.
    :return: ``(b, h_out)``-projected attention output, same as attend's.
    """
    if lengths is not None and project_out is not None:
        out = _ragged_kernel_attention(
            q, pool_k, pool_v, table, lengths.astype(jnp.int32),
            block_size=block_size, scale_k=scale_k, scale_v=scale_v,
            project_out=project_out,
        )
        if out is not None:
            return out
    flat = flat_position_indices(table, block_size, n)
    out_dtype = q.dtype if scale_k is not None else None
    k = gather_kv(pool_k, flat, scale_k, out_dtype)  # gather_constraint applies inside
    v = gather_kv(pool_v, flat, scale_v, out_dtype)
    return attend(q, k, v, pad_mask=pad_mask, deterministic=True)


def paged_window_attention(
    attend,
    q: jnp.ndarray,
    pool_k: jnp.ndarray,
    pool_v: jnp.ndarray,
    table: jnp.ndarray,
    *,
    block_size: int,
    n: int,
    pad_count: jnp.ndarray,
    scale_k: Optional[jnp.ndarray] = None,
    scale_v: Optional[jnp.ndarray] = None,
    project_out=None,
) -> jnp.ndarray:
    """Window-aligned cross attention for the multi-query paged phases
    (prefill finalize, boundary step): the latent queries attend the whole
    ``n``-slot window, front-padded by ``pad_count`` garbage slots the pad
    mask removes.

    Gather path: position ``i`` reads pool position ``max(i - pad, 0)``
    (pads re-read position 0 and are masked) — bitwise identical to the
    dense layout's aligned gather, with ``attend`` applying the
    right-aligned causal mask ``j <= i + (j_len - i_len)`` in slot space.
    Kernel path: dropping the pad slots shifts both keys and queries left
    by ``pad``, so the slot-space causal mask becomes the kernel's
    position-space bound (query ``i`` sees positions
    ``<= lengths[r] - q_len + i``) over the CONTIGUOUS live span
    ``[0, n - pad_count)`` — exactly the block-table + lengths contract
    the decode rows use, which is what lets ONE kernel serve both row
    shapes (q length 1 or ``max_latents``) with no per-phase variant.

    :param pad_count: ``(b,)`` leading pad slots per row.
    :return: projected attention output (same contract as ``attend``'s).
    """
    lengths = (n - pad_count).astype(jnp.int32)
    if project_out is not None:
        out = _ragged_kernel_attention(
            q, pool_k, pool_v, table, lengths,
            block_size=block_size, scale_k=scale_k, scale_v=scale_v,
            project_out=project_out,
        )
        if out is not None:
            return out
    slot_abs = jnp.maximum(jnp.arange(n)[None, :] - pad_count[:, None], 0)
    flat_g = flat_write_indices(table, slot_abs, block_size)
    out_dtype = q.dtype if scale_k is not None else None
    k_slots = gather_kv(pool_k, flat_g, scale_k, out_dtype)
    v_slots = gather_kv(pool_v, flat_g, scale_v, out_dtype)
    pad_mask = jnp.arange(n)[None, :] < pad_count[:, None]
    return attend(q, k_slots, v_slots, pad_mask=pad_mask, deterministic=True)
