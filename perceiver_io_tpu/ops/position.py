"""Position encodings: shifted absolute positions, rotary embeddings,
inverse-frequency encodings and N-D Fourier features.

Capability parity with reference ``perceiver/model/core/position.py:9-138``;
implemented as pure functions / pytree dataclasses so everything is traceable
and shardable under ``jit``.
"""
from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np
from flax import struct


def positions(b: int, n: int, shift: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Absolute positions ``0..n-1`` per batch row, optionally shifted left by a
    per-row pad count (for left-padded batches) and clamped at 0.

    Mirrors reference ``position.py:9-17``.

    :param shift: optional ``(b, 1)`` int array — number of left-pad tokens.
    :return: ``(b, n)`` int32 positions.
    """
    pos = jnp.broadcast_to(jnp.arange(n, dtype=jnp.int32), (b, n))
    if shift is not None:
        if shift.shape != (b, 1):
            raise ValueError(f"shift must have shape {(b, 1)} but has shape {shift.shape}")
        pos = pos - shift.astype(jnp.int32)
    return jnp.maximum(pos, 0)


def frequency_position_encoding(abs_pos: jnp.ndarray, dim: int) -> jnp.ndarray:
    """Inverse-frequency encoding of absolute positions (rotary frequencies).

    ``inv_freq_i = 10000 ** (-2i/dim)``; each frequency is repeated twice along
    the channel axis so that consecutive channel pairs share a frequency (the
    pair layout consumed by :func:`rotate_half`). Mirrors reference
    ``position.py:53-71``.

    :param abs_pos: ``(..., n)`` integer positions.
    :param dim: number of rotated channels (even).
    :return: ``(..., n, dim)`` float32 angles ``pos * inv_freq``.
    """
    inv_freq = 1.0 / (10000 ** (jnp.arange(0, dim, 2, dtype=jnp.float32) / dim))
    pos_enc = abs_pos.astype(jnp.float32)[..., None] * inv_freq
    # [f0, f0, f1, f1, ...] pairing, matching the reference's (pf r) repeat.
    return jnp.repeat(pos_enc, 2, axis=-1)


def rotate_half(x: jnp.ndarray) -> jnp.ndarray:
    """Channel-pair rotation ``[x1, x2, x3, x4, ...] -> [-x2, x1, -x4, x3, ...]``."""
    x = x.reshape(*x.shape[:-1], x.shape[-1] // 2, 2)
    x1, x2 = x[..., 0], x[..., 1]
    x = jnp.stack((-x2, x1), axis=-1)
    return x.reshape(*x.shape[:-2], -1)


@struct.dataclass
class RotaryEmbedding:
    """Rotary position embedding (RoFormer) applied to the leading
    ``rotate_dim`` channels of q/k heads; remaining channels pass through.

    ``frq_pos_enc`` has shape ``(b, n, rotate_dim)``. When ``right_align`` is
    set, a shorter input of length ``m < n`` is aligned to the *last* ``m``
    positions — used by Perceiver AR where latents sit at the sequence tail.
    Mirrors reference ``position.py:20-50``.
    """

    frq_pos_enc: jnp.ndarray
    right_align: bool = struct.field(pytree_node=False, default=False)

    @property
    def rotate_dim(self) -> int:
        return self.frq_pos_enc.shape[-1]

    def rotate(self, t: jnp.ndarray) -> jnp.ndarray:
        """Rotate ``t`` of shape ``(b, h, m, c)`` with ``c >= rotate_dim``."""
        seq_len = t.shape[-2]
        pos_enc = self.frq_pos_enc[:, None, :, :]  # (b, 1, n, rd)
        if self.right_align:
            pos_enc = pos_enc[..., pos_enc.shape[-2] - seq_len :, :]
        else:
            pos_enc = pos_enc[..., :seq_len, :]
        pos_enc = pos_enc.astype(jnp.float32)
        t_rot, t_pass = t[..., : self.rotate_dim], t[..., self.rotate_dim :]
        t_dtype = t_rot.dtype
        t_rot = t_rot.astype(jnp.float32)
        t_rot = t_rot * jnp.cos(pos_enc) + rotate_half(t_rot) * jnp.sin(pos_enc)
        return jnp.concatenate((t_rot.astype(t_dtype), t_pass), axis=-1)


import functools


@functools.lru_cache(maxsize=32)
def _fourier_table(input_shape: Tuple[int, ...], num_frequency_bands: int) -> np.ndarray:
    coords = [np.linspace(-1.0, 1.0, num=s, dtype=np.float32) for s in input_shape]
    pos = np.stack(np.meshgrid(*coords, indexing="ij"), axis=-1)  # (*shape, d)
    encodings = [pos]
    grids = []
    for i, max_freq in enumerate(input_shape):
        freqs = np.linspace(1.0, max_freq / 2.0, num=num_frequency_bands, dtype=np.float32)
        grids.append(pos[..., i : i + 1] * freqs)
    encodings.extend([np.sin(math.pi * g) for g in grids])
    encodings.extend([np.cos(math.pi * g) for g in grids])
    enc = np.concatenate(encodings, axis=-1)
    return enc.reshape(-1, enc.shape[-1])


class FourierPositionEncoding:
    """N-D Fourier feature position encoding for grid-shaped inputs (images).

    Positions are evenly spaced in ``[-1, 1]`` per spatial dim (``ij`` indexed
    meshgrid, matching reference ``position.py:91-99``); each coordinate is
    expanded with ``num_frequency_bands`` sin/cos features with frequencies
    linearly spaced in ``[1, max_freq/2]`` plus the raw coordinate.

    The encoding is input-independent; the table is built once per
    (shape, bands) pair via an lru_cache (adapters and model ``setup`` may
    construct this object many times per trace) and becomes an XLA constant
    under ``jit``.
    """

    def __init__(self, input_shape: Sequence[int], num_frequency_bands: int):
        self.input_shape = tuple(input_shape)
        self.num_frequency_bands = num_frequency_bands
        self._encoding = _fourier_table(self.input_shape, num_frequency_bands)

    @property
    def num_channels(self) -> int:
        return len(self.input_shape) * (2 * self.num_frequency_bands + 1)

    def __call__(self, b: int) -> jnp.ndarray:
        """Return ``(b, prod(input_shape), num_channels)`` encodings."""
        enc = jnp.asarray(self._encoding)
        return jnp.broadcast_to(enc, (b, *enc.shape))
