from perceiver_io_tpu.ops.position import (
    FourierPositionEncoding,
    RotaryEmbedding,
    frequency_position_encoding,
    positions,
)
from perceiver_io_tpu.ops.attention import dot_product_attention
