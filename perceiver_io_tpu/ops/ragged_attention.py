"""One ragged paged-attention kernel for mixed prefill/decode rows.

The gather reference in :mod:`perceiver_io_tpu.ops.paged_attention`
materializes a dense ``(b, h, n, d)`` view of every row's FULL window —
``n`` positions of HBM traffic per step regardless of how few tokens the
row actually holds. This module is the ragged alternative (the "Ragged
Paged Attention" TPU kernel design, PAPERS.md): ONE Pallas kernel that
consumes the block table and per-row lengths directly, reads only the
mapped pages, and computes a blockwise online softmax over the live span
``[0, lengths[r])`` under the Perceiver-AR right-aligned causal
contract: query ``i`` of a ``q_len``-query row sits at position
``lengths[r] - q_len + i`` and sees only positions up to its own. Rows
are ragged in two senses and the kernel handles both in one launch:

- **decode rows**: a single query token (``q_len = 1``) over however
  many positions the row has accumulated;
- **chunked-prefill / boundary rows**: the full latent segment
  (``q_len = max_latents``) over the row's prompt span.

Both phases call the SAME kernel body — only the ``q_len`` of the
launch's q block differs — so there are no per-phase kernel variants and
the engine's compile bound is unchanged (pinned by
``tests/test_ragged_attention.py``).

Backend policy (ISSUE 16): Pallas-compiled on TPU; ``interpret=True``
everywhere else so the tier-1 CPU suite executes the same kernel body —
the parity tests stay honest while the TPU relay is down. The kernel's
online softmax is exact but not bitwise-equal to the XLA einsum, so the
gather reference remains the bitwise oracle and the kernel is opt-in via
``PERCEIVER_RAGGED_KERNEL=1`` (folded into
``modules.trace_env_fingerprint`` + the CompileLedger ``kv_layout``
component, so flips rebuild and attribute instead of silently reusing a
stale trace).

Quantized pools: optional per-(position, head) f32 scales ride along as
two more page-blocked inputs and the dequantize multiply happens inside
the kernel, on the one page actually being processed — int8 HBM traffic,
f32 math (docs/serving.md "Quantized KV").

Sharding: the kernel honors the SAME
:func:`~perceiver_io_tpu.ops.paged_attention.gather_constraint` hint the
gather path uses — rows shard along the constraint's first (data) axis,
heads along its second (model) axis, pages replicated — via an explicit
``shard_map``, so the sharded slot engine (docs/serving.md "Sharded
serving") can flip the kernel on without touching its mesh plumbing.
"""
from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

#: trace-time env flag enabling the ragged kernel on every paged read
#: path (see module docstring; folded into ``trace_env_fingerprint``)
ENV_KERNEL = "PERCEIVER_RAGGED_KERNEL"

#: number of times a kernel launch was TRACED this process — a retrace
#: probe for tests (steady-state decode must not grow it), not a metric;
#: the serving engine's dispatch counter is ``kv_ragged_kernel_steps_total``
TRACE_COUNT = 0


def kernel_requested() -> bool:
    """Normalized read of :data:`ENV_KERNEL` (trace-time, like the flash
    knobs — ``attention._flash_eligible`` discipline)."""
    return os.environ.get(ENV_KERNEL, "0") == "1"


def kernel_enabled() -> bool:
    """True when the ragged kernel should be traced. Unlike the retired
    dense-Pallas opt-in this is NOT TPU-gated: non-TPU backends run the
    same kernel body under the Pallas interpreter, so enabling the flag
    in the CPU test suite exercises the real code path."""
    return kernel_requested()


def _make_kernel(block_size: int, pages: int, quantized: bool):
    """Build the kernel body for one (block_size, pages-per-row, layout)
    geometry. ``pages`` is baked in so the final-page epilogue is a
    trace-time predicate; the grid iterates pages minor, so the scratch
    accumulators carry one row's running softmax across its pages."""

    def kernel(table_ref, len_ref, q_ref, k_ref, v_ref, *rest):
        if quantized:
            sk_ref, sv_ref, o_ref, m_ref, l_ref, acc_ref = rest
        else:
            o_ref, m_ref, l_ref, acc_ref = rest
        r = pl.program_id(0)
        p = pl.program_id(1)

        @pl.when(p == 0)
        def _init():
            # finite sentinel, not -inf: exp(m_prev - m_new) must stay
            # well-defined for rows whose every position is masked
            m_ref[...] = jnp.full(m_ref.shape, -1e30, m_ref.dtype)
            l_ref[...] = jnp.zeros(l_ref.shape, l_ref.dtype)
            acc_ref[...] = jnp.zeros(acc_ref.shape, acc_ref.dtype)

        q = q_ref[0].astype(jnp.float32)            # (h, q_len, d)
        k = k_ref[0].astype(jnp.float32)            # (block_size, h, d)
        v = v_ref[0].astype(jnp.float32)
        if quantized:
            # dequant on the page in registers: int8 HBM reads, f32 math;
            # zero scale (never-written row) multiplies to exactly 0.0
            k = k * sk_ref[0].astype(jnp.float32)
            v = v * sv_ref[0].astype(jnp.float32)
        k = k.transpose(1, 0, 2)                    # (h, block_size, d)
        v = v.transpose(1, 0, 2)

        # q arrives pre-scaled by ck**-0.5 (the projection applies it);
        # the kernel adds no scale of its own — same as the einsum path
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )                                           # (h, q_len, block_size)
        pos = p * block_size + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
        # right-aligned causal bound, matching the dense attend's
        # `j <= i + (j_len - i_len)` (ops/attention.py): query qi of a
        # window row sits at position lengths[r] - q_len + qi and may not
        # see the later latents' entries; q_len = 1 decode rows reduce to
        # the plain live-span mask pos < lengths[r]
        q_len = s.shape[1]
        qi = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        valid = (pos + (q_len - 1) - qi) < len_ref[r]
        s = jnp.where(valid, s, -1e30)

        m_prev = m_ref[...]                         # (h, q_len)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        alpha = jnp.exp(m_prev - m_new)
        # explicit zeroing, not exp(-1e30 - m): a fully-masked page must
        # contribute exactly nothing to l and acc
        probs = jnp.where(valid, jnp.exp(s - m_new[..., None]), 0.0)
        l_ref[...] = l_ref[...] * alpha + jnp.sum(probs, axis=-1)
        acc_ref[...] = acc_ref[...] * alpha[..., None] + jax.lax.dot_general(
            probs, v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        m_ref[...] = m_new

        @pl.when(p == pages - 1)
        def _emit():
            # l == 0 (an idle row with length <= 0) divides the zero acc
            # by the epsilon: finite zeros, discarded by write routing
            o_ref[0] = (
                acc_ref[...] / jnp.maximum(l_ref[...], 1e-30)[..., None]
            ).astype(o_ref.dtype)

    return kernel


def _launch(q, k_pages, v_pages, table, lengths, scales, *, block_size, interpret):
    """One pallas_call over grid (rows, pages-per-row). Scalar-prefetched
    table/lengths drive the page index maps, so each step fetches exactly
    the row's mapped page — the ragged read the gather path lacks."""
    b, h, q_len, d = q.shape
    pages = table.shape[1]
    quantized = scales is not None

    row_map = lambda r, p, tbl, lens: (r, 0, 0, 0)
    page_map = lambda r, p, tbl, lens: (tbl[r, p], 0, 0, 0)
    in_specs = [
        pl.BlockSpec((1, h, q_len, d), row_map),
        pl.BlockSpec((1, block_size, h, d), page_map),
        pl.BlockSpec((1, block_size, h, d), page_map),
    ]
    inputs = [q, k_pages, v_pages]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, block_size, h, 1), page_map),
            pl.BlockSpec((1, block_size, h, 1), page_map),
        ]
        inputs += list(scales)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, pages),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, h, q_len, d), row_map),
        scratch_shapes=[
            pltpu.VMEM((h, q_len), jnp.float32),      # running max
            pltpu.VMEM((h, q_len), jnp.float32),      # running denominator
            pltpu.VMEM((h, q_len, d), jnp.float32),   # running numerator
        ],
    )
    return pl.pallas_call(
        _make_kernel(block_size, pages, quantized),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, q_len, d), q.dtype),
        interpret=interpret,
    )(table, lengths, *inputs)


def ragged_paged_attention(
    q: jnp.ndarray,
    pool_k: jnp.ndarray,
    pool_v: jnp.ndarray,
    table: jnp.ndarray,
    lengths: jnp.ndarray,
    *,
    block_size: int,
    scale_k: Optional[jnp.ndarray] = None,
    scale_v: Optional[jnp.ndarray] = None,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Ragged paged attention over the flat pool.

    :param q: ``(b, h, q_len, d)`` pre-scaled, pre-rotated queries —
        ``q_len`` is 1 for decode rows, ``max_latents`` for prefill
        finalize / boundary rows; both shapes run this same kernel.
    :param pool_k/pool_v: ``(pool_tokens, h, d)`` flat token-major pools
        (int8 when scales are given; ``pool_tokens`` must be a multiple
        of ``block_size`` — the pool is allocated in whole blocks).
    :param table: ``(b, pages)`` int32 block ids (0 = null block; rows
        attend only ``[0, lengths[r])`` — right-aligned causally for
        multi-query rows, matching the dense attend's
        ``j <= i + (j_len - i_len)`` mask — so unmapped tail pages read
        the null block and are masked by the length predicate).
    :param lengths: ``(b,)`` int32 live-span lengths; ``<= 0`` rows
        produce all-zero output (idle slots, discarded by the engine's
        write routing).
    :param scale_k/scale_v: optional ``(pool_tokens, h, 1)`` f32 dequant
        scales (the int8 layout).
    :param interpret: force the Pallas interpreter; default: compiled on
        TPU, interpreted elsewhere.
    :return: ``(b, h, q_len, d)`` raw attention (NO output projection —
        the caller applies ``mha.project_out``; the gather reference's
        ``attend`` includes it).
    """
    global TRACE_COUNT
    TRACE_COUNT += 1
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    tokens, h, d = pool_k.shape
    if tokens % block_size:
        raise ValueError(
            f"pool_tokens={tokens} not a multiple of block_size={block_size}"
        )
    pages_total = tokens // block_size
    k_pages = pool_k.reshape(pages_total, block_size, h, d)
    v_pages = pool_v.reshape(pages_total, block_size, h, d)
    scales = None
    if scale_k is not None:
        scales = (
            scale_k.astype(jnp.float32).reshape(pages_total, block_size, h, 1),
            scale_v.astype(jnp.float32).reshape(pages_total, block_size, h, 1),
        )
    table = table.astype(jnp.int32)
    lengths = lengths.astype(jnp.int32)
    launch = functools.partial(_launch, block_size=block_size, interpret=interpret)

    from perceiver_io_tpu.ops import paged_attention as paged  # cycle-free: lazy

    constraint = paged._GATHER_SHARDING.get()
    if constraint is None:
        return launch(q, k_pages, v_pages, table, lengths, scales)

    # Same placement the gather constraint encodes for its (b, h, n, d)
    # view: rows along the data axis, heads along the model axis, pool
    # pages replicated... but shard_map needs exact divisibility, so any
    # non-divisible dim degrades to replicated (the _constrain_gather
    # discipline).
    mesh, spec = constraint.mesh, constraint.spec

    def _axis(i, size):
        ax = spec[i] if i < len(spec) else None
        if ax is None or int(mesh.shape.get(ax, 1)) <= 1 or size % int(mesh.shape[ax]):
            return None
        return ax

    row_ax, head_ax = _axis(0, q.shape[0]), _axis(1, h)
    if row_ax is None and head_ax is None:
        return launch(q, k_pages, v_pages, table, lengths, scales)

    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    page_spec = P(None, None, head_ax, None)
    in_specs = [
        P(row_ax, head_ax, None, None),  # q
        page_spec, page_spec,            # k/v pages
        P(row_ax, None),                 # table
        P(row_ax,),                      # lengths
    ]
    if scales is not None:
        in_specs += [page_spec, page_spec]

    def body(q_, k_, v_, tbl_, lens_, *maybe_scales):
        return launch(q_, k_, v_, tbl_, lens_, maybe_scales or None)

    fn = shard_map(
        body, mesh=mesh, in_specs=tuple(in_specs),
        out_specs=P(row_ax, head_ax, None, None), check_rep=False,
    )
    args = (q, k_pages, v_pages, table, lengths) + (scales if scales else ())
    return fn(*args)
