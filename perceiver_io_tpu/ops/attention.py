"""Scaled dot-product attention — the single attention primitive shared by all
Perceiver cross-/self-attention modules.

Capability parity with reference ``perceiver/model/core/modules.py:84-154``:
optional causal masking of right-aligned q/kv of unequal length, boolean key
pad masking, attention-matrix dropout, and a ``max_heads_parallel`` knob that
bounds peak memory by serializing over head groups.

TPU-first design notes:
- logits/softmax always computed in float32 regardless of input dtype
  (bf16 q/k/v stay bf16 for the matmuls feeding the MXU; the softmax runs on
  the VPU in fp32 for numerical parity with the reference).
- masks are applied as ``where(mask, -inf_min, logits)`` selects on the fp32
  logits; XLA fuses them into the softmax.
- ``impl='flash'`` dispatches to the Pallas flash kernel
  (:mod:`perceiver_io_tpu.ops.flash_attention`) when shapes permit;
  ``impl='xla'`` is the reference-semantics einsum path. ``'auto'`` picks
  flash on TPU for long sequences.
- ``impl='ring'`` dispatches to ring attention
  (:mod:`perceiver_io_tpu.parallel.ring`): q and k/v sequence dims are
  sharded over the ambient mesh's ``seq`` axis and k/v chunks rotate via
  ``ppermute`` — context parallelism for sequences one device cannot hold.
  Requires an active ``Mesh`` context with a ``seq`` axis (the trainer's
  ``shard_seq`` path provides one).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def _mask_value() -> float:
    return float(jnp.finfo(jnp.float32).min)


def dot_product_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    pad_mask: Optional[jnp.ndarray] = None,
    causal: bool = False,
    dropout_rate: float = 0.0,
    dropout_rng: Optional[jax.Array] = None,
    max_heads_parallel: Optional[int] = None,
    impl: str = "auto",
) -> jnp.ndarray:
    """Attention over pre-projected (and pre-scaled, pre-rotated) heads.

    :param q: ``(b, h, i, ck)`` queries — already multiplied by ``ck**-0.5``
        and rotary-rotated by the caller (mirroring the reference's order of
        operations, ``modules.py:104-115``).
    :param k: ``(b, h, j, ck)`` keys (rotary-rotated by caller).
    :param v: ``(b, h, j, cv)`` values.
    :param pad_mask: optional boolean ``(b, j)``; **True marks padding** (the
        reference's convention, ``modules.py:97``).
    :param causal: apply right-aligned causal masking.
    :param dropout_rate: dropout on the post-softmax attention matrix.
    :param max_heads_parallel: process at most this many heads at once
        (memory bound); ``None`` = all heads.
    :param impl: ``'auto' | 'xla' | 'flash'``.
    :return: ``(b, h, i, cv)``.
    """
    if impl == "ring":
        if dropout_rate > 0.0:
            raise ValueError("ring attention does not support attention dropout")
        mesh = _ambient_mesh()
        if mesh is None or "seq" not in mesh.axis_names or mesh.shape["seq"] == 1:
            # No seq-sharded mesh in scope (e.g. model.init outside the mesh
            # context): ring is numerically identical to the einsum path, so
            # degrade gracefully instead of failing.
            import warnings

            warnings.warn(
                "impl='ring' without an active Mesh with a 'seq' axis of "
                "size > 1 — falling back to the XLA einsum path; wrap the "
                "call in `with make_mesh(MeshConfig(seq=...)):` for "
                "sequence-parallel execution",
                UserWarning,
                stacklevel=2,
            )
        else:
            from perceiver_io_tpu.parallel.ring import ring_attention_sharded

            return ring_attention_sharded(
                q, k, v, mesh, axis_name="seq", pad_mask=pad_mask, causal=causal
            )

    use_flash = False
    if impl == "flash" or (impl == "auto" and _flash_eligible(q, k, v, dropout_rate)):
        from perceiver_io_tpu.ops import flash_attention

        if impl == "flash" and dropout_rate > 0.0:
            raise ValueError("flash attention does not support attention dropout")
        use_flash = flash_attention.supported(q, k, v, causal=causal)
        if impl == "flash" and not use_flash:
            raise ValueError(
                f"flash attention requested but unsupported for shapes q={q.shape} k={k.shape}"
            )
    if use_flash:
        from perceiver_io_tpu.ops import flash_attention

        return flash_attention.flash_attention(q, k, v, pad_mask=pad_mask, causal=causal)

    num_heads = q.shape[1]
    if max_heads_parallel is None or max_heads_parallel >= num_heads:
        return _attention_xla(q, k, v, pad_mask, causal, dropout_rate, dropout_rng)

    chunks = []
    for h0 in range(0, num_heads, max_heads_parallel):
        h1 = min(h0 + max_heads_parallel, num_heads)
        rng = None
        if dropout_rng is not None:
            dropout_rng, rng = jax.random.split(dropout_rng)
        chunks.append(
            _attention_xla(
                q[:, h0:h1], k[:, h0:h1], v[:, h0:h1], pad_mask, causal, dropout_rate, rng
            )
        )
    return jnp.concatenate(chunks, axis=1)


def _ambient_mesh():
    """The physical mesh of the enclosing ``with mesh:`` context, or None."""
    try:
        from jax.interpreters import pxla

        mesh = pxla.thread_resources.env.physical_mesh
        return None if mesh.empty else mesh
    except Exception:
        return None


def _flash_eligible(q, k, v, dropout_rate) -> bool:
    # Flash path only on TPU, without attention dropout (the reference default
    # is dropout 0.0 everywhere; training configs that enable it fall back).
    if dropout_rate > 0.0:
        return False
    # Optional kv-length floor for 'auto' (PERCEIVER_FLASH_MIN_KV): below it,
    # the materialized XLA softmax is cheap and the blockwise schedule's
    # per-block overhead can dominate — lets short self-attention use XLA
    # while long-kv cross-attention stays flash. Default 0 = flash everywhere.
    #
    # TRACE-TIME: this (and PERCEIVER_FLASH_BLOCKS in flash_attention.py) is
    # read at trace time. The inference executor caches (generation, beam,
    # slot serving) fold it into their cache keys via
    # ``modules.trace_env_fingerprint``, so a mid-process toggle rebuilds
    # those executors; plain ``jax.jit`` call sites (train steps) are NOT
    # keyed on it — set it before the first forward pass there, or isolate
    # per-setting in a subprocess as examples/perf/tune_step.py does.
    import os

    try:
        min_kv = int(os.environ.get("PERCEIVER_FLASH_MIN_KV", "0"))
    except ValueError:
        min_kv = 0
    if k.shape[2] < min_kv:
        return False
    try:
        platform = q.devices().pop().platform if hasattr(q, "devices") else jax.default_backend()
    except Exception:
        platform = jax.default_backend()
    return platform == "tpu"


def _attention_xla(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    pad_mask: Optional[jnp.ndarray],
    causal: bool,
    dropout_rate: float,
    dropout_rng: Optional[jax.Array],
) -> jnp.ndarray:
    i, j = q.shape[-2], k.shape[-2]
    logits = jnp.einsum("bhic,bhjc->bhij", q, k, preferred_element_type=jnp.float32)
    logits = logits.astype(jnp.float32)

    if pad_mask is not None:
        logits = jnp.where(pad_mask[:, None, None, :], _mask_value(), logits)
    if causal:
        allowed = jnp.arange(j)[None, :] <= jnp.arange(i)[:, None] + (j - i)
        logits = jnp.where(allowed[None, None], logits, _mask_value())

    attn = jax.nn.softmax(logits, axis=-1)
    if dropout_rate > 0.0 and dropout_rng is not None:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, attn.shape)
        attn = jnp.where(keep, attn / (1.0 - dropout_rate), 0.0)
    attn = attn.astype(v.dtype)
    return jnp.einsum("bhij,bhjc->bhic", attn, v)
