"""Scaled dot-product attention — the single attention primitive shared by all
Perceiver cross-/self-attention modules.

Capability parity with reference ``perceiver/model/core/modules.py:84-154``:
optional causal masking of right-aligned q/kv of unequal length, boolean key
pad masking, attention-matrix dropout, and a ``max_heads_parallel`` knob that
bounds peak memory by serializing over head groups.

TPU-first design notes:
- logits/softmax always computed in float32 regardless of input dtype
  (bf16 q/k/v stay bf16 for the matmuls feeding the MXU; the softmax runs on
  the VPU in fp32 for numerical parity with the reference).
- masks are applied as ``where(mask, -inf_min, logits)`` selects on the fp32
  logits; XLA fuses them into the softmax.
- ``impl='flash'`` dispatches to the Pallas flash kernel
  (:mod:`perceiver_io_tpu.ops.flash_attention`) when shapes permit;
  ``impl='xla'`` is the reference-semantics einsum path. ``'auto'`` picks
  flash on TPU for long sequences.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def _mask_value() -> float:
    return float(jnp.finfo(jnp.float32).min)


def dot_product_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    pad_mask: Optional[jnp.ndarray] = None,
    causal: bool = False,
    dropout_rate: float = 0.0,
    dropout_rng: Optional[jax.Array] = None,
    max_heads_parallel: Optional[int] = None,
    impl: str = "auto",
) -> jnp.ndarray:
    """Attention over pre-projected (and pre-scaled, pre-rotated) heads.

    :param q: ``(b, h, i, ck)`` queries — already multiplied by ``ck**-0.5``
        and rotary-rotated by the caller (mirroring the reference's order of
        operations, ``modules.py:104-115``).
    :param k: ``(b, h, j, ck)`` keys (rotary-rotated by caller).
    :param v: ``(b, h, j, cv)`` values.
    :param pad_mask: optional boolean ``(b, j)``; **True marks padding** (the
        reference's convention, ``modules.py:97``).
    :param causal: apply right-aligned causal masking.
    :param dropout_rate: dropout on the post-softmax attention matrix.
    :param max_heads_parallel: process at most this many heads at once
        (memory bound); ``None`` = all heads.
    :param impl: ``'auto' | 'xla' | 'flash'``.
    :return: ``(b, h, i, cv)``.
    """
    use_flash = False
    if impl == "flash" or (impl == "auto" and _flash_eligible(q, k, v, dropout_rate)):
        from perceiver_io_tpu.ops import flash_attention

        if impl == "flash" and dropout_rate > 0.0:
            raise ValueError("flash attention does not support attention dropout")
        use_flash = flash_attention.supported(q, k, v, causal=causal)
        if impl == "flash" and not use_flash:
            raise ValueError(
                f"flash attention requested but unsupported for shapes q={q.shape} k={k.shape}"
            )
    if use_flash:
        from perceiver_io_tpu.ops import flash_attention

        return flash_attention.flash_attention(q, k, v, pad_mask=pad_mask, causal=causal)

    num_heads = q.shape[1]
    if max_heads_parallel is None or max_heads_parallel >= num_heads:
        return _attention_xla(q, k, v, pad_mask, causal, dropout_rate, dropout_rng)

    chunks = []
    for h0 in range(0, num_heads, max_heads_parallel):
        h1 = min(h0 + max_heads_parallel, num_heads)
        rng = None
        if dropout_rng is not None:
            dropout_rng, rng = jax.random.split(dropout_rng)
        chunks.append(
            _attention_xla(
                q[:, h0:h1], k[:, h0:h1], v[:, h0:h1], pad_mask, causal, dropout_rate, rng
            )
        )
    return jnp.concatenate(chunks, axis=1)


def _flash_eligible(q, k, v, dropout_rate) -> bool:
    # Flash path only on TPU, without attention dropout (the reference default
    # is dropout 0.0 everywhere; training configs that enable it fall back).
    if dropout_rate > 0.0:
        return False
    try:
        platform = q.devices().pop().platform if hasattr(q, "devices") else jax.default_backend()
    except Exception:
        platform = jax.default_backend()
    return platform == "tpu"


def _attention_xla(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    pad_mask: Optional[jnp.ndarray],
    causal: bool,
    dropout_rate: float,
    dropout_rng: Optional[jax.Array],
) -> jnp.ndarray:
    i, j = q.shape[-2], k.shape[-2]
    logits = jnp.einsum("bhic,bhjc->bhij", q, k, preferred_element_type=jnp.float32)
    logits = logits.astype(jnp.float32)

    if pad_mask is not None:
        logits = jnp.where(pad_mask[:, None, None, :], _mask_value(), logits)
    if causal:
        allowed = jnp.arange(j)[None, :] <= jnp.arange(i)[:, None] + (j - i)
        logits = jnp.where(allowed[None, None], logits, _mask_value())

    attn = jax.nn.softmax(logits, axis=-1)
    if dropout_rate > 0.0 and dropout_rng is not None:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, attn.shape)
        attn = jnp.where(keep, attn / (1.0 - dropout_rate), 0.0)
    attn = attn.astype(v.dtype)
    return jnp.einsum("bhij,bhjc->bhic", attn, v)
