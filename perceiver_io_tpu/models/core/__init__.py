from perceiver_io_tpu.models.core.adapter import (
    ClassificationOutputAdapter,
    InputAdapter,
    TrainableQueryProvider,
    rotary_frequencies,
)
from perceiver_io_tpu.models.core.config import (
    ClassificationDecoderConfig,
    DecoderConfig,
    EncoderConfig,
    PerceiverARConfig,
    PerceiverIOConfig,
    config_from_dict,
    config_to_dict,
)
from perceiver_io_tpu.models.core.modules import (
    CrossAttention,
    CrossAttentionLayer,
    MLP,
    MultiHeadAttention,
    PerceiverAR,
    PerceiverDecoder,
    PerceiverEncoder,
    PerceiverIO,
    SelfAttention,
    SelfAttentionBlock,
    SelfAttentionLayer,
)
