"""Canonical hyperparameter dataclasses for the core Perceiver runtime.

One config system serves the trainer, the CLI (flags are generated from these
dataclasses), checkpoint metadata (serialized alongside orbax state) and the
inference wrappers — mirroring the reference's single-dataclass design
(``perceiver/model/core/config.py:5-83``).
"""
from __future__ import annotations

import dataclasses
from dataclasses import asdict, dataclass, fields
from typing import Any, Dict, Generic, Optional, Tuple, TypeVar


@dataclass
class EncoderConfig:
    """Perceiver IO encoder hyperparameters (reference ``config.py:5-25``)."""

    num_cross_attention_heads: int = 8
    num_cross_attention_qk_channels: Optional[int] = None
    num_cross_attention_v_channels: Optional[int] = None
    num_cross_attention_layers: int = 1
    first_cross_attention_layer_shared: bool = False
    cross_attention_widening_factor: int = 1
    num_self_attention_heads: int = 8
    num_self_attention_qk_channels: Optional[int] = None
    num_self_attention_v_channels: Optional[int] = None
    num_self_attention_layers_per_block: int = 8
    num_self_attention_blocks: int = 1
    first_self_attention_block_shared: bool = True
    self_attention_widening_factor: int = 1
    dropout: float = 0.0
    init_scale: float = 0.02
    freeze: bool = False

    def base_kwargs(self, exclude=("freeze",)) -> Dict[str, Any]:
        return _base_kwargs(self, EncoderConfig, exclude)


@dataclass
class DecoderConfig:
    """Perceiver IO decoder hyperparameters (reference ``config.py:28-40``)."""

    num_cross_attention_heads: int = 8
    num_cross_attention_qk_channels: Optional[int] = None
    num_cross_attention_v_channels: Optional[int] = None
    cross_attention_widening_factor: int = 1
    cross_attention_residual: bool = True
    dropout: float = 0.0
    init_scale: float = 0.02
    freeze: bool = False

    def base_kwargs(self, exclude=("freeze",)) -> Dict[str, Any]:
        return _base_kwargs(self, DecoderConfig, exclude)


@dataclass
class ClassificationDecoderConfig(DecoderConfig):
    num_output_queries: int = 1
    num_output_query_channels: int = 256
    num_classes: int = 100


E = TypeVar("E", bound=EncoderConfig)
D = TypeVar("D", bound=DecoderConfig)


@dataclass
class PerceiverIOConfig(Generic[E, D]):
    """Container pairing an encoder and decoder config (reference
    ``config.py:54-61``). ``activation_checkpointing`` maps to ``jax.remat``
    on attention layers; CPU offload maps to a remat policy with host
    offloading."""

    encoder: E
    decoder: D
    num_latents: int
    num_latent_channels: int
    activation_checkpointing: bool = False
    activation_offloading: bool = False


@dataclass
class PerceiverARConfig:
    """Perceiver AR hyperparameters (reference ``config.py:64-78``)."""

    num_heads: int = 8
    max_heads_parallel: Optional[int] = None
    num_self_attention_layers: int = 8
    self_attention_widening_factor: int = 4
    cross_attention_widening_factor: int = 4
    cross_attention_dropout: float = 0.5
    post_attention_dropout: float = 0.0
    residual_dropout: float = 0.0
    activation_checkpointing: bool = False
    activation_offloading: bool = False

    def base_kwargs(self, exclude=()) -> Dict[str, Any]:
        return _base_kwargs(self, PerceiverARConfig, exclude)


def _base_kwargs(config, base_class, exclude) -> Dict[str, Any]:
    base_field_names = [f.name for f in fields(base_class) if f.name not in exclude]
    return {k: v for k, v in asdict(config).items() if k in base_field_names}


# Registry of config dataclasses by class name, for round-tripping nested
# configs whose static field type is a TypeVar (PerceiverIOConfig is
# Generic[E, D] — the concrete encoder/decoder class is only known at
# runtime, so config_to_dict records it under "_type").
_CONFIG_REGISTRY: Dict[str, type] = {}


def register_config(cls):
    """Class decorator: make a config dataclass round-trippable through
    :func:`config_to_dict` / :func:`config_from_dict`."""
    _CONFIG_REGISTRY[cls.__name__] = cls
    return cls


for _cls in (EncoderConfig, DecoderConfig, ClassificationDecoderConfig, PerceiverIOConfig, PerceiverARConfig):
    register_config(_cls)


def config_to_dict(config) -> Dict[str, Any]:
    """Serialize any (possibly nested) config dataclass to plain dicts —
    checkpoint metadata / CLI round-trip. Records the concrete class name
    under ``"_type"`` so nested generic fields rebuild correctly."""
    if dataclasses.is_dataclass(config):
        d = {f.name: config_to_dict(getattr(config, f.name)) for f in fields(config)}
        d["_type"] = type(config).__name__
        return d
    if isinstance(config, (list, tuple)):
        return [config_to_dict(v) for v in config]
    return config


def config_from_dict(cls, d: Dict[str, Any]):
    """Rebuild a config dataclass from :func:`config_to_dict` output.

    ``cls`` is the expected (base) class; an embedded ``"_type"`` naming a
    registered subclass takes precedence.
    """
    type_name = d.get("_type", "")
    if type_name and type_name not in _CONFIG_REGISTRY:
        # Task configs register at their module's import; a checkpoint can be
        # loaded before any model module was touched (e.g. bare
        # ``load_pretrained``) — pull them in once, then retry. This must run
        # even when a fallback ``cls`` is supplied: a stale fallback would
        # silently rebuild the wrong (base) dataclass.
        from perceiver_io_tpu.models import import_task_modules

        import_task_modules()
    target = _CONFIG_REGISTRY.get(type_name, cls)
    if target is None:
        raise ValueError(f"unknown config type {type_name!r} (not registered)")
    kwargs = {}
    for f in fields(target):
        if f.name not in d:
            continue
        v = d[f.name]
        if isinstance(v, dict) and "_type" in v:
            kwargs[f.name] = config_from_dict(None, v)
        else:
            kwargs[f.name] = tuple(v) if isinstance(v, list) else v
    return target(**kwargs)
