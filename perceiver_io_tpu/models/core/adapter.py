"""Input/output adapters and query providers.

Capability parity with reference ``perceiver/model/core/adapter.py:8-83``.
Adapters transform task-specific input into the generic ``(B, M, C)`` encoder
input; output adapters map decoder cross-attention output to task output;
query providers supply the trainable latent / output query arrays.
"""
from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax.numpy as jnp

from perceiver_io_tpu.ops.position import frequency_position_encoding, positions


class InputAdapter(nn.Module):
    """Base class: subclasses must expose ``num_input_channels``."""

    @property
    def num_input_channels(self) -> int:
        raise NotImplementedError


class TrainableQueryProvider(nn.Module):
    """Learnable query array — the latent array in encoders and the output
    query array in most decoders (reference ``adapter.py:63-83``)."""

    num_queries: int
    num_query_channels_: int
    init_scale: float = 0.02
    dtype: jnp.dtype = jnp.float32

    @property
    def num_query_channels(self) -> int:
        return self.num_query_channels_

    @nn.compact
    def __call__(self, x: Optional[jnp.ndarray] = None) -> jnp.ndarray:
        query = self.param(
            "query",
            nn.initializers.normal(stddev=self.init_scale),
            (self.num_queries, self.num_query_channels_),
        )
        return query[None].astype(self.dtype)


class ClassificationOutputAdapter(nn.Module):
    """Linear head over output queries; squeezes a singleton query dim
    (reference ``adapter.py:39-49``)."""

    num_classes: int
    num_output_query_channels: int
    init_scale: float = 0.02
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        x = nn.Dense(
            self.num_classes,
            kernel_init=nn.initializers.normal(stddev=self.init_scale),
            bias_init=nn.initializers.zeros,
            dtype=self.dtype,
            name="linear",
        )(x)
        if x.shape[1] == 1:
            x = x[:, 0]
        return x


def rotary_frequencies(x_shape, rotated_channels_per_head: int, abs_pos=None):
    """Frequency position encoding used to build rotary embeddings for
    Perceiver AR (the ``RotarySupport`` mixin, reference ``adapter.py:22-32``).

    :param x_shape: ``(b, n)`` token-grid shape.
    :param abs_pos: optional precomputed ``(b, n)`` positions (e.g. shifted
        for left padding); defaults to ``0..n-1``.
    :return: ``(b, n, rotated_channels_per_head)`` angles.
    """
    b, n = x_shape
    if abs_pos is None:
        abs_pos = positions(b, n)
    return frequency_position_encoding(abs_pos, rotated_channels_per_head)
