"""Core Perceiver runtime — attention modules, Perceiver IO encoder/decoder,
and Perceiver AR — as flax linen modules.

Capability parity with reference ``perceiver/model/core/modules.py``; built
TPU-first:

- all control flow is static (python loops over static layer counts unroll at
  trace time; weight sharing is module reuse, which XLA sees as the same
  parameters applied at several depths);
- attention math lives in :func:`perceiver_io_tpu.ops.attention.dot_product_attention`
  (fp32 softmax, Pallas flash dispatch);
- activation checkpointing maps to ``flax.linen.remat`` over attention layers
  (the fairscale ``checkpoint_wrapper`` equivalent, reference
  ``modules.py:347-348,452-454``);
- dtype policy: parameters are fp32; ``dtype`` selects the computation dtype
  (bf16 on TPU keeps the MXU fed at full rate).

Dropout rngs: ``'dropout'`` for attention/residual dropout, ``'prefix'`` for
Perceiver AR cross-attention (prefix) dropout. Pass ``deterministic=True``
for inference.
"""
from __future__ import annotations

from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from perceiver_io_tpu.models.core.adapter import TrainableQueryProvider
from perceiver_io_tpu.ops.attention import dot_product_attention
from perceiver_io_tpu.ops.position import RotaryEmbedding, positions

# torch defaults, required for numerical parity with the reference.
LAYER_NORM_EPS = 1e-5


def _dense(features: int, use_bias: bool, init_scale: float, dtype, name: str) -> nn.Dense:
    return nn.Dense(
        features,
        use_bias=use_bias,
        kernel_init=nn.initializers.normal(stddev=init_scale),
        bias_init=nn.initializers.zeros,
        dtype=dtype,
        name=name,
    )


def _layer_norm(dtype, name: str) -> nn.LayerNorm:
    # use_fast_variance=False: two-pass variance matches torch numerically
    return nn.LayerNorm(epsilon=LAYER_NORM_EPS, dtype=dtype, name=name, use_fast_variance=False)


def fused_qkv_enabled() -> bool:
    """``PERCEIVER_FUSED_QKV=1`` merges same-input q/k/v (self-attention) and
    k/v (cross-attention) projections into single wider matmuls. Like the
    ``PERCEIVER_FLASH_*`` knobs this is read at trace time, so a toggle only
    affects traces captured afterwards (the tuning sweep isolates each
    setting in a subprocess). The generation/beam/slot executor caches fold
    every trace-time knob into their cache keys
    (:func:`trace_env_fingerprint`), so a mid-process toggle rebuilds those
    executors instead of silently serving a program traced under the other
    setting. Default off until measured on hardware; exactness vs the
    unfused path is tested either way."""
    import os

    return os.environ.get("PERCEIVER_FUSED_QKV", "0") == "1"


def trace_env_fingerprint() -> tuple:
    """Every trace-time env knob that changes the compiled program, as one
    hashable tuple for executor cache keys (``generate._generation_executor``,
    ``beam._beam_executor``, ``serving.slots``). Folding ALL of them in —
    not just ``PERCEIVER_FUSED_QKV`` — means a mid-process toggle of a
    flash knob rebuilds the executor instead of silently no-op'ing
    (ADVICE r5 on the process-start-only footgun). Values are normalized to
    what the consumers parse (``attention._flash_eligible``,
    ``flash_attention._candidates`` — without importing the pallas module,
    which only loads on TPU), so semantically identical settings (unset vs
    ``"0"``, an unparseable override vs the default) share one key instead
    of retracing. Plain ``jax.jit`` call sites (train steps) still read
    these at trace time only; the tuning sweep's subprocess isolation
    remains the contract there."""
    import os

    try:
        min_kv = int(os.environ.get("PERCEIVER_FLASH_MIN_KV", "0"))
    except ValueError:
        min_kv = 0
    raw = os.environ.get("PERCEIVER_FLASH_BLOCKS", "")
    try:
        blocks = tuple(int(x) for x in raw.split(",")) if raw else ()
    except ValueError:
        blocks = ()
    if not (blocks and all(b > 0 and b % 128 == 0 for b in blocks)):
        # mirror flash_attention._candidates' validation (LANES == 128):
        # overrides it would ignore must fingerprint like the unset default
        blocks = ()
    # PERCEIVER_RAGGED_KERNEL switches the slot engine's paged attends
    # between the gather reference and the ragged Pallas kernel at trace
    # time (ops/ragged_attention.py; interpreted off-TPU) — same
    # mid-process-toggle contract as the flash knobs
    ragged_kernel = os.environ.get("PERCEIVER_RAGGED_KERNEL", "0") == "1"
    return (fused_qkv_enabled(), min_kv, blocks, ragged_kernel)


def _remat_policy(offload: bool):
    """Remat saving policy for activation checkpointing. ``offload=False``
    saves nothing (pure rematerialization). ``offload=True`` is the TPU-native
    equivalent of the reference's ``checkpoint_wrapper(offload_to_cpu=True)``
    (reference ``modules.py:347-348``): the layer-boundary inputs (tagged
    ``remat_layer_input`` via ``checkpoint_name``) are saved but moved to
    pinned host memory, everything else is rematerialized — HBM holds no
    per-layer activations between forward and backward."""
    if not offload:
        return None
    return jax.checkpoint_policies.save_and_offload_only_these_names(
        names_which_can_be_saved=[],
        names_which_can_be_offloaded=["remat_layer_input"],
        offload_src="device",
        offload_dst="pinned_host",
    )


class MultiHeadAttention(nn.Module):
    """Multi-head attention (Perceiver IO paper App. E) with optional rotary
    embeddings and causal attention over right-aligned q/kv.

    Reference: ``perceiver/model/core/modules.py:19-154``.
    """

    num_heads: int
    num_q_input_channels: int
    num_kv_input_channels: int
    num_qk_channels: Optional[int] = None
    num_v_channels: Optional[int] = None
    num_output_channels: Optional[int] = None
    max_heads_parallel: Optional[int] = None
    causal_attention: bool = False
    dropout: float = 0.0
    qkv_bias: bool = True
    out_bias: bool = True
    init_scale: float = 0.02
    dtype: Any = jnp.float32
    attention_impl: str = "auto"

    def _channels(self) -> Tuple[int, int, int]:
        qk = self.num_qk_channels or self.num_q_input_channels
        v = self.num_v_channels or qk
        out = self.num_output_channels or self.num_q_input_channels
        if qk % self.num_heads != 0:
            raise ValueError("num_qk_channels must be divisible by num_heads")
        if v % self.num_heads != 0:
            raise ValueError("num_v_channels must be divisible by num_heads")
        return qk, v, out

    def setup(self):
        qk, v, out = self._channels()
        self.q_proj = _dense(qk, self.qkv_bias, self.init_scale, self.dtype, "q_proj")
        self.k_proj = _dense(qk, self.qkv_bias, self.init_scale, self.dtype, "k_proj")
        self.v_proj = _dense(v, self.qkv_bias, self.init_scale, self.dtype, "v_proj")
        self.o_proj = _dense(out, self.out_bias, self.init_scale, self.dtype, "o_proj")

    def _split_heads(self, x: jnp.ndarray) -> jnp.ndarray:
        b, n, _ = x.shape
        return x.reshape(b, n, self.num_heads, -1).transpose(0, 2, 1, 3)

    def _merge_heads(self, x: jnp.ndarray) -> jnp.ndarray:
        b, h, n, c = x.shape
        return x.transpose(0, 2, 1, 3).reshape(b, n, h * c)

    def project_q(self, x_q: jnp.ndarray, rot_pos_emb: Optional[RotaryEmbedding] = None) -> jnp.ndarray:
        """(b, n, Dq) -> scaled + rotated (b, h, n, ck). Exposed for the
        KV-cache decode loop."""
        return self._finish_q(self.q_proj(x_q), rot_pos_emb)

    def _finish_q(
        self, q_flat: jnp.ndarray, rot_pos_emb: Optional[RotaryEmbedding]
    ) -> jnp.ndarray:
        """Shared post-projection q path (fused and unfused): split heads,
        scale, then rotate — the reference's order of operations."""
        qk, _, _ = self._channels()
        q = self._split_heads(q_flat) * ((qk // self.num_heads) ** -0.5)
        if rot_pos_emb is not None:
            q = rot_pos_emb.rotate(q)
        return q

    def _finish_k(
        self, k_flat: jnp.ndarray, rot_pos_emb: Optional[RotaryEmbedding]
    ) -> jnp.ndarray:
        k = self._split_heads(k_flat)
        if rot_pos_emb is not None:
            k = rot_pos_emb.rotate(k)
        return k

    def project_kv(
        self, x_kv: jnp.ndarray, rot_pos_emb: Optional[RotaryEmbedding] = None
    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """(b, n, Dkv) -> rotated (b, h, n, ck), (b, h, n, cv). Exposed for
        the KV-cache decode loop (keys are cached post-rotation; rotary is
        relative so a global position offset cancels in attention scores)."""
        if fused_qkv_enabled() and not self.is_initializing():
            # One (n, Dkv) x (Dkv, ck+cv) matmul instead of two: k and v
            # always project from the same (often window-length) input, and
            # a single wider matmul keeps the MXU busier per dispatch. The
            # param tree is untouched; the concat of the (loop-varying)
            # kernels re-executes every step — ~2D² extra HBM traffic per
            # layer against the n·D-dominated matmul reads, negligible for
            # n >> D but part of what the sweep measures. Mathematically
            # identical to the separate projections (same per-element dot
            # products).
            kv = self._fused_dense((self.k_proj, self.v_proj), x_kv)
            qk, _, _ = self._channels()
            k_flat, v_flat = kv[..., :qk], kv[..., qk:]
        else:
            k_flat, v_flat = self.k_proj(x_kv), self.v_proj(x_kv)
        return self._finish_k(k_flat, rot_pos_emb), self._split_heads(v_flat)

    def _fused_dense(self, projs, x: jnp.ndarray) -> jnp.ndarray:
        """Apply several same-input Dense submodules as one matmul over their
        output-axis-concatenated kernels (numerics preserved: computation
        dtype and bias handling mirror ``nn.Dense``)."""
        ws = [p.variables["params"]["kernel"] for p in projs]
        w = jnp.concatenate([jnp.asarray(w, self.dtype) for w in ws], axis=1)
        out = jnp.dot(x.astype(self.dtype), w)
        if self.qkv_bias:
            bs = [p.variables["params"]["bias"] for p in projs]
            out = out + jnp.concatenate(
                [jnp.asarray(b, self.dtype) for b in bs], axis=0
            )
        return out

    def project_out(self, o: jnp.ndarray) -> jnp.ndarray:
        """(b, h, n, cv) raw attention -> merged + output-projected
        (b, n, out). Exposed for attention implementations that bypass
        :meth:`attend` (the ragged paged kernel returns raw per-head
        attention; this is the projection ``attend`` would have applied)."""
        return self.o_proj(self._merge_heads(o))

    def attend(
        self,
        q: jnp.ndarray,
        k: jnp.ndarray,
        v: jnp.ndarray,
        pad_mask: Optional[jnp.ndarray] = None,
        deterministic: bool = True,
    ) -> jnp.ndarray:
        """Attention + output projection over pre-projected heads."""
        dropout_rng = None
        if not deterministic and self.dropout > 0.0:
            dropout_rng = self.make_rng("dropout")
        o = dot_product_attention(
            q,
            k,
            v,
            pad_mask=pad_mask,
            causal=self.causal_attention,
            dropout_rate=0.0 if deterministic else self.dropout,
            dropout_rng=dropout_rng,
            max_heads_parallel=self.max_heads_parallel,
            impl=self.attention_impl,
        )
        return self.project_out(o)

    def __call__(
        self,
        x_q: jnp.ndarray,
        x_kv: jnp.ndarray,
        pad_mask: Optional[jnp.ndarray] = None,
        rot_pos_emb_q: Optional[RotaryEmbedding] = None,
        rot_pos_emb_k: Optional[RotaryEmbedding] = None,
        deterministic: bool = True,
    ) -> jnp.ndarray:
        if (
            fused_qkv_enabled()
            and x_q is x_kv  # self-attention: one source feeds q, k and v
            and not self.is_initializing()
        ):
            qk, _, _ = self._channels()
            qkv = self._fused_dense((self.q_proj, self.k_proj, self.v_proj), x_q)
            q = self._finish_q(qkv[..., :qk], rot_pos_emb_q)
            k = self._finish_k(qkv[..., qk:2 * qk], rot_pos_emb_k)
            v = self._split_heads(qkv[..., 2 * qk:])
            return self.attend(q, k, v, pad_mask=pad_mask, deterministic=deterministic)
        q = self.project_q(x_q, rot_pos_emb_q)
        k, v = self.project_kv(x_kv, rot_pos_emb_k)
        return self.attend(q, k, v, pad_mask=pad_mask, deterministic=deterministic)


class CrossAttention(nn.Module):
    """Pre-layer-norm cross-attention with the Perceiver-AR ``x_kv_prefix``
    path: keys/values = concat(prefix, query) so latents self-attend at the
    sequence tail (reference ``modules.py:157-203``)."""

    num_heads: int
    num_q_input_channels: int
    num_kv_input_channels: int
    num_qk_channels: Optional[int] = None
    num_v_channels: Optional[int] = None
    max_heads_parallel: Optional[int] = None
    causal_attention: bool = False
    dropout: float = 0.0
    qkv_bias: bool = True
    out_bias: bool = True
    init_scale: float = 0.02
    dtype: Any = jnp.float32
    attention_impl: str = "auto"

    def setup(self):
        self.q_norm = _layer_norm(self.dtype, "q_norm")
        self.kv_norm = _layer_norm(self.dtype, "kv_norm")
        self.attention = MultiHeadAttention(
            num_heads=self.num_heads,
            num_q_input_channels=self.num_q_input_channels,
            num_kv_input_channels=self.num_kv_input_channels,
            num_qk_channels=self.num_qk_channels,
            num_v_channels=self.num_v_channels,
            max_heads_parallel=self.max_heads_parallel,
            causal_attention=self.causal_attention,
            dropout=self.dropout,
            qkv_bias=self.qkv_bias,
            out_bias=self.out_bias,
            init_scale=self.init_scale,
            dtype=self.dtype,
            attention_impl=self.attention_impl,
            name="attention",
        )

    def __call__(
        self,
        x_q: jnp.ndarray,
        x_kv: Optional[jnp.ndarray] = None,
        x_kv_prefix: Optional[jnp.ndarray] = None,
        pad_mask: Optional[jnp.ndarray] = None,
        rot_pos_emb_q: Optional[RotaryEmbedding] = None,
        rot_pos_emb_k: Optional[RotaryEmbedding] = None,
        deterministic: bool = True,
    ) -> jnp.ndarray:
        x_q = self.q_norm(x_q)
        if x_kv is None:
            x_kv_prefix = self.kv_norm(x_kv_prefix)
            x_kv = jnp.concatenate([x_kv_prefix, x_q], axis=1)
        else:
            x_kv = self.kv_norm(x_kv)
        return self.attention(
            x_q,
            x_kv,
            pad_mask=pad_mask,
            rot_pos_emb_q=rot_pos_emb_q,
            rot_pos_emb_k=rot_pos_emb_k,
            deterministic=deterministic,
        )


class SelfAttention(nn.Module):
    """Pre-layer-norm self-attention (reference ``modules.py:206-238``)."""

    num_heads: int
    num_channels: int
    num_qk_channels: Optional[int] = None
    num_v_channels: Optional[int] = None
    max_heads_parallel: Optional[int] = None
    causal_attention: bool = False
    dropout: float = 0.0
    qkv_bias: bool = True
    out_bias: bool = True
    init_scale: float = 0.02
    dtype: Any = jnp.float32
    attention_impl: str = "auto"

    def setup(self):
        self.norm = _layer_norm(self.dtype, "norm")
        self.attention = MultiHeadAttention(
            num_heads=self.num_heads,
            num_q_input_channels=self.num_channels,
            num_kv_input_channels=self.num_channels,
            num_qk_channels=self.num_qk_channels,
            num_v_channels=self.num_v_channels,
            max_heads_parallel=self.max_heads_parallel,
            causal_attention=self.causal_attention,
            dropout=self.dropout,
            qkv_bias=self.qkv_bias,
            out_bias=self.out_bias,
            init_scale=self.init_scale,
            dtype=self.dtype,
            attention_impl=self.attention_impl,
            name="attention",
        )

    def __call__(
        self,
        x: jnp.ndarray,
        pad_mask: Optional[jnp.ndarray] = None,
        rot_pos_emb: Optional[RotaryEmbedding] = None,
        deterministic: bool = True,
    ) -> jnp.ndarray:
        x = self.norm(x)
        return self.attention(
            x,
            x,
            pad_mask=pad_mask,
            rot_pos_emb_q=rot_pos_emb,
            rot_pos_emb_k=rot_pos_emb,
            deterministic=deterministic,
        )


class MLP(nn.Module):
    """LayerNorm -> Dense(widening*ch) -> GELU(exact) -> Dense(ch)
    (reference ``modules.py:353-360``)."""

    num_channels: int
    widening_factor: int
    bias: bool = True
    init_scale: float = 0.02
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        x = _layer_norm(self.dtype, "norm")(x)
        x = _dense(self.widening_factor * self.num_channels, self.bias, self.init_scale, self.dtype, "hidden")(x)
        x = nn.gelu(x, approximate=False)
        x = _dense(self.num_channels, self.bias, self.init_scale, self.dtype, "out")(x)
        return x


class _ResidualDropout(nn.Module):
    """Dropout on the residual branch before adding (reference
    ``utils.py:17-24``)."""

    rate: float

    @nn.compact
    def __call__(self, branch: jnp.ndarray, x: jnp.ndarray, deterministic: bool = True) -> jnp.ndarray:
        branch = nn.Dropout(rate=self.rate, name="drop")(branch, deterministic=deterministic)
        return branch + x


class CrossAttentionLayer(nn.Module):
    """Residual cross-attention + residual MLP (reference ``modules.py:241-274``)."""

    num_heads: int
    num_q_input_channels: int
    num_kv_input_channels: int
    num_qk_channels: Optional[int] = None
    num_v_channels: Optional[int] = None
    max_heads_parallel: Optional[int] = None
    causal_attention: bool = False
    widening_factor: int = 1
    dropout: float = 0.0
    residual_dropout: float = 0.0
    attention_residual: bool = True
    qkv_bias: bool = True
    out_bias: bool = True
    mlp_bias: bool = True
    init_scale: float = 0.02
    dtype: Any = jnp.float32
    attention_impl: str = "auto"

    def setup(self):
        self.cross_attn = CrossAttention(
            num_heads=self.num_heads,
            num_q_input_channels=self.num_q_input_channels,
            num_kv_input_channels=self.num_kv_input_channels,
            num_qk_channels=self.num_qk_channels,
            num_v_channels=self.num_v_channels,
            max_heads_parallel=self.max_heads_parallel,
            causal_attention=self.causal_attention,
            dropout=self.dropout,
            qkv_bias=self.qkv_bias,
            out_bias=self.out_bias,
            init_scale=self.init_scale,
            dtype=self.dtype,
            attention_impl=self.attention_impl,
            name="cross_attn",
        )
        self.mlp = MLP(
            num_channels=self.num_q_input_channels,
            widening_factor=self.widening_factor,
            bias=self.mlp_bias,
            init_scale=self.init_scale,
            dtype=self.dtype,
            name="mlp",
        )
        self.attn_residual = _ResidualDropout(self.residual_dropout, name="attn_residual")
        self.mlp_residual = _ResidualDropout(self.residual_dropout, name="mlp_residual")

    def __call__(
        self,
        x_q: jnp.ndarray,
        x_kv: Optional[jnp.ndarray] = None,
        x_kv_prefix: Optional[jnp.ndarray] = None,
        pad_mask: Optional[jnp.ndarray] = None,
        rot_pos_emb_q: Optional[RotaryEmbedding] = None,
        rot_pos_emb_k: Optional[RotaryEmbedding] = None,
        deterministic: bool = True,
    ) -> jnp.ndarray:
        x_q = checkpoint_name(x_q, "remat_layer_input")
        attn_out = self.cross_attn(
            x_q,
            x_kv=x_kv,
            x_kv_prefix=x_kv_prefix,
            pad_mask=pad_mask,
            rot_pos_emb_q=rot_pos_emb_q,
            rot_pos_emb_k=rot_pos_emb_k,
            deterministic=deterministic,
        )
        if self.attention_residual:
            x = self.attn_residual(attn_out, x_q, deterministic=deterministic)
        else:
            x = attn_out
        return self.mlp_residual(self.mlp(x), x, deterministic=deterministic)


class SelfAttentionLayer(nn.Module):
    """Residual self-attention + residual MLP (reference ``modules.py:277-307``)."""

    num_heads: int
    num_channels: int
    num_qk_channels: Optional[int] = None
    num_v_channels: Optional[int] = None
    max_heads_parallel: Optional[int] = None
    causal_attention: bool = False
    widening_factor: int = 1
    dropout: float = 0.0
    residual_dropout: float = 0.0
    qkv_bias: bool = True
    out_bias: bool = True
    mlp_bias: bool = True
    init_scale: float = 0.02
    dtype: Any = jnp.float32
    attention_impl: str = "auto"

    def setup(self):
        self.self_attn = SelfAttention(
            num_heads=self.num_heads,
            num_channels=self.num_channels,
            num_qk_channels=self.num_qk_channels,
            num_v_channels=self.num_v_channels,
            max_heads_parallel=self.max_heads_parallel,
            causal_attention=self.causal_attention,
            dropout=self.dropout,
            qkv_bias=self.qkv_bias,
            out_bias=self.out_bias,
            init_scale=self.init_scale,
            dtype=self.dtype,
            attention_impl=self.attention_impl,
            name="self_attn",
        )
        self.mlp = MLP(
            num_channels=self.num_channels,
            widening_factor=self.widening_factor,
            bias=self.mlp_bias,
            init_scale=self.init_scale,
            dtype=self.dtype,
            name="mlp",
        )
        self.attn_residual = _ResidualDropout(self.residual_dropout, name="attn_residual")
        self.mlp_residual = _ResidualDropout(self.residual_dropout, name="mlp_residual")

    def __call__(
        self,
        x: jnp.ndarray,
        pad_mask: Optional[jnp.ndarray] = None,
        rot_pos_emb: Optional[RotaryEmbedding] = None,
        deterministic: bool = True,
    ) -> jnp.ndarray:
        x = checkpoint_name(x, "remat_layer_input")
        attn_out = self.self_attn(x, pad_mask=pad_mask, rot_pos_emb=rot_pos_emb, deterministic=deterministic)
        x = self.attn_residual(attn_out, x, deterministic=deterministic)
        return self.mlp_residual(self.mlp(x), x, deterministic=deterministic)


class SelfAttentionBlock(nn.Module):
    """Stack of self-attention layers; ``activation_checkpointing`` remats
    each layer (fairscale ``checkpoint_wrapper`` equivalent, reference
    ``modules.py:310-350``).

    ``rotary_all_layers=False`` replicates a load-bearing reference behavior:
    its custom ``Sequential`` forwards kwargs only to the *first* submodule
    (reference ``utils.py:4-14``), so rotary embeddings reach only the first
    self-attention layer of a block — Perceiver AR checkpoints are trained
    with that semantics. Set True for rotary at every layer. ``pad_mask`` is
    always forwarded to every layer (no reference call site passes one to a
    block, so parity is unaffected)."""

    num_layers: int
    num_heads: int
    num_channels: int
    num_qk_channels: Optional[int] = None
    num_v_channels: Optional[int] = None
    max_heads_parallel: Optional[int] = None
    causal_attention: bool = False
    widening_factor: int = 1
    dropout: float = 0.0
    residual_dropout: float = 0.0
    activation_checkpointing: bool = False
    activation_offloading: bool = False
    rotary_all_layers: bool = False
    qkv_bias: bool = True
    out_bias: bool = True
    mlp_bias: bool = True
    init_scale: float = 0.02
    dtype: Any = jnp.float32
    attention_impl: str = "auto"

    def setup(self):
        layer_cls = SelfAttentionLayer
        if self.activation_checkpointing:
            # argnums include the module as 0: (x=1, pad_mask=2, rot_pos_emb=3, deterministic=4)
            layer_cls = nn.remat(
                SelfAttentionLayer,
                static_argnums=(4,),
                policy=_remat_policy(self.activation_offloading),
            )
        self.layers = [
            layer_cls(
                num_heads=self.num_heads,
                num_channels=self.num_channels,
                num_qk_channels=self.num_qk_channels,
                num_v_channels=self.num_v_channels,
                max_heads_parallel=self.max_heads_parallel,
                causal_attention=self.causal_attention,
                widening_factor=self.widening_factor,
                dropout=self.dropout,
                residual_dropout=self.residual_dropout,
                qkv_bias=self.qkv_bias,
                out_bias=self.out_bias,
                mlp_bias=self.mlp_bias,
                init_scale=self.init_scale,
                dtype=self.dtype,
                attention_impl=self.attention_impl,
                name=f"layers_{i}",
            )
            for i in range(self.num_layers)
        ]

    def __call__(
        self,
        x: jnp.ndarray,
        pad_mask: Optional[jnp.ndarray] = None,
        rot_pos_emb: Optional[RotaryEmbedding] = None,
        deterministic: bool = True,
    ) -> jnp.ndarray:
        for i, layer in enumerate(self.layers):
            rot = rot_pos_emb if (i == 0 or self.rotary_all_layers) else None
            x = layer(x, pad_mask, rot, deterministic)
        return x


class PerceiverEncoder(nn.Module):
    """Perceiver IO encoder: a trainable latent array cross-attends to the
    adapted input, followed by self-attention blocks; supports repeated
    cross-attention with weight-sharing rules (reference
    ``modules.py:363-513``).

    Weight sharing is module reuse: ``cross_attn_1``/``self_attn_1`` are
    reapplied at later depths unless an extra unshared module is configured —
    one parameter set appears once in the pytree regardless of how many times
    it is applied, which keeps checkpoint layout 1:1 with the reference.
    """

    input_adapter: nn.Module
    num_latents: int
    num_latent_channels: int
    num_cross_attention_heads: int = 4
    num_cross_attention_qk_channels: Optional[int] = None
    num_cross_attention_v_channels: Optional[int] = None
    num_cross_attention_layers: int = 1
    first_cross_attention_layer_shared: bool = False
    cross_attention_widening_factor: int = 1
    num_self_attention_heads: int = 4
    num_self_attention_qk_channels: Optional[int] = None
    num_self_attention_v_channels: Optional[int] = None
    num_self_attention_layers_per_block: int = 6
    num_self_attention_blocks: int = 1
    first_self_attention_block_shared: bool = True
    self_attention_widening_factor: int = 1
    dropout: float = 0.0
    residual_dropout: float = 0.0
    init_scale: float = 0.02
    activation_checkpointing: bool = False
    activation_offloading: bool = False
    dtype: Any = jnp.float32
    attention_impl: str = "auto"

    @property
    def extra_cross_attention_layer(self) -> bool:
        return self.num_cross_attention_layers > 1 and not self.first_cross_attention_layer_shared

    @property
    def extra_self_attention_block(self) -> bool:
        return self.num_self_attention_blocks > 1 and not self.first_self_attention_block_shared

    def setup(self):
        if self.num_cross_attention_layers <= 0:
            raise ValueError("num_cross_attention_layers must be > 0")
        if self.num_self_attention_blocks <= 0:
            raise ValueError("num_self_attention_blocks must be > 0")
        if self.num_cross_attention_layers > self.num_self_attention_blocks:
            raise ValueError("num_cross_attention_layers must be <= num_self_attention_blocks")

        self.latent_provider = TrainableQueryProvider(
            num_queries=self.num_latents,
            num_query_channels_=self.num_latent_channels,
            init_scale=self.init_scale,
            dtype=self.dtype,
            name="latent_provider",
        )

        def cross_attn(name):
            cls = CrossAttentionLayer
            if self.activation_checkpointing:
                # argnums include the module as 0: (x_q=1, x_kv=2, x_kv_prefix=3, pad_mask=4,
                # rot_q=5, rot_k=6, deterministic=7)
                cls = nn.remat(
                    CrossAttentionLayer,
                    static_argnums=(7,),
                    policy=_remat_policy(self.activation_offloading),
                )
            return cls(
                num_heads=self.num_cross_attention_heads,
                num_q_input_channels=self.num_latent_channels,
                num_kv_input_channels=self.input_adapter.num_input_channels,
                num_qk_channels=self.num_cross_attention_qk_channels,
                num_v_channels=self.num_cross_attention_v_channels,
                widening_factor=self.cross_attention_widening_factor,
                dropout=self.dropout,
                residual_dropout=self.residual_dropout,
                init_scale=self.init_scale,
                dtype=self.dtype,
                attention_impl=self.attention_impl,
                name=name,
            )

        def self_attn(name):
            return SelfAttentionBlock(
                num_layers=self.num_self_attention_layers_per_block,
                num_heads=self.num_self_attention_heads,
                num_channels=self.num_latent_channels,
                num_qk_channels=self.num_self_attention_qk_channels,
                num_v_channels=self.num_self_attention_v_channels,
                widening_factor=self.self_attention_widening_factor,
                dropout=self.dropout,
                residual_dropout=self.residual_dropout,
                activation_checkpointing=self.activation_checkpointing,
                activation_offloading=self.activation_offloading,
                init_scale=self.init_scale,
                dtype=self.dtype,
                attention_impl=self.attention_impl,
                name=name,
            )

        self.cross_attn_1 = cross_attn("cross_attn_1")
        self.self_attn_1 = self_attn("self_attn_1")
        if self.extra_cross_attention_layer:
            self.cross_attn_n = cross_attn("cross_attn_n")
        if self.extra_self_attention_block:
            self.self_attn_n = self_attn("self_attn_n")

    def __call__(
        self,
        x: jnp.ndarray,
        pad_mask: Optional[jnp.ndarray] = None,
        return_adapted_input: bool = False,
        deterministic: bool = True,
    ):
        x_adapted = self.input_adapter(x)
        b = x_adapted.shape[0]
        x_latent = jnp.broadcast_to(
            self.latent_provider(), (b, self.num_latents, self.num_latent_channels)
        )

        # Positional calls: rematted modules index static_argnums positionally.
        x_latent = self.cross_attn_1(x_latent, x_adapted, None, pad_mask, None, None, deterministic)
        x_latent = self.self_attn_1(x_latent, None, None, deterministic)

        cross_attn_n = self.cross_attn_n if self.extra_cross_attention_layer else self.cross_attn_1
        self_attn_n = self.self_attn_n if self.extra_self_attention_block else self.self_attn_1

        for i in range(1, self.num_self_attention_blocks):
            if i < self.num_cross_attention_layers:
                x_latent = cross_attn_n(x_latent, x_adapted, None, pad_mask, None, None, deterministic)
            x_latent = self_attn_n(x_latent, None, None, deterministic)

        if return_adapted_input:
            return x_latent, x_adapted
        return x_latent


class PerceiverDecoder(nn.Module):
    """Perceiver IO decoder: output queries cross-attend to latents; optional
    non-residual cross-attention (MLM); output adapter maps to task output
    (reference ``modules.py:516-581``).

    ``output_query_provider`` may be None, in which case decoder queries are
    the adapted encoder input passed via ``x_adapted`` (optical flow,
    reference ``backend.py:124,135-137``).
    """

    output_adapter: nn.Module
    output_query_provider: Optional[nn.Module]
    num_latent_channels: int
    num_output_query_channels: int
    num_cross_attention_heads: int = 4
    num_cross_attention_qk_channels: Optional[int] = None
    num_cross_attention_v_channels: Optional[int] = None
    cross_attention_widening_factor: int = 1
    cross_attention_residual: bool = True
    dropout: float = 0.0
    init_scale: float = 0.02
    activation_checkpointing: bool = False
    activation_offloading: bool = False
    dtype: Any = jnp.float32
    attention_impl: str = "auto"

    def setup(self):
        cls = CrossAttentionLayer
        if self.activation_checkpointing:
            cls = nn.remat(
                CrossAttentionLayer,
                static_argnums=(7,),
                policy=_remat_policy(self.activation_offloading),
            )
        self.cross_attn = cls(
            num_heads=self.num_cross_attention_heads,
            num_q_input_channels=self.num_output_query_channels,
            num_kv_input_channels=self.num_latent_channels,
            num_qk_channels=self.num_cross_attention_qk_channels,
            num_v_channels=self.num_cross_attention_v_channels,
            widening_factor=self.cross_attention_widening_factor,
            attention_residual=self.cross_attention_residual,
            dropout=self.dropout,
            init_scale=self.init_scale,
            dtype=self.dtype,
            attention_impl=self.attention_impl,
            name="cross_attn",
        )

    def __call__(
        self,
        x_latent: jnp.ndarray,
        x_adapted: Optional[jnp.ndarray] = None,
        deterministic: bool = True,
        **adapter_kwargs,
    ) -> jnp.ndarray:
        if self.output_query_provider is not None:
            output_query = self.output_query_provider(x_adapted)
            if output_query.shape[0] == 1 and x_latent.shape[0] > 1:
                output_query = jnp.broadcast_to(
                    output_query, (x_latent.shape[0], *output_query.shape[1:])
                )
        else:
            output_query = x_adapted
        output = self.cross_attn(output_query, x_latent, None, None, None, None, deterministic)
        return self.output_adapter(output, **adapter_kwargs)


class PerceiverIO(nn.Module):
    """Encoder + decoder container (reference ``modules.py:584-594``)."""

    encoder: nn.Module
    decoder: nn.Module

    def __call__(self, x, pad_mask=None, deterministic: bool = True, **decoder_kwargs):
        x_latent = self.encoder(x, pad_mask=pad_mask, deterministic=deterministic)
        return self.decoder(x_latent, deterministic=deterministic, **decoder_kwargs)


class PerceiverAR(nn.Module):
    """Perceiver AR (https://arxiv.org/abs/2202.07765): a causal cross-attention
    of latents (the sequence tail) over [prefix ‖ latents], followed by a causal
    self-attention stack over latents, with rotary position embeddings and
    train-time cross-attention (prefix) dropout (reference
    ``modules.py:597-735``).

    ``input_adapter`` must return ``(x_embedded, frq_pos_enc)`` given
    ``(token_ids, abs_pos)`` — the RotarySupport contract
    (reference ``adapter.py:22-32``).

    Prefix dropout keeps a *static* number of positions
    ``keep = prefix_len - int(prefix_len * p)`` chosen by per-row ``top_k``
    over uniform scores with indices re-sorted to preserve order — a
    fixed-shape formulation of the reference's ragged boolean-mask gather
    (``modules.py:697-714``), required for XLA static shapes.
    """

    input_adapter: nn.Module
    num_heads: int = 8
    max_heads_parallel: Optional[int] = None
    num_self_attention_layers: int = 6
    self_attention_widening_factor: int = 4
    cross_attention_widening_factor: int = 4
    cross_attention_dropout: float = 0.5
    post_attention_dropout: float = 0.0
    residual_dropout: float = 0.0
    activation_checkpointing: bool = False
    activation_offloading: bool = False
    init_scale: float = 0.02
    dtype: Any = jnp.float32
    attention_impl: str = "auto"

    def setup(self):
        num_channels = self.input_adapter.num_input_channels
        cls = CrossAttentionLayer
        if self.activation_checkpointing:
            cls = nn.remat(
                CrossAttentionLayer,
                static_argnums=(7,),
                policy=_remat_policy(self.activation_offloading),
            )
        self.cross_attention = cls(
            num_heads=self.num_heads,
            num_q_input_channels=num_channels,
            num_kv_input_channels=num_channels,
            max_heads_parallel=self.max_heads_parallel,
            causal_attention=True,
            widening_factor=self.cross_attention_widening_factor,
            dropout=self.post_attention_dropout,
            residual_dropout=self.residual_dropout,
            qkv_bias=False,
            out_bias=True,
            mlp_bias=False,
            init_scale=self.init_scale,
            dtype=self.dtype,
            attention_impl=self.attention_impl,
            name="cross_attention",
        )
        self.self_attention = SelfAttentionBlock(
            num_layers=self.num_self_attention_layers,
            num_heads=self.num_heads,
            num_channels=num_channels,
            causal_attention=True,
            widening_factor=self.self_attention_widening_factor,
            dropout=self.post_attention_dropout,
            residual_dropout=self.residual_dropout,
            activation_checkpointing=self.activation_checkpointing,
            activation_offloading=self.activation_offloading,
            qkv_bias=False,
            out_bias=False,
            mlp_bias=False,
            init_scale=self.init_scale,
            dtype=self.dtype,
            attention_impl=self.attention_impl,
            name="self_attention",
        )

    def __call__(
        self,
        x: jnp.ndarray,
        prefix_len: int,
        pad_mask: Optional[jnp.ndarray] = None,
        deterministic: bool = True,
    ) -> jnp.ndarray:
        b, n = x.shape
        if not 0 <= prefix_len < n:
            raise ValueError(f"prefix_len ({prefix_len}) out of valid range [0..{n})")

        if pad_mask is None:
            shift = None
        else:
            # caller must ensure that x is left-padded
            shift = pad_mask.sum(axis=1, keepdims=True)

        x, frq_pos_enc = self.input_adapter(x, abs_pos=positions(b, n, shift=shift))

        x_latent = x[:, prefix_len:]
        x_prefix = x[:, :prefix_len]
        frq_pos_enc_latent = frq_pos_enc[:, prefix_len:]
        frq_pos_enc_prefix = frq_pos_enc[:, :prefix_len]
        pad_mask_latent = pad_mask[:, prefix_len:] if pad_mask is not None else None
        pad_mask_prefix = pad_mask[:, :prefix_len] if pad_mask is not None else None

        if not deterministic and prefix_len > 0 and self.cross_attention_dropout > 0.0:
            keep = prefix_len - int(prefix_len * self.cross_attention_dropout)
            rand = jax.random.uniform(self.make_rng("prefix"), (b, prefix_len))
            _, keep_indices = jax.lax.top_k(rand, keep)
            keep_indices = jnp.sort(keep_indices, axis=-1)  # preserve sequence order
            x_prefix = jnp.take_along_axis(x_prefix, keep_indices[..., None], axis=1)
            frq_pos_enc_prefix = jnp.take_along_axis(frq_pos_enc_prefix, keep_indices[..., None], axis=1)
            if pad_mask_prefix is not None:
                pad_mask_prefix = jnp.take_along_axis(pad_mask_prefix, keep_indices, axis=1)

        frq_pos_enc_q = frq_pos_enc_latent
        frq_pos_enc_k = jnp.concatenate([frq_pos_enc_prefix, frq_pos_enc_latent], axis=1)

        if pad_mask is not None:
            pad_mask = jnp.concatenate([pad_mask_prefix, pad_mask_latent], axis=1)

        x_latent = self.cross_attention(
            x_latent,
            None,
            x_prefix,
            pad_mask,
            RotaryEmbedding(frq_pos_enc_q, right_align=True),
            RotaryEmbedding(frq_pos_enc_k, right_align=True),
            deterministic,
        )
        x_latent = self.self_attention(
            x_latent,
            None,
            RotaryEmbedding(frq_pos_enc_latent, right_align=True),
            deterministic,
        )
        return x_latent
