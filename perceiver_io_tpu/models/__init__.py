"""Model layer: flax Perceiver / Perceiver IO / Perceiver AR runtime plus
task backends (SURVEY.md §2.1-2.2).

:func:`model_for_config` resolves a config dataclass to its task model — the
glue that lets a checkpoint dir rebuild its model (the reference embeds the
backend config in checkpoints the same way, ``clm/huggingface.py:15-23``).
"""
from __future__ import annotations

from typing import Any


def import_task_modules() -> None:
    """Import every task-model module — the canonical registration point.
    Importing a module registers its config dataclasses (``register_config``),
    so this is what makes bare checkpoint loading (``load_pretrained`` before
    any model import) able to rebuild configs. ``model_for_config`` routes
    through here too; a new task model only needs adding to this list (its
    dispatch entry below will then fail loudly in tests if forgotten)."""
    import perceiver_io_tpu.models.audio.symbolic  # noqa: F401
    import perceiver_io_tpu.models.text.classifier  # noqa: F401
    import perceiver_io_tpu.models.text.clm  # noqa: F401
    import perceiver_io_tpu.models.text.mlm  # noqa: F401
    import perceiver_io_tpu.models.vision.image_classifier  # noqa: F401
    import perceiver_io_tpu.models.vision.optical_flow  # noqa: F401


def model_for_config(config: Any, *, dtype=None, attention_impl: str = "auto"):
    """Instantiate the task model matching a (nested) config dataclass."""
    import jax.numpy as jnp

    import_task_modules()

    from perceiver_io_tpu.models.audio.symbolic import SymbolicAudioModel, SymbolicAudioModelConfig
    from perceiver_io_tpu.models.core.config import (
        ClassificationDecoderConfig,
        PerceiverIOConfig,
    )
    from perceiver_io_tpu.models.text.clm import CausalLanguageModel, CausalLanguageModelConfig
    from perceiver_io_tpu.models.text.classifier import TextClassifier
    from perceiver_io_tpu.models.text.common import TextEncoderConfig
    from perceiver_io_tpu.models.text.mlm import MaskedLanguageModel, TextDecoderConfig
    from perceiver_io_tpu.models.vision.image_classifier import ImageClassifier, ImageEncoderConfig
    from perceiver_io_tpu.models.vision.optical_flow import OpticalFlow, OpticalFlowEncoderConfig

    dtype = dtype or jnp.float32
    kwargs = {"dtype": dtype, "attention_impl": attention_impl}

    if isinstance(config, CausalLanguageModelConfig):
        return CausalLanguageModel(config, **kwargs)
    if isinstance(config, SymbolicAudioModelConfig):
        return SymbolicAudioModel(config, **kwargs)
    if isinstance(config, PerceiverIOConfig):
        enc, dec = config.encoder, config.decoder
        if isinstance(enc, ImageEncoderConfig):
            return ImageClassifier(config, **kwargs)
        if isinstance(enc, OpticalFlowEncoderConfig):
            return OpticalFlow(config, **kwargs)
        if isinstance(enc, TextEncoderConfig) and isinstance(dec, TextDecoderConfig):
            return MaskedLanguageModel(config, **kwargs)
        if isinstance(enc, TextEncoderConfig) and isinstance(dec, ClassificationDecoderConfig):
            return TextClassifier(config, **kwargs)
    raise ValueError(f"no model registered for config {type(config).__name__}")


__all__ = ["import_task_modules", "model_for_config"]
