"""Causal language model (Perceiver AR) — reference
``perceiver/model/text/clm/backend.py``. A thin specialization of the shared
autoregressive sequence model (UTF-8 bytes vocab 262, 4096 ctx, 512 latents)."""
from __future__ import annotations

from dataclasses import dataclass

from perceiver_io_tpu.models.core.config import register_config
from perceiver_io_tpu.models.sequence import AutoregressiveSequenceModel, SequenceModelConfig


@register_config
@dataclass
class CausalLanguageModelConfig(SequenceModelConfig):
    """Defaults per reference ``clm/backend.py:11-24``."""

    vocab_size: int = 262
    max_seq_len: int = 4096
    max_latents: int = 512
    num_channels: int = 512


class CausalLanguageModel(AutoregressiveSequenceModel):
    """Reference ``clm/backend.py:57-107``."""
