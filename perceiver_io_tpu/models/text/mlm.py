"""Masked language model (Perceiver IO) — reference
``perceiver/model/text/mlm/backend.py``."""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

from perceiver_io_tpu.models.core.config import DecoderConfig, PerceiverIOConfig, register_config
from perceiver_io_tpu.models.core.adapter import TrainableQueryProvider
from perceiver_io_tpu.models.core.modules import PerceiverDecoder
from perceiver_io_tpu.models.sequence import TiedOutputAdapter
from perceiver_io_tpu.models.text.common import TextEncoderConfig, make_text_encoder


@register_config
@dataclass
class TextDecoderConfig(DecoderConfig):
    """Reference ``mlm/backend.py:17-21``. ``num_output_query_channels=None``
    selects the weight-tied output adapter."""

    num_output_query_channels: Optional[int] = None
    vocab_size: int = 10003
    max_seq_len: int = 512


MaskedLanguageModelConfig = PerceiverIOConfig[TextEncoderConfig, TextDecoderConfig]


class UntiedTextOutputAdapter(nn.Module):
    """Linear vocab projection (untied path, reference ``mlm/backend.py:27-33``)."""

    vocab_size: int
    num_output_query_channels: int
    init_scale: float = 0.02
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        return nn.Dense(
            self.vocab_size,
            kernel_init=nn.initializers.normal(stddev=self.init_scale),
            bias_init=nn.initializers.zeros,
            dtype=self.dtype,
            name="linear",
        )(x)


class MaskedLanguageModel(nn.Module):
    """Text encoder + decoder with ``max_seq_len`` trainable output queries;
    logits truncated to the input length (reference ``mlm/backend.py:36-84``)."""

    config: MaskedLanguageModelConfig
    dtype: Any = jnp.float32
    attention_impl: str = "auto"

    @property
    def tied(self) -> bool:
        return self.config.decoder.num_output_query_channels is None

    def setup(self):
        cfg = self.config
        self.encoder = make_text_encoder(
            cfg.encoder,
            num_latents=cfg.num_latents,
            num_latent_channels=cfg.num_latent_channels,
            activation_checkpointing=cfg.activation_checkpointing,
            activation_offloading=cfg.activation_offloading,
            dtype=self.dtype,
            attention_impl=self.attention_impl,
            name="encoder",
        )
        if self.tied:
            num_query_channels = cfg.encoder.num_input_channels
            output_adapter = TiedOutputAdapter(
                vocab_size=cfg.decoder.vocab_size, dtype=self.dtype
            )
        else:
            num_query_channels = cfg.decoder.num_output_query_channels
            output_adapter = UntiedTextOutputAdapter(
                vocab_size=cfg.decoder.vocab_size,
                num_output_query_channels=num_query_channels,
                init_scale=cfg.decoder.init_scale,
                dtype=self.dtype,
            )
        self.decoder = PerceiverDecoder(
            output_adapter=output_adapter,
            output_query_provider=TrainableQueryProvider(
                num_queries=cfg.decoder.max_seq_len,
                num_query_channels_=num_query_channels,
                init_scale=cfg.decoder.init_scale,
                dtype=self.dtype,
            ),
            num_latent_channels=cfg.num_latent_channels,
            num_output_query_channels=num_query_channels,
            activation_checkpointing=cfg.activation_checkpointing,
            activation_offloading=cfg.activation_offloading,
            dtype=self.dtype,
            attention_impl=self.attention_impl,
            name="decoder",
            **cfg.decoder.base_kwargs(),
        )

    def __call__(
        self,
        x_masked: jnp.ndarray,
        pad_mask: Optional[jnp.ndarray] = None,
        deterministic: bool = True,
    ) -> jnp.ndarray:
        _, n = x_masked.shape
        x_latent = self.encoder(x_masked, pad_mask=pad_mask, deterministic=deterministic)
        if self.tied:
            logits = self.decoder(
                x_latent,
                deterministic=deterministic,
                txt_embedding=self.encoder.input_adapter.embeddings,
            )
        else:
            logits = self.decoder(x_latent, deterministic=deterministic)
        return logits[:, :n, :]
