"""Text classifier (Perceiver IO): text encoder + single-query classification
decoder — reference ``perceiver/model/text/classifier/backend.py``.

Two-stage training (load a pretrained MLM encoder, optionally freeze it) is
handled by the trainer: ``TextEncoderConfig.params`` names the checkpoint and
``TextEncoderConfig.freeze`` produces an optimizer mask (see
``perceiver_io_tpu.training``)."""
from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

from perceiver_io_tpu.models.core.adapter import ClassificationOutputAdapter, TrainableQueryProvider
from perceiver_io_tpu.models.core.config import ClassificationDecoderConfig, PerceiverIOConfig
from perceiver_io_tpu.models.core.modules import PerceiverDecoder
from perceiver_io_tpu.models.text.common import TextEncoderConfig, make_text_encoder

TextClassifierConfig = PerceiverIOConfig[TextEncoderConfig, ClassificationDecoderConfig]


class TextClassifier(nn.Module):
    """Reference ``classifier/backend.py:15-43``."""

    config: TextClassifierConfig
    dtype: Any = jnp.float32
    attention_impl: str = "auto"

    def setup(self):
        cfg = self.config
        self.encoder = make_text_encoder(
            cfg.encoder,
            num_latents=cfg.num_latents,
            num_latent_channels=cfg.num_latent_channels,
            activation_checkpointing=cfg.activation_checkpointing,
            activation_offloading=cfg.activation_offloading,
            dtype=self.dtype,
            attention_impl=self.attention_impl,
            name="encoder",
        )
        self.decoder = PerceiverDecoder(
            output_adapter=ClassificationOutputAdapter(
                num_classes=cfg.decoder.num_classes,
                num_output_query_channels=cfg.decoder.num_output_query_channels,
                init_scale=cfg.decoder.init_scale,
                dtype=self.dtype,
            ),
            output_query_provider=TrainableQueryProvider(
                num_queries=cfg.decoder.num_output_queries,
                num_query_channels_=cfg.decoder.num_output_query_channels,
                init_scale=cfg.decoder.init_scale,
                dtype=self.dtype,
            ),
            num_latent_channels=cfg.num_latent_channels,
            num_output_query_channels=cfg.decoder.num_output_query_channels,
            activation_checkpointing=cfg.activation_checkpointing,
            activation_offloading=cfg.activation_offloading,
            dtype=self.dtype,
            attention_impl=self.attention_impl,
            name="decoder",
            **cfg.decoder.base_kwargs(),
        )

    def __call__(
        self,
        x: jnp.ndarray,
        pad_mask: Optional[jnp.ndarray] = None,
        deterministic: bool = True,
    ) -> jnp.ndarray:
        x_latent = self.encoder(x, pad_mask=pad_mask, deterministic=deterministic)
        return self.decoder(x_latent, deterministic=deterministic)
