"""Shared text components: encoder config, token input adapter, and the text
Perceiver IO encoder builder (reference ``perceiver/model/text/common/backend.py``)."""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

from perceiver_io_tpu.models.core.adapter import InputAdapter
from perceiver_io_tpu.models.core.config import EncoderConfig, register_config
from perceiver_io_tpu.models.core.modules import PerceiverEncoder
from perceiver_io_tpu.ops.position import positions


@register_config
@dataclass
class TextEncoderConfig(EncoderConfig):
    """Reference ``text/common/backend.py:12-17``. ``params`` points at a
    checkpoint to warm-start the encoder from (e.g. a pretrained MLM)."""

    vocab_size: int = 10003
    max_seq_len: int = 256
    num_input_channels: int = 64
    params: Optional[str] = None
    freeze: bool = False


class TextInputAdapter(InputAdapter):
    """Token embedding + learned absolute position embedding (reference
    ``text/common/backend.py:20-45``). Unlike :class:`SequenceInputAdapter`
    this is for (non-rotary) Perceiver IO encoders and returns only the
    embedded input."""

    vocab_size: int
    max_seq_len: int
    num_channels: int
    abs_pos_emb: bool = True
    init_scale: float = 0.02
    dtype: Any = jnp.float32

    @property
    def num_input_channels(self) -> int:
        return self.num_channels

    def setup(self):
        self.txt_embedding = nn.Embed(
            self.vocab_size,
            self.num_channels,
            embedding_init=nn.initializers.normal(stddev=self.init_scale),
            name="txt_embedding",
        )
        if self.abs_pos_emb:
            self.pos_embedding = nn.Embed(
                self.max_seq_len,
                self.num_channels,
                embedding_init=nn.initializers.normal(stddev=self.init_scale),
                name="pos_embedding",
            )

    def __call__(self, x: jnp.ndarray, abs_pos: Optional[jnp.ndarray] = None) -> jnp.ndarray:
        if x.shape[1] > self.max_seq_len:
            # nn.Embed clamps out-of-range position indices silently; the
            # torch reference raises IndexError. Fail loudly instead.
            raise ValueError(
                f"sequence length ({x.shape[1]}) exceeds max_seq_len ({self.max_seq_len})"
            )
        emb = self.txt_embedding(x)
        if self.abs_pos_emb:
            if abs_pos is None:
                abs_pos = positions(*x.shape)
            emb = emb + self.pos_embedding(abs_pos)
        return emb.astype(self.dtype)

    @property
    def embeddings(self) -> jnp.ndarray:
        return self.txt_embedding.embedding


def make_text_encoder(
    config: TextEncoderConfig,
    num_latents: int,
    num_latent_channels: int,
    activation_checkpointing: bool = False,
    activation_offloading: bool = False,
    dtype: Any = jnp.float32,
    attention_impl: str = "auto",
    name: str = "encoder",
) -> PerceiverEncoder:
    """Build the text Perceiver IO encoder (reference
    ``text/common/backend.py:63-88``). Freezing (``config.freeze``) is applied
    at the optimizer level (see ``perceiver_io_tpu.training.optim.freeze_mask``),
    not by mutating the module."""
    input_adapter = TextInputAdapter(
        vocab_size=config.vocab_size,
        max_seq_len=config.max_seq_len,
        num_channels=config.num_input_channels,
        init_scale=config.init_scale,
        dtype=dtype,
    )
    return PerceiverEncoder(
        input_adapter=input_adapter,
        num_latents=num_latents,
        num_latent_channels=num_latent_channels,
        activation_checkpointing=activation_checkpointing,
        activation_offloading=activation_offloading,
        dtype=dtype,
        attention_impl=attention_impl,
        name=name,
        **config.base_kwargs(),
    )
