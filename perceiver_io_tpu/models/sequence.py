"""Generic autoregressive sequence model over a token vocabulary — the shared
backbone of the text CLM (reference ``perceiver/model/text/clm/backend.py``)
and the symbolic audio model (``perceiver/model/audio/symbolic/backend.py``),
which are the same model with different config defaults (the reference
acknowledges the duplication with TODOs, ``symbolic/backend.py:26,55,92``;
here it is factored properly).
"""
from __future__ import annotations

from dataclasses import dataclass, fields
from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

from perceiver_io_tpu.models.core.adapter import InputAdapter
from perceiver_io_tpu.models.core.config import PerceiverARConfig, register_config
from perceiver_io_tpu.models.core.modules import LAYER_NORM_EPS, PerceiverAR
from perceiver_io_tpu.ops.position import frequency_position_encoding, positions


@register_config
@dataclass
class SequenceModelConfig(PerceiverARConfig):
    """Hyperparameters shared by CLM and symbolic audio (reference
    ``clm/backend.py:11-24`` / ``symbolic/backend.py:10-23``)."""

    vocab_size: int = 262
    max_seq_len: int = 4096
    max_latents: int = 512
    num_channels: int = 512
    output_norm: bool = False
    output_bias: bool = True
    abs_pos_emb: bool = True
    init_scale: float = 0.02

    @classmethod
    def create(cls, **kwargs):
        return cls(**{f.name: kwargs[f.name] for f in fields(cls) if f.name in kwargs})

    @property
    def max_prefix_len(self) -> int:
        return self.max_seq_len - self.max_latents

    @property
    def rotated_channels_per_head(self) -> int:
        """Rotary on 100% of head channels, or 50% when an absolute position
        embedding is also used (reference ``clm/backend.py:59-63``)."""
        n = self.num_channels // self.num_heads
        return n // 2 if self.abs_pos_emb else n


class SequenceInputAdapter(InputAdapter):
    """Token embedding + optional learned absolute position embedding, plus
    rotary frequency encodings (the RotarySupport contract) — reference
    ``text/common/backend.py:20-45`` + ``core/adapter.py:22-32``."""

    vocab_size: int
    max_seq_len: int
    num_channels: int
    rotated_channels_per_head: int
    abs_pos_emb: bool = True
    init_scale: float = 0.02
    dtype: Any = jnp.float32

    @property
    def num_input_channels(self) -> int:
        return self.num_channels

    def setup(self):
        self.txt_embedding = nn.Embed(
            self.vocab_size,
            self.num_channels,
            embedding_init=nn.initializers.normal(stddev=self.init_scale),
            name="txt_embedding",
        )
        if self.abs_pos_emb:
            self.pos_embedding = nn.Embed(
                self.max_seq_len,
                self.num_channels,
                embedding_init=nn.initializers.normal(stddev=self.init_scale),
                name="pos_embedding",
            )

    def __call__(self, x: jnp.ndarray, abs_pos: Optional[jnp.ndarray] = None):
        if abs_pos is None:
            abs_pos = positions(*x.shape)
        emb = self.txt_embedding(x)
        if self.abs_pos_emb:
            emb = emb + self.pos_embedding(abs_pos)
        frq = frequency_position_encoding(abs_pos, self.rotated_channels_per_head)
        return emb.astype(self.dtype), frq

    @property
    def embeddings(self) -> jnp.ndarray:
        """(vocab, channels) embedding table, for tied output projection."""
        return self.txt_embedding.embedding


class TiedOutputAdapter(nn.Module):
    """Logits = x · Eᵀ (+ bias): weight-tied output head (reference
    ``text/common/backend.py:48-60``)."""

    vocab_size: int
    emb_bias: bool = True
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, txt_embedding: jnp.ndarray) -> jnp.ndarray:
        logits = x @ txt_embedding.astype(self.dtype).T
        if self.emb_bias:
            bias = self.param("bias", nn.initializers.zeros, (self.vocab_size,))
            logits = logits + bias.astype(self.dtype)
        return logits


class AutoregressiveSequenceModel(nn.Module):
    """Perceiver AR over a token vocabulary with tied input/output embeddings
    (reference ``clm/backend.py:57-107`` / ``symbolic/backend.py:93-143``)."""

    config: SequenceModelConfig
    dtype: Any = jnp.float32
    attention_impl: str = "auto"

    @property
    def max_seq_len(self) -> int:
        return self.config.max_seq_len

    @property
    def max_latents(self) -> int:
        return self.config.max_latents

    @property
    def max_prefix_len(self) -> int:
        return self.config.max_prefix_len

    def setup(self):
        cfg = self.config
        adapter = SequenceInputAdapter(
            vocab_size=cfg.vocab_size,
            max_seq_len=cfg.max_seq_len,
            num_channels=cfg.num_channels,
            rotated_channels_per_head=cfg.rotated_channels_per_head,
            abs_pos_emb=cfg.abs_pos_emb,
            init_scale=cfg.init_scale,
            dtype=self.dtype,
        )
        self.perceiver_ar = PerceiverAR(
            input_adapter=adapter,
            init_scale=cfg.init_scale,
            dtype=self.dtype,
            attention_impl=self.attention_impl,
            name="perceiver_ar",
            **cfg.base_kwargs(),
        )
        if cfg.output_norm:
            self.out_norm = nn.LayerNorm(epsilon=LAYER_NORM_EPS, dtype=self.dtype, name="out_norm", use_fast_variance=False)
        self.output_adapter = TiedOutputAdapter(
            vocab_size=cfg.vocab_size,
            emb_bias=cfg.output_bias,
            dtype=self.dtype,
            name="output_adapter",
        )

    def __call__(
        self,
        x: jnp.ndarray,
        prefix_len: int,
        pad_mask: Optional[jnp.ndarray] = None,
        deterministic: bool = True,
    ) -> jnp.ndarray:
        """:return: ``(b, n - prefix_len, vocab_size)`` logits for the latent
        positions (next-token predictions)."""
        if x.shape[1] > self.max_seq_len:
            # Explicit guard: nn.Embed clamps out-of-range position indices
            # silently (the torch reference raises IndexError instead).
            raise ValueError(
                f"sequence length ({x.shape[1]}) exceeds max_seq_len ({self.max_seq_len})"
            )
        if prefix_len > self.max_prefix_len:
            raise ValueError(
                f"prefix_len ({prefix_len}) exceeds max_prefix_len ({self.max_prefix_len})"
            )
        x_latent = self.perceiver_ar(x, prefix_len, pad_mask, deterministic)
        if self.config.output_norm:
            x_latent = self.out_norm(x_latent)
        return self.output_adapter(x_latent, self.perceiver_ar.input_adapter.embeddings)
