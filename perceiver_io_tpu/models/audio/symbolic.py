"""Symbolic audio model (Perceiver AR over MIDI event tokens) — reference
``perceiver/model/audio/symbolic/backend.py``. Same backbone as the text CLM
(the shared :class:`AutoregressiveSequenceModel`), 389-token event vocab."""
from __future__ import annotations

from dataclasses import dataclass

from perceiver_io_tpu.models.core.config import register_config
from perceiver_io_tpu.models.sequence import AutoregressiveSequenceModel, SequenceModelConfig


@register_config
@dataclass
class SymbolicAudioModelConfig(SequenceModelConfig):
    """Defaults per reference ``symbolic/backend.py:10-23``."""

    vocab_size: int = 389
    max_seq_len: int = 4096
    max_latents: int = 1024
    num_channels: int = 512


class SymbolicAudioModel(AutoregressiveSequenceModel):
    """Reference ``symbolic/backend.py:93-143``."""
