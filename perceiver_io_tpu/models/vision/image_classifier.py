"""Image classifier (Perceiver IO) with Fourier-feature position encodings —
reference ``perceiver/model/vision/image_classifier/backend.py``."""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax.numpy as jnp

from perceiver_io_tpu.models.core.adapter import (
    ClassificationOutputAdapter,
    InputAdapter,
    TrainableQueryProvider,
)
from perceiver_io_tpu.models.core.config import (
    ClassificationDecoderConfig,
    EncoderConfig,
    PerceiverIOConfig,
    register_config,
)
from perceiver_io_tpu.models.core.modules import PerceiverDecoder, PerceiverEncoder
from perceiver_io_tpu.ops.position import FourierPositionEncoding


@register_config
@dataclass
class ImageEncoderConfig(EncoderConfig):
    """Reference ``image_classifier/backend.py:21-25``."""

    image_shape: Tuple[int, int, int] = (224, 224, 3)
    num_frequency_bands: int = 32


ImageClassifierConfig = PerceiverIOConfig[ImageEncoderConfig, ClassificationDecoderConfig]


class ImageInputAdapter(InputAdapter):
    """Flatten pixels (channels-last) and concatenate Fourier position
    features (reference ``image_classifier/backend.py:30-48``)."""

    image_shape: Tuple[int, int, int]
    num_frequency_bands: int
    dtype: Any = jnp.float32

    @property
    def num_input_channels(self) -> int:
        return self.image_shape[-1] + self._position_encoding.num_channels

    @property
    def _position_encoding(self) -> FourierPositionEncoding:
        # Frozen dataclass, so no instance caching; the underlying table is
        # lru_cached by (shape, bands) in ops.position.
        return FourierPositionEncoding(self.image_shape[:-1], self.num_frequency_bands)

    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        b, *d = x.shape
        if tuple(d) != self.image_shape:
            raise ValueError(
                f"Input image shape {tuple(d)} different from required shape {self.image_shape}"
            )
        x = x.reshape(b, -1, self.image_shape[-1])
        pos = self._position_encoding(b)
        return jnp.concatenate([x, pos], axis=-1).astype(self.dtype)


class ImageClassifier(nn.Module):
    """Reference ``image_classifier/backend.py:51-88``: cross-attention qk
    channels default to the adapter's input channel count."""

    config: ImageClassifierConfig
    dtype: Any = jnp.float32
    attention_impl: str = "auto"

    def setup(self):
        cfg = self.config
        input_adapter = ImageInputAdapter(
            image_shape=cfg.encoder.image_shape,
            num_frequency_bands=cfg.encoder.num_frequency_bands,
            dtype=self.dtype,
        )
        encoder_kwargs = cfg.encoder.base_kwargs()
        if encoder_kwargs["num_cross_attention_qk_channels"] is None:
            encoder_kwargs["num_cross_attention_qk_channels"] = input_adapter.num_input_channels
        self.encoder = PerceiverEncoder(
            input_adapter=input_adapter,
            num_latents=cfg.num_latents,
            num_latent_channels=cfg.num_latent_channels,
            activation_checkpointing=cfg.activation_checkpointing,
            activation_offloading=cfg.activation_offloading,
            dtype=self.dtype,
            attention_impl=self.attention_impl,
            name="encoder",
            **encoder_kwargs,
        )
        self.decoder = PerceiverDecoder(
            output_adapter=ClassificationOutputAdapter(
                num_classes=cfg.decoder.num_classes,
                num_output_query_channels=cfg.decoder.num_output_query_channels,
                init_scale=cfg.decoder.init_scale,
                dtype=self.dtype,
            ),
            output_query_provider=TrainableQueryProvider(
                num_queries=cfg.decoder.num_output_queries,
                num_query_channels_=cfg.decoder.num_output_query_channels,
                init_scale=cfg.decoder.init_scale,
                dtype=self.dtype,
            ),
            num_latent_channels=cfg.num_latent_channels,
            num_output_query_channels=cfg.decoder.num_output_query_channels,
            activation_checkpointing=cfg.activation_checkpointing,
            activation_offloading=cfg.activation_offloading,
            dtype=self.dtype,
            attention_impl=self.attention_impl,
            name="decoder",
            **cfg.decoder.base_kwargs(),
        )

    def __call__(self, x: jnp.ndarray, deterministic: bool = True) -> jnp.ndarray:
        x_latent = self.encoder(x, deterministic=deterministic)
        return self.decoder(x_latent, deterministic=deterministic)
