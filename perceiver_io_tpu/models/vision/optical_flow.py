"""Optical flow (Perceiver IO) — reference
``perceiver/model/vision/optical_flow/backend.py``. Decoder queries are the
adapted encoder input (per-pixel queries)."""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Tuple

import flax.linen as nn
import jax.numpy as jnp

from perceiver_io_tpu.models.core.adapter import InputAdapter
from perceiver_io_tpu.models.core.config import (
    DecoderConfig,
    EncoderConfig,
    PerceiverIOConfig,
    register_config,
)
from perceiver_io_tpu.models.core.modules import PerceiverDecoder, PerceiverEncoder
from perceiver_io_tpu.ops.position import FourierPositionEncoding


@register_config
@dataclass
class OpticalFlowEncoderConfig(EncoderConfig):
    """Reference ``optical_flow/backend.py:22-27``."""

    image_shape: Tuple[int, int] = (368, 496)
    num_patch_input_channels: int = 27
    num_patch_hidden_channels: int = 64
    num_frequency_bands: int = 64


@register_config
@dataclass
class OpticalFlowDecoderConfig(DecoderConfig):
    """Reference ``optical_flow/backend.py:30-33``."""

    image_shape: Tuple[int, int] = (368, 496)
    rescale_factor: float = 100.0


OpticalFlowConfig = PerceiverIOConfig[OpticalFlowEncoderConfig, OpticalFlowDecoderConfig]


class OpticalFlowInputAdapter(InputAdapter):
    """Two frames of 3x3-patch features -> linear -> concat 2-D Fourier
    encodings (reference ``optical_flow/backend.py:39-60``).

    Input: ``(b, 2, c, h, w)`` — temporal frames concatenated in channels."""

    image_shape: Tuple[int, int]
    num_patch_input_channels: int
    num_patch_hidden_channels: int
    num_frequency_bands: int
    init_scale: float = 0.02
    dtype: Any = jnp.float32

    @property
    def _position_encoding(self) -> FourierPositionEncoding:
        return FourierPositionEncoding(self.image_shape, self.num_frequency_bands)

    @property
    def num_input_channels(self) -> int:
        return self.num_patch_hidden_channels + self._position_encoding.num_channels

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        b, t, c, h, w = x.shape
        # (b, t, c, h, w) -> (b, h, w, t*c): concatenate temporal frames in channels
        x = x.transpose(0, 3, 4, 1, 2).reshape(b, h, w, t * c)
        x = nn.Dense(
            self.num_patch_hidden_channels,
            kernel_init=nn.initializers.normal(stddev=self.init_scale),
            bias_init=nn.initializers.zeros,
            dtype=self.dtype,
            name="linear",
        )(x)
        x = x.reshape(b, h * w, self.num_patch_hidden_channels)
        pos = self._position_encoding(b)
        return jnp.concatenate([x, pos], axis=-1).astype(self.dtype)


class OpticalFlowOutputAdapter(nn.Module):
    """Linear to 2 flow channels, rescaled, reshaped to image grid (reference
    ``optical_flow/backend.py:63-78``)."""

    image_shape: Tuple[int, int]
    num_output_query_channels: int
    num_output_image_channels: int = 2
    rescale_factor: float = 100.0
    init_scale: float = 0.02
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        x = nn.Dense(
            self.num_output_image_channels,
            kernel_init=nn.initializers.normal(stddev=self.init_scale),
            bias_init=nn.initializers.zeros,
            dtype=self.dtype,
            name="linear",
        )(x) / self.rescale_factor
        b = x.shape[0]
        h, w = self.image_shape
        return x.reshape(b, h, w, self.num_output_image_channels)


class OpticalFlow(nn.Module):
    """Reference ``optical_flow/backend.py:95-137``: encoder qk/v channels
    default to the adapter channel count; decoder queries = adapted input."""

    config: OpticalFlowConfig
    dtype: Any = jnp.float32
    attention_impl: str = "auto"

    def setup(self):
        cfg = self.config
        input_adapter = OpticalFlowInputAdapter(
            image_shape=cfg.encoder.image_shape,
            num_patch_input_channels=cfg.encoder.num_patch_input_channels,
            num_patch_hidden_channels=cfg.encoder.num_patch_hidden_channels,
            num_frequency_bands=cfg.encoder.num_frequency_bands,
            init_scale=cfg.encoder.init_scale,
            dtype=self.dtype,
        )
        encoder_kwargs = cfg.encoder.base_kwargs()
        if encoder_kwargs["num_cross_attention_qk_channels"] is None:
            encoder_kwargs["num_cross_attention_qk_channels"] = input_adapter.num_input_channels
        if encoder_kwargs["num_cross_attention_v_channels"] is None:
            encoder_kwargs["num_cross_attention_v_channels"] = input_adapter.num_input_channels
        self.encoder = PerceiverEncoder(
            input_adapter=input_adapter,
            num_latents=cfg.num_latents,
            num_latent_channels=cfg.num_latent_channels,
            activation_checkpointing=cfg.activation_checkpointing,
            activation_offloading=cfg.activation_offloading,
            dtype=self.dtype,
            attention_impl=self.attention_impl,
            name="encoder",
            **encoder_kwargs,
        )
        self.decoder = PerceiverDecoder(
            output_adapter=OpticalFlowOutputAdapter(
                image_shape=cfg.decoder.image_shape,
                num_output_query_channels=input_adapter.num_input_channels,
                rescale_factor=cfg.decoder.rescale_factor,
                init_scale=cfg.decoder.init_scale,
                dtype=self.dtype,
            ),
            output_query_provider=None,  # queries = adapted encoder input
            num_latent_channels=cfg.num_latent_channels,
            num_output_query_channels=input_adapter.num_input_channels,
            activation_checkpointing=cfg.activation_checkpointing,
            activation_offloading=cfg.activation_offloading,
            dtype=self.dtype,
            attention_impl=self.attention_impl,
            name="decoder",
            **cfg.decoder.base_kwargs(),
        )

    def __call__(self, x: jnp.ndarray, deterministic: bool = True) -> jnp.ndarray:
        x_latent, x_adapted = self.encoder(
            x, return_adapted_input=True, deterministic=deterministic
        )
        return self.decoder(x_latent, x_adapted=x_adapted, deterministic=deterministic)
