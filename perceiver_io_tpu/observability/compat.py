"""Compat reader for ``metrics.jsonl`` across the schema fix.

Historical rows mixed two shapes in one file with no discriminator:

- scalar rows   ``{"step": 5, "train/loss": 2.1, "train/lr": 1e-4}``
- text rows     ``{"step": 5, "samples/generated": "..."}``  (``log_text``)

— so every parser had to type-sniff each value. The fixed schema keeps
scalar rows flat (every non-``step`` value is a float — documented
invariant) and namespaces text events under one ``"text"`` key:

- scalar rows   ``{"step": 5, "train/loss": 2.1}``           (unchanged)
- text rows     ``{"step": 5, "text": {"samples/generated": "..."}}``

:func:`read_metrics_jsonl` normalizes BOTH generations to
``{"step", "metrics", "text"}`` rows, so downstream tooling (longrun's
analyzer, notebook plots) reads old and new files through one function and
never sniffs again.
"""
from __future__ import annotations

import json
from typing import List


def normalize_row(row: dict) -> dict:
    """One raw metrics.jsonl row → ``{"step", "metrics", "text"}``.

    New-schema text rows have the ``"text"`` namespace; old-schema text rows
    are detected by value type (the sniff this module exists to retire —
    done once, here, instead of in every consumer)."""
    step = row.get("step")
    metrics = {}
    text = dict(row.get("text") or {})
    for key, value in row.items():
        if key in ("step", "text"):
            continue
        if isinstance(value, str):
            text[key] = value  # old-schema text row
        else:
            metrics[key] = float(value)
    return {"step": step, "metrics": metrics, "text": text}


def read_metrics_jsonl(path: str) -> List[dict]:
    """Parse a metrics.jsonl (old or new schema) into normalized rows,
    skipping blank/torn lines."""
    rows: List[dict] = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                raw = json.loads(line)
            except json.JSONDecodeError:
                continue
            rows.append(normalize_row(raw))
    return rows
