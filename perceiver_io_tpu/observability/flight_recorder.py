"""Incident flight recorder: always-on cheap state capture, total recall
at incident time (docs/observability.md "Flight recorder & incident
bundles").

Steady-state telemetry is (deliberately) sampled and bounded — the trace
sampler keeps a fraction of clean traces, the registry keeps sliding
windows — which is exactly wrong at the moment something breaks: an SLO
breach, a replica crash, a pool exhaustion, or an autoscaler ladder walk
deserves *everything recent*, captured automatically, bounded on disk.
Production TPU serving stacks run this shape (PAPERS.md: the Gemma-on-TPU
serving comparison is the deployment reference): a ring buffer nobody
reads until the moment nobody can afford not to.

:class:`FlightRecorder` is that black box:

- **always-on ring** — the tracer's in-memory ``finished`` span deque
  (which retains sampled-out traces too), a bounded ring of periodic
  registry snapshots (:meth:`maybe_record`, cadence-gated like
  ``SnapshotWriter``), and the compile ledger's recent records, all read
  lazily at dump time — steady-state cost is one deque append the tracer
  already pays.
- **triggered bundles** — :meth:`trigger` fires from the wired seams
  (:data:`TRIGGER_KINDS`), respects a per-kind cooldown and a global
  ``max_bundles`` budget (the ProfilerTrigger discipline: a sustained
  incident must not bury the disk), and writes one ATOMIC bundle
  directory: ``spans.jsonl`` (the ring slice) + ``manifest.json``
  (trigger metadata with trace ids, before/after registry snapshots, the
  snapshot ring, recent ledger records, and every registered source's
  state — engine/fleet ``health()`` incl. ``replica_detail``, KV-pool
  stats incl. ``frees_by_cause``, autoscaler rung/streak state, SLO burn
  state). Bundles build under a dot-prefixed temp dir and rename into
  place, so a reader never sees a torn bundle.
- **observability of the observer** — ``incident_triggers_total`` /
  ``incident_bundles_total`` / ``incident_suppressed_total`` /
  ``incident_dump_errors_total`` counters, plus one ``incident.dump``
  span event per bundle (the events.jsonl join key for the analyzer).

Like every telemetry component here: injectable clock (FakeClock drills
replay bit-identically), ``trigger()`` NEVER raises (an incident capture
failing must not compound the incident), and components hold a
``flight_recorder=None`` attr and skip the seam when unset.

The offline side is ``obs incident``
(:mod:`~perceiver_io_tpu.observability.report`): causal timeline plus the
per-request TTFT critical-path decomposition over a bundle.
"""
from __future__ import annotations

import dataclasses
import json
import os
import shutil
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence

from perceiver_io_tpu.observability.tracing import _json_default

#: the wired trigger seams → who fires them (docs/observability.md):
#:
#: ==========================  ================================================
#: kind                        seam
#: ==========================  ================================================
#: ``slo_breach``              :class:`~perceiver_io_tpu.observability.slo.SLOMonitor`
#:                             breach transition (per dimension)
#: ``replica_failure``         ``FleetRouter._on_replica_failure`` (crash/hang)
#: ``breaker_open``            a replica circuit breaker opening
#: ``pool_exhausted``          slot-engine admission stalled on KV pool blocks
#:                             (the ``kv_pool_admit_waits_total`` instant)
#: ``autoscaler_escalation``   degradation-ladder rung walked UP to
#:                             scale_up/shed
#: ``spawn_failed``            autoscaler replica spawn failure
#: ``mass_disconnect``         gateway: ``threshold`` client disconnects
#:                             inside ``window_s`` (:class:`DisconnectWatch`)
#: ``manual``                  operator / test-driven :meth:`FlightRecorder.trigger`
#: ==========================  ================================================
TRIGGER_KINDS = (
    "slo_breach",
    "replica_failure",
    "breaker_open",
    "pool_exhausted",
    "autoscaler_escalation",
    "spawn_failed",
    "mass_disconnect",
    "manual",
)

INCIDENT_COUNTERS = (
    "incident_triggers_total",
    "incident_bundles_total",
    "incident_suppressed_total",
    "incident_dump_errors_total",
)

#: manifest schema tag — the analyzer refuses bundles it cannot read
BUNDLE_SCHEMA = "incident-bundle-v1"


@dataclasses.dataclass
class IncidentArgs:
    """The CLI's ``--obs.incident.*`` flag sub-group
    (docs/observability.md). Setting ``dir`` enables the recorder; the
    rest tune its budget — off by default like the whole ``--obs.*``
    group."""

    #: bundle destination directory; setting it enables the flight
    #: recorder (relative paths resolve like the other --obs paths)
    dir: Optional[str] = None
    #: per-trigger-kind cooldown, seconds on the run's clock
    cooldown_s: float = 60.0
    #: hard cap on bundles per process lifetime
    max_bundles: int = 8
    #: finished spans included per bundle (the ring slice)
    keep_spans: int = 512

    @property
    def enabled(self) -> bool:
        return self.dir is not None


class DisconnectWatch:
    """Sliding-window mass-disconnect detector for the gateway seam: one
    :meth:`note` per client-disconnect cancellation; returns True (and
    resets) when ``threshold`` disconnects landed inside ``window_s`` —
    one abandoned stream is churn, a burst is an incident. Deterministic
    on the injectable clock."""

    def __init__(self, *, threshold: int = 3, window_s: float = 5.0,
                 clock: Callable[[], float] = time.monotonic):
        if threshold < 1:
            raise ValueError(f"threshold must be >= 1, got {threshold}")
        if window_s <= 0:
            raise ValueError(f"window_s must be > 0, got {window_s}")
        self.threshold = int(threshold)
        self.window_s = float(window_s)
        self._clock = clock
        self._times: deque = deque()

    def note(self) -> bool:
        now = self._clock()
        self._times.append(now)
        while self._times and self._times[0] < now - self.window_s:
            self._times.popleft()
        if len(self._times) >= self.threshold:
            self._times.clear()
            return True
        return False


class FlightRecorder:
    """The serving fleet's black box (module docstring).

    :param dir: bundle destination; created if missing.
    :param tracer: the run's :class:`~perceiver_io_tpu.observability.Tracer`
        — its ``finished`` ring is the span source, and bundles emit one
        ``incident.dump`` event onto it. Settable after construction (the
        CLI builds the recorder before the tracer is final).
    :param registry: where the ``incident_*`` counters live and whose
        snapshots the ring records (None skips both).
    :param clock: injectable time source (FakeClock in drills).
    :param cooldown_s: minimum seconds between bundles of the SAME kind —
        a breach polling every step must not write a bundle per poll.
    :param max_bundles: lifetime bundle budget; past it every trigger is
        suppressed (counted) — bounded disk is the whole point.
    :param keep_spans: ring-slice size per bundle.
    :param snapshot_every_s: cadence for :meth:`maybe_record`'s periodic
        registry snapshots (the "before" evidence).
    :param keep_snapshots: how many periodic snapshots the ring retains.
    """

    def __init__(self, dir: str, *, tracer=None, registry=None,
                 clock: Callable[[], float] = time.monotonic,
                 cooldown_s: float = 60.0, max_bundles: int = 8,
                 keep_spans: int = 512, snapshot_every_s: float = 5.0,
                 keep_snapshots: int = 8):
        if cooldown_s < 0:
            raise ValueError(f"cooldown_s must be >= 0, got {cooldown_s}")
        if max_bundles < 1:
            raise ValueError(f"max_bundles must be >= 1, got {max_bundles}")
        if keep_spans < 1:
            raise ValueError(f"keep_spans must be >= 1, got {keep_spans}")
        self.dir = dir
        os.makedirs(dir, exist_ok=True)
        self.tracer = tracer
        self.registry = registry
        self._clock = clock
        self.cooldown_s = float(cooldown_s)
        self.max_bundles = int(max_bundles)
        self.keep_spans = int(keep_spans)
        self.snapshot_every_s = float(snapshot_every_s)
        self._lock = threading.Lock()
        self._last_fired: Dict[str, float] = {}
        self._last_record: Optional[float] = None
        self._snapshots: deque = deque(maxlen=max(1, int(keep_snapshots)))
        self._sources: Dict[str, Callable[[], object]] = {}
        # dumps reserved under the lock but not yet appended to `bundles`
        # — the budget check counts them so concurrent triggers of
        # DIFFERENT kinds cannot overshoot max_bundles together
        self._reserved = 0
        # resume the sequence past any bundles a previous process left in
        # the same dir, or the first dump's rename would collide with (and
        # lose) the new incident's capture
        self._seq = 0
        for entry in os.listdir(dir):
            parts = entry.split("-", 2)
            if len(parts) == 3 and parts[0] == "incident" and parts[1].isdigit():
                self._seq = max(self._seq, int(parts[1]))
        #: bundle paths written, oldest first
        self.bundles: List[str] = []
        if registry is not None:
            registry.declare_counters(*INCIDENT_COUNTERS)

    # -- always-on state -----------------------------------------------------
    def add_source(self, name: str, fn: Callable[[], object]) -> None:
        """Register a zero-arg state provider evaluated AT DUMP TIME
        (engine/fleet ``health()``, kv-pool stats, autoscaler stats, SLO
        burn state). A raising source contributes its error string instead
        of aborting the bundle."""
        self._sources[str(name)] = fn

    def maybe_record(self, *, force: bool = False) -> bool:
        """Cadence-gated periodic registry snapshot into the bounded ring
        (the bundle's "before" evidence) — call it opportunistically from
        the drive loop, the ``SnapshotWriter.maybe_write`` convention."""
        if self.registry is None:
            return False
        now = self._clock()
        with self._lock:
            if not force and self._last_record is not None and (
                now - self._last_record < self.snapshot_every_s
            ):
                return False
            self._last_record = now
            self._snapshots.append({"t": now, **self.registry.snapshot()})
            return True

    # -- the trigger path ----------------------------------------------------
    def trigger(self, kind: str, reason: str, *,
                trace_ids: Sequence[str] = (), **attrs) -> Optional[str]:
        """One incident signal from a wired seam: write a bundle unless the
        kind's cooldown or the lifetime budget suppresses it. Returns the
        bundle path, or None when suppressed or the dump failed. NEVER
        raises — the capture path must not compound the incident it
        records (failures count ``incident_dump_errors_total``)."""
        try:
            now = self._clock()
            with self._lock:
                self._inc("incident_triggers_total")
                last = self._last_fired.get(kind)
                if len(self.bundles) + self._reserved >= self.max_bundles or (
                    last is not None and now - last < self.cooldown_s
                ):
                    self._inc("incident_suppressed_total")
                    return None
                # reserve the cooldown AND a budget slot under the lock so
                # concurrent triggers (a scrape thread + the owner loop, or
                # two different kinds) cannot overshoot together
                self._last_fired[kind] = now
                self._reserved += 1
                self._seq += 1
                seq = self._seq
            try:
                path = self._dump(
                    seq, kind, reason, list(trace_ids), dict(attrs), now
                )
            except Exception as e:
                self._inc("incident_dump_errors_total")
                with self._lock:  # give back the cooldown and budget slot
                    self._reserved -= 1
                    if self._last_fired.get(kind) == now:
                        del self._last_fired[kind]
                try:  # a torn temp dir must not accumulate across retries
                    shutil.rmtree(
                        os.path.join(self.dir, f".incident-{seq:03d}-{kind}.tmp"),
                        ignore_errors=True,
                    )
                except Exception:
                    pass
                del e
                return None
            with self._lock:
                self._reserved -= 1
                self.bundles.append(path)
            self._inc("incident_bundles_total")
            if self.tracer is not None:
                self.tracer.event(
                    "incident.dump", trigger=kind, reason=reason,
                    bundle=os.path.basename(path), seq=seq,
                    trace_ids=list(trace_ids),
                )
            return path
        except Exception:
            try:
                self._inc("incident_dump_errors_total")
            except Exception:
                pass
            return None

    def _inc(self, name: str) -> None:
        if self.registry is not None:
            self.registry.inc(name)

    def _dump(self, seq: int, kind: str, reason: str, trace_ids: List[str],
              attrs: dict, now: float) -> str:
        name = f"incident-{seq:03d}-{kind}"
        final = os.path.join(self.dir, name)
        tmp = os.path.join(self.dir, f".{name}.tmp")
        os.makedirs(tmp, exist_ok=True)
        rows: List[dict] = []
        if self.tracer is not None:
            spans = list(self.tracer.finished)[-self.keep_spans:]
            rows = [s.to_row() for s in spans]
        with open(os.path.join(tmp, "spans.jsonl"), "w") as fh:
            for row in rows:
                fh.write(json.dumps(row, default=_json_default) + "\n")
        sources = {}
        for src_name, fn in self._sources.items():
            try:
                sources[src_name] = fn()
            except Exception as e:  # a broken source is itself evidence
                sources[src_name] = {"error": f"{type(e).__name__}: {e}"}
        ledger_records = None
        try:
            from perceiver_io_tpu.observability.ledger import default_ledger

            snap = default_ledger().snapshot()
            snap["records"] = (snap.get("records") or [])[-64:]
            ledger_records = snap
        except Exception:
            pass
        with self._lock:
            snapshots = list(self._snapshots)
        manifest = {
            "schema": BUNDLE_SCHEMA,
            "seq": seq,
            "trigger": {
                "kind": kind,
                "reason": reason,
                "at_s": round(now, 6),
                "trace_ids": trace_ids,
                **attrs,
            },
            "metrics": {
                # last periodic ring entry = the steady state BEFORE the
                # incident; "now" = the registry at dump time
                "before": snapshots[-1] if snapshots else None,
                "now": (
                    None if self.registry is None else self.registry.snapshot()
                ),
            },
            "snapshots": snapshots,
            "compile_ledger": ledger_records,
            "sources": sources,
            "spans": len(rows),
        }
        with open(os.path.join(tmp, "manifest.json"), "w") as fh:
            json.dump(manifest, fh, indent=2, default=_json_default)
        os.rename(tmp, final)
        return final

    # -- observability -------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return {
                "dir": self.dir,
                "bundles": len(self.bundles),
                "max_bundles": self.max_bundles,
                "cooldown_s": self.cooldown_s,
                "last_fired": {
                    k: round(v, 6) for k, v in sorted(self._last_fired.items())
                },
                "snapshots_recorded": len(self._snapshots),
                "sources": sorted(self._sources),
            }
