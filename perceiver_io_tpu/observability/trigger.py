"""Span-threshold profiler trigger: capture a ``jax.profiler`` trace of the
next step when the step-time p95 regresses.

A steady-state p95 regression is exactly the moment a profile is worth its
overhead — and exactly the moment nobody is watching to start one by hand.
:class:`ProfilerTrigger` watches per-step durations (the trainer feeds it
its ``trainer.step`` span times), freezes a baseline p95 over the first
``min_samples`` healthy steps, and arms a one-shot capture when the rolling
p95 exceeds ``factor ×`` that baseline. The trainer then wraps the *next*
step in :func:`perceiver_io_tpu.utils.profiling.trace`, writing a
TensorBoard/Perfetto-viewable capture into ``log_dir`` — so the trace shows
a representative regressed step, not the tail of whatever blip armed it.

``capture_fn`` is injectable (tests count captures without touching the real
profiler); a cooldown keeps a sustained regression from re-arming every
step and burying the run in trace files.
"""
from __future__ import annotations

import contextlib
from collections import deque
from typing import Callable, Optional

from perceiver_io_tpu.observability.registry import Histogram


class ProfilerTrigger:
    """Arm a one-shot profiler capture on step-time p95 regression.

    :param log_dir: where captures land (``<dir>/regress-step<N>``).
    :param factor: rolling p95 must exceed ``factor * baseline_p95`` to arm.
    :param min_samples: observations used to freeze the baseline p95 (also
        the rolling-window size).
    :param cooldown: observations to ignore after a capture before re-arming.
    :param max_captures: hard cap on captures per trigger lifetime.
    :param warmup: observations discarded BEFORE the baseline starts —
        compile steps are orders of magnitude slower than steady state, and
        even one in the baseline window would freeze an inflated p95 that no
        real regression could ever exceed (the same exclusion
        ``utils/profiling.StepTimer`` applies).
    :param capture_fn: ``(log_dir) -> context manager`` — defaults to
        :func:`perceiver_io_tpu.utils.profiling.trace`; injectable for tests.
    """

    def __init__(self, log_dir: str, *, factor: float = 1.5,
                 min_samples: int = 20, cooldown: int = 100,
                 max_captures: int = 3, warmup: int = 3,
                 capture_fn: Optional[Callable] = None):
        if factor < 0:
            raise ValueError(f"factor must be >= 0, got {factor}")
        if min_samples < 1:
            raise ValueError(f"min_samples must be >= 1, got {min_samples}")
        self.log_dir = log_dir
        self.factor = factor
        self.min_samples = min_samples
        self.cooldown = cooldown
        self.max_captures = max_captures
        self._warmup_left = warmup
        self._capture_fn = capture_fn
        self._baseline: deque = deque(maxlen=min_samples)
        self.baseline_p95: Optional[float] = None
        self._window: deque = deque(maxlen=min_samples)
        self._cooldown_left = 0
        self._armed = False
        self.captures = 0

    def observe(self, duration_ms: float) -> bool:
        """Feed one step duration; returns True when this observation armed
        a capture (the caller profiles its *next* step)."""
        if self._warmup_left > 0:
            self._warmup_left -= 1
            return False
        if self.baseline_p95 is None:
            self._baseline.append(float(duration_ms))
            if len(self._baseline) >= self.min_samples:
                hist = Histogram(window=self.min_samples)
                for v in self._baseline:
                    hist.observe(v)
                self.baseline_p95 = hist.percentile(95.0)
            return False
        self._window.append(float(duration_ms))
        if self._cooldown_left > 0:
            self._cooldown_left -= 1
            return False
        if self._armed or self.captures >= self.max_captures:
            return False
        if len(self._window) < self._window.maxlen:
            # a p95 over 1-2 samples is just the last blip; require a full
            # window so one GC pause / co-tenant spike cannot burn a capture
            # (and its cooldown) on a perfectly healthy run
            return False
        hist = Histogram(window=len(self._window))
        for v in self._window:
            hist.observe(v)
        p95 = hist.percentile(95.0)
        if p95 is not None and p95 > self.factor * self.baseline_p95:
            self._armed = True
            return True
        return False

    @property
    def armed(self) -> bool:
        """Whether the next step should be captured."""
        return self._armed

    def arm(self) -> bool:
        """Arm a capture of the next step directly — the external-signal
        path (an :class:`~perceiver_io_tpu.observability.slo.SLOMonitor`
        breach arms a capture even when the regression lives in queueing,
        not step time). Respects the capture budget and cooldown exactly
        like :meth:`observe`; returns whether the trigger is now armed."""
        if self.captures >= self.max_captures or self._cooldown_left > 0:
            return self._armed
        self._armed = True
        return True

    @contextlib.contextmanager
    def capture(self, *, step: Optional[int] = None):
        """Run the enclosed (regressed) step under a profiler capture and
        disarm; enters the cooldown window afterwards."""
        self._armed = False
        self.captures += 1
        self._cooldown_left = self.cooldown
        target = self.log_dir
        if step is not None:
            import os

            target = os.path.join(self.log_dir, f"regress-step{step}")
        if self._capture_fn is not None:
            cm = self._capture_fn(target)
        else:
            from perceiver_io_tpu.utils.profiling import trace

            cm = trace(target)
        with cm:
            yield
