"""Device-cost ledger: compile / memory / retrace attribution per executor.

The telemetry spine (registry, spans, exporters) sees only the host side:
it can say a serve run spent 40 s before first traffic, but not *what* each
executor cost to build, how many bytes it holds resident, or *why* a
logically-same executor rebuilt. Both the Gemma-on-TPU serving comparison
and the pjit/TPUv4 scalable-training paper (PAPERS.md) treat exactly that
device-level attribution — compile time, HBM footprint, retrace cause — as
prerequisites for capacity planning, and the ROADMAP's paged-KV and
sharded-serving items are bounded by compile count and KV memory today.

:class:`CompileLedger` is that attribution layer. Every executor build site
(``inference/generate.py`` generation executors — which the bucket engine's
warmup drives — ``inference/beam.py``, and the slot engine's
prefill/decode/boundary/chunk executors in ``serving/slots.py``) routes
through :func:`~perceiver_io_tpu.inference.generate.cached_executor`, which
hands each fresh build to :meth:`CompileLedger.wrap`. The wrapper AOT-lowers
and compiles the program on its first call (``jit(f).lower().compile()`` —
the same trace+compile work the first jit dispatch would do, paid once) and
records, per cache key:

- **compile wall time** (trace + XLA compile, measured on the ledger clock);
- **cost analysis** — lowered FLOPs and bytes-accessed from XLA's
  ``compiled.cost_analysis()``;
- **memory analysis** — argument / output / temp / generated-code bytes
  from ``compiled.memory_analysis()`` (the executor's resident HBM claim);
- **retrace attribution** — when a logically-same executor (same site, same
  model fingerprint) rebuilds, the named cache-key components are diffed
  against the previous build and the rebuild is counted under every
  component that changed (``bucket_shape``, ``trace_env``,
  ``decode_strategy``, ``phase_plan``, ``config``, ...). The first build of
  an identity is a cold compile, not a retrace.

Registry families fed (docs/observability.md):

- ``compile_total`` counter and ``compile_ms`` histogram;
- ``retrace_total`` plus per-reason ``retrace_reason_<component>_total``;
- ``executor_resident_bytes`` gauge (sum of live executors' temp+output
  bytes — the analytic footprint XLA claims);
- ``hbm_bytes_in_use`` gauge via :meth:`update_device_gauges` — device
  ``memory_stats()`` where the backend provides it (TPU/GPU; CPU returns
  None and the gauge is skipped);
- ``kv_cache_resident_bytes`` gauge — the analytic slot-KV footprint the
  slot engine publishes at construction (everywhere, device stats or not).

Failure containment: observation must never change execution semantics. If
AOT compile fails (a backend without AOT support) or the compiled dispatch
rejects the call signature (``TypeError`` — AOT executables are
shape/dtype/weak-type strict), the wrapper permanently falls back to the
plain jitted callable for that executor and counts
``compile_ledger_fallback_total`` — the run proceeds exactly as before the
ledger existed, minus one row of attribution. Genuine *execution* errors
(device OOM, XLA runtime failures) re-raise untouched: retrying a dispatch
that may already have consumed donated buffers would mask the real
failure.

Determinism: with an injected clock (``reliability.FakeClock``) the ledger's
records — ordering, sequence numbers, retrace reasons — are a pure function
of the build sequence, pinned by ``tests/test_ledger.py``.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

from perceiver_io_tpu.observability.registry import MetricsRegistry


def _sanitize_reason(name: str) -> str:
    """Component name -> metric-name-safe reason token."""
    return "".join(c if (c.isalnum() or c == "_") else "_" for c in name)


class LedgeredExecutor:
    """A jitted executor whose first call is AOT-lowered, compiled, timed,
    and cost/memory-analyzed into the owning ledger; later calls dispatch
    the compiled executable directly. Any AOT failure (lowering, analysis
    mismatch, strict-signature drift) permanently falls back to the plain
    jitted callable — observation never fails the computation."""

    __slots__ = ("_fn", "_compiled", "_ledger", "_entry", "_fallback", "_lock")

    def __init__(self, fn: Callable, ledger: "CompileLedger", entry: dict):
        self._fn = fn
        self._compiled = None
        self._ledger = ledger
        self._entry = entry
        self._fallback = False
        self._lock = threading.Lock()

    def __call__(self, *args, **kwargs):
        compiled = self._compiled
        if compiled is None and not self._fallback:
            with self._lock:  # one compiler, even under a scrape thread
                if self._compiled is None and not self._fallback:
                    self._aot_compile(*args, **kwargs)
                compiled = self._compiled
        if compiled is not None:  # local read: a concurrent demotion can't
            try:                  # null the reference mid-dispatch
                return compiled(*args, **kwargs)
            except TypeError:
                # strict AOT signature (no weak-type/shape promotion):
                # demote to the jitted path rather than fail a request over
                # telemetry. Anything else is a genuine execution error —
                # re-raise rather than retry against possibly-donated
                # buffers and mask the real failure. Demote under the lock,
                # exactly once even when several threads hit the drift
                # together, so AOT can't re-arm and the fallback counter
                # counts demotions, not racers.
                with self._lock:
                    first = not self._fallback
                    self._fallback = True
                    self._compiled = None
                if first:
                    self._ledger._count_fallback(self._entry)
        return self._fn(*args, **kwargs)

    def _aot_compile(self, *args, **kwargs) -> None:
        clock = self._ledger._clock
        t0 = clock()
        try:
            compiled = self._fn.lower(*args, **kwargs).compile()
        except Exception:
            self._fallback = True
            self._ledger._count_fallback(self._entry)
            return
        compile_ms = (clock() - t0) * 1e3
        self._compiled = compiled
        cost = _cost_summary(compiled)
        memory = _memory_summary(compiled)
        self._ledger._record_compiled(self._entry, compile_ms, cost, memory)


def _cost_summary(compiled) -> Dict[str, Optional[float]]:
    """``cost_analysis()`` across jax versions returns a dict or a 1-list of
    dicts; normalize to {flops, bytes_accessed} (None when unavailable)."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return {"flops": None, "bytes_accessed": None}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        return {"flops": None, "bytes_accessed": None}
    flops = ca.get("flops")
    accessed = ca.get("bytes accessed")
    return {
        "flops": None if flops is None else float(flops),
        "bytes_accessed": None if accessed is None else float(accessed),
    }


def _memory_summary(compiled) -> Dict[str, Optional[int]]:
    """``memory_analysis()`` -> {argument,output,temp,generated_code}_bytes
    (all None on backends that don't implement it)."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        ma = None
    fields = (
        ("argument_bytes", "argument_size_in_bytes"),
        ("output_bytes", "output_size_in_bytes"),
        ("temp_bytes", "temp_size_in_bytes"),
        ("generated_code_bytes", "generated_code_size_in_bytes"),
    )
    if ma is None:
        return {k: None for k, _ in fields}
    out = {}
    for key, attr in fields:
        value = getattr(ma, attr, None)
        out[key] = None if value is None else int(value)
    return out


class CompileLedger:
    """Per-executor compile/memory/retrace ledger over one metrics registry.

    :param registry: registry the canonical families land on; defaults to
        the process-wide :func:`~perceiver_io_tpu.observability.default_registry`
        (executor caches are process-global, so their ledger is too).
    :param clock: monotonic time source for compile timing —
        ``reliability.FakeClock`` makes records fully deterministic.
    :param keep: bound on retained per-key records (FIFO; the registry
        counters keep counting past it).
    """

    def __init__(self, *, registry: Optional[MetricsRegistry] = None,
                 clock: Callable[[], float] = time.monotonic,
                 keep: int = 512):
        if registry is None:
            from perceiver_io_tpu.observability.registry import default_registry

            registry = default_registry()
        self.registry = registry
        self._clock = clock
        self._keep = keep
        self._lock = threading.Lock()
        self._records: List[dict] = []
        #: identity -> components of that identity's most recent build
        self._last: Dict[tuple, Dict[str, str]] = {}
        #: (site, components) -> latest build's temp+output bytes; kept
        #: incrementally so the resident gauge costs O(1) per compile
        #: (independent of the ``keep`` record bound)
        self._resident: Dict[tuple, int] = {}
        #: lifetime totals — unlike ``_records`` these never FIFO out, so
        #: the rollup stays exact past the ``keep`` bound
        self._total_retraces = 0
        self._total_compile_ms = 0.0
        self._reason_totals: Dict[str, int] = {}
        self._seq = 0
        self._on_record: List[Callable[[dict], None]] = []
        registry.declare_counters(
            "compile_total", "retrace_total", "compile_ledger_fallback_total"
        )

    # -- wiring ---------------------------------------------------------------
    def wrap(self, executor: Callable, *, site: str,
             components: Dict[str, Any]) -> Callable:
        """Wrap one freshly built jitted executor for ledger accounting.

        :param site: build-site name (``generate``, ``beam``,
            ``slot_prefill``, ``slot_decode``, ``slot_prefill_chunk``).
        :param components: the NAMED cache-key components — retrace
            attribution diffs these, so every key-relevant knob must appear
            (``model``, ``bucket_shape``, ``trace_env``, ...). Values are
            stringified; ``model`` (or the whole dict) defines the identity
            a rebuild is compared against.
        """
        comps = {k: str(v) for k, v in components.items()}
        entry = {"site": site, "components": comps}
        return LedgeredExecutor(executor, self, entry)

    def attach(self, callback: Callable[[dict], None]) -> Callable[[], None]:
        """Register a per-record callback (the serve CLI forwards records as
        ``ledger.compile`` span events into events.jsonl); returns a detach
        function. Callback exceptions are swallowed — the ledger must never
        fail the build it observes."""
        self._on_record.append(callback)

        def detach() -> None:
            try:
                self._on_record.remove(callback)
            except ValueError:
                pass

        return detach

    # -- recording -------------------------------------------------------------
    def _identity(self, site: str, components: Dict[str, str]) -> tuple:
        """A rebuild is "logically the same executor" when site + model
        match; everything else (bucket shape, phase plan, env fingerprint,
        decode strategy) is a variant axis a retrace is attributed to."""
        return (site, components.get("model", ""))

    def _record_compiled(self, entry: dict, compile_ms: float,
                         cost: Dict[str, Optional[float]],
                         memory: Dict[str, Optional[int]]) -> None:
        site, comps = entry["site"], entry["components"]
        identity = self._identity(site, comps)
        with self._lock:
            self._seq += 1
            prev = self._last.get(identity)
            reasons: tuple = ()
            if prev is not None:
                changed = sorted(
                    k for k in (set(prev) | set(comps))
                    if prev.get(k) != comps.get(k)
                )
                reasons = tuple(changed) if changed else ("duplicate_key",)
            self._last[identity] = comps
            record = {
                "seq": self._seq,
                "site": site,
                "components": dict(comps),
                "compile_ms": round(compile_ms, 3),
                "flops": cost["flops"],
                "bytes_accessed": cost["bytes_accessed"],
                **memory,
                "retrace": prev is not None,
                "retrace_reasons": list(reasons),
            }
            self._records.append(record)
            if len(self._records) > self._keep:
                self._records.pop(0)
            self._total_compile_ms += compile_ms
            if reasons:
                self._total_retraces += 1
                for reason in reasons:
                    self._reason_totals[reason] = (
                        self._reason_totals.get(reason, 0) + 1
                    )
            # one entry per distinct (site, components) executor — a
            # rebuild of the same program replaces its bytes rather than
            # accumulating (the ledger can't see cache evictions; evicted
            # executors stay counted until reset)
            self._resident[(site, tuple(sorted(comps.items())))] = (
                (memory["temp_bytes"] or 0) + (memory["output_bytes"] or 0)
            )
            resident = sum(self._resident.values())
        reg = self.registry
        reg.inc("compile_total")
        reg.observe("compile_ms", compile_ms)
        if reasons:
            reg.inc("retrace_total")
            for reason in reasons:
                reg.inc(f"retrace_reason_{_sanitize_reason(reason)}_total")
        reg.set_gauge("executor_resident_bytes", resident)
        for callback in list(self._on_record):
            try:
                callback(record)
            except Exception:
                pass

    def _count_fallback(self, entry: Optional[dict] = None) -> None:
        """Count a demotion; when the executor had recorded resident bytes
        (post-compile strict-signature demotion frees the AOT executable),
        drop them from the gauge — the plain-jit replacement is untracked."""
        if entry is not None:
            key = (entry["site"], tuple(sorted(entry["components"].items())))
            with self._lock:
                dropped = self._resident.pop(key, None)
                resident = sum(self._resident.values())
            if dropped is not None:
                self.registry.set_gauge("executor_resident_bytes", resident)
        self.registry.inc("compile_ledger_fallback_total")

    # -- device gauges -----------------------------------------------------------
    def update_device_gauges(self) -> Optional[int]:
        """Publish ``hbm_bytes_in_use`` from the backend's live
        ``memory_stats()`` (first device). Returns the bytes value, or None
        on backends (CPU) that report nothing — the analytic gauges
        (``kv_cache_resident_bytes``, ``executor_resident_bytes``) are the
        everywhere-available fallback."""
        try:
            import jax

            stats = jax.devices()[0].memory_stats()
        except Exception:
            stats = None
        if not stats or "bytes_in_use" not in stats:
            return None
        value = int(stats["bytes_in_use"])
        self.registry.set_gauge("hbm_bytes_in_use", value)
        return value

    def set_kv_cache_bytes(self, nbytes: int) -> None:
        """Analytic KV-cache footprint gauge (the slot engine publishes its
        persistent slot state's byte size — exact on every backend)."""
        self.registry.set_gauge("kv_cache_resident_bytes", int(nbytes))

    # -- introspection / export ---------------------------------------------------
    def records(self, site: Optional[str] = None) -> List[dict]:
        with self._lock:
            out = [dict(r) for r in self._records]
        if site is not None:
            out = [r for r in out if r["site"] == site]
        return out

    def rollup(self) -> dict:
        """Records-free summary — counts, reasons, compile-time total. This
        is what pollable surfaces (``ServingEngine.stats()``) embed: no
        per-record dict copies on the scrape path. All values are LIFETIME
        totals (matching the registry counters), not views over the
        ``keep``-bounded record list."""
        with self._lock:
            rollup = {
                "compiles": self._seq,
                "retraces": self._total_retraces,
                "retrace_reasons": dict(sorted(self._reason_totals.items())),
                "compile_ms_total": round(self._total_compile_ms, 3),
            }
        rollup["fallbacks"] = int(
            self.registry.counter("compile_ledger_fallback_total")
        )
        return rollup

    def snapshot(self) -> dict:
        """JSON-able ledger view: the lifetime rollup plus the per-key
        compile/memory table every durable consumer (``serve_stats``,
        snapshots, bench records, ``obs report``) embeds. The table is
        bounded by ``keep`` (oldest rows FIFO out); the rollup keeps
        counting past it."""
        return {**self.rollup(), "records": self.records()}

    def reset(self) -> None:
        """Drop records and identity history (test isolation; registry
        counters are reset separately via ``registry.reset``)."""
        with self._lock:
            self._records.clear()
            self._last.clear()
            self._resident.clear()
            self._total_retraces = 0
            self._total_compile_ms = 0.0
            self._reason_totals.clear()
            self._seq = 0
        # the executors the gauge described are gone too
        self.registry.set_gauge("executor_resident_bytes", 0)


#: Process-wide default ledger, mirroring ``default_registry()``: the
#: executor caches it observes are process-global singletons.
_DEFAULT: Optional[CompileLedger] = None
_DEFAULT_LOCK = threading.Lock()


def default_ledger() -> CompileLedger:
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            _DEFAULT = CompileLedger()
        return _DEFAULT
