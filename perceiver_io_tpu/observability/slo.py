"""SLO telemetry: latency/error targets, multi-window burn-rate
monitoring, and the shared goodput-under-SLO accounting.

Serving quality on TPU pods is judged by latency-percentile SLOs under an
offered-load sweep — p95 time-to-first-token (TTFT) and p95 inter-token
latency (ITL) vs offered load — not by raw tokens/s (PAPERS.md: the
Gemma-on-TPU serving comparison). Both engines record the raw samples
(``serving_ttft_ms`` / ``serving_inter_token_ms`` histograms plus a
``serving.first_token`` event per request trace; docs/observability.md);
this module turns those samples into an *operational* signal:

- :class:`SLOPolicy` — the targets: p95 TTFT, p95 ITL, error rate.
- :class:`SLOMonitor` — a multi-window burn-rate evaluator (the SRE
  fast+slow window pattern): each observation is classified good/bad
  against its target, and per window the **burn rate** is
  ``bad_fraction / error_budget`` (budget = 5% for a p95 target, the
  policy's ``error_rate`` for dispositions). A dimension **breaches**
  when BOTH windows burn above ``breach_burn_rate`` — the fast window
  proves the problem is current, the slow window proves it is sustained,
  so a single blip can neither trip nor instantly clear the alarm. On
  breach the monitor increments ``slo_breach_total``, emits an
  ``slo.breach`` span event, arms the serving
  :class:`~perceiver_io_tpu.observability.ProfilerTrigger` (a breach is
  exactly the moment a capture pays for itself), and — through
  :attr:`SLOMonitor.breached` — tightens
  :class:`~perceiver_io_tpu.serving.FleetRouter` admission
  (``max_pending`` / deadline shedding scale down by ``slo_shed_factor``
  while the burn lasts; docs/serving.md). Recovery is fast-window-driven:
  once fresh samples burn below threshold the dimension recovers
  (``slo.recover`` event, ``slo_recoveries_total``).
- :func:`offered_load` / :func:`goodput_ratio` — the ONE definition of
  the goodput denominator, shared by ``bench.py``'s ``extras.fleet_chaos``
  and ``extras.slo_goodput`` probes and the ``obs report`` SLO section:
  offered load is *everything the callers asked for* (accepted + shed +
  rejected), so an engine that sheds half its traffic cannot report
  goodput 1.0.

Everything runs on an injectable clock and is stdlib-only, so drills
compose with :class:`~perceiver_io_tpu.reliability.FakeClock` like the
rest of the registry and replay bit-identically (tests/test_slo.py).
"""
from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Callable, Dict, List, Mapping, Optional, Tuple

#: the registry histogram names the engines record token latency under —
#: also the names :meth:`SLOMonitor.sink` routes on (engine ``latency_sink``
#: compatibility)
TTFT_METRIC = "serving_ttft_ms"
INTER_TOKEN_METRIC = "serving_inter_token_ms"

#: error budget implied by a p95 latency target: 5% of samples may miss it
_P95_BUDGET = 0.05


# -- shared goodput accounting ----------------------------------------------
def offered_load(counts: Mapping[str, float], prefix: str = "serving") -> int:
    """The goodput DENOMINATOR: every request the callers offered —
    accepted (``*_requests_submitted_total``) plus shed plus rejected.
    ``prefix`` selects the counter family (``serving`` or ``fleet``)."""
    return int(
        counts.get(f"{prefix}_requests_submitted_total", 0)
        + counts.get(f"{prefix}_requests_shed_total", 0)
        + counts.get(f"{prefix}_requests_rejected_total", 0)
    )


def goodput_ratio(counts: Mapping[str, float], prefix: str = "serving") -> float:
    """Completed / offered (:func:`offered_load`) — the one shared
    definition, so the bench probes cannot drift on the denominator."""
    return (
        counts.get(f"{prefix}_requests_completed_total", 0)
        / max(1, offered_load(counts, prefix))
    )


@dataclasses.dataclass
class SLOPolicy:
    """The serving-quality targets a deployment promises. ``None`` disables
    that dimension; at least one target must be set to build a monitor.

    :param ttft_p95_ms: p95 time-to-first-token target (``serving_ttft_ms``).
    :param inter_token_p95_ms: p95 inter-token latency target
        (``serving_inter_token_ms``).
    :param error_rate: max fraction of dispositions that may be non-ok
        (failed + timed_out + shed), e.g. ``0.01`` for 99% success.
    """

    ttft_p95_ms: Optional[float] = None
    inter_token_p95_ms: Optional[float] = None
    error_rate: Optional[float] = None

    def dimensions(self) -> List[Tuple[str, float]]:
        """``(name, error_budget)`` per configured dimension."""
        dims = []
        if self.ttft_p95_ms is not None:
            dims.append(("ttft", _P95_BUDGET))
        if self.inter_token_p95_ms is not None:
            dims.append(("inter_token", _P95_BUDGET))
        if self.error_rate is not None:
            if not 0.0 < self.error_rate < 1.0:
                raise ValueError(
                    f"error_rate must be in (0, 1), got {self.error_rate}"
                )
            dims.append(("error", self.error_rate))
        if not dims:
            raise ValueError(
                "SLOPolicy needs at least one target (ttft_p95_ms / "
                "inter_token_p95_ms / error_rate)"
            )
        return dims


@dataclasses.dataclass
class SLOArgs:
    """The CLI's ``--obs.slo.*`` flag sub-group (docs/observability.md):
    targets plus monitor knobs. All targets default to off — the monitor
    is only built when at least one target is set, matching the rest of
    the ``--obs.*`` group's off-by-default contract."""

    #: p95 time-to-first-token target in ms (None = dimension off)
    ttft_p95_ms: Optional[float] = None
    #: p95 inter-token latency target in ms (None = dimension off)
    inter_token_p95_ms: Optional[float] = None
    #: max non-ok disposition fraction, e.g. 0.01 (None = dimension off)
    error_rate: Optional[float] = None
    #: the two burn windows, seconds
    fast_window_s: float = 60.0
    slow_window_s: float = 600.0
    #: both windows must burn at or above this to breach
    burn_rate: float = 2.0
    #: fleet admission multiplier while breached (``--serve.replicas > 1``)
    shed_factor: float = 0.5

    @property
    def enabled(self) -> bool:
        return (
            self.ttft_p95_ms is not None
            or self.inter_token_p95_ms is not None
            or self.error_rate is not None
        )

    def policy(self) -> SLOPolicy:
        return SLOPolicy(
            ttft_p95_ms=self.ttft_p95_ms,
            inter_token_p95_ms=self.inter_token_p95_ms,
            error_rate=self.error_rate,
        )


class _Window:
    """One dimension's observation log, evaluated over the trailing fast
    and slow windows with INCREMENTAL accounting: each window keeps its own
    deque of ``(t, bad)`` plus running sample/bad counts, so a poll pays
    only for the entries that aged out since the last one — O(1) amortized
    per observation, not a rescan of the slow window per engine step.
    Deterministic on the injectable clock, no sampling."""

    __slots__ = ("_fast", "_slow", "fast_n", "fast_bad", "slow_n", "slow_bad")

    def __init__(self):
        self._fast: deque = deque()
        self._slow: deque = deque()
        self.fast_n = self.fast_bad = 0
        self.slow_n = self.slow_bad = 0

    def observe(self, t: float, bad: bool) -> None:
        entry = (t, bad)
        self._fast.append(entry)
        self._slow.append(entry)
        self.fast_n += 1
        self.slow_n += 1
        if bad:
            self.fast_bad += 1
            self.slow_bad += 1

    def evict(self, now: float, fast_window_s: float, slow_window_s: float) -> None:
        for events, cutoff, n_attr, bad_attr in (
            (self._fast, now - fast_window_s, "fast_n", "fast_bad"),
            (self._slow, now - slow_window_s, "slow_n", "slow_bad"),
        ):
            while events and events[0][0] < cutoff:
                _, was_bad = events.popleft()
                setattr(self, n_attr, getattr(self, n_attr) - 1)
                if was_bad:
                    setattr(self, bad_attr, getattr(self, bad_attr) - 1)

    def burns(self, budget: float) -> Tuple[float, int, float]:
        """``(fast burn, fast sample count, slow burn)`` from the running
        counts (call :meth:`evict` first)."""
        fast = 0.0 if self.fast_n == 0 else (self.fast_bad / self.fast_n) / budget
        slow = 0.0 if self.slow_n == 0 else (self.slow_bad / self.slow_n) / budget
        return fast, self.fast_n, slow


class SLOMonitor:
    """Multi-window burn-rate evaluator over the policy's dimensions
    (module docstring for the breach semantics).

    :param policy: the targets.
    :param clock: monotonic time source — pass the engine/fleet's
        :class:`~perceiver_io_tpu.reliability.FakeClock` in drills so the
        windows advance deterministically.
    :param registry: where ``slo_burn_rate*`` gauges and
        ``slo_breach_total`` / ``slo_recoveries_total`` counters live
        (usually the same registry the serving histograms are on).
    :param tracer: optional — emits ``slo.breach`` / ``slo.recover`` span
        events.
    :param profiler_trigger: optional
        :class:`~perceiver_io_tpu.observability.ProfilerTrigger`; a breach
        arms it so the next device dispatch is captured.
    :param fast_window_s / slow_window_s: the two burn windows.
    :param breach_burn_rate: both windows must burn at or above this to
        breach (1.0 = burning the budget exactly; 2.0 = at double rate).
    :param min_samples: fewest fast-window samples that can support a
        breach — one bad observation in an idle window is a blip, not an
        outage.
    """

    def __init__(self, policy: SLOPolicy, *,
                 clock: Callable[[], float] = time.monotonic,
                 registry=None, tracer=None, profiler_trigger=None,
                 flight_recorder=None,
                 fast_window_s: float = 60.0, slow_window_s: float = 600.0,
                 breach_burn_rate: float = 2.0, min_samples: int = 5):
        if fast_window_s <= 0 or slow_window_s <= 0:
            raise ValueError("burn windows must be > 0 seconds")
        if fast_window_s > slow_window_s:
            raise ValueError(
                f"fast_window_s={fast_window_s} must not exceed "
                f"slow_window_s={slow_window_s}"
            )
        if breach_burn_rate <= 0:
            raise ValueError(f"breach_burn_rate must be > 0, got {breach_burn_rate}")
        self.policy = policy
        self._dims: Dict[str, float] = dict(policy.dimensions())
        self._clock = clock
        self.registry = registry
        self.tracer = tracer
        self.profiler_trigger = profiler_trigger
        #: optional :class:`~perceiver_io_tpu.observability.FlightRecorder`
        #: — a breach transition dumps an incident bundle (cooldown- and
        #: budget-gated by the recorder), the same "a breach is the moment
        #: a capture pays for itself" stance as the profiler-trigger arm
        self.flight_recorder = flight_recorder
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.breach_burn_rate = float(breach_burn_rate)
        self.min_samples = int(min_samples)
        self._windows: Dict[str, _Window] = {d: _Window() for d in self._dims}
        self._breached: Dict[str, bool] = {d: False for d in self._dims}
        self._burn: Dict[str, Tuple[float, float]] = {
            d: (0.0, 0.0) for d in self._dims
        }
        self._counter_source: Optional[Callable[[], Mapping[str, float]]] = None
        self._counter_prefix = "serving"
        self._counter_seen: Dict[str, float] = {}
        if registry is not None:
            registry.declare_counters("slo_breach_total", "slo_recoveries_total")

    # -- feeds ---------------------------------------------------------------
    def sink(self, name: str, value_ms: float) -> None:
        """Engine ``latency_sink``-compatible feed: routes the two token
        histogram names onto their dimensions; other names are ignored (the
        engine mirrors every token-latency observation here)."""
        if name == TTFT_METRIC:
            self.observe_ttft(value_ms)
        elif name == INTER_TOKEN_METRIC:
            self.observe_inter_token(value_ms)

    def observe_ttft(self, value_ms: float) -> None:
        target = self.policy.ttft_p95_ms
        if target is not None:
            self._windows["ttft"].observe(self._clock(), value_ms > target)

    def observe_inter_token(self, value_ms: float) -> None:
        target = self.policy.inter_token_p95_ms
        if target is not None:
            self._windows["inter_token"].observe(self._clock(), value_ms > target)

    def observe_request(self, ok: bool) -> None:
        """One terminal disposition for the error-rate dimension (bad =
        failed / timed_out / shed)."""
        if "error" in self._windows:
            self._windows["error"].observe(self._clock(), not ok)

    def watch_counters(self, source: Callable[[], Mapping[str, float]],
                       prefix: str = "serving") -> None:
        """Feed the error dimension from a registry's cumulative counters:
        each :meth:`poll` diffs ``{prefix}_requests_{completed,failed,
        timed_out,shed}_total`` against the last poll and records the delta
        as that many dispositions — so a caller that never sees individual
        requests (the serve CLI drain loop, the fleet router) still
        evaluates the error SLO."""
        self._counter_source = source
        self._counter_prefix = prefix
        self._counter_seen = {}

    def _pull_counters(self) -> None:
        if self._counter_source is None or "error" not in self._windows:
            return
        counts = self._counter_source()
        p = self._counter_prefix

        def delta(key: str) -> int:
            now_v = float(counts.get(key, 0.0))
            d = int(now_v - self._counter_seen.get(key, 0.0))
            self._counter_seen[key] = now_v
            return max(0, d)

        # Sheds caused by the breach's OWN admission tightening
        # (fleet_slo_shed_total, double-counted in the ordinary shed
        # counter) are excluded from the error feed: counting them would
        # close a feedback loop — tightening sheds load, the sheds burn the
        # error budget, the burn sustains the breach that tightened — and
        # the breach could never recover while any load persists.
        slo_sheds = delta(f"{p}_slo_shed_total")
        for key, ok, exclude in (
            (f"{p}_requests_completed_total", True, 0),
            (f"{p}_requests_failed_total", False, 0),
            (f"{p}_requests_timed_out_total", False, 0),
            (f"{p}_requests_shed_total", False, slo_sheds),
        ):
            for _ in range(max(0, delta(key) - exclude)):
                self.observe_request(ok)

    # -- evaluation ----------------------------------------------------------
    @property
    def breached(self) -> bool:
        """True while ANY dimension is in breach (as of the last
        :meth:`poll`) — the bit fleet admission tightens on."""
        return any(self._breached.values())

    @property
    def active_breaches(self) -> List[str]:
        return sorted(d for d, b in self._breached.items() if b)

    def poll(self) -> dict:
        """Evaluate every dimension's fast/slow burn, publish gauges, and
        run the breach/recovery transitions. Call it from the serving drive
        loop (the serve CLI per drain pass; the fleet router per step) —
        evaluation is O(window events), far off the per-token path."""
        self._pull_counters()
        now = self._clock()
        worst = 0.0
        out: Dict[str, dict] = {}
        for dim, budget in self._dims.items():
            window = self._windows[dim]
            window.evict(now, self.fast_window_s, self.slow_window_s)
            fast, fast_n, slow = window.burns(budget)
            self._burn[dim] = (fast, slow)
            # the sustained burn: what BOTH windows agree on
            worst = max(worst, min(fast, slow))
            if self.registry is not None:
                self.registry.set_gauge(f"slo_burn_rate_{dim}_fast", round(fast, 4))
                self.registry.set_gauge(f"slo_burn_rate_{dim}_slow", round(slow, 4))
            breaching = (
                fast >= self.breach_burn_rate
                and slow >= self.breach_burn_rate
                and fast_n >= self.min_samples
            )
            if breaching and not self._breached[dim]:
                self._breached[dim] = True
                if self.registry is not None:
                    self.registry.inc("slo_breach_total")
                    self.registry.inc(f"slo_breach_{dim}_total")
                if self.tracer is not None:
                    self.tracer.event(
                        "slo.breach", dimension=dim,
                        burn_fast=round(fast, 4), burn_slow=round(slow, 4),
                    )
                if self.profiler_trigger is not None:
                    self.profiler_trigger.arm()
                if self.flight_recorder is not None:
                    self.flight_recorder.trigger(
                        "slo_breach",
                        f"SLO {dim} breach: burn fast={fast:.2f} "
                        f"slow={slow:.2f} (threshold "
                        f"{self.breach_burn_rate})",
                        dimension=dim, burn_fast=round(fast, 4),
                        burn_slow=round(slow, 4),
                    )
            elif (
                self._breached[dim]
                and fast < self.breach_burn_rate
                and fast_n >= self.min_samples
            ):
                # fast-window recovery: fresh samples prove health NOW; the
                # slow window may stay hot for its whole span, and holding
                # tightened admission that long would turn one incident
                # into a self-inflicted outage. Symmetric with the breach
                # guard, recovery also needs min_samples of EVIDENCE — an
                # empty fast window is a stalled system (no tokens, no
                # dispositions), not a healthy one, and must not read as
                # recovered mid-outage.
                self._breached[dim] = False
                if self.registry is not None:
                    self.registry.inc("slo_recoveries_total")
                if self.tracer is not None:
                    self.tracer.event(
                        "slo.recover", dimension=dim, burn_fast=round(fast, 4),
                    )
            out[dim] = {
                "burn_fast": round(fast, 4), "burn_slow": round(slow, 4),
                "breached": self._breached[dim], "samples_fast": fast_n,
            }
        if self.registry is not None:
            self.registry.set_gauge("slo_burn_rate", round(worst, 4))
        return out

    def stats(self) -> dict:
        """JSON-able snapshot for ``serve_stats`` / bench records."""
        return {
            "policy": dataclasses.asdict(self.policy),
            "fast_window_s": self.fast_window_s,
            "slow_window_s": self.slow_window_s,
            "breach_burn_rate": self.breach_burn_rate,
            "breached": self.breached,
            "active_breaches": self.active_breaches,
            "burn_rates": {
                d: {"fast": round(f, 4), "slow": round(s, 4)}
                for d, (f, s) in sorted(self._burn.items())
            },
            "breaches": (
                int(self.registry.counter("slo_breach_total"))
                if self.registry is not None else None
            ),
        }
