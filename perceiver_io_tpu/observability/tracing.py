"""Span tracing: where did this request's (or this step's) latency go?

A :class:`Span` is one named, timed region with attributes; a
:class:`Tracer` allocates deterministic trace/span IDs, retains finished
spans in a bounded buffer (test introspection), and optionally streams each
finished span as one JSON line to a sink (:class:`JsonlSpanSink` →
``events.jsonl``).

The serving lifecycle threads ONE trace per request through
``submit → queued → batched → executed → split/complete`` — every submitted
request ends in exactly one terminal ``serving.request`` span whose
``status`` is ``ok``/``shed``/``timed_out``/``failed``/``rejected``, which
is what makes span accounting *closeable*: terminal spans reconcile 1:1
against ``ServingEngine.stats()`` counters. The trainer emits per-step
``trainer.data_wait`` / ``trainer.step`` / ``trainer.log_flush`` /
``trainer.checkpoint`` spans under one trace per ``fit``.

IDs are sequential (``t000001``, ``s000001``), not random: deterministic
under the chaos harness and trivially joinable from the serve CLI's JSON
lines. Because the JSONL sink appends, two *processes* writing the same
events file would collide on restarted IDs — pass a per-run ``prefix``
(the CLI derives one from the pid + start time) to disambiguate; the
default stays bare for deterministic tests.

Components take ``tracer=None`` and skip every span site when unset — the
same zero-cost-when-off contract as the chaos hooks.

**Trace sampling** (docs/observability.md "Trace sampling"): at fleet
scale the span stream is a firehose — every request writes ~6 lines — so
:class:`SamplingSpanSink` sits between the tracer and the JSONL sink and
keeps a deterministic fraction of *ok* request traces (head sampling on a
per-trace counter: every Nth new trace — no RNG, so FakeClock drills
replay bit-identically) while ALWAYS retaining the traces an operator
actually reads: any trace ending in a non-``ok`` terminal status
(:data:`TAIL_KEEP_STATUSES`) or whose terminal span exceeded
``keep_slow_ms``. Dropped spans are counted
(``tracing_spans_sampled_out_total`` etc.) so accounting stays closeable,
and sampled-out traces still land in the tracer's in-memory ring — the
:class:`~perceiver_io_tpu.observability.flight_recorder.FlightRecorder`'s
incident bundles see everything recent regardless of the disk policy.
"""
from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Callable, Dict, List, Optional


@dataclasses.dataclass
class Span:
    """One timed region. ``end_s`` is None while open; ``status`` is set at
    end time (``ok`` unless the region raised or the caller overrode it)."""

    name: str
    trace_id: str
    span_id: str
    parent_id: Optional[str]
    start_s: float
    end_s: Optional[float] = None
    status: str = "open"
    attrs: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def duration_ms(self) -> Optional[float]:
        if self.end_s is None:
            return None
        return (self.end_s - self.start_s) * 1e3

    def to_row(self) -> dict:
        """The events.jsonl line shape."""
        return {
            "span": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_s": round(self.start_s, 6),
            "duration_ms": None if self.duration_ms is None else round(self.duration_ms, 3),
            "status": self.status,
            **({"attrs": self.attrs} if self.attrs else {}),
        }


class Tracer:
    """Span factory + finished-span buffer + optional JSONL sink.

    :param clock: monotonic time source (``FakeClock`` for deterministic
        tests).
    :param sink: callable receiving each finished span's ``to_row()`` dict —
        usually a :class:`JsonlSpanSink`. None keeps spans in memory only.
    :param keep: how many finished spans the in-memory buffer retains.
    :param prefix: prepended to every trace/span ID. Default "" keeps IDs
        deterministic for tests; pass a per-run token when several runs
        append to one events file (trace IDs restart per process).
    """

    def __init__(self, *, clock: Callable[[], float] = time.monotonic,
                 sink: Optional[Callable[[dict], None]] = None, keep: int = 8192,
                 prefix: str = ""):
        self._clock = clock
        self._sink = sink
        self._lock = threading.Lock()
        self._prefix = prefix
        self._next_trace = 0
        self._next_span = 0
        self.finished: deque = deque(maxlen=keep)

    def now(self) -> float:
        """The tracer's clock — callers that backdate spans from durations
        measured on a DIFFERENT clock must translate into this domain
        (``start_s = tracer.now() - duration``), or span durations mix two
        time bases (e.g. a FakeClock engine with a wall-clock tracer)."""
        return self._clock()

    # -- ids ----------------------------------------------------------------
    def new_trace_id(self) -> str:
        with self._lock:
            self._next_trace += 1
            return f"{self._prefix}t{self._next_trace:06d}"

    def _new_span_id(self) -> str:
        self._next_span += 1
        return f"{self._prefix}s{self._next_span:06d}"

    # -- span lifecycle -----------------------------------------------------
    def start_span(self, name: str, *, trace_id: Optional[str] = None,
                   parent: Optional[Span] = None,
                   start_s: Optional[float] = None, **attrs: Any) -> Span:
        """Open a span. ``start_s`` backdates it (the engine opens a request's
        terminal span at its recorded submit time)."""
        with self._lock:
            span_id = self._new_span_id()
        if trace_id is None:
            trace_id = parent.trace_id if parent is not None else self.new_trace_id()
        return Span(
            name=name, trace_id=trace_id, span_id=span_id,
            parent_id=None if parent is None else parent.span_id,
            start_s=self._clock() if start_s is None else float(start_s),
            attrs=dict(attrs),
        )

    def end_span(self, span: Span, status: str = "ok", **attrs: Any) -> Span:
        span.end_s = self._clock()
        span.status = status
        span.attrs.update(attrs)
        with self._lock:
            self.finished.append(span)
            sink = self._sink
        if sink is not None:
            sink(span.to_row())
        return span

    @contextlib.contextmanager
    def span(self, name: str, *, trace_id: Optional[str] = None,
             parent: Optional[Span] = None, **attrs: Any):
        """Context-managed span; a raising body ends it ``status="error"``
        (and re-raises)."""
        sp = self.start_span(name, trace_id=trace_id, parent=parent, **attrs)
        try:
            yield sp
        except BaseException:
            self.end_span(sp, status="error")
            raise
        self.end_span(sp)

    def event(self, name: str, *, trace_id: Optional[str] = None,
              status: str = "ok", start_s: Optional[float] = None,
              **attrs: Any) -> Span:
        """A point (or backdated) span ended immediately — terminal request
        states, shed/rejected submissions."""
        sp = self.start_span(name, trace_id=trace_id, start_s=start_s, **attrs)
        return self.end_span(sp, status=status)

    # -- introspection ------------------------------------------------------
    def spans(self, name: Optional[str] = None,
              trace_id: Optional[str] = None) -> List[Span]:
        """Finished spans, optionally filtered — the accounting tests' view."""
        with self._lock:
            out = list(self.finished)
        if name is not None:
            out = [s for s in out if s.name == name]
        if trace_id is not None:
            out = [s for s in out if s.trace_id == trace_id]
        return out


def _json_default(obj):
    """Last-resort JSON coercion for span attrs: numpy scalars carry
    ``item()`` (their native Python value — keeps numbers numeric in the
    file); anything else degrades to ``str`` so one exotic attr can never
    poison the telemetry write path."""
    item = getattr(obj, "item", None)
    if callable(item):
        try:
            return item()
        except Exception:
            pass
    return str(obj)


class JsonlSpanSink:
    """Append finished spans to a JSONL file (``events.jsonl``), one line
    per span, flushed per write so a crashed run still leaves a complete
    prefix. Rank gating is the caller's job (the trainer only constructs a
    sink on process 0).

    Write failures — disk full, directory removed mid-run, and
    serialization failures alike (a span attr that ``json`` cannot encode
    is coerced via :func:`_json_default` first; only a genuinely
    un-stringable row fails) — are counted in :attr:`write_errors`, never
    raised: telemetry must not kill the run it observes (the same contract
    as ``SnapshotWriter.maybe_write``).

    :param max_bytes: on-disk bound. When appending a line would push the
        file past it, the current file rotates to ``<path>.1`` (replacing
        any previous rotation) and writing restarts fresh — single-file
        rotation, so the pair never exceeds ``2 × max_bytes`` (plus one
        line) and ``events.jsonl`` itself stays under the bound.
        :func:`read_events_jsonl` reads the rotated pair transparently.
        None (default) keeps the historical unbounded append."""

    def __init__(self, path: str, *, max_bytes: Optional[int] = None):
        if max_bytes is not None and max_bytes < 1:
            raise ValueError(f"max_bytes must be >= 1, got {max_bytes}")
        self.path = path
        self.max_bytes = max_bytes
        self._lock = threading.Lock()
        self._fh = open(path, "a")
        try:
            self._size = self._fh.tell()
        except OSError:
            self._size = 0
        self.write_errors = 0
        self.rotations = 0

    def _rotate_locked(self) -> None:
        self._fh.close()
        os.replace(self.path, self.path + ".1")
        self._fh = open(self.path, "w")
        self._size = 0
        self.rotations += 1

    def __call__(self, row: dict) -> None:
        with self._lock:
            if self._fh is None:
                return
            try:
                line = json.dumps(row, default=_json_default) + "\n"
            except (TypeError, ValueError):
                self.write_errors += 1
                return
            try:
                if (
                    self.max_bytes is not None
                    and self._size > 0
                    and self._size + len(line) > self.max_bytes
                ):
                    self._rotate_locked()
                self._fh.write(line)
                self._fh.flush()
                self._size += len(line)
            except OSError:
                self.write_errors += 1

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    self.write_errors += 1
                self._fh = None


#: terminal request-span names — a trace's sampling fate is decided when
#: one of these finishes (every submission ends in exactly one; the
#: docstring lifecycle diagram)
TERMINAL_SPANS = frozenset({"serving.request", "fleet.request"})

#: span-name prefixes subject to sampling: the per-request firehose.
#: Operational streams (``ledger.compile``, ``slo.*``, ``autoscaler.*``,
#: ``trainer.*``, ``incident.*``) always write through — they are rare and
#: exactly what an operator greps first.
SAMPLED_PREFIXES = ("serving.", "fleet.", "gateway.")

#: terminal statuses that tail-keep a trace regardless of head sampling —
#: every way a request can end other than cleanly
TAIL_KEEP_STATUSES = frozenset(
    {"shed", "timed_out", "failed", "rejected", "cancelled", "error"}
)


class SamplingSpanSink:
    """Deterministic head-sampled span sink with tail-keep (module
    docstring; docs/observability.md "Trace sampling").

    Sits between a :class:`Tracer` and its real sink (usually a
    :class:`JsonlSpanSink`). Per in-scope trace (:data:`SAMPLED_PREFIXES`),
    the FIRST span seen assigns the trace a sequence number; every
    ``stride``-th trace (``stride = round(1 / rate)``) is head-kept and
    streams through immediately. Other traces buffer until their terminal
    span (:data:`TERMINAL_SPANS`) decides them: a non-``ok`` status
    (:data:`TAIL_KEEP_STATUSES`) or a terminal duration at or above
    ``keep_slow_ms`` tail-keeps the WHOLE buffered trace; a clean fast
    trace drops, counted. Counter-based, no RNG, no clock — bit-identical
    under replay.

    Registry families (declared up front): ``tracing_spans_total`` /
    ``tracing_spans_kept_total`` / ``tracing_spans_sampled_out_total``
    (kept + sampled_out == total, the closeable-accounting invariant) and
    ``tracing_traces_kept_total`` / ``tracing_traces_sampled_out_total``.
    Out-of-scope spans count as kept, so the span accounting covers every
    row the tracer emitted.

    :param sink: the downstream row consumer.
    :param rate: fraction of clean traces kept, in ``(0, 1]``.
    :param keep_slow_ms: tail-keep latency threshold on the terminal
        span's ``duration_ms`` (None disables the latency rule).
    :param registry: where the ``tracing_*`` counters live (None skips).
    :param max_pending: bound on undecided buffered traces; overflow
        force-drops the OLDEST pending trace (counted) — a trace whose
        terminal span never arrives must not grow the buffer forever.
    """

    COUNTERS = (
        "tracing_spans_total",
        "tracing_spans_kept_total",
        "tracing_spans_sampled_out_total",
        "tracing_traces_kept_total",
        "tracing_traces_sampled_out_total",
    )

    def __init__(self, sink: Callable[[dict], None], *, rate: float,
                 keep_slow_ms: Optional[float] = None, registry=None,
                 max_pending: int = 4096):
        if not 0.0 < rate <= 1.0:
            raise ValueError(f"rate must be in (0, 1], got {rate}")
        if max_pending < 1:
            raise ValueError(f"max_pending must be >= 1, got {max_pending}")
        self._sink = sink
        self.rate = float(rate)
        self.stride = max(1, int(round(1.0 / rate)))
        self.keep_slow_ms = keep_slow_ms
        self.registry = registry
        self.max_pending = int(max_pending)
        self._lock = threading.Lock()
        self._seq = 0  # per-new-trace counter (the head-sampling basis)
        # trace_id -> keep decision; bounded FIFO so a long run cannot grow
        # it forever (late spans of an evicted trace just re-sample)
        self._decided: "OrderedDict[str, bool]" = OrderedDict()
        self._pending: "OrderedDict[str, List[dict]]" = OrderedDict()
        if registry is not None:
            registry.declare_counters(*self.COUNTERS)

    def _inc(self, name: str, n: float = 1.0) -> None:
        if self.registry is not None and n:
            self.registry.inc(name, n)

    def _write(self, row: dict) -> None:
        self._sink(row)
        self._inc("tracing_spans_kept_total")

    def _decide(self, trace_id: str, keep: bool) -> None:
        self._decided[trace_id] = keep
        while len(self._decided) > 4 * self.max_pending:
            self._decided.popitem(last=False)
        if keep:
            self._inc("tracing_traces_kept_total")
        else:
            self._inc("tracing_traces_sampled_out_total")

    def __call__(self, row: dict) -> None:
        with self._lock:
            name = str(row.get("span") or "")
            self._inc("tracing_spans_total")
            trace_id = row.get("trace_id")
            if not name.startswith(SAMPLED_PREFIXES) or trace_id is None:
                self._write(row)  # operational stream: never sampled
                return
            decided = self._decided.get(trace_id)
            if decided is not None:
                if decided:
                    self._write(row)
                else:
                    self._inc("tracing_spans_sampled_out_total")
                return
            buf = self._pending.get(trace_id)
            if buf is None:
                index = self._seq
                self._seq += 1
                if index % self.stride == 0:
                    self._decide(trace_id, True)  # head-kept: stream through
                    self._write(row)
                    return
                buf = self._pending[trace_id] = []
                while len(self._pending) > self.max_pending:
                    # overflow: force-drop the oldest undecided trace
                    stale_id, stale = self._pending.popitem(last=False)
                    self._decide(stale_id, False)
                    self._inc("tracing_spans_sampled_out_total", len(stale))
                    buf = self._pending.get(trace_id)
                    if buf is None:  # the overflow victim was this trace
                        self._inc("tracing_spans_sampled_out_total")
                        return
            buf.append(row)
            if name not in TERMINAL_SPANS:
                return
            # the trace's fate: tail-keep on a dirty or slow terminal
            duration = row.get("duration_ms")
            keep = row.get("status") in TAIL_KEEP_STATUSES or (
                self.keep_slow_ms is not None
                and isinstance(duration, (int, float))
                and duration >= self.keep_slow_ms
            )
            del self._pending[trace_id]
            self._decide(trace_id, keep)
            if keep:
                for buffered in buf:
                    self._write(buffered)
            else:
                self._inc("tracing_spans_sampled_out_total", len(buf))

    def flush(self) -> int:
        """Write every still-undecided buffered trace (kept — a trace with
        no terminal span at shutdown is an interrupted request, exactly
        what a post-mortem wants on disk); returns spans written."""
        with self._lock:
            written = 0
            while self._pending:
                trace_id, buf = self._pending.popitem(last=False)
                self._decide(trace_id, True)
                for row in buf:
                    self._write(row)
                    written += 1
            return written

    def close(self) -> None:
        """Flush pending traces, then close the wrapped sink (if it has a
        ``close``) — drop-in for the callers that close ``JsonlSpanSink``."""
        self.flush()
        close = getattr(self._sink, "close", None)
        if close is not None:
            close()

    def stats(self) -> dict:
        with self._lock:
            return {
                "rate": self.rate,
                "stride": self.stride,
                "keep_slow_ms": self.keep_slow_ms,
                "pending_traces": len(self._pending),
                "decided_traces": len(self._decided),
            }


def read_events_jsonl(path: str) -> List[dict]:
    """Parse an events.jsonl file, skipping torn trailing lines (the file is
    flushed per span, but a SIGKILL can still truncate the last write).
    When the sink rotated (``JsonlSpanSink(max_bytes=...)``), the rotated
    predecessor ``<path>.1`` is read first so rows come back in write
    order across the pair."""
    rows: List[dict] = []
    paths = [p for p in (path + ".1", path) if os.path.exists(p)]
    if not paths:
        paths = [path]  # surface the caller's FileNotFoundError unchanged
    for part in paths:
        with open(part) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rows.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
    return rows
