"""Span tracing: where did this request's (or this step's) latency go?

A :class:`Span` is one named, timed region with attributes; a
:class:`Tracer` allocates deterministic trace/span IDs, retains finished
spans in a bounded buffer (test introspection), and optionally streams each
finished span as one JSON line to a sink (:class:`JsonlSpanSink` →
``events.jsonl``).

The serving lifecycle threads ONE trace per request through
``submit → queued → batched → executed → split/complete`` — every submitted
request ends in exactly one terminal ``serving.request`` span whose
``status`` is ``ok``/``shed``/``timed_out``/``failed``/``rejected``, which
is what makes span accounting *closeable*: terminal spans reconcile 1:1
against ``ServingEngine.stats()`` counters. The trainer emits per-step
``trainer.data_wait`` / ``trainer.step`` / ``trainer.log_flush`` /
``trainer.checkpoint`` spans under one trace per ``fit``.

IDs are sequential (``t000001``, ``s000001``), not random: deterministic
under the chaos harness and trivially joinable from the serve CLI's JSON
lines. Because the JSONL sink appends, two *processes* writing the same
events file would collide on restarted IDs — pass a per-run ``prefix``
(the CLI derives one from the pid + start time) to disambiguate; the
default stays bare for deterministic tests.

Components take ``tracer=None`` and skip every span site when unset — the
same zero-cost-when-off contract as the chaos hooks.
"""
from __future__ import annotations

import contextlib
import dataclasses
import json
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional


@dataclasses.dataclass
class Span:
    """One timed region. ``end_s`` is None while open; ``status`` is set at
    end time (``ok`` unless the region raised or the caller overrode it)."""

    name: str
    trace_id: str
    span_id: str
    parent_id: Optional[str]
    start_s: float
    end_s: Optional[float] = None
    status: str = "open"
    attrs: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def duration_ms(self) -> Optional[float]:
        if self.end_s is None:
            return None
        return (self.end_s - self.start_s) * 1e3

    def to_row(self) -> dict:
        """The events.jsonl line shape."""
        return {
            "span": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_s": round(self.start_s, 6),
            "duration_ms": None if self.duration_ms is None else round(self.duration_ms, 3),
            "status": self.status,
            **({"attrs": self.attrs} if self.attrs else {}),
        }


class Tracer:
    """Span factory + finished-span buffer + optional JSONL sink.

    :param clock: monotonic time source (``FakeClock`` for deterministic
        tests).
    :param sink: callable receiving each finished span's ``to_row()`` dict —
        usually a :class:`JsonlSpanSink`. None keeps spans in memory only.
    :param keep: how many finished spans the in-memory buffer retains.
    :param prefix: prepended to every trace/span ID. Default "" keeps IDs
        deterministic for tests; pass a per-run token when several runs
        append to one events file (trace IDs restart per process).
    """

    def __init__(self, *, clock: Callable[[], float] = time.monotonic,
                 sink: Optional[Callable[[dict], None]] = None, keep: int = 8192,
                 prefix: str = ""):
        self._clock = clock
        self._sink = sink
        self._lock = threading.Lock()
        self._prefix = prefix
        self._next_trace = 0
        self._next_span = 0
        self.finished: deque = deque(maxlen=keep)

    def now(self) -> float:
        """The tracer's clock — callers that backdate spans from durations
        measured on a DIFFERENT clock must translate into this domain
        (``start_s = tracer.now() - duration``), or span durations mix two
        time bases (e.g. a FakeClock engine with a wall-clock tracer)."""
        return self._clock()

    # -- ids ----------------------------------------------------------------
    def new_trace_id(self) -> str:
        with self._lock:
            self._next_trace += 1
            return f"{self._prefix}t{self._next_trace:06d}"

    def _new_span_id(self) -> str:
        self._next_span += 1
        return f"{self._prefix}s{self._next_span:06d}"

    # -- span lifecycle -----------------------------------------------------
    def start_span(self, name: str, *, trace_id: Optional[str] = None,
                   parent: Optional[Span] = None,
                   start_s: Optional[float] = None, **attrs: Any) -> Span:
        """Open a span. ``start_s`` backdates it (the engine opens a request's
        terminal span at its recorded submit time)."""
        with self._lock:
            span_id = self._new_span_id()
        if trace_id is None:
            trace_id = parent.trace_id if parent is not None else self.new_trace_id()
        return Span(
            name=name, trace_id=trace_id, span_id=span_id,
            parent_id=None if parent is None else parent.span_id,
            start_s=self._clock() if start_s is None else float(start_s),
            attrs=dict(attrs),
        )

    def end_span(self, span: Span, status: str = "ok", **attrs: Any) -> Span:
        span.end_s = self._clock()
        span.status = status
        span.attrs.update(attrs)
        with self._lock:
            self.finished.append(span)
            sink = self._sink
        if sink is not None:
            sink(span.to_row())
        return span

    @contextlib.contextmanager
    def span(self, name: str, *, trace_id: Optional[str] = None,
             parent: Optional[Span] = None, **attrs: Any):
        """Context-managed span; a raising body ends it ``status="error"``
        (and re-raises)."""
        sp = self.start_span(name, trace_id=trace_id, parent=parent, **attrs)
        try:
            yield sp
        except BaseException:
            self.end_span(sp, status="error")
            raise
        self.end_span(sp)

    def event(self, name: str, *, trace_id: Optional[str] = None,
              status: str = "ok", start_s: Optional[float] = None,
              **attrs: Any) -> Span:
        """A point (or backdated) span ended immediately — terminal request
        states, shed/rejected submissions."""
        sp = self.start_span(name, trace_id=trace_id, start_s=start_s, **attrs)
        return self.end_span(sp, status=status)

    # -- introspection ------------------------------------------------------
    def spans(self, name: Optional[str] = None,
              trace_id: Optional[str] = None) -> List[Span]:
        """Finished spans, optionally filtered — the accounting tests' view."""
        with self._lock:
            out = list(self.finished)
        if name is not None:
            out = [s for s in out if s.name == name]
        if trace_id is not None:
            out = [s for s in out if s.trace_id == trace_id]
        return out


class JsonlSpanSink:
    """Append finished spans to a JSONL file (``events.jsonl``), one line
    per span, flushed per write so a crashed run still leaves a complete
    prefix. Rank gating is the caller's job (the trainer only constructs a
    sink on process 0).

    Write failures (disk full, directory removed mid-run) are counted in
    :attr:`write_errors`, never raised — telemetry must not kill the run it
    observes (the same contract as ``SnapshotWriter.maybe_write``)."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._fh = open(path, "a")
        self.write_errors = 0

    def __call__(self, row: dict) -> None:
        with self._lock:
            if self._fh is None:
                return
            try:
                self._fh.write(json.dumps(row) + "\n")
                self._fh.flush()
            except OSError:
                self.write_errors += 1

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    self.write_errors += 1
                self._fh = None


def read_events_jsonl(path: str) -> List[dict]:
    """Parse an events.jsonl file, skipping torn trailing lines (the file is
    flushed per span, but a SIGKILL can still truncate the last write)."""
    rows: List[dict] = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rows.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return rows
