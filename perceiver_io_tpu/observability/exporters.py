"""Exporters: Prometheus text format and JSON snapshots of a
:class:`~perceiver_io_tpu.observability.MetricsRegistry`.

Two formats, one source:

- :func:`to_prometheus_text` — the ``text/plain; version=0.0.4`` exposition
  format a scrape endpoint (or a human with ``curl``) reads. Histograms
  render as Prometheus *summaries* (quantile series + ``_sum``/``_count``):
  we keep raw reservoirs, not fixed buckets, so quantiles are the honest
  export.
- :func:`snapshot_json` / :class:`SnapshotWriter` — the machine-readable
  snapshot the serve CLI appends to ``serve_stats``, the trainer drops next
  to ``metrics.jsonl``, and ``bench.py`` embeds in its record so every
  BENCH_* file carries telemetry.

``SnapshotWriter`` is cadence-gated on an injectable clock
(``--obs.snapshot_every_s``): callers invoke :meth:`SnapshotWriter.maybe_write`
opportunistically from their own loop (the trainer at each log flush, the
serve CLI per drain pass) and the writer decides whether enough time has
passed — no background thread to leak.
"""
from __future__ import annotations

import json
import os
import time
from typing import Callable, Optional

from perceiver_io_tpu.observability.registry import MetricsRegistry

_QUANTILES = (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99"))

#: one-line human descriptions for the canonical metric families
#: (docs/observability.md) — rendered as ``# HELP`` lines in the
#: exposition so a scrape endpoint is self-describing
HELP_TEXT = {
    "serving_requests_submitted_total": "Requests accepted into the serving queue.",
    "serving_requests_completed_total": "Requests that finished with a generated result.",
    "serving_requests_shed_total": "Submissions rejected by bounded-queue backpressure.",
    "serving_requests_timed_out_total": "Requests whose deadline expired before completion.",
    "serving_requests_failed_total": "Requests failed by an executor or injected fault.",
    "serving_requests_rejected_total": "Submissions rejected as infeasible (empty / over the largest bucket).",
    "serving_requests_cancelled_total": "Requests withdrawn mid-flight via cancel() (gateway client disconnects).",
    "serving_token_sink_errors_total": "Per-request on_token sinks that raised and were isolated.",
    "serving_batches_total": "Micro-batches executed by the bucket engine.",
    "serving_tokens_generated_total": "Real (non-filler) tokens generated across requests.",
    "serving_prompt_tokens_real_total": "Prompt tokens submitted by callers.",
    "serving_prompt_tokens_padded_total": "Prompt tokens after bucket padding (real + pad).",
    "serving_decode_rows_total": "Decode-step rows executed (real + filler).",
    "serving_decode_rows_padded_total": "Decode-step rows that were padding filler.",
    "serving_decode_steps_total": "Fixed-shape slot decode steps executed.",
    "serving_prefills_total": "Slot admissions prefilled (single-call or chunked).",
    "serving_prefill_chunks_total": "Chunked-prefill staging calls executed.",
    "serving_queue_wait_ms": "Queue wait per request: submit to batch/prefill start.",
    "serving_batch_assembly_ms": "Host-side micro-batch packing time.",
    "serving_device_execute_ms": "Device execute time per micro-batch (dispatch + fence).",
    "serving_request_latency_ms": "End-to-end request latency: submit to terminal state.",
    "serving_decode_step_ms": "One fixed-shape slot decode step (dispatch + fence).",
    "serving_prefill_ms": "Per-admission prefill time (summed chunks when chunked).",
    "serving_prefill_chunk_ms": "Per-call chunked-prefill stall (staging or finalize).",
    "serving_prefill_chunks": "Staging chunks per chunked admission.",
    "serving_slots_active": "Slots holding a resident request right now.",
    "serving_slots_idle": "Slots free for admission right now.",
    "serving_ttft_ms": "Time to first token per request: submit (fleet front door when fleeted) to first generated token.",
    "serving_inter_token_ms": "Inter-token latency: gap between a resident request's consecutive tokens (batch-amortized on the bucket engine).",
    "slo_breach_total": "SLO burn-rate breaches entered (any dimension; see slo_breach_<dim>_total).",
    "slo_recoveries_total": "SLO breach recoveries (fast-window burn back under threshold).",
    "slo_burn_rate": "Worst sustained SLO burn rate across dimensions (min of fast/slow windows).",
    "serving_throughput_tokens_per_sec": "Serving throughput gauge (bench probe).",
    "serving_goodput_ratio": "Completed / offered requests (bench probe).",
    "serving_mfu": "Serving model-FLOPs utilization gauge (bench probe).",
    "executor_cache_hits_total": "Executor-cache hits (no trace, no compile).",
    "executor_cache_misses_total": "Executor-cache misses (a fresh trace + compile).",
    "executor_cache_evictions_total": "Executors dropped by the FIFO cache bound.",
    "compile_total": "Executor builds recorded by the compile ledger.",
    "compile_ms": "Per-executor trace + XLA compile wall time.",
    "retrace_total": "Rebuilds of a logically-same executor (see retrace_reason_*).",
    "compile_ledger_fallback_total": "Executors demoted from AOT ledger dispatch to plain jit.",
    "hbm_bytes_in_use": "Live device memory from memory_stats() (absent on CPU).",
    "kv_cache_resident_bytes": "Live slot-KV bytes: allocated pages + latent-stack caches under the paged layout; equals capacity when dense.",
    "kv_cache_capacity_bytes": "Worst-case slot-KV bytes from the resolved layout's dtype: pool blocks (+ int8 dequant scales) when paged, dense per-slot caches at full context otherwise, + latent-stack caches.",
    "kv_cache_resident_bytes_per_shard": "Model-axis shard of the live KV bytes on a sharded serving mesh (docs/serving.md \"Sharded serving\").",
    "serving_mesh_devices": "Devices claimed by the engine's serving mesh (data x model); absent when serving unsharded.",
    "serving_mesh_data": "Serving-mesh data-axis size (slot/batch parallelism).",
    "serving_mesh_model": "Serving-mesh model-axis size (attention-head / KV tensor parallelism).",
    "kv_pool_blocks": "Usable KV pool capacity in blocks (null block excluded).",
    "kv_pool_blocks_in_use": "Pool blocks currently mapped to live token positions.",
    "kv_pool_blocks_reserved": "Pool blocks reserved by resident requests' worst cases (mapped or not).",
    "kv_pool_blocks_high_water": "Peak pool blocks in use over the engine lifetime.",
    "kv_pool_block_bytes": "Bytes per pool block (block_size positions x per-position k+v at the resolved layout's dtype; scale bytes excluded).",
    "kv_pool_block_scale_bytes": "Per-block dequant-scale bytes under kv_layout='paged_int8' (f32 per position/head/tensor); 0 for exact layouts.",
    "kv_quant_fallback_total": "Autotune runs whose int8 quality gate failed, degrading the verdict to an exact layout (docs/serving.md \"Quantized KV\").",
    "kv_ragged_kernel_steps_total": "Decode steps served by the ragged paged-attention kernel (PERCEIVER_RAGGED_KERNEL=1) instead of the gather-to-dense reference.",
    "kv_ragged_kernel_enabled": "1 when a paged engine dispatches the ragged paged-attention kernel, 0 when on the gather reference.",
    "kv_pool_block_allocs_total": "Pool block map operations (admit, chunk progress, decode page crossings).",
    "kv_pool_block_frees_total": "Pool blocks returned on retire/failure.",
    "kv_pool_admit_waits_total": "Requests that waited at the queue head for pool blocks to free.",
    "kv_prefix_hits_total": "Paged admissions that mapped at least one cached prefix block by reference.",
    "kv_prefix_misses_total": "Paged admissions with no usable cached prefix (prefix cache on).",
    "kv_prefix_shared_blocks_total": "Pool blocks mapped by reference (full + COW'd partial) across hit admissions.",
    "kv_prefix_shared_tokens_total": "Prompt token positions whose projection was skipped via prefix sharing.",
    "kv_prefix_cow_copies_total": "Copy-on-write page copies (partial/divergent block at admit, or the decode write guard).",
    "kv_prefix_evicted_blocks_total": "Cached prefix blocks LRU-dropped from the index under pool pressure.",
    "kv_prefix_published_blocks_total": "Full prefix blocks published into the prefix index after admission.",
    "kv_prefix_cached_blocks": "Pool blocks currently retained by the prefix index.",
    "kv_preemptions_total": "Residents preempted under pool pressure: pages returned, request requeued for recompute-from-prompt replay (docs/serving.md \"Preemption & priorities\").",
    "kv_readmissions_total": "Previously preempted requests readmitted to a slot (each eventually completing token-identically).",
    "kv_swaps_total": "Preemption victims whose KV pages were gathered to host memory instead of discarded (docs/serving.md \"Host-swap preemption\").",
    "kv_swap_restores_total": "Swapped victims restored into free pool blocks at readmission, resuming decode at their pre-preemption position (no prompt replay).",
    "kv_swap_bytes_total": "Bytes moved over the host link by swap extracts + restores (KV pages, int8 scales, and the resumable decode row).",
    "kv_swap_ms": "Fenced wall time of one swap transfer leg (device-to-host extract or host-to-device restore).",
    "kv_pool_headroom_blocks": "Free pool blocks beyond the sum of live reservations — the lazy-admission safety margin; 0 means the next boundary crossing may preempt.",
    "spec_rounds_total": "Speculative draft+verify rounds executed (one fixed-shape round per scheduler pass with speculation on; docs/serving.md \"Speculative decoding\").",
    "spec_tokens_proposed_total": "Draft tokens proposed by the truncated-stack self-draft head (k per active row per round).",
    "spec_tokens_accepted_total": "Draft tokens accepted by the batched verify pass (longest matching prefix; acceptance = accepted / proposed).",
    "spec_tokens_emitted_total": "Tokens emitted by speculative rounds (accepted drafts + the verify pass's own token per row).",
    "executor_resident_bytes": "Sum of recorded executors' temp+output bytes (XLA memory analysis).",
    "trainer_steps_total": "Executed optimizer steps (skipped steps included).",
    "trainer_skipped_steps_total": "Steps discarded by the non-finite skip policy.",
    "trainer_rollbacks_total": "Divergence rollbacks to a saved training state.",
    "trainer_callback_errors_total": "Callbacks that raised and were isolated.",
    "trainer_step_dispatch_ms": "Host dispatch time per step (unfenced; device async).",
    "trainer_step_ms": "Fenced true step time (profiler-trigger runs only).",
    "trainer_steps_per_sec": "Recent steady-state training step rate.",
    "trainer_loss": "Most recently logged training loss.",
    "fleet_requests_submitted_total": "Requests accepted fleet-wide.",
    "fleet_requests_completed_total": "Fleet requests completed exactly once.",
    "fleet_requests_shed_total": "Submissions shed by fleet-level max_pending backpressure.",
    "fleet_requests_timed_out_total": "Fleet requests whose deadline expired before completion.",
    "fleet_requests_failed_total": "Fleet requests failed terminally (failover budget spent or failover off).",
    "fleet_requests_rejected_total": "Submissions rejected as infeasible at the fleet front door.",
    "fleet_requests_cancelled_total": "Fleet requests withdrawn mid-flight via cancel() (gateway client disconnects).",
    "fleet_dispatch_total": "Successful request placements onto a replica.",
    "fleet_failover_total": "Replica-failure events that re-dispatched in-flight work.",
    "fleet_redispatch_total": "Requests re-queued for replay on another replica.",
    "fleet_breaker_open_total": "Circuit-breaker open transitions across replicas.",
    "fleet_replica_failures_total": "Replica failures observed (crash, hang, dispatch fault).",
    "fleet_replica_restarts_total": "Replica rebuilds (crash recovery or rolling restart).",
    "fleet_duplicate_results_total": "Late duplicate completions absorbed by exactly-once dedupe.",
    "fleet_slo_shed_total": "Sheds caused by SLO-tightened admission (also counted in fleet_requests_shed_total).",
    "fleet_replicas": "Replicas owned by the fleet router.",
    "fleet_replicas_healthy": "Replicas with a closed circuit breaker right now.",
    "fleet_replicas_draining": "Replicas currently draining (rolling restart or scale-down in progress).",
    "fleet_request_latency_ms": "Fleet request latency: submit to terminal state (failovers included).",
    "fleet_scale_up_total": "Replicas added to the fleet (autoscaler- or operator-driven).",
    "fleet_scale_down_total": "Replicas retired from the fleet with exactly-once failover of their in-flight work.",
    "fleet_scale_up_failed_total": "Replica spawn attempts that failed (factory raise / fleet.scale_up chaos fault).",
    "autoscaler_evaluations_total": "Autoscaler control-loop polls (one per fleet scheduling pass).",
    "autoscaler_holds_total": "Scale actions suppressed by cooldown or victim ineligibility (hysteresis at work).",
    "autoscaler_ladder_rung": "Current degradation-ladder rung index (0 steady, 1 tighten, 2 scale-up, 3 shed, 4 recover).",
    "autoscaler_breach_streak": "Consecutive polls of fresh scale-up evidence (breach / queue pressure / unhealthy capacity).",
    "autoscaler_healthy_streak": "Consecutive polls of fresh scale-down evidence (no breach, queue under the low watermark).",
    "gateway_connections_total": "TCP connections accepted by the HTTP streaming gateway.",
    "gateway_connections_active": "Gateway connections open right now.",
    "gateway_streams_total": "Generate streams accepted (submission admitted, response streaming).",
    "gateway_streams_active": "Generate streams currently in flight.",
    "gateway_streams_completed_total": "Streams whose request reached a server-side terminal state.",
    "gateway_streams_cancelled_total": "Streams abandoned by the client mid-generation (request cancelled, slot + pool pages freed).",
    "gateway_streams_rejected_total": "Generate submissions answered 400/503 (infeasible or shed) without becoming streams.",
    "gateway_bytes_sent_total": "Bytes written to gateway sockets (token events, terminals, error/metrics responses).",
    "gateway_socket_ttft_ms": "Socket-anchored time to first token: connection accept to the first token byte written.",
    "tracing_spans_total": "Spans offered to the sampling span sink (in-scope and pass-through alike).",
    "tracing_spans_kept_total": "Spans written through to the events sink (head-kept, tail-kept, or pass-through).",
    "tracing_spans_sampled_out_total": "Spans dropped by trace sampling (kept + sampled_out == total).",
    "tracing_traces_kept_total": "Request traces retained: head-sampled, non-ok terminal, or over the slow threshold.",
    "tracing_traces_sampled_out_total": "Clean request traces dropped by head sampling (still in the in-memory ring).",
    "incident_triggers_total": "Flight-recorder trigger firings from the wired seams (suppressed or not).",
    "incident_bundles_total": "Incident bundles written to disk by the flight recorder.",
    "incident_suppressed_total": "Triggers suppressed by per-kind cooldown or the max-bundles budget.",
    "incident_dump_errors_total": "Incident bundle dumps that failed (capture must never compound the incident).",
    "timeline_steps_total": "Scheduler passes recorded into the step timeline ring (docs/observability.md \"Scheduler timeline & post-mortems\").",
    "timeline_records_dropped_total": "Step-timeline records evicted past the ring capacity (--obs.timeline.steps).",
    "timeline_ring_records": "Step-timeline records currently retained in the ring.",
    # the always-published members of the per-tier / per-tenant attribution
    # families get direct entries (the *_has_direct_help satellite bar);
    # other labels resolve through _HELP_PREFIXES below
    "serving_tokens_tier_0_total": "Real tokens generated for requests at the default priority tier 0 (per-tier cost attribution).",
    "kv_pool_tenant_blocks_in_use_default": "Pool blocks currently mapped for untagged (no-tenant) resident requests (per-tenant cost attribution).",
}

#: prefix-matched fallbacks for generated families (per-reason counters,
#: StepTimer gauges) — first hit wins
_HELP_PREFIXES = (
    ("retrace_reason_", "Retraces attributed to this changed cache-key component."),
    ("slo_burn_rate_", "Per-dimension SLO burn rate over one window (bad fraction / error budget)."),
    ("slo_breach_", "SLO breaches entered on this dimension."),
    ("kv_preemptions_tier_", "Preemptions whose victim held this priority tier (neg<k> spells a negative tier)."),
    ("kv_pool_tenant_blocks_in_use_", "Pool blocks currently mapped for this tenant's resident requests (per-tenant cost attribution)."),
    ("serving_tokens_tier_", "Real tokens generated for requests at this priority tier (per-tier cost attribution)."),
)


def help_text(name: str) -> Optional[str]:
    """Human description for a canonical family, or None for ad-hoc names."""
    known = HELP_TEXT.get(name)
    if known is not None:
        return known
    for prefix, text in _HELP_PREFIXES:
        if name.startswith(prefix):
            return text
    return None


def _sanitize(name: str) -> str:
    """Prometheus metric names: [a-zA-Z_:][a-zA-Z0-9_:]*."""
    out = "".join(c if (c.isalnum() or c in "_:") else "_" for c in name)
    return out if out and not out[0].isdigit() else f"_{out}"


def _num(value: float) -> str:
    """Full-precision numeric rendering: '%g' would quantize counters past
    1e6 (12,345,678 -> 1.23457e+07), corrupting scraped rate()/delta math.
    Integral values render bare; others use the shortest round-trip repr."""
    value = float(value)
    if value.is_integer():
        return str(int(value))
    return repr(value)


def to_prometheus_text(registry: MetricsRegistry) -> str:
    """Render the registry in Prometheus exposition format (counters,
    gauges, histogram summaries), sorted by name for stable diffs. Every
    canonical family gets a ``# HELP`` line (:data:`HELP_TEXT`); ad-hoc
    names render with ``# TYPE`` only."""
    snap = registry.snapshot()
    lines = []

    def _header(name: str, metric: str, kind: str) -> None:
        desc = help_text(name)
        if desc is not None:
            lines.append(f"# HELP {metric} {desc}")
        lines.append(f"# TYPE {metric} {kind}")

    for name, value in sorted(snap["counters"].items()):
        metric = _sanitize(name)
        _header(name, metric, "counter")
        lines.append(f"{metric} {_num(value)}")
    for name, value in sorted(snap["gauges"].items()):
        metric = _sanitize(name)
        _header(name, metric, "gauge")
        lines.append(f"{metric} {_num(value)}")
    for name, summ in sorted(snap["histograms"].items()):
        metric = _sanitize(name)
        _header(name, metric, "summary")
        for q, key in _QUANTILES:
            if summ[key] is not None:
                lines.append(f'{metric}{{quantile="{q}"}} {_num(summ[key])}')
        lines.append(f"{metric}_sum {_num(summ['sum'])}")
        lines.append(f"{metric}_count {_num(summ['count'])}")
    return "\n".join(lines) + ("\n" if lines else "")


def snapshot_json(registry: MetricsRegistry, *, indent: Optional[int] = None,
                  extra: Optional[dict] = None) -> str:
    """Registry snapshot as JSON; ``extra`` keys are merged at the top level
    (the serve CLI embeds the compile ledger's table this way, so an offline
    ``obs report`` over the snapshot sees the per-executor costs)."""
    snap = registry.snapshot()
    if extra:
        snap.update(extra)
    return json.dumps(snap, indent=indent, sort_keys=True)


class SnapshotWriter:
    """Periodically dump a registry snapshot to one JSON file, atomically
    (tmp + rename: a reader never sees a torn file).

    :param every_s: minimum seconds between writes; None = only explicit
        ``maybe_write(force=True)`` calls write.
    :param clock: injectable time source (FakeClock in tests).
    :param extra: optional zero-arg callable whose dict result is merged
        into every written snapshot (e.g. ``lambda: {"compile_ledger":
        default_ledger().snapshot()}``); a raising ``extra`` is dropped for
        that write, never fatal.
    """

    def __init__(self, registry: MetricsRegistry, path: str,
                 *, every_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic,
                 extra: Optional[Callable[[], dict]] = None):
        self.registry = registry
        self.path = path
        self.every_s = every_s
        self._clock = clock
        self._extra = extra
        self._last_write: Optional[float] = None
        self.writes = 0
        self.write_errors = 0

    def maybe_write(self, *, force: bool = False) -> bool:
        """Write if forced, or if ``every_s`` has elapsed since the last
        write (the first cadenced call always writes). Returns whether a
        write happened.

        A failing write (disk full, path removed mid-run) is counted in
        :attr:`write_errors` and returns False instead of raising —
        telemetry must never kill the run it observes. Path/permission
        misconfigurations still surface early: the CLI resolves and creates
        the parent directory at construction time."""
        now = self._clock()
        due = (
            self.every_s is not None
            and (self._last_write is None or now - self._last_write >= self.every_s)
        )
        if not (force or due):
            return False
        extra = None
        if self._extra is not None:
            try:
                extra = self._extra()
            except Exception:
                extra = None  # telemetry enrichment must not block the write
        tmp = self.path + ".tmp"
        try:
            with open(tmp, "w") as fh:
                fh.write(snapshot_json(self.registry, indent=2, extra=extra))
            os.replace(tmp, self.path)
        except OSError:
            self.write_errors += 1
            return False
        self._last_write = now
        self.writes += 1
        return True
