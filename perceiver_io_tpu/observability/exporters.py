"""Exporters: Prometheus text format and JSON snapshots of a
:class:`~perceiver_io_tpu.observability.MetricsRegistry`.

Two formats, one source:

- :func:`to_prometheus_text` — the ``text/plain; version=0.0.4`` exposition
  format a scrape endpoint (or a human with ``curl``) reads. Histograms
  render as Prometheus *summaries* (quantile series + ``_sum``/``_count``):
  we keep raw reservoirs, not fixed buckets, so quantiles are the honest
  export.
- :func:`snapshot_json` / :class:`SnapshotWriter` — the machine-readable
  snapshot the serve CLI appends to ``serve_stats``, the trainer drops next
  to ``metrics.jsonl``, and ``bench.py`` embeds in its record so every
  BENCH_* file carries telemetry.

``SnapshotWriter`` is cadence-gated on an injectable clock
(``--obs.snapshot_every_s``): callers invoke :meth:`SnapshotWriter.maybe_write`
opportunistically from their own loop (the trainer at each log flush, the
serve CLI per drain pass) and the writer decides whether enough time has
passed — no background thread to leak.
"""
from __future__ import annotations

import json
import os
import time
from typing import Callable, Optional

from perceiver_io_tpu.observability.registry import MetricsRegistry

_QUANTILES = (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99"))


def _sanitize(name: str) -> str:
    """Prometheus metric names: [a-zA-Z_:][a-zA-Z0-9_:]*."""
    out = "".join(c if (c.isalnum() or c in "_:") else "_" for c in name)
    return out if out and not out[0].isdigit() else f"_{out}"


def _num(value: float) -> str:
    """Full-precision numeric rendering: '%g' would quantize counters past
    1e6 (12,345,678 -> 1.23457e+07), corrupting scraped rate()/delta math.
    Integral values render bare; others use the shortest round-trip repr."""
    value = float(value)
    if value.is_integer():
        return str(int(value))
    return repr(value)


def to_prometheus_text(registry: MetricsRegistry) -> str:
    """Render the registry in Prometheus exposition format (counters,
    gauges, histogram summaries), sorted by name for stable diffs."""
    snap = registry.snapshot()
    lines = []
    for name, value in sorted(snap["counters"].items()):
        metric = _sanitize(name)
        lines.append(f"# TYPE {metric} counter")
        lines.append(f"{metric} {_num(value)}")
    for name, value in sorted(snap["gauges"].items()):
        metric = _sanitize(name)
        lines.append(f"# TYPE {metric} gauge")
        lines.append(f"{metric} {_num(value)}")
    for name, summ in sorted(snap["histograms"].items()):
        metric = _sanitize(name)
        lines.append(f"# TYPE {metric} summary")
        for q, key in _QUANTILES:
            if summ[key] is not None:
                lines.append(f'{metric}{{quantile="{q}"}} {_num(summ[key])}')
        lines.append(f"{metric}_sum {_num(summ['sum'])}")
        lines.append(f"{metric}_count {_num(summ['count'])}")
    return "\n".join(lines) + ("\n" if lines else "")


def snapshot_json(registry: MetricsRegistry, *, indent: Optional[int] = None) -> str:
    return json.dumps(registry.snapshot(), indent=indent, sort_keys=True)


class SnapshotWriter:
    """Periodically dump a registry snapshot to one JSON file, atomically
    (tmp + rename: a reader never sees a torn file).

    :param every_s: minimum seconds between writes; None = only explicit
        ``maybe_write(force=True)`` calls write.
    :param clock: injectable time source (FakeClock in tests).
    """

    def __init__(self, registry: MetricsRegistry, path: str,
                 *, every_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.registry = registry
        self.path = path
        self.every_s = every_s
        self._clock = clock
        self._last_write: Optional[float] = None
        self.writes = 0
        self.write_errors = 0

    def maybe_write(self, *, force: bool = False) -> bool:
        """Write if forced, or if ``every_s`` has elapsed since the last
        write (the first cadenced call always writes). Returns whether a
        write happened.

        A failing write (disk full, path removed mid-run) is counted in
        :attr:`write_errors` and returns False instead of raising —
        telemetry must never kill the run it observes. Path/permission
        misconfigurations still surface early: the CLI resolves and creates
        the parent directory at construction time."""
        now = self._clock()
        due = (
            self.every_s is not None
            and (self._last_write is None or now - self._last_write >= self.every_s)
        )
        if not (force or due):
            return False
        tmp = self.path + ".tmp"
        try:
            with open(tmp, "w") as fh:
                fh.write(snapshot_json(self.registry, indent=2))
            os.replace(tmp, self.path)
        except OSError:
            self.write_errors += 1
            return False
        self._last_write = now
        self.writes += 1
        return True
