"""Scheduler step timeline (docs/observability.md "Scheduler timeline &
post-mortems").

Spans (``tracing.py``) answer *what happened to one request*; the metrics
registry answers *how much in aggregate*. Neither records **what the
scheduler did each pass** — which requests admitted, which slots decoded
real rows vs padding, who was preempted for whom, and where the wall time
of the pass went. The serving papers this repo reproduces (PAPERS.md: the
Gemma-on-TPU serving comparison, the ragged paged-attention kernel paper)
justify their scheduling and kernel choices with exactly that step-level
occupancy/phase evidence; :class:`StepTimeline` is the instrument.

Both engines (:class:`~perceiver_io_tpu.serving.engine.ServingEngine`
micro-batch passes, :class:`~perceiver_io_tpu.serving.slots.SlotServingEngine`
token-granular passes) append ONE structured record per scheduler pass when
an operator attaches a timeline (``engine.timeline = StepTimeline(...)`` /
``--obs.timeline.steps``). A record is a plain JSON-serializable dict:

- ``step``        monotone pass index (assigned here, never reused)
- ``engine``      ``"slots"`` | ``"bucket"``
- ``t_start_s`` / ``t_end_s``  pass window on the ENGINE clock (the
  injectable one — composes with :class:`~perceiver_io_tpu.reliability.FakeClock`
  so chaos drills replay bit-identically)
- ``phases_ms``   per-phase wall ms within the pass (slots: ``admit`` /
  ``decode`` / ``account`` + ``total``; bucket: ``assemble`` / ``execute``
  + ``total``)
- ``slots``       occupancy vector: per-slot resident ``request_id`` or None
- ``rows``        real vs padded decode rows this pass (slot engine)
- ``pool``        KV pool blocks in_use / reserved / headroom
- ``tenants``     resident pool pages per tenant (sanitized label)
- event lists keyed by kind — ``admitted`` / ``chunks`` / ``tokens`` /
  ``finished`` / ``preempted`` / ``readmitted`` — each entry a small dict
  carrying the ids the ``obs timeline`` analyzer joins against span events.

Token entries carry the SAME rounded ``ttft_ms`` / ``itl_ms`` values the
span events do, so the analyzer's per-request phase decomposition
telescopes exactly to the registry-recorded ``serving_ttft_ms`` /
``serving_inter_token_ms`` (0.0 unattributed under FakeClock — the
``report.ttft_decomposition`` exactness bar).

The ring is bounded (``cap`` records; evictions counted on
``timeline_records_dropped_total``) and stdlib-only, same as the rest of
the observability package.
"""
from __future__ import annotations

import dataclasses
import json
import os
import re
from collections import deque
from typing import Deque, Dict, List, Optional

#: first line of a timeline JSONL export — readers verify before parsing
TIMELINE_SCHEMA = "step-timeline-v1"

_LABEL_RE = re.compile(r"[^0-9A-Za-z_]")


def tenant_label(tenant: Optional[str]) -> str:
    """Metric-safe label for a tenant id: ``None`` (untagged traffic) maps
    to ``"default"``; anything else keeps ``[0-9A-Za-z_]`` and replaces the
    rest with ``_`` (Prometheus metric-name charset). Collisions after
    sanitization share a label — attribution, not authentication."""
    if tenant is None:
        return "default"
    out = _LABEL_RE.sub("_", str(tenant))
    return out or "default"


def tier_label(tier: int) -> str:
    """Metric-safe label for a priority tier: metric names can't hold
    ``-``, so negative tiers spell the sign out (``neg1``) — the
    ``kv_preemptions_tier_*`` naming convention."""
    tier = int(tier)
    return f"neg{-tier}" if tier < 0 else str(tier)


@dataclasses.dataclass
class TimelineArgs:
    """The ``--obs.timeline.*`` CLI sub-group (nested in
    ``ObservabilityArgs`` like ``slo``/``incident``). Setting ``steps > 0``
    attaches a :class:`StepTimeline` to every serve-run engine; the other
    knobs require it (inapplicable-flag convention)."""

    #: ring capacity in scheduler passes; 0 disables the timeline
    steps: int = 0
    #: write the ring as JSONL here when the serve run ends (the ``obs
    #: timeline`` analyzer's input)
    export: Optional[str] = None
    #: modeled host-link bandwidth (GB/s, decimal) for the preemption
    #: post-mortems' hypothetical swap cost — victim bytes / this rate
    swap_gbps: float = 16.0

    @property
    def enabled(self) -> bool:
        return self.steps > 0


class StepTimeline:
    """Bounded ring of per-scheduler-pass records (one ``append`` per
    ``engine.step()`` call). Thread-compat with the engines' existing
    single-scheduler discipline — no lock; the appending engine owns it."""

    def __init__(self, cap: int = 256, registry=None):
        if cap < 1:
            raise ValueError(f"timeline cap must be >= 1, got {cap}")
        self.cap = int(cap)
        self._records: Deque[dict] = deque(maxlen=self.cap)
        self._next_step = 0
        self.dropped = 0
        self.registry = registry
        if registry is not None:
            registry.declare_counters(
                "timeline_steps_total", "timeline_records_dropped_total"
            )

    def __len__(self) -> int:
        return len(self._records)

    def append(self, record: dict) -> dict:
        """Stamp ``record`` with the next pass index and append it,
        evicting (and counting) the oldest record past ``cap``."""
        record = dict(record)
        record["step"] = self._next_step
        self._next_step += 1
        if len(self._records) == self.cap:
            self.dropped += 1
            if self.registry is not None:
                self.registry.inc("timeline_records_dropped_total")
        self._records.append(record)
        if self.registry is not None:
            self.registry.inc("timeline_steps_total")
            self.registry.set_gauge("timeline_ring_records", len(self._records))
        return record

    def records(self) -> List[dict]:
        return list(self._records)

    def last(self) -> Optional[dict]:
        return self._records[-1] if self._records else None

    def clear(self) -> None:
        self._records.clear()

    def summary(self) -> dict:
        """Aggregate view for ``stats()`` / ``serve_stats``: pass counts,
        ring occupancy, and per-kind event totals over the retained ring."""
        kinds: Dict[str, int] = {}
        for rec in self._records:
            for key, value in rec.items():
                if isinstance(value, list) and key != "slots":
                    kinds[key] = kinds.get(key, 0) + len(value)
        return {
            "steps": self._next_step,
            "retained": len(self._records),
            "cap": self.cap,
            "dropped": self.dropped,
            "events": dict(sorted(kinds.items())),
        }

    # -- persistence ---------------------------------------------------------
    def write_jsonl(self, path: str) -> int:
        """Write the retained ring as JSONL: one schema header line, then
        one record per line (the ``obs timeline`` analyzer's input format).
        Returns the number of records written. Atomic (dot-tmp rename),
        same discipline as the flight recorder's bundle dump."""
        header = {
            "schema": TIMELINE_SCHEMA,
            "cap": self.cap,
            "dropped": self.dropped,
            "steps": self._next_step,
        }
        directory = os.path.dirname(os.path.abspath(path))
        os.makedirs(directory, exist_ok=True)
        tmp = os.path.join(directory, f".{os.path.basename(path)}.tmp")
        with open(tmp, "w", encoding="utf-8") as fh:
            fh.write(json.dumps(header, sort_keys=True) + "\n")
            for rec in self._records:
                fh.write(json.dumps(rec, sort_keys=True) + "\n")
        os.replace(tmp, path)
        return len(self._records)


def read_timeline_jsonl(path: str) -> List[dict]:
    """Read a :meth:`StepTimeline.write_jsonl` export back: verifies the
    schema header and returns the record dicts in step order. Tolerates a
    torn final line (the events.jsonl reader's convention)."""
    records: List[dict] = []
    with open(path, "r", encoding="utf-8") as fh:
        first = fh.readline()
        if not first.strip():
            return records
        header = json.loads(first)
        schema = header.get("schema")
        if schema != TIMELINE_SCHEMA:
            raise ValueError(
                f"not a step-timeline export: schema {schema!r} "
                f"(expected {TIMELINE_SCHEMA!r})"
            )
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                break  # torn tail from an interrupted writer
    return records
