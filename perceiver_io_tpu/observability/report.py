"""``obs report`` — offline analyzer over ``events.jsonl`` + a metrics
snapshot: the dashboard-less debugging path.

A BENCH file or a production serve run leaves two artifacts behind —
span events (``--obs.events_path``) and a registry snapshot
(``--obs.snapshot_path``, ledger table included). This module turns them
back into the four questions an operator asks first, with no dashboard,
no scrape endpoint, and no live process:

1. **Per-phase latency breakdown** — every span family's count, total,
   p50/p95/max, so "where did the wall time go" reads off one table.
2. **Worst-request waterfall** — the slowest terminal ``serving.request``
   trace, with every span/event on that trace laid out by offset from
   submit.
3. **Compile/memory table** — the device-cost ledger's per-executor rows
   (compile ms, FLOPs, bytes accessed, temp/output/argument bytes, retrace
   reasons), read from the snapshot's ``compile_ledger`` or — when only
   events exist — from the ``ledger.compile`` events the serve CLI
   forwards.
4. **Padding waste** — prompt-token and decode-row real-vs-padded ratios
   from the snapshot counters.
5. **Fleet supervision** (when the run had one, docs/serving.md) —
   per-replica completion attribution from the terminal ``fleet.request``
   spans, plus failover / redispatch / breaker-open / duplicate-dedupe
   accounting from the ``fleet_*`` counters.

Percentiles are computed through the SAME
:class:`~perceiver_io_tpu.observability.Histogram` the live registry uses
(nearest-rank over the window), so the report's request-latency breakdown
reproduces what ``stats()`` reported at record time — pinned by
``tests/test_ledger.py``.

Entry points: ``<family CLI> obs report --events events.jsonl
[--snapshot snap.json]`` or ``python -m
perceiver_io_tpu.observability.report events.jsonl --snapshot snap.json``
(also behind ``make obs-report``). Stdlib-only: the analyzer must run
where jax does not.

**`obs incident`** (docs/observability.md "Flight recorder & incident
bundles") is the second analyzer in this module: point it at one
:class:`~perceiver_io_tpu.observability.FlightRecorder` bundle and it
renders the trigger metadata, a causal timeline (breaches, replica
failures, breaker transitions, scale events, cancellations, every non-ok
terminal), the counter movement between the bundle's before/now registry
snapshots, captured state (engine/fleet health, KV pool, autoscaler), and
— the headline — a per-request **TTFT critical-path decomposition**
straight from the span events the engines already emit: front-door wait
(socket accept / fleet queue before the engine submit), engine queue
wait, prefill (chunked admissions included), and the first decode step,
telescoping EXACTLY to the request's recorded ``serving_ttft_ms`` — with
the worst request pinned against the registry's nearest-rank percentiles
like every other section.

**`obs timeline`** (docs/observability.md "Scheduler timeline &
post-mortems") is the third analyzer: point it at a
:class:`~perceiver_io_tpu.observability.StepTimeline` JSONL export
(``--obs.timeline.export``) and it renders the scheduler flight deck — a
per-slot Gantt text view of admissions / prefill chunks / tokens /
retirements / preemptions, per-pass phase percentiles, disposition
accounting, and a per-request ``ttft + Σ itl`` decomposition that
telescopes exactly to the terminal span durations (0.0 unattributed on a
FakeClock run, same bar as the incident TTFT split). ``--trace-out``
additionally emits Chrome-trace JSON built from the ring AND the span
events — load it in Perfetto / ``chrome://tracing``.
"""
from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

from perceiver_io_tpu.observability.registry import Histogram
from perceiver_io_tpu.observability.timeline import read_timeline_jsonl
from perceiver_io_tpu.observability.tracing import (
    TAIL_KEEP_STATUSES,
    read_events_jsonl,
)


def _percentiles(values: List[float]) -> dict:
    """count/total/p50/p95/max via the registry's own Histogram at its
    default window, so offline numbers match the live export's nearest-rank
    convention — including the last-2048 sliding window on runs whose span
    count exceeds it (events stream in observation order)."""
    hist = Histogram()
    for v in values:
        hist.observe(v)
    summ = hist.summary()
    return {
        "count": summ["count"],
        "total_ms": summ["sum"],
        "p50_ms": summ["p50"],
        "p95_ms": summ["p95"],
        "max_ms": summ["max"],
    }


def analyze(events: List[dict], snapshot: Optional[dict] = None) -> dict:
    """Pure analysis over parsed events rows (+ optional snapshot dict);
    returns the JSON-able report body ``format_report`` renders."""
    snapshot = snapshot or {}
    by_span: Dict[str, List[float]] = {}
    for row in events:
        if row.get("span") == "ledger.compile":
            # a forwarded ledger record is a point event — its real cost is
            # attrs.compile_ms, rendered in the compile table below; a
            # 0-duration row here would contradict that table
            continue
        dur = row.get("duration_ms")
        if isinstance(dur, (int, float)):
            by_span.setdefault(row.get("span", "?"), []).append(float(dur))
    phases = {name: _percentiles(vals) for name, vals in sorted(by_span.items())}

    terminals = [r for r in events if r.get("span") == "serving.request"]
    by_status: Dict[str, int] = {}
    for r in terminals:
        status = r.get("status", "?")
        by_status[status] = by_status.get(status, 0) + 1
    latencies = [
        float(r["duration_ms"]) for r in terminals
        if isinstance(r.get("duration_ms"), (int, float))
    ]
    requests = {
        "terminal_spans": len(terminals),
        "by_status": dict(sorted(by_status.items())),
        "latency": _percentiles(latencies) if latencies else None,
    }

    worst = None
    timed = [r for r in terminals if isinstance(r.get("duration_ms"), (int, float))]
    if timed:
        worst_row = max(timed, key=lambda r: r["duration_ms"])
        trace_id = worst_row.get("trace_id")
        trace_rows = [r for r in events if r.get("trace_id") == trace_id]
        t0 = min(
            (r["start_s"] for r in trace_rows if isinstance(r.get("start_s"), (int, float))),
            default=0.0,
        )
        waterfall = []
        for r in sorted(trace_rows, key=lambda r: (r.get("start_s") or 0.0)):
            attrs = r.get("attrs") or {}
            waterfall.append({
                "span": r.get("span"),
                "offset_ms": round(((r.get("start_s") or t0) - t0) * 1e3, 3),
                "duration_ms": r.get("duration_ms"),
                "status": r.get("status"),
                # the scheduling attrs a human reads first; the rest stay
                # in the events file
                "attrs": {
                    k: attrs[k] for k in
                    ("slot", "bucket", "prefill_ms", "chunk", "decode_steps",
                     "size", "execute_ms", "error", "shared_tokens",
                     "shared_blocks", "cow")
                    if k in attrs
                },
            })
        worst = {
            "trace_id": trace_id,
            "status": worst_row.get("status"),
            "duration_ms": worst_row.get("duration_ms"),
            "spans": waterfall,
        }

    compiles = _compile_table(events, snapshot)
    padding = _padding_waste(snapshot)
    return {
        "phases": phases,
        "requests": requests,
        "worst_request": worst,
        "compiles": compiles,
        "padding": padding,
        "fleet": _fleet_section(events, snapshot),
        "kv_pool": _kv_pool_section(snapshot),
        "sharding": _sharding_section(snapshot),
        "slo": _slo_section(events, snapshot),
        "gateway": _gateway_section(events, snapshot),
        "elasticity": _elasticity_section(events, snapshot),
    }


def _elasticity_section(events: List[dict], snapshot: dict) -> Optional[dict]:
    """Fleet-elasticity rollup (docs/serving.md "Elasticity"): the
    scale-event timeline from the ``autoscaler.*`` events (scale-up/-down
    transitions, spawn failures, ladder-rung changes, each with its
    replica-count attrs), scale counts from the ``fleet_scale_*`` counters,
    and the autoscaler's ladder/hysteresis gauges. None when the run had no
    elasticity (pre-autoscaler artifacts stay unchanged)."""
    counters = snapshot.get("counters") or {}
    gauges = snapshot.get("gauges") or {}
    scale_events = [
        r for r in events
        if (r.get("span") or "").startswith("autoscaler.")
    ]
    # the fleet pre-declares fleet_scale_* at 0 (FLEET_COUNTERS), so key
    # PRESENCE means "a fleet ran", not "elasticity happened" — require an
    # autoscaler (its counters only exist when one was built), a nonzero
    # scale count (operator-driven add/remove), or an autoscaler.* event
    has_elasticity = bool(scale_events) or any(
        k.startswith("autoscaler_") for k in counters
    ) or any(
        counters.get(k) for k in counters if k.startswith("fleet_scale_")
    )
    if not has_elasticity:
        return None

    def c(name: str) -> Optional[int]:
        v = counters.get(name)
        return None if v is None else int(v)

    t0 = min(
        (r["start_s"] for r in events
         if isinstance(r.get("start_s"), (int, float))),
        default=0.0,
    )
    timeline = []
    by_event: Dict[str, int] = {}
    for r in sorted(scale_events, key=lambda r: r.get("start_s") or 0.0):
        name = r.get("span", "?")
        by_event[name] = by_event.get(name, 0) + 1
        attrs = r.get("attrs") or {}
        timeline.append({
            "offset_s": round(float(r.get("start_s") or t0) - t0, 6),
            "event": name,
            "replica": attrs.get("replica"),
            "reason": attrs.get("reason"),
            "rung": attrs.get("rung"),
            "replicas_after": attrs.get("replicas_after"),
            "in_flight_replayed": attrs.get("in_flight_replayed"),
        })
    rung = gauges.get("autoscaler_ladder_rung")
    return {
        "scale_ups": c("fleet_scale_up_total"),
        "scale_downs": c("fleet_scale_down_total"),
        "spawn_failures": c("fleet_scale_up_failed_total"),
        "evaluations": c("autoscaler_evaluations_total"),
        "holds": c("autoscaler_holds_total"),
        "ladder_rung": None if rung is None else int(rung),
        "replicas": (
            None if gauges.get("fleet_replicas") is None
            else int(gauges["fleet_replicas"])
        ),
        "events_by_kind": dict(sorted(by_event.items())),
        "timeline": timeline,
    }


def _gateway_section(events: List[dict], snapshot: dict) -> Optional[dict]:
    """HTTP streaming gateway rollup (docs/serving.md "Streaming"): the
    connection/stream table from the ``gateway_*`` counters, per-stream
    outcomes from the ``gateway.request`` events, cancellation accounting
    (``serving.cancelled`` events + the cancelled counters), and the
    socket-vs-engine TTFT delta — ``gateway_socket_ttft_ms`` measures
    accept → first token byte on the wire, ``serving_ttft_ms`` is anchored
    at the same accept instant but ends when the ENGINE materializes the
    token, so the difference is the response-path overhead. None when the
    run had no gateway (pre-gateway artifacts stay unchanged)."""
    counters = snapshot.get("counters") or {}
    gauges = snapshot.get("gauges") or {}
    hists = snapshot.get("histograms") or {}
    gw_events = [r for r in events if r.get("span") == "gateway.request"]
    cancel_events = [r for r in events if r.get("span") == "serving.cancelled"]
    has_gateway = gw_events or any(k.startswith("gateway_") for k in counters)
    if not has_gateway:
        return None

    def c(name: str) -> Optional[int]:
        v = counters.get(name)
        return None if v is None else int(v)

    by_status: Dict[str, int] = {}
    tokens = 0
    stream_bytes = 0
    for r in gw_events:
        status = r.get("status", "?")
        by_status[status] = by_status.get(status, 0) + 1
        attrs = r.get("attrs") or {}
        tokens += int(attrs.get("tokens") or 0)
        stream_bytes += int(attrs.get("bytes") or 0)

    def summ(name: str) -> Optional[dict]:
        h = hists.get(name)
        if h is None:
            return None
        return {
            "count": h.get("count"), "p50_ms": h.get("p50"),
            "p95_ms": h.get("p95"), "max_ms": h.get("max"),
        }

    socket_ttft = summ("gateway_socket_ttft_ms")
    engine_ttft = summ("serving_ttft_ms")
    ttft_delta = None
    if socket_ttft and engine_ttft:
        ttft_delta = {
            q: (
                None
                if socket_ttft[q] is None or engine_ttft[q] is None
                else round(socket_ttft[q] - engine_ttft[q], 3)
            )
            for q in ("p50_ms", "p95_ms")
        }
    # events-only fallback (the slo/fleet-section stance): with no snapshot,
    # the gateway.request events still yield the stream table. "completed"
    # means SERVER-SIDE terminal reached (ok/failed/timed_out alike — the
    # live gateway_streams_completed_total semantics), so it is everything
    # that was not client-cancelled; by_status carries the breakdown.
    streams_total = c("gateway_streams_total")
    streams_completed = c("gateway_streams_completed_total")
    streams_cancelled = c("gateway_streams_cancelled_total")
    source = "snapshot"
    if streams_total is None and gw_events:
        source = "events"
        streams_total = len(gw_events)
        streams_cancelled = by_status.get("cancelled", 0)
        streams_completed = streams_total - streams_cancelled
    return {
        "source": source,
        "connections": {
            "total": c("gateway_connections_total"),
            "active": (
                None if gauges.get("gateway_connections_active") is None
                else int(gauges["gateway_connections_active"])
            ),
        },
        "streams": {
            "total": streams_total,
            "completed": streams_completed,
            "cancelled": streams_cancelled,
            "rejected": c("gateway_streams_rejected_total"),
            "by_status": dict(sorted(by_status.items())),
            "events": len(gw_events),
            "tokens_streamed": tokens,
            "stream_bytes": stream_bytes,
        },
        "cancellations": {
            "events": len(cancel_events),
            "requests_cancelled": c("serving_requests_cancelled_total"),
            "fleet_requests_cancelled": c("fleet_requests_cancelled_total"),
        },
        "bytes_sent": c("gateway_bytes_sent_total"),
        "socket_ttft": socket_ttft,
        "engine_ttft": engine_ttft,
        "socket_vs_engine_ttft_delta_ms": ttft_delta,
    }


def _slo_section(events: List[dict], snapshot: dict) -> Optional[dict]:
    """SLO telemetry rollup (docs/observability.md): TTFT / inter-token
    latency tables, the breach timeline, burn-rate gauges, and the shared
    goodput-under-SLO accounting. Latency percentiles come straight from
    the snapshot's registry histogram summaries — the registry's own
    nearest-rank values, reproduced exactly — with a fallback recomputation
    from ``serving.first_token`` events through the SAME
    :class:`~perceiver_io_tpu.observability.Histogram` when only events
    exist. None when the run recorded nothing SLO-shaped (old artifacts
    stay unchanged)."""
    from perceiver_io_tpu.observability.slo import goodput_ratio, offered_load

    hists = snapshot.get("histograms") or {}
    counters = snapshot.get("counters") or {}
    gauges = snapshot.get("gauges") or {}
    first_tokens = [r for r in events if r.get("span") == "serving.first_token"]
    transitions = [
        r for r in events if r.get("span") in ("slo.breach", "slo.recover")
    ]
    has_slo = (
        "serving_ttft_ms" in hists or "serving_inter_token_ms" in hists
        or first_tokens or transitions
        or any(k.startswith("slo_") for k in counters)
    )
    if not has_slo:
        return None

    def latency(hist_name: str, event_attr: str) -> Optional[dict]:
        summ = hists.get(hist_name)
        if summ is not None:
            return {
                "source": "snapshot", "count": summ.get("count"),
                "p50_ms": summ.get("p50"), "p95_ms": summ.get("p95"),
                "p99_ms": summ.get("p99"), "max_ms": summ.get("max"),
            }
        vals = [
            float((r.get("attrs") or {})[event_attr]) for r in first_tokens
            if isinstance((r.get("attrs") or {}).get(event_attr), (int, float))
        ]
        if not vals:
            return None
        hist = Histogram()
        for v in vals:
            hist.observe(v)
        summ = hist.summary()
        return {
            "source": "events", "count": summ["count"],
            "p50_ms": summ["p50"], "p95_ms": summ["p95"],
            "p99_ms": summ["p99"], "max_ms": summ["max"],
        }

    t0 = min(
        (r["start_s"] for r in events
         if isinstance(r.get("start_s"), (int, float))),
        default=0.0,
    )
    timeline = [
        {
            "offset_s": round(float(r.get("start_s") or t0) - t0, 6),
            "event": r.get("span"),
            "dimension": (r.get("attrs") or {}).get("dimension"),
            "burn_fast": (r.get("attrs") or {}).get("burn_fast"),
            "burn_slow": (r.get("attrs") or {}).get("burn_slow"),
        }
        for r in sorted(transitions, key=lambda r: r.get("start_s") or 0.0)
    ]

    def c(name: str) -> Optional[int]:
        v = counters.get(name)
        return None if v is None else int(v)

    goodput = None
    prefix = "fleet" if any(
        k.startswith("fleet_requests_") for k in counters
    ) else "serving"
    if counters:
        goodput = {
            "prefix": prefix,
            "offered": offered_load(counters, prefix),
            "completed": c(f"{prefix}_requests_completed_total"),
            "ratio": round(goodput_ratio(counters, prefix), 4),
        }
    return {
        "ttft": latency("serving_ttft_ms", "ttft_ms"),
        "inter_token": latency("serving_inter_token_ms", "inter_token_ms"),
        "first_token_events": len(first_tokens),
        "breaches": c("slo_breach_total"),
        "recoveries": c("slo_recoveries_total"),
        "burn_rates": {
            k: gauges[k] for k in sorted(gauges) if k.startswith("slo_burn_rate")
        },
        "timeline": timeline,
        "goodput": goodput,
    }


def _kv_pool_section(snapshot: dict) -> Optional[dict]:
    """Block-paged KV pool rollup (docs/serving.md "Block-paged KV"):
    page utilization / high-water mark from the ``kv_pool_*`` gauges,
    alloc/free churn from the counters, and the live-vs-worst-case byte
    gauges (``kv_cache_resident_bytes`` vs ``kv_cache_capacity_bytes``).
    None when the run had no paged slot engine — dense-run artifacts stay
    unchanged."""
    gauges = snapshot.get("gauges") or {}
    counters = snapshot.get("counters") or {}
    blocks = gauges.get("kv_pool_blocks")
    if blocks is None:
        return None

    def g(name):
        v = gauges.get(name)
        return None if v is None else int(v)

    def c(name):
        v = counters.get(name)
        return None if v is None else int(v)

    in_use = g("kv_pool_blocks_in_use")
    high = g("kv_pool_blocks_high_water")
    # prefix-cache rollup (docs/serving.md "Prefix sharing"): hit/miss
    # ratio, skipped-projection tokens, COW/eviction churn from the
    # kv_prefix_* families (per-admission serving.prefix_hit events render
    # in the request waterfall). None when the run never enabled sharing —
    # pre-prefix artifacts stay unchanged.
    prefix = None
    hits = c("kv_prefix_hits_total")
    misses = c("kv_prefix_misses_total")
    if hits is not None or misses is not None:
        prefix = {
            "hits": hits or 0,
            "misses": misses or 0,
            "hit_ratio": round((hits or 0) / max(1, (hits or 0) + (misses or 0)), 4),
            "shared_blocks": c("kv_prefix_shared_blocks_total"),
            "shared_tokens": c("kv_prefix_shared_tokens_total"),
            "cow_copies": c("kv_prefix_cow_copies_total"),
            "evicted_blocks": c("kv_prefix_evicted_blocks_total"),
            "published_blocks": c("kv_prefix_published_blocks_total"),
            "cached_blocks": g("kv_prefix_cached_blocks"),
        }
    # preemption rollup (docs/serving.md "Preemption & priorities"):
    # preempt/readmit churn plus the live free-beyond-reservations
    # headroom gauge. None when the run never preempted AND never ran
    # lazily — strict-admission artifacts stay unchanged.
    preemption = None
    preempts = c("kv_preemptions_total")
    headroom = g("kv_pool_headroom_blocks")
    if preempts is not None or headroom is not None:
        preemption = {
            "preemptions": preempts or 0,
            "readmissions": c("kv_readmissions_total") or 0,
            "headroom_blocks": headroom,
        }
        # host-swap rollup (docs/serving.md "Host-swap preemption"):
        # victims that paid transfer instead of recompute. Omitted when
        # the run never swapped — recompute artifacts stay unchanged.
        swaps = c("kv_swaps_total")
        if swaps:
            preemption["swaps"] = swaps
            preemption["swap_restores"] = c("kv_swap_restores_total") or 0
            preemption["swap_bytes"] = c("kv_swap_bytes_total") or 0
    return {
        "blocks": int(blocks),
        "blocks_in_use": in_use,
        "blocks_reserved": g("kv_pool_blocks_reserved"),
        "high_water": high,
        "utilization": (
            None if in_use is None else round(in_use / max(1, int(blocks)), 4)
        ),
        "high_water_utilization": (
            None if high is None else round(high / max(1, int(blocks)), 4)
        ),
        "block_allocs": c("kv_pool_block_allocs_total"),
        "block_frees": c("kv_pool_block_frees_total"),
        "admit_waits": c("kv_pool_admit_waits_total"),
        "resident_bytes": g("kv_cache_resident_bytes"),
        "capacity_bytes": g("kv_cache_capacity_bytes"),
        # quantized-KV / ragged-kernel rollup (docs/serving.md "Quantized
        # KV"): nonzero block_scale_bytes is how a report reader tells an
        # int8 pool from an exact one without the engine's stats dict
        "block_bytes": g("kv_pool_block_bytes"),
        "block_scale_bytes": g("kv_pool_block_scale_bytes"),
        "quant_fallbacks": c("kv_quant_fallback_total"),
        "ragged_kernel_enabled": g("kv_ragged_kernel_enabled"),
        "ragged_kernel_steps": c("kv_ragged_kernel_steps_total"),
        "prefix_cache": prefix,
        "preemption": preemption,
    }


def _sharding_section(snapshot: dict) -> Optional[dict]:
    """Sharded-serving rollup (docs/serving.md "Sharded serving"): the mesh
    shape from the ``serving_mesh_*`` gauges, per-shard vs total live KV
    bytes, and the mesh-attributed retrace accounting — the
    ``retrace_reason_mesh_total`` counter plus the distinct ``mesh``
    components in the compile ledger (a mesh flip rebuilds; a reuse would
    show zero here and a stale single-device executor in production). None
    when the run served unsharded — pre-mesh artifacts stay unchanged."""
    gauges = snapshot.get("gauges") or {}
    counters = snapshot.get("counters") or {}
    devices = gauges.get("serving_mesh_devices")
    if devices is None:
        return None

    def g(name):
        v = gauges.get(name)
        return None if v is None else int(v)

    ledger = snapshot.get("compile_ledger") or {}
    meshes = sorted({
        str((rec.get("components") or {}).get("mesh"))
        for rec in ledger.get("records") or []
        if (rec.get("components") or {}).get("mesh")
    })
    resident = g("kv_cache_resident_bytes")
    per_shard = g("kv_cache_resident_bytes_per_shard")
    retraces = counters.get("retrace_reason_mesh_total")
    return {
        "devices": int(devices),
        "data": g("serving_mesh_data"),
        "model": g("serving_mesh_model"),
        "resident_bytes": resident,
        "per_shard_resident_bytes": per_shard,
        "mesh_retraces": None if retraces is None else int(retraces),
        "ledger_meshes": meshes,
    }


def _fleet_section(events: List[dict], snapshot: dict) -> Optional[dict]:
    """Fleet supervision rollup (docs/serving.md): terminal ``fleet.request``
    spans give per-replica completion attribution; the snapshot's ``fleet_*``
    counters give failover / redispatch / breaker accounting. None when the
    run had no fleet layer (single-engine artifacts stay unchanged)."""
    terminals = [r for r in events if r.get("span") == "fleet.request"]
    counters = snapshot.get("counters") or {}
    gauges = snapshot.get("gauges") or {}
    has_counters = any(k.startswith("fleet_") for k in counters)
    if not terminals and not has_counters:
        return None
    by_status: Dict[str, int] = {}
    by_replica: Dict[str, int] = {}
    redispatched = 0
    for r in terminals:
        status = r.get("status", "?")
        by_status[status] = by_status.get(status, 0) + 1
        attrs = r.get("attrs") or {}
        if status == "ok":
            rid = attrs.get("replica")
            if rid is not None:
                by_replica[str(rid)] = by_replica.get(str(rid), 0) + 1
        if (attrs.get("dispatches") or 0) > 1:
            redispatched += 1

    def c(name: str) -> Optional[int]:
        v = counters.get(name)
        return None if v is None else int(v)

    return {
        "terminal_spans": len(terminals),
        "by_status": dict(sorted(by_status.items())),
        "completed_by_replica": dict(sorted(by_replica.items())),
        "requests_redispatched": redispatched,
        "failovers": c("fleet_failover_total"),
        "redispatches": c("fleet_redispatch_total"),
        "breaker_opens": c("fleet_breaker_open_total"),
        "replica_failures": c("fleet_replica_failures_total"),
        "replica_restarts": c("fleet_replica_restarts_total"),
        "duplicates_ignored": c("fleet_duplicate_results_total"),
        "replicas": (
            None if gauges.get("fleet_replicas") is None
            else int(gauges["fleet_replicas"])
        ),
        "replicas_healthy": (
            None if gauges.get("fleet_replicas_healthy") is None
            else int(gauges["fleet_replicas_healthy"])
        ),
    }


def _compile_table(events: List[dict], snapshot: dict) -> dict:
    ledger = snapshot.get("compile_ledger") or {}
    records = list(ledger.get("records") or [])
    source = "snapshot" if records else None
    if not records:
        # fall back to the ledger.compile events the serve CLI forwards
        for row in events:
            if row.get("span") != "ledger.compile":
                continue
            attrs = row.get("attrs") or {}
            records.append({
                "site": attrs.get("site"),
                # the one component the CLI forwards per event — keeps
                # per-bucket rows distinguishable in the rendered table
                "components": (
                    {"bucket_shape": attrs["bucket_shape"]}
                    if attrs.get("bucket_shape") else {}
                ),
                "compile_ms": attrs.get("compile_ms"),
                "flops": attrs.get("flops"),
                "bytes_accessed": attrs.get("bytes_accessed"),
                "temp_bytes": attrs.get("temp_bytes"),
                "output_bytes": attrs.get("output_bytes"),
                "argument_bytes": attrs.get("argument_bytes"),
                "retrace": attrs.get("retrace"),
                "retrace_reasons": [
                    r for r in (attrs.get("reasons") or "").split(",") if r
                ],
            })
        source = "events" if records else None
    reasons: Dict[str, int] = dict(ledger.get("retrace_reasons") or {})
    if not reasons:
        for rec in records:
            for reason in rec.get("retrace_reasons") or []:
                reasons[reason] = reasons.get(reason, 0) + 1
    # prefer the snapshot's LIFETIME rollup fields: on long runs the record
    # table is FIFO-bounded (keep=512) and summing it would under-report;
    # events-only input recomputes from the rows it has
    count = ledger.get("compiles")
    retraces = ledger.get("retraces")
    total_ms = ledger.get("compile_ms_total")
    if count is None:
        count = len(records)
    if retraces is None:
        retraces = sum(1 for r in records if r.get("retrace"))
    if total_ms is None:
        total_ms = round(
            sum(float(r["compile_ms"]) for r in records
                if isinstance(r.get("compile_ms"), (int, float))), 3,
        )
    return {
        "source": source,
        "count": int(count),
        "retraces": int(retraces),
        "retrace_reasons": dict(sorted(reasons.items())),
        "compile_ms_total": float(total_ms),
        "records": records,
    }


def _padding_waste(snapshot: dict) -> Optional[dict]:
    counters = snapshot.get("counters") or {}
    if not counters:
        return None

    def ratio(padded_key: str, total_key: str) -> Optional[float]:
        total = counters.get(total_key)
        if not total:
            return None
        return round(float(counters.get(padded_key, 0.0)) / float(total), 4)

    real = counters.get("serving_prompt_tokens_real_total")
    padded = counters.get("serving_prompt_tokens_padded_total")
    return {
        "prompt_padding_efficiency": (
            None if not padded else round(float(real or 0.0) / float(padded), 4)
        ),
        "decode_rows_padding_waste": ratio(
            "serving_decode_rows_padded_total", "serving_decode_rows_total"
        ),
    }


def _fmt(value, width: int = 10) -> str:
    if value is None:
        return "-".rjust(width)
    if isinstance(value, float):
        return f"{value:,.2f}".rjust(width)
    return f"{value:,}".rjust(width)


def format_report(analysis: dict, *, top: int = 20) -> str:
    """Human-readable rendering of :func:`analyze`'s output."""
    out: List[str] = []

    out.append("== per-phase latency breakdown ==")
    phases = analysis["phases"]
    if phases:
        out.append(
            f"{'span':<28}{'count':>8}{'total_ms':>12}{'p50_ms':>10}"
            f"{'p95_ms':>10}{'max_ms':>10}"
        )
        for name, p in phases.items():
            out.append(
                f"{name:<28}{p['count']:>8}{_fmt(p['total_ms'], 12)}"
                f"{_fmt(p['p50_ms'])}{_fmt(p['p95_ms'])}{_fmt(p['max_ms'])}"
            )
    else:
        out.append("(no timed spans in events)")

    req = analysis["requests"]
    out.append("")
    out.append("== requests ==")
    out.append(
        f"terminal spans: {req['terminal_spans']}  by status: "
        + (", ".join(f"{k}={v}" for k, v in req["by_status"].items()) or "-")
    )
    if req["latency"]:
        lat = req["latency"]
        out.append(
            f"request latency ms: p50={lat['p50_ms']} p95={lat['p95_ms']} "
            f"max={lat['max_ms']} (n={lat['count']})"
        )

    worst = analysis["worst_request"]
    out.append("")
    out.append("== worst-request waterfall ==")
    if worst:
        out.append(
            f"trace {worst['trace_id']}  status={worst['status']}  "
            f"latency={worst['duration_ms']} ms"
        )
        for row in worst["spans"]:
            attrs = "".join(
                f" {k}={v}" for k, v in (row["attrs"] or {}).items()
            )
            out.append(
                f"  +{row['offset_ms']:>10.3f} ms  {row['span']:<24}"
                f" {row['duration_ms'] if row['duration_ms'] is not None else '-':>10}"
                f" ms  [{row['status']}]{attrs}"
            )
    else:
        out.append("(no timed terminal request spans)")

    comp = analysis["compiles"]
    out.append("")
    out.append("== compile/memory ledger ==")
    if comp["count"]:
        out.append(
            f"{comp['count']} compiles ({comp['retraces']} retraces) from "
            f"{comp['source']}; compile_ms_total={comp['compile_ms_total']}"
        )
        if comp["retrace_reasons"]:
            out.append(
                "retrace reasons: "
                + ", ".join(f"{k}={v}" for k, v in comp["retrace_reasons"].items())
            )
        out.append(
            f"{'site':<20}{'compile_ms':>12}{'flops':>14}{'bytes_acc':>12}"
            f"{'temp_B':>10}{'out_B':>10}  retrace"
        )
        ranked = sorted(
            comp["records"],
            key=lambda r: -(r.get("compile_ms") or 0.0),
        )[:top]
        for rec in ranked:
            comps = rec.get("components") or {}
            shape = comps.get("bucket_shape") or comps.get("chunk") or ""
            site = f"{rec.get('site')}{f'[{shape}]' if shape else ''}"
            reason = ",".join(rec.get("retrace_reasons") or []) or "-"
            out.append(
                f"{site:<20}{_fmt(rec.get('compile_ms'), 12)}"
                f"{_fmt(rec.get('flops'), 14)}{_fmt(rec.get('bytes_accessed'), 12)}"
                f"{_fmt(rec.get('temp_bytes'))}{_fmt(rec.get('output_bytes'))}"
                f"  {reason}"
            )
        if len(comp["records"]) > top:
            out.append(f"(+{len(comp['records']) - top} more; --top to widen)")
    else:
        out.append("(no ledger data: pass --snapshot or record ledger.compile events)")

    fleet = analysis.get("fleet")
    if fleet:
        out.append("")
        out.append("== fleet ==")
        replicas = fleet.get("replicas")
        healthy = fleet.get("replicas_healthy")
        if replicas is not None:
            out.append(f"replicas: {healthy}/{replicas} healthy")
        out.append(
            f"terminal spans: {fleet['terminal_spans']}  by status: "
            + (", ".join(f"{k}={v}" for k, v in fleet["by_status"].items()) or "-")
        )
        if fleet["completed_by_replica"]:
            out.append(
                "completed by replica: "
                + ", ".join(
                    f"r{k}={v}" for k, v in fleet["completed_by_replica"].items()
                )
            )
        if fleet["failovers"] is None:
            # events-only input: the fleet.request spans exist but the
            # fleet_* counters live in the snapshot (same fallback stance
            # as the compile table's no-ledger message)
            out.append(
                "(no snapshot: failover/breaker counters unavailable — "
                "pass --snapshot)"
            )
        else:
            out.append(
                f"failovers={fleet['failovers']}  "
                f"redispatches={fleet['redispatches']}  "
                f"breaker_opens={fleet['breaker_opens']}  "
                f"replica_restarts={fleet['replica_restarts']}  "
                f"duplicates_ignored={fleet['duplicates_ignored']}"
            )

    elastic = analysis.get("elasticity")
    if elastic:
        out.append("")
        out.append("== elasticity ==")

        def ev(value):
            return "-" if value is None else value

        out.append(
            f"scale_ups={ev(elastic['scale_ups'])}  "
            f"scale_downs={ev(elastic['scale_downs'])}  "
            f"spawn_failures={ev(elastic['spawn_failures'])}  "
            f"evaluations={ev(elastic['evaluations'])}  "
            f"holds={ev(elastic['holds'])}"
            + (
                f"  ladder_rung={elastic['ladder_rung']}"
                if elastic["ladder_rung"] is not None else ""
            )
            + (
                f"  replicas_now={elastic['replicas']}"
                if elastic["replicas"] is not None else ""
            )
        )
        if elastic["timeline"]:
            out.append("scale-event timeline:")
            for row in elastic["timeline"]:
                detail = "".join(
                    f" {k}={row[k]}" for k in
                    ("replica", "reason", "rung", "replicas_after",
                     "in_flight_replayed")
                    if row.get(k) is not None
                )
                out.append(
                    f"  +{row['offset_s']:>10.3f} s  {row['event']:<24}{detail}"
                )

    slo = analysis.get("slo")
    if slo:
        out.append("")
        out.append("== slo ==")
        out.append(
            f"{'metric':<18}{'count':>8}{'p50_ms':>10}{'p95_ms':>10}"
            f"{'p99_ms':>10}{'max_ms':>10}  source"
        )
        for label, key in (("ttft", "ttft"), ("inter_token", "inter_token")):
            row = slo.get(key)
            if row:
                out.append(
                    f"{label:<18}{_fmt(row['count'], 8)}{_fmt(row['p50_ms'])}"
                    f"{_fmt(row['p95_ms'])}{_fmt(row['p99_ms'])}"
                    f"{_fmt(row['max_ms'])}  {row['source']}"
                )
            else:
                out.append(f"{label:<18}{'-':>8}  (no samples)")
        if slo["breaches"] is not None:
            out.append(
                f"breaches={slo['breaches']}  recoveries={slo['recoveries']}"
            )
        if slo["burn_rates"]:
            out.append(
                "burn rates: "
                + ", ".join(f"{k}={v}" for k, v in slo["burn_rates"].items())
            )
        if slo["timeline"]:
            out.append("breach timeline:")
            for row in slo["timeline"]:
                out.append(
                    f"  +{row['offset_s']:>10.3f} s  {row['event']:<14}"
                    f" dim={row['dimension']}"
                    f" burn_fast={row['burn_fast']}"
                    + (f" burn_slow={row['burn_slow']}"
                       if row["burn_slow"] is not None else "")
                )
        if slo["goodput"]:
            g = slo["goodput"]
            out.append(
                f"goodput ({g['prefix']}): {g['completed']}/{g['offered']} "
                f"offered = {g['ratio']}"
            )

    kv = analysis.get("kv_pool")
    if kv:
        out.append("")
        out.append("== kv pool ==")
        out.append(
            f"blocks: {kv['blocks_in_use']}/{kv['blocks']} in use "
            f"(reserved {kv['blocks_reserved']}, high water {kv['high_water']}"
            f" = {kv['high_water_utilization']})"
        )
        out.append(
            f"churn: allocs={kv['block_allocs']} frees={kv['block_frees']} "
            f"admit_waits={kv['admit_waits']}"
        )
        if kv["resident_bytes"] is not None and kv["capacity_bytes"]:
            out.append(
                f"resident {kv['resident_bytes']:,} B of worst-case "
                f"{kv['capacity_bytes']:,} B "
                f"({kv['resident_bytes'] / kv['capacity_bytes']:.1%})"
            )
        if kv.get("block_bytes") is not None:
            scale = kv.get("block_scale_bytes") or 0
            layout = "paged_int8" if scale else "paged (exact)"
            out.append(
                f"layout: {layout}  block_bytes={kv['block_bytes']:,}"
                + (f" + {scale:,} scale" if scale else "")
                + (
                    f"  quant_fallbacks={kv['quant_fallbacks']}"
                    if kv.get("quant_fallbacks") else ""
                )
            )
        if kv.get("ragged_kernel_enabled"):
            out.append(
                "ragged kernel: on  steps="
                f"{kv.get('ragged_kernel_steps') or 0}"
            )
        pc = kv.get("prefix_cache")
        if pc:
            out.append(
                f"prefix cache: {pc['hits']}/{pc['hits'] + pc['misses']} "
                f"admissions hit (ratio {pc['hit_ratio']})  "
                f"shared_blocks={pc['shared_blocks']} "
                f"shared_tokens={pc['shared_tokens']}"
            )
            out.append(
                f"prefix churn: published={pc['published_blocks']} "
                f"evicted={pc['evicted_blocks']} cow={pc['cow_copies']} "
                f"cached_now={pc['cached_blocks']}"
            )
        pre = kv.get("preemption")
        if pre:
            out.append(
                f"preemption: {pre['preemptions']} preempted, "
                f"{pre['readmissions']} readmitted"
                + (
                    f"  headroom_blocks={pre['headroom_blocks']}"
                    if pre["headroom_blocks"] is not None else ""
                )
            )
            if pre.get("swaps"):
                out.append(
                    f"host swap: {pre['swaps']} swapped out, "
                    f"{pre['swap_restores']} restored, "
                    f"{pre['swap_bytes']:,} B over the link"
                )

    mesh = analysis.get("sharding")
    if mesh:
        out.append("")
        out.append("== sharded serving ==")
        shape = (
            f"{mesh['data']}x{mesh['model']}"
            if mesh["data"] is not None and mesh["model"] is not None
            else "?"
        )
        out.append(f"mesh: {shape} over {mesh['devices']} devices")
        if mesh["resident_bytes"] is not None:
            per = mesh["per_shard_resident_bytes"]
            out.append(
                f"kv resident: {mesh['resident_bytes']:,} B total"
                + (f", {per:,} B per model shard" if per is not None else "")
            )
        out.append(
            f"mesh-attributed retraces: "
            f"{mesh['mesh_retraces'] if mesh['mesh_retraces'] is not None else 0}"
            + (
                "  ledger meshes: " + ", ".join(mesh["ledger_meshes"])
                if mesh["ledger_meshes"] else ""
            )
        )

    gw = analysis.get("gateway")
    if gw:
        out.append("")
        out.append("== gateway ==")
        conns = gw["connections"]
        streams = gw["streams"]

        def v(value):
            return "-" if value is None else value

        out.append(
            f"connections: {v(conns['total'])} total"
            + (f" ({conns['active']} active)" if conns["active"] is not None else "")
            + f"  bytes sent: {v(gw['bytes_sent'])}"
        )
        out.append(
            f"streams: {v(streams['total'])} accepted  "
            f"completed={v(streams['completed'])}  "
            f"cancelled={v(streams['cancelled'])}  "
            f"rejected={v(streams['rejected'])}"
            + (
                "  by status: "
                + ", ".join(f"{k}={n}" for k, n in streams["by_status"].items())
                if streams["by_status"] else ""
            )
            + ("  (from events)" if gw.get("source") == "events" else "")
        )
        canc = gw["cancellations"]
        out.append(
            f"cancellations: {canc['events']} serving.cancelled events, "
            f"requests_cancelled={v(canc['requests_cancelled'])}"
            + (
                f", fleet={canc['fleet_requests_cancelled']}"
                if canc["fleet_requests_cancelled"] is not None else ""
            )
        )
        if gw["socket_ttft"]:
            s = gw["socket_ttft"]
            out.append(
                f"socket ttft ms: p50={s['p50_ms']} p95={s['p95_ms']} "
                f"(n={s['count']})"
            )
        if gw["socket_vs_engine_ttft_delta_ms"]:
            d = gw["socket_vs_engine_ttft_delta_ms"]
            out.append(
                f"socket-vs-engine ttft delta ms: p50={d['p50_ms']} "
                f"p95={d['p95_ms']} (response-path overhead)"
            )

    pad = analysis["padding"]
    out.append("")
    out.append("== padding waste ==")
    if pad:
        out.append(
            f"prompt_padding_efficiency={pad['prompt_padding_efficiency']}  "
            f"decode_rows_padding_waste={pad['decode_rows_padding_waste']}"
        )
    else:
        out.append("(no snapshot counters)")
    return "\n".join(out)


# -- `obs incident`: the flight-recorder bundle analyzer ---------------------

#: events that BELONG on an incident's causal timeline regardless of
#: status — the control-plane transitions around the trigger
_CAUSAL_EVENTS = frozenset({
    "slo.breach", "slo.recover",
    "fleet.replica_failed", "fleet.breaker_open", "fleet.breaker_close",
    "fleet.replica_restarted",
    "autoscaler.scale_up", "autoscaler.scale_down",
    "autoscaler.spawn_failed", "autoscaler.rung",
    "serving.cancelled", "incident.dump",
})

#: terminal statuses that put a request span on the timeline — the same
#: set the sampler tail-keeps, so every trace retained for being dirty
#: also surfaces here
_BAD_STATUSES = TAIL_KEEP_STATUSES


def load_bundle(path: str) -> Tuple[dict, List[dict]]:
    """Read one incident bundle — a directory holding ``manifest.json`` +
    ``spans.jsonl`` (or a direct path to the manifest). Returns
    ``(manifest, spans)``; raises ``ValueError`` on a schema the analyzer
    does not understand."""
    if os.path.isdir(path):
        manifest_path = os.path.join(path, "manifest.json")
    else:
        manifest_path = path
    with open(manifest_path) as fh:
        manifest = json.load(fh)
    if manifest.get("schema") != "incident-bundle-v1":
        raise ValueError(
            f"{manifest_path} is not an incident bundle "
            f"(schema={manifest.get('schema')!r}; expected incident-bundle-v1)"
        )
    spans_path = os.path.join(os.path.dirname(manifest_path), "spans.jsonl")
    spans = read_events_jsonl(spans_path) if os.path.exists(spans_path) else []
    return manifest, spans


def _by_trace(events: List[dict]) -> Dict[str, List[dict]]:
    out: Dict[str, List[dict]] = {}
    for row in events:
        tid = row.get("trace_id")
        if tid is not None:
            out.setdefault(tid, []).append(row)
    return out


def ttft_decomposition(events: List[dict]) -> List[dict]:
    """Per-request TTFT critical-path split from the span events the
    engines already emit, worst first. Anchors are reconstructed from the
    events themselves, so the components TELESCOPE: front_door + queue +
    prefill + first_step == the request's recorded ``serving_ttft_ms``
    exactly (``unattributed`` carries any rounding residue; 0.0 on a
    FakeClock run — the acceptance pin).

    Per trace: the terminal ``serving.request`` span's (backdated) start
    is the ENGINE submit instant; ``serving.first_token``'s start is the
    token instant and its ``ttft_ms`` attr reaches back to the TTFT
    anchor (fleet front door / gateway socket accept), so the gap before
    engine submit is the front-door share; ``serving.slot_assigned``
    marks prefill completion (``prefill_ms`` device time, first
    ``serving.prefill_chunk`` event marks the admission start when
    chunked); what remains up to the token instant is the first decode
    step. Bucket-engine traces (no slot events) fall back to a two-way
    front-door / engine split (``batch_granular``)."""
    rows: List[dict] = []
    for trace_id, trace in sorted(_by_trace(events).items()):
        first = next(
            (r for r in trace if r.get("span") == "serving.first_token"), None
        )
        if first is None:
            continue
        attrs = first.get("attrs") or {}
        ttft_ms = attrs.get("ttft_ms")
        token_s = first.get("start_s")
        if not isinstance(ttft_ms, (int, float)) or not isinstance(
            token_s, (int, float)
        ):
            continue
        anchor_s = token_s - ttft_ms / 1e3
        terminal = next(
            (r for r in trace if r.get("span") == "serving.request"), None
        )
        submit_s = terminal.get("start_s") if terminal else None
        assigned = next(
            (r for r in trace if r.get("span") == "serving.slot_assigned"),
            None,
        )
        row = {
            "trace_id": trace_id,
            "ttft_ms": round(float(ttft_ms), 3),
            "status": terminal.get("status") if terminal else None,
            "prompt_len": (
                (terminal.get("attrs") or {}).get("prompt_len")
                if terminal else None
            ),
            "slot": attrs.get("slot"),
        }
        components: Dict[str, float] = {}
        if assigned is not None and submit_s is not None:
            a_attrs = assigned.get("attrs") or {}
            prefill_end_s = assigned.get("start_s")
            prefill_ms = float(a_attrs.get("prefill_ms") or 0.0)
            chunks = sorted(
                (r for r in trace if r.get("span") == "serving.prefill_chunk"),
                key=lambda r: r.get("start_s") or 0.0,
            )
            if chunks:
                c0 = chunks[0]
                prefill_start_s = (
                    float(c0.get("start_s") or prefill_end_s)
                    - float((c0.get("attrs") or {}).get("ms") or 0.0) / 1e3
                )
            else:
                prefill_start_s = prefill_end_s - prefill_ms / 1e3
            components = {
                "front_door_ms": (submit_s - anchor_s) * 1e3,
                "queue_ms": (prefill_start_s - submit_s) * 1e3,
                "prefill_ms": (prefill_end_s - prefill_start_s) * 1e3,
                "first_step_ms": (token_s - prefill_end_s) * 1e3,
            }
            if a_attrs.get("chunks") is not None:
                row["prefill_chunks"] = a_attrs["chunks"]
        elif submit_s is not None:
            # bucket engine: tokens materialize at batch completion — only
            # the front-door / engine split is recoverable
            components = {
                "front_door_ms": (submit_s - anchor_s) * 1e3,
                "engine_ms": (token_s - submit_s) * 1e3,
            }
            row["batch_granular"] = True
        else:
            components = {"engine_ms": float(ttft_ms)}
        components = {k: round(v, 3) for k, v in components.items()}
        row["components"] = components
        row["unattributed_ms"] = round(
            float(ttft_ms) - sum(components.values()), 3
        )
        rows.append(row)
    rows.sort(key=lambda r: -r["ttft_ms"])
    return rows


def _counter_movement(manifest: dict) -> Optional[List[dict]]:
    """Counters that MOVED between the bundle's last periodic snapshot and
    the dump-time registry — the incident's disposition delta."""
    metrics = manifest.get("metrics") or {}
    before, now = metrics.get("before"), metrics.get("now")
    if not before or not now:
        return None
    before_c = before.get("counters") or {}
    out = []
    for name, value in sorted((now.get("counters") or {}).items()):
        delta = float(value) - float(before_c.get(name, 0.0))
        if delta:
            out.append({
                "name": name, "before": float(before_c.get(name, 0.0)),
                "now": float(value), "delta": round(delta, 6),
            })
    return out


def analyze_incident(manifest: dict, spans: List[dict]) -> dict:
    """Pure analysis over one loaded bundle; returns the JSON-able body
    ``format_incident_report`` renders."""
    trigger = dict(manifest.get("trigger") or {})
    t0 = min(
        (r["start_s"] for r in spans
         if isinstance(r.get("start_s"), (int, float))),
        default=float(trigger.get("at_s") or 0.0),
    )
    timeline = []
    for r in sorted(spans, key=lambda r: r.get("start_s") or 0.0):
        name = r.get("span", "?")
        status = r.get("status")
        if name not in _CAUSAL_EVENTS and status not in _BAD_STATUSES:
            continue
        attrs = r.get("attrs") or {}
        timeline.append({
            "offset_s": round(float(r.get("start_s") or t0) - t0, 6),
            "event": name,
            "status": status,
            "trace_id": r.get("trace_id"),
            "attrs": {
                k: attrs[k] for k in
                ("dimension", "burn_fast", "replica", "reason", "rung",
                 "error", "in_flight", "stage", "cause", "trigger",
                 "bundle", "replicas_after")
                if k in attrs
            },
        })
    now = (manifest.get("metrics") or {}).get("now") or {}
    hists = now.get("histograms") or {}

    def summ(name: str) -> Optional[dict]:
        h = hists.get(name)
        if h is None:
            return None
        return {
            "count": h.get("count"), "p50_ms": h.get("p50"),
            "p95_ms": h.get("p95"), "p99_ms": h.get("p99"),
            "max_ms": h.get("max"),
        }

    decomposition = ttft_decomposition(spans)
    replays = sum(
        1 for r in spans
        if r.get("span") == "fleet.dispatch"
        and ((r.get("attrs") or {}).get("attempt") or 1) > 1
    )
    return {
        "trigger": trigger,
        "seq": manifest.get("seq"),
        "spans": len(spans),
        "trigger_offset_s": (
            None if trigger.get("at_s") is None
            else round(float(trigger["at_s"]) - t0, 6)
        ),
        "timeline": timeline,
        "ttft": summ("serving_ttft_ms"),
        "inter_token": summ("serving_inter_token_ms"),
        "decomposition": decomposition,
        "failover_replays": replays,
        "counter_movement": _counter_movement(manifest),
        "sources": manifest.get("sources") or {},
    }


def format_incident_report(analysis: dict, *, top: int = 8) -> str:
    """Human-readable rendering of :func:`analyze_incident`'s output."""
    out: List[str] = []
    trig = analysis["trigger"]
    out.append("== incident ==")
    out.append(
        f"trigger: {trig.get('kind')}  seq={analysis.get('seq')}  "
        f"spans={analysis['spans']}"
        + (
            f"  at +{analysis['trigger_offset_s']:.3f} s"
            if analysis.get("trigger_offset_s") is not None else ""
        )
    )
    out.append(f"reason: {trig.get('reason')}")
    if trig.get("trace_ids"):
        out.append("trace ids: " + ", ".join(trig["trace_ids"]))

    out.append("")
    out.append("== causal timeline ==")
    if analysis["timeline"]:
        for row in analysis["timeline"]:
            attrs = "".join(
                f" {k}={v}" for k, v in (row["attrs"] or {}).items()
            )
            status = (
                f" [{row['status']}]"
                if row["status"] not in (None, "ok") else ""
            )
            trace = f"  ({row['trace_id']})" if row.get("trace_id") else ""
            out.append(
                f"  +{row['offset_s']:>10.3f} s  {row['event']:<26}"
                f"{status}{attrs}{trace}"
            )
    else:
        out.append("(no causal events in the span slice)")

    out.append("")
    out.append("== per-request ttft decomposition ==")
    rows = analysis["decomposition"]
    if rows:
        keys = ("front_door_ms", "queue_ms", "prefill_ms", "first_step_ms",
                "engine_ms")
        out.append(
            f"{'trace':<16}{'ttft_ms':>10}"
            + "".join(f"{k[:-3]:>12}" for k in keys)
            + f"{'unattrib':>10}  status"
        )
        for row in rows[:top]:
            comp = row["components"]
            out.append(
                f"{str(row['trace_id']):<16}{_fmt(row['ttft_ms'])}"
                + "".join(_fmt(comp.get(k), 12) for k in keys)
                + f"{_fmt(row['unattributed_ms'])}  {row.get('status') or '-'}"
            )
        if len(rows) > top:
            out.append(f"(+{len(rows) - top} more; --top to widen)")
        if analysis.get("failover_replays"):
            out.append(
                f"failover replays in slice: {analysis['failover_replays']} "
                "(re-dispatched requests replay from their prompts; the "
                "replay wait lands in front_door)"
            )
    else:
        out.append("(no serving.first_token events in the span slice)")

    ttft = analysis.get("ttft")
    if ttft:
        out.append("")
        out.append("== registry percentiles (nearest-rank, at dump) ==")
        out.append(
            f"{'metric':<14}{'count':>8}{'p50_ms':>10}{'p95_ms':>10}"
            f"{'p99_ms':>10}{'max_ms':>10}"
        )
        for label, key in (("ttft", "ttft"), ("inter_token", "inter_token")):
            row = analysis.get(key)
            if row:
                out.append(
                    f"{label:<14}{_fmt(row['count'], 8)}{_fmt(row['p50_ms'])}"
                    f"{_fmt(row['p95_ms'])}{_fmt(row['p99_ms'])}"
                    f"{_fmt(row['max_ms'])}"
                )
        if rows and rows[0]["ttft_ms"] is not None and ttft.get("max_ms"):
            out.append(
                f"worst decomposed request = {rows[0]['ttft_ms']} ms "
                f"(registry max {ttft['max_ms']} ms)"
            )

    movement = analysis.get("counter_movement")
    if movement:
        out.append("")
        out.append("== counter movement (last snapshot -> dump) ==")
        for row in movement:
            out.append(
                f"  {row['name']:<44} {row['before']:>10g} -> "
                f"{row['now']:<10g} (+{row['delta']:g})"
            )

    sources = analysis.get("sources") or {}
    if sources:
        out.append("")
        out.append("== captured state ==")
        for name in sorted(sources):
            state = sources[name]
            if isinstance(state, dict):
                # one line per source: the fields an operator reads first
                keys = [
                    k for k in (
                        "ready", "replicas", "replicas_healthy", "draining",
                        "queue_depth", "rung", "breached", "active_breaches",
                        "in_use", "reserved", "blocks", "leaked",
                        "frees_by_cause", "bundles", "error",
                    ) if k in state
                ]
                summary = "  ".join(f"{k}={state[k]}" for k in keys)
                out.append(f"  {name}: {summary or json.dumps(state)[:160]}")
            else:
                out.append(f"  {name}: {state}")
    return "\n".join(out)


def run_incident(bundle_path: str, *, top: int = 8,
                 as_json: bool = False) -> str:
    """Load one bundle, analyze, return the rendered incident report."""
    manifest, spans = load_bundle(bundle_path)
    analysis = analyze_incident(manifest, spans)
    if as_json:
        return json.dumps(analysis, indent=2, sort_keys=True)
    return format_incident_report(analysis, top=top)


def run(events_path: str, snapshot_path: Optional[str] = None, *,
        top: int = 20, as_json: bool = False) -> str:
    """Load artifacts, analyze, and return the rendered report (the string
    the CLI prints)."""
    events = read_events_jsonl(events_path)
    snapshot = None
    if snapshot_path:
        with open(snapshot_path) as fh:
            snapshot = json.load(fh)
    analysis = analyze(events, snapshot)
    if as_json:
        return json.dumps(analysis, indent=2, sort_keys=True)
    return format_report(analysis, top=top)


# ===========================================================================
# `obs timeline` — the scheduler flight deck (docs/observability.md
# "Scheduler timeline & post-mortems"): render a StepTimeline export as a
# per-slot Gantt text view + per-request phase decomposition, and/or emit
# Chrome-trace JSON (load in Perfetto / chrome://tracing) built from the
# ring and the span events together.
# ===========================================================================

#: Gantt cell glyphs, highest display priority first — a pass where a slot
#: was both decoded and preempted shows the preemption.
_GANTT_PRIORITY = "SXRrap#=."
_GANTT_LEGEND = (
    "S=swapped out  X=preempted  R=restored  r=retired  a=admitted  "
    "p=prefill chunk  #=token  ==resident (no token)  .=idle"
)


def load_timeline(path: str) -> List[dict]:
    """Read a ``--obs.timeline.export`` JSONL back (schema-checked)."""
    return read_timeline_jsonl(path)


def _terminal_spans_by_request(events: List[dict]) -> Dict[int, dict]:
    """``request_id -> terminal serving.request row`` — the join key between
    ring records (request_id) and the span stream (trace_id)."""
    out: Dict[int, dict] = {}
    for row in events:
        if row.get("span") != "serving.request":
            continue
        rid = (row.get("attrs") or {}).get("request_id")
        if rid is not None:
            out[int(rid)] = row
    return out


def _timeline_requests(records: List[dict],
                       events: List[dict]) -> List[dict]:
    """Per-request phase decomposition from the ring's token entries, worst
    first. Token entries carry the SAME rounded ``ttft_ms`` / ``itl_ms``
    values the registry observed, so ``ttft_ms + decode_ms`` telescopes
    exactly to the terminal ``serving.request`` span's duration
    (``unattributed_ms`` == 0.0 on a FakeClock run — the
    :func:`ttft_decomposition` exactness bar).

    A preemption replay re-anchors nothing: the replayed first token's
    ``ttft_ms`` still reaches back to the ORIGINAL anchor, so the segment
    from the LAST ``first=True`` entry onward covers the request end to end
    (earlier entries are the discarded replay — surfaced as
    ``replayed_tokens``). ``unattributed_ms`` goes negative exactly when a
    front door (fleet/gateway) anchored TTFT before the engine submit —
    that share lives outside the engine-side terminal span."""
    toks: Dict[int, List[dict]] = {}
    order: List[int] = []
    for rec in records:
        for e in rec.get("tokens") or []:
            rid = e.get("request_id")
            if rid is None:
                continue
            rid = int(rid)
            if rid not in toks:
                order.append(rid)
                toks[rid] = []
            toks[rid].append(e)
    terminals = _terminal_spans_by_request(events)
    rows: List[dict] = []
    for rid in order:
        entries = toks[rid]
        seg_start, attempts = 0, 0
        for i, e in enumerate(entries):
            if e.get("first"):
                attempts += 1
                seg_start = i
        seg = entries[seg_start:]
        ttft = seg[0].get("ttft_ms") if seg and seg[0].get("first") else None
        decode = round(
            sum(float(e.get("itl_ms") or 0.0) for e in seg[1:]), 3
        )
        row: dict = {
            "request_id": rid,
            "tokens": len(seg),
            "replayed_tokens": len(entries) - len(seg),
            "attempts": attempts,
            "ttft_ms": ttft,
            "decode_ms": decode,
        }
        if ttft is not None:
            row["total_ms"] = round(float(ttft) + decode, 3)
        term = terminals.get(rid)
        if term is not None:
            row["status"] = term.get("status")
            row["trace_id"] = term.get("trace_id")
            dur = term.get("duration_ms")
            if isinstance(dur, (int, float)) and ttft is not None:
                row["span_ms"] = round(float(dur), 3)
                row["unattributed_ms"] = round(
                    float(dur) - float(ttft) - decode, 3
                )
        rows.append(row)
    rows.sort(key=lambda r: -(r.get("total_ms") or -1.0))
    return rows


def analyze_timeline(records: List[dict],
                     events: Optional[List[dict]] = None,
                     snapshot: Optional[dict] = None) -> dict:
    """Pure analysis over StepTimeline records (+ optional span events for
    the request join, + optional registry snapshot for the accounting
    closure); returns the JSON-able body ``format_timeline`` renders."""
    events = events or []
    snapshot = snapshot or {}
    phase_vals: Dict[str, List[float]] = {}
    occ_busy = occ_total = 0
    rows_real = rows_padded = 0
    kinds: Dict[str, int] = {}
    by_status: Dict[str, int] = {}
    queue_depths: List[int] = []
    for rec in records:
        for key, val in (rec.get("phases_ms") or {}).items():
            phase_vals.setdefault(key, []).append(float(val))
        slots = rec.get("slots")
        if isinstance(slots, list):
            occ_busy += sum(1 for s in slots if s is not None)
            occ_total += len(slots)
        rows = rec.get("rows") or {}
        rows_real += int(rows.get("real", 0))
        rows_padded += int(rows.get("padded", 0))
        qd = rec.get("queue_depth")
        if isinstance(qd, int):
            queue_depths.append(qd)
        for kind in ("admitted", "chunks", "tokens", "finished",
                     "preempted", "readmitted", "swapped", "restored"):
            entries = rec.get(kind) or []
            if entries:
                kinds[kind] = kinds.get(kind, 0) + len(entries)
        for e in rec.get("finished") or []:
            status = e.get("status", "?")
            by_status[status] = by_status.get(status, 0) + 1
    # disposition closure over the retained ring: every admission is either
    # still resident, finished, or was preempted back to the queue (each
    # readmission re-admits, so preempted - readmitted nets the requeued)
    accounting = {
        "admitted": kinds.get("admitted", 0),
        "finished": sum(by_status.values()),
        "finished_by_status": dict(sorted(by_status.items())),
        "preempted": kinds.get("preempted", 0),
        "readmitted": kinds.get("readmitted", 0),
        "swapped": kinds.get("swapped", 0),
        "restored": kinds.get("restored", 0),
    }
    counters = snapshot.get("counters") or {}
    if counters:
        accounting["registry"] = {
            name: int(counters.get(f"serving_requests_{name}_total", 0))
            for name in ("completed", "cancelled", "timed_out", "failed")
        }
    last = records[-1] if records else None
    return {
        "meta": {
            "records": len(records),
            "steps": (
                None if not records
                else [records[0].get("step"), records[-1].get("step")]
            ),
            "engines": sorted(
                {str(r.get("engine", "?")) for r in records}
            ),
        },
        "phases": {
            k: _percentiles(v) for k, v in sorted(phase_vals.items())
        },
        "occupancy": {
            "slot_steps_busy": occ_busy,
            "slot_steps_total": occ_total,
            "fraction": (
                round(occ_busy / occ_total, 4) if occ_total else None
            ),
            "queue_depth_max": max(queue_depths, default=0),
        },
        "rows": {
            "real": rows_real,
            "padded": rows_padded,
            "padding_waste": (
                round(rows_padded / (rows_real + rows_padded), 4)
                if rows_real + rows_padded else None
            ),
        },
        "events": dict(sorted(kinds.items())),
        "accounting": accounting,
        "pool": (last or {}).get("pool"),
        "tenants": (last or {}).get("tenants"),
        "requests": _timeline_requests(records, events),
    }


def timeline_gantt(records: List[dict], *, width: int = 96) -> List[str]:
    """Per-slot Gantt over the most recent ``width`` passes: one text row
    per slot, one character per pass (legend: ``_GANTT_LEGEND``; a cell
    takes the highest-priority event that touched it). Bucket-engine rings
    (no occupancy vector) collapse to a single ``batch`` row."""
    slotted = [r for r in records if isinstance(r.get("slots"), list)]
    recs = (slotted or records)[-width:]
    if not recs:
        return ["(no records)"]
    nslots = (
        max(len(r["slots"]) for r in slotted[-width:]) if slotted else 1
    )
    prio = {ch: i for i, ch in enumerate(reversed(_GANTT_PRIORITY))}
    grid = [["."] * len(recs) for _ in range(nslots)]

    def mark(slot, col, ch):
        if slot is None or not 0 <= slot < nslots:
            return
        if prio[ch] > prio[grid[slot][col]]:
            grid[slot][col] = ch

    prev_slots: List = []
    for col, rec in enumerate(recs):
        slots = rec.get("slots") if isinstance(rec.get("slots"), list) else []
        # request -> slot map for slot-less `finished` entries: this pass's
        # slot-carrying events first, then residency (a retiring request
        # left the occupancy vector before the record was cut)
        rid2slot: Dict[int, int] = {}
        for kind in ("tokens", "chunks", "admitted", "preempted"):
            for e in rec.get(kind) or []:
                if e.get("request_id") is not None and e.get("slot") is not None:
                    rid2slot.setdefault(int(e["request_id"]), int(e["slot"]))
        for occ in (slots, prev_slots):
            for i, rid in enumerate(occ):
                if rid is not None:
                    rid2slot.setdefault(int(rid), i)
        for i, rid in enumerate(slots):
            if rid is not None:
                mark(i, col, "=")
        if not slots:  # bucket engine: everything lands on the one row
            if rec.get("tokens"):
                mark(0, col, "#")
            if rec.get("admitted"):
                mark(0, col, "a")
            if rec.get("finished"):
                mark(0, col, "r")
        for e in rec.get("tokens") or []:
            mark(e.get("slot"), col, "#")
        for e in rec.get("chunks") or []:
            mark(e.get("slot"), col, "p")
        for e in rec.get("admitted") or []:
            mark(e.get("slot"), col, "a")
        for e in rec.get("finished") or []:
            rid = e.get("request_id")
            if rid is not None:
                mark(rid2slot.get(int(rid)), col, "r")
        for e in rec.get("preempted") or []:
            mark(e.get("slot"), col, "X")
        for e in rec.get("swapped") or []:
            mark(e.get("slot"), col, "S")
        for e in rec.get("restored") or []:
            mark(e.get("slot"), col, "R")
        prev_slots = slots
    first_step = recs[0].get("step")
    last_step = recs[-1].get("step")
    out = [f"steps {first_step}..{last_step} (one column per pass)"]
    label = "batch" if not slotted else "slot"
    for i, row in enumerate(grid):
        name = label if not slotted else f"{label} {i}"
        out.append(f"  {name:<8}|{''.join(row)}|")
    out.append(f"  {_GANTT_LEGEND}")
    return out


def chrome_trace(records: List[dict],
                 events: Optional[List[dict]] = None) -> dict:
    """Chrome trace-event JSON (the ``chrome://tracing`` / Perfetto load
    format) from the ring + span events: pid 1 holds the scheduler lane
    (one complete ``X`` event per pass, phases/pool in ``args``) and one
    lane per slot (contiguous residency runs as ``X``, lifecycle moments as
    ``i`` instants); pid 2 holds the request spans from events.jsonl, one
    lane per trace. Timestamps are microseconds on the engine clock, per
    the trace-event schema."""
    trace_events: List[dict] = []

    def us(t: float) -> float:
        return round(float(t) * 1e6, 3)

    trace_events.append({
        "ph": "M", "pid": 1, "tid": 0, "name": "process_name",
        "args": {"name": "scheduler timeline"},
    })
    trace_events.append({
        "ph": "M", "pid": 1, "tid": 0, "name": "thread_name",
        "args": {"name": "scheduler"},
    })
    nslots = max(
        (len(r["slots"]) for r in records
         if isinstance(r.get("slots"), list)),
        default=0,
    )
    for s in range(nslots):
        trace_events.append({
            "ph": "M", "pid": 1, "tid": s + 1, "name": "thread_name",
            "args": {"name": f"slot {s}"},
        })
    # residency runs: (slot, request_id, start_s) while the occupant holds
    runs: Dict[int, Tuple[int, float]] = {}
    for rec in records:
        t0, t1 = rec.get("t_start_s"), rec.get("t_end_s")
        if not isinstance(t0, (int, float)) or not isinstance(t1, (int, float)):
            continue
        args = {
            "step": rec.get("step"),
            "queue_depth": rec.get("queue_depth"),
            "phases_ms": rec.get("phases_ms"),
        }
        for key in ("pool", "rows", "tenants"):
            if rec.get(key) is not None:
                args[key] = rec[key]
        trace_events.append({
            "ph": "X", "pid": 1, "tid": 0, "cat": "scheduler",
            "name": f"step {rec.get('step')}",
            "ts": us(t0), "dur": max(us(t1) - us(t0), 0.0), "args": args,
        })
        for kind, label in (("admitted", "admit"), ("preempted", "preempt"),
                            ("swapped", "swap"), ("restored", "restore"),
                            ("readmitted", "readmit"), ("finished", "finish")):
            for e in rec.get(kind) or []:
                slot = e.get("slot")
                trace_events.append({
                    "ph": "i", "pid": 1, "s": "t", "cat": "lifecycle",
                    "tid": slot + 1 if isinstance(slot, int) else 0,
                    "ts": us(t1),
                    "name": f"{label} req {e.get('request_id')}",
                    "args": dict(e),
                })
        slots = rec.get("slots")
        if isinstance(slots, list):
            for i, rid in enumerate(slots):
                open_run = runs.get(i)
                if open_run is not None and (rid is None or int(rid) != open_run[0]):
                    trace_events.append({
                        "ph": "X", "pid": 1, "tid": i + 1, "cat": "residency",
                        "name": f"req {open_run[0]}", "ts": us(open_run[1]),
                        "dur": max(us(t0) - us(open_run[1]), 0.0),
                        "args": {"request_id": open_run[0]},
                    })
                    runs.pop(i)
                if rid is not None and i not in runs:
                    runs[i] = (int(rid), float(t0))
    if records and runs:
        t_last = records[-1].get("t_end_s") or 0.0
        for i, (rid, start) in sorted(runs.items()):
            trace_events.append({
                "ph": "X", "pid": 1, "tid": i + 1, "cat": "residency",
                "name": f"req {rid}", "ts": us(start),
                "dur": max(us(t_last) - us(start), 0.0),
                "args": {"request_id": rid},
            })
    if events:
        trace_events.append({
            "ph": "M", "pid": 2, "tid": 0, "name": "process_name",
            "args": {"name": "request spans"},
        })
        lanes: Dict[str, int] = {}
        for row in events:
            dur = row.get("duration_ms")
            t0 = row.get("start_s")
            if not isinstance(dur, (int, float)) or not isinstance(t0, (int, float)):
                continue
            trace_id = str(row.get("trace_id") or "?")
            tid = lanes.get(trace_id)
            if tid is None:
                tid = lanes[trace_id] = len(lanes) + 1
                trace_events.append({
                    "ph": "M", "pid": 2, "tid": tid, "name": "thread_name",
                    "args": {"name": trace_id},
                })
            args = {"trace_id": trace_id, "status": row.get("status")}
            if row.get("attrs"):
                args.update(row["attrs"])
            trace_events.append({
                "ph": "X", "pid": 2, "tid": tid, "cat": "span",
                "name": str(row.get("span", "?")),
                "ts": us(t0), "dur": round(float(dur) * 1e3, 3),
                "args": args,
            })
    return {
        "traceEvents": trace_events,
        "displayTimeUnit": "ms",
        "otherData": {"schema": "step-timeline-v1"},
    }


def format_timeline(analysis: dict, records: List[dict], *,
                    top: int = 20, width: int = 96) -> str:
    """Human-readable flight-deck rendering of :func:`analyze_timeline`."""
    out: List[str] = []
    meta = analysis["meta"]
    out.append("== scheduler timeline ==")
    steps = meta["steps"]
    out.append(
        f"records: {meta['records']}"
        + (f"  steps {steps[0]}..{steps[1]}" if steps else "")
        + f"  engines: {', '.join(meta['engines']) or '-'}"
    )
    occ = analysis["occupancy"]
    if occ["slot_steps_total"]:
        out.append(
            f"occupancy: {occ['slot_steps_busy']}/{occ['slot_steps_total']} "
            f"slot-steps busy ({occ['fraction']})  "
            f"queue depth max: {occ['queue_depth_max']}"
        )
    rows = analysis["rows"]
    if rows["real"] or rows["padded"]:
        out.append(
            f"decode rows: real={rows['real']} padded={rows['padded']} "
            f"(waste {rows['padding_waste']})"
        )

    out.append("")
    out.append("== per-pass phases (ms) ==")
    if analysis["phases"]:
        out.append(
            f"{'phase':<12}{'count':>8}{'total_ms':>12}{'p50_ms':>10}"
            f"{'p95_ms':>10}{'max_ms':>10}"
        )
        for name, p in analysis["phases"].items():
            out.append(
                f"{name:<12}{p['count']:>8}{_fmt(p['total_ms'], 12)}"
                f"{_fmt(p['p50_ms'])}{_fmt(p['p95_ms'])}{_fmt(p['max_ms'])}"
            )
    else:
        out.append("(no phase marks in ring)")

    acct = analysis["accounting"]
    out.append("")
    out.append("== accounting ==")
    out.append(
        f"admitted={acct['admitted']}  finished={acct['finished']} "
        + (
            "("
            + ", ".join(
                f"{k}={v}" for k, v in acct["finished_by_status"].items()
            )
            + ")  " if acct["finished_by_status"] else " "
        )
        + f"preempted={acct['preempted']}  readmitted={acct['readmitted']}"
        + (
            f"  swapped={acct['swapped']}  restored={acct['restored']}"
            if acct.get("swapped") or acct.get("restored") else ""
        )
    )
    if acct.get("registry"):
        out.append(
            "registry: "
            + "  ".join(f"{k}={v}" for k, v in acct["registry"].items())
        )
    if analysis.get("pool"):
        pool = analysis["pool"]
        out.append(
            f"pool (last pass): in_use={pool.get('in_use')} "
            f"reserved={pool.get('reserved')} headroom={pool.get('headroom')}"
        )
    if analysis.get("tenants"):
        out.append(
            "tenant pages (last pass): "
            + ", ".join(
                f"{k}={v}" for k, v in analysis["tenants"].items()
            )
        )

    reqs = analysis["requests"]
    out.append("")
    out.append("== per-request decomposition (worst first) ==")
    if reqs:
        out.append(
            f"{'request':>8}{'status':>11}{'tok':>5}{'replay':>7}"
            f"{'ttft_ms':>10}{'decode_ms':>11}{'total_ms':>10}"
            f"{'span_ms':>10}{'unattr_ms':>10}"
        )
        for row in reqs[:top]:
            out.append(
                f"{row['request_id']:>8}{str(row.get('status') or '-'):>11}"
                f"{row['tokens']:>5}{row['replayed_tokens']:>7}"
                f"{_fmt(row.get('ttft_ms'))}{_fmt(row.get('decode_ms'), 11)}"
                f"{_fmt(row.get('total_ms'))}{_fmt(row.get('span_ms'))}"
                f"{_fmt(row.get('unattributed_ms'))}"
            )
        if len(reqs) > top:
            out.append(f"  ... {len(reqs) - top} more")
    else:
        out.append("(no token events in ring)")

    out.append("")
    out.append("== slot gantt ==")
    out.extend(timeline_gantt(records, width=width))
    return "\n".join(out)


def run_timeline(timeline_path: str, events_path: Optional[str] = None,
                 snapshot_path: Optional[str] = None, *,
                 trace_out: Optional[str] = None, top: int = 20,
                 as_json: bool = False) -> str:
    """Load a timeline export (+ optional events/snapshot), analyze, and
    return the rendered flight deck; ``trace_out`` additionally writes the
    Chrome-trace JSON next to it."""
    records = load_timeline(timeline_path)
    events = read_events_jsonl(events_path) if events_path else []
    snapshot = None
    if snapshot_path:
        with open(snapshot_path) as fh:
            snapshot = json.load(fh)
    analysis = analyze_timeline(records, events, snapshot)
    extra = ""
    if trace_out:
        with open(trace_out, "w", encoding="utf-8") as fh:
            json.dump(chrome_trace(records, events), fh, sort_keys=True)
        extra = f"\n\nchrome trace: {trace_out} (load in Perfetto)"
    if as_json:
        return json.dumps(analysis, indent=2, sort_keys=True)
    return format_timeline(analysis, records, top=top) + extra


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="perceiver_io_tpu.observability.report",
        description=(
            "Offline obs report over events.jsonl (+ snapshot), or — with "
            "--incident — over one flight-recorder bundle."
        ),
    )
    parser.add_argument("events", nargs="?", default=None,
                        help="events.jsonl path (--obs.events_path)")
    parser.add_argument("--snapshot", default=None,
                        help="metrics snapshot JSON (--obs.snapshot_path)")
    parser.add_argument("--incident", default=None,
                        help="incident bundle directory (or its "
                             "manifest.json) — renders the incident report "
                             "instead of the events report")
    parser.add_argument("--timeline", default=None,
                        help="StepTimeline JSONL export "
                             "(--obs.timeline.export) — renders the "
                             "scheduler flight deck instead of the events "
                             "report (the events positional becomes the "
                             "optional span join input)")
    parser.add_argument("--trace-out", default=None,
                        help="with --timeline: also write Chrome-trace "
                             "JSON here (load in Perfetto / "
                             "chrome://tracing)")
    parser.add_argument("--top", type=int, default=20,
                        help="rows shown in the compile table (report) / "
                             "decomposition (incident)")
    parser.add_argument("--json", action="store_true",
                        help="emit the raw analysis JSON instead of text")
    args = parser.parse_args(argv)
    try:
        if args.incident is not None:
            print(run_incident(args.incident, top=args.top, as_json=args.json))
        elif args.timeline is not None:
            print(run_timeline(
                args.timeline, args.events, args.snapshot,
                trace_out=args.trace_out, top=args.top, as_json=args.json,
            ))
        elif args.events is None:
            parser.error(
                "an events.jsonl path (or --incident / --timeline) is "
                "required"
            )
        else:
            print(run(args.events, args.snapshot, top=args.top,
                      as_json=args.json))
    # JSONDecodeError IS a ValueError — it must be caught first or the
    # generic clause swallows it without the file-name context
    except json.JSONDecodeError as e:
        raise SystemExit(
            f"obs report: artifact is not valid JSON "
            f"({args.incident or args.timeline or args.snapshot or args.events}: {e})"
        )
    except (OSError, ValueError) as e:
        raise SystemExit(f"obs report: {e}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
