"""Unified telemetry layer (docs/observability.md).

Before this package the repo had four telemetry islands — trainer
``metrics.jsonl``/TensorBoard, ``ServingEngine.stats()``,
``inference.executor_cache_stats``, and ``Trainer.fault_stats`` — with no
shared names, no latency attribution, and no export path. Serving-on-TPU
and pjit-scale-training practice (PAPERS.md: the Gemma-on-TPU serving
comparison; the pjit/TPUv4 scalable-training paper) both treat per-phase
latency histograms and goodput/MFU telemetry as prerequisites for perf
work; this is that instrumentation spine:

- :class:`MetricsRegistry` — thread-safe counters / gauges /
  bounded-reservoir histograms (p50/p95/p99/max) with an injectable clock
  (composes with :class:`~perceiver_io_tpu.reliability.FakeClock`).
- :class:`Tracer` / :class:`Span` — per-request trace IDs through the
  ServingEngine lifecycle and per-step spans through the Trainer loop,
  streamed to a rank-0 ``events.jsonl`` (:class:`JsonlSpanSink`).
- :func:`to_prometheus_text` / :func:`snapshot_json` /
  :class:`SnapshotWriter` — one registry, two export formats.
- :class:`ProfilerTrigger` — arms a ``jax.profiler`` capture of the next
  step when the step-time p95 regresses (trainer loop AND the serving
  decode path).
- :class:`CompileLedger` / :func:`default_ledger` — the device-cost
  ledger: per-executor compile wall time, XLA cost/memory analysis, and
  retrace attribution over named cache-key components
  (:mod:`~perceiver_io_tpu.observability.ledger`).
- :mod:`~perceiver_io_tpu.observability.report` — the offline ``obs
  report`` analyzer over ``events.jsonl`` + snapshot.
- :mod:`~perceiver_io_tpu.observability.compat` — the metrics.jsonl
  schema-migration reader.

Everything here is stdlib-only (no jax import at module scope), so the
inference/serving/training layers can depend on it without cycles and the
hot-path cost is dict ops under one lock.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

from perceiver_io_tpu.observability.compat import normalize_row, read_metrics_jsonl
from perceiver_io_tpu.observability.exporters import (
    HELP_TEXT,
    SnapshotWriter,
    help_text,
    snapshot_json,
    to_prometheus_text,
)
from perceiver_io_tpu.observability.flight_recorder import (
    DisconnectWatch,
    FlightRecorder,
    IncidentArgs,
)
from perceiver_io_tpu.observability.ledger import (
    CompileLedger,
    LedgeredExecutor,
    default_ledger,
)
from perceiver_io_tpu.observability.loadgen import (
    GatewayHttpClient,
    HttpStreamHandle,
    LoadGenerator,
    TTFTProbe,
    WorkloadSpec,
)
from perceiver_io_tpu.observability.registry import (
    Histogram,
    MetricsRegistry,
    default_registry,
)
from perceiver_io_tpu.observability.slo import (
    SLOArgs,
    SLOMonitor,
    SLOPolicy,
    goodput_ratio,
    offered_load,
)
from perceiver_io_tpu.observability.timeline import (
    StepTimeline,
    TimelineArgs,
    read_timeline_jsonl,
    tenant_label,
    tier_label,
)
from perceiver_io_tpu.observability.tracing import (
    JsonlSpanSink,
    SamplingSpanSink,
    Span,
    Tracer,
    read_events_jsonl,
)
from perceiver_io_tpu.observability.trigger import ProfilerTrigger


@dataclasses.dataclass
class ObservabilityArgs:
    """The CLI's ``--obs.*`` flag group, shared by ``fit`` and ``serve``.

    All fields default to off: telemetry costs nothing unless asked for,
    matching the ``chaos=None`` / ``tracer=None`` convention.
    """

    #: span events JSONL path (rank-0). For ``fit``, relative paths land
    #: under ``--trainer.default_root_dir``.
    events_path: Optional[str] = None
    #: write a registry snapshot JSON at most every N seconds (the trainer
    #: checks at each log flush; the serve CLI per drain pass)
    snapshot_every_s: Optional[float] = None
    #: snapshot destination; defaults next to the events/metrics files
    snapshot_path: Optional[str] = None
    #: arm a jax.profiler capture of the next step when the step-time p95
    #: exceeds this factor × the warmed-up baseline p95 (None disables).
    #: ``fit`` watches trainer step times; ``serve`` watches the decode
    #: path (slot-engine ``serving_decode_step_ms`` / bucket-engine
    #: ``serving_device_execute_ms``) and captures the next dispatch
    profile_on_regress_factor: Optional[float] = None
    #: head-sample the events.jsonl span stream: fraction of clean request
    #: traces kept, in (0, 1] (docs/observability.md "Trace sampling").
    #: Deterministic (counter-based, no RNG); traces ending in a non-ok
    #: terminal status are ALWAYS kept, as are terminals slower than
    #: ``trace_keep_slow_ms``. Requires ``events_path``. None = keep all.
    trace_sample: Optional[float] = None
    #: tail-keep latency threshold: a sampled-out trace whose terminal
    #: span is at least this slow is retained anyway (None disables)
    trace_keep_slow_ms: Optional[float] = None
    #: on-disk bound for events.jsonl: past it the file rotates once to
    #: ``events.jsonl.1`` (read back transparently); requires
    #: ``events_path``. None = unbounded append (the historical behavior)
    events_max_bytes: Optional[int] = None
    #: the ``--obs.slo.*`` sub-group: SLO targets (p95 TTFT / p95 ITL /
    #: error rate) plus burn-window knobs. Setting any target builds an
    #: :class:`SLOMonitor` for the serve run (docs/observability.md) —
    #: burn-rate gauges, breach counters/events, profiler-trigger arming,
    #: and (with ``--serve.replicas > 1``) tightened fleet admission.
    slo: SLOArgs = dataclasses.field(default_factory=SLOArgs)
    #: the ``--obs.incident.*`` sub-group: the incident flight recorder
    #: (docs/observability.md "Flight recorder & incident bundles").
    #: Setting ``incident.dir`` arms triggered incident bundles at the
    #: serving seams (SLO breach, replica failure, pool exhaustion,
    #: autoscaler escalation, gateway mass-disconnect), each a bounded
    #: atomic spans+state capture the ``obs incident`` analyzer reads.
    incident: IncidentArgs = dataclasses.field(default_factory=IncidentArgs)
    #: the ``--obs.timeline.*`` sub-group: the scheduler step timeline
    #: (docs/observability.md "Scheduler timeline & post-mortems").
    #: Setting ``timeline.steps`` attaches a bounded :class:`StepTimeline`
    #: ring to every serve-run engine — one structured record per
    #: scheduler pass (admissions, chunk progress, retirements,
    #: preemptions, occupancy, per-phase wall ms) the ``obs timeline``
    #: analyzer renders as a Gantt view / Chrome-trace JSON.
    timeline: TimelineArgs = dataclasses.field(default_factory=TimelineArgs)


__all__ = [
    "CompileLedger",
    "DisconnectWatch",
    "FlightRecorder",
    "GatewayHttpClient",
    "HELP_TEXT",
    "Histogram",
    "HttpStreamHandle",
    "IncidentArgs",
    "JsonlSpanSink",
    "LedgeredExecutor",
    "LoadGenerator",
    "TTFTProbe",
    "MetricsRegistry",
    "ObservabilityArgs",
    "ProfilerTrigger",
    "SLOArgs",
    "SLOMonitor",
    "SLOPolicy",
    "SamplingSpanSink",
    "SnapshotWriter",
    "Span",
    "StepTimeline",
    "TimelineArgs",
    "Tracer",
    "WorkloadSpec",
    "default_ledger",
    "default_registry",
    "goodput_ratio",
    "help_text",
    "normalize_row",
    "offered_load",
    "read_events_jsonl",
    "read_metrics_jsonl",
    "read_timeline_jsonl",
    "snapshot_json",
    "tenant_label",
    "tier_label",
    "to_prometheus_text",
]
