"""Process-wide metrics registry: counters, gauges, bounded-reservoir
histograms.

The repo grew four disjoint telemetry islands (trainer ``metrics.jsonl``/TB,
``ServingEngine.stats()``, ``inference.executor_cache_stats``, trainer
``fault_stats``) — none sharing names or an export path. This registry is
the one source of truth they migrate onto: a component increments a counter
under its canonical name exactly once, and every exporter
(:mod:`~perceiver_io_tpu.observability.exporters`), the serve CLI, and the
bench probe read the same numbers.

Design constraints, in order:

- **Cheap on the hot path.** ``inc``/``observe`` are a lock acquire plus a
  dict update — microseconds against millisecond device steps (the slow-tier
  overhead test pins the total at < 2% of a CPU bench step).
- **Thread-safe.** One lock guards every map, so multiple threads can emit
  metrics concurrently (e.g. a front-end thread counting its own events
  while the engine's owner thread drains). NOTE: this makes the *registry*
  safe to share — the ServingEngine queue itself stays synchronous and
  single-owner (``serving/engine.py`` docstring).
- **Deterministic.** Histograms keep a sliding window of the most recent
  observations (a ring buffer, not a random-replacement reservoir), so
  percentiles are a pure function of the observation sequence and chaos
  tests replay bit-identically.
- **Injectable clock.** :meth:`MetricsRegistry.timer` measures on the
  registry's clock, so ``reliability.FakeClock`` drives deterministic
  latency tests with zero sleeps.

Naming convention (Prometheus-style): monotonic counters end in ``_total``,
durations are ``*_ms`` histograms, instantaneous values are bare-named
gauges — e.g. ``serving_requests_completed_total``,
``serving_queue_wait_ms``, ``trainer_steps_per_sec``.
"""
from __future__ import annotations

import contextlib
import threading
import time
from collections import deque
from typing import Callable, Dict, Optional


class Histogram:
    """Bounded-reservoir histogram: lifetime ``count``/``sum``/``max`` plus
    percentiles over a sliding window of the last ``window`` observations.

    The window is a ring buffer — deterministic, O(window) memory — not a
    probabilistic reservoir: serving percentiles should reflect *recent*
    latency anyway, and chaos tests need replayable numbers.
    """

    __slots__ = ("count", "total", "max", "_ring")

    def __init__(self, window: int = 2048):
        if window < 1:
            raise ValueError(f"histogram window must be >= 1, got {window}")
        self.count = 0
        self.total = 0.0
        self.max: Optional[float] = None
        self._ring: deque = deque(maxlen=window)

    def observe(self, value: float) -> None:
        value = float(value)
        self.count += 1
        self.total += value
        if self.max is None or value > self.max:
            self.max = value
        self._ring.append(value)

    def percentile(self, p: float) -> Optional[float]:
        """Nearest-rank percentile over the sliding window (None if empty)."""
        if not self._ring:
            return None
        ordered = sorted(self._ring)
        idx = min(len(ordered) - 1, int(round(p / 100.0 * (len(ordered) - 1))))
        return ordered[idx]

    def summary(self) -> dict:
        """The export shape every consumer sees: lifetime count/sum/max plus
        window p50/p95/p99."""
        return {
            "count": self.count,
            "sum": round(self.total, 6),
            "max": None if self.max is None else round(self.max, 6),
            "p50": _round(self.percentile(50.0)),
            "p95": _round(self.percentile(95.0)),
            "p99": _round(self.percentile(99.0)),
        }


def _round(v: Optional[float]) -> Optional[float]:
    return None if v is None else round(v, 6)


class MetricsRegistry:
    """Thread-safe map of counters, gauges, and histograms.

    :param clock: monotonic time source for :meth:`timer`; tests pass a
        :class:`~perceiver_io_tpu.reliability.FakeClock`.
    :param histogram_window: sliding-window size for new histograms.
    """

    def __init__(self, *, clock: Callable[[], float] = time.monotonic,
                 histogram_window: int = 2048):
        self._lock = threading.Lock()
        self._clock = clock
        self._histogram_window = histogram_window
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        self._histograms: Dict[str, Histogram] = {}

    # -- counters -----------------------------------------------------------
    def inc(self, name: str, value: float = 1.0) -> float:
        """Add ``value`` to counter ``name`` (created at 0); returns the new
        total. Counters are monotonic — use a gauge for values that move both
        ways."""
        if value < 0:
            raise ValueError(f"counter {name} cannot decrease (value={value})")
        with self._lock:
            new = self._counters.get(name, 0.0) + value
            self._counters[name] = new
            return new

    def counter(self, name: str) -> float:
        with self._lock:
            return self._counters.get(name, 0.0)

    def counters(self) -> Dict[str, float]:
        """One consistent copy of the counters map (single lock hold) —
        cheaper than :meth:`snapshot` for pollers that don't need histogram
        summaries (which sort every window under the lock)."""
        with self._lock:
            return dict(self._counters)

    def declare_counters(self, *names: str) -> None:
        """Pre-register counters at 0 so exports show the full schema before
        the first event (a dashboard key that appears only after the first
        failure is a dashboard nobody trusts)."""
        with self._lock:
            for name in names:
                self._counters.setdefault(name, 0.0)

    # -- gauges -------------------------------------------------------------
    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = float(value)

    def gauge(self, name: str) -> Optional[float]:
        with self._lock:
            return self._gauges.get(name)

    # -- histograms ---------------------------------------------------------
    def observe(self, name: str, value: float) -> None:
        with self._lock:
            hist = self._histograms.get(name)
            if hist is None:
                hist = self._histograms[name] = Histogram(self._histogram_window)
            hist.observe(value)

    def histogram(self, name: str) -> Optional[Histogram]:
        with self._lock:
            return self._histograms.get(name)

    def percentile(self, name: str, p: float) -> Optional[float]:
        with self._lock:
            hist = self._histograms.get(name)
            return None if hist is None else hist.percentile(p)

    @contextlib.contextmanager
    def timer(self, name: str):
        """Observe the enclosed region's duration into histogram ``name``,
        in milliseconds, on the registry's (injectable) clock."""
        t0 = self._clock()
        try:
            yield
        finally:
            self.observe(name, (self._clock() - t0) * 1e3)

    # -- export / lifecycle -------------------------------------------------
    def snapshot(self) -> dict:
        """One JSON-able view of everything: ``{"counters", "gauges",
        "histograms"}`` — the export shape both the Prometheus dump and the
        JSON snapshot writer render from."""
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": {k: h.summary() for k, h in self._histograms.items()},
            }

    def reset(self, prefix: str = "") -> None:
        """Zero counters/gauges and drop histograms whose name starts with
        ``prefix`` ('' = everything) — test isolation, and the hook
        ``inference.generate.reset_executor_caches`` uses to rewind the
        executor-cache counters."""
        with self._lock:
            for k in list(self._counters):
                if k.startswith(prefix):
                    self._counters[k] = 0.0
            for k in list(self._gauges):
                if k.startswith(prefix):
                    del self._gauges[k]
            for k in list(self._histograms):
                if k.startswith(prefix):
                    del self._histograms[k]


#: The process-wide default registry. Process-global state (the executor
#: caches in ``inference.generate``/``inference.beam``) counts here; scoped
#: components (one ServingEngine, one Trainer) default to their own registry
#: so two engines never double-count each other's traffic, but accept a
#: shared one for unified export.
_DEFAULT = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    return _DEFAULT
