"""Synthetic-user load generator: offered-load drills against the serving
stack, deterministic end to end.

The SLO layer (``observability/slo.py``, docs/observability.md) judges
serving by latency percentiles *vs offered load* — which needs a load
source with controlled arrival statistics, not "submit everything then
drain". This module is that source: a :class:`LoadGenerator` drives any
object exposing the shared request surface (``submit`` / ``step`` /
``pending`` — both engines and the :class:`~perceiver_io_tpu.serving.FleetRouter`)
with synthetic users.

Two loop disciplines (both standard in serving evaluation — PAPERS.md's
Gemma-on-TPU comparison sweeps offered load open-loop):

- **Open loop** — arrivals come from an arrival process regardless of
  completions, so a saturated engine builds queue instead of silently
  back-pressuring the generator (the failure mode closed-loop-only
  benchmarks hide). Processes: ``poisson`` (exponential inter-arrivals at
  ``rate_rps``), ``bursty`` (bursts of ``burst_size`` back to back, burst
  starts Poisson at ``rate_rps / burst_size``), ``ramp`` (rate ramps
  linearly from ``rate_rps`` to ``ramp_to_rps`` across the run), and
  ``uniform`` (fixed spacing — the deterministic baseline).
- **Closed loop** — ``users`` synthetic users each keep one request in
  flight: submit, await completion, think
  (``workload.think_time_s``), resubmit. Offered load self-limits to
  completion rate — the drill for per-user latency under steady
  concurrency.

Determinism: every random draw (arrival gaps, prompt lengths, prompt
tokens, ``max_new_tokens``, think times) comes from ONE injected
``numpy`` generator, and all timing runs on the injectable clock. Under a
:class:`~perceiver_io_tpu.reliability.FakeClock` the generator *advances*
the clock itself — ``step_cost_s`` per engine step, and straight to the
next arrival when idle — so a whole offered-load drill replays
bit-identically with zero sleeps (tests/test_slo.py pins this). With a
real clock it sleeps instead, and the measured latencies are real.

The report (:meth:`LoadGenerator.run`) carries the shared
goodput-under-SLO accounting (:func:`~perceiver_io_tpu.observability.slo.offered_load`):
offered = accepted + shed + rejected, so saturation shows up as goodput
< 1, never as a shrunk denominator.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Callable, List, Optional, Tuple

import numpy as np

ARRIVALS = ("poisson", "bursty", "ramp", "uniform")
MODES = ("open", "closed")


@dataclasses.dataclass
class WorkloadSpec:
    """Per-request shape distributions, all sampled from the generator's
    injected rng. Ranges are inclusive ``(lo, hi)``."""

    prompt_len: Tuple[int, int] = (4, 12)
    max_new_tokens: Tuple[int, int] = (4, 8)
    #: token-id draw range (lo inclusive, hi exclusive); keep below the
    #: model's vocab and off the pad id
    vocab: Tuple[int, int] = (1, 64)
    #: closed-loop think time between a completion and the user's next
    #: submission, seconds
    think_time_s: Tuple[float, float] = (0.0, 0.0)

    def sample_prompt(self, rng: np.random.Generator) -> np.ndarray:
        lo, hi = self.prompt_len
        n = int(rng.integers(lo, hi + 1))
        return rng.integers(self.vocab[0], self.vocab[1], size=n, dtype=np.int32)

    def sample_max_new(self, rng: np.random.Generator) -> int:
        lo, hi = self.max_new_tokens
        return int(rng.integers(lo, hi + 1))

    def sample_think(self, rng: np.random.Generator) -> float:
        lo, hi = self.think_time_s
        return lo if hi <= lo else float(rng.uniform(lo, hi))


class LoadGenerator:
    """Drive an engine/fleet with a synthetic workload (module docstring).

    :param engine: anything with the shared request surface — ``submit`` /
        ``step`` / ``pending`` (both engines, the fleet router).
    :param workload: the per-request shape distributions.
    :param mode: ``"open"`` or ``"closed"``.
    :param arrival: open-loop arrival process (:data:`ARRIVALS`).
    :param rate_rps: open-loop offered rate (requests/second); for
        ``ramp`` the starting rate.
    :param ramp_to_rps: ``ramp``'s final rate, reached at the last arrival.
    :param burst_size: ``bursty``'s requests per burst.
    :param users: closed-loop concurrent synthetic users.
    :param max_requests: total requests to offer, then drain and stop.
    :param config: optional :class:`GenerationConfig` template; each
        request gets ``dataclasses.replace(config,
        max_new_tokens=sampled)``. None submits with the engine default
        config (no per-request max_new variation).
    :param deadline_s: per-request deadline forwarded to ``submit``.
    :param rng: ``numpy`` Generator or int seed — the run's ONE source of
        randomness.
    :param clock: the engine's clock (share it!). A clock with
        ``advance`` (FakeClock) is driven by the generator; a real clock
        is slept against.
    :param step_cost_s: simulated wall cost of one ``engine.step()`` under
        a FakeClock (ignored for real clocks). This is what makes offered
        rate meaningful in a frozen-clock drill — and the knob a test
        turns up to inject a deterministic latency fault.
    """

    def __init__(self, engine, *, workload: Optional[WorkloadSpec] = None,
                 mode: str = "open", arrival: str = "poisson",
                 rate_rps: float = 10.0, ramp_to_rps: Optional[float] = None,
                 burst_size: int = 4, users: int = 4, max_requests: int = 32,
                 config=None, deadline_s: Optional[float] = None,
                 rng=0, clock: Callable[[], float] = time.monotonic,
                 step_cost_s: float = 0.001):
        if mode not in MODES:
            raise ValueError(f"mode must be one of {MODES}, got {mode!r}")
        if arrival not in ARRIVALS:
            raise ValueError(
                f"arrival must be one of {ARRIVALS}, got {arrival!r}"
            )
        if rate_rps <= 0:
            raise ValueError(f"rate_rps must be > 0, got {rate_rps}")
        if max_requests < 1:
            raise ValueError(f"max_requests must be >= 1, got {max_requests}")
        if users < 1:
            raise ValueError(f"users must be >= 1, got {users}")
        if burst_size < 1:
            raise ValueError(f"burst_size must be >= 1, got {burst_size}")
        if arrival == "ramp" and (ramp_to_rps is None or ramp_to_rps <= 0):
            raise ValueError(
                f"arrival='ramp' needs ramp_to_rps > 0, got {ramp_to_rps}"
            )
        if step_cost_s <= 0:
            # under a FakeClock the step cost is the only thing that moves
            # time while the engine works; zero would spin the open loop
            # forever inside one arrival gap
            raise ValueError(f"step_cost_s must be > 0, got {step_cost_s}")
        self.engine = engine
        self.workload = workload if workload is not None else WorkloadSpec()
        self.mode = mode
        self.arrival = arrival
        self.rate_rps = float(rate_rps)
        self.ramp_to_rps = None if ramp_to_rps is None else float(ramp_to_rps)
        self.burst_size = int(burst_size)
        self.users = int(users)
        self.max_requests = int(max_requests)
        self.config = config
        self.deadline_s = deadline_s
        self.rng = rng if isinstance(rng, np.random.Generator) else np.random.default_rng(rng)
        self._clock = clock
        self.step_cost_s = float(step_cost_s)
        self.handles: List[object] = []
        self.offered = 0
        self.shed = 0
        self.rejected = 0

    # -- time ----------------------------------------------------------------
    def _tick(self) -> None:
        """One engine step, charged ``step_cost_s`` on a FakeClock."""
        self.engine.step()
        advance = getattr(self._clock, "advance", None)
        if advance is not None:
            advance(self.step_cost_s)

    def _wait_until(self, t: float) -> None:
        """Idle until ``t``: jump a FakeClock straight there; nap a real
        one (short naps — a real engine may retire work meanwhile)."""
        advance = getattr(self._clock, "advance", None)
        if advance is not None:
            if t > self._clock():
                advance(t - self._clock())
        else:
            now = self._clock()
            if t > now:
                time.sleep(min(t - now, 0.005))

    # -- arrivals ------------------------------------------------------------
    def _gaps(self) -> List[float]:
        """The full open-loop inter-arrival schedule, drawn up front so the
        offered pattern is independent of service times (the open-loop
        contract)."""
        n = self.max_requests
        rng = self.rng
        if self.arrival == "uniform":
            return [1.0 / self.rate_rps] * n
        if self.arrival == "poisson":
            return [float(g) for g in rng.exponential(1.0 / self.rate_rps, size=n)]
        if self.arrival == "bursty":
            gaps = []
            burst_gap = self.burst_size / self.rate_rps
            for i in range(n):
                if i % self.burst_size == 0:
                    gaps.append(float(rng.exponential(burst_gap)))
                else:
                    gaps.append(0.0)
            return gaps
        # ramp: rate interpolates rate_rps -> ramp_to_rps across arrivals
        gaps = []
        for i in range(n):
            frac = i / max(1, n - 1)
            rate = self.rate_rps + frac * (self.ramp_to_rps - self.rate_rps)
            gaps.append(float(rng.exponential(1.0 / rate)))
        return gaps

    # -- submission ----------------------------------------------------------
    def _submit_one(self) -> Optional[object]:
        from perceiver_io_tpu.reliability import QueueFull

        prompt = self.workload.sample_prompt(self.rng)
        cfg = self.config
        if cfg is not None:
            cfg = dataclasses.replace(
                cfg, max_new_tokens=self.workload.sample_max_new(self.rng)
            )
        self.offered += 1
        try:
            handle = self.engine.submit(prompt, cfg, deadline_s=self.deadline_s)
        except QueueFull:
            self.shed += 1
            return None
        except ValueError:
            self.rejected += 1
            return None
        self.handles.append(handle)
        return handle

    # -- the drills ----------------------------------------------------------
    def _run_open(self) -> None:
        gaps = self._gaps()
        next_at = self._clock()
        for gap in gaps:
            next_at += gap
            # serve residents while waiting out the arrival gap; an idle
            # engine skips straight to the arrival (open loop never slows
            # its offered schedule to match service rate)
            while self._clock() < next_at:
                if self.engine.pending():
                    self._tick()
                else:
                    self._wait_until(next_at)
            self._submit_one()
        while self.engine.pending():
            self._tick()

    def _run_closed(self) -> None:
        # per-user state: (handle or None, next submit time)
        users: List[list] = [[None, self._clock()] for _ in range(self.users)]
        while True:
            now = self._clock()
            for user in users:
                handle, next_at = user
                if handle is not None and handle.done:
                    user[0] = None
                    user[1] = now + self.workload.sample_think(self.rng)
                    handle, next_at = user
                if handle is None and self.offered < self.max_requests and now >= next_at:
                    user[0] = self._submit_one()
            if self.offered >= self.max_requests and not self.engine.pending():
                if all(u[0] is None or u[0].done for u in users):
                    return
            if self.engine.pending():
                self._tick()
            else:
                soonest = min(
                    (u[1] for u in users if u[0] is None), default=None
                )
                if soonest is None or self.offered >= self.max_requests:
                    return
                self._wait_until(max(soonest, now))

    def run(self) -> dict:
        """Offer the whole workload, drain, and return the report:
        generator-side offered/shed/rejected accounting, terminal
        disposition counts from the request handles, wall span on the
        run's clock, and the achieved rates. ``handles`` stays on the
        instance for per-request inspection."""
        t0 = self._clock()
        if self.mode == "open":
            self._run_open()
        else:
            self._run_closed()
        span_s = max(self._clock() - t0, 1e-9)
        by_status: dict = {}
        for h in self.handles:
            by_status[h.status] = by_status.get(h.status, 0) + 1
        completed = by_status.get("ok", 0)
        return {
            "mode": self.mode,
            "arrival": self.arrival if self.mode == "open" else None,
            "offered": self.offered,
            "accepted": len(self.handles),
            "shed": self.shed,
            "rejected": self.rejected,
            "completed": completed,
            "timed_out": by_status.get("timed_out", 0),
            "failed": by_status.get("failed", 0),
            "by_status": dict(sorted(by_status.items())),
            "span_s": round(span_s, 6),
            "offered_rps": round(self.offered / span_s, 4),
            "completed_rps": round(completed / span_s, 4),
            # the shared goodput definition: completed / offered
            # (observability/slo.py — shed and rejected stay in the
            # denominator)
            "goodput_ratio": round(completed / max(1, self.offered), 4),
        }
