"""Synthetic-user load generator: offered-load drills against the serving
stack, deterministic end to end.

The SLO layer (``observability/slo.py``, docs/observability.md) judges
serving by latency percentiles *vs offered load* — which needs a load
source with controlled arrival statistics, not "submit everything then
drain". This module is that source: a :class:`LoadGenerator` drives any
object exposing the shared request surface (``submit`` / ``step`` /
``pending`` — both engines and the :class:`~perceiver_io_tpu.serving.FleetRouter`)
with synthetic users.

Two loop disciplines (both standard in serving evaluation — PAPERS.md's
Gemma-on-TPU comparison sweeps offered load open-loop):

- **Open loop** — arrivals come from an arrival process regardless of
  completions, so a saturated engine builds queue instead of silently
  back-pressuring the generator (the failure mode closed-loop-only
  benchmarks hide). Processes: ``poisson`` (exponential inter-arrivals at
  ``rate_rps``), ``bursty`` (bursts of ``burst_size`` back to back, burst
  starts Poisson at ``rate_rps / burst_size``), ``ramp`` (rate ramps
  linearly from ``rate_rps`` to ``ramp_to_rps`` across the run),
  ``uniform`` (fixed spacing — the deterministic baseline), and ``spike``
  (Poisson at ``rate_rps`` with a ``spike_factor``× rate step over the
  window ``[spike_start_s, spike_start_s + spike_duration_s)`` — the
  flash-crowd workload the fleet-elasticity drill and the
  ``extras.elasticity`` bench offer; docs/serving.md "Elasticity").
- **Closed loop** — ``users`` synthetic users each keep one request in
  flight: submit, await completion, think
  (``workload.think_time_s``), resubmit. Offered load self-limits to
  completion rate — the drill for per-user latency under steady
  concurrency.

Determinism: every random draw (arrival gaps, prompt lengths, prompt
tokens, ``max_new_tokens``, think times) comes from ONE injected
``numpy`` generator, and all timing runs on the injectable clock. Under a
:class:`~perceiver_io_tpu.reliability.FakeClock` the generator *advances*
the clock itself — ``step_cost_s`` per engine step, and straight to the
next arrival when idle — so a whole offered-load drill replays
bit-identically with zero sleeps (tests/test_slo.py pins this). With a
real clock it sleeps instead, and the measured latencies are real.

The report (:meth:`LoadGenerator.run`) carries the shared
goodput-under-SLO accounting — computed through
:func:`~perceiver_io_tpu.observability.slo.offered_load` /
:func:`~perceiver_io_tpu.observability.slo.goodput_ratio`, the SAME
helpers the bench probes and ``obs report`` use: offered = accepted +
shed + rejected, so saturation shows up as goodput < 1, never as a
shrunk denominator.

**HTTP client mode** (docs/serving.md "Streaming"): point the generator
at a :class:`GatewayHttpClient` instead of an engine and the whole drill
runs over real sockets — POST ``/v1/generate`` per request, streamed
tokens read off the wire, shed/reject mapped back from 503/400 — so the
``extras.slo_goodput`` sweep measures goodput-under-SLO through the full
network path (socket-anchored TTFT included) with ONE flag flipped. The
client reports ``bytes_on_wire`` (response bytes received), which
:meth:`LoadGenerator.run` surfaces beside offered/completed. HTTP mode
requires a real clock: sockets cannot be driven by a
:class:`~perceiver_io_tpu.reliability.FakeClock`.
"""
from __future__ import annotations

import dataclasses
import http.client
import json
import threading
import time
from typing import Callable, List, Optional, Tuple

import numpy as np

ARRIVALS = ("poisson", "bursty", "ramp", "uniform", "spike")
MODES = ("open", "closed")


@dataclasses.dataclass
class WorkloadSpec:
    """Per-request shape distributions, all sampled from the generator's
    injected rng. Ranges are inclusive ``(lo, hi)``.

    **Shared prefixes** (docs/serving.md "Prefix sharing"): with
    ``shared_prefix_pool > 0`` every prompt is ``prefix + fresh tail`` —
    the prefix drawn from a pool of ``shared_prefix_pool`` fixed "system
    prompts" (materialized once from the SAME injected rng, so the whole
    workload stays deterministic) sampled by popularity rank from a Zipf
    law with exponent ``shared_prefix_zipf``, the production skew the
    prefix cache exists for. ``prompt_len`` then sizes the per-request
    TAIL, not the whole prompt."""

    prompt_len: Tuple[int, int] = (4, 12)
    max_new_tokens: Tuple[int, int] = (4, 8)
    #: token-id draw range (lo inclusive, hi exclusive); keep below the
    #: model's vocab and off the pad id
    vocab: Tuple[int, int] = (1, 64)
    #: closed-loop think time between a completion and the user's next
    #: submission, seconds
    think_time_s: Tuple[float, float] = (0.0, 0.0)
    #: number of distinct shared prefixes (0 = every prompt fully random)
    shared_prefix_pool: int = 0
    #: token length range of each shared prefix (sampled per prefix, once)
    shared_prefix_len: Tuple[int, int] = (8, 8)
    #: Zipf popularity exponent (> 1; larger = hotter head)
    shared_prefix_zipf: float = 1.5
    #: lazily-materialized prefix pool (drawn from the run's rng on first
    #: use — not part of the spec's identity)
    _prefixes: Optional[list] = dataclasses.field(
        default=None, repr=False, compare=False
    )

    def _prefix(self, rng: np.random.Generator) -> np.ndarray:
        if self._prefixes is None:
            if self.shared_prefix_zipf <= 1.0:
                raise ValueError(
                    f"shared_prefix_zipf must be > 1, got {self.shared_prefix_zipf}"
                )
            lo, hi = self.shared_prefix_len
            self._prefixes = [
                rng.integers(
                    self.vocab[0], self.vocab[1],
                    size=int(rng.integers(lo, hi + 1)), dtype=np.int32,
                )
                for _ in range(self.shared_prefix_pool)
            ]
        # unbounded Zipf rank folded onto the pool: rank 1 (the hottest
        # system prompt) keeps its Zipf mass, the tail wraps — skew is
        # preserved and every prefix stays reachable
        rank = (int(rng.zipf(self.shared_prefix_zipf)) - 1) % self.shared_prefix_pool
        return self._prefixes[rank]

    def sample_prompt(self, rng: np.random.Generator) -> np.ndarray:
        lo, hi = self.prompt_len
        n = int(rng.integers(lo, hi + 1))
        tail = rng.integers(self.vocab[0], self.vocab[1], size=n, dtype=np.int32)
        if self.shared_prefix_pool > 0:
            return np.concatenate([self._prefix(rng), tail])
        return tail

    def sample_max_new(self, rng: np.random.Generator) -> int:
        lo, hi = self.max_new_tokens
        return int(rng.integers(lo, hi + 1))

    def sample_think(self, rng: np.random.Generator) -> float:
        lo, hi = self.think_time_s
        return lo if hi <= lo else float(rng.uniform(lo, hi))


class HttpStreamHandle:
    """One in-flight HTTP stream: the client-side mirror of a
    ``ServeRequest`` handle — ``status`` / ``done`` / ``result`` — fed by a
    background reader thread consuming the gateway's SSE / JSON-lines
    response. ``result`` holds the streamed token ids (unpadded)."""

    def __init__(self, request_index: int):
        self.request_index = request_index
        self.tokens: List[int] = []
        self.status = "queued"
        self.error: Optional[str] = None
        self.trace_id: Optional[str] = None
        self.bytes_received = 0
        self.result: Optional[np.ndarray] = None
        self.first_token_at: Optional[float] = None

    @property
    def done(self) -> bool:
        return self.status not in ("queued",)


class GatewayHttpClient:
    """Engine-surface adapter over a :class:`~perceiver_io_tpu.serving.gateway.StreamingGateway`
    address: ``submit`` POSTs ``/v1/generate`` and returns an
    :class:`HttpStreamHandle` whose tokens stream in on a reader thread;
    ``step``/``pending`` satisfy the :class:`LoadGenerator` drive loop (the
    SERVER drives the engine — the client's ``step`` just yields).

    Admission mapping mirrors the in-process surface so the generator's
    offered/shed/rejected accounting is transport-independent: HTTP 503
    (bounded-queue backpressure) raises
    :class:`~perceiver_io_tpu.reliability.QueueFull`, HTTP 400 (infeasible
    prompt) raises ``ValueError`` — both at submit time, read from the
    response head before the body streams.

    :param host / port: the gateway's bound address.
    :param mode: wire framing requested per stream (``jsonl`` parses
        cheapest; ``sse`` exercises the event framing).
    :param clock: time source for ``first_token_at`` stamps (client-side
        TTFT; the authoritative socket-anchored number lives on the
        server's ``serving_ttft_ms``).
    :param timeout_s: socket timeout per connection.
    """

    def __init__(self, host: str, port: int, *, mode: str = "jsonl",
                 clock: Callable[[], float] = time.monotonic,
                 timeout_s: float = 60.0):
        if mode not in ("sse", "jsonl"):
            raise ValueError(f"mode must be 'sse' or 'jsonl', got {mode!r}")
        self.host = host
        self.port = int(port)
        self.mode = mode
        self._clock = clock
        self.timeout_s = float(timeout_s)
        self._lock = threading.Lock()
        #: handles not yet terminal — pruned on every pending() poll so the
        #: per-millisecond drive loop never rescans the whole run's history
        self._live_handles: List[HttpStreamHandle] = []
        self._next_index = 0
        #: total response-body bytes read off the wire — the
        #: bytes-on-wire number :meth:`LoadGenerator.run` reports
        self.bytes_received = 0

    def submit(self, prompt, config=None, *, deadline_s: Optional[float] = None,
               **_ignored) -> HttpStreamHandle:
        from perceiver_io_tpu.reliability import QueueFull

        body: dict = {"prompt_ids": np.asarray(prompt, np.int32).reshape(-1).tolist(),
                      "stream": self.mode}
        if config is not None:
            body["max_new_tokens"] = int(config.max_new_tokens)
        if deadline_s is not None:
            body["deadline_s"] = float(deadline_s)
        conn = http.client.HTTPConnection(
            self.host, self.port, timeout=self.timeout_s
        )
        try:
            conn.request(
                "POST", "/v1/generate", body=json.dumps(body),
                headers={"Content-Type": "application/json"},
            )
            # the gateway answers the head as soon as admission decides, so
            # shed/reject surface synchronously — the loadgen accounting
            # point
            resp = conn.getresponse()
        except OSError as e:
            # a transient connect failure / socket timeout is ONE failed
            # request, not the end of the whole offered-load run: return a
            # terminal handle so the generator's accounting absorbs it
            conn.close()
            handle = HttpStreamHandle(self._next_index)
            self._next_index += 1
            handle.status = "failed"
            handle.error = f"{type(e).__name__}: {e}"
            return handle
        if resp.status == 503:
            detail = resp.read().decode(errors="replace")
            conn.close()
            raise QueueFull(f"gateway backpressure (503): {detail.strip()}")
        if resp.status != 200:
            detail = resp.read().decode(errors="replace")
            conn.close()
            raise ValueError(
                f"gateway rejected the request ({resp.status}): {detail.strip()}"
            )
        handle = HttpStreamHandle(self._next_index)
        self._next_index += 1
        self._live_handles.append(handle)
        threading.Thread(
            target=self._read_stream, args=(conn, resp, handle), daemon=True
        ).start()
        return handle

    def _read_stream(self, conn, resp, handle: HttpStreamHandle) -> None:
        try:
            while True:
                line = resp.readline()
                if not line:
                    # EOF without a terminal record: the server went away
                    if not handle.done:
                        handle.status = "failed"
                        handle.error = "stream ended without a terminal record"
                    break
                with self._lock:
                    self.bytes_received += len(line)
                    handle.bytes_received += len(line)
                line = line.strip()
                if not line:
                    continue
                if line.startswith(b"data:"):  # SSE framing
                    line = line[5:].strip()
                record = json.loads(line)
                if record.get("done"):
                    handle.trace_id = record.get("trace_id")
                    handle.error = record.get("error")
                    handle.result = np.asarray(handle.tokens, np.int32)
                    handle.status = record.get("status", "failed")
                    break
                if handle.first_token_at is None:
                    handle.first_token_at = self._clock()
                handle.tokens.append(int(record["token"]))
        except Exception as e:
            if not handle.done:
                handle.status = "failed"
                handle.error = f"{type(e).__name__}: {e}"
        finally:
            conn.close()

    def step(self) -> int:
        """The server drives the engine; the client's step just yields so
        the drive loop doesn't spin."""
        time.sleep(0.001)
        return 0

    def pending(self) -> bool:
        # reader threads flip handle.status; a racy read only delays one
        # polling pass, never deadlocks the drive loop. Terminal handles
        # are pruned here so the poll stays O(in-flight), not O(run).
        self._live_handles = [h for h in self._live_handles if not h.done]
        return bool(self._live_handles)

    def health(self) -> dict:
        conn = http.client.HTTPConnection(
            self.host, self.port, timeout=self.timeout_s
        )
        try:
            conn.request("GET", "/healthz")
            resp = conn.getresponse()
            return json.loads(resp.read().decode())
        finally:
            conn.close()


class TTFTProbe:
    """Engine-surface proxy recording CLIENT-SIDE per-request TTFT through
    the ``on_token`` sink: ``submit`` stamps the clock, the first index-0
    token stamps it again (a fleet failover replay re-fires index 0 — the
    FIRST observation wins, matching the wire dedupe). Point a
    :class:`LoadGenerator` at ``TTFTProbe(fleet, clock)`` and every
    accepted request gains a ``{"index", "ttft_ms", "handle"}`` row in
    :attr:`records`, submit-ordered — the per-request goodput-under-SLO
    join for FLEET drills, where the engines' ``serving.first_token``
    events carry per-replica trace ids that never match the fleet
    handle's (single-engine drills can keep joining on the tracer).
    ``index`` is the request's position in the OFFERED sequence (shed /
    rejected offers advance it without leaving a record), so two runs of
    the same workload pair their common requests by ``index`` even when
    they shed differently. Everything else proxies, so the generator's
    accounting is unchanged."""

    def __init__(self, engine, clock: Callable[[], float] = time.monotonic):
        self.engine = engine
        self._clock = clock
        self.offered = 0
        self.records: List[dict] = []

    def submit(self, prompt, config=None, **kwargs):
        idx = self.offered
        self.offered += 1
        rec = {"index": idx, "ttft_ms": None, "handle": None}
        t0 = self._clock()
        user_sink = kwargs.pop("on_token", None)

        def on_token(index: int, token: int) -> None:
            if index == 0 and rec["ttft_ms"] is None:
                rec["ttft_ms"] = (self._clock() - t0) * 1e3
            if user_sink is not None:
                user_sink(index, token)

        handle = self.engine.submit(prompt, config, on_token=on_token, **kwargs)
        rec["handle"] = handle
        self.records.append(rec)
        return handle

    def step(self) -> int:
        return self.engine.step()

    def pending(self) -> bool:
        return self.engine.pending()

    def health(self) -> dict:
        return self.engine.health()

    def good_under(self, ttft_target_ms: float) -> int:
        """Requests that completed AND whose own first token met the
        target — the shared per-request goodput numerator."""
        return sum(
            1 for r in self.records
            if r["handle"] is not None and r["handle"].status == "ok"
            and r["ttft_ms"] is not None and r["ttft_ms"] <= ttft_target_ms
        )


class LoadGenerator:
    """Drive an engine/fleet with a synthetic workload (module docstring).

    :param engine: anything with the shared request surface — ``submit`` /
        ``step`` / ``pending`` (both engines, the fleet router).
    :param workload: the per-request shape distributions.
    :param mode: ``"open"`` or ``"closed"``.
    :param arrival: open-loop arrival process (:data:`ARRIVALS`).
    :param rate_rps: open-loop offered rate (requests/second); for
        ``ramp`` the starting rate.
    :param ramp_to_rps: ``ramp``'s final rate, reached at the last arrival.
    :param burst_size: ``bursty``'s requests per burst.
    :param spike_factor: ``spike``'s rate multiplier inside the window
        (offered rate = ``spike_factor * rate_rps`` there, ``rate_rps``
        outside).
    :param spike_start_s / spike_duration_s: the spike window, in seconds
        from the first arrival draw.
    :param users: closed-loop concurrent synthetic users.
    :param max_requests: total requests to offer, then drain and stop.
    :param config: optional :class:`GenerationConfig` template; each
        request gets ``dataclasses.replace(config,
        max_new_tokens=sampled)``. None submits with the engine default
        config (no per-request max_new variation).
    :param deadline_s: per-request deadline forwarded to ``submit``.
    :param rng: ``numpy`` Generator or int seed — the run's ONE source of
        randomness.
    :param clock: the engine's clock (share it!). A clock with
        ``advance`` (FakeClock) is driven by the generator; a real clock
        is slept against.
    :param step_cost_s: simulated wall cost of one ``engine.step()`` under
        a FakeClock (ignored for real clocks). This is what makes offered
        rate meaningful in a frozen-clock drill — and the knob a test
        turns up to inject a deterministic latency fault.
    """

    def __init__(self, engine, *, workload: Optional[WorkloadSpec] = None,
                 mode: str = "open", arrival: str = "poisson",
                 rate_rps: float = 10.0, ramp_to_rps: Optional[float] = None,
                 burst_size: int = 4, spike_factor: float = 4.0,
                 spike_start_s: float = 0.0,
                 spike_duration_s: Optional[float] = None,
                 users: int = 4, max_requests: int = 32,
                 config=None, deadline_s: Optional[float] = None,
                 rng=0, clock: Callable[[], float] = time.monotonic,
                 step_cost_s: float = 0.001):
        if mode not in MODES:
            raise ValueError(f"mode must be one of {MODES}, got {mode!r}")
        if arrival not in ARRIVALS:
            raise ValueError(
                f"arrival must be one of {ARRIVALS}, got {arrival!r}"
            )
        if rate_rps <= 0:
            raise ValueError(f"rate_rps must be > 0, got {rate_rps}")
        if max_requests < 1:
            raise ValueError(f"max_requests must be >= 1, got {max_requests}")
        if users < 1:
            raise ValueError(f"users must be >= 1, got {users}")
        if burst_size < 1:
            raise ValueError(f"burst_size must be >= 1, got {burst_size}")
        if arrival == "ramp" and (ramp_to_rps is None or ramp_to_rps <= 0):
            raise ValueError(
                f"arrival='ramp' needs ramp_to_rps > 0, got {ramp_to_rps}"
            )
        if arrival == "spike":
            if spike_factor <= 0:
                raise ValueError(
                    f"arrival='spike' needs spike_factor > 0, got {spike_factor}"
                )
            if spike_duration_s is None or spike_duration_s <= 0:
                raise ValueError(
                    f"arrival='spike' needs spike_duration_s > 0, "
                    f"got {spike_duration_s}"
                )
            if spike_start_s < 0:
                raise ValueError(
                    f"spike_start_s must be >= 0, got {spike_start_s}"
                )
        if step_cost_s <= 0:
            # under a FakeClock the step cost is the only thing that moves
            # time while the engine works; zero would spin the open loop
            # forever inside one arrival gap
            raise ValueError(f"step_cost_s must be > 0, got {step_cost_s}")
        self.engine = engine
        self.workload = workload if workload is not None else WorkloadSpec()
        self.mode = mode
        self.arrival = arrival
        self.rate_rps = float(rate_rps)
        self.ramp_to_rps = None if ramp_to_rps is None else float(ramp_to_rps)
        self.burst_size = int(burst_size)
        self.spike_factor = float(spike_factor)
        self.spike_start_s = float(spike_start_s)
        self.spike_duration_s = (
            None if spike_duration_s is None else float(spike_duration_s)
        )
        self.users = int(users)
        self.max_requests = int(max_requests)
        self.config = config
        self.deadline_s = deadline_s
        self.rng = rng if isinstance(rng, np.random.Generator) else np.random.default_rng(rng)
        self._clock = clock
        self.step_cost_s = float(step_cost_s)
        self.handles: List[object] = []
        self.offered = 0
        self.shed = 0
        self.rejected = 0

    # -- time ----------------------------------------------------------------
    def _tick(self) -> None:
        """One engine step, charged ``step_cost_s`` on a FakeClock."""
        self.engine.step()
        advance = getattr(self._clock, "advance", None)
        if advance is not None:
            advance(self.step_cost_s)

    def _wait_until(self, t: float) -> None:
        """Idle until ``t``: jump a FakeClock straight there; nap a real
        one (short naps — a real engine may retire work meanwhile)."""
        advance = getattr(self._clock, "advance", None)
        if advance is not None:
            if t > self._clock():
                advance(t - self._clock())
        else:
            now = self._clock()
            if t > now:
                time.sleep(min(t - now, 0.005))

    # -- arrivals ------------------------------------------------------------
    def _gaps(self) -> List[float]:
        """The full open-loop inter-arrival schedule, drawn up front so the
        offered pattern is independent of service times (the open-loop
        contract)."""
        n = self.max_requests
        rng = self.rng
        if self.arrival == "uniform":
            return [1.0 / self.rate_rps] * n
        if self.arrival == "poisson":
            return [float(g) for g in rng.exponential(1.0 / self.rate_rps, size=n)]
        if self.arrival == "bursty":
            gaps = []
            burst_gap = self.burst_size / self.rate_rps
            for i in range(n):
                if i % self.burst_size == 0:
                    gaps.append(float(rng.exponential(burst_gap)))
                else:
                    gaps.append(0.0)
            return gaps
        if self.arrival == "spike":
            # flash crowd: baseline Poisson with a K-step over the window.
            # The schedule is simulated arrival-time-forward so the rate a
            # gap is drawn at depends on WHEN the previous arrival landed —
            # the step is a property of the offered timeline, not of an
            # arrival index
            gaps = []
            t = 0.0
            spike_end = self.spike_start_s + self.spike_duration_s
            for _ in range(n):
                in_spike = self.spike_start_s <= t < spike_end
                rate = self.rate_rps * (self.spike_factor if in_spike else 1.0)
                gap = float(rng.exponential(1.0 / rate))
                # a baseline gap that would leap the whole window still
                # offers the spike: clip the draw to the window start so
                # the crowd actually arrives (the window is the event, the
                # gap is just the sampler)
                if not in_spike and t < self.spike_start_s \
                        and t + gap > self.spike_start_s:
                    gap = self.spike_start_s - t
                    gap = max(gap, 1e-9)
                gaps.append(gap)
                t += gap
            return gaps
        # ramp: rate interpolates rate_rps -> ramp_to_rps across arrivals
        gaps = []
        for i in range(n):
            frac = i / max(1, n - 1)
            rate = self.rate_rps + frac * (self.ramp_to_rps - self.rate_rps)
            gaps.append(float(rng.exponential(1.0 / rate)))
        return gaps

    # -- submission ----------------------------------------------------------
    def _submit_one(self) -> Optional[object]:
        from perceiver_io_tpu.reliability import QueueFull

        prompt = self.workload.sample_prompt(self.rng)
        cfg = self.config
        if cfg is not None:
            cfg = dataclasses.replace(
                cfg, max_new_tokens=self.workload.sample_max_new(self.rng)
            )
        self.offered += 1
        try:
            handle = self.engine.submit(prompt, cfg, deadline_s=self.deadline_s)
        except QueueFull:
            self.shed += 1
            return None
        except ValueError:
            self.rejected += 1
            return None
        self.handles.append(handle)
        return handle

    # -- the drills ----------------------------------------------------------
    def _run_open(self) -> None:
        gaps = self._gaps()
        next_at = self._clock()
        for gap in gaps:
            next_at += gap
            # serve residents while waiting out the arrival gap; an idle
            # engine skips straight to the arrival (open loop never slows
            # its offered schedule to match service rate)
            while self._clock() < next_at:
                if self.engine.pending():
                    self._tick()
                else:
                    self._wait_until(next_at)
            self._submit_one()
        while self.engine.pending():
            self._tick()

    def _run_closed(self) -> None:
        # per-user state: (handle or None, next submit time)
        users: List[list] = [[None, self._clock()] for _ in range(self.users)]
        while True:
            now = self._clock()
            for user in users:
                handle, next_at = user
                if handle is not None and handle.done:
                    user[0] = None
                    user[1] = now + self.workload.sample_think(self.rng)
                    handle, next_at = user
                if handle is None and self.offered < self.max_requests and now >= next_at:
                    user[0] = self._submit_one()
            if self.offered >= self.max_requests and not self.engine.pending():
                if all(u[0] is None or u[0].done for u in users):
                    return
            if self.engine.pending():
                self._tick()
            else:
                soonest = min(
                    (u[1] for u in users if u[0] is None), default=None
                )
                if soonest is None or self.offered >= self.max_requests:
                    return
                self._wait_until(max(soonest, now))

    def run(self) -> dict:
        """Offer the whole workload, drain, and return the report:
        generator-side offered/shed/rejected accounting, terminal
        disposition counts from the request handles, wall span on the
        run's clock, and the achieved rates. ``handles`` stays on the
        instance for per-request inspection."""
        from perceiver_io_tpu.observability.slo import goodput_ratio, offered_load

        t0 = self._clock()
        if self.mode == "open":
            self._run_open()
        else:
            self._run_closed()
        span_s = max(self._clock() - t0, 1e-9)
        by_status: dict = {}
        for h in self.handles:
            by_status[h.status] = by_status.get(h.status, 0) + 1
        completed = by_status.get("ok", 0)
        # the shared goodput definition (observability/slo.py): the
        # generator's own accounting rendered as the counter mapping the
        # helpers read, so in-process, fleet, and over-socket drills all
        # share ONE denominator (shed and rejected stay in it)
        counts = {
            "serving_requests_submitted_total": len(self.handles),
            "serving_requests_shed_total": self.shed,
            "serving_requests_rejected_total": self.rejected,
            "serving_requests_completed_total": completed,
        }
        return {
            "mode": self.mode,
            "arrival": self.arrival if self.mode == "open" else None,
            "offered": offered_load(counts),
            "accepted": len(self.handles),
            "shed": self.shed,
            "rejected": self.rejected,
            "completed": completed,
            "timed_out": by_status.get("timed_out", 0),
            "failed": by_status.get("failed", 0),
            "cancelled": by_status.get("cancelled", 0),
            "by_status": dict(sorted(by_status.items())),
            "span_s": round(span_s, 6),
            "offered_rps": round(self.offered / span_s, 4),
            "completed_rps": round(completed / span_s, 4),
            "goodput_ratio": round(goodput_ratio(counts), 4),
            # over-socket drills (GatewayHttpClient) report response bytes
            # read off the wire; None for in-process engines
            "bytes_on_wire": (
                int(self.engine.bytes_received)
                if hasattr(self.engine, "bytes_received") else None
            ),
        }
