"""Importers for the official DeepMind Hugging Face Perceiver models
(``transformers.PerceiverForMaskedLM`` = ``deepmind/language-perceiver``,
``transformers.PerceiverForImageClassificationFourier`` =
``deepmind/vision-perceiver-fourier``).

Strategy: translate the ``transformers`` state-dict keys into the reference
library's module layout (the correspondence the reference establishes in its
``copy_*`` helpers, ``perceiver/model/core/huggingface.py:17-76``,
``text/common/huggingface.py:12-18``, ``text/mlm/huggingface.py:158-165``,
``vision/image_classifier/huggingface.py``), then reuse the parity-tested
reference-layout importers in :mod:`perceiver_io_tpu.convert.torch_import`.

Config conversion mirrors the reference's ``convert_config`` functions
(``mlm/huggingface.py:116-155``, ``image_classifier/huggingface.py:182-210``).

Oracle: ``tests/test_hf_convert.py`` builds randomly initialized
``transformers`` models (no hub access) and asserts logit parity.
"""
from __future__ import annotations

from typing import Any, Dict, Mapping

from perceiver_io_tpu.convert import torch_import


def _expand(module_map: Mapping[str, str], hf_sd: Mapping[str, Any]) -> Dict[str, Any]:
    """Expand module-path renames to parameter keys present in ``hf_sd``."""
    out: Dict[str, Any] = {}
    for hf_base, ref_base in module_map.items():
        if hf_base in hf_sd:  # bare parameter (latents, position embeddings)
            out[ref_base] = hf_sd[hf_base]
            continue
        for suffix in (".weight", ".bias"):
            if hf_base + suffix in hf_sd:
                out[ref_base + suffix] = hf_sd[hf_base + suffix]
    return out


def _layer_map(hf: str, ref: str, *, residual: bool = True, self_attn: bool = False) -> Dict[str, str]:
    """transformers ``PerceiverLayer`` → reference
    CrossAttentionLayer/SelfAttentionLayer module paths (reference
    ``core/huggingface.py:26-57``)."""
    pre = f"{ref}.0.module" if residual else f"{ref}.0"
    m: Dict[str, str] = {}
    if self_attn:
        m[f"{hf}.attention.self.layernorm1"] = f"{pre}.norm"
    else:
        m[f"{hf}.attention.self.layernorm1"] = f"{pre}.q_norm"
        m[f"{hf}.attention.self.layernorm2"] = f"{pre}.kv_norm"
    m[f"{hf}.attention.self.query"] = f"{pre}.attention.q_proj"
    m[f"{hf}.attention.self.key"] = f"{pre}.attention.k_proj"
    m[f"{hf}.attention.self.value"] = f"{pre}.attention.v_proj"
    m[f"{hf}.attention.output.dense"] = f"{pre}.attention.o_proj"
    # reference MLP = Sequential(LayerNorm, Linear, GELU, Linear)
    m[f"{hf}.layernorm"] = f"{ref}.1.module.0"
    m[f"{hf}.mlp.dense1"] = f"{ref}.1.module.1"
    m[f"{hf}.mlp.dense2"] = f"{ref}.1.module.3"
    return m


def _encoder_map(num_self_attention_layers: int) -> Dict[str, str]:
    m = {"perceiver.embeddings.latents": "encoder.latent_provider._query"}
    m.update(_layer_map("perceiver.encoder.cross_attention", "encoder.cross_attn_1"))
    for i in range(num_self_attention_layers):
        m.update(
            _layer_map(
                f"perceiver.encoder.self_attends.{i}",
                f"encoder.self_attn_1.{i}",
                self_attn=True,
            )
        )
    return m


# -- masked language model -------------------------------------------------
def mlm_config_from_hf(config) -> Any:
    """``transformers.PerceiverConfig`` → :data:`MaskedLanguageModelConfig`
    (reference ``mlm/huggingface.py:116-155``)."""
    from perceiver_io_tpu.models.core.config import PerceiverIOConfig
    from perceiver_io_tpu.models.text.common import TextEncoderConfig
    from perceiver_io_tpu.models.text.mlm import TextDecoderConfig

    assert config.hidden_act == "gelu"
    assert config.tie_word_embeddings
    encoder = TextEncoderConfig(
        vocab_size=config.vocab_size,
        max_seq_len=config.max_position_embeddings,
        num_input_channels=config.d_model,
        num_cross_attention_qk_channels=config.qk_channels,
        num_cross_attention_v_channels=config.v_channels,
        num_cross_attention_heads=config.num_cross_attention_heads,
        num_self_attention_qk_channels=config.qk_channels,
        num_self_attention_v_channels=config.v_channels,
        num_self_attention_heads=config.num_self_attention_heads,
        num_self_attention_layers_per_block=config.num_self_attends_per_block,
        num_self_attention_blocks=config.num_blocks,
        cross_attention_widening_factor=config.cross_attention_widening_factor,
        self_attention_widening_factor=config.self_attention_widening_factor,
        dropout=config.attention_probs_dropout_prob,
        init_scale=config.initializer_range,
    )
    # transformers.PerceiverForMaskedLM hardcodes its decoder attention shape
    # (qk_channels=8*32, num_heads=8, v_channels=d_model) regardless of the
    # PerceiverConfig (transformers modeling_perceiver.py, PerceiverForMaskedLM
    # __init__) — the reference's convert_config gets away with config.qk_channels
    # only because the official checkpoint happens to have qk_channels=256.
    decoder = TextDecoderConfig(
        vocab_size=config.vocab_size,
        max_seq_len=config.max_position_embeddings,
        num_cross_attention_qk_channels=8 * 32,
        num_cross_attention_v_channels=config.d_model,
        num_cross_attention_heads=8,
        cross_attention_widening_factor=config.cross_attention_widening_factor,
        cross_attention_residual=False,
        dropout=config.attention_probs_dropout_prob,
        init_scale=config.initializer_range,
    )
    return PerceiverIOConfig(
        encoder,
        decoder,
        num_latents=config.num_latents,
        num_latent_channels=config.d_latents,
    )


def import_hf_masked_language_model(hf_state_dict: Mapping[str, Any], config) -> Dict[str, Any]:
    """``PerceiverForMaskedLM`` state dict → flax params."""
    m = _encoder_map(config.encoder.num_self_attention_layers_per_block)
    m.update(
        {
            "perceiver.input_preprocessor.embeddings": "encoder.input_adapter.txt_embedding",
            "perceiver.input_preprocessor.position_embeddings": "encoder.input_adapter.pos_embedding",
            "perceiver.decoder.output_position_encodings.position_embeddings":
                "decoder.output_query_provider._query",
            "embedding_decoder.bias": "decoder.output_adapter.bias",
        }
    )
    m.update(
        _layer_map(
            "perceiver.decoder.decoding_cross_attention", "decoder.cross_attn",
            residual=config.decoder.cross_attention_residual,
        )
    )
    ref_sd = _expand(m, hf_state_dict)
    return torch_import.import_masked_language_model(ref_sd, config)


# -- image classifier (fourier) --------------------------------------------
def image_classifier_config_from_hf(config) -> Any:
    """``transformers.PerceiverConfig`` → :data:`ImageClassifierConfig`
    (reference ``image_classifier/huggingface.py:182-210``)."""
    from perceiver_io_tpu.models.core.config import (
        ClassificationDecoderConfig,
        PerceiverIOConfig,
    )
    from perceiver_io_tpu.models.vision.image_classifier import ImageEncoderConfig

    assert config.hidden_act == "gelu"
    encoder = ImageEncoderConfig(
        image_shape=(224, 224, 3),
        num_frequency_bands=64,
        num_cross_attention_heads=config.num_cross_attention_heads,
        num_self_attention_heads=config.num_self_attention_heads,
        num_self_attention_layers_per_block=config.num_self_attends_per_block,
        num_self_attention_blocks=config.num_blocks,
        dropout=config.attention_probs_dropout_prob,
        init_scale=config.initializer_range,
    )
    decoder = ClassificationDecoderConfig(
        num_classes=config.num_labels,
        num_output_query_channels=config.d_latents,
        num_cross_attention_heads=config.num_cross_attention_heads,
        cross_attention_residual=True,
        dropout=config.attention_probs_dropout_prob,
        init_scale=config.initializer_range,
    )
    return PerceiverIOConfig(
        encoder,
        decoder,
        num_latents=config.num_latents,
        num_latent_channels=config.d_latents,
    )


def import_hf_image_classifier(hf_state_dict: Mapping[str, Any], config) -> Dict[str, Any]:
    """``PerceiverForImageClassificationFourier`` state dict → flax params."""
    m = _encoder_map(config.encoder.num_self_attention_layers_per_block)
    m.update(
        _layer_map("perceiver.decoder.decoder.decoding_cross_attention", "decoder.cross_attn")
    )
    m.update(
        {
            "perceiver.decoder.decoder.output_position_encodings.position_embeddings":
                "decoder.output_query_provider._query",
            "perceiver.decoder.decoder.final_layer": "decoder.output_adapter.linear",
        }
    )
    ref_sd = _expand(m, hf_state_dict)
    return torch_import.import_image_classifier(ref_sd, config)


# -- optical flow ----------------------------------------------------------
def optical_flow_config_from_hf(config) -> Any:
    """``transformers.PerceiverConfig`` → :data:`OpticalFlowConfig` (the
    mapping the reference does in ``optical_flow/huggingface.py:177-203``,
    corrected to what transformers actually builds: the flow preprocessor
    hardcodes 64 post-patch channels + 64 Fourier bands
    (``modeling_perceiver.py`` ``PerceiverForOpticalFlow.__init__``), and
    ``PerceiverBasicDecoder`` defaults give the decoder ONE head with
    qk = v = kv channels (``cross_attention_shape_for_attention="kv"`` →
    the latent width) — not the config's qk/v settings."""
    from perceiver_io_tpu.models.core.config import PerceiverIOConfig
    from perceiver_io_tpu.models.vision.optical_flow import (
        OpticalFlowDecoderConfig,
        OpticalFlowEncoderConfig,
    )

    assert config.hidden_act == "gelu"
    image_shape = tuple(config.train_size)
    num_bands = 64
    hidden = 64  # PerceiverImagePreprocessor out_channels default
    query_channels = hidden + 2 * (2 * num_bands + 1)  # + concat fourier pos
    assert config.d_model == query_channels, (
        f"flow d_model must be {query_channels} (64 patch channels + fourier), "
        f"got {config.d_model}"
    )
    encoder = OpticalFlowEncoderConfig(
        image_shape=image_shape,
        num_patch_input_channels=27,
        num_patch_hidden_channels=hidden,
        num_frequency_bands=num_bands,
        num_cross_attention_qk_channels=config.qk_channels,
        num_cross_attention_v_channels=config.v_channels,
        num_cross_attention_heads=config.num_cross_attention_heads,
        num_self_attention_qk_channels=config.qk_channels,
        num_self_attention_v_channels=config.v_channels,
        num_self_attention_heads=config.num_self_attention_heads,
        num_self_attention_layers_per_block=config.num_self_attends_per_block,
        num_self_attention_blocks=config.num_blocks,
        cross_attention_widening_factor=config.cross_attention_widening_factor,
        self_attention_widening_factor=config.self_attention_widening_factor,
        dropout=config.attention_probs_dropout_prob,
        init_scale=config.initializer_range,
    )
    decoder = OpticalFlowDecoderConfig(
        image_shape=image_shape,
        num_cross_attention_qk_channels=config.d_latents,
        num_cross_attention_v_channels=config.d_latents,
        num_cross_attention_heads=1,
        cross_attention_widening_factor=config.cross_attention_widening_factor,
        cross_attention_residual=False,
        dropout=config.attention_probs_dropout_prob,
        init_scale=config.initializer_range,
        rescale_factor=100.0,
    )
    return PerceiverIOConfig(
        encoder,
        decoder,
        num_latents=config.num_latents,
        num_latent_channels=config.d_latents,
    )


def import_hf_optical_flow(hf_state_dict: Mapping[str, Any], config) -> Dict[str, Any]:
    """``PerceiverForOpticalFlow`` state dict → flax params (module
    correspondence per reference ``optical_flow/huggingface.py:177-203``:
    ``conv_after_patches`` is the patch embedding, the decoder queries are
    the adapted inputs so there is no trainable query)."""
    m = _encoder_map(config.encoder.num_self_attention_layers_per_block)
    m.update(
        _layer_map(
            "perceiver.decoder.decoder.decoding_cross_attention", "decoder.cross_attn",
            residual=config.decoder.cross_attention_residual,
        )
    )
    m.update(
        {
            "perceiver.input_preprocessor.conv_after_patches": "encoder.input_adapter.linear",
            "perceiver.decoder.decoder.final_layer": "decoder.output_adapter.linear",
        }
    )
    ref_sd = _expand(m, hf_state_dict)
    return torch_import.import_optical_flow(ref_sd, config)
