"""Export perceiver_io_tpu parameter pytrees to the reference (torch)
``perceiver-io`` formats — the inverse of :mod:`.torch_import`.

This completes the reference's three-form round-trip invariant (weights move
freely between trainer, inference, and converter forms — reference
``docs/library-design.md:17-50``): a model trained in this framework can be
loaded by the reference library (``load_state_dict`` on its backend modules,
strict) or served from a reference-format ``save_pretrained`` directory
(reference ``examples/convert.py:14-89`` produces the same artifact from
Lightning checkpoints).

Layout correspondences are the same tables as the import direction
(``torch_import`` module docstring), applied in reverse:

==============================  =======================================
perceiver_io_tpu (flax)         reference (torch)
==============================  =======================================
``Dense.kernel`` (in, out)      ``Linear.weight`` (out, in) — transposed
``LayerNorm.scale``             ``LayerNorm.weight``
``Embed.embedding``             ``Embedding.weight``
``TrainableQueryProvider.query``  ``TrainableQueryProvider._query``
named modules (norm/hidden/out) ``Sequential`` indices (0/1/3)
(flax tree, no wrapper)         ``Residual.module`` wrapper
``encoder.``/``decoder.``       ``0.``/``1.`` (PerceiverIO Sequential)
==============================  =======================================

Buffers the reference registers but we compute on the fly (rotary
``frq_pos_encoding.inv_freq``, reference ``core/position.py:62-65``) are
re-materialized from the config so ``load_state_dict(strict=True)`` passes.

Oracle: ``tests/test_export.py`` loads exports into the REAL reference torch
modules (via ``tests/_reference.py``) with strict key checking and asserts
logits parity at atol 1e-4 after an optimizer step on the JAX side.
"""
from __future__ import annotations

from typing import Any, Dict, Mapping

import numpy as np


def _np(x) -> np.ndarray:
    return np.asarray(x, dtype=np.float32)


def _linear(out: Dict[str, np.ndarray], tree: Mapping[str, Any], name: str) -> None:
    out[f"{name}.weight"] = _np(tree["kernel"]).T
    if "bias" in tree:
        out[f"{name}.bias"] = _np(tree["bias"])


def _norm(out, tree, name: str) -> None:
    out[f"{name}.weight"] = _np(tree["scale"])
    out[f"{name}.bias"] = _np(tree["bias"])


def _embed(out, tree, name: str) -> None:
    out[f"{name}.weight"] = _np(tree["embedding"])


def _attention(out, tree, base: str) -> None:
    for p in ("q_proj", "k_proj", "v_proj", "o_proj"):
        _linear(out, tree[p], f"{base}.{p}")


def _mlp(out, tree, base: str) -> None:
    # reference MLP = Sequential(LayerNorm, Linear, GELU, Linear) → 0, 1, 3
    _norm(out, tree["norm"], f"{base}.0")
    _linear(out, tree["hidden"], f"{base}.1")
    _linear(out, tree["out"], f"{base}.3")


def _cross_attn_layer(out, tree, base: str, attention_residual: bool = True) -> None:
    pre = f"{base}.0.module" if attention_residual else f"{base}.0"
    _norm(out, tree["cross_attn"]["q_norm"], f"{pre}.q_norm")
    _norm(out, tree["cross_attn"]["kv_norm"], f"{pre}.kv_norm")
    _attention(out, tree["cross_attn"]["attention"], f"{pre}.attention")
    _mlp(out, tree["mlp"], f"{base}.1.module")


def _self_attn_layer(out, tree, base: str) -> None:
    _norm(out, tree["self_attn"]["norm"], f"{base}.0.module.norm")
    _attention(out, tree["self_attn"]["attention"], f"{base}.0.module.attention")
    _mlp(out, tree["mlp"], f"{base}.1.module")


def _self_attn_block(out, tree, base: str) -> None:
    for name, layer in tree.items():
        i = int(name.split("_", 1)[1])  # layers_{i}
        _self_attn_layer(out, layer, f"{base}.{i}")


def _encoder(out, tree, base: str, encoder_config) -> None:
    """PerceiverEncoder params (without the input adapter). The config is
    cross-checked against the tree's weight-sharing structure so a
    config/params mismatch fails loudly instead of exporting an artifact the
    reference would misload."""
    c = encoder_config
    want_can = c.num_cross_attention_layers > 1 and not c.first_cross_attention_layer_shared
    want_san = c.num_self_attention_blocks > 1 and not c.first_self_attention_block_shared
    for want, key in ((want_can, "cross_attn_n"), (want_san, "self_attn_n")):
        if want != (key in tree):
            raise ValueError(
                f"config/params mismatch: config {'requires' if want else 'forbids'} "
                f"a separate {key!r} tower but params "
                f"{'lack' if want else 'contain'} it"
            )
    out[f"{base}.latent_provider._query"] = _np(tree["latent_provider"]["query"])
    _cross_attn_layer(out, tree["cross_attn_1"], f"{base}.cross_attn_1")
    _self_attn_block(out, tree["self_attn_1"], f"{base}.self_attn_1")
    if "cross_attn_n" in tree:
        _cross_attn_layer(out, tree["cross_attn_n"], f"{base}.cross_attn_n")
    if "self_attn_n" in tree:
        _self_attn_block(out, tree["self_attn_n"], f"{base}.self_attn_n")


def _text_input_adapter(out, tree, base: str) -> None:
    _embed(out, tree["txt_embedding"], f"{base}.txt_embedding")
    if "pos_embedding" in tree:
        _embed(out, tree["pos_embedding"], f"{base}.pos_embedding")


def _decoder(out, tree, base: str, decoder_config) -> None:
    residual = getattr(decoder_config, "cross_attention_residual", True)
    _cross_attn_layer(out, tree["cross_attn"], f"{base}.cross_attn", attention_residual=residual)


def _rotary_inv_freq(config) -> np.ndarray:
    """The ``frq_pos_encoding.inv_freq`` buffer the reference AR input adapter
    registers (reference ``core/position.py:62-65``), re-computed from the
    config's rotated-channel count."""
    dim = config.rotated_channels_per_head
    return (1.0 / (10000 ** (np.arange(0, dim, 2, dtype=np.float32) / dim))).astype(
        np.float32
    )


# ---------------------------------------------------------------------------
# Task models (inverses of torch_import.import_*)
# ---------------------------------------------------------------------------


def export_masked_language_model(params: Mapping[str, Any], config) -> Dict[str, np.ndarray]:
    """:class:`MaskedLanguageModel` params → reference ``MaskedLanguageModel``
    state_dict (Sequential layout: ``0.`` encoder, ``1.`` decoder)."""
    out: Dict[str, np.ndarray] = {}
    _text_input_adapter(out, params["encoder"]["input_adapter"], "0.input_adapter")
    _encoder(out, params["encoder"], "0", config.encoder)
    out["1.output_query_provider._query"] = _np(
        params["decoder"]["output_query_provider"]["query"]
    )
    _decoder(out, params["decoder"], "1", config.decoder)
    if config.decoder.num_output_query_channels is None:
        if "output_adapter" in params["decoder"]:
            out["1.output_adapter.bias"] = _np(params["decoder"]["output_adapter"]["bias"])
    else:
        _linear(out, params["decoder"]["output_adapter"]["linear"], "1.output_adapter.linear")
    return out


def export_text_classifier(params: Mapping[str, Any], config) -> Dict[str, np.ndarray]:
    out: Dict[str, np.ndarray] = {}
    _text_input_adapter(out, params["encoder"]["input_adapter"], "0.input_adapter")
    _encoder(out, params["encoder"], "0", config.encoder)
    out["1.output_query_provider._query"] = _np(
        params["decoder"]["output_query_provider"]["query"]
    )
    _linear(out, params["decoder"]["output_adapter"]["linear"], "1.output_adapter.linear")
    _decoder(out, params["decoder"], "1", config.decoder)
    return out


def _fourier_buffer(spatial_shape, num_frequency_bands) -> np.ndarray:
    """The reference vision adapters register the precomputed Fourier table
    as a buffer (reference ``core/position.py:81-89``); ours is computed on
    the fly (``ops/position.py``, logits-parity-tested), so re-materialize it
    for strict state_dict compatibility."""
    from perceiver_io_tpu.ops.position import FourierPositionEncoding

    return np.asarray(
        FourierPositionEncoding(tuple(spatial_shape), num_frequency_bands)._encoding,
        dtype=np.float32,
    )


def export_image_classifier(params: Mapping[str, Any], config) -> Dict[str, np.ndarray]:
    """The image input adapter holds no parameters (Fourier features are
    deterministic; the reference's buffer is re-materialized)."""
    out: Dict[str, np.ndarray] = {}
    out["0.input_adapter.position_encoding.position_encoding"] = _fourier_buffer(
        config.encoder.image_shape[:-1], config.encoder.num_frequency_bands
    )
    _encoder(out, params["encoder"], "0", config.encoder)
    out["1.output_query_provider._query"] = _np(
        params["decoder"]["output_query_provider"]["query"]
    )
    _linear(out, params["decoder"]["output_adapter"]["linear"], "1.output_adapter.linear")
    _decoder(out, params["decoder"], "1", config.decoder)
    return out


def export_optical_flow(params: Mapping[str, Any], config) -> Dict[str, np.ndarray]:
    out: Dict[str, np.ndarray] = {}
    out["0.input_adapter.position_encoding.position_encoding"] = _fourier_buffer(
        config.encoder.image_shape, config.encoder.num_frequency_bands
    )
    _linear(out, params["encoder"]["input_adapter"]["linear"], "0.input_adapter.linear")
    _encoder(out, params["encoder"], "0", config.encoder)
    _linear(out, params["decoder"]["output_adapter"]["linear"], "1.output_adapter.linear")
    _decoder(out, params["decoder"], "1", config.decoder)
    return out


def _sequence_model(params: Mapping[str, Any], config) -> Dict[str, np.ndarray]:
    """Shared CLM / symbolic-audio export: our ``perceiver_ar``-nested layout →
    reference flat PerceiverAR layout (incl. the rotary inv_freq buffer)."""
    out: Dict[str, np.ndarray] = {}
    ar = params["perceiver_ar"]
    _text_input_adapter(out, ar["input_adapter"], "input_adapter")
    out["input_adapter.frq_pos_encoding.inv_freq"] = _rotary_inv_freq(config)
    _cross_attn_layer(out, ar["cross_attention"], "cross_attention")
    _self_attn_block(out, ar["self_attention"], "self_attention")
    if config.output_norm:
        _norm(out, params["out_norm"], "out_norm")
    if config.output_bias:
        out["output_adapter.bias"] = _np(params["output_adapter"]["bias"])
    return out


def export_causal_language_model(params: Mapping[str, Any], config) -> Dict[str, np.ndarray]:
    return _sequence_model(params, config)


def export_symbolic_audio_model(params: Mapping[str, Any], config) -> Dict[str, np.ndarray]:
    return _sequence_model(params, config)


# ---------------------------------------------------------------------------
# save_pretrained-style artifact (reference HF wrapper format)
# ---------------------------------------------------------------------------

# task → (exporter, reference wrapper model_type, wrapper class name)
# model_type strings from the reference huggingface.py modules
# (e.g. clm/huggingface.py:13, mlm/huggingface.py:22).
TASKS: Dict[str, Any] = {
    "clm": (
        export_causal_language_model,
        "perceiver-ar-causal-language-model",
        "PerceiverCausalLanguageModel",
    ),
    "sam": (
        export_symbolic_audio_model,
        "perceiver-ar-symbolic-audio-model",
        "PerceiverSymbolicAudioModel",
    ),
    "mlm": (
        export_masked_language_model,
        "perceiver-io-masked-language-model",
        "PerceiverMaskedLanguageModel",
    ),
    "txt-clf": (
        export_text_classifier,
        "perceiver-io-text-classifier",
        "PerceiverTextClassifier",
    ),
    "img-clf": (
        export_image_classifier,
        "perceiver-io-image-classifier",
        "PerceiverImageClassifier",
    ),
    "flow": (
        export_optical_flow,
        "perceiver-io-optical-flow",
        "PerceiverOpticalFlow",
    ),
}


def infer_task(config) -> str:
    """Derive the export task from the config's concrete type (the config
    registry guarantees distinct dataclasses per family), so a mislabeled
    ``export <task>`` cannot silently write the wrong wrapper metadata."""
    name = type(config).__name__
    if name == "CausalLanguageModelConfig":
        return "clm"
    if name == "SymbolicAudioModelConfig":
        return "sam"
    enc = type(getattr(config, "encoder", None)).__name__
    dec = type(getattr(config, "decoder", None)).__name__
    if dec == "TextDecoderConfig":
        return "mlm"
    if dec == "ClassificationDecoderConfig":
        if enc == "TextEncoderConfig":
            return "txt-clf"
        if enc == "ImageEncoderConfig":
            return "img-clf"
    if dec == "OpticalFlowDecoderConfig":
        return "flow"
    raise ValueError(f"cannot infer export task from config type {name} ({enc}/{dec})")


def _jsonable(obj):
    if isinstance(obj, dict):
        return {k: _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    return obj


def save_reference_checkpoint(params, config, save_dir: str, task: str) -> str:
    """Write a reference-format ``save_pretrained`` directory: ``config.json``
    (the reference wrapper's ``PretrainedConfig`` serialization:
    ``model_type`` + ``model_config = asdict(backend_config)`` — our config
    dataclasses are field-identical to the reference's, verified by
    ``tests/test_export.py``) and ``pytorch_model.bin`` (torch state dict
    with the wrapper's ``backend_model.`` prefix).

    The resulting directory loads in the reference library with
    ``Perceiver<Task>.from_pretrained(save_dir)``.
    """
    import dataclasses
    import json
    import os

    import torch

    if task not in TASKS:
        raise ValueError(f"unknown task {task!r}; expected one of {sorted(TASKS)}")
    actual = infer_task(config)
    if actual != task:
        raise ValueError(
            f"task mismatch: requested export as {task!r} but the model's "
            f"config is a {type(config).__name__} ({actual!r})"
        )
    exporter, model_type, arch = TASKS[task]

    sd = exporter(params, config)
    os.makedirs(save_dir, exist_ok=True)
    cfg_dict = {
        "model_type": model_type,
        "model_config": _jsonable(dataclasses.asdict(config)),
        "architectures": [arch],
        "is_decoder": task in ("clm", "sam"),
    }
    with open(os.path.join(save_dir, "config.json"), "w") as f:
        json.dump(cfg_dict, f, indent=2)
    torch.save(
        {f"backend_model.{k}": torch.from_numpy(np.ascontiguousarray(v)) for k, v in sd.items()},
        os.path.join(save_dir, "pytorch_model.bin"),
    )
    return save_dir
