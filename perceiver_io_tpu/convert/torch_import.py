"""Import reference (torch) `perceiver-io` weights into perceiver_io_tpu
parameter pytrees.

The mapping tables here are the JAX-side equivalent of the reference's
``perceiver/model/core/huggingface.py:17-76`` copy helpers, and double as
the numerical-equivalence test fixtures (SURVEY.md §4: logits allclose at
atol 1e-4 is the de-facto correctness oracle).

Accepted inputs are plain state-dict-like mappings ``name -> array`` (torch
tensors or numpy arrays), so torch is only needed by the caller. Layout
correspondences:

==============================  =======================================
reference (torch)               perceiver_io_tpu (flax)
==============================  =======================================
``Linear.weight`` (out, in)     ``Dense.kernel`` (in, out) — transposed
``LayerNorm.weight``            ``LayerNorm.scale``
``Embedding.weight``            ``Embed.embedding``
``TrainableQueryProvider._query``  ``TrainableQueryProvider.query``
``Sequential`` indices (0/1/3)  named modules (norm/hidden/out)
``Residual.module`` wrapper     (transparent)
==============================  =======================================
"""
from __future__ import annotations

from typing import Any, Dict, Mapping, Optional

import numpy as np


def _np(x) -> np.ndarray:
    if hasattr(x, "detach"):  # torch tensor
        x = x.detach().cpu().numpy()
    return np.asarray(x)


def _strip_wrappers(state_dict: Mapping[str, Any]) -> Dict[str, np.ndarray]:
    """Remove fairscale checkpoint-wrapper name fragments and map the
    reference ``PerceiverIO`` Sequential indices (``0.`` = encoder, ``1.`` =
    decoder, reference ``modules.py:584-594``) to named prefixes."""
    out = {}
    for k, v in state_dict.items():
        k = k.replace("_checkpoint_wrapped_module.", "")
        if k.startswith("0."):
            k = "encoder." + k[2:]
        elif k.startswith("1."):
            k = "decoder." + k[2:]
        out[k] = v
    return out


def _linear(sd, name) -> Dict[str, np.ndarray]:
    out = {"kernel": _np(sd[f"{name}.weight"]).T}
    if f"{name}.bias" in sd:
        out["bias"] = _np(sd[f"{name}.bias"])
    return out


def _norm(sd, name) -> Dict[str, np.ndarray]:
    return {"scale": _np(sd[f"{name}.weight"]), "bias": _np(sd[f"{name}.bias"])}


def _embed(sd, name) -> Dict[str, np.ndarray]:
    return {"embedding": _np(sd[f"{name}.weight"])}


def _attention(sd, base) -> Dict[str, Any]:
    return {p: _linear(sd, f"{base}.{p}") for p in ("q_proj", "k_proj", "v_proj", "o_proj")}


def _mlp(sd, base) -> Dict[str, Any]:
    # reference MLP = Sequential(LayerNorm, Linear, GELU, Linear) → indices 0, 1, 3
    return {
        "norm": _norm(sd, f"{base}.0"),
        "hidden": _linear(sd, f"{base}.1"),
        "out": _linear(sd, f"{base}.3"),
    }


def _cross_attn_layer(sd, base, attention_residual: bool = True) -> Dict[str, Any]:
    # CrossAttentionLayer = Sequential(Residual(CrossAttention) | CrossAttention, Residual(MLP))
    pre = f"{base}.0.module" if attention_residual else f"{base}.0"
    return {
        "cross_attn": {
            "q_norm": _norm(sd, f"{pre}.q_norm"),
            "kv_norm": _norm(sd, f"{pre}.kv_norm"),
            "attention": _attention(sd, f"{pre}.attention"),
        },
        "mlp": _mlp(sd, f"{base}.1.module"),
    }


def _self_attn_layer(sd, base) -> Dict[str, Any]:
    return {
        "self_attn": {
            "norm": _norm(sd, f"{base}.0.module.norm"),
            "attention": _attention(sd, f"{base}.0.module.attention"),
        },
        "mlp": _mlp(sd, f"{base}.1.module"),
    }


def _self_attn_block(sd, base, num_layers: int) -> Dict[str, Any]:
    return {f"layers_{i}": _self_attn_layer(sd, f"{base}.{i}") for i in range(num_layers)}


def _encoder(sd, base, encoder_config, prefix_sep=".") -> Dict[str, Any]:
    """PerceiverEncoder params (without the input adapter)."""
    c = encoder_config
    out = {
        "latent_provider": {"query": _np(sd[f"{base}{prefix_sep}latent_provider._query"])},
        "cross_attn_1": _cross_attn_layer(sd, f"{base}{prefix_sep}cross_attn_1"),
        "self_attn_1": _self_attn_block(
            sd, f"{base}{prefix_sep}self_attn_1", c.num_self_attention_layers_per_block
        ),
    }
    if c.num_cross_attention_layers > 1 and not c.first_cross_attention_layer_shared:
        out["cross_attn_n"] = _cross_attn_layer(sd, f"{base}{prefix_sep}cross_attn_n")
    if c.num_self_attention_blocks > 1 and not c.first_self_attention_block_shared:
        out["self_attn_n"] = _self_attn_block(
            sd, f"{base}{prefix_sep}self_attn_n", c.num_self_attention_layers_per_block
        )
    return out


def _text_input_adapter(sd, base, abs_pos_emb: bool = True) -> Dict[str, Any]:
    out = {"txt_embedding": _embed(sd, f"{base}.txt_embedding")}
    if abs_pos_emb and f"{base}.pos_embedding.weight" in sd:
        out["pos_embedding"] = _embed(sd, f"{base}.pos_embedding")
    return out


def _decoder(sd, base, decoder_config) -> Dict[str, Any]:
    residual = getattr(decoder_config, "cross_attention_residual", True)
    return {"cross_attn": _cross_attn_layer(sd, f"{base}.cross_attn", attention_residual=residual)}


# ---------------------------------------------------------------------------
# Task models
# ---------------------------------------------------------------------------


def import_masked_language_model(state_dict: Mapping[str, Any], config) -> Dict[str, Any]:
    """Reference ``MaskedLanguageModel`` state_dict → :class:`MaskedLanguageModel`
    params (config = :data:`MaskedLanguageModelConfig`)."""
    sd = _strip_wrappers(state_dict)
    params = {
        "encoder": {
            "input_adapter": _text_input_adapter(sd, "encoder.input_adapter"),
            **_encoder(sd, "encoder", config.encoder),
        },
        "decoder": {
            "output_query_provider": {"query": _np(sd["decoder.output_query_provider._query"])},
            **_decoder(sd, "decoder", config.decoder),
        },
    }
    if config.decoder.num_output_query_channels is None:
        if "decoder.output_adapter.bias" in sd:
            params["decoder"]["output_adapter"] = {"bias": _np(sd["decoder.output_adapter.bias"])}
    else:
        params["decoder"]["output_adapter"] = {
            "linear": _linear(sd, "decoder.output_adapter.linear")
        }
    return params


def import_text_classifier(state_dict: Mapping[str, Any], config) -> Dict[str, Any]:
    """Reference ``TextClassifier`` state_dict → :class:`TextClassifier` params."""
    sd = _strip_wrappers(state_dict)
    return {
        "encoder": {
            "input_adapter": _text_input_adapter(sd, "encoder.input_adapter"),
            **_encoder(sd, "encoder", config.encoder),
        },
        "decoder": {
            "output_query_provider": {"query": _np(sd["decoder.output_query_provider._query"])},
            "output_adapter": {"linear": _linear(sd, "decoder.output_adapter.linear")},
            **_decoder(sd, "decoder", config.decoder),
        },
    }


def import_image_classifier(state_dict: Mapping[str, Any], config) -> Dict[str, Any]:
    """Reference ``ImageClassifier`` state_dict → :class:`ImageClassifier` params
    (the image input adapter holds no parameters — Fourier features are
    deterministic)."""
    sd = _strip_wrappers(state_dict)
    return {
        "encoder": _encoder(sd, "encoder", config.encoder),
        "decoder": {
            "output_query_provider": {"query": _np(sd["decoder.output_query_provider._query"])},
            "output_adapter": {"linear": _linear(sd, "decoder.output_adapter.linear")},
            **_decoder(sd, "decoder", config.decoder),
        },
    }


def import_optical_flow(state_dict: Mapping[str, Any], config) -> Dict[str, Any]:
    """Reference ``OpticalFlow`` state_dict → :class:`OpticalFlow` params."""
    sd = _strip_wrappers(state_dict)
    return {
        "encoder": {
            "input_adapter": {"linear": _linear(sd, "encoder.input_adapter.linear")},
            **_encoder(sd, "encoder", config.encoder),
        },
        "decoder": {
            "output_adapter": {"linear": _linear(sd, "decoder.output_adapter.linear")},
            **_decoder(sd, "decoder", config.decoder),
        },
    }


def _sequence_model(state_dict: Mapping[str, Any], config) -> Dict[str, Any]:
    """Shared CLM / symbolic-audio import: reference flat PerceiverAR layout →
    our ``perceiver_ar``-nested layout."""
    sd = _strip_wrappers(state_dict)
    params: Dict[str, Any] = {
        "perceiver_ar": {
            "input_adapter": _text_input_adapter(
                sd, "input_adapter", abs_pos_emb=config.abs_pos_emb
            ),
            "cross_attention": _cross_attn_layer(sd, "cross_attention"),
            "self_attention": _self_attn_block(
                sd, "self_attention", config.num_self_attention_layers
            ),
        }
    }
    if config.output_norm:
        params["out_norm"] = _norm(sd, "out_norm")
    if config.output_bias:
        params["output_adapter"] = {"bias": _np(sd["output_adapter.bias"])}
    return params


def import_causal_language_model(state_dict: Mapping[str, Any], config) -> Dict[str, Any]:
    """Reference ``CausalLanguageModel`` state_dict → :class:`CausalLanguageModel`
    params (config = :class:`CausalLanguageModelConfig`)."""
    return _sequence_model(state_dict, config)


def import_symbolic_audio_model(state_dict: Mapping[str, Any], config) -> Dict[str, Any]:
    """Reference ``SymbolicAudioModel`` state_dict → :class:`SymbolicAudioModel`
    params."""
    return _sequence_model(state_dict, config)
