from perceiver_io_tpu.convert.export import (
    export_causal_language_model,
    export_image_classifier,
    export_masked_language_model,
    export_optical_flow,
    export_symbolic_audio_model,
    export_text_classifier,
    save_reference_checkpoint,
)
from perceiver_io_tpu.convert.torch_import import (
    import_causal_language_model,
    import_image_classifier,
    import_masked_language_model,
    import_optical_flow,
    import_symbolic_audio_model,
    import_text_classifier,
)
