from perceiver_io_tpu.convert.torch_import import (
    import_causal_language_model,
    import_image_classifier,
    import_masked_language_model,
    import_optical_flow,
    import_symbolic_audio_model,
    import_text_classifier,
)
