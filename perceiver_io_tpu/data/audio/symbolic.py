"""Symbolic-audio datamodule: MIDI dirs → flat int16 token stream →
random-window samples → static left/right-padded shift-by-one batches.

Behavioral parity with the reference (``perceiver/data/audio/symbolic.py:16-232``):

- **storage**: every encoded piece is appended to one flat ``int16`` array
  with ``-1`` separators between pieces, saved as ``train.bin``/``valid.bin``
  memmaps — O(1) random access into the token stream.
- **sampling**: a sample is a random window of ``max_seq_len + 1`` tokens;
  if it crosses piece boundaries, the longest separator-free span is kept;
  with ``min_seq_len`` set, the span is further truncated to a random length
  (the AR curriculum over sequence lengths, reference ``symbolic.py:161-191``).
- **collation**: pad to ``max_seq_len + 1`` on the configured side, then emit
  the shift-by-one ``{"labels": x[1:], "input_ids": x[:-1], "pad_mask"}``
  dict — static shapes, one XLA compilation.

TPU-first deltas: sampling uses a per-epoch seeded generator (reproducible
across restarts; the reference draws from the global torch RNG), and batches
are NumPy dicts for ``device_put`` straight into the sharded train step.
"""
from __future__ import annotations

import os
from pathlib import Path
from typing import Dict, List, Optional, Sequence

import numpy as np

from perceiver_io_tpu.data.audio.midi import (
    PAD_TOKEN,
    SEPARATOR,
    VOCAB_SIZE,
    encode_midi_files,
)
from perceiver_io_tpu.data.loader import DataLoader
from perceiver_io_tpu.data.text.collators import IGNORE_INDEX


class SymbolicAudioDataset:
    """Random windows over the flat separator-delimited token stream."""

    def __init__(
        self,
        data: np.ndarray,
        max_seq_len: int,
        *,
        min_seq_len: Optional[int] = None,
        seed: int = 0,
    ):
        if data.shape[0] <= max_seq_len + 1:
            raise ValueError(
                f"token stream of {data.shape[0]} tokens is too short for "
                f"max_seq_len={max_seq_len}"
            )
        self._data = data
        self._window = max_seq_len + 1  # +1 for the shift-by-one view
        self._min_window = min_seq_len + 1 if min_seq_len is not None else None
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return self._data.shape[0] // self._window

    def __getitem__(self, index) -> Dict:
        start = int(self._rng.integers(0, self._data.shape[0] - self._window))
        sample = np.asarray(self._data[start : start + self._window], np.int64)

        if (sample == SEPARATOR).any():
            # longest separator-free span (reference symbolic.py:173-183)
            bounds = np.flatnonzero(sample == SEPARATOR)
            edges = np.concatenate([[-1], bounds, [len(sample)]])
            spans = [
                sample[edges[i] + 1 : edges[i + 1]]
                for i in range(len(edges) - 1)
            ]
            sample = max(spans, key=len)

        if self._min_window is not None and self._min_window < len(sample):
            length = int(self._rng.integers(self._min_window, self._window))
            sample = sample[:length]
        return {"input_ids": sample}


class SymbolicAudioCollator:
    """Pad to ``max_seq_len + 1``, emit shift-by-one dict (reference
    ``symbolic.py:194-232``; pad labels become ``IGNORE_INDEX`` so the loss
    mask needs no separate plumbing)."""

    def __init__(self, max_seq_len: int, padding_side: str = "left"):
        if padding_side not in ("left", "right"):
            raise ValueError(f"invalid padding side '{padding_side}'")
        self._width = max_seq_len + 1
        self._side = padding_side

    def __call__(self, examples: Sequence[Dict]) -> Dict[str, np.ndarray]:
        rows = np.full((len(examples), self._width), PAD_TOKEN, np.int32)
        for r, example in enumerate(examples):
            ids = example["input_ids"][: self._width]
            if self._side == "left":
                rows[r, self._width - len(ids) :] = ids
            else:
                rows[r, : len(ids)] = ids
        input_ids = rows[:, :-1]
        labels = rows[:, 1:].astype(np.int32)
        pad_mask = input_ids == PAD_TOKEN
        labels = np.where(labels == PAD_TOKEN, IGNORE_INDEX, labels)
        return {"labels": labels, "input_ids": input_ids, "pad_mask": pad_mask}


class SymbolicAudioDataModule:
    """Reference ``SymbolicAudioDataModule`` (``symbolic.py:16-157``).

    Subclasses (or callers) provide the source MIDI directories via
    :meth:`load_source_dataset`; :meth:`from_token_streams` injects already
    encoded streams (tests, custom corpora).
    """

    vocab_size: int = VOCAB_SIZE

    def __init__(
        self,
        dataset_dir: str,
        max_seq_len: int,
        *,
        min_seq_len: Optional[int] = None,
        padding_side: str = "left",
        batch_size: int = 16,
        preproc_workers: int = 1,
        seed: int = 0,
        shard_index: Optional[int] = None,
        shard_count: Optional[int] = None,
    ):
        if min_seq_len is not None and not 0 < min_seq_len < max_seq_len:
            raise ValueError("need 0 < min_seq_len < max_seq_len")
        self.dataset_dir = Path(dataset_dir)
        self.max_seq_len = max_seq_len
        self.min_seq_len = min_seq_len
        self.padding_side = padding_side
        self.batch_size = batch_size
        self.preproc_workers = preproc_workers
        self.seed = seed
        self.shard_index = shard_index
        self.shard_count = shard_count
        self._splits: Dict[str, np.ndarray] = {}

    # -- sourcing ----------------------------------------------------------
    @property
    def preproc_dir(self) -> Path:
        return self.dataset_dir / "preproc"

    def load_source_dataset(self) -> Dict[str, object]:
        """Return ``{"train": ..., "valid": ...}`` (optionally ``"test"``)
        MIDI sources.

        Each value is either a directory (``rglob``-ed for ``.mid``/``.midi``)
        or an explicit list of files (manifest- or bucket-derived splits).
        Splits must be disjoint — overlap leaks training data into
        evaluation and makes the metrics meaningless.
        """
        raise NotImplementedError

    def split_signature(self) -> str:
        """Configuration that determines split *membership* (not content).
        Stored in the preproc manifest; a cache built under a different
        signature is refused instead of silently reusing wrong splits.
        Default "" matches caches written before this hook existed."""
        return ""

    @staticmethod
    def _midi_files(source) -> List[Path]:
        if isinstance(source, (list, tuple)):
            return sorted(Path(f) for f in source)
        midi_dir = Path(source)
        return sorted(midi_dir.rglob("**/*.mid")) + sorted(midi_dir.rglob("**/*.midi"))

    @classmethod
    def from_token_streams(
        cls,
        train: np.ndarray,
        valid: np.ndarray,
        max_seq_len: int,
        test: Optional[np.ndarray] = None,
        **kwargs,
    ) -> "SymbolicAudioDataModule":
        dm = cls(dataset_dir=".", max_seq_len=max_seq_len, **kwargs)
        dm._splits = {
            "train": np.asarray(train, np.int16),
            "valid": np.asarray(valid, np.int16),
        }
        if test is not None:
            dm._splits["test"] = np.asarray(test, np.int16)
        return dm

    @staticmethod
    def flatten_pieces(pieces: List[np.ndarray], shuffle_seed: Optional[int] = None) -> np.ndarray:
        """Concatenate encoded pieces with separators (reference
        ``symbolic.py:117-118``)."""
        if shuffle_seed is not None:
            order = np.random.default_rng(shuffle_seed).permutation(len(pieces))
            pieces = [pieces[i] for i in order]
        parts = [np.append(p.astype(np.int16), np.int16(SEPARATOR)) for p in pieces]
        return np.concatenate(parts)

    def prepare_data(self) -> None:
        if self._splits:
            return
        if self.preproc_dir.exists():
            import json

            # Caches written before disjoint splits existed have no manifest
            # and were built with train == valid — refuse to reuse them.
            manifest_file = self.preproc_dir / "split_manifest.json"
            if not manifest_file.exists():
                raise ValueError(
                    f"{self.preproc_dir} was built by an older version with "
                    "overlapping train/valid splits (no split_manifest.json); "
                    "delete it and re-run preprocessing"
                )
            stored = json.loads(manifest_file.read_text()).get("_signature", "")
            if stored != self.split_signature():
                raise ValueError(
                    f"{self.preproc_dir} was preprocessed under a different "
                    f"split configuration ({stored!r} vs "
                    f"{self.split_signature()!r}) — reusing it would mix "
                    "split memberships; delete it and re-run preprocessing"
                )
            return
        sources = self.load_source_dataset()
        names = [s for s in ("train", "valid", "test") if s in sources]
        split_files = {s: self._midi_files(sources[s]) for s in names}
        for a in names:
            for b in names:
                if a >= b:
                    continue
                overlap = set(map(str, split_files[a])) & set(map(str, split_files[b]))
                if overlap:
                    raise ValueError(
                        f"{a}/{b} splits overlap on {len(overlap)} files "
                        f"(e.g. {sorted(overlap)[0]}) — evaluation would leak "
                        "training data"
                    )
        os.makedirs(self.preproc_dir)
        for split in names:
            files = split_files[split]
            pieces = encode_midi_files(files, num_workers=self.preproc_workers)
            flat = self.flatten_pieces(
                pieces, shuffle_seed=self.seed if split == "train" else None
            )
            fp = np.memmap(
                self.preproc_dir / f"{split}.bin", np.int16, mode="w+", shape=flat.shape
            )
            fp[:] = flat
            fp.flush()
        import json

        manifest = {s: [str(f) for f in split_files[s]] for s in names}
        manifest["_signature"] = self.split_signature()
        (self.preproc_dir / "split_manifest.json").write_text(json.dumps(manifest))

    def setup(self) -> None:
        if self._splits:
            return
        self._splits = {
            split: np.memmap(self.preproc_dir / f"{split}.bin", np.int16, mode="r")
            for split in ("train", "valid", "test")
            if (self.preproc_dir / f"{split}.bin").exists()
        }

    # -- loaders -----------------------------------------------------------
    def _loader(self, split: str, min_seq_len: Optional[int]) -> DataLoader:
        dataset = SymbolicAudioDataset(
            self._splits[split],
            self.max_seq_len,
            min_seq_len=min_seq_len,
            seed=self.seed,
        )
        return DataLoader(
            dataset,
            batch_size=self.batch_size,
            shuffle=False,  # samples are already random windows
            drop_last=True,
            collate_fn=SymbolicAudioCollator(self.max_seq_len, self.padding_side),
            seed=self.seed,
            shard_index=self.shard_index,
            shard_count=self.shard_count,
        )

    def train_dataloader(self) -> DataLoader:
        return self._loader("train", self.min_seq_len)

    def val_dataloader(self) -> DataLoader:
        # validation always uses full windows (reference symbolic.py:133-137)
        return self._loader("valid", None)

    def test_dataloader(self) -> DataLoader:
        if "test" not in self._splits:
            raise ValueError(
                f"{type(self).__name__} materialized no test split — either "
                "the source provides none, or the preproc cache at "
                f"{self.preproc_dir} predates test-split support; in the "
                "latter case delete it and re-run preprocessing"
            )
        return self._loader("test", None)


class MaestroV3DataModule(SymbolicAudioDataModule):
    """MAESTRO v3 piano corpus: expects the extracted archive at
    ``<dataset_dir>/maestro-v3.0.0`` (zero-egress images cannot download;
    point ``dataset_dir`` at a local copy).

    Splits follow the official ``maestro-v3.0.0.json`` manifest exactly as
    the reference does (``maestro_v3.py:58-76``): columnar
    ``metadata["midi_filename"]``/``metadata["split"]``, ``train`` → train,
    ``validation`` → valid, and ``test`` → the test split (which the
    reference discards; here it feeds the CLI ``test`` subcommand).
    """

    def load_source_dataset(self) -> Dict[str, List[Path]]:
        import json

        root = self.dataset_dir / "maestro-v3.0.0"
        if not root.exists():
            raise FileNotFoundError(
                f"{root} not found — place the extracted MAESTRO v3 archive there"
            )
        meta_file = root / "maestro-v3.0.0.json"
        if not meta_file.exists():
            raise FileNotFoundError(f"missing MAESTRO manifest {meta_file}")
        with open(meta_file) as f:
            metadata = json.load(f)
        splits: Dict[str, List[Path]] = {"train": [], "valid": [], "test": []}
        names = {"train": "train", "validation": "valid", "test": "test"}
        for _id, file_path in metadata["midi_filename"].items():
            splits[names[metadata["split"][_id]]].append(root / file_path)
        return splits


class GiantMidiPianoDataModule(SymbolicAudioDataModule):
    """GiantMIDI-Piano corpus: expects MIDI files under ``<dataset_dir>/midis``.

    The reference's hosted archive ships pre-split ``train``/``valid``
    directories (``giantmidi_piano.py:38-47``); when those exist they are
    used as-is. A flat ``midis`` directory (the upstream GiantMIDI layout)
    is split deterministically by filename hash instead: ``valid`` = files
    whose ``crc32(name) % num_buckets == valid_bucket`` — stable across runs
    and machines, and disjoint from train by construction.
    """

    valid_bucket: int = 0
    #: hash bucket carved out as the test split; ``None`` (default) keeps the
    #: historical train/valid layout byte-identical (no test split).
    test_bucket: Optional[int] = None
    num_buckets: int = 10

    def split_signature(self) -> str:
        # "" for the historical default so pre-existing caches stay valid.
        if (self.valid_bucket, self.test_bucket, self.num_buckets) == (0, None, 10):
            return ""
        return f"buckets:{self.valid_bucket},{self.test_bucket},{self.num_buckets}"

    def load_source_dataset(self) -> Dict[str, object]:
        root = self.dataset_dir / "midis"
        if not root.exists():
            raise FileNotFoundError(f"{root} not found — place GiantMIDI midis there")
        train_dir, valid_dir = root / "train", root / "valid"
        if train_dir.exists() and valid_dir.exists():
            out = {"train": train_dir, "valid": valid_dir}
            if (root / "test").exists():
                out["test"] = root / "test"
            return out
        if train_dir.exists() or valid_dir.exists():
            raise ValueError(
                f"{root} has only one of train/valid — a partially extracted "
                "pre-split archive; hash-splitting it would discard the "
                "curated split. Restore both directories or remove the one."
            )
        import zlib

        files = self._midi_files(root)
        if self.test_bucket is not None and self.test_bucket == self.valid_bucket:
            raise ValueError("test_bucket must differ from valid_bucket")
        buckets = [zlib.crc32(f.name.encode()) % self.num_buckets for f in files]
        out = {
            "train": [
                f for f, b in zip(files, buckets)
                if b != self.valid_bucket and b != self.test_bucket
            ],
            "valid": [f for f, b in zip(files, buckets) if b == self.valid_bucket],
        }
        if self.test_bucket is not None:
            out["test"] = [f for f, b in zip(files, buckets) if b == self.test_bucket]
        return out


class SyntheticSymbolicAudioDataModule(SymbolicAudioDataModule):
    """Deterministic synthetic event streams — offline smoke runs and config
    dry-runs (no reference counterpart; Maestro/GiantMIDI must download).
    Pieces are order-1 Markov walks over a seeded transition structure on the
    MIDI event vocab, so the next-event task is learnable, and piece lengths
    vary so separator/window handling is exercised."""

    def __init__(
        self,
        max_seq_len: int,
        *,
        dataset_dir: str = ".cache/synthetic_sam",
        num_train_pieces: int = 24,
        num_valid_pieces: int = 8,
        num_test_pieces: int = 8,
        mean_piece_len: int = 4096,
        **kwargs,
    ):
        super().__init__(dataset_dir=dataset_dir, max_seq_len=max_seq_len, **kwargs)
        self._gen = (num_train_pieces, num_valid_pieces, num_test_pieces, mean_piece_len)

    def prepare_data(self) -> None:  # nothing to download or encode
        pass

    def setup(self) -> None:
        if self._splits:
            return
        num_train, num_valid, num_test, mean_piece_len = self._gen
        rng = np.random.default_rng(self.seed)
        # sparse row-peaked transitions: each event strongly prefers a few
        # successors, so the stream has learnable structure
        successors = rng.integers(0, VOCAB_SIZE - 1, size=(VOCAB_SIZE, 4))

        def piece():
            n = int(rng.integers(mean_piece_len // 2, mean_piece_len * 3 // 2))
            out = np.empty(n, np.int16)
            s = int(rng.integers(VOCAB_SIZE - 1))
            for i in range(n):
                s = int(successors[s, rng.integers(4)]) if rng.random() < 0.9 else int(
                    rng.integers(VOCAB_SIZE - 1)
                )
                out[i] = s
            return out

        self._splits = {
            "train": self.flatten_pieces([piece() for _ in range(num_train)],
                                         shuffle_seed=self.seed),
            "valid": self.flatten_pieces([piece() for _ in range(num_valid)]),
        }
        if num_test:
            # drawn after train/valid from the same stream: enabling the
            # test split never changes the other two
            self._splits["test"] = self.flatten_pieces(
                [piece() for _ in range(num_test)]
            )
