"""MIDI event codec — the symbolic-audio token vocabulary.

Behavioral parity with the reference's MIDI processor
(``perceiver/data/audio/midi_processor.py:13-270``, itself adapted from the
public midi-neural-processor): 388-event vocabulary

- ``note_on``    pitch 0-127   → ids   0-127
- ``note_off``   pitch 0-127   → ids 128-255
- ``time_shift`` 10ms-1s steps → ids 256-355 (value ``v`` = (v+1)/100 s)
- ``velocity``   32 buckets    → ids 356-387 (bucket = velocity // 4)

plus PAD=388 (vocab size 389, reference ``symbolic.py:17-19``). Encoding
emits a velocity event only when the bucket changes; time gaps > 1s emit
repeated max shifts. Sustain-pedal (CC 64) handling extends note-offs to the
pedal-release or the next same-pitch note-on, matching the reference's
``SustainDownManager`` transposition.

The codec works on a neutral :class:`Note` representation so it is fully
testable without a MIDI I/O library; :func:`encode_midi_file` /
:func:`decode_to_midi_file` bridge to ``pretty_midi`` when installed (it is
not part of the baked TPU image).
"""
from __future__ import annotations

import concurrent.futures as cf
from dataclasses import dataclass
from pathlib import Path
from typing import Iterable, List, Optional, Sequence, Tuple

import numpy as np

RANGE_NOTE_ON = 128
RANGE_NOTE_OFF = 128
RANGE_TIME_SHIFT = 100
RANGE_VELOCITY = 32

NOTE_ON_OFFSET = 0
NOTE_OFF_OFFSET = RANGE_NOTE_ON
TIME_SHIFT_OFFSET = RANGE_NOTE_ON + RANGE_NOTE_OFF
VELOCITY_OFFSET = RANGE_NOTE_ON + RANGE_NOTE_OFF + RANGE_TIME_SHIFT

NUM_EVENTS = VELOCITY_OFFSET + RANGE_VELOCITY  # 388
PAD_TOKEN = NUM_EVENTS  # 388
VOCAB_SIZE = NUM_EVENTS + 1  # 389
SEPARATOR = -1  # example separator in flat storage (reference symbolic.py:17)

TIME_STEP = 0.01  # seconds per time_shift unit


@dataclass
class Note:
    """One played note; the neutral exchange type of the codec."""

    pitch: int
    velocity: int
    start: float
    end: float


@dataclass
class ControlChange:
    """A control-change message; only CC 64 (sustain) is interpreted."""

    number: int
    value: int
    time: float


def _apply_sustain(notes: List[Note], controls: Sequence[ControlChange]) -> List[Note]:
    """Extend notes held by the sustain pedal (CC64 ≥ 64 down, < 64 up):
    within a pedal window a note's end is moved to the next start of the same
    pitch, or to the pedal release if no such note follows (reference
    ``midi_processor.py:31-47,172-208``)."""
    pedals: List[Tuple[float, float]] = []
    down: Optional[float] = None
    for ctrl in sorted((c for c in controls if c.number == 64), key=lambda c: c.time):
        if ctrl.value >= 64 and down is None:
            down = ctrl.time
        elif ctrl.value < 64 and down is not None:
            pedals.append((down, ctrl.time))
            down = None
        elif ctrl.value < 64 and pedals:
            pedals[-1] = (pedals[-1][0], ctrl.time)
    if not pedals:
        return notes

    notes = sorted((Note(n.pitch, n.velocity, n.start, n.end) for n in notes),
                   key=lambda n: n.start)
    for start, end in pedals:
        managed = [n for n in notes if start <= n.start <= end]
        # Walk backwards: each managed note ends at the next same-pitch start,
        # the last one at max(pedal end, its own end).
        next_start: dict = {}
        for note in reversed(managed):
            if note.pitch in next_start:
                note.end = next_start[note.pitch]
            else:
                note.end = max(end, note.end)
            next_start[note.pitch] = note.start
    return notes


def events_from_notes(
    notes: Iterable[Note],
    controls: Sequence[ControlChange] = (),
) -> List[int]:
    """Notes (+ sustain controls) → event-id sequence."""
    notes = _apply_sustain(list(notes), controls)

    # Split into timed on/off markers, stable-ordered by time.
    markers: List[Tuple[float, str, Note]] = []
    for note in sorted(notes, key=lambda n: n.start):
        markers.append((note.start, "note_on", note))
        markers.append((note.end, "note_off", note))
    markers.sort(key=lambda m: m[0])

    events: List[int] = []
    cur_time = 0.0
    cur_vel_bucket = 0
    for time, kind, note in markers:
        # time shifts (repeat max shift for gaps > 1s)
        interval = int(round((time - cur_time) / TIME_STEP))
        while interval >= RANGE_TIME_SHIFT:
            events.append(TIME_SHIFT_OFFSET + RANGE_TIME_SHIFT - 1)
            interval -= RANGE_TIME_SHIFT
        if interval > 0:
            events.append(TIME_SHIFT_OFFSET + interval - 1)

        if kind == "note_on":
            bucket = note.velocity // 4
            if bucket != cur_vel_bucket:
                events.append(VELOCITY_OFFSET + bucket)
                cur_vel_bucket = bucket
            events.append(NOTE_ON_OFFSET + note.pitch)
        else:
            events.append(NOTE_OFF_OFFSET + note.pitch)
        cur_time = time
    return events


def notes_from_events(event_ids: Iterable[int]) -> List[Note]:
    """Event-id sequence → notes. Unmatched note-offs are dropped,
    zero-length notes discarded (reference ``_merge_note``)."""
    timeline = 0.0
    velocity = 0
    open_notes: dict = {}
    notes: List[Note] = []
    for idx in event_ids:
        idx = int(idx)
        if idx < 0 or idx >= NUM_EVENTS:
            continue  # pad / separator / out-of-vocab
        if idx < NOTE_OFF_OFFSET:
            open_notes[idx] = (timeline, velocity)
        elif idx < TIME_SHIFT_OFFSET:
            pitch = idx - NOTE_OFF_OFFSET
            if pitch in open_notes:
                start, vel = open_notes.pop(pitch)
                if timeline > start:
                    notes.append(Note(pitch, vel, start, timeline))
        elif idx < VELOCITY_OFFSET:
            timeline += (idx - TIME_SHIFT_OFFSET + 1) * TIME_STEP
        else:
            velocity = (idx - VELOCITY_OFFSET) * 4
    notes.sort(key=lambda n: n.start)
    return notes


# -- pretty_midi bridge (optional dependency) ------------------------------
def encode_midi_file(path: Path) -> Optional[np.ndarray]:
    """MIDI file → int16 event array, or None on parse failure."""
    try:
        import pretty_midi
    except ImportError as e:
        raise ImportError("encode_midi_file requires pretty_midi") from e
    try:
        midi = pretty_midi.PrettyMIDI(str(path))
        notes: List[Note] = []
        controls: List[ControlChange] = []
        for inst in midi.instruments:
            sub_controls = [
                ControlChange(c.number, c.value, c.time)
                for c in inst.control_changes
                if c.number == 64
            ]
            sub_notes = [Note(n.pitch, n.velocity, n.start, n.end) for n in inst.notes]
            # Sustain is per-instrument in the reference; encode respecting that.
            notes.extend(_apply_sustain(sub_notes, sub_controls))
        return np.asarray(events_from_notes(notes), np.int16)
    except Exception as e:  # unreadable/corrupt files are skipped, as in reference
        print(f"error encoding midi file [{path}]: {e}")
        return None


def decode_to_midi_file(event_ids: Iterable[int], path: Optional[Path] = None):
    """Event ids → pretty_midi object (optionally written to ``path``)."""
    try:
        import pretty_midi
    except ImportError as e:
        raise ImportError("decode_to_midi_file requires pretty_midi") from e
    midi = pretty_midi.PrettyMIDI()
    instrument = pretty_midi.Instrument(1)
    for note in notes_from_events(event_ids):
        instrument.notes.append(
            pretty_midi.Note(note.velocity, note.pitch, note.start, note.end)
        )
    midi.instruments.append(instrument)
    if path is not None:
        midi.write(str(path))
    return midi


def encode_midi_files(files: Sequence[Path], num_workers: int = 1) -> List[np.ndarray]:
    """Encode files in a process pool (reference ``midi_processor.py:258-263``)."""
    if num_workers <= 1:
        encoded = [encode_midi_file(f) for f in files]
    else:
        with cf.ProcessPoolExecutor(max_workers=num_workers) as pool:
            encoded = list(pool.map(encode_midi_file, files))
    return [e for e in encoded if e is not None]
