"""Audio data layer (host-side NumPy) — capability surface of the
reference's ``perceiver/data/audio/`` package (SURVEY.md §2.3): the MIDI
event codec and the symbolic-audio datamodule feeding Perceiver AR training.
"""
from perceiver_io_tpu.data.audio.midi import (
    PAD_TOKEN,
    SEPARATOR,
    VOCAB_SIZE,
    ControlChange,
    Note,
    decode_to_midi_file,
    encode_midi_file,
    encode_midi_files,
    events_from_notes,
    notes_from_events,
)
from perceiver_io_tpu.data.audio.symbolic import (
    GiantMidiPianoDataModule,
    MaestroV3DataModule,
    SymbolicAudioCollator,
    SymbolicAudioDataModule,
    SyntheticSymbolicAudioDataModule,
    SymbolicAudioDataset,
)

__all__ = [
    "PAD_TOKEN",
    "SEPARATOR",
    "VOCAB_SIZE",
    "Note",
    "ControlChange",
    "events_from_notes",
    "notes_from_events",
    "encode_midi_file",
    "encode_midi_files",
    "decode_to_midi_file",
    "SymbolicAudioCollator",
    "SymbolicAudioDataModule",
    "SyntheticSymbolicAudioDataModule",
    "SymbolicAudioDataset",
    "MaestroV3DataModule",
    "GiantMidiPianoDataModule",
]
