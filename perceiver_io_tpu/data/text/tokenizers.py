"""Tokenizers behind one small protocol.

The reference resolves every tokenizer through ``AutoTokenizer.from_pretrained``
(``perceiver/data/text/common.py:27,116``), including the UTF-8 bytes
``deepmind/language-perceiver`` tokenizer. Here:

- :class:`ByteTokenizer` is a **native, offline** implementation of that byte
  vocabulary (262 = 6 specials + 256 bytes, offset 6 — the layout of
  ``transformers.PerceiverTokenizer``), so byte-level models (CLM / MLM /
  enwik8) need no hub access.
- :class:`HFTokenizer` adapts any Hugging Face tokenizer to the same protocol
  (used e.g. for the SentencePiece C4 models).
- :func:`load_tokenizer` resolves a name to one of the two.

The protocol methods every consumer (preprocessor, collators, datamodule)
relies on: ``encode``, ``decode``, ``encode_batch``, ``word_ids``, and the
``vocab_size`` / ``pad_token_id`` / ``mask_token_id`` / ``eos_token_id`` /
``padding_side`` attributes.
"""
from __future__ import annotations

import string
from typing import List, Optional, Sequence, Tuple

import numpy as np

# Byte-tokenizer special tokens — the PerceiverTokenizer layout.
PAD_ID, BOS_ID, EOS_ID, MASK_ID, CLS_ID, SEP_ID = range(6)
BYTE_OFFSET = 6
BYTE_VOCAB_SIZE = 262


class ByteTokenizer:
    """UTF-8 bytes tokenizer: token = byte + 6; ids 0..5 are
    [PAD] [BOS] [EOS] [MASK] [CLS] [SEP]. Word boundaries (for whole-word
    masking) are whitespace runs, synthesised like the reference's
    ``PerceiverTokenizerUtil`` (``perceiver/data/text/utils.py:13-39``)."""

    vocab_size = BYTE_VOCAB_SIZE
    pad_token_id = PAD_ID
    bos_token_id = BOS_ID
    eos_token_id = EOS_ID
    mask_token_id = MASK_ID
    cls_token_id = CLS_ID
    sep_token_id = SEP_ID
    mask_token = "<mask>"  # placeholder substring, mapped to MASK_ID in encode
    name = "byte"

    _WHITESPACE_IDS = frozenset(b + BYTE_OFFSET for b in string.whitespace.encode())

    def __init__(self, padding_side: str = "right"):
        self.padding_side = padding_side

    # -- encode / decode ----------------------------------------------------
    def encode(self, text: str, add_special_tokens: bool = False) -> List[int]:
        ids: List[int] = []
        for i, part in enumerate(text.split(self.mask_token)):
            if i > 0:
                ids.append(MASK_ID)
            # vectorized byte mapping: the corpus-preproc hot loop
            ids.extend(
                (np.frombuffer(part.encode("utf-8"), np.uint8).astype(np.int64) + BYTE_OFFSET).tolist()
            )
        if add_special_tokens:
            ids = [CLS_ID] + ids + [SEP_ID]
        return ids

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        out = bytearray()
        for i in ids:
            i = int(i)
            if i >= BYTE_OFFSET:
                out.append(i - BYTE_OFFSET)
            elif not skip_special_tokens:
                out += f"[{i}]".encode()
        return out.decode("utf-8", errors="replace")

    def batch_decode(self, rows, skip_special_tokens: bool = True) -> List[str]:
        return [self.decode(r, skip_special_tokens) for r in rows]

    def encode_batch(
        self,
        texts: Sequence[str],
        max_length: Optional[int] = None,
        add_special_tokens: bool = False,
        pad_to_max: bool = True,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Returns ``(input_ids, pad_mask)`` with pad_mask True at padding —
        the reference's inverted-attention-mask convention
        (``perceiver/data/text/common.py:35-46``)."""
        seqs = [self.encode(t, add_special_tokens) for t in texts]
        if max_length is not None:
            seqs = [
                s[:max_length] if len(s) <= max_length or not add_special_tokens
                # keep the trailing [SEP] when truncating a special-tokens encode
                else s[: max_length - 1] + [SEP_ID]
                for s in seqs
            ]
        width = max(len(s) for s in seqs) if seqs else 0
        if pad_to_max and max_length is not None:
            width = max_length
        ids = np.full((len(seqs), width), self.pad_token_id, dtype=np.int32)
        mask = np.ones((len(seqs), width), dtype=bool)
        for row, s in enumerate(seqs):
            n = len(s)
            if self.padding_side == "left":
                ids[row, width - n :] = s
                mask[row, width - n :] = False
            else:
                ids[row, :n] = s
                mask[row, :n] = False
        return ids, mask

    # -- word ids for whole-word masking ------------------------------------
    def word_ids(self, token_ids: Sequence[int]) -> List[Optional[int]]:
        """Whitespace-boundary word ids; whitespaces join the *following* word;
        special tokens get ``None`` (reference ``utils.py:13-39`` semantics:
        distinct words ⇒ distinct ids)."""
        out: List[Optional[int]] = []
        curr = 0
        in_word = True
        for t in token_ids:
            t = int(t)
            if t < BYTE_OFFSET:
                out.append(None)
                curr += 1
            elif t in self._WHITESPACE_IDS:
                if in_word:
                    in_word = False
                    curr += 1
                out.append(curr)
            else:
                in_word = True
                out.append(curr)
        return out


class HFTokenizer:
    """Adapter: any Hugging Face (fast) tokenizer → the local protocol."""

    def __init__(self, tokenizer, padding_side: Optional[str] = None):
        self.hf = tokenizer
        if padding_side is not None:
            self.hf.padding_side = padding_side
        self.name = getattr(tokenizer, "name_or_path", "hf")

    @property
    def padding_side(self) -> str:
        return self.hf.padding_side

    @padding_side.setter
    def padding_side(self, side: str) -> None:
        self.hf.padding_side = side

    @property
    def vocab_size(self) -> int:
        return self.hf.vocab_size

    @property
    def pad_token_id(self):
        return self.hf.pad_token_id

    @property
    def mask_token_id(self):
        return self.hf.mask_token_id

    @property
    def mask_token(self):
        return self.hf.mask_token

    @property
    def eos_token_id(self):
        return self.hf.eos_token_id

    def batch_decode(self, rows, skip_special_tokens: bool = True) -> List[str]:
        return self.hf.batch_decode(
            [[int(i) for i in r] for r in rows], skip_special_tokens=skip_special_tokens
        )

    def encode(self, text: str, add_special_tokens: bool = False) -> List[int]:
        return self.hf(text, add_special_tokens=add_special_tokens)["input_ids"]

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        return self.hf.decode([int(i) for i in ids], skip_special_tokens=skip_special_tokens)

    def encode_batch(
        self,
        texts: Sequence[str],
        max_length: Optional[int] = None,
        add_special_tokens: bool = False,
        pad_to_max: bool = True,
    ) -> Tuple[np.ndarray, np.ndarray]:
        enc = self.hf(
            list(texts),
            padding="max_length" if (pad_to_max and max_length) else bool(self.hf.pad_token),
            truncation=max_length is not None,
            max_length=max_length,
            add_special_tokens=add_special_tokens,
            return_attention_mask=True,
        )
        ids = np.asarray(enc["input_ids"], dtype=np.int32)
        pad_mask = ~np.asarray(enc["attention_mask"], dtype=bool)
        return ids, pad_mask

    def word_ids(self, token_ids: Sequence[int]) -> List[Optional[int]]:
        # Fast tokenizers expose word ids only at encode time; re-derive from a
        # round-trip is lossy, so synthesize whitespace-boundary ids from the
        # decoded pieces (sufficient for WordMaskingCollator: distinct words
        # get distinct ids).
        out: List[Optional[int]] = []
        special = set(self.hf.all_special_ids)
        curr = 0
        in_word = True
        for t in token_ids:
            t = int(t)
            if t in special:
                out.append(None)
                curr += 1
                continue
            piece = self.hf.convert_ids_to_tokens(t)
            starts_word = piece.startswith(("Ġ", "▁", " ")) or piece.isspace()
            if starts_word and in_word:
                curr += 1
            in_word = not (starts_word and piece.isspace())
            out.append(curr)
        return out


def load_tokenizer(name: str, padding_side: Optional[str] = None):
    """Resolve a tokenizer name. ``"byte"`` / the two Perceiver byte-tokenizer
    repo ids map to the offline :class:`ByteTokenizer`; anything else goes
    through ``AutoTokenizer`` (reference ``common.py:116-126``)."""
    if name in ("byte", "deepmind/language-perceiver", "krasserm/perceiver-io-mlm"):
        return ByteTokenizer(padding_side=padding_side or "right")
    from transformers import AutoTokenizer

    return HFTokenizer(AutoTokenizer.from_pretrained(name, verbose=False), padding_side)
