"""Concrete text dataset modules (reference
``perceiver/data/text/{wikitext,imdb,enwik8,bookcorpus,wikipedia}.py``): each
only overrides :meth:`load_source_dataset`. Hub-backed sources import
``datasets`` lazily so the package works fully offline; :class:`ListDataModule`
feeds in-memory text (the test/offline path — the reference has no offline
equivalent, its tests download real IMDb subsets, SURVEY.md §4)."""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

import numpy as np

from perceiver_io_tpu.data.text.datamodule import Task, TextDataModule


class ListDataModule(TextDataModule):
    """In-memory source: ``train_texts`` / ``valid_texts`` are lists of
    strings, or (text, label) behavior via ``train_labels``/``valid_labels``."""

    def __init__(
        self,
        train_texts: Sequence[str],
        valid_texts: Sequence[str],
        train_labels: Optional[Sequence[int]] = None,
        valid_labels: Optional[Sequence[int]] = None,
        test_texts: Optional[Sequence[str]] = None,
        test_labels: Optional[Sequence[int]] = None,
        num_classes: Optional[int] = None,
        **kwargs,
    ):
        super().__init__(**kwargs)
        self._train = (list(train_texts), list(train_labels) if train_labels else None)
        self._valid = (list(valid_texts), list(valid_labels) if valid_labels else None)
        self._test = (
            (list(test_texts), list(test_labels) if test_labels else None)
            if test_texts is not None
            else None
        )
        self._num_classes = num_classes

    @property
    def num_classes(self) -> Optional[int]:
        return self._num_classes

    def load_source_dataset(self) -> Dict[str, object]:
        def pack(texts, labels):
            return {"text": texts, "label": labels} if labels is not None else texts

        out = {"train": pack(*self._train), "valid": pack(*self._valid)}
        if self._test is not None:
            out["test"] = pack(*self._test)
        return out


class _HubDataModule(TextDataModule):
    """Shared plumbing for Hugging Face hub sources."""

    def __init__(self, dataset_dir: Optional[str] = None, **kwargs):
        super().__init__(dataset_dir=dataset_dir or os.path.join(".cache", self.cache_name), **kwargs)

    cache_name = "hub"

    def _load(self, path: str, name: Optional[str] = None, **kwargs):
        from datasets import load_dataset

        return load_dataset(path, name, cache_dir=self.dataset_dir, **kwargs)

    @staticmethod
    def _texts(split) -> List[str]:
        return split["text"]


class _CarvedTestSplit:
    """Mixin for sources that carve train/valid (and optionally test) from a
    single upstream split. The test slice is taken from just before the valid
    tail, so enabling it leaves the valid split byte-identical and only
    shrinks train — no leakage, no golden churn."""

    source_valid_size: float
    source_test_size: float

    def _carved_splits(self, texts, n_valid: int) -> Dict[str, object]:
        n_test = int(len(texts) * self.source_test_size)
        train_end = len(texts) - n_valid - n_test
        if train_end <= 0:
            raise ValueError(
                f"source_valid_size + source_test_size leave no training data "
                f"({len(texts)} docs, {n_valid} valid + {n_test} test) — "
                "negative slicing here would silently overlap the splits"
            )
        out = {"train": texts[:train_end], "valid": texts[len(texts) - n_valid:]}
        if n_test:
            out["test"] = texts[train_end: train_end + n_test]
        return out

    def preproc_dir_hash_input(self) -> str:
        key = super().preproc_dir_hash_input()  # type: ignore[misc]
        if self.source_test_size:
            key += f"|test:{self.source_test_size}"
        return key


class WikiTextDataModule(_HubDataModule):
    """wikitext-103-raw (reference ``wikitext.py:10-20``); the upstream
    ``test`` split is materialized for the CLI ``test`` subcommand."""

    cache_name = "wikitext"

    def load_source_dataset(self) -> Dict[str, object]:
        ds = self._load("wikitext", "wikitext-103-raw-v1")
        return {
            "train": self._texts(ds["train"]),
            "valid": self._texts(ds["validation"]),
            "test": self._texts(ds["test"]),
        }


class ImdbDataModule(_HubDataModule):
    """IMDb: clf uses train/test with labels; mlm/clm use unsupervised/test
    text only (reference ``imdb.py:10-33``)."""

    cache_name = "imdb"

    @property
    def num_classes(self) -> int:
        return 2

    def load_source_dataset(self) -> Dict[str, object]:
        # IMDb publishes no validation split; the reference evaluates on the
        # official test split as "valid" (``imdb.py:10-33``). The test split
        # here is that same official split, so the ``test`` subcommand
        # reports on exactly the protocol the reference's numbers use.
        ds = self._load("imdb", "plain_text")
        if self.task == Task.clf:
            official_test = {"text": ds["test"]["text"], "label": ds["test"]["label"]}
            return {
                "train": {"text": ds["train"]["text"], "label": ds["train"]["label"]},
                "valid": official_test,
                "test": official_test,
            }
        official_test = self._texts(ds["test"])  # one object: tokenized once
        return {
            "train": self._texts(ds["unsupervised"]),
            "valid": official_test,
            "test": official_test,
        }


class Enwik8DataModule(_CarvedTestSplit, _HubDataModule):
    """enwik8 with a train/valid split and per-line trailing newline
    (reference ``enwik8.py:10-37``)."""

    cache_name = "enwik8"

    def __init__(self, source_valid_size: float = 0.05, source_test_size: float = 0.0, **kwargs):
        self.source_valid_size = source_valid_size
        self.source_test_size = source_test_size
        super().__init__(**kwargs)

    def load_source_dataset(self) -> Dict[str, object]:
        ds = self._load("enwik8", "enwik8", split="train")
        texts = [t + "\n" for t in ds["text"]]
        return self._carved_splits(texts, int(len(texts) * self.source_valid_size))


class BookCorpusDataModule(_CarvedTestSplit, _HubDataModule):
    cache_name = "bookcorpus"

    def __init__(self, source_valid_size: float = 0.05, source_test_size: float = 0.0, **kwargs):
        self.source_valid_size = source_valid_size
        self.source_test_size = source_test_size
        super().__init__(**kwargs)

    def load_source_dataset(self) -> Dict[str, object]:
        ds = self._load("bookcorpus", split="train")
        texts = self._texts(ds)
        return self._carved_splits(texts, int(len(texts) * self.source_valid_size))


class BookCorpusOpenDataModule(_CarvedTestSplit, _HubDataModule):
    """bookcorpusopen: whole books, one record each (reference
    ``perceiver/data/text/bookcorpusopen.py``)."""

    cache_name = "bookcorpusopen"

    def __init__(self, source_valid_size: float = 0.05, source_test_size: float = 0.0, **kwargs):
        self.source_valid_size = source_valid_size
        self.source_test_size = source_test_size
        super().__init__(**kwargs)

    def load_source_dataset(self) -> Dict[str, object]:
        ds = self._load("bookcorpusopen", split="train")
        texts = self._texts(ds)
        return self._carved_splits(texts, max(1, int(len(texts) * self.source_valid_size)))


class WikipediaDataModule(_CarvedTestSplit, _HubDataModule):
    cache_name = "wikipedia"

    def __init__(
        self,
        config_name: str = "20220301.en",
        source_valid_size: float = 0.01,
        source_test_size: float = 0.0,
        **kwargs,
    ):
        self.config_name = config_name
        self.source_valid_size = source_valid_size
        self.source_test_size = source_test_size
        super().__init__(**kwargs)

    def load_source_dataset(self) -> Dict[str, object]:
        ds = self._load("wikipedia", self.config_name, split="train")
        texts = self._texts(ds)
        return self._carved_splits(texts, int(len(texts) * self.source_valid_size))


def markov_transition(rng) -> "np.ndarray":
    """The synthetic corpus's order-1 Markov transition matrix — the FIRST
    draw from the corpus rng (``default_rng(corpus_seed)``). Shared with the
    entropy-floor oracle (``examples/training/longrun.py``) so the floor can
    never silently diverge from the data it bounds: rows are
    ``dirichlet(0.3)`` over the 27-char alphabet (peaked → entropy well
    below uniform)."""
    k = len(SyntheticTextDataModule._ALPHABET)
    return rng.dirichlet(np.full(k, 0.3), size=k)


class SyntheticTextDataModule(TextDataModule):
    """Deterministic synthetic corpus — offline smoke runs, CI, and config
    dry-runs (no reference counterpart: the reference cannot train without
    downloading a dataset).

    For mlm/clm, documents are order-1 Markov character text over a seeded
    transition matrix: structured (entropy well below uniform) so a model
    can visibly learn, yet fully reproducible. For the clf task, each
    document samples words from one of two disjoint pools and the label is
    the pool index — linearly separable, so accuracy climbs within a few
    steps. Generation happens lazily in :meth:`load_source_dataset` (cache
    misses only), and the generation parameters are part of the preproc
    cache key — changing them regenerates instead of reusing stale arrays.
    """

    _ALPHABET = "abcdefghijklmnopqrstuvwxyz "

    def __init__(
        self,
        dataset_dir: str = ".cache/synthetic",
        num_train_docs: int = 64,
        num_valid_docs: int = 16,
        num_test_docs: Optional[int] = None,
        doc_chars: int = 2048,
        corpus_seed: int = 0,
        **kwargs,
    ):
        self.num_train_docs = num_train_docs
        self.num_valid_docs = num_valid_docs
        # default: a test split the size of valid (drawn after train/valid
        # from the same stream, so enabling it never changes those splits)
        self.num_test_docs = num_valid_docs if num_test_docs is None else num_test_docs
        self.doc_chars = doc_chars
        self.corpus_seed = corpus_seed
        task = kwargs.get("task", "mlm")
        self._clf = (task if isinstance(task, str) else getattr(task, "name", "mlm")) == "clf"
        super().__init__(dataset_dir=dataset_dir, **kwargs)
        self._num_classes = 2 if self._clf else None

    @property
    def num_classes(self):
        return self._num_classes

    def preproc_dir_hash_input(self) -> str:
        return (
            super().preproc_dir_hash_input()
            + f"|synthetic:{self.num_train_docs},{self.num_valid_docs},"
            + f"{self.doc_chars},{self.corpus_seed}"
            + (f",test:{self.num_test_docs}" if self.num_test_docs else "")
        )

    def load_source_dataset(self) -> Dict[str, object]:
        rng = np.random.default_rng(self.corpus_seed)
        if self._clf:
            pools = (
                ["alpha", "bravo", "carbon", "delta", "ember"],
                ["zinc", "yarrow", "xenon", "willow", "vortex"],
            )

            def split(n):
                labels = [int(i % 2) for i in range(n)]
                texts = [
                    " ".join(rng.choice(pools[l], size=max(1, self.doc_chars // 8)))
                    for l in labels
                ]
                return {"text": texts, "label": labels}

            out = {"train": split(self.num_train_docs), "valid": split(self.num_valid_docs)}
            if self.num_test_docs:
                out["test"] = split(self.num_test_docs)
            return out

        trans = markov_transition(rng)
        k = trans.shape[0]

        def doc():
            states = np.empty(self.doc_chars, np.int64)
            s = int(rng.integers(k))
            for i in range(self.doc_chars):
                s = int(rng.choice(k, p=trans[s]))
                states[i] = s
            return "".join(self._ALPHABET[c] for c in states)

        out = {
            "train": [doc() for _ in range(self.num_train_docs)],
            "valid": [doc() for _ in range(self.num_valid_docs)],
        }
        if self.num_test_docs:
            out["test"] = [doc() for _ in range(self.num_test_docs)]
        return out
