"""Text data pipelines (reference ``perceiver/data/text/``, SURVEY.md §2.3)."""
from perceiver_io_tpu.data.text.collators import (
    DefaultCollator,
    RandomTruncateCollator,
    TokenMaskingCollator,
    WordMaskingCollator,
)
from perceiver_io_tpu.data.text.datamodule import (
    ChunkedTokenDataset,
    CLMView,
    RandomShiftView,
    Task,
    TextDataModule,
)
from perceiver_io_tpu.data.text.preprocessor import TextPreprocessor
from perceiver_io_tpu.data.text.streaming import (
    C4DataModule,
    StreamingTextPipeline,
    shard_iterable,
    window_shuffle,
)
from perceiver_io_tpu.data.text.sources import (
    BookCorpusDataModule,
    Enwik8DataModule,
    ImdbDataModule,
    ListDataModule,
    WikipediaDataModule,
    WikiTextDataModule,
)
from perceiver_io_tpu.data.text.tokenizers import ByteTokenizer, HFTokenizer, load_tokenizer

__all__ = [
    "ByteTokenizer",
    "BookCorpusDataModule",
    "C4DataModule",
    "StreamingTextPipeline",
    "shard_iterable",
    "window_shuffle",
    "CLMView",
    "ChunkedTokenDataset",
    "DefaultCollator",
    "Enwik8DataModule",
    "HFTokenizer",
    "ImdbDataModule",
    "ListDataModule",
    "RandomShiftView",
    "RandomTruncateCollator",
    "Task",
    "TextDataModule",
    "TextPreprocessor",
    "TokenMaskingCollator",
    "WikiTextDataModule",
    "WikipediaDataModule",
    "WordMaskingCollator",
    "load_tokenizer",
]
