"""Inference-time text preprocessing (reference ``TextPreprocessor``,
``perceiver/data/text/common.py:25-46``): text → (input_ids, pad_mask) with
pad_mask True at padding positions."""
from __future__ import annotations

from typing import Sequence, Tuple, Union

import numpy as np

from perceiver_io_tpu.data.text.tokenizers import load_tokenizer


class TextPreprocessor:
    def __init__(self, tokenizer, max_seq_len: int, add_special_tokens: bool = False):
        if isinstance(tokenizer, str):
            tokenizer = load_tokenizer(tokenizer)
        self.tokenizer = tokenizer
        self.max_seq_len = max_seq_len
        self.add_special_tokens = add_special_tokens

    def preprocess(self, text: str) -> Tuple[np.ndarray, np.ndarray]:
        ids, mask = self.preprocess_batch([text])
        return ids[0], mask[0]

    def preprocess_batch(
        self, texts: Sequence[str], pad_to_max: bool = False
    ) -> Tuple[np.ndarray, np.ndarray]:
        return self.tokenizer.encode_batch(
            list(texts),
            max_length=self.max_seq_len,
            add_special_tokens=self.add_special_tokens,
            pad_to_max=pad_to_max,
        )
