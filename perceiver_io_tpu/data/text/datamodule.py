"""Text datamodule: tokenize → chunk → (mask) → cache, then task-specific
dataset views and loaders.

Mirrors the reference's ``TextDataModule`` pipeline
(``perceiver/data/text/common.py:55-361``) with a TPU-first storage design:
after chunking, a split is a single ``(num_chunks, chunk_size)`` int32 array
saved as ``.npy`` and memory-mapped at load — no arrow/pyarrow layer, O(1)
random access, zero-copy slices into the collator. The cache directory is
keyed by an md5 of the preprocessing config, exactly the reference's scheme
(``common.py:164-188``).

Task pipelines (``common.py:255-272``):

- ``clm``: tokenize (no word ids) → chunk to ``max_seq_len + 1``; the
  :class:`CLMView` then yields the shift-by-one (input, label) pair.
- ``mlm``: tokenize with word ids → chunk to ``max_seq_len``; masking happens
  either dynamically in the collator or statically here.
- ``clf``: tokenize each document truncated to ``max_seq_len``, keep labels.
"""
from __future__ import annotations

import hashlib
import json
import os
from enum import Enum
from typing import Dict, List, Optional, Sequence

import numpy as np

from perceiver_io_tpu.data.loader import DataLoader
from perceiver_io_tpu.data.text.collators import (
    IGNORE_INDEX,
    NO_WORD,
    DefaultCollator,
    RandomTruncateCollator,
    TokenMaskingCollator,
    WordMaskingCollator,
)
from perceiver_io_tpu.data.text.preprocessor import TextPreprocessor
from perceiver_io_tpu.data.text.tokenizers import load_tokenizer


class Task(Enum):
    mlm = 0
    clm = 1
    clf = 2


class ChunkedTokenDataset:
    """A split after preprocessing: dense 2-D arrays, one row per example."""

    def __init__(
        self,
        input_ids: np.ndarray,
        word_ids: Optional[np.ndarray] = None,
        labels: Optional[np.ndarray] = None,
        lengths: Optional[np.ndarray] = None,
    ):
        self.input_ids = input_ids
        self.word_ids = word_ids
        self.labels = labels
        self.lengths = lengths

    def __len__(self) -> int:
        return len(self.input_ids)

    def __getitem__(self, idx: int) -> Dict:
        n = int(self.lengths[idx]) if self.lengths is not None else self.input_ids.shape[1]
        ex: Dict = {"input_ids": np.asarray(self.input_ids[idx][:n])}
        if self.word_ids is not None:
            ex["word_ids"] = np.asarray(self.word_ids[idx][:n])
        if self.labels is not None:
            if self.labels.ndim == 1:  # classification scalar
                ex["label"] = int(self.labels[idx])
            else:  # static-masking label ids
                ex["label_ids"] = np.asarray(self.labels[idx][:n])
        return ex


class RandomShiftView:
    """Example ``i`` = ``concat(rec[i][shift:], rec[i+1][:shift])`` with a
    random per-access shift — the reference's concatenation augmentation
    (``common.py:364-387``). Applies the same shift to every key."""

    def __init__(self, dataset, seed: int = 0):
        self.dataset = dataset
        self.rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return len(self.dataset) - 1

    def __getitem__(self, idx: int) -> Dict:
        a, b = self.dataset[idx], self.dataset[idx + 1]
        shift = int(self.rng.integers(0, len(a["input_ids"])))
        return {
            k: np.concatenate([a[k][shift:], b[k][:shift]])
            for k in a
            if isinstance(a[k], np.ndarray)
        } | {k: v for k, v in a.items() if not isinstance(v, np.ndarray)}


class CLMView:
    """Shift-by-one view over ``max_seq_len + 1`` chunks (reference
    ``CLMDataset``, ``common.py:390-399``)."""

    def __init__(self, dataset):
        self.dataset = dataset

    def __len__(self) -> int:
        return len(self.dataset)

    def __getitem__(self, idx: int) -> Dict:
        ids = self.dataset[idx]["input_ids"]
        return {"input_ids": ids[:-1], "label_ids": ids[1:]}


class TextDataModule:
    """Base text datamodule. Subclasses implement :meth:`load_source_dataset`
    returning ``{"train": split, "valid": split}`` where a split is either a
    list of strings or a dict ``{"text": [...], "label": [...]}``.

    Constructor args mirror the reference's (``common.py:56-108``); loading
    knobs that are torch-specific (pin_memory, worker counts) are dropped —
    the loader prefetches on a thread and shards per host instead.
    """

    def __init__(
        self,
        dataset_dir: str,
        tokenizer: str = "byte",
        max_seq_len: int = 2048,
        task: Task = Task.mlm,
        mask_prob: float = 0.15,
        mask_words: bool = True,
        static_masking: bool = False,
        add_special_tokens: bool = False,
        add_eos_token: bool = False,
        padding_side: Optional[str] = None,
        random_train_shift: bool = False,
        random_valid_shift: bool = False,
        random_train_truncation: bool = False,
        random_valid_truncation: bool = False,
        random_min_seq_len: int = 16,
        batch_size: int = 64,
        valid_batch_size: Optional[int] = None,
        seed: int = 0,
    ):
        if static_masking and not mask_words:
            raise ValueError("static_masking=true is only supported for mask_words=true")
        if isinstance(task, str):
            task = Task[task]
        self.dataset_dir = dataset_dir
        self.tokenizer_name = tokenizer
        self.tokenizer = load_tokenizer(tokenizer, padding_side)
        self.max_seq_len = max_seq_len
        self.task = task
        self.mask_prob = mask_prob
        self.mask_words = mask_words
        self.static_masking = static_masking
        self.add_special_tokens = add_special_tokens
        self.add_eos_token = add_eos_token
        self.random_train_shift = random_train_shift
        self.random_valid_shift = random_valid_shift
        self.random_train_truncation = random_train_truncation
        self.random_valid_truncation = random_valid_truncation
        self.random_min_seq_len = random_min_seq_len
        self.batch_size = batch_size
        self.valid_batch_size = valid_batch_size or batch_size
        self.seed = seed
        self.ds_train = None
        self.ds_valid = None
        self.ds_test = None

    # -- source hook --------------------------------------------------------
    def load_source_dataset(self) -> Dict[str, object]:
        raise NotImplementedError

    @property
    def vocab_size(self) -> int:
        return self.tokenizer.vocab_size

    @property
    def num_classes(self) -> Optional[int]:
        return None

    @property
    def random_shift(self) -> bool:
        return self.random_train_shift or self.random_valid_shift

    # -- cache keying (reference common.py:164-188) -------------------------
    def preproc_dir_hash_input(self) -> str:
        key = f"{self.tokenizer_name}-{self.max_seq_len}-{self.task.name}-{self.random_shift}"
        if self.task == Task.mlm and self.static_masking:
            key += f"-{self.mask_words}-{self.mask_prob}"
        if self.add_special_tokens:
            key += "-st"
        if self.add_eos_token:
            key += "-eos"
        return key

    @property
    def preproc_dir(self) -> str:
        h = hashlib.md5(self.preproc_dir_hash_input().encode()).hexdigest()
        return os.path.join(self.dataset_dir, "preproc", h)

    # -- preprocessing ------------------------------------------------------
    def prepare_data(self) -> None:
        if os.path.exists(os.path.join(self.preproc_dir, "meta.json")):
            return
        source = self.load_source_dataset()
        os.makedirs(self.preproc_dir, exist_ok=True)
        meta = {"task": self.task.name, "splits": {}}
        prepared: Dict[int, Dict[str, np.ndarray]] = {}
        for split, data in source.items():
            # Sources may alias one object across splits (e.g. IMDb's valid
            # and test are both the official test split) — tokenize it once.
            if id(data) in prepared:
                arrays = prepared[id(data)]
            else:
                arrays = prepared.setdefault(id(data), self._prepare_split(data))
            for name, arr in arrays.items():
                np.save(os.path.join(self.preproc_dir, f"{split}.{name}.npy"), arr)
            meta["splits"][split] = {
                "num_examples": int(len(arrays["input_ids"])),
                "arrays": sorted(arrays),
            }
        with open(os.path.join(self.preproc_dir, "meta.json"), "w") as f:
            json.dump(meta, f)

    def _texts_and_labels(self, data) -> tuple[List[str], Optional[List[int]]]:
        if isinstance(data, dict):
            return list(data["text"]), list(data["label"]) if "label" in data else None
        return list(data), None

    def _prepare_split(self, data) -> Dict[str, np.ndarray]:
        texts, labels = self._texts_and_labels(data)
        if self.add_eos_token:
            eos = (
                self.tokenizer.decode([self.tokenizer.eos_token_id], skip_special_tokens=False)
                if self.tokenizer.eos_token_id is not None
                else ""
            )
        tok = self.tokenizer

        if self.task == Task.clf:
            assert labels is not None, "clf task requires labels in the source dataset"
            rows = [
                np.asarray(
                    tok.encode(t, add_special_tokens=self.add_special_tokens)[: self.max_seq_len],
                    dtype=np.int32,
                )
                for t in texts
            ]
            lengths = np.asarray([len(r) for r in rows], dtype=np.int32)
            ids = np.zeros((len(rows), self.max_seq_len), dtype=np.int32)
            for i, r in enumerate(rows):
                ids[i, : len(r)] = r
            return {
                "input_ids": ids,
                "lengths": lengths,
                "labels": np.asarray(labels, dtype=np.int32),
            }

        # clm / mlm: tokenize everything, concatenate, chunk.
        want_word_ids = self.task == Task.mlm
        chunk_size = self.max_seq_len + 1 if self.task == Task.clm else self.max_seq_len
        all_ids: List[np.ndarray] = []
        all_wids: List[np.ndarray] = []
        wid_base = 0
        for text in texts:
            if self.add_eos_token and self.tokenizer.eos_token_id is not None:
                ids = tok.encode(text, add_special_tokens=self.add_special_tokens)
                ids = ids + [self.tokenizer.eos_token_id]
            else:
                ids = tok.encode(text, add_special_tokens=self.add_special_tokens)
            all_ids.append(np.asarray(ids, dtype=np.int32))
            if want_word_ids:
                wids = tok.word_ids(ids)
                arr = np.asarray(
                    [NO_WORD if w is None else w + wid_base for w in wids], dtype=np.int64
                )
                # offset so words never collide across documents
                wid_base = int(arr.max()) + 2 if len(arr) and arr.max() >= 0 else wid_base
                all_wids.append(arr)

        flat_ids = np.concatenate(all_ids) if all_ids else np.zeros(0, np.int32)
        n_chunks = len(flat_ids) // chunk_size
        ids = flat_ids[: n_chunks * chunk_size].reshape(n_chunks, chunk_size)
        out = {"input_ids": ids}
        if want_word_ids:
            flat_wids = np.concatenate(all_wids)
            out["word_ids"] = flat_wids[: n_chunks * chunk_size].reshape(n_chunks, chunk_size)
        if self.task == Task.mlm and self.static_masking:
            wmc = WordMaskingCollator(tok, self.mask_prob, seed=self.seed)
            masked = np.empty_like(out["input_ids"])
            labels_arr = np.empty_like(out["input_ids"])
            for i in range(n_chunks):
                masked[i], labels_arr[i] = wmc.mask_example(out["input_ids"][i], out["word_ids"][i])
            out["input_ids"] = masked
            out["labels"] = labels_arr
            del out["word_ids"]
        return out

    # -- load + views -------------------------------------------------------
    def _load_split(self, split: str) -> ChunkedTokenDataset:
        def load(name):
            path = os.path.join(self.preproc_dir, f"{split}.{name}.npy")
            return np.load(path, mmap_mode="r") if os.path.exists(path) else None

        return ChunkedTokenDataset(
            input_ids=load("input_ids"),
            word_ids=load("word_ids"),
            labels=load("labels"),
            lengths=load("lengths"),
        )

    def setup(self) -> None:
        self.ds_train = self._load_split("train")
        self.ds_valid = self._load_split("valid")
        if os.path.exists(os.path.join(self.preproc_dir, "test.input_ids.npy")):
            # Deterministic: no random shift/truncation views on test.
            self.ds_test = self._load_split("test")
        if self.task in (Task.clm, Task.mlm):
            if self.random_train_shift:
                self.ds_train = RandomShiftView(self.ds_train, seed=self.seed)
            if self.random_valid_shift:
                self.ds_valid = RandomShiftView(self.ds_valid, seed=self.seed + 1)
        if self.task == Task.clm:
            self.ds_train = CLMView(self.ds_train)
            self.ds_valid = CLMView(self.ds_valid)
            if self.ds_test is not None:
                self.ds_test = CLMView(self.ds_test)

    # -- collator / loaders (reference common.py:127-139,206-234) -----------
    def _base_collator(self):
        if self.task == Task.mlm and not self.static_masking:
            cls = WordMaskingCollator if self.mask_words else TokenMaskingCollator
            return cls(self.tokenizer, self.mask_prob, seed=self.seed)
        return DefaultCollator(self.tokenizer, max_seq_len=self.max_seq_len)

    def _loader(self, dataset, batch_size, shuffle, truncate, seed) -> DataLoader:
        collator = self._base_collator()
        if truncate:
            collator = RandomTruncateCollator(collator, self.random_min_seq_len, seed=seed)
        return DataLoader(
            dataset, batch_size=batch_size, shuffle=shuffle, seed=seed, collate_fn=collator
        )

    def train_dataloader(self) -> DataLoader:
        return self._loader(
            self.ds_train, self.batch_size, True, self.random_train_truncation, self.seed
        )

    def val_dataloader(self) -> DataLoader:
        return self._loader(
            self.ds_valid, self.valid_batch_size, False, self.random_valid_truncation, self.seed + 1
        )

    def test_dataloader(self) -> DataLoader:
        """Deterministic pass over the test split (CLI ``test`` subcommand,
        reference LightningCLI fit/validate/test parity,
        ``perceiver/scripts/cli.py:13-48``)."""
        if self.ds_test is None:
            raise ValueError(
                f"{type(self).__name__} materialized no test split — either "
                "the source dataset provides none (source_test_size=0), or "
                f"the preproc cache at {self.preproc_dir} predates test-split "
                "support; in the latter case delete it and re-run preproc"
            )
        return self._loader(self.ds_test, self.valid_batch_size, False, False, self.seed + 2)

    def text_preprocessor(self) -> TextPreprocessor:
        return TextPreprocessor(
            self.tokenizer, max_seq_len=self.max_seq_len, add_special_tokens=self.add_special_tokens
        )
