"""Collators — reference ``perceiver/data/text/collator.py`` semantics with a
TPU-critical change: batches are padded to a **fixed** ``max_seq_len`` rather
than the batch max, so every training step has one static shape and XLA
compiles exactly once. (The reference pads to the longest example per batch,
``collator.py:53-56`` — fine for eager torch, a retrace storm under jit.)

All collators emit dict batches ``{"labels", "input_ids", "pad_mask"}``
(int32 / int32 / bool, True at padding) — the dict form of the reference's
``(labels, input_ids, ~attention_mask)`` tuple protocol (``collator.py:20-22``).
Word ids ride along as int32 arrays with ``-1`` in place of the reference's
``None`` (special tokens).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

IGNORE_INDEX = -100
NO_WORD = -1


def _pad_rows(
    rows: Sequence[np.ndarray],
    width: int,
    pad_value: int,
    padding_side: str,
) -> tuple[np.ndarray, np.ndarray]:
    """Pad/truncate 1-D int rows to ``width``; returns (array, pad_mask)."""
    out = np.full((len(rows), width), pad_value, dtype=np.int32)
    mask = np.ones((len(rows), width), dtype=bool)
    for i, row in enumerate(rows):
        row = np.asarray(row, dtype=np.int32)[:width]
        n = len(row)
        if padding_side == "left":
            out[i, width - n :] = row
            mask[i, width - n :] = False
        else:
            out[i, :n] = row
            mask[i, :n] = False
    return out, mask


class DefaultCollator:
    """Pad-to-``max_seq_len`` collator for clf / clm-view batches (reference
    ``DefaultCollator``, ``collator.py:44-85``). Labels priority: per-token
    ``label_ids`` (CLM shift view) > scalar ``label`` (classification) >
    all-ignore."""

    def __init__(self, tokenizer, max_seq_len: int):
        self.tokenizer = tokenizer
        self.max_seq_len = max_seq_len

    def __call__(self, examples: Sequence[Dict]) -> Dict[str, np.ndarray]:
        side = self.tokenizer.padding_side
        pad_id = self.tokenizer.pad_token_id or 0
        ids, pad_mask = _pad_rows(
            [e["input_ids"] for e in examples], self.max_seq_len, pad_id, side
        )
        if "label_ids" in examples[0]:
            labels, _ = _pad_rows(
                [e["label_ids"] for e in examples], self.max_seq_len, IGNORE_INDEX, side
            )
            labels = np.where(pad_mask, IGNORE_INDEX, labels)
        elif "label" in examples[0]:
            labels = np.asarray([e["label"] for e in examples], dtype=np.int32)
        else:
            labels = np.where(pad_mask, IGNORE_INDEX, ids)
        return {"labels": labels, "input_ids": ids, "pad_mask": pad_mask}


class WordMaskingCollator:
    """Whole-word masking (reference ``WordMaskingCollator``,
    ``collator.py:88-144``): select words with ``mask_prob``; replace the
    selected word's tokens with [MASK] (80%), random tokens (10%), or leave
    them (10%); labels are the original ids at selected positions and
    ``IGNORE_INDEX`` elsewhere. The 80/10/10 draw is per *word* (both random
    numbers drawn once per word, exactly the reference's branching)."""

    def __init__(self, tokenizer, mask_prob: float = 0.15, seed: Optional[int] = None):
        self.tokenizer = tokenizer
        self.mask_prob = mask_prob
        self.rng = np.random.default_rng(seed)

    def mask_example(
        self, input_ids: np.ndarray, word_ids: np.ndarray
    ) -> tuple[np.ndarray, np.ndarray]:
        input_ids = np.asarray(input_ids, dtype=np.int32).copy()
        word_ids = np.asarray(word_ids)
        labels = np.full_like(input_ids, IGNORE_INDEX)

        # Group consecutive equal word ids into words (ids need not be
        # globally unique — only distinct between adjacent words).
        words: List[np.ndarray] = []
        start = None
        for i in range(len(word_ids) + 1):
            boundary = (
                i == len(word_ids)
                or word_ids[i] == NO_WORD
                or (start is not None and word_ids[i] != word_ids[start])
            )
            if boundary:
                if start is not None:
                    words.append(np.arange(start, i))
                start = None if i == len(word_ids) or word_ids[i] == NO_WORD else i
            elif start is None:
                start = i
        if start is not None:
            words.append(np.arange(start, len(word_ids)))

        if words:
            select = self.rng.binomial(1, self.mask_prob, len(words)).astype(bool)
            for word, sel in zip(words, select):
                if not sel:
                    continue
                r_mask, r_rand = self.rng.random(2)
                labels[word] = input_ids[word]
                if r_mask < 0.8:
                    input_ids[word] = self.tokenizer.mask_token_id
                elif r_rand < 0.5:
                    input_ids[word] = self.rng.integers(
                        0, self.tokenizer.vocab_size, size=len(word)
                    )
        return input_ids, labels

    def __call__(self, examples: Sequence[Dict]) -> Dict[str, np.ndarray]:
        masked = []
        for e in examples:
            ids, labels = self.mask_example(e["input_ids"], e["word_ids"])
            masked.append({"input_ids": ids, "label_ids": labels})
        side = self.tokenizer.padding_side
        width = max(len(e["input_ids"]) for e in masked)
        ids, pad_mask = _pad_rows(
            [e["input_ids"] for e in masked], width, self.tokenizer.pad_token_id or 0, side
        )
        labels, _ = _pad_rows([e["label_ids"] for e in masked], width, IGNORE_INDEX, side)
        return {"labels": labels, "input_ids": ids, "pad_mask": pad_mask}


class TokenMaskingCollator:
    """Per-token BERT masking (reference ``TokenMaskingCollator`` wrapping HF's
    ``DataCollatorForLanguageModeling``, ``collator.py:147-152``): each token
    independently selected with ``mask_prob``; of selected, 80% → [MASK],
    10% → random, 10% unchanged."""

    def __init__(self, tokenizer, mask_prob: float = 0.15, seed: Optional[int] = None):
        self.tokenizer = tokenizer
        self.mask_prob = mask_prob
        self.rng = np.random.default_rng(seed)

    def __call__(self, examples: Sequence[Dict]) -> Dict[str, np.ndarray]:
        side = self.tokenizer.padding_side
        width = max(len(e["input_ids"]) for e in examples)
        ids, pad_mask = _pad_rows(
            [e["input_ids"] for e in examples], width, self.tokenizer.pad_token_id or 0, side
        )
        labels = np.full_like(ids, IGNORE_INDEX)
        select = (self.rng.random(ids.shape) < self.mask_prob) & ~pad_mask
        labels[select] = ids[select]
        r = self.rng.random(ids.shape)
        ids = np.where(select & (r < 0.8), self.tokenizer.mask_token_id, ids)
        rand_ids = self.rng.integers(0, self.tokenizer.vocab_size, ids.shape)
        ids = np.where(select & (r >= 0.8) & (r < 0.9), rand_ids, ids).astype(np.int32)
        return {"labels": labels, "input_ids": ids, "pad_mask": pad_mask}


class RandomTruncateCollator:
    """Random tail truncation to length ≥ ``min_seq_len`` (reference
    ``RandomTruncateCollator``, ``collator.py:25-41``). TPU twist: instead of
    shrinking the batch width (which would retrace XLA per width), the dropped
    tail is *converted to padding* — input ids → pad, pad_mask → True,
    labels → ignore — so the model sees the truncated sequence while the
    batch shape stays static."""

    def __init__(self, collator, min_seq_len: int, seed: Optional[int] = None):
        self.collator = collator
        self.min_seq_len = min_seq_len
        self.rng = np.random.default_rng(seed)

    def __call__(self, examples: Sequence[Dict]) -> Dict[str, np.ndarray]:
        batch = self.collator(examples)
        seq_len = batch["input_ids"].shape[1]
        if seq_len <= self.min_seq_len:
            return batch
        drop = int(self.rng.integers(1, seq_len - self.min_seq_len + 1))
        pad_id = getattr(self.collator, "tokenizer").pad_token_id or 0
        batch["input_ids"][:, seq_len - drop :] = pad_id
        batch["pad_mask"][:, seq_len - drop :] = True
        if batch["labels"].ndim == 2:
            batch["labels"][:, seq_len - drop :] = IGNORE_INDEX
        return batch
