"""Streaming text pipeline — the reference's C4 path
(``perceiver/data/text/c4.py:20-164``) rebuilt host-side:

source iterator → per-host shard → window shuffle → tokenize → concatenate
with EOS separators → chunk to ``max_seq_len + 1`` → batch → shift-by-one.

Differences from the reference, all TPU-motivated:

- sharding uses ``(shard_index, shard_count)`` (wired to jax process info)
  instead of ``torch.distributed`` rank (``c4.py:56-79``);
- chunks are emitted at a **fixed** width; the ``min_seq_len`` randomization
  (``c4.py:100-104``) keeps static batch shapes by masking the tail to
  padding instead of emitting ragged chunks;
- batches are dict-of-NumPy ``{"labels", "input_ids", "pad_mask"}`` — the
  shift-by-one happens here, like ``C4Collator`` (``c4.py:156-164``).
"""
from __future__ import annotations

import random
import time
from typing import Callable, Dict, Iterable, Iterator, Optional

import numpy as np

from perceiver_io_tpu.data.loader import host_shard_info
from perceiver_io_tpu.data.text.collators import IGNORE_INDEX
from perceiver_io_tpu.data.text.tokenizers import load_tokenizer
from perceiver_io_tpu.reliability.retry import RetryPolicy, resilient_source


def shard_iterable(source: Iterable, shard_index: int, shard_count: int) -> Iterator:
    """Round-robin shard of a stream (what ``split_dataset_by_node`` does for
    non-sharded iterable datasets)."""
    for i, item in enumerate(source):
        if i % shard_count == shard_index:
            yield item


def window_shuffle(source: Iterable, window_size: int, seed: int) -> Iterator:
    """Buffered shuffle: maintain a ``window_size`` reservoir, emit a random
    element as each new one arrives (HF streaming ``dataset.shuffle``
    semantics, ``c4.py:78``)."""
    rng = random.Random(seed)
    buffer = []
    for item in source:
        if len(buffer) < window_size:
            buffer.append(item)
            continue
        j = rng.randrange(window_size)
        yield buffer[j]
        buffer[j] = item
    rng.shuffle(buffer)
    yield from buffer


class StreamingTextPipeline:
    """Token-stream chunker over any iterable of text records.

    :param source_fn: zero-arg callable returning a fresh text iterator
        (each epoch / retry re-invokes it).
    :param tokenizer: protocol tokenizer or name for :func:`load_tokenizer`.
    :param max_seq_len: chunk width is ``max_seq_len + 1`` (shift-by-one).
    :param min_seq_len: if set, each chunk keeps a random
        ``[min_seq_len, max_seq_len]`` prefix and pads the rest.
    :param shard_index/shard_count: this host's shard; default from jax.
    :param retry_policy: survive transient source failures (HTTP hiccups on
        hub streams) by re-opening the source with exponential backoff and
        fast-forwarding past the records already consumed
        (:func:`~perceiver_io_tpu.reliability.resilient_source`). None
        (default) fails fast like before.
    :param retry_sleep: backoff sleep hook (injectable for chaos tests).
    """

    def __init__(
        self,
        source_fn: Callable[[], Iterable[str]],
        tokenizer,
        max_seq_len: int,
        min_seq_len: Optional[int] = None,
        batch_size: int = 4,
        shuffle_window_size: int = 0,
        seed: int = 0,
        shard_index: Optional[int] = None,
        shard_count: Optional[int] = None,
        retry_policy: Optional[RetryPolicy] = None,
        retry_sleep: Callable[[float], None] = time.sleep,
    ):
        if isinstance(tokenizer, str):
            tokenizer = load_tokenizer(tokenizer)
        if shard_index is None or shard_count is None:
            auto_index, auto_count = host_shard_info()
            shard_index = auto_index if shard_index is None else shard_index
            shard_count = auto_count if shard_count is None else shard_count
        self.source_fn = source_fn
        self.tokenizer = tokenizer
        self.max_seq_len = max_seq_len
        self.min_seq_len = min_seq_len
        self.batch_size = batch_size
        self.shuffle_window_size = shuffle_window_size
        self.seed = seed
        self.shard_index = shard_index
        self.shard_count = shard_count
        self.retry_policy = retry_policy
        self.retry_sleep = retry_sleep

    def _chunks(self) -> Iterator[np.ndarray]:
        chunk_size = self.max_seq_len + 1
        if self.retry_policy is not None:
            # retry wraps the RAW source so a re-opened stream fast-forwards
            # in source order, before sharding/shuffling see any records
            source: Iterable = resilient_source(
                self.source_fn, self.retry_policy, sleep=self.retry_sleep
            )
        else:
            source = self.source_fn()
        source = shard_iterable(source, self.shard_index, self.shard_count)
        if self.shuffle_window_size:
            source = window_shuffle(source, self.shuffle_window_size, self.seed)
        eos = self.tokenizer.eos_token_id
        buf: list[int] = []
        for text in source:
            buf.extend(self.tokenizer.encode(text, add_special_tokens=False))
            if eos is not None:
                buf.append(eos)
            while len(buf) >= chunk_size:
                yield np.asarray(buf[:chunk_size], dtype=np.int32)
                del buf[:chunk_size]

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        rng = np.random.default_rng(self.seed)
        pad_id = self.tokenizer.pad_token_id or 0
        rows = []
        for chunk in self._chunks():
            rows.append(chunk)
            if len(rows) < self.batch_size:
                continue
            batch = np.stack(rows)
            rows = []
            ids = batch[:, :-1]
            labels = batch[:, 1:].astype(np.int32)
            pad_mask = np.zeros_like(ids, dtype=bool)
            if self.min_seq_len is not None:
                # static-shape version of the reference's random chunk length:
                # keep a random prefix per row, pad the tail.
                keep = rng.integers(self.min_seq_len, self.max_seq_len + 1, size=len(ids))
                cols = np.arange(ids.shape[1])[None, :]
                tail = cols >= keep[:, None]
                ids = np.where(tail, pad_id, ids)
                labels = np.where(tail, IGNORE_INDEX, labels)
                pad_mask = tail
            yield {
                "labels": labels,
                "input_ids": ids.astype(np.int32),
                "pad_mask": pad_mask,
            }


class C4DataModule:
    """C4-en streaming datamodule (reference ``C4DataModule``,
    ``c4.py:20-154``): streaming hub splits, window shuffle, per-host
    sharding, SentencePiece (or any HF) tokenizer."""

    def __init__(
        self,
        tokenizer: str = "google-t5/t5-small",
        max_seq_len: int = 1024,
        min_seq_len: Optional[int] = None,
        batch_size: int = 4,
        shuffle_window_seed: int = 0,
        shuffle_window_size: int = 10000,
        shard_index: Optional[int] = None,
        shard_count: Optional[int] = None,
        dataset_path: str = "allenai/c4",
        dataset_name: str = "en",
        source_max_retries: int = 3,
    ):
        self.tokenizer = load_tokenizer(tokenizer)
        self.max_seq_len = max_seq_len
        self.min_seq_len = min_seq_len
        self.batch_size = batch_size
        self.shuffle_window_seed = shuffle_window_seed
        self.shuffle_window_size = shuffle_window_size
        self.shard_index = shard_index
        self.shard_count = shard_count
        self.dataset_path = dataset_path
        self.dataset_name = dataset_name
        # hub streams fail transiently as a matter of course; retry them by
        # default (0 disables — fail fast)
        self.retry_policy = (
            RetryPolicy(max_retries=source_max_retries)
            if source_max_retries > 0
            else None
        )

    @property
    def vocab_size(self) -> int:
        return self.tokenizer.vocab_size

    def _hub_texts(self, split: str) -> Callable[[], Iterable[str]]:
        def source():
            from datasets import load_dataset

            ds = load_dataset(self.dataset_path, self.dataset_name, split=split, streaming=True)
            for record in ds:
                yield record["text"]

        return source

    def _pipeline(self, split: str, min_seq_len, shuffle: bool) -> StreamingTextPipeline:
        return StreamingTextPipeline(
            self._hub_texts(split),
            self.tokenizer,
            max_seq_len=self.max_seq_len,
            min_seq_len=min_seq_len,
            batch_size=self.batch_size,
            shuffle_window_size=self.shuffle_window_size if shuffle else 0,
            seed=self.shuffle_window_seed,
            shard_index=self.shard_index,
            shard_count=self.shard_count,
            retry_policy=self.retry_policy,
        )

    def train_dataloader(self) -> StreamingTextPipeline:
        return self._pipeline("train", self.min_seq_len, shuffle=True)

    def val_dataloader(self) -> StreamingTextPipeline:
        return self._pipeline("validation", None, shuffle=False)
