"""Vision data layer (host-side NumPy) — capability surface of the
reference's ``perceiver/data/vision/`` package (SURVEY.md §2.3): image
preprocessing + MNIST datamodule for classifier training, and the optical
flow patch/blend/render processor feeding the optical-flow pipeline.
"""
from perceiver_io_tpu.data.vision.image import (
    ImagePreprocessor,
    MNISTDataModule,
    SyntheticImageDataModule,
    random_crop_and_flip,
)
from perceiver_io_tpu.data.vision.imagenet import ImageNetPreprocessor, resize_bilinear
from perceiver_io_tpu.data.vision.optical_flow import (
    OpticalFlowProcessor,
    render_optical_flow,
)

__all__ = [
    "ImagePreprocessor",
    "ImageNetPreprocessor",
    "resize_bilinear",
    "MNISTDataModule",
    "SyntheticImageDataModule",
    "random_crop_and_flip",
    "OpticalFlowProcessor",
    "render_optical_flow",
]
