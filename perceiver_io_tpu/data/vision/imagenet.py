"""ImageNet preprocessing (reference ``perceiver/data/vision/imagenet.py``):
resize-shorter-side → center crop (eval) / random resized crop + flip
(train) → channels-last float normalization with ImageNet statistics.

Pure NumPy with area-mean resize — no torchvision/PIL dependency; inputs are
uint8 HWC arrays (any decoder can produce those).
"""
from __future__ import annotations

from typing import Tuple

import numpy as np

IMAGENET_MEAN = np.asarray([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.asarray([0.229, 0.224, 0.225], np.float32)


def resize_bilinear(img: np.ndarray, out_hw: Tuple[int, int]) -> np.ndarray:
    """(h, w, c) → (H, W, c) bilinear resize (align_corners=False)."""
    h, w = img.shape[:2]
    out_h, out_w = out_hw
    ys = (np.arange(out_h) + 0.5) * h / out_h - 0.5
    xs = (np.arange(out_w) + 0.5) * w / out_w - 0.5
    y0 = np.clip(np.floor(ys).astype(np.int64), 0, h - 1)
    x0 = np.clip(np.floor(xs).astype(np.int64), 0, w - 1)
    y1 = np.clip(y0 + 1, 0, h - 1)
    x1 = np.clip(x0 + 1, 0, w - 1)
    wy = np.clip(ys - y0, 0.0, 1.0)[:, None, None]
    wx = np.clip(xs - x0, 0.0, 1.0)[None, :, None]

    img = img.astype(np.float32)
    top = img[y0][:, x0] * (1 - wx) + img[y0][:, x1] * wx
    bot = img[y1][:, x0] * (1 - wx) + img[y1][:, x1] * wx
    return top * (1 - wy) + bot * wy


class ImageNetPreprocessor:
    """uint8 HWC image(s) → normalized (b, crop, crop, 3) float32.

    :param resize_to: shorter-side target before cropping.
    :param crop: output square size.
    """

    def __init__(self, resize_to: int = 256, crop: int = 224, *,
                 mean: np.ndarray = IMAGENET_MEAN, std: np.ndarray = IMAGENET_STD):
        self.resize_to = resize_to
        self.crop = crop
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)

    def _one(self, img: np.ndarray, rng: np.random.Generator = None) -> np.ndarray:
        img = np.asarray(img)
        if img.ndim == 2:
            img = np.stack([img] * 3, axis=-1)
        h, w = img.shape[:2]
        scale = self.resize_to / min(h, w)
        img = resize_bilinear(img, (round(h * scale), round(w * scale)))
        h, w = img.shape[:2]
        if rng is None:  # center crop
            y0 = (h - self.crop) // 2
            x0 = (w - self.crop) // 2
        else:  # random crop + horizontal flip
            y0 = int(rng.integers(0, h - self.crop + 1))
            x0 = int(rng.integers(0, w - self.crop + 1))
        img = img[y0 : y0 + self.crop, x0 : x0 + self.crop]
        if rng is not None and rng.random() < 0.5:
            img = img[:, ::-1]
        return img

    def __call__(self, images, *, rng: np.random.Generator = None) -> np.ndarray:
        if isinstance(images, np.ndarray) and images.ndim <= 3:
            images = [images]
        out = np.stack([self._one(im, rng) for im in images])
        out = out / 255.0
        return ((out - self.mean) / self.std).astype(np.float32)
