"""Image preprocessing + MNIST datamodule.

Capability parity with the reference's vision data package
(``perceiver/data/vision/mnist.py:17-96``, ``common.py``): channels-last
uint8 → normalized float32, optional train-time augmentation, and a
datamodule yielding ``{"image": (b, h, w, c) f32, "label": (b,) i32}``
batches — the input contract of
:class:`perceiver_io_tpu.models.vision.image_classifier.ImageClassifier`.

TPU-first notes: everything is NumPy on the host; batches have static shapes
(drop_last always) so the jitted train step compiles once. Normalization is
folded into the collator rather than a per-sample transform pipeline —
vectorized over the batch instead of Python-per-example as in torchvision
transforms.

Dataset sourcing: `load_arrays()` pulls MNIST from a local HF datasets cache
when available; `from_arrays(...)` injects arrays directly (tests, custom
datasets) — the reference's torchvision download path has no offline
equivalent.
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from perceiver_io_tpu.data.loader import DataLoader

# Reference normalization (perceiver/data/vision/mnist.py:28-31): mean/std of
# MNIST pixels in [0, 1].
MNIST_MEAN = 0.1307
MNIST_STD = 0.3081


def random_crop_and_flip(
    images: np.ndarray,
    rng: np.random.Generator,
    *,
    pad: int = 2,
    flip: bool = False,
) -> np.ndarray:
    """Batched random-shift crop (zero-pad by ``pad`` then crop back) and
    optional horizontal flip — the standard small-image augmentation
    (reference uses RandomCrop via torchvision, ``mnist.py:33-39``)."""
    b, h, w, c = images.shape
    padded = np.zeros((b, h + 2 * pad, w + 2 * pad, c), images.dtype)
    padded[:, pad : pad + h, pad : pad + w] = images
    ys = rng.integers(0, 2 * pad + 1, size=b)
    xs = rng.integers(0, 2 * pad + 1, size=b)
    out = np.empty_like(images)
    for idx in range(b):  # b is a host batch; cost is negligible vs the step
        out[idx] = padded[idx, ys[idx] : ys[idx] + h, xs[idx] : xs[idx] + w]
    if flip:
        do_flip = rng.random(b) < 0.5
        out[do_flip] = out[do_flip, :, ::-1]
    return out


class ImagePreprocessor:
    """uint8 channels-last image → normalized float32 model input
    (single-image inference entry, reference ``perceiver/data/vision/common.py``)."""

    def __init__(self, mean: float = MNIST_MEAN, std: float = MNIST_STD):
        self.mean = mean
        self.std = std

    def __call__(self, images: np.ndarray) -> np.ndarray:
        x = np.asarray(images)
        if x.ndim == 2:  # single grayscale image
            x = x[None, :, :, None]
        elif x.ndim == 3 and x.shape[-1] in (1, 3):  # single image
            x = x[None]
        elif x.ndim == 3:  # batch of grayscale
            x = x[..., None]
        x = x.astype(np.float32) / 255.0
        return (x - self.mean) / self.std


class _ImageDataset:
    def __init__(self, images: np.ndarray, labels: np.ndarray):
        assert len(images) == len(labels)
        self.images = images
        self.labels = labels

    def __len__(self) -> int:
        return len(self.images)

    def __getitem__(self, idx: int) -> Dict:
        return {"image": self.images[idx], "label": self.labels[idx]}


class MNISTDataModule:
    """MNIST datamodule: 28×28×1 channels-last, normalized, shuffled static
    batches (reference ``perceiver/data/vision/mnist.py:17-96``).

    :param augment: random-shift crop on the train split.
    """

    image_shape: Tuple[int, int, int] = (28, 28, 1)
    num_classes: int = 10

    def __init__(
        self,
        batch_size: int = 64,
        *,
        augment: bool = True,
        seed: int = 0,
        shard_index: Optional[int] = None,
        shard_count: Optional[int] = None,
    ):
        self.batch_size = batch_size
        self.augment = augment
        self.seed = seed
        self.shard_index = shard_index
        self.shard_count = shard_count
        self.preprocessor = ImagePreprocessor()
        self._splits: Dict[str, _ImageDataset] = {}

    # -- sourcing ----------------------------------------------------------
    @classmethod
    def from_arrays(
        cls,
        train: Tuple[np.ndarray, np.ndarray],
        valid: Tuple[np.ndarray, np.ndarray],
        test: Optional[Tuple[np.ndarray, np.ndarray]] = None,
        **kwargs,
    ) -> "MNISTDataModule":
        dm = cls(**kwargs)
        dm._splits = {
            "train": _ImageDataset(*train),
            "valid": _ImageDataset(*valid),
        }
        if test is not None:
            dm._splits["test"] = _ImageDataset(*test)
        return dm

    def load_arrays(self) -> None:
        """Load MNIST from the local HF datasets cache.

        MNIST publishes train + test only; following the reference, ``valid``
        is the official test set (reference ``mnist.py:60``), and the
        ``test`` split materializes that same official set for the CLI
        ``test`` subcommand — the split the reference's MNIST val_acc numbers
        are reported on."""
        import datasets

        ds = datasets.load_dataset("mnist")
        for split, name in (("train", "train"), ("valid", "test")):
            imgs = np.stack([np.asarray(im) for im in ds[name]["image"]])[..., None]
            labels = np.asarray(ds[name]["label"], np.int64)
            self._splits[split] = _ImageDataset(imgs, labels)
        self._splits["test"] = self._splits["valid"]  # same official set, one copy

    def prepare_data(self) -> None:
        """Source acquisition phase (the CLI calls this before ``setup``)."""
        if not self._splits:
            self.load_arrays()

    def setup(self) -> None:
        if not self._splits:
            self.load_arrays()

    # -- loaders -----------------------------------------------------------
    def _collate(self, train: bool):
        aug_rng = np.random.default_rng(self.seed + 1)

        def collate(examples):
            images = np.stack([e["image"] for e in examples]).astype(np.uint8)
            labels = np.asarray([e["label"] for e in examples], np.int32)
            if train and self.augment:
                images = random_crop_and_flip(images, aug_rng)
            return {"image": self.preprocessor(images), "label": labels}

        return collate

    def _loader(self, split: str, shuffle: bool) -> DataLoader:
        return DataLoader(
            self._splits[split],
            batch_size=self.batch_size,
            shuffle=shuffle,
            drop_last=True,
            collate_fn=self._collate(train=shuffle),
            seed=self.seed,
            shard_index=self.shard_index,
            shard_count=self.shard_count,
        )

    def train_dataloader(self) -> DataLoader:
        return self._loader("train", shuffle=True)

    def val_dataloader(self) -> DataLoader:
        return self._loader("valid", shuffle=False)

    def test_dataloader(self) -> DataLoader:
        if "test" not in self._splits:
            raise ValueError(
                f"{type(self).__name__} has no test split — from_arrays was "
                "called without test arrays"
            )
        return self._loader("test", shuffle=False)


class SyntheticImageDataModule(MNISTDataModule):
    """Deterministic synthetic images — offline smoke runs and config
    dry-runs (no reference counterpart; its MNIST module must download).
    The label places a bright patch on a 2×5 grid over a noise floor, so the
    10-way task is trivially learnable and accuracy visibly climbs."""

    def __init__(
        self,
        batch_size: int = 64,
        *,
        num_train: int = 512,
        num_valid: int = 128,
        num_test: int = 128,
        **kwargs,
    ):
        super().__init__(batch_size, **kwargs)
        self._sizes = {"train": num_train, "valid": num_valid, "test": num_test}

    def prepare_data(self) -> None:  # synthetic: nothing to acquire
        self.setup()

    def setup(self) -> None:
        if not self._splits:
            rng = np.random.default_rng(self.seed)
            h, w, c = self.image_shape

            def split(n):
                labels = rng.integers(0, self.num_classes, size=n)
                imgs = rng.integers(0, 48, size=(n, h, w, c), dtype=np.int64)
                rows, cols = labels // 5, labels % 5
                for i in range(n):
                    r0, c0 = 2 + int(rows[i]) * 14, 1 + int(cols[i]) * 5
                    imgs[i, r0 : r0 + 8, c0 : c0 + 4] = 220
                return imgs.astype(np.uint8), labels.astype(np.int64)

            self._splits = {
                name: _ImageDataset(*split(n))
                for name, n in self._sizes.items()
                if n > 0
            }
        super().setup()
