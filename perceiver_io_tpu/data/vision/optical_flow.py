"""Optical-flow pre/post-processing — patch grid, 3×3 pixel features,
weighted patch re-blending, HSV flow rendering.

Behavioral parity with the reference's ``OpticalFlowProcessor``
(``perceiver/data/vision/optical_flow.py:16-258``), re-implemented as
vectorized host-side NumPy (no torch/cv2 at runtime):

- **patch grid**: stride ``patch_size - min_overlap`` in each axis, last
  index clamped to ``dim - patch_size`` so patches tile the image with at
  least ``min_overlap`` pixels of overlap (grid scheme from the DeepMind
  optical-flow colab, cited at ``optical_flow.py:227``).
- **preprocess**: frames normalized ``x/255*2-1``; for every pixel its 3×3
  neighborhood (SAME zero padding) is stacked into channels in
  ``(ky, kx, c)`` order — 27 channels for RGB — matching torch
  ``unfold(2,3).unfold(3,3).permute(0,4,5,1,2,3)`` semantics
  (``optical_flow.py:103-117``). Output ``(P, 2, 27, ph, pw)`` per pair.
- **postprocess**: per-patch flow × ``flow_scale_factor``, blended with the
  pyramid weight ``min(x+1, W-x, y+1, H-y)`` and normalized by the summed
  weights (``optical_flow.py:185-204``).
- **render**: flow → HSV (hue = angle, saturation ∝ magnitude/24, value 255)
  → RGB, matching the cv2 rendering (``optical_flow.py:243-253``) without
  the cv2 dependency.

The model forward used by :meth:`process` is any callable
``(p, 2, 27, ph, pw) float32 -> (p, ph, pw, 2)`` — typically a jitted
``OpticalFlow.apply`` closure; micro-batching keeps the device shape static.
"""
from __future__ import annotations

import itertools
from typing import Callable, List, Sequence, Tuple

import numpy as np


class OpticalFlowProcessor:
    def __init__(
        self,
        patch_size: Tuple[int, int] = (368, 496),
        patch_min_overlap: int = 20,
        flow_scale_factor: int = 20,
    ):
        if patch_min_overlap >= patch_size[0] or patch_min_overlap >= patch_size[1]:
            raise ValueError(
                f"patch_min_overlap={patch_min_overlap} must be smaller than "
                f"patch_size={patch_size}"
            )
        self.patch_size = patch_size
        self.patch_min_overlap = patch_min_overlap
        self.flow_scale_factor = flow_scale_factor

    # -- grid --------------------------------------------------------------
    def grid_indices(self, image_shape: Tuple[int, ...]) -> List[Tuple[int, int]]:
        ph, pw = self.patch_size
        ys = list(range(0, image_shape[0], ph - self.patch_min_overlap))
        xs = list(range(0, image_shape[1], pw - self.patch_min_overlap))
        ys[-1] = image_shape[0] - ph
        xs[-1] = image_shape[1] - pw
        # The reference keeps duplicate indices when patches fit exactly
        # (clamping the last stride onto an earlier one) and runs the model
        # on identical patches twice; dedup is an intentional deviation —
        # pre/post always use this same grid, so blending is unaffected.
        ys = sorted(set(ys))
        xs = sorted(set(xs))
        return list(itertools.product(ys, xs))

    # -- preprocess --------------------------------------------------------
    @staticmethod
    def _pixel_features(img: np.ndarray) -> np.ndarray:
        """(c, h, w) normalized frame → (9c, h, w): each pixel's 3×3
        neighborhood stacked into channels in (ky, kx, c) order."""
        c, h, w = img.shape
        padded = np.zeros((c, h + 2, w + 2), img.dtype)
        padded[:, 1:-1, 1:-1] = img
        windows = np.lib.stride_tricks.sliding_window_view(padded, (3, 3), axis=(1, 2))
        # windows: (c, h, w, 3, 3) -> (ky, kx, c, h, w) -> (9c, h, w)
        return windows.transpose(3, 4, 0, 1, 2).reshape(9 * c, h, w)

    def preprocess(self, image_pair: Sequence[np.ndarray]) -> np.ndarray:
        """One frame pair (two (h, w, c) or (h, w) uint8/float arrays) →
        ``(num_patches, 2, 9c, ph, pw)`` float32 patch features."""
        img1, img2 = (np.asarray(im) for im in image_pair)
        if img1.shape != img2.shape:
            raise ValueError(f"frame shapes differ: {img1.shape} vs {img2.shape}")
        h, w = img1.shape[:2]
        ph, pw = self.patch_size
        if h < ph or w < pw:
            raise ValueError(f"image {img1.shape} smaller than patch {self.patch_size}")

        frames = []
        for img in (img1, img2):
            x = img.astype(np.float32) / 255.0 * 2.0 - 1.0
            if x.ndim == 2:
                x = x[None]
            else:
                x = x.transpose(2, 0, 1)  # channels-first
            frames.append(self._pixel_features(x))
        features = np.stack(frames)  # (2, 9c, h, w)

        patches = [
            features[..., y : y + ph, x : x + pw] for y, x in self.grid_indices((h, w))
        ]
        return np.stack(patches)

    def preprocess_batch(self, image_pairs: Sequence[Sequence[np.ndarray]]) -> np.ndarray:
        """Batch of pairs → ``(b, num_patches, 2, 9c, ph, pw)``."""
        shapes = {np.asarray(im).shape for pair in image_pairs for im in pair}
        if len(shapes) != 1:
            raise ValueError(f"all frames must share one shape, got {shapes}")
        return np.stack([self.preprocess(pair) for pair in image_pairs])

    # -- postprocess -------------------------------------------------------
    def _patch_weights(self) -> np.ndarray:
        ph, pw = self.patch_size
        wy = np.minimum(np.arange(ph) + 1, ph - np.arange(ph))[:, None]
        wx = np.minimum(np.arange(pw) + 1, pw - np.arange(pw))[None, :]
        return np.minimum(wy, wx).astype(np.float32)[..., None]  # (ph, pw, 1)

    def postprocess(self, predictions: np.ndarray, image_shape: Tuple[int, ...]) -> np.ndarray:
        """``(p, ph, pw, 2)`` or ``(b, p, ph, pw, 2)`` patch predictions →
        ``(b, height, width, 2)`` blended flow."""
        preds = np.asarray(predictions, np.float32)
        if preds.ndim == 4:
            preds = preds[None]
        h, w = image_shape[0], image_shape[1]
        grid = self.grid_indices(image_shape)
        b, p = preds.shape[:2]
        if p != len(grid):
            raise ValueError(f"got {p} patches, grid expects {len(grid)}")

        ph, pw = self.patch_size
        weights = self._patch_weights()
        flow = np.zeros((b, h, w, 2), np.float32)
        total = np.zeros((1, h, w, 1), np.float32)
        for patch_idx, (y, x) in enumerate(grid):
            flow[:, y : y + ph, x : x + pw] += (
                preds[:, patch_idx] * self.flow_scale_factor * weights
            )
            total[:, y : y + ph, x : x + pw] += weights
        return flow / total

    # -- end to end --------------------------------------------------------
    def process(
        self,
        model_fn: Callable[[np.ndarray], np.ndarray],
        image_pairs: Sequence[Sequence[np.ndarray]],
        batch_size: int = 1,
    ) -> np.ndarray:
        """preprocess → micro-batched ``model_fn`` → blend. ``model_fn`` maps
        ``(batch_size, 2, 9c, ph, pw)`` → ``(batch_size, ph, pw, 2)``; the
        final micro batch is zero-padded to keep the compiled shape static."""
        image_shape = np.asarray(image_pairs[0][0]).shape
        features = self.preprocess_batch(image_pairs)  # (b, p, 2, 9c, ph, pw)
        b, p = features.shape[:2]
        flat = features.reshape(b * p, *features.shape[2:])

        outputs = []
        for start in range(0, len(flat), batch_size):
            chunk = flat[start : start + batch_size]
            pad = batch_size - len(chunk)
            if pad:
                chunk = np.concatenate([chunk, np.zeros((pad, *chunk.shape[1:]), chunk.dtype)])
            out = np.asarray(model_fn(chunk))
            outputs.append(out[: batch_size - pad])
        preds = np.concatenate(outputs).reshape(b, p, *outputs[0].shape[1:])
        return self.postprocess(preds, image_shape)


def render_optical_flow(flow: np.ndarray) -> np.ndarray:
    """(h, w, 2) flow → (h, w, 3) uint8 RGB (hue = direction, saturation =
    magnitude), matching the reference's cv2 HSV rendering
    (``optical_flow.py:243-253``)."""
    fx, fy = flow[..., 0], flow[..., 1]
    mag = np.sqrt(fx * fx + fy * fy)
    ang = np.arctan2(fy, fx)  # cv2.cartToPolar: [0, 2pi)
    ang = np.where(ang < 0, ang + 2 * np.pi, ang)

    hue_deg = ang / np.pi / 2 * 180  # reference scales to [0, 180) (cv2 hue)
    sat = np.clip(mag * 255.0 / 24.0, 0, 255) / 255.0
    val = np.ones_like(sat)

    # HSV -> RGB with hue in cv2's [0, 180) half-degrees convention.
    h6 = (hue_deg * 2.0 / 60.0) % 6.0
    c = val * sat
    x = c * (1 - np.abs(h6 % 2 - 1))
    zeros = np.zeros_like(c)
    idx = h6.astype(np.int32) % 6
    r = np.select([idx == 0, idx == 1, idx == 2, idx == 3, idx == 4, idx == 5],
                  [c, x, zeros, zeros, x, c])
    g = np.select([idx == 0, idx == 1, idx == 2, idx == 3, idx == 4, idx == 5],
                  [x, c, c, x, zeros, zeros])
    b = np.select([idx == 0, idx == 1, idx == 2, idx == 3, idx == 4, idx == 5],
                  [zeros, zeros, x, c, c, x])
    m = val - c
    rgb = np.stack([r + m, g + m, b + m], axis=-1)
    return (rgb * 255.0).astype(np.uint8)
