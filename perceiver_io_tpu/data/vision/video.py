"""Video frame I/O (reference ``perceiver/data/vision/video_utils.py:8-46``,
which shells through cv2). cv2 is not in the TPU image, so reading prefers
cv2 when importable and otherwise falls back to ``ffmpeg`` subprocesses
(rawvideo pipes) — no hard native dependency either way.

Used by the optical-flow pipeline to process frame pairs from video files
and to write rendered flow back out.
"""
from __future__ import annotations

import json
import shutil
import subprocess
from pathlib import Path
from typing import Iterator, List, Sequence, Tuple

import numpy as np


def _have(binary: str) -> bool:
    return shutil.which(binary) is not None


def _probe(path: Path) -> Tuple[int, int, float]:
    out = subprocess.run(
        ["ffprobe", "-v", "error", "-select_streams", "v:0",
         "-show_entries", "stream=width,height,r_frame_rate", "-of", "json", str(path)],
        capture_output=True, check=True,
    )
    stream = json.loads(out.stdout)["streams"][0]
    num, den = stream["r_frame_rate"].split("/")
    return int(stream["width"]), int(stream["height"]), float(num) / float(den)


def read_video_frames(path, max_frames: int = None) -> List[np.ndarray]:
    """Decode a video into a list of RGB (h, w, 3) uint8 frames."""
    path = Path(path)
    try:
        import cv2

        cap = cv2.VideoCapture(str(path))
        frames = []
        while max_frames is None or len(frames) < max_frames:
            ok, frame = cap.read()
            if not ok:
                break
            frames.append(cv2.cvtColor(frame, cv2.COLOR_BGR2RGB))
        cap.release()
        return frames
    except ImportError:
        pass
    if not _have("ffmpeg"):
        raise RuntimeError("video IO needs cv2 or ffmpeg; neither is available")
    w, h, _ = _probe(path)
    cmd = ["ffmpeg", "-v", "error", "-i", str(path),
           "-f", "rawvideo", "-pix_fmt", "rgb24"]
    if max_frames is not None:
        cmd += ["-frames:v", str(max_frames)]
    raw = subprocess.run(cmd + ["-"], capture_output=True, check=True).stdout
    n = len(raw) // (w * h * 3)
    return list(np.frombuffer(raw, np.uint8)[: n * w * h * 3].reshape(n, h, w, 3))


def write_video(path, frames: Sequence[np.ndarray], fps: int = 30) -> None:
    """Encode RGB uint8 frames to a video file."""
    path = Path(path)
    frames = [np.asarray(f, np.uint8) for f in frames]
    if not frames:
        raise ValueError("no frames to write")
    h, w = frames[0].shape[:2]
    try:
        import cv2

        writer = cv2.VideoWriter(
            str(path), cv2.VideoWriter_fourcc(*"mp4v"), fps, (w, h)
        )
        for frame in frames:
            writer.write(cv2.cvtColor(frame, cv2.COLOR_RGB2BGR))
        writer.release()
        return
    except ImportError:
        pass
    if not _have("ffmpeg"):
        raise RuntimeError("video IO needs cv2 or ffmpeg; neither is available")
    proc = subprocess.Popen(
        ["ffmpeg", "-v", "error", "-y", "-f", "rawvideo", "-pix_fmt", "rgb24",
         "-s", f"{w}x{h}", "-r", str(fps), "-i", "-", "-pix_fmt", "yuv420p", str(path)],
        stdin=subprocess.PIPE,
    )
    for frame in frames:
        proc.stdin.write(frame.tobytes())
    proc.stdin.close()
    if proc.wait() != 0:
        raise RuntimeError("ffmpeg encode failed")


def frame_pairs(frames: Sequence[np.ndarray]) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Consecutive frame pairs for optical-flow processing."""
    for a, b in zip(frames[:-1], frames[1:]):
        yield (a, b)
