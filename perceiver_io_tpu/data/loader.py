"""Sharded, prefetching batch loader.

Replaces the reference's ``torch.utils.data.DataLoader`` usage (e.g.
``perceiver/data/text/common.py:206-234``) with a dependency-free loader that

- shards the index space across hosts (``jax.process_index()`` on pods),
- shuffles deterministically per epoch from a seed,
- collates map-style examples into dict-of-NumPy batches,
- prefetches batches on a background thread so host preprocessing overlaps
  with TPU step time (the reference relies on worker processes + pinned
  memory for the same effect).

Batches are dicts of NumPy arrays; ``parallel.shard_batch`` moves them onto
the mesh inside the trainer.
"""
from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Dict, Iterator, Optional, Sequence

import numpy as np

from perceiver_io_tpu.reliability.retry import RetryPolicy, call_with_retry


def host_shard_info() -> tuple[int, int]:
    """(shard_index, shard_count) for the current host — ``jax.process_index``
    / ``process_count`` when jax is initialised, else (0, 1)."""
    try:
        import jax

        return jax.process_index(), jax.process_count()
    except Exception:
        return 0, 1


def default_collate(examples: Sequence[Dict[str, Any]]) -> Dict[str, np.ndarray]:
    """Stack same-keyed example dicts into a batch dict."""
    out = {}
    for key in examples[0]:
        out[key] = np.stack([np.asarray(e[key]) for e in examples], axis=0)
    return out


class DataLoader:
    """Map-style loader: ``dataset[i] -> example dict``, collated into batches.

    :param dataset: anything with ``__len__`` and ``__getitem__``.
    :param batch_size: per-host batch size (global batch = batch_size ×
        shard_count when every host runs its own loader).
    :param shuffle: reshuffle the index space every epoch.
    :param seed: base RNG seed; epoch ``e`` uses ``seed + e`` so ordering is
        reproducible and differs between epochs.
    :param shard_index/shard_count: this host's slice of the index space.
        Defaults to :func:`host_shard_info`. Sharding happens *after* the
        epoch shuffle so every host sees a disjoint, epoch-varying subset.
    :param drop_last: drop the trailing partial batch (keeps shapes static —
        on TPU a partial batch would trigger a recompile; default True).
    :param collate_fn: ``examples -> batch dict``; default stacks arrays.
    :param prefetch: number of batches buffered on a background thread
        (0 disables threading).
    :param retry_policy: retry transient per-example fetch failures with
        exponential backoff (:class:`~perceiver_io_tpu.reliability.RetryPolicy`)
        instead of killing the run — for datasets backed by remote/flaky
        storage. None (default) fails fast like before.
    :param retry_sleep: backoff sleep hook (injectable for deterministic
        chaos tests).
    """

    def __init__(
        self,
        dataset,
        batch_size: int,
        shuffle: bool = False,
        seed: int = 0,
        shard_index: Optional[int] = None,
        shard_count: Optional[int] = None,
        drop_last: bool = True,
        collate_fn: Optional[Callable] = None,
        prefetch: int = 2,
        retry_policy: Optional[RetryPolicy] = None,
        retry_sleep: Callable[[float], None] = time.sleep,
    ):
        if shard_index is None or shard_count is None:
            auto_index, auto_count = host_shard_info()
            shard_index = auto_index if shard_index is None else shard_index
            shard_count = auto_count if shard_count is None else shard_count
        if not 0 <= shard_index < shard_count:
            raise ValueError(f"invalid shard {shard_index}/{shard_count}")
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.shard_index = shard_index
        self.shard_count = shard_count
        self.drop_last = drop_last
        self.collate_fn = collate_fn or default_collate
        self.prefetch = prefetch
        self.retry_policy = retry_policy
        self.retry_sleep = retry_sleep
        self._epoch = 0
        self._start_batch = 0

    def _fetch(self, i: int):
        if self.retry_policy is None:
            return self.dataset[i]
        return call_with_retry(
            lambda: self.dataset[i], self.retry_policy, sleep=self.retry_sleep
        )

    def set_epoch(self, epoch: int) -> None:
        self._epoch = epoch

    def skip_batches(self, n: int) -> None:
        """Advance the stream position by ``n`` batches in O(1) — the
        resume fast-forward hook (the trainer uses this instead of
        materializing and discarding ``n`` batches when available). The
        position lands exactly where a continuous iteration would be:
        ``n // len(self)`` epochs ahead, ``n % len(self)`` batches in."""
        per_epoch = len(self)
        if per_epoch == 0 or n <= 0:
            return
        self._epoch += n // per_epoch
        self._start_batch = n % per_epoch

    def _epoch_indices(self) -> np.ndarray:
        n = len(self.dataset)
        if self.shuffle:
            order = np.random.default_rng(self.seed + self._epoch).permutation(n)
        else:
            order = np.arange(n)
        return order[self.shard_index :: self.shard_count]

    def __len__(self) -> int:
        n = len(self._epoch_indices())
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def _batches(self) -> Iterator[Dict[str, np.ndarray]]:
        indices = self._epoch_indices()
        limit = len(indices)
        if self.drop_last:
            limit = (limit // self.batch_size) * self.batch_size
        first = self._start_batch * self.batch_size
        self._start_batch = 0
        for start in range(first, limit, self.batch_size):
            chunk = indices[start : start + self.batch_size]
            if not len(chunk):
                return
            yield self.collate_fn([self._fetch(int(i)) for i in chunk])
        self._epoch += 1  # auto-advance so re-iteration reshuffles

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        if self.prefetch <= 0:
            yield from self._batches()
            return
        q: queue.Queue = queue.Queue(maxsize=self.prefetch)
        sentinel = object()
        err: list = []

        def worker():
            try:
                for batch in self._batches():
                    q.put(batch)
            except BaseException as e:  # surface worker errors in the consumer
                err.append(e)
            finally:
                q.put(sentinel)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is sentinel:
                break
            yield item
        t.join()
        if err:
            raise err[0]
