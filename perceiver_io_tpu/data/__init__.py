"""Host-side data layer (NumPy until ``device_put``) — the capability surface
of the reference's ``perceiver/data/`` package (SURVEY.md §2.3), re-designed
for TPU input pipelines:

- **static shapes**: collators pad to a fixed ``max_seq_len`` so every batch
  compiles once (the reference pads to the batch max, which would retrace XLA).
- **per-host sharding**: loaders shard by ``(shard_index, shard_count)`` —
  wired to ``jax.process_index()/process_count()`` on pods — replacing the
  reference's ``torch.distributed`` rank-based sharding
  (``perceiver/data/text/c4.py:56-79``).
- **flat tensor storage**: preprocessed token chunks are stored as 2-D
  ``np.memmap``-able arrays instead of arrow datasets; a chunked dataset is
  literally one ``(num_chunks, chunk_size)`` int32 array.
"""
from perceiver_io_tpu.data.loader import DataLoader, host_shard_info

__all__ = ["DataLoader", "host_shard_info"]
