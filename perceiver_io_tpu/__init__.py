"""perceiver_io_tpu — a TPU-native (JAX/XLA/Pallas/pjit) framework with the
capabilities of the `perceiver-io` reference library (Perceiver, Perceiver IO,
Perceiver AR), re-designed TPU-first.

Layering (mirrors the reference's 5-layer stack, reference
``docs/library-design.md:1-9``, but idiomatic JAX):

- ``ops``       — functional numerics: attention, position encodings, masks,
                  Pallas kernels. Pure functions of arrays.
- ``models``    — flax modules: the core Perceiver runtime plus task backends
                  (text / vision / audio).
- ``parallel``  — mesh construction, partitioning rules (dp/fsdp/tp/sp),
                  jitted train-step factories, remat policies, ring attention.
- ``data``      — tokenizers, datamodules, collators (NumPy until device_put).
- ``training``  — trainer loop, optimizers/schedules, orbax checkpointing.
- ``inference`` — KV-cache decode loops, samplers, task pipelines.
- ``convert``   — weight import from the reference's torch checkpoints.
"""

__version__ = "0.1.0"
