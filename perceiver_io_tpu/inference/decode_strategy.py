"""Per-phase decode strategies: cached vs recompute, chosen by measurement.

The decode loop passes through three cache phases (``generate.py`` module
docstring): latent growth (cached step runs O(1) tokens of compute — a
measured ~6× win at every context length, docs/benchmarks.md), prefix
growth ("boundary" — the cache elides only the ``2·n·c²`` full-window
embedding + cross-k/v projections while the latent stack is recomputed
either way), and the sliding window (recompute is semantically forced by
the learned absolute position embedding). Round-5 measurements showed the
cached boundary step *losing* to full recompute on CPU (0.83–0.97× at
1k–8k ctx): whether the elision beats its own bookkeeping is a platform
and shape question — exactly the portable-caching tradeoff of the
compiler-first O(1)-caching paper (PAPERS.md) — so it should be a
*measured choice*, not a hardcoded one.

This module is that choice:

- :class:`DecodeStrategy` — the per-phase table ``{latent, boundary,
  window} -> {cached, recompute}``. Both boundary implementations are
  exact (the cached step's gather+attend is bitwise identical to the
  uncached forward), so greedy output is token-identical across every
  strategy — pinned by ``tests/test_decode_strategy.py``.
- :func:`resolve` — strategy resolution for ``generate()`` and the
  serving engines: explicit argument > ``PERCEIVER_DECODE_STRATEGY`` env
  var > ``"auto"`` (registry lookup, falling back to ``cached`` when
  nothing has been measured — the status-quo default).
- :func:`autotune_boundary` — the warmup-time autotuner: microbenchmark
  cached vs recompute boundary-phase decoding at the bound shape, pick
  the winner, memoize it in a process registry keyed by
  ``(shape, platform, modules.trace_env_fingerprint())``. With optional
  JSON persistence (``persist=`` / ``PERCEIVER_DECODE_STRATEGY_FILE``) a
  deployment measures once and every later process loads the verdict.
- ``python -m perceiver_io_tpu.inference.decode_strategy`` — the
  standalone probe behind ``make decode-tune``;
  ``examples/perf/decode_scaling.py`` emits the same JSON artifact.

The registry key deliberately excludes batch size (the cached-vs-recompute
tradeoff is a per-row FLOP balance; both sides scale with batch) so one
warmup measurement covers every micro-batch shape an engine dispatches.
"""
from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Callable, Optional, Union

MODES = ("auto", "cached", "recompute")
PHASE_CHOICES = ("cached", "recompute")

#: slot-engine cross-KV layouts (docs/serving.md "Block-paged KV"): the
#: dense-vs-paged choice is the SAME kind of measured platform/shape
#: property as cached-vs-recompute — the paged gather's bookkeeping
#: competes with the dense layout's footprint — so it lives in this
#: module's registry, resolved and autotuned the same way.
KV_LAYOUTS = ("auto", "dense", "paged", "paged_int8")
KV_LAYOUT_CHOICES = ("dense", "paged", "paged_int8")
#: the layouts that address KV through the block pool (``paged_int8`` is
#: ``paged`` plus int8 storage with per-(position, head) dequant scales,
#: docs/serving.md "Quantized KV") — everywhere the engine asks "is this
#: the paged machinery" it checks membership here, not ``== "paged"``
PAGED_KV_LAYOUTS = ("paged", "paged_int8")

#: slot-engine cross-request prefix-cache axis (docs/serving.md "Prefix
#: sharing"): whether paged admissions map hot prompt-prefix blocks by
#: reference instead of re-projecting them. Like the layouts it is a
#: deployment property (traffic skew decides whether the radix index pays
#: its bookkeeping), so it rides in the same persisted registry.
PREFIX_CACHE_MODES = ("auto", "on", "off")
PREFIX_CACHE_CHOICES = ("on", "off")

#: slot-engine self-draft speculative-decoding axis (docs/serving.md
#: "Speculative decoding"): ``k{K}d{D}`` proposes K tokens per round from a
#: D-layer truncated latent stack (full-model params, no second checkpoint)
#: and verifies all K+1 positions in one batched forward — greedy output
#: token-identical to the non-speculative step, so whether it PAYS is the
#: same measured platform/shape property as every other axis here:
#: acceptance rate × per-round cost vs K+1 plain steps. Draft depths past 2
#: approach full-model cost and stop being drafts, so the measured grid
#: stops there.
SPECULATION_CHOICES = ("off",) + tuple(
    f"k{k}d{d}" for d in (1, 2) for k in (2, 4, 8)
)
SPECULATION_MODES = ("auto",) + SPECULATION_CHOICES

#: env var overriding the boundary-phase strategy process-wide
ENV_VAR = "PERCEIVER_DECODE_STRATEGY"
#: env var overriding the slot engine's KV layout process-wide
ENV_KV_LAYOUT = "PERCEIVER_KV_LAYOUT"
#: env var overriding the slot engine's prefix-cache mode process-wide
ENV_PREFIX_CACHE = "PERCEIVER_PREFIX_CACHE"
#: env var overriding the slot engine's speculation mode process-wide
ENV_SPECULATION = "PERCEIVER_SPECULATION"
#: env var pointing at a persisted strategy-registry JSON file
ENV_FILE = "PERCEIVER_DECODE_STRATEGY_FILE"
#: env var overriding the int8 quality-gate budget (max greedy logit
#: delta vs the exact paged layout the autotuner will accept)
ENV_KV_QUANT_BUDGET = "PERCEIVER_KV_QUANT_BUDGET"
#: default quality-gate budget: max |logit delta| across every greedy
#: decode step of the probe workload. 0.05 is far below typical
#: top-1/top-2 logit gaps at the probe shapes yet generous to 8-bit
#: rounding noise; deployments tune it like any other strategy knob.
DEFAULT_KV_QUANT_BUDGET = 0.05


def kv_quant_budget() -> float:
    """The int8 quality-gate budget (:data:`ENV_KV_QUANT_BUDGET` >
    :data:`DEFAULT_KV_QUANT_BUDGET`; unparseable overrides fall back to
    the default, the registry-env-knob discipline)."""
    raw = os.environ.get(ENV_KV_QUANT_BUDGET)
    if raw:
        try:
            return float(raw)
        except ValueError:
            pass
    return DEFAULT_KV_QUANT_BUDGET


@dataclasses.dataclass(frozen=True)
class DecodeStrategy:
    """Per-phase cache strategy table. ``window`` is pinned to recompute —
    with the reference's learned absolute position embedding an incremental
    sliding-window step is semantically impossible, not merely slow
    (``generate.py`` module docstring). ``latent == "recompute"`` forces
    the boundary phase to recompute too: the boundary cache is built by
    the prefill/latent steps, so skipping them leaves it stale."""

    latent: str = "cached"
    boundary: str = "cached"
    window: str = "recompute"

    def __post_init__(self):
        for phase in ("latent", "boundary"):
            value = getattr(self, phase)
            if value not in PHASE_CHOICES:
                raise ValueError(
                    f"{phase} strategy must be one of {PHASE_CHOICES}, got {value!r}"
                )
        if self.window != "recompute":
            raise ValueError(
                "window strategy is pinned to 'recompute': the learned "
                "absolute position embedding re-positions every surviving "
                "token each step, so no exact incremental form exists"
            )

    @property
    def boundary_cached(self) -> bool:
        return self.latent == "cached" and self.boundary == "cached"


#: (shape_key, platform, trace_env_fingerprint) -> measurement entry dict
_REGISTRY: dict = {}
#: same key space -> {"kv_layout": "dense"|"paged", ...} measurement entry
#: (separate dict so a boundary-only artifact and a kv-only artifact can
#: merge without clobbering each other)
_KV_REGISTRY: dict = {}
#: same key space -> {"prefix_cache": "on"|"off", ...} measurement entry
_PREFIX_REGISTRY: dict = {}
#: same key space -> {"speculation": "off"|"k{K}d{D}", ...} measurement entry
_SPEC_REGISTRY: dict = {}
#: platform -> {"swap_gbps": float, ...} calibrated host-link rate for the
#: swap-preemption cost model (docs/serving.md "Host-swap preemption").
#: Keyed by PLATFORM ALONE: the device<->host link is a hardware property,
#: not a model-shape or trace-env one — one measured rate serves every
#: engine on the box.
_SWAP_REGISTRY: dict = {}
_FILE_LOADED: set = set()  # paths already merged into the registries


def shape_key(model) -> tuple:
    """The architecture coordinates the boundary tradeoff depends on —
    window size (the elided ``2·n·c²`` work), latent count and stack depth
    (the recomputed-in-both-paths work), and width/heads."""
    cfg = model.config
    return (
        int(cfg.max_seq_len),
        int(cfg.max_latents),
        int(cfg.num_channels),
        int(cfg.num_heads),
        int(cfg.num_self_attention_layers),
    )


def registry_key(model, platform: Optional[str] = None) -> tuple:
    from perceiver_io_tpu.models.core.modules import trace_env_fingerprint

    if platform is None:
        import jax

        platform = jax.default_backend()
    return (shape_key(model), str(platform), trace_env_fingerprint())


def _maybe_load_env_file() -> None:
    path = os.environ.get(ENV_FILE)
    if path and path not in _FILE_LOADED and os.path.exists(path):
        load_registry(path)


def lookup(model, platform: Optional[str] = None) -> Optional[str]:
    """Measured boundary winner for this shape/platform/env, or None."""
    _maybe_load_env_file()
    entry = _REGISTRY.get(registry_key(model, platform))
    return None if entry is None else entry["boundary"]


def record(model, boundary: str, *, platform: Optional[str] = None,
           **extra) -> dict:
    """Store a boundary verdict (plus measurement metadata) for this
    shape/platform/env; returns the entry. Used by the autotuner and by
    ``examples/perf/decode_scaling.py`` so the scaling study feeds the same
    registry the serving warmup reads."""
    if boundary not in PHASE_CHOICES:
        raise ValueError(f"boundary must be one of {PHASE_CHOICES}, got {boundary!r}")
    entry = {"boundary": boundary, **extra}
    _REGISTRY[registry_key(model, platform)] = entry
    return entry


def lookup_kv_layout(model, platform: Optional[str] = None) -> Optional[str]:
    """Measured KV-layout winner for this shape/platform/env, or None."""
    _maybe_load_env_file()
    entry = _KV_REGISTRY.get(registry_key(model, platform))
    return None if entry is None else entry["kv_layout"]


def kv_entry(model, platform: Optional[str] = None) -> Optional[dict]:
    """The full KV-layout registry entry (verdict + measurement metadata,
    including the ``quant_gate`` dict the autotuner records), or None.
    Read-only view for observability (the engine's warmup reports the
    quality-gate outcome through ``kv_quant_fallback_total``)."""
    _maybe_load_env_file()
    entry = _KV_REGISTRY.get(registry_key(model, platform))
    return None if entry is None else dict(entry)


def record_kv_layout(model, kv_layout: str, *, platform: Optional[str] = None,
                     **extra) -> dict:
    """Store a KV-layout verdict (plus measurement metadata) for this
    shape/platform/env; returns the entry."""
    if kv_layout not in KV_LAYOUT_CHOICES:
        raise ValueError(
            f"kv_layout must be one of {KV_LAYOUT_CHOICES}, got {kv_layout!r}"
        )
    entry = {"kv_layout": kv_layout, **extra}
    _KV_REGISTRY[registry_key(model, platform)] = entry
    return entry


def lookup_prefix_cache(model, platform: Optional[str] = None) -> Optional[str]:
    """Recorded prefix-cache verdict for this shape/platform/env, or None."""
    _maybe_load_env_file()
    entry = _PREFIX_REGISTRY.get(registry_key(model, platform))
    return None if entry is None else entry["prefix_cache"]


def record_prefix_cache(model, prefix_cache: str, *,
                        platform: Optional[str] = None, **extra) -> dict:
    """Store a prefix-cache verdict (plus metadata — e.g. the measured hit
    ratio a deployment observed) for this shape/platform/env."""
    if prefix_cache not in PREFIX_CACHE_CHOICES:
        raise ValueError(
            f"prefix_cache must be one of {PREFIX_CACHE_CHOICES}, "
            f"got {prefix_cache!r}"
        )
    entry = {"prefix_cache": prefix_cache, **extra}
    _PREFIX_REGISTRY[registry_key(model, platform)] = entry
    return entry


def resolve_prefix_cache(
    mode: Optional[str],
    model=None,
    *,
    platform: Optional[str] = None,
) -> str:
    """Resolve a slot-engine prefix-cache request into ``"on"`` or
    ``"off"`` (docs/serving.md "Prefix sharing").

    Order mirrors :func:`resolve_kv_layout`: explicit mode >
    :data:`ENV_PREFIX_CACHE` > ``"auto"`` (registry lookup, falling back
    to ``"off"`` — the status-quo unshared path — when nothing has been
    recorded). Sharing only exists under ``kv_layout="paged"``; the
    engine enforces that pairing, not this resolver.
    """
    if mode is None:
        mode = os.environ.get(ENV_PREFIX_CACHE) or "auto"
    if mode not in PREFIX_CACHE_MODES:
        raise ValueError(
            f"prefix cache must be one of {PREFIX_CACHE_MODES}, got {mode!r}"
        )
    if mode == "auto":
        measured = (
            lookup_prefix_cache(model, platform) if model is not None else None
        )
        return measured or "off"
    return mode


def lookup_speculation(model, platform: Optional[str] = None) -> Optional[str]:
    """Measured speculation winner for this shape/platform/env, or None."""
    _maybe_load_env_file()
    entry = _SPEC_REGISTRY.get(registry_key(model, platform))
    return None if entry is None else entry["speculation"]


def spec_entry(model, platform: Optional[str] = None) -> Optional[dict]:
    """The full speculation registry entry (verdict + measurement metadata,
    including the acceptance rate the autotuner observed), or None.
    Read-only view for observability and the perf examples."""
    _maybe_load_env_file()
    entry = _SPEC_REGISTRY.get(registry_key(model, platform))
    return None if entry is None else dict(entry)


def record_speculation(model, speculation: str, *,
                       platform: Optional[str] = None, **extra) -> dict:
    """Store a speculation verdict (plus measurement metadata — acceptance
    rate, per-token timings) for this shape/platform/env."""
    if speculation not in SPECULATION_CHOICES:
        raise ValueError(
            f"speculation must be one of {SPECULATION_CHOICES}, "
            f"got {speculation!r}"
        )
    entry = {"speculation": speculation, **extra}
    _SPEC_REGISTRY[registry_key(model, platform)] = entry
    return entry


def resolve_speculation(
    mode: Optional[str],
    model=None,
    *,
    platform: Optional[str] = None,
) -> str:
    """Resolve a slot-engine speculation request into one of
    :data:`SPECULATION_CHOICES` (docs/serving.md "Speculative decoding").

    Order mirrors :func:`resolve_kv_layout`: explicit mode >
    :data:`ENV_SPECULATION` > ``"auto"`` (registry lookup, falling back to
    ``"off"`` — the status-quo one-token step — when nothing has been
    measured). Speculation is greedy-only; the engine enforces that
    pairing, not this resolver.
    """
    if mode is None:
        mode = os.environ.get(ENV_SPECULATION) or "auto"
    if mode not in SPECULATION_MODES:
        raise ValueError(
            f"speculation must be one of {SPECULATION_MODES}, got {mode!r}"
        )
    if mode == "auto":
        measured = (
            lookup_speculation(model, platform) if model is not None else None
        )
        return measured or "off"
    return mode


def lookup_swap_gbps(platform: Optional[str] = None) -> Optional[float]:
    """Calibrated host-link rate (decimal GB/s) for this platform, or
    None when no swap has ever been measured here — the slot engine's
    ``swap_link_gbps=None`` resolution falls back to its prior then."""
    _maybe_load_env_file()
    if platform is None:
        import jax

        platform = jax.default_backend()
    entry = _SWAP_REGISTRY.get(str(platform))
    return None if entry is None else float(entry["swap_gbps"])


def swap_entry(platform: Optional[str] = None) -> Optional[dict]:
    """The full calibrated-swap registry entry (rate + measurement
    metadata), or None. Read-only view for observability and the bench
    probes."""
    _maybe_load_env_file()
    if platform is None:
        import jax

        platform = jax.default_backend()
    entry = _SWAP_REGISTRY.get(str(platform))
    return None if entry is None else dict(entry)


def record_swap_gbps(gbps: float, *, platform: Optional[str] = None,
                     **extra) -> dict:
    """Store a measured host-link rate for this platform (plus
    measurement metadata — bytes moved, transfer wall time); returns the
    entry. The slot engine calls this after every real swap transfer, so
    the persisted artifact carries a calibrated rate forward to the next
    process (``swap_entries``, beside ``spec_entries``)."""
    gbps = float(gbps)
    if not gbps > 0:
        raise ValueError(f"swap_gbps must be > 0, got {gbps!r}")
    if platform is None:
        import jax

        platform = jax.default_backend()
    entry = {"swap_gbps": gbps, **extra}
    _SWAP_REGISTRY[str(platform)] = entry
    return entry


def reset_registry() -> None:
    """Test isolation: drop every memoized verdict and forget loaded files."""
    _REGISTRY.clear()
    _KV_REGISTRY.clear()
    _PREFIX_REGISTRY.clear()
    _SPEC_REGISTRY.clear()
    _SWAP_REGISTRY.clear()
    _FILE_LOADED.clear()


def _key_to_json(key: tuple) -> dict:
    shape, platform, env = key
    return {"shape": list(shape), "platform": platform, "env": repr(env)}


def _key_from_json(obj: dict) -> tuple:
    # env fingerprints are tuples of primitives; repr round-trips via eval-free
    # literal parsing
    import ast

    return (tuple(obj["shape"]), obj["platform"], ast.literal_eval(obj["env"]))


def save_registry(path: str) -> None:
    """Persist every memoized verdict as the deployment JSON artifact
    (atomic write; ``load_registry`` and ``PERCEIVER_DECODE_STRATEGY_FILE``
    consume it)."""
    entries = [
        {"key": _key_to_json(key), **entry} for key, entry in sorted(
            _REGISTRY.items(), key=lambda kv: repr(kv[0])
        )
    ]
    kv_entries = [
        {"key": _key_to_json(key), **entry} for key, entry in sorted(
            _KV_REGISTRY.items(), key=lambda kv: repr(kv[0])
        )
    ]
    prefix_entries = [
        {"key": _key_to_json(key), **entry} for key, entry in sorted(
            _PREFIX_REGISTRY.items(), key=lambda kv: repr(kv[0])
        )
    ]
    spec_entries = [
        {"key": _key_to_json(key), **entry} for key, entry in sorted(
            _SPEC_REGISTRY.items(), key=lambda kv: repr(kv[0])
        )
    ]
    # platform-keyed (not shape/env-keyed): the host link is hardware
    swap_entries = [
        {"platform": platform, **entry}
        for platform, entry in sorted(_SWAP_REGISTRY.items())
    ]
    tmp = path + ".tmp"
    dirpath = os.path.dirname(path)
    if dirpath:
        os.makedirs(dirpath, exist_ok=True)
    with open(tmp, "w") as fh:
        # version stays 1: kv_entries / prefix_entries / spec_entries /
        # swap_entries are additive and readers written before them simply
        # ignore the keys
        json.dump(
            {"version": 1, "entries": entries, "kv_entries": kv_entries,
             "prefix_entries": prefix_entries, "spec_entries": spec_entries,
             "swap_entries": swap_entries},
            fh, indent=2,
        )
    os.replace(tmp, path)


def load_registry(path: str) -> int:
    """Merge a persisted artifact into the process registry; returns the
    number of entries loaded. Unparseable files load zero entries rather
    than raising (a corrupt cache must degrade to re-measurement, not take
    serving down)."""
    _FILE_LOADED.add(path)
    try:
        with open(path) as fh:
            data = json.load(fh)
    except (OSError, ValueError):
        return 0
    if not isinstance(data, dict):
        return 0
    loaded = 0
    for field, dest, value_key, choices in (
        ("entries", _REGISTRY, "boundary", PHASE_CHOICES),
        ("kv_entries", _KV_REGISTRY, "kv_layout", KV_LAYOUT_CHOICES),
        ("prefix_entries", _PREFIX_REGISTRY, "prefix_cache", PREFIX_CACHE_CHOICES),
        ("spec_entries", _SPEC_REGISTRY, "speculation", SPECULATION_CHOICES),
    ):
        entries = data.get(field)
        if not isinstance(entries, list):
            continue
        for item in entries:
            if not isinstance(item, dict):
                continue
            try:
                key = _key_from_json(item["key"])
                entry = {k: v for k, v in item.items() if k != "key"}
                if entry.get(value_key) not in choices:
                    continue
            except (KeyError, ValueError, SyntaxError, TypeError):
                continue
            dest[key] = entry
            loaded += 1
    swap_items = data.get("swap_entries")
    if isinstance(swap_items, list):
        for item in swap_items:
            if not isinstance(item, dict):
                continue
            platform = item.get("platform")
            gbps = item.get("swap_gbps")
            if not isinstance(platform, str) or \
                    not isinstance(gbps, (int, float)) or not gbps > 0:
                continue
            _SWAP_REGISTRY[platform] = {
                k: v for k, v in item.items() if k != "platform"
            }
            loaded += 1
    return loaded


def resolve(
    mode: Union[None, str, DecodeStrategy],
    model=None,
    *,
    platform: Optional[str] = None,
) -> DecodeStrategy:
    """Resolve a strategy request into a concrete :class:`DecodeStrategy`.

    Order: an explicit :class:`DecodeStrategy` wins; an explicit mode
    string next; then :data:`ENV_VAR`; then ``"auto"``. ``"auto"`` means
    "use the measured winner for this shape/platform/env when one exists,
    else keep the cached default" — so an untuned process behaves exactly
    like the pre-strategy code.
    """
    if isinstance(mode, DecodeStrategy):
        return mode
    if mode is None:
        mode = os.environ.get(ENV_VAR) or "auto"
    if mode not in MODES:
        raise ValueError(
            f"decode strategy must be one of {MODES} (or a DecodeStrategy), "
            f"got {mode!r}"
        )
    if mode == "auto":
        measured = lookup(model, platform) if model is not None else None
        return DecodeStrategy(boundary=measured or "cached")
    return DecodeStrategy(boundary=mode)


#: package-level export name (``resolve`` is ambiguous outside this module)
resolve_decode_strategy = resolve


def autotune_boundary(
    model,
    params,
    *,
    batch: int = 1,
    new_tokens: int = 4,
    clock: Callable[[], float] = time.perf_counter,
    persist: Optional[str] = None,
    force: bool = False,
) -> str:
    """Measure cached vs recompute boundary-phase decoding at the bound
    shape and memoize the winner; returns ``"cached"`` or ``"recompute"``.

    The probe pins every generated token into the boundary phase (latents
    start maxed, the prompt fills the window minus ``new_tokens`` — the
    ``decode_scaling.py`` recipe), runs each implementation once to compile
    and once timed on ``clock``, and records both per-token times. Ties
    (including the all-zero durations an un-advanced
    :class:`~perceiver_io_tpu.reliability.FakeClock` produces) break toward
    ``cached`` — deterministically, so chaos-clock tests replay. A shape
    whose window equals its latent count has no boundary phase at all; the
    verdict is recorded as ``cached`` without measuring.

    :param persist: JSON path — merged before deciding (a persisted verdict
        short-circuits the measurement unless ``force``) and rewritten
        after, so one deployment measures once.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from perceiver_io_tpu.inference.generate import GenerationConfig, generate

    if persist:
        load_registry(persist)
    _maybe_load_env_file()
    key = registry_key(model)
    if not force and key in _REGISTRY:
        return _REGISTRY[key]["boundary"]

    n = model.max_seq_len
    max_latents = model.max_latents
    boundary_room = n - max_latents  # == max_prefix_len for this family
    if boundary_room < 1:
        record(model, "cached", note="no boundary phase at this shape")
        if persist:
            save_registry(persist)
        return "cached"
    new_tokens = max(1, min(new_tokens, boundary_room))
    prompt_len = n - new_tokens
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(
        rng.integers(1, model.config.vocab_size, size=(batch, prompt_len),
                     dtype=np.int32)
    )
    # latents start maxed: every generated token migrates the boundary
    gcfg = GenerationConfig(max_new_tokens=new_tokens, num_latents=max_latents)

    timings = {}
    for mode in PHASE_CHOICES:
        ids = generate(model, params, prompt, gcfg, decode_strategy=mode)
        int(np.asarray(jax.device_get(ids))[0, -1])  # compile + fence
        t0 = clock()
        ids = generate(model, params, prompt, gcfg, decode_strategy=mode)
        int(np.asarray(jax.device_get(ids))[0, -1])
        timings[mode] = (clock() - t0) / new_tokens * 1e3
    winner = "cached" if timings["cached"] <= timings["recompute"] else "recompute"
    record(
        model, winner,
        cached_ms_per_token=round(timings["cached"], 4),
        recompute_ms_per_token=round(timings["recompute"], 4),
        batch=batch, new_tokens=new_tokens,
    )
    if persist:
        save_registry(persist)
    return winner


def resolve_kv_layout(
    mode: Optional[str],
    model=None,
    *,
    platform: Optional[str] = None,
) -> str:
    """Resolve a slot-engine KV-layout request into one of
    :data:`KV_LAYOUT_CHOICES` (``"dense"``, ``"paged"``, ``"paged_int8"``).

    Order mirrors :func:`resolve`: explicit mode > :data:`ENV_KV_LAYOUT` >
    ``"auto"`` (registry lookup, falling back to ``dense`` — the
    status-quo layout — when nothing has been measured). ``paged_int8``
    only wins a lookup when the autotuner's quality gate passed at record
    time (:func:`autotune_kv_layout`); an explicit request is taken at
    face value — the operator owns the quality tradeoff then.
    """
    if mode is None:
        mode = os.environ.get(ENV_KV_LAYOUT) or "auto"
    if mode not in KV_LAYOUTS:
        raise ValueError(
            f"kv layout must be one of {KV_LAYOUTS}, got {mode!r}"
        )
    if mode == "auto":
        measured = lookup_kv_layout(model, platform) if model is not None else None
        return measured or "dense"
    return mode


def _kv_probe_workload(model, slots: int, new_tokens: int):
    """The shared KV-probe geometry (autotune + quality gate): mid-context
    prompts — the paged gather's cost scales with the context, so probing
    at a trivial length would flatter the paged arm — and an EOS-free
    greedy config, so retirement is purely by count and every arm runs
    the identical schedule regardless of token divergence."""
    import numpy as np

    from perceiver_io_tpu.inference.generate import GenerationConfig
    from perceiver_io_tpu.serving import BucketTable

    n = model.max_seq_len
    num_latents = min(2, model.max_latents)
    prompt_len = max(num_latents, min(n // 2, model.max_prefix_len + num_latents))
    new_tokens = max(1, min(new_tokens, n - prompt_len))
    table = BucketTable(prompt_lens=(prompt_len,), batch_sizes=(1,))
    gcfg = GenerationConfig(max_new_tokens=new_tokens, num_latents=num_latents)
    rng = np.random.default_rng(0)
    prompts = [
        rng.integers(1, model.config.vocab_size, size=prompt_len, dtype=np.int32)
        for _ in range(slots)
    ]
    return table, gcfg, prompts, new_tokens


def quant_quality_probe(
    model,
    params,
    *,
    slots: int = 2,
    block_size: int = 16,
    new_tokens: int = 8,
    budget: Optional[float] = None,
) -> dict:
    """Measure the int8 layout's greedy fidelity against the exact paged
    layout at the bound shape — the *quality gate* the autotuner applies
    before it will select ``paged_int8``.

    Drives one exact-paged and one int8-paged engine in LOCKSTEP over the
    shared probe workload (EOS-free, so both schedules are identical by
    construction) and after every step compares the per-slot logits of
    slots active in BOTH engines (idle-slot logits are garbage and
    excluded). Returns::

        {"max_logit_delta": float,   # worst |exact - int8| logit, any step
         "token_match_rate": float,  # greedy tokens identical across arms
         "budget": float,            # the gate threshold applied
         "passed": bool}             # max_logit_delta <= budget

    The verdict rides in the registry entry (``quant_gate``) so serving
    warmup can report a failed gate through ``kv_quant_fallback_total``.
    """
    import numpy as np

    from perceiver_io_tpu.serving.slots import SlotServingEngine

    budget = kv_quant_budget() if budget is None else float(budget)
    table, gcfg, prompts, _ = _kv_probe_workload(model, slots, new_tokens)

    engines, reqs = {}, {}
    for layout in PAGED_KV_LAYOUTS:
        eng = SlotServingEngine(
            model, params, gcfg, table, slots=slots, kv_layout=layout,
            kv_block_size=block_size,
        )
        engines[layout] = eng
        reqs[layout] = [eng.submit(p) for p in prompts]
    exact, quant = engines["paged"], engines["paged_int8"]
    max_delta = 0.0
    while exact.pending() or quant.pending():
        if exact.pending():
            exact.step()
        if quant.pending():
            quant.step()
        live = [
            i for i, (se, sq) in enumerate(zip(exact._slots, quant._slots))
            if se is not None and sq is not None
        ]
        if live:
            le = np.asarray(exact._state["logits"])[live]
            lq = np.asarray(quant._state["logits"])[live]
            max_delta = max(max_delta, float(np.max(np.abs(le - lq))))
    matched = total = 0
    for r_exact, r_quant in zip(reqs["paged"], reqs["paged_int8"]):
        te, tq = list(r_exact.result), list(r_quant.result)
        total += max(len(te), len(tq))
        matched += sum(1 for a, b in zip(te, tq) if a == b)
    return {
        "max_logit_delta": round(max_delta, 6),
        "token_match_rate": round(matched / max(total, 1), 4),
        "budget": budget,
        "passed": bool(max_delta <= budget),
    }


def autotune_kv_layout(
    model,
    params,
    *,
    slots: int = 2,
    block_size: int = 16,
    new_tokens: int = 8,
    clock: Callable[[], float] = time.perf_counter,
    persist: Optional[str] = None,
    force: bool = False,
) -> str:
    """Measure dense vs block-paged vs int8-paged slot decoding at the
    bound shape and memoize the winner; returns one of
    :data:`KV_LAYOUT_CHOICES`.

    The probe drives a tiny :class:`~perceiver_io_tpu.serving.slots.
    SlotServingEngine` per layout (same prompts, same schedule, greedy):
    one pass to compile, one timed pass, per-token ms on ``clock``. Ties —
    including the all-zero durations an un-advanced FakeClock produces —
    break toward ``dense`` (the status-quo layout), deterministically,
    and toward exact ``paged`` over ``paged_int8``. The int8 arm is
    additionally **quality-gated**: :func:`quant_quality_probe` must
    measure a greedy logit delta within :func:`kv_quant_budget`, else the
    autotuner falls back to exact ``paged`` no matter the timing (the
    gate verdict is recorded either way, as ``quant_gate``).
    Note the tradeoff being measured is TIME at equal capacity; the paged
    layouts' admission win (more residents per HBM byte — ~4x more again
    for int8) is a capacity property the ``extras.paged_kv`` /
    ``extras.quant_kv`` benches measure separately — an operator who
    sizes ``kv_blocks`` below dense capacity has already chosen paged and
    should pass it explicitly.

    :param persist: JSON path — merged before deciding (a persisted verdict
        short-circuits the measurement unless ``force``) and rewritten
        after, sharing the boundary registry's artifact file.
    """
    import jax
    import numpy as np

    from perceiver_io_tpu.serving.slots import SlotServingEngine

    if persist:
        load_registry(persist)
    _maybe_load_env_file()
    key = registry_key(model)
    if not force and key in _KV_REGISTRY:
        return _KV_REGISTRY[key]["kv_layout"]

    table, gcfg, prompts, new_tokens = _kv_probe_workload(model, slots, new_tokens)

    timings = {}
    for layout in KV_LAYOUT_CHOICES:
        # explicit pool sizing implies a paged layout (the engine rejects
        # sizing a dense pool), so only those arms get block_size
        kv_kwargs = (
            {"kv_block_size": block_size} if layout in PAGED_KV_LAYOUTS else {}
        )

        def make():
            return SlotServingEngine(
                model, params, gcfg, table, slots=slots, kv_layout=layout,
                **kv_kwargs,
            )

        compile_engine = make()
        compile_engine.serve(prompts)  # pays the per-layout executor builds
        engine = make()
        for p in prompts:
            engine.submit(p)
        t0 = clock()
        engine.run_until_idle()
        timings[layout] = (clock() - t0) / (slots * new_tokens) * 1e3
    quality = quant_quality_probe(
        model, params, slots=slots, block_size=block_size,
        new_tokens=new_tokens,
    )
    winner = "dense" if timings["dense"] <= timings["paged"] else "paged"
    if (
        winner == "paged"
        and quality["passed"]
        and timings["paged_int8"] < timings["paged"]
    ):
        winner = "paged_int8"
    record_kv_layout(
        model, winner,
        dense_ms_per_token=round(timings["dense"], 4),
        paged_ms_per_token=round(timings["paged"], 4),
        paged_int8_ms_per_token=round(timings["paged_int8"], 4),
        quant_gate=quality,
        slots=slots, block_size=block_size, new_tokens=new_tokens,
    )
    if persist:
        save_registry(persist)
    return winner


#: acceptance-rate floor below which the speculation autotuner declines no
#: matter the timing: at acceptance a, a k-token round emits ~1 + a·k
#: tokens, so below ~0.5 the verify work is mostly thrown away and the
#: measured "win" is noise at probe scale. Deterministic gate (a rate, not
#: a clock), so FakeClock runs decline reproducibly.
DEFAULT_SPEC_ACCEPT_FLOOR = 0.5


def autotune_speculation(
    model,
    params,
    *,
    slots: int = 2,
    new_tokens: int = 8,
    candidates: tuple = ("k4d1",),
    accept_floor: float = DEFAULT_SPEC_ACCEPT_FLOOR,
    clock: Callable[[], float] = time.perf_counter,
    persist: Optional[str] = None,
    force: bool = False,
) -> str:
    """Measure self-draft speculation against the plain one-token step at
    the bound shape and memoize the winner; returns one of
    :data:`SPECULATION_CHOICES`.

    The probe drives a tiny :class:`~perceiver_io_tpu.serving.slots.
    SlotServingEngine` per arm over the shared KV-probe workload (same
    prompts, greedy, EOS-free — and speculation is token-identical by
    construction, so every arm emits the identical schedule): one pass to
    compile, one timed pass, per-token ms on ``clock``. A speculative arm
    must clear TWO gates to win: its measured acceptance rate must reach
    ``accept_floor`` (the deterministic decline — drafts the model keeps
    rejecting can never pay), and its per-token time must beat ``"off"``
    strictly. Ties — including the all-zero durations an un-advanced
    FakeClock produces — break toward ``"off"``, the status-quo step.
    Candidates whose draft depth is not a strict truncation of the bound
    model's stack are skipped (a full-depth "draft" is just the model).

    :param persist: JSON path — merged before deciding (a persisted verdict
        short-circuits the measurement unless ``force``) and rewritten
        after, sharing the boundary registry's artifact file.
    """
    from perceiver_io_tpu.serving.slots import SlotServingEngine

    if persist:
        load_registry(persist)
    _maybe_load_env_file()
    key = registry_key(model)
    if not force and key in _SPEC_REGISTRY:
        return _SPEC_REGISTRY[key]["speculation"]

    num_layers = int(model.config.num_self_attention_layers)
    arms = ["off"]
    skipped = []
    for cand in candidates:
        if cand not in SPECULATION_CHOICES or cand == "off":
            raise ValueError(
                f"candidates must come from {SPECULATION_CHOICES[1:]}, "
                f"got {cand!r}"
            )
        draft_layers = int(cand.split("d")[1])
        (arms if draft_layers < num_layers else skipped).append(cand)

    table, gcfg, prompts, new_tokens = _kv_probe_workload(model, slots, new_tokens)

    timings, acceptance = {}, {}
    for arm in arms:
        def make():
            return SlotServingEngine(
                model, params, gcfg, table, slots=slots, speculation=arm,
            )

        compile_engine = make()
        compile_engine.serve(prompts)  # pays the per-arm executor builds
        engine = make()
        for p in prompts:
            engine.submit(p)
        t0 = clock()
        engine.run_until_idle()
        timings[arm] = (clock() - t0) / (slots * new_tokens) * 1e3
        if arm != "off":
            acceptance[arm] = engine.stats()["speculation"]["acceptance_rate"]

    winner = "off"
    for arm in arms[1:]:
        if acceptance[arm] < accept_floor:
            continue  # the deterministic decline: drafting isn't landing
        if timings[arm] >= timings[winner if winner != "off" else "off"]:
            continue
        winner = arm
    record_speculation(
        model, winner,
        timings_ms_per_token={a: round(t, 4) for a, t in timings.items()},
        acceptance={a: round(r, 4) for a, r in acceptance.items()},
        accept_floor=accept_floor, skipped=skipped,
        slots=slots, new_tokens=new_tokens,
    )
    if persist:
        save_registry(persist)
    return winner


def main(argv=None) -> dict:
    """``make decode-tune``: run the autotune probe on a CLM shape (CPU by
    default) and print the verdict + measurements as one JSON line."""
    import argparse

    p = argparse.ArgumentParser(description=main.__doc__)
    p.add_argument("--ctx", type=int, default=512)
    p.add_argument("--num-latents", type=int, default=64)
    p.add_argument("--num-channels", type=int, default=64)
    p.add_argument("--num-layers", type=int, default=2)
    p.add_argument("--num-heads", type=int, default=8)
    p.add_argument("--batch", type=int, default=1)
    p.add_argument("--new-tokens", type=int, default=4)
    p.add_argument("--out", default=None,
                   help="persist the registry JSON artifact here")
    p.add_argument("--tpu", action="store_true",
                   help="run on the default accelerator backend (else force CPU)")
    args = p.parse_args(argv)

    import jax

    if not args.tpu:
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp

    from perceiver_io_tpu.models.text.clm import (
        CausalLanguageModel,
        CausalLanguageModelConfig,
    )

    cfg = CausalLanguageModelConfig(
        vocab_size=262,
        max_seq_len=args.ctx,
        max_latents=args.num_latents,
        num_channels=args.num_channels,
        num_heads=args.num_heads,
        num_self_attention_layers=args.num_layers,
    )
    model = CausalLanguageModel(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, args.ctx), jnp.int32),
        args.ctx - args.num_latents,
    )["params"]
    winner = autotune_boundary(
        model, params, batch=args.batch, new_tokens=args.new_tokens,
        persist=args.out, force=True,
    )
    entry = dict(_REGISTRY[registry_key(model)])
    out = {
        "boundary": winner,
        "platform": jax.default_backend(),
        "shape": list(shape_key(model)),
        **entry,
    }
    print(json.dumps(out))
    return out


if __name__ == "__main__":
    main()
