"""Autoregressive generation for Perceiver AR sequence models.

Reference semantics (``perceiver/model/text/clm/huggingface.py:53-143``):
the initial prompt tail of ``num_latents`` positions is latent; per generated
token the latent count grows to ``max_latents``, then the prefix grows to
``max_prefix_len``, then the window slides. The reference re-runs the full
model per token from Python; here the **whole generation is one
``lax.scan``** over a static-shape decode step, so it compiles once and stays
on-device.

Static shapes come from a right-aligned window formulation: the token window
is always ``(b, max_seq_len)`` with left padding tracked by ``pad_count``;
the latent segment is always the last ``max_latents`` positions, with a
dynamic scalar ``m`` (true latent count) masking which of them are real
latents. The phase schedule then reduces to ``m = min(m + 1, max_latents)``
per token — no per-phase control flow. Garbage query rows (window positions
classified latent but currently prefix) are computed and discarded; their
keys are masked at every layer, so real rows match the reference's ragged
computation exactly (same trick as the left-padded batches the reference
supports natively, ``clm/lightning.py:71-77``).

The prefix/latent boundary feeds the computation in two places that a KV
cache must respect: boundary-side key normalization (prefix keys use
``kv_norm``, latent keys use ``q_norm`` — reference ``modules.py:188-203``)
and latent-stack membership. Both are masked dynamically here.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from perceiver_io_tpu.inference.samplers import SamplingConfig, sample_logits
from perceiver_io_tpu.ops.position import RotaryEmbedding, positions


@dataclass(frozen=True)
class GenerationConfig:
    max_new_tokens: int = 64
    num_latents: int = 1
    eos_token_id: Optional[int] = None
    pad_token_id: int = 0
    sampling: SamplingConfig = SamplingConfig()


def _decode_forward(mdl, window: jnp.ndarray, pad_count: jnp.ndarray, m: jnp.ndarray):
    """Static-shape forward over the right-aligned window; returns next-token
    logits for the last position.

    :param mdl: bound ``AutoregressiveSequenceModel``.
    :param window: ``(b, N)`` tokens, right-aligned, left pads arbitrary ids.
    :param pad_count: ``(b,)`` number of left-pad slots per row.
    :param m: scalar — true latent count (last ``m`` window positions).
    """
    ar = mdl.perceiver_ar
    b, n = window.shape
    num_latents = mdl.max_latents  # static query length I

    pad_mask = jnp.arange(n)[None, :] < pad_count[:, None]  # (b, N) True = pad
    abs_pos = positions(b, n, shift=pad_count[:, None])
    emb, frq = ar.input_adapter(window, abs_pos=abs_pos)

    # Cross-attention layer (reference CrossAttentionLayer with the
    # x_kv_prefix path): latent-classified keys are q_norm'ed, prefix keys
    # kv_norm'ed — selected by mask since the boundary is dynamic.
    layer = ar.cross_attention
    ca = layer.cross_attn
    mha = ca.attention
    is_latent = (jnp.arange(n) >= n - num_latents)[None, :] & (
        jnp.arange(n)[None, :] >= n - m
    )
    x_q_all = ca.q_norm(emb)
    x_kv = jnp.where(is_latent[..., None], x_q_all, ca.kv_norm(emb))

    x_q = x_q_all[:, -num_latents:]
    rot_q = RotaryEmbedding(frq, right_align=True)
    rot_k = RotaryEmbedding(frq, right_align=True)
    q = mha.project_q(x_q, rot_q)
    k, v = mha.project_kv(x_kv, rot_k)
    attn = mha.attend(q, k, v, pad_mask=pad_mask, deterministic=True)
    x = attn + emb[:, -num_latents:]
    x = layer.mlp(x) + x

    # Self-attention stack over the (padded) latent segment. Positions that
    # are not yet real latents are masked as keys at every layer; the
    # reference passes no per-row pad mask to its stack (modules.py:730-733),
    # so none is added here either.
    stack_pad = jnp.broadcast_to(jnp.arange(num_latents)[None, :] < num_latents - m, (b, num_latents))
    frq_latent = frq[:, -num_latents:]
    x = ar.self_attention(
        x, stack_pad, RotaryEmbedding(frq_latent, right_align=True), True
    )

    x_last = x[:, -1]
    if mdl.config.output_norm:
        x_last = mdl.out_norm(x_last)
    logits = mdl.output_adapter(
        x_last[:, None], ar.input_adapter.embeddings
    )[:, 0]
    return logits


def generate(
    model,
    params,
    input_ids: jnp.ndarray,
    config: GenerationConfig,
    *,
    rng: Optional[jax.Array] = None,
    prompt_pad_count: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Generate ``config.max_new_tokens`` tokens after ``input_ids``.

    :param model: an ``AutoregressiveSequenceModel`` (CLM / symbolic audio).
    :param input_ids: ``(b, prompt_len)`` prompt, left-padded if ragged.
    :param prompt_pad_count: ``(b,)`` left-pad counts for ragged prompts.
    :return: ``(b, max_new_tokens)`` generated ids (pad after EOS).
    """
    b, prompt_len = input_ids.shape
    n = model.max_seq_len
    max_latents = model.max_latents
    if not 0 < prompt_len <= n:
        raise ValueError(f"prompt length out of valid range [1..{n}]")
    if not 0 < config.num_latents <= max_latents:
        raise ValueError(
            f"num_latents={config.num_latents} out of valid range [1..{max_latents}]"
        )
    num_latents = min(prompt_len, config.num_latents)
    prefix_len = prompt_len - num_latents
    if prefix_len > model.max_prefix_len:
        raise ValueError(
            f"for sequence length {prompt_len}, num_latents must be >= "
            f"{num_latents + prefix_len - model.max_prefix_len}"
        )
    if rng is None:
        rng = jax.random.PRNGKey(0)
    if prompt_pad_count is None:
        prompt_pad_count = jnp.zeros((b,), jnp.int32)

    # Right-align the prompt into the full-size window.
    window = jnp.full((b, n), config.pad_token_id, input_ids.dtype)
    window = window.at[:, n - prompt_len :].set(input_ids)
    pad_count = prompt_pad_count.astype(jnp.int32) + (n - prompt_len)

    def step(carry, step_rng):
        window, pad_count, m, finished = carry
        logits = model.apply(
            {"params": params},
            window,
            pad_count,
            m,
            method=_decode_forward,
        )
        token = sample_logits(step_rng, logits, config.sampling)
        if config.eos_token_id is not None:
            token = jnp.where(finished, config.pad_token_id, token)
            finished = finished | (token == config.eos_token_id)
        window = jnp.concatenate([window[:, 1:], token[:, None].astype(window.dtype)], axis=1)
        pad_count = jnp.maximum(pad_count - 1, 0)
        m = jnp.minimum(m + 1, max_latents)
        return (window, pad_count, m, finished), token

    carry = (
        window,
        pad_count,
        jnp.asarray(num_latents, jnp.int32),
        jnp.zeros((b,), bool),
    )
    _, tokens = jax.lax.scan(
        step, carry, jax.random.split(rng, config.max_new_tokens)
    )
    return tokens.T.astype(input_ids.dtype)
