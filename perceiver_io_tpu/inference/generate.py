"""Autoregressive generation for Perceiver AR sequence models.

Reference semantics (``perceiver/model/text/clm/huggingface.py:53-143``):
the initial prompt tail of ``num_latents`` positions is latent; per generated
token the latent count grows to ``max_latents``, then the prefix grows to
``max_prefix_len``, then the window slides. The reference re-runs the full
model per token from Python; here the **whole generation is one
``lax.scan``** over a static-shape decode step, so it compiles once and stays
on-device.

Static shapes come from a right-aligned window formulation: the token window
is always ``(b, max_seq_len)`` with left padding tracked by ``pad_count``;
the latent segment is always the last ``max_latents`` positions, with a
dynamic scalar ``m`` (true latent count) masking which of them are real
latents. The phase schedule then reduces to ``m = min(m + 1, max_latents)``
per token — no per-phase control flow. Garbage query rows (window positions
classified latent but currently prefix) are computed and discarded; their
keys are masked at every layer, so real rows match the reference's ragged
computation exactly (same trick as the left-padded batches the reference
supports natively, ``clm/lightning.py:71-77``).

The prefix/latent boundary feeds the computation in two places that a KV
cache must respect: boundary-side key normalization (prefix keys use
``kv_norm``, latent keys use ``q_norm`` — reference ``modules.py:188-203``)
and latent-stack membership. Both are masked dynamically here.

Cache coverage by phase (``use_cache=True`` spans all of ``max_new_tokens``
in a single chained-scan program):

1. **Latent growth** (``_decode_step``): fully incremental — only the new
   token runs through the model, attending over cross- and per-layer stack
   caches. O(1) tokens of compute per step.
2. **Prefix growth** (``_decode_step_boundary``): token positions are stable
   (the window still slides over left pads), but the latent/prefix boundary
   migrates one position per step: the oldest latent becomes prefix, so its
   cross k/v are recomputed ``kv_norm``-side and overwritten in the cache
   (reference ``modules.py:188-203``). Because every latent attends to the
   migrated key, all latent cross-attention outputs — and therefore the
   whole self-attention stack — change each step and are recomputed; what
   the cache elides is the full-window embedding + cross k/v projections
   (the ``2·n·c²`` matmuls, the dominant projection cost for ``n ≫ m``).
3. **Sliding window** (``_decode_forward`` recompute): with the reference's
   learned absolute position embedding (``abs_pos_emb=True``, the default),
   incremental caching in this phase is *semantically impossible*, not
   merely hard: positions are window-relative (reference
   ``clm/huggingface.py:66`` truncates to the last ``max_seq_len`` tokens),
   so every surviving token's position embedding — and hence every key,
   value, and latent input — changes on every step. The only exact step is
   a full recompute, which is what the reference itself does each token;
   here it stays inside ``lax.scan``, compiled once. (For a rotary-only
   model, ``abs_pos_emb=False``, positions enter attention only relatively
   and a stable-angle cache would be mathematically exact — but not
   bit-exact against the window-relative recompute, so it is not used.)
"""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from perceiver_io_tpu.inference.samplers import (
    SamplingConfig,
    apply_min_new_tokens,
    sample_logits,
)
from perceiver_io_tpu.ops.position import RotaryEmbedding, positions


@dataclass(frozen=True)
class GenerationConfig:
    max_new_tokens: int = 64
    num_latents: int = 1
    eos_token_id: Optional[int] = None
    pad_token_id: int = 0
    sampling: SamplingConfig = SamplingConfig()
    #: beam width; > 1 dispatches :func:`generate` to beam search (greedy
    #: candidate expansion, HF ``GenerationMixin`` semantics).
    num_beams: int = 1
    #: HF exponent on generated length when ranking hypotheses (matches the
    #: vectorized ``_beam_search`` in transformers >= 4.50).
    length_penalty: float = 1.0
    #: EOS is masked to -inf until this many new tokens exist — greedy,
    #: sampled, and beam decoding alike (HF MinNewTokensLengthLogitsProcessor).
    min_new_tokens: int = 0


def _decode_forward(mdl, window: jnp.ndarray, pad_count: jnp.ndarray, m: jnp.ndarray):
    """Static-shape forward over the right-aligned window; returns next-token
    logits for the last position.

    :param mdl: bound ``AutoregressiveSequenceModel``.
    :param window: ``(b, N)`` tokens, right-aligned, left pads arbitrary ids.
    :param pad_count: ``(b,)`` number of left-pad slots per row.
    :param m: true latent count (last ``m`` window positions) — scalar, or
        per-row ``(b,)`` (the speculative verify lanes give each row its own
        post-candidate latent count; the scalar path is unchanged).
    """
    ar = mdl.perceiver_ar
    b, n = window.shape
    num_latents = mdl.max_latents  # static query length I

    pad_mask = jnp.arange(n)[None, :] < pad_count[:, None]  # (b, N) True = pad
    abs_pos = positions(b, n, shift=pad_count[:, None])
    emb, frq = ar.input_adapter(window, abs_pos=abs_pos)

    # Cross-attention layer (reference CrossAttentionLayer with the
    # x_kv_prefix path): latent-classified keys are q_norm'ed, prefix keys
    # kv_norm'ed — selected by mask since the boundary is dynamic.
    layer = ar.cross_attention
    ca = layer.cross_attn
    mha = ca.attention
    m = jnp.asarray(m)
    m_col = m[:, None] if m.ndim else m  # (b, 1) per-row or scalar
    is_latent = (jnp.arange(n) >= n - num_latents)[None, :] & (
        jnp.arange(n)[None, :] >= n - m_col
    )
    x_q_all = ca.q_norm(emb)
    x_kv = jnp.where(is_latent[..., None], x_q_all, ca.kv_norm(emb))

    x_q = x_q_all[:, -num_latents:]
    rot_q = RotaryEmbedding(frq, right_align=True)
    rot_k = RotaryEmbedding(frq, right_align=True)
    q = mha.project_q(x_q, rot_q)
    k, v = mha.project_kv(x_kv, rot_k)
    attn = mha.attend(q, k, v, pad_mask=pad_mask, deterministic=True)
    x = attn + emb[:, -num_latents:]
    x = layer.mlp(x) + x

    # Self-attention stack over the (padded) latent segment. Positions that
    # are not yet real latents are masked as keys at every layer; the
    # reference passes no per-row pad mask to its stack (modules.py:730-733),
    # so none is added here either.
    stack_pad = jnp.broadcast_to(
        jnp.arange(num_latents)[None, :] < num_latents - m_col, (b, num_latents)
    )
    frq_latent = frq[:, -num_latents:]
    x = ar.self_attention(
        x, stack_pad, RotaryEmbedding(frq_latent, right_align=True), True
    )

    x_last = x[:, -1]
    if mdl.config.output_norm:
        x_last = mdl.out_norm(x_last)
    logits = mdl.output_adapter(
        x_last[:, None], ar.input_adapter.embeddings
    )[:, 0]
    return logits


def _latent_stack_capture(ar, x, stack_pad, rot_latent, seg_idx):
    """Self-attention stack over the latent segment with per-layer k/v
    capture at the ``m`` real latents' segment slots (rotary on layer 0
    only, mirroring the stack's first-layer-rotary semantics) — ONE
    implementation shared by the one-shot prefill and both finalize paths
    (dense chunked, paged shared-prefix), so the admission paths cannot
    drift bitwise: same masks, same capture indices.

    :return: ``(x, stack_k, stack_v)`` — the stack output and the per-layer
        captured caches.
    """
    stack_k, stack_v = [], []
    for i, sa_layer in enumerate(ar.self_attention.layers):
        sa = sa_layer.self_attn
        r = rot_latent if (i == 0 or ar.self_attention.rotary_all_layers) else None
        normed = sa.norm(x)
        q_s = sa.attention.project_q(normed, r)
        k_s, v_s = sa.attention.project_kv(normed, r)
        stack_k.append(jnp.take_along_axis(k_s, seg_idx[None, None, :, None], axis=2))
        stack_v.append(jnp.take_along_axis(v_s, seg_idx[None, None, :, None], axis=2))
        attn = sa.attention.attend(q_s, k_s, v_s, pad_mask=stack_pad, deterministic=True)
        x = attn + x
        x = sa_layer.mlp(x) + x
    return x, stack_k, stack_v


def _decode_prefill(mdl, window: jnp.ndarray, pad_count: jnp.ndarray, m: jnp.ndarray):
    """Forward over the right-aligned window that additionally builds the
    decode caches for the latent-growth phase.

    Cache layout is **left-aligned by token index** ``p = slot - pad_count``
    (stable as the window slides over left pads), so appends are in-place
    writes, not rolls:

    - ``cross_k/v``: ``(b, h, N, d)`` — cross-attention keys/values of every
      real token, in its boundary-side normalization (latent keys q_norm'd,
      prefix keys kv_norm'd — reference ``modules.py:188-203``), rotary
      applied at angle ``p`` (relative, so shared offsets cancel).
    - ``stack_k/v``: per layer ``(b, h, max_latents, d)`` over the ``m`` real
      latents (left-aligned by latent age); rotary on layer 0 only,
      mirroring the stack's first-layer-rotary semantics.

    :return: (next-token logits, cache dict, length ``(b,)``, m).
    """
    ar = mdl.perceiver_ar
    b, n = window.shape
    num_latents = mdl.max_latents

    pad_mask = jnp.arange(n)[None, :] < pad_count[:, None]
    abs_pos = positions(b, n, shift=pad_count[:, None])
    emb, frq = ar.input_adapter(window, abs_pos=abs_pos)

    layer = ar.cross_attention
    ca = layer.cross_attn
    mha = ca.attention
    is_latent = (jnp.arange(n) >= n - num_latents)[None, :] & (
        jnp.arange(n)[None, :] >= n - m
    )
    x_q_all = ca.q_norm(emb)
    x_kv = jnp.where(is_latent[..., None], x_q_all, ca.kv_norm(emb))

    x_q = x_q_all[:, -num_latents:]
    rot = RotaryEmbedding(frq, right_align=True)
    q = mha.project_q(x_q, rot)
    k, v = mha.project_kv(x_kv, rot)
    attn = mha.attend(q, k, v, pad_mask=pad_mask, deterministic=True)
    x = attn + emb[:, -num_latents:]
    x = layer.mlp(x) + x

    # Left-align the window-slot cross k/v by token index p = slot - pad_count.
    slot_idx = jnp.clip(jnp.arange(n)[None, :] + pad_count[:, None], 0, n - 1)
    cross_k = jnp.take_along_axis(k, slot_idx[:, None, :, None], axis=2)
    cross_v = jnp.take_along_axis(v, slot_idx[:, None, :, None], axis=2)
    length = (n - pad_count).astype(jnp.int32)

    # Self-attention stack, capturing per-layer k/v of the m real latents
    # (segment slot num_latents - m + t for latent age t).
    stack_pad = jnp.broadcast_to(
        jnp.arange(num_latents)[None, :] < num_latents - m, (b, num_latents)
    )
    frq_latent = frq[:, -num_latents:]
    rot_latent = RotaryEmbedding(frq_latent, right_align=True)
    seg_idx = jnp.clip(num_latents - m + jnp.arange(num_latents), 0, num_latents - 1)
    x, stack_k, stack_v = _latent_stack_capture(ar, x, stack_pad, rot_latent, seg_idx)

    x_last = x[:, -1]
    if mdl.config.output_norm:
        x_last = mdl.out_norm(x_last)
    logits = mdl.output_adapter(x_last[:, None], ar.input_adapter.embeddings)[:, 0]
    cache = {"cross_k": cross_k, "cross_v": cross_v,
             "stack_k": stack_k, "stack_v": stack_v}
    return logits, cache, length, m


def _prefill_chunk_kv(mdl, tokens: jnp.ndarray, offset: jnp.ndarray):
    """Cross k/v (``kv_norm``-side) for a fixed-size chunk of **prefix**
    token positions — the unit of chunked prefill (``serving/slots.py``).

    The full prefill's cross-k/v cache is per-position math: embedding at
    the token's absolute index, ``kv_norm``, k/v projection with rotary at
    angle ``p`` (:func:`_decode_prefill`'s left-aligned layout). None of it
    couples positions, so a chunk of ``C`` consecutive prefix positions
    computes values identical to the one-shot full-window pass — which is
    what lets the slot engine split a long admission into bounded-stall
    pieces interleaved with resident decode steps.

    :param tokens: ``(b, C)`` token ids at absolute indices
        ``offset .. offset + C - 1``.
    :param offset: traced scalar — the chunk's first absolute token index
        (one compiled program serves every chunk of every bucket).
    :return: ``(k, v)`` of shape ``(b, h, C, d)`` for those positions.
    """
    ar = mdl.perceiver_ar
    b, c = tokens.shape
    pos = jnp.broadcast_to(
        offset + jnp.arange(c, dtype=jnp.int32)[None, :], (b, c)
    )
    emb, frq = ar.input_adapter(tokens, abs_pos=pos)
    ca = ar.cross_attention.cross_attn
    return ca.attention.project_kv(ca.kv_norm(emb), RotaryEmbedding(frq))


def _prefill_finalize(mdl, window: jnp.ndarray, pad_count: jnp.ndarray,
                      m: jnp.ndarray, cross_k, cross_v):
    """Complete a chunked prefill: with the prefix cross k/v already staged
    by :func:`_prefill_chunk_kv` calls, project the ``m`` real latents'
    ``q_norm``-side k/v into the cache, attend the latent segment over the
    cache gathered back into window-slot alignment (pad slots gather
    garbage the pad mask zeroes out — the :func:`_decode_step_boundary`
    argument), and run the self-attention stack capturing its caches.

    Returns the same ``(logits, cache, length, m)`` contract as
    :func:`_decode_prefill`, so the slot engine inserts either path's
    output identically.
    """
    ar = mdl.perceiver_ar
    b, n = window.shape
    num_latents = mdl.max_latents
    layer = ar.cross_attention
    ca = layer.cross_attn
    mha = ca.attention
    rows = jnp.arange(b)

    # Latent segment (last max_latents window slots) at true token indices;
    # p_seg < 0 marks pad slots (prompt shorter than the latent budget).
    p_seg = jnp.arange(n - num_latents, n)[None, :] - pad_count[:, None]
    lat_abs = jnp.maximum(p_seg, 0)
    emb_lat, frq_lat = ar.input_adapter(window[:, n - num_latents:], abs_pos=lat_abs)
    x_q_lat = ca.q_norm(emb_lat)

    # q_norm-side k/v of the m real latents, written at their abs indices.
    # Segment slots that are prefix-classified (m < max_latents) or pads
    # route to the out-of-bounds sentinel ``n`` and are DROPPED: their
    # kv_norm-side entries came from the chunk passes and must survive.
    k_lat, v_lat = mha.project_kv(x_q_lat, RotaryEmbedding(frq_lat))
    is_real = jnp.arange(num_latents)[None, :] >= num_latents - m
    idx = jnp.where(is_real, jnp.clip(p_seg, 0, n - 1), n)
    cross_k = cross_k.at[rows[:, None], :, idx].set(
        k_lat.transpose(0, 2, 1, 3), mode="drop"
    )
    cross_v = cross_v.at[rows[:, None], :, idx].set(
        v_lat.transpose(0, 2, 1, 3), mode="drop"
    )

    # Gather into window-slot alignment and attend exactly as
    # _decode_prefill's direct pass does (masking included).
    slot_abs = jnp.maximum(jnp.arange(n)[None, :] - pad_count[:, None], 0)
    k_slots = jnp.take_along_axis(cross_k, slot_abs[:, None, :, None], axis=2)
    v_slots = jnp.take_along_axis(cross_v, slot_abs[:, None, :, None], axis=2)
    pad_mask = jnp.arange(n)[None, :] < pad_count[:, None]
    q = mha.project_q(x_q_lat, RotaryEmbedding(frq_lat, right_align=True))
    attn = mha.attend(q, k_slots, v_slots, pad_mask=pad_mask, deterministic=True)
    x = attn + emb_lat
    x = layer.mlp(x) + x

    # Self-attention stack with per-layer cache capture (_decode_prefill's
    # shared helper: same masks, same first-layer-rotary semantics).
    stack_pad = jnp.broadcast_to(
        jnp.arange(num_latents)[None, :] < num_latents - m, (b, num_latents)
    )
    rot_latent = RotaryEmbedding(frq_lat, right_align=True)
    seg_idx = jnp.clip(num_latents - m + jnp.arange(num_latents), 0, num_latents - 1)
    x, stack_k, stack_v = _latent_stack_capture(ar, x, stack_pad, rot_latent, seg_idx)

    x_last = x[:, -1]
    if mdl.config.output_norm:
        x_last = mdl.out_norm(x_last)
    logits = mdl.output_adapter(x_last[:, None], ar.input_adapter.embeddings)[:, 0]
    length = (n - pad_count).astype(jnp.int32)
    cache = {"cross_k": cross_k, "cross_v": cross_v,
             "stack_k": stack_k, "stack_v": stack_v}
    return logits, cache, length, m


def _prefill_finalize_paged(
    mdl, window: jnp.ndarray, pad_count: jnp.ndarray, m: jnp.ndarray,
    pool_k, pool_v, table_row: jnp.ndarray, block_size: int,
    scale_k=None, scale_v=None,
):
    """:func:`_prefill_finalize` over the block-paged KV layout with a
    **suffix-only** contract (docs/serving.md "Prefix sharing"): cross k/v
    for every prefix position are ALREADY RESIDENT in the pool — shared
    prefix blocks another request published (never re-projected: the TTFT
    win prefix sharing exists for) and/or this admission's own staged
    chunks, which only covered ``[start_position, prefix_len)`` — so this
    call only projects the ``m`` real latents' ``q_norm``-side k/v,
    scatters them through the slot's block table, gathers the WHOLE window
    back from the pool, and runs the attend + self-attention stack exactly
    as the dense finalize does. A fully-hot prefix stages zero chunks and
    the admission collapses to block-table writes plus this one call.

    ``scale_k``/``scale_v`` (both or neither) carry the int8 layout's
    per-(position, head) dequant scales: appends quantize through
    :func:`~perceiver_io_tpu.ops.paged_attention.scatter_kv` and the
    updated scales join the return tuple right after the pools.

    Latent scatter routing: non-real segment slots (prompt shorter than
    the latent budget) route to the null block — the paged analogue of the
    dense finalize's ``mode="drop"`` — so staged/shared prefix values
    survive, and the gather + masked attend is bitwise identical to the
    dense path (the parity bar ``tests/test_prefix_cache.py`` pins).

    :return: ``(logits, pool_k, pool_v, stack cache, length, m)``.
    """
    from perceiver_io_tpu.ops import paged_attention as paged

    ar = mdl.perceiver_ar
    b, n = window.shape
    num_latents = mdl.max_latents
    layer = ar.cross_attention
    ca = layer.cross_attn
    mha = ca.attention
    table = table_row[None] if table_row.ndim == 1 else table_row

    # Latent segment (last max_latents window slots) at true token indices;
    # p_seg < 0 marks pad slots (prompt shorter than the latent budget).
    p_seg = jnp.arange(n - num_latents, n)[None, :] - pad_count[:, None]
    lat_abs = jnp.maximum(p_seg, 0)
    emb_lat, frq_lat = ar.input_adapter(window[:, n - num_latents:], abs_pos=lat_abs)
    x_q_lat = ca.q_norm(emb_lat)

    # q_norm-side k/v of the m real latents, scattered at their abs
    # indices through the block table; prefix-classified or pad segment
    # slots route to the null block (their kv_norm-side pool entries came
    # from chunk passes / shared blocks and must survive).
    k_lat, v_lat = mha.project_kv(x_q_lat, RotaryEmbedding(frq_lat))
    is_real = jnp.arange(num_latents)[None, :] >= num_latents - m
    idx = jnp.clip(p_seg, 0, n - 1)
    flat_lat = paged.flat_write_indices(table, idx, block_size)
    flat_lat = jnp.where(is_real, flat_lat, idx % block_size)  # null-route
    pool_k, scale_k = paged.scatter_kv(
        pool_k, scale_k, flat_lat[0], k_lat[0].transpose(1, 0, 2)
    )
    pool_v, scale_v = paged.scatter_kv(
        pool_v, scale_v, flat_lat[0], v_lat[0].transpose(1, 0, 2)
    )

    # Window-aligned attend exactly as the dense finalize's (gather path:
    # pad slots re-read position 0 and the pad mask zeroes them out of
    # the softmax — the _decode_step_boundary argument; kernel path: the
    # ragged kernel over the live span [0, n - pad_count)).
    q = mha.project_q(x_q_lat, RotaryEmbedding(frq_lat, right_align=True))
    attn = paged.paged_window_attention(
        mha.attend, q, pool_k, pool_v, table,
        block_size=block_size, n=n, pad_count=pad_count,
        scale_k=scale_k, scale_v=scale_v, project_out=mha.project_out,
    )
    x = attn + emb_lat
    x = layer.mlp(x) + x

    # Self-attention stack with per-layer cache capture (the shared
    # helper: same masks, same first-layer-rotary semantics as the dense
    # prefill/finalize — the bitwise half of the parity claim).
    stack_pad = jnp.broadcast_to(
        jnp.arange(num_latents)[None, :] < num_latents - m, (b, num_latents)
    )
    rot_latent = RotaryEmbedding(frq_lat, right_align=True)
    seg_idx = jnp.clip(num_latents - m + jnp.arange(num_latents), 0, num_latents - 1)
    x, stack_k, stack_v = _latent_stack_capture(ar, x, stack_pad, rot_latent, seg_idx)

    x_last = x[:, -1]
    if mdl.config.output_norm:
        x_last = mdl.out_norm(x_last)
    logits = mdl.output_adapter(x_last[:, None], ar.input_adapter.embeddings)[:, 0]
    length = (n - pad_count).astype(jnp.int32)
    cache = {"stack_k": stack_k, "stack_v": stack_v}
    if scale_k is not None:
        return logits, pool_k, pool_v, scale_k, scale_v, cache, length, m
    return logits, pool_k, pool_v, cache, length, m


def _decode_step(mdl, token: jnp.ndarray, cache: dict, length: jnp.ndarray, m: jnp.ndarray):
    """One cached decode step: run ONLY the new token through the model,
    attending over the caches — valid while the new token is a fresh latent
    (latent-growth phase: no boundary migration, no position shifts).

    :param token: ``(b,)`` the token just appended.
    :return: (next-token logits, cache, length + 1, m + 1).
    """
    ar = mdl.perceiver_ar
    b = token.shape[0]
    n = cache["cross_k"].shape[2]
    num_latents = mdl.max_latents

    p_new = length[:, None]  # (b, 1) token index of the new position
    emb, frq = ar.input_adapter(token[:, None], abs_pos=p_new)
    rot = RotaryEmbedding(frq)

    layer = ar.cross_attention
    ca = layer.cross_attn
    mha = ca.attention
    x_q = ca.q_norm(emb)  # the new token is a latent: q_norm on both sides
    q = mha.project_q(x_q, rot)
    k_new, v_new = mha.project_kv(x_q, rot)
    rows = jnp.arange(b)
    cross_k = cache["cross_k"].at[rows, :, length].set(k_new[:, :, 0])
    cross_v = cache["cross_v"].at[rows, :, length].set(v_new[:, :, 0])
    future = jnp.arange(n)[None, :] > length[:, None]  # True = not yet written
    attn = mha.attend(q, cross_k, cross_v, pad_mask=future, deterministic=True)
    x = attn + emb
    x = layer.mlp(x) + x

    stack_k, stack_v = [], []
    stack_future = jnp.broadcast_to(jnp.arange(num_latents)[None, :] > m, (b, num_latents))
    for i, sa_layer in enumerate(ar.self_attention.layers):
        sa = sa_layer.self_attn
        r = rot if (i == 0 or ar.self_attention.rotary_all_layers) else None
        normed = sa.norm(x)
        q_s = sa.attention.project_q(normed, r)
        k_s, v_s = sa.attention.project_kv(normed, r)
        k_i = jax.lax.dynamic_update_slice(cache["stack_k"][i], k_s, (0, 0, m, 0))
        v_i = jax.lax.dynamic_update_slice(cache["stack_v"][i], v_s, (0, 0, m, 0))
        stack_k.append(k_i)
        stack_v.append(v_i)
        attn = sa.attention.attend(q_s, k_i, v_i, pad_mask=stack_future, deterministic=True)
        x = attn + x
        x = sa_layer.mlp(x) + x

    x_last = x[:, 0]
    if mdl.config.output_norm:
        x_last = mdl.out_norm(x_last)
    logits = mdl.output_adapter(x_last[:, None], ar.input_adapter.embeddings)[:, 0]
    cache = {"cross_k": cross_k, "cross_v": cross_v,
             "stack_k": stack_k, "stack_v": stack_v}
    return logits, cache, length + 1, m + 1


def _slot_decode_step(mdl, token: jnp.ndarray, cache: dict, length: jnp.ndarray, m: jnp.ndarray):
    """Per-row variant of :func:`_decode_step` for the slot serving engine
    (``serving/slots.py``): ``m`` is a ``(b,)`` vector, not a scalar, because
    persistent slots are admitted at different times and therefore sit at
    different latent counts. The stack-cache append and the stack future
    mask become per-row scatters; every other op is already per-row. For a
    row whose ``m`` equals the batch scalar, the math is identical to
    :func:`_decode_step` — that is the slot engine's token-parity claim.

    Write indices are clamped (``min(length, N-1)``, ``min(m, I-1)``) so
    retired/idle slots whose counters have saturated stay in-bounds; active
    rows never hit the clamp (the engine rejects requests that would
    overrun the window).

    :param token: ``(b,)`` the token just appended.
    :param length: ``(b,)`` real-token count before the append.
    :param m: ``(b,)`` per-row latent count before the append.
    :return: (next-token logits, cache, length + 1, m + 1).
    """
    ar = mdl.perceiver_ar
    b = token.shape[0]
    n = cache["cross_k"].shape[2]
    num_latents = mdl.max_latents

    wl = jnp.minimum(length, n - 1)  # write index; no-op clamp for active rows
    p_new = wl[:, None]  # (b, 1) token index of the new position
    emb, frq = ar.input_adapter(token[:, None], abs_pos=p_new)
    rot = RotaryEmbedding(frq)

    layer = ar.cross_attention
    ca = layer.cross_attn
    mha = ca.attention
    x_q = ca.q_norm(emb)  # the new token is a latent: q_norm on both sides
    q = mha.project_q(x_q, rot)
    k_new, v_new = mha.project_kv(x_q, rot)
    rows = jnp.arange(b)
    cross_k = cache["cross_k"].at[rows, :, wl].set(k_new[:, :, 0])
    cross_v = cache["cross_v"].at[rows, :, wl].set(v_new[:, :, 0])
    future = jnp.arange(n)[None, :] > length[:, None]  # True = not yet written
    attn = mha.attend(q, cross_k, cross_v, pad_mask=future, deterministic=True)
    x = attn + emb
    x = layer.mlp(x) + x

    wm = jnp.minimum(m, num_latents - 1)
    stack_k, stack_v = [], []
    stack_future = jnp.arange(num_latents)[None, :] > m[:, None]
    for i, sa_layer in enumerate(ar.self_attention.layers):
        sa = sa_layer.self_attn
        r = rot if (i == 0 or ar.self_attention.rotary_all_layers) else None
        normed = sa.norm(x)
        q_s = sa.attention.project_q(normed, r)
        k_s, v_s = sa.attention.project_kv(normed, r)
        k_i = cache["stack_k"][i].at[rows, :, wm].set(k_s[:, :, 0])
        v_i = cache["stack_v"][i].at[rows, :, wm].set(v_s[:, :, 0])
        stack_k.append(k_i)
        stack_v.append(v_i)
        attn = sa.attention.attend(q_s, k_i, v_i, pad_mask=stack_future, deterministic=True)
        x = attn + x
        x = sa_layer.mlp(x) + x

    x_last = x[:, 0]
    if mdl.config.output_norm:
        x_last = mdl.out_norm(x_last)
    logits = mdl.output_adapter(x_last[:, None], ar.input_adapter.embeddings)[:, 0]
    cache = {"cross_k": cross_k, "cross_v": cross_v,
             "stack_k": stack_k, "stack_v": stack_v}
    return logits, cache, length + 1, m + 1


def _slot_decode_step_paged(
    mdl, token: jnp.ndarray, pool_k, pool_v, block_table: jnp.ndarray,
    stack_cache: dict, length: jnp.ndarray, m: jnp.ndarray,
    block_size: int, write_ok: Optional[jnp.ndarray] = None,
    scale_k=None, scale_v=None,
):
    """:func:`_slot_decode_step` over the block-paged KV layout
    (``serving/kv_pool.py``): the per-slot dense ``cross_k/cross_v`` rows
    are replaced by ONE flat ``(pool_tokens, h, d)`` pool addressed through
    ``block_table`` (``(b, pages)``; block 0 is the null/trash block). The
    new token's k/v scatter lands at the table-translated append index, and
    the attend runs through
    :func:`~perceiver_io_tpu.ops.paged_attention.paged_decode_attention` —
    a gather back to the dense view (bitwise-identical masked attend) or
    the ragged Pallas kernel when ``PERCEIVER_RAGGED_KERNEL=1``. The latent-stack cache stays dense:
    it is bounded by ``max_latents`` (a model constant), not the context
    length, so it is outside the ``slots × max_context`` scaling the pool
    exists to break (docs/serving.md).

    ``write_ok`` (per-row bool) redirects a row's append write to the null
    block — the boundary-variant executor passes ``~is_boundary`` so the
    per-row select between this step and the boundary step becomes *write
    routing*: each live pool position is written by exactly the step the
    dense layout's ``where`` select would have kept.

    ``scale_k``/``scale_v`` (both or neither) carry the int8 layout's
    dequant scales; appends quantize via ``scatter_kv`` and the updated
    scales join the return tuple right after the pools.

    :return: (next-token logits, pool_k, pool_v, [scale_k, scale_v,]
        stack cache, length + 1, m + 1).
    """
    from perceiver_io_tpu.ops import paged_attention as paged

    ar = mdl.perceiver_ar
    b = token.shape[0]
    n = mdl.max_seq_len
    num_latents = mdl.max_latents

    wl = jnp.minimum(length, n - 1)  # write index; no-op clamp for active rows
    p_new = wl[:, None]
    emb, frq = ar.input_adapter(token[:, None], abs_pos=p_new)
    rot = RotaryEmbedding(frq)

    layer = ar.cross_attention
    ca = layer.cross_attn
    mha = ca.attention
    x_q = ca.q_norm(emb)  # the new token is a latent: q_norm on both sides
    q = mha.project_q(x_q, rot)
    k_new, v_new = mha.project_kv(x_q, rot)
    flat_w = paged.flat_write_indices(block_table, wl, block_size)
    if write_ok is not None:
        # boundary rows' appends are owned by the boundary step; route this
        # one to the null block (flat index < block_size is always trash)
        flat_w = jnp.where(write_ok, flat_w, flat_w % block_size)
    pool_k, scale_k = paged.scatter_kv(pool_k, scale_k, flat_w, k_new[:, :, 0])
    pool_v, scale_v = paged.scatter_kv(pool_v, scale_v, flat_w, v_new[:, :, 0])
    future = jnp.arange(n)[None, :] > length[:, None]  # True = not yet written
    attn = paged.paged_decode_attention(
        mha.attend, q, pool_k, pool_v, block_table,
        block_size=block_size, n=n, pad_mask=future,
        lengths=jnp.minimum(length + 1, n),
        scale_k=scale_k, scale_v=scale_v, project_out=mha.project_out,
    )
    x = attn + emb
    x = layer.mlp(x) + x

    wm = jnp.minimum(m, num_latents - 1)
    rows = jnp.arange(b)
    stack_k, stack_v = [], []
    stack_future = jnp.arange(num_latents)[None, :] > m[:, None]
    for i, sa_layer in enumerate(ar.self_attention.layers):
        sa = sa_layer.self_attn
        r = rot if (i == 0 or ar.self_attention.rotary_all_layers) else None
        normed = sa.norm(x)
        q_s = sa.attention.project_q(normed, r)
        k_s, v_s = sa.attention.project_kv(normed, r)
        k_i = stack_cache["stack_k"][i].at[rows, :, wm].set(k_s[:, :, 0])
        v_i = stack_cache["stack_v"][i].at[rows, :, wm].set(v_s[:, :, 0])
        stack_k.append(k_i)
        stack_v.append(v_i)
        attn = sa.attention.attend(q_s, k_i, v_i, pad_mask=stack_future, deterministic=True)
        x = attn + x
        x = sa_layer.mlp(x) + x

    x_last = x[:, 0]
    if mdl.config.output_norm:
        x_last = mdl.out_norm(x_last)
    logits = mdl.output_adapter(x_last[:, None], ar.input_adapter.embeddings)[:, 0]
    stack = {"stack_k": stack_k, "stack_v": stack_v}
    if scale_k is not None:
        return logits, pool_k, pool_v, scale_k, scale_v, stack, length + 1, m + 1
    return logits, pool_k, pool_v, stack, length + 1, m + 1


def _decode_step_boundary_paged(
    mdl, window: jnp.ndarray, pad_count: jnp.ndarray, pool_k, pool_v,
    block_table: jnp.ndarray, length: jnp.ndarray, block_size: int,
    write_ok: Optional[jnp.ndarray] = None,
    scale_k=None, scale_v=None,
):
    """:func:`_decode_step_boundary` over the block-paged KV layout: the
    migration + append writes become table-translated pool scatters and the
    window-slot-aligned gather reads the pool instead of a dense per-row
    cache. The computation between scatter and gather — latent embedding,
    boundary-side re-normalization, attend, the full self-attention stack —
    is the dense step's verbatim, so live rows' logits are bitwise
    identical to the dense layout (the paged engine's parity claim).

    ``write_ok`` routes NON-boundary rows' writes to the null block (the
    inverse of :func:`_slot_decode_step_paged`'s routing — together they
    reproduce the dense executor's per-row ``where`` select at every live
    pool position).

    ``scale_k``/``scale_v`` follow the same int8-layout contract as
    :func:`_slot_decode_step_paged`.

    :return: (next-token logits, pool_k, pool_v, [scale_k, scale_v,]
        length + 1).
    """
    from perceiver_io_tpu.ops import paged_attention as paged

    ar = mdl.perceiver_ar
    b, n = window.shape
    num_latents = mdl.max_latents
    layer = ar.cross_attention
    ca = layer.cross_attn
    mha = ca.attention

    mig_abs = jnp.maximum((n - num_latents - 1) - pad_count[:, None], 0)
    # append index clamped only for idle rows (saturated length); active
    # boundary rows always satisfy length < n, matching the dense step
    write_idx = jnp.concatenate(
        [mig_abs, jnp.minimum(length, n - 1)[:, None]], axis=1
    )

    lat_abs = jnp.maximum(
        jnp.arange(n - num_latents, n)[None, :] - pad_count[:, None], 0
    )
    emb_lat, frq_lat = ar.input_adapter(window[:, n - num_latents :], abs_pos=lat_abs)
    x_q_lat = ca.q_norm(emb_lat)

    emb_mig, frq_mig = ar.input_adapter(
        window[:, n - num_latents - 1 : n - num_latents], abs_pos=mig_abs
    )
    k_mig, v_mig = mha.project_kv(ca.kv_norm(emb_mig), RotaryEmbedding(frq_mig))
    k_new, v_new = mha.project_kv(
        x_q_lat[:, -1:], RotaryEmbedding(frq_lat[:, -1:])
    )
    k_upd = jnp.concatenate([k_mig, k_new], axis=2).transpose(0, 2, 1, 3)
    v_upd = jnp.concatenate([v_mig, v_new], axis=2).transpose(0, 2, 1, 3)
    flat_wi = paged.flat_write_indices(block_table, write_idx, block_size)
    if write_ok is not None:
        flat_wi = jnp.where(write_ok[:, None], flat_wi, flat_wi % block_size)
    pool_k, scale_k = paged.scatter_kv(pool_k, scale_k, flat_wi, k_upd)
    pool_v, scale_v = paged.scatter_kv(pool_v, scale_v, flat_wi, v_upd)

    q = mha.project_q(x_q_lat, RotaryEmbedding(frq_lat, right_align=True))
    attn = paged.paged_window_attention(
        mha.attend, q, pool_k, pool_v, block_table,
        block_size=block_size, n=n, pad_count=pad_count,
        scale_k=scale_k, scale_v=scale_v, project_out=mha.project_out,
    )
    x = attn + emb_lat
    x = layer.mlp(x) + x

    stack_pad = jnp.zeros((b, num_latents), bool)
    x = ar.self_attention(
        x, stack_pad, RotaryEmbedding(frq_lat, right_align=True), True
    )

    x_last = x[:, -1]
    if mdl.config.output_norm:
        x_last = mdl.out_norm(x_last)
    logits = mdl.output_adapter(x_last[:, None], ar.input_adapter.embeddings)[:, 0]
    if scale_k is not None:
        return logits, pool_k, pool_v, scale_k, scale_v, length + 1
    return logits, pool_k, pool_v, length + 1


def _decode_step_boundary(
    mdl, window: jnp.ndarray, pad_count: jnp.ndarray, cross_k, cross_v, length,
    write_idx: Optional[jnp.ndarray] = None,
):
    """One cached decode step for the **prefix-growth** phase (the latent
    count is pinned at ``max_latents`` and the boundary migrates one position
    per step — reference window schedule ``clm/huggingface.py:56-62``).

    Token positions are stable in this phase (every row still slides over
    left pads), so the abs-indexed cross k/v cache stays valid except at two
    positions, which are (re)projected per step:

    - the **new token** enters as the freshest latent (``q_norm``-side k/v,
      appended at index ``length``);
    - the **oldest latent** (abs index ``n - max_latents - 1 - pad_count``)
      becomes prefix — its k/v are recomputed ``kv_norm``-side (the
      boundary-side normalization swap, reference ``modules.py:188-203``).

    Both cache updates land in ONE fused scatter per array (the step is
    bookkeeping-bound on CPU — docs/benchmarks.md round-5 curves — so the
    fixed per-step overhead matters as much as the FLOPs). The migrated and
    appended indices are always distinct (``length - max_latents`` vs
    ``length``), so the fused scatter stays deterministic.

    Every latent attends to the migrated key, so all latent cross-attention
    outputs and the self-attention stack are recomputed (their inputs
    changed); the cache elides the ``2·n·c²`` full-window k/v projections
    and the full-window embedding. The attend itself runs over the cache
    gathered back into window-slot alignment so the computation — including
    masking — is bitwise identical to :func:`_decode_forward`.

    :param window: ``(b, N)`` tokens, right-aligned (new token last).
    :param pad_count: ``(b,)`` left-pad counts *after* the append.
    :param cross_k/cross_v: ``(b, h, N, d)`` abs-indexed cross k/v cache.
    :param length: ``(b,)`` real-token count *before* the append.
    :param write_idx: optional ``(b, 2)`` precomputed ``[migrated index,
        append index]`` — the generation executor hoists this arithmetic
        out of the scan body; None derives it from ``pad_count``/``length``
        (the slot engine's per-call path).
    :return: (next-token logits, cross_k, cross_v, length + 1).
    """
    ar = mdl.perceiver_ar
    b, n = window.shape
    num_latents = mdl.max_latents
    layer = ar.cross_attention
    ca = layer.cross_attn
    mha = ca.attention
    rows = jnp.arange(b)

    if write_idx is None:
        mig_abs = jnp.maximum((n - num_latents - 1) - pad_count[:, None], 0)
        write_idx = jnp.concatenate([mig_abs, length[:, None]], axis=1)
    else:
        mig_abs = write_idx[:, :1]

    # Latent segment: the last max_latents window slots, all real tokens
    # (guaranteed by the caller's phase-2 precondition).
    lat_abs = jnp.maximum(
        jnp.arange(n - num_latents, n)[None, :] - pad_count[:, None], 0
    )
    emb_lat, frq_lat = ar.input_adapter(window[:, n - num_latents :], abs_pos=lat_abs)
    x_q_lat = ca.q_norm(emb_lat)

    # Boundary migration: recompute the ex-latent's k/v kv_norm-side.
    emb_mig, frq_mig = ar.input_adapter(
        window[:, n - num_latents - 1 : n - num_latents], abs_pos=mig_abs
    )
    k_mig, v_mig = mha.project_kv(ca.kv_norm(emb_mig), RotaryEmbedding(frq_mig))

    # The new token's q_norm-side k/v at its abs index, fused with the
    # migration write: one (b, 2)-indexed scatter per cache array.
    k_new, v_new = mha.project_kv(
        x_q_lat[:, -1:], RotaryEmbedding(frq_lat[:, -1:])
    )
    k_upd = jnp.concatenate([k_mig, k_new], axis=2).transpose(0, 2, 1, 3)
    v_upd = jnp.concatenate([v_mig, v_new], axis=2).transpose(0, 2, 1, 3)
    cross_k = cross_k.at[rows[:, None], :, write_idx].set(k_upd)
    cross_v = cross_v.at[rows[:, None], :, write_idx].set(v_upd)

    # Gather the abs-indexed cache into window-slot alignment and attend
    # exactly as the uncached forward does (pad slots gather garbage that the
    # pad mask zeroes out of the softmax).
    slot_abs = jnp.maximum(jnp.arange(n)[None, :] - pad_count[:, None], 0)
    k_slots = jnp.take_along_axis(cross_k, slot_abs[:, None, :, None], axis=2)
    v_slots = jnp.take_along_axis(cross_v, slot_abs[:, None, :, None], axis=2)
    pad_mask = jnp.arange(n)[None, :] < pad_count[:, None]
    q = mha.project_q(x_q_lat, RotaryEmbedding(frq_lat, right_align=True))
    attn = mha.attend(q, k_slots, v_slots, pad_mask=pad_mask, deterministic=True)
    x = attn + emb_lat
    x = layer.mlp(x) + x

    # Full self-attention stack over the max_latents latents (all real; the
    # all-False mask keeps the masking ops bitwise identical to
    # _decode_forward with m == max_latents).
    stack_pad = jnp.zeros((b, num_latents), bool)
    x = ar.self_attention(
        x, stack_pad, RotaryEmbedding(frq_lat, right_align=True), True
    )

    x_last = x[:, -1]
    if mdl.config.output_norm:
        x_last = mdl.out_norm(x_last)
    logits = mdl.output_adapter(x_last[:, None], ar.input_adapter.embeddings)[:, 0]
    return logits, cross_k, cross_v, length + 1


def generate(
    model,
    params,
    input_ids: jnp.ndarray,
    config: GenerationConfig,
    *,
    rng: Optional[jax.Array] = None,
    prompt_pad_count: Optional[jnp.ndarray] = None,
    use_cache: bool = True,
    decode_strategy=None,
) -> jnp.ndarray:
    """Generate ``config.max_new_tokens`` tokens after ``input_ids``.

    :param model: an ``AutoregressiveSequenceModel`` (CLM / symbolic audio).
    :param input_ids: ``(b, prompt_len)`` prompt, left-padded if ragged.
    :param prompt_pad_count: ``(b,)`` left-pad counts for ragged prompts.
    :param decode_strategy: per-phase cache strategy —
        ``"auto" | "cached" | "recompute"`` or a
        :class:`~perceiver_io_tpu.inference.decode_strategy.DecodeStrategy`.
        ``None`` defers to ``PERCEIVER_DECODE_STRATEGY`` then ``"auto"``
        (the measured winner for this shape when the autotuner has run,
        else the cached default). Every strategy is exact; greedy output is
        token-identical across all of them. Beam search (``num_beams > 1``)
        ignores the strategy (its executor has no boundary segment).
    :return: ``(b, max_new_tokens)`` generated ids (pad after EOS).
    """
    if config.num_beams > 1:
        from perceiver_io_tpu.inference.beam import beam_search

        return beam_search(
            model,
            params,
            input_ids,
            config,
            num_beams=config.num_beams,
            length_penalty=config.length_penalty,
            prompt_pad_count=prompt_pad_count,
        )
    b, prompt_len = input_ids.shape
    n = model.max_seq_len
    max_latents = model.max_latents
    if not 0 < prompt_len <= n:
        raise ValueError(f"prompt length out of valid range [1..{n}]")
    if not 0 < config.num_latents <= max_latents:
        raise ValueError(
            f"num_latents={config.num_latents} out of valid range [1..{max_latents}]"
        )
    num_latents = min(prompt_len, config.num_latents)
    prefix_len = prompt_len - num_latents
    if prefix_len > model.max_prefix_len:
        raise ValueError(
            f"for sequence length {prompt_len}, num_latents must be >= "
            f"{num_latents + prefix_len - model.max_prefix_len}"
        )
    if rng is None:
        rng = jax.random.PRNGKey(0)
    if prompt_pad_count is None:
        prompt_pad_count = jnp.zeros((b,), jnp.int32)

    # Phase schedule (see module docstring). Phase 1 (latent growth) is
    # fully incremental; phase 2 (prefix growth) reuses the cross k/v cache
    # with per-step boundary migration — valid only while pads never occupy
    # latent slots (prompt pads fit in the nominal prefix); phase 3 (slide)
    # is windowed recompute, semantically forced by the learned absolute
    # position embedding (reference window schedule ``clm/huggingface.py:
    # 53-74``). The per-phase cached-vs-recompute choice is the decode
    # strategy (``inference/decode_strategy.py`` — measured, env- and
    # flag-overridable; the boundary phase loses to recompute on some
    # platforms, docs/benchmarks.md). The schedule is host-side static, so
    # it is part of the executor cache key rather than traced control flow.
    from perceiver_io_tpu.inference import decode_strategy as _strategy

    strat = _strategy.resolve(decode_strategy, model)
    latent_cached = use_cache and strat.latent == "cached"
    s1 = (
        min(config.max_new_tokens, max_latents - num_latents, n - prompt_len)
        if latent_cached
        else 0
    )
    phase2_ok = (
        use_cache
        and strat.boundary_cached
        and bool((np.asarray(jax.device_get(prompt_pad_count)) <= prefix_len).all())
    )
    s2 = min(config.max_new_tokens, n - prompt_len) if phase2_ok else s1
    s2 = max(s1, s2)

    executor = _generation_executor(
        model, config, b, prompt_len, num_latents, s1, s2, str(input_ids.dtype)
    )
    return executor(params, input_ids, rng, prompt_pad_count)


def _pad_positions(pad_count: jnp.ndarray, n: int) -> jnp.ndarray:
    """(b, n) True where the right-aligned window slot is left padding."""
    return jnp.arange(n)[None, :] < pad_count[:, None]


_FINGERPRINTS: dict = {}  # id(model) -> (weakref, repr string)


def model_fingerprint(model) -> str:
    """Architecture fingerprint for executor-cache keys. Flax modules with
    mutable config dataclasses are not hashable, and ``repr(model)`` renders
    the whole module tree — too slow to rebuild per call — so the repr is
    memoized per live module instance (id-keyed, weakref-validated)."""
    import weakref

    entry = _FINGERPRINTS.get(id(model))
    if entry is not None:
        ref, fingerprint = entry
        if ref() is model:
            return fingerprint
    fingerprint = repr(model)
    try:
        ref = weakref.ref(model)
    except TypeError:  # un-weakref-able object: don't cache
        return fingerprint
    _FINGERPRINTS[id(model)] = (ref, fingerprint)
    if len(_FINGERPRINTS) > 256:  # drop dead entries
        for mid in [m for m, (r, _) in _FINGERPRINTS.items() if r() is None]:
            del _FINGERPRINTS[mid]
    return fingerprint


def ledger_model_id(model) -> str:
    """Short stable identity for ledger components: the architecture
    fingerprint is a whole module-tree repr — far too long to display in a
    compile table or diff line — so components carry its hash. Two models
    share an ID iff they share a fingerprint (the same equivalence the
    executor cache keys use)."""
    import hashlib

    digest = hashlib.md5(model_fingerprint(model).encode()).hexdigest()[:10]
    return f"{type(model).__qualname__}:{digest}"


#: Process-wide hit/miss/evict counters across ALL executor caches (the
#: generation cache here and the beam cache in ``beam.py``). A miss means a
#: fresh trace+compile (~1.5 s at test scale) — the serving layer reads these
#: so retracing under real traffic is observable rather than silent. The
#: counters live on the process-wide observability registry under the
#: canonical ``executor_cache_*_total`` names (docs/observability.md); the
#: bare "hits"/"misses"/"evictions" keys remain as deprecation aliases.
_CACHE_COUNTERS = {
    "hits": "executor_cache_hits_total",
    "misses": "executor_cache_misses_total",
    "evictions": "executor_cache_evictions_total",
}


def executor_cache_stats() -> dict:
    """Snapshot of the shared executor-cache counters, under both the
    canonical registry names (``executor_cache_hits_total``, ...) and the
    legacy short keys (``hits``, ...) — prefer the canonical ones; the
    aliases exist for the serve CLI / bench probes written before the
    unified telemetry layer."""
    from perceiver_io_tpu.observability import default_registry

    reg = default_registry()
    out = {}
    for alias, name in _CACHE_COUNTERS.items():
        value = int(reg.counter(name))
        out[alias] = value
        out[name] = value
    return out


#: extra executor caches (e.g. the slot engine's, ``serving/slots.py``)
#: registered so :func:`reset_executor_caches` clears them too without a
#: static import cycle (serving imports this module, not vice versa)
_EXTRA_CACHES: list = []


def register_executor_cache(cache: dict) -> dict:
    """Register an executor cache dict for :func:`reset_executor_caches`;
    returns it for inline use at module scope."""
    _EXTRA_CACHES.append(cache)
    return cache


def reset_executor_caches() -> None:
    """Drop every cached executor and zero the counters (test isolation and
    serving-warmup measurement hook). Rewinding the global counters makes
    live ``ServingEngine`` instances' construction-time snapshots stale —
    their ``stats()`` deltas clamp at 0 rather than going negative, but
    create engines after the reset when exact counts matter. The compile
    ledger's records and identity history reset too: the builds they
    describe no longer exist, and a post-reset rebuild is a cold compile,
    not a retrace of a dropped executor."""
    from perceiver_io_tpu.inference import beam
    from perceiver_io_tpu.observability import default_ledger, default_registry

    _EXECUTOR_CACHE.clear()
    beam._EXECUTOR_CACHE.clear()
    for cache in _EXTRA_CACHES:
        cache.clear()
    default_registry().reset("executor_cache_")
    default_registry().reset("compile_")
    default_registry().reset("retrace_")
    default_ledger().reset()


def cached_executor(cache: dict, key, build, *, max_entries: int = 64,
                    ledger_site: Optional[str] = None,
                    ledger_components: Optional[dict] = None):
    """FIFO-bounded compile-once cache shared by the generation, beam, and
    slot executors: ``build()`` is called (and jitted) only on a key miss.

    ``ledger_site``/``ledger_components`` opt the fresh build into the
    device-cost ledger (``observability/ledger.py``): the executor is
    wrapped so its first call is AOT-compiled, timed, and cost/memory-
    analyzed under ``ledger_site``, with the NAMED ``ledger_components``
    diffed against the previous build of the same (site, model) identity
    for retrace attribution. Pass ``ledger_components`` as a ZERO-ARG
    CALLABLE: component assembly (model-id hashing, config normalization)
    is miss-only work, and every caller sits on a per-dispatch hot path
    where the cache hits."""
    from perceiver_io_tpu.observability import default_registry

    reg = default_registry()
    cached = cache.get(key)
    if cached is not None:
        reg.inc("executor_cache_hits_total")
        return cached
    reg.inc("executor_cache_misses_total")
    executor = build()
    if ledger_site is not None:
        from perceiver_io_tpu.observability import default_ledger

        components = (
            ledger_components() if callable(ledger_components)
            else (ledger_components or {})
        )
        executor = default_ledger().wrap(
            executor, site=ledger_site, components=components
        )
    if len(cache) >= max_entries:
        cache.pop(next(iter(cache)))
        reg.inc("executor_cache_evictions_total")
    cache[key] = executor
    return executor


_EXECUTOR_CACHE: dict = {}


def _generation_executor(
    model, config: GenerationConfig, b: int, prompt_len: int,
    num_latents: int, s1: int, s2: int, ids_dtype: str,
):
    """Build (once) and jit the full generation program for one static plan.

    Re-tracing the eager body cost ~1.5 s per :func:`generate` call (vs
    ~2 ms/token of actual compute at test scale); this cache makes repeated
    pipeline calls with the same shape/config dispatch a compiled program.
    Keyed by the module's fingerprint, the frozen :class:`GenerationConfig`,
    shapes, the phase plan, and every trace-time env knob
    (``PERCEIVER_FUSED_QKV`` and the ``PERCEIVER_FLASH_*`` flags, via
    :func:`~perceiver_io_tpu.models.core.modules.trace_env_fingerprint`) — a
    mid-process toggle must rebuild the executor, not silently reuse a trace
    captured under the other setting."""
    from perceiver_io_tpu.models.core.modules import trace_env_fingerprint

    key = (
        type(model).__qualname__, model_fingerprint(model), config,
        b, prompt_len, num_latents, s1, s2, ids_dtype, trace_env_fingerprint(),
    )
    return cached_executor(
        _EXECUTOR_CACHE, key,
        lambda: _build_generation_executor(
            model, config, b, prompt_len, num_latents, s1, s2, ids_dtype
        ),
        ledger_site="generate",
        ledger_components=lambda: {
            "model": ledger_model_id(model),
            # max_new_tokens is routine per-request variation already
            # captured by phase_plan (s2 is the compiled scan length);
            # `config` means sampling/eos/latents (docs/observability.md)
            "config": dataclasses.replace(config, max_new_tokens=0),
            "bucket_shape": f"{b}x{prompt_len}",
            "num_latents": num_latents,
            "phase_plan": f"s1={s1},s2={s2}",
            "ids_dtype": ids_dtype,
            "trace_env": trace_env_fingerprint(),
        },
    )


def _build_generation_executor(
    model, config: GenerationConfig, b: int, prompt_len: int,
    num_latents: int, s1: int, s2: int, ids_dtype: str,
):
    n = model.max_seq_len
    max_latents = model.max_latents

    def advance(window, pad_count, finished, token, m):
        if config.eos_token_id is not None:
            token = jnp.where(finished, config.pad_token_id, token)
            finished = finished | (token == config.eos_token_id)
        window = jnp.concatenate(
            [window[:, 1:], token[:, None].astype(window.dtype)], axis=1
        )
        pad_count = jnp.maximum(pad_count - 1, 0)
        m = jnp.minimum(m + 1, max_latents)
        return window, pad_count, finished, token, m

    # EOS unreachable until min_new_tokens (applies to greedy and sampling,
    # not only beam — HF MinNewTokensLengthLogitsProcessor).
    min_new = (
        min(config.min_new_tokens, config.max_new_tokens)
        if config.eos_token_id is not None
        else 0
    )

    def mask_eos_until_min(logits, t):
        return apply_min_new_tokens(logits, t, min_new, config.eos_token_id or 0)

    def run(params, input_ids, rng, prompt_pad_count):
        # Right-align the prompt into the full-size window.
        window = jnp.full((b, n), config.pad_token_id, input_ids.dtype)
        window = window.at[:, n - prompt_len :].set(input_ids)
        pad_count = prompt_pad_count.astype(jnp.int32) + (n - prompt_len)
        step_rngs = jax.random.split(rng, config.max_new_tokens)

        token_blocks = []
        m0 = jnp.asarray(num_latents, jnp.int32)
        finished = jnp.zeros((b,), bool)
        cache = length = logits = None

        if s2 > 0:
            logits, cache, length, _ = model.apply(
                {"params": params}, window, pad_count, m0, method=_decode_prefill
            )

        if s1 > 0:

            def cached_step(carry, xs):
                step_rng, t = xs
                window, pad_count, finished, logits, cache, length, m = carry
                token = sample_logits(
                    step_rng, mask_eos_until_min(logits, t), config.sampling,
                    window, _pad_positions(pad_count, n),
                )
                window, pad_count, finished, token, _ = advance(
                    window, pad_count, finished, token, m
                )
                logits, cache, length, m = model.apply(
                    {"params": params}, token, cache, length, m, method=_decode_step
                )
                return (window, pad_count, finished, logits, cache, length, m), token

            carry = (window, pad_count, finished, logits, cache, length, m0)
            carry, tokens = jax.lax.scan(
                cached_step, carry, (step_rngs[:s1], jnp.arange(s1))
            )
            window, pad_count, finished, logits, cache, length, m0 = carry
            token_blocks.append(tokens)

        if s2 > s1:
            cross_k, cross_v = cache["cross_k"], cache["cross_v"]
            m_full = jnp.asarray(max_latents, jnp.int32)

            # Hoisted scatter-index arithmetic: the migrated and appended
            # cache indices are affine in the step counter, so the whole
            # (T, b, 2) sequence is computed once here and fed through the
            # scan's xs instead of being re-derived inside every iteration
            # (the boundary step is bookkeeping-bound on CPU).
            t_rel = jnp.arange(s2 - s1, dtype=jnp.int32)
            pad_seq = jnp.maximum(pad_count[None, :] - (t_rel + 1)[:, None], 0)
            mig_seq = jnp.maximum((n - max_latents - 1) - pad_seq, 0)
            len_seq = length[None, :] + t_rel[:, None]
            write_idx_seq = jnp.stack([mig_seq, len_seq], axis=-1)

            def boundary_step(carry, xs):
                step_rng, t, write_idx = xs
                window, pad_count, finished, logits, cross_k, cross_v, length = carry
                token = sample_logits(
                    step_rng, mask_eos_until_min(logits, t), config.sampling,
                    window, _pad_positions(pad_count, n),
                )
                window, pad_count, finished, token, _ = advance(
                    window, pad_count, finished, token, m_full
                )
                logits, cross_k, cross_v, length = model.apply(
                    {"params": params},
                    window,
                    pad_count,
                    cross_k,
                    cross_v,
                    length,
                    write_idx,
                    method=_decode_step_boundary,
                )
                return (
                    (window, pad_count, finished, logits, cross_k, cross_v, length),
                    token,
                )

            carry = (window, pad_count, finished, logits, cross_k, cross_v, length)
            carry, tokens = jax.lax.scan(
                boundary_step, carry,
                (step_rngs[s1:s2], jnp.arange(s1, s2), write_idx_seq),
            )
            window, pad_count, finished = carry[0], carry[1], carry[2]
            m0 = m_full
            token_blocks.append(tokens)

        if config.max_new_tokens > s2:

            def step(carry, xs):
                step_rng, t = xs
                window, pad_count, m, finished = carry
                logits = model.apply(
                    {"params": params}, window, pad_count, m, method=_decode_forward
                )
                token = sample_logits(
                    step_rng, mask_eos_until_min(logits, t), config.sampling,
                    window, _pad_positions(pad_count, n),
                )
                window, pad_count, finished, token, m = advance(
                    window, pad_count, finished, token, m
                )
                return (window, pad_count, m, finished), token

            carry = (window, pad_count, m0, finished)
            _, tokens = jax.lax.scan(
                step, carry, (step_rngs[s2:], jnp.arange(s2, config.max_new_tokens))
            )
            token_blocks.append(tokens)

        return jnp.concatenate(token_blocks, axis=0).T.astype(
            jnp.dtype(ids_dtype)
        )

    return jax.jit(run)
